#!/usr/bin/env bash
# Backfill a RANGE of days, one load_data.sh invocation per day
# (equivalent of the reference's run.sh:1-7, which listed per-day
# simple_reporter commands by hand).
#
# Usage: ./run.sh FIRST_DAY LAST_DAY SRC_PREFIX DEST [DATA_DIR]
set -euo pipefail
cd "$(dirname "$0")"

FIRST="${1:?usage: run.sh FIRST_DAY LAST_DAY SRC_PREFIX DEST [DATA_DIR]}"
LAST="${2:?need LAST_DAY}"
SRC="${3:?need SRC_PREFIX}"
DEST="${4:?need DEST}"
DATA_DIR="${5:-/data}"

# ordinal comparison (not string equality) so an unpadded or reversed
# range terminates instead of looping past the end date
LAST_TS="$(date -u -d "${LAST}" +%s)"
DAY="$(date -u -d "${FIRST}" +%F)"
while [ "$(date -u -d "${DAY}" +%s)" -le "${LAST_TS}" ]; do
  echo "[backfill] ${DAY}"
  ./load_data.sh "${DAY}" "${SRC}" "${DEST}" "${DATA_DIR}"
  DAY="$(date -u -d "${DAY} + 1 day" +%F)"
done
echo "[backfill] done ${FIRST}..${LAST}"
