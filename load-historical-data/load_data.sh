#!/usr/bin/env bash
# Backfill ONE day of historical probe data through the batch pipeline
# (equivalent of the reference's load_data.sh:1-13, which ran
# simple_reporter.py with concurrency 16 over a day's S3 prefix).
#
# Usage: ./load_data.sh YYYY-MM-DD SRC_PREFIX DEST [DATA_DIR]
#   SRC_PREFIX  s3://bucket/prefix or a local directory of part files;
#               the day is appended as .../YYYY/MM/DD
#   DEST        s3://bucket[/prefix] or a local output directory
set -euo pipefail
cd "$(dirname "$0")/.."

DAY="${1:?usage: load_data.sh YYYY-MM-DD SRC_PREFIX DEST [DATA_DIR] [extra pipeline flags]}"
SRC="${2:?need SRC_PREFIX}"
DEST="${3:?need DEST}"
shift 3
DATA_DIR="/data"
if [ "$#" -ge 1 ] && [ "${1#--}" = "${1}" ]; then
  DATA_DIR="$1"
  shift
fi

DAY_PATH="$(echo "${DAY}" | tr - /)"

# concurrency drives stages 1+3 (host process fan-out); stage 2 batches
# --device-batch traces per TPU dispatch. To RESUME a failed day from its
# intermediate outputs, append --trace-dir <dir> (skips the download
# stage) or --match-dir <dir> (skips download + match) using the scratch
# paths the failed run logged.
python -m reporter_tpu pipeline \
    --src "${SRC}/${DAY_PATH}" \
    --match-config "${DATA_DIR}/reporter.json" \
    --dest "${DEST}" \
    --report-levels 0,1,2 --transition-levels 0,1,2 \
    --quantisation 3600 --privacy 2 --inactivity 120 \
    --concurrency "${CONCURRENCY:-16}" \
    --device-batch "${DEVICE_BATCH:-512}" \
    --source-id "backfill_${DAY}" \
    "$@"
