#!/usr/bin/env bash
# One-time host setup for bulk backfill (equivalent of the reference's
# load-historical-data/setup.sh:1-58, which apt-installed valhalla and
# downloaded the planet tile tarball). Here: build the native runtime and
# materialise a road graph + matcher config under $DATA_DIR.
#
# Usage: ./setup.sh [DATA_DIR] ; env GRAPH_SOURCE=<.npz|tile-dir> to use a
# real graph instead of the synthetic default.
set -euo pipefail
cd "$(dirname "$0")/.."

DATA_DIR="${1:-/data}"
mkdir -p "${DATA_DIR}"

echo "[setup] building native host runtime"
make -C reporter_tpu/native

GRAPH="${DATA_DIR}/graph.npz"
if [ -n "${GRAPH_SOURCE:-}" ]; then
  if [ -d "${GRAPH_SOURCE}" ]; then
    echo "[setup] composing graph from tile tree ${GRAPH_SOURCE}"
    python -m reporter_tpu graph untile --tile-dir "${GRAPH_SOURCE}" \
        --out "${GRAPH}"
  else
    echo "[setup] using graph ${GRAPH_SOURCE}"
    cp "${GRAPH_SOURCE}" "${GRAPH}"
  fi
else
  echo "[setup] no GRAPH_SOURCE; generating a synthetic city graph"
  python -m reporter_tpu graph build-synth --rows 24 --cols 24 \
      --spacing-m 200 --seed 0 --out "${GRAPH}"
fi

printf '{"graph": "%s"}\n' "${GRAPH}" > "${DATA_DIR}/reporter.json"
python -m reporter_tpu graph info "${GRAPH}"
echo "[setup] done: ${DATA_DIR}/reporter.json"
