#!/usr/bin/env python
"""Benchmark: batched TPU map-matching throughput vs the reference's
one-trace-at-a-time architecture.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "traces/sec", "vs_baseline": N}

Method: build a synthetic city, synthesise noisy GPS traces, prepare the
fixed-width candidate/route tensors once on the host (steady-state: the
route cache is warm, as in a long-running city service), then time

  baseline leg — decode traces ONE AT A TIME (batch=1), the reference's
  architecture (one C++ Meili call per trace behind one HTTP request,
  reference: py/reporter_service.py:240, Batch.java:66-68), but already on
  the accelerator — a *generous* stand-in for single-process Meili;

  batched leg  — the same traces decoded through the vmapped
  associative-scan Viterbi in large padded batches, plus host-side segment
  assembly + report() (the full per-trace post-processing the service
  does), i.e. the architecture this framework exists for.

``vs_baseline`` is batched/baseline throughput — the architectural
speedup toward BASELINE.md's >=50x north star. Env knobs:
BENCH_TRACES (default 512), BENCH_BASELINE_TRACES (default 24),
BENCH_T (bucket, default 64), BENCH_K (default 8).
"""
import json
import os
import sys
import time

import numpy as np


def build_inputs(n_traces, T_bucket, K):
    from reporter_tpu.matcher import MatchParams, SegmentMatcher
    from reporter_tpu.matcher.batchpad import pack_batches, prepare_trace
    from reporter_tpu.synth import build_grid_city, generate_trace

    city = build_grid_city(rows=20, cols=20, spacing_m=200.0, seed=42)
    params = MatchParams(max_candidates=K)
    matcher = SegmentMatcher(net=city, params=params)
    rng = np.random.default_rng(7)
    prepared, reqs = [], []
    # routes long enough to fill the bucket at ~1 point/sec, then sliced
    min_edges = max(4, T_bucket // 12)
    attempts = 0
    while len(prepared) < n_traces:
        attempts += 1
        if attempts > 50 * n_traces:
            raise RuntimeError(f"could not build T={T_bucket} traces")
        tr = generate_trace(city, f"veh-{len(prepared)}", rng, noise_m=4.0,
                            min_route_edges=min_edges, max_route_edges=60)
        if tr is None or len(tr.points) < T_bucket // 2:
            continue
        points = tr.points[:T_bucket]
        p = prepare_trace(city, matcher.grid, points, params,
                          matcher.route_cache)
        if p.T != T_bucket:
            continue
        prepared.append(p)
        req = tr.request_json()
        req["trace"] = points
        reqs.append(req)
    return city, matcher, params, prepared, reqs


def time_decode(decode_fn, batches, sigma, beta, repeats=3):
    import jax

    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        outs = []
        for b in batches:
            paths, scores = decode_fn(b.dist_m, b.valid, b.route_m, b.gc_m,
                                      b.case, sigma, beta)
            outs.append(paths)
        jax.block_until_ready(outs)
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    n_traces = int(os.environ.get("BENCH_TRACES", 512))
    n_base = int(os.environ.get("BENCH_BASELINE_TRACES", 24))
    T_bucket = int(os.environ.get("BENCH_T", 64))
    K = int(os.environ.get("BENCH_K", 8))

    # bounded-patience accelerator init: probe the chip in a subprocess
    # (bounded, retried), fall back to CPU and say so in the metric rather
    # than exiting nonzero on a tunnel flake (round-1 BENCH rc=1)
    from reporter_tpu.utils.runtime import ensure_backend
    ensure_backend(probe_tries=3)

    import jax

    from reporter_tpu.matcher.batchpad import pack_batches
    from reporter_tpu.matcher.assemble import assemble_segments
    from reporter_tpu.ops import decode_batch, decode_backend
    from reporter_tpu.service.report import report as make_report

    platform = jax.devices()[0].platform
    city, matcher, params, prepared, reqs = build_inputs(
        n_traces, T_bucket, K)
    sigma = np.float32(params.effective_sigma)
    beta = np.float32(params.beta)

    # chunked so h2d transfer, decode, and host post-processing of
    # successive chunks overlap (mirrors SegmentMatcher.match_many)
    chunk = int(os.environ.get("BENCH_CHUNK", 128))
    batches = pack_batches(prepared, max_batch=chunk)

    # -- warmup / compile both shapes ------------------------------------
    b0 = batches[0]
    decode_batch(b0.dist_m, b0.valid, b0.route_m, b0.gc_m, b0.case,
                        sigma, beta)[0].block_until_ready()
    single = pack_batches(prepared[:1])[0]
    decode_batch(single.dist_m, single.valid, single.route_m,
                        single.gc_m, single.case, sigma, beta)[0].block_until_ready()

    # -- baseline leg: one trace per device call -------------------------
    t0 = time.perf_counter()
    for i, p in enumerate(prepared[:n_base]):
        sb = pack_batches([p])[0]
        paths, _ = decode_batch(sb.dist_m, sb.valid, sb.route_m,
                                       sb.gc_m, sb.case, sigma, beta)
        paths.block_until_ready()
        match = assemble_segments(city, p, np.asarray(paths)[0])
        make_report(match, reqs[i], 15, {0, 1, 2}, {0, 1, 2})
    baseline_tps = n_base / (time.perf_counter() - t0)

    # -- batched leg: full pipeline decode + assembly + report -----------
    # dispatch every chunk (decode + async d2h copy) before draining any:
    # later chunks' transfers/compute overlap earlier chunks' host work
    best = float("inf")
    for _ in range(int(os.environ.get("BENCH_REPEATS", 5))):
        t0 = time.perf_counter()
        pend = []
        for b in batches:
            paths, _ = decode_batch(b.dist_m, b.valid, b.route_m,
                                           b.gc_m, b.case, sigma, beta)
            if hasattr(paths, "copy_to_host_async"):
                paths.copy_to_host_async()
            pend.append((b, paths))
        idx = 0
        for b, paths in pend:
            paths = np.asarray(paths)
            for j, p in enumerate(b.traces):
                match = assemble_segments(city, p, paths[j])
                make_report(match, reqs[idx], 15, {0, 1, 2}, {0, 1, 2})
                idx += 1
        best = min(best, time.perf_counter() - t0)
    batched_tps = n_traces / best

    print(json.dumps({
        "metric": f"synthetic-city traces/sec map-matched end-to-end "
                  f"(decode+assemble+report, T={T_bucket}, K={K}, "
                  f"platform={platform}, decode={decode_backend(T_bucket, K)}) "
                  f"batched vs one-trace-per-call",
        "value": round(batched_tps, 1),
        "unit": "traces/sec",
        "vs_baseline": round(batched_tps / baseline_tps, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
