#!/usr/bin/env python
"""Benchmark: batched TPU map-matching throughput vs the reference's
one-trace-at-a-time single-process architecture.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "traces/sec", "vs_baseline": N,
   "stages": {...}, "report_writers": {...}, "baseline": {...},
   "probe": {...}, "pallas": {...}}

Method: build a synthetic city, synthesise noisy GPS traces, then time
two END-TO-END legs over the same traces (steady state: route caches
warm, shapes compiled — a long-running city service):

  baseline leg — the reference's architecture (reference:
  py/reporter_service.py:240, Batch.java:66-68 — one C++ Meili call per
  trace on one CPU thread): single-threaded host prep + the pure-numpy
  single-trace Viterbi (matcher/cpu_ref.py) + segment assembly +
  report(), one trace at a time, no accelerator; best-of-N over >=100
  traces so the denominator is not a single noisy pass.

  batched leg  — this framework's architecture: SegmentMatcher.match_many
  (ONE native prep call per chunk — C++ candidates/jitter-filter/route
  matrices straight into padded tensors — the platform-default batched
  Viterbi (assoc on accelerators/meshes, scan on a lone CPU device;
  ops.decode_backend), async d2h, ONE native assembly call per batch)
  + report().

``vs_baseline`` is batched/baseline throughput — the architectural
speedup toward BASELINE.md's >=50x-over-single-process-Meili north star,
with the baseline an honest single-process CPU stand-in, not a batch=1
accelerator call.

The artifact is self-diagnosing: ``stages`` carries per-stage seconds of
the best batched run (prep / decode dispatch / decode wait / assemble,
from the matcher's metrics timers, plus report), ``baseline`` the
denominator's scope, ``probe`` the accelerator probe attempts and the
fallback reason when the run landed on CPU, and ``pallas`` a second
decode-backend leg (REPORTER_TPU_DECODE=pallas) recorded on TPU runs so
kernel claims trace to a committed artifact.

Env knobs: BENCH_TRACES (default 512), BENCH_BASELINE_TRACES (default
128), BENCH_T (bucket, default 64), BENCH_K (default 8), BENCH_REPEATS
(default 5), BENCH_BASELINE_REPEATS (default 3), BENCH_PALLAS
(default: auto — on when the platform is tpu), BENCH_PROFILE (a
directory: record one jax.profiler device trace of a batched pass),
BENCH_PIPE_PROBE_TIMEOUT (default 240 s: patience for the bounded
subprocess that proves the threaded device lanes on the accelerator
before the artifact run trusts them; on failure the run serializes
with REPORTER_TPU_PIPELINE=0 and records why in ``probe``),
REPORTER_TPU_PROBE_TIMEOUT_S / _TRIES (probe patience).

One argv escape hatch: ``python bench.py --feed-fanout N [...]`` runs
the freshness tier's change-feed fan-out leg (tools/
feed_fanout_bench.py — N concurrent /feed subscribers over a pre-fork
fleet) instead of the matcher legs.
"""
import json
import os
import sys
import time

import numpy as np


def build_inputs(n_traces, T_bucket, K):
    from reporter_tpu.core.tracebatch import TraceBatch
    from reporter_tpu.matcher import MatchParams, SegmentMatcher
    from reporter_tpu.synth import build_grid_city, generate_trace

    city = build_grid_city(rows=20, cols=20, spacing_m=200.0, seed=42)
    params = MatchParams(max_candidates=K)
    matcher = SegmentMatcher(net=city, params=params)
    rng = np.random.default_rng(7)
    reqs = []
    # routes long enough to fill the bucket at ~1 point/sec, then sliced
    min_edges = max(4, T_bucket // 12)
    attempts = 0
    while len(reqs) < n_traces:
        attempts += 1
        if attempts > 50 * n_traces:
            raise RuntimeError(f"could not build T={T_bucket} traces")
        tr = generate_trace(city, f"veh-{len(reqs)}", rng, noise_m=4.0,
                            min_route_edges=min_edges, max_route_edges=60)
        if tr is None or len(tr.points) < T_bucket // 2:
            continue
        points = tr.points[:T_bucket]
        # prepared only to check the trace fills the bucket exactly
        if matcher.prepare(points).T != T_bucket:
            continue
        req = tr.request_json()
        req["trace"] = points
        req["match_options"] = {"mode": "auto",
                                "report_levels": [0, 1, 2],
                                "transition_levels": [0, 1, 2]}
        reqs.append(req)
    # columnar TraceBatch with ONE shared match_options — what a real
    # ingestion edge (service/streaming/pipeline) hands the matcher; the
    # batched leg measures the zero-dict hot path the service actually
    # runs, the baseline leg keeps the reference's per-trace dicts
    tb = TraceBatch.from_requests(reqs)
    tb.options = reqs[0]["match_options"]
    return city, matcher, params, reqs, tb


def _probe_pipelined_accel(timeout_s):
    """The device-lane pipeline drives the accelerator from worker
    threads; the tunnel PJRT client this environment exposes is
    experimental and has never been proven under that pattern on
    hardware. ONE bounded subprocess match decides — a hang or crash
    there costs this timeout, not the artifact: the real run then
    serializes (REPORTER_TPU_PIPELINE=0) and says so in the JSON.

    MUST run while this process has NOT initialised the accelerator
    (the chip is single-client: a child probing against a held chip
    measures contention, not pipeline viability — main() sequences
    this before rt.ensure_backend's in-parent init). The child asserts
    it actually came up on an accelerator, so a silent CPU fallback in
    the child cannot vacuously pass the probe."""
    import subprocess
    code = (
        "from reporter_tpu.utils.runtime import enable_compile_cache\n"
        "enable_compile_cache()  # share the accel AOT cache with the run\n"
        "import jax\n"
        "assert jax.devices()[0].platform != 'cpu', 'child on cpu'\n"
        "import numpy as np\n"
        "from reporter_tpu.matcher import SegmentMatcher\n"
        "from reporter_tpu.synth import build_grid_city, generate_trace\n"
        "city = build_grid_city(rows=6, cols=6, spacing_m=200.0, seed=1)\n"
        "m = SegmentMatcher(net=city)\n"
        "rng = np.random.default_rng(0)\n"
        "reqs, attempts = [], 0\n"
        "while len(reqs) < 4:\n"
        "    attempts += 1\n"
        "    assert attempts < 200, 'trace generation starved'\n"
        "    tr = generate_trace(city, f'p{len(reqs)}', rng, noise_m=3.0)\n"
        "    if tr is not None: reqs.append(tr.request_json())\n"
        "out = m.match_many(reqs)\n"
        "assert all(r and r['segments'] for r in out)\n"
        "print('PIPELINED_OK')\n")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"pipelined probe timed out after {timeout_s:.0f}s"
    if proc.returncode == 0 and "PIPELINED_OK" in proc.stdout:
        return True, "pipelined probe ok"
    return False, (f"pipelined probe rc={proc.returncode}: "
                   + (proc.stderr.strip()[-120:] or "no stderr"))


def _time_batched_leg(matcher, tb, reqs, make_report, repeats):
    """Best-of-N end-to-end timing of match_many + report over the
    columnar batch ``tb``; returns (best_seconds, stage breakdown of the
    best run). ``reqs`` supplies the request dicts report() reads."""
    from reporter_tpu.matcher import pipeline_enabled
    from reporter_tpu.utils import metrics

    best, best_stages = float("inf"), {}
    for _ in range(repeats):
        metrics.default.reset()
        t0 = time.perf_counter()
        matches = matcher.match_many(tb)
        t_match = time.perf_counter()
        for req, match in zip(reqs, matches):
            make_report(match, req, 15, {0, 1, 2}, {0, 1, 2})
        elapsed = time.perf_counter() - t0
        if elapsed < best:
            best = elapsed
            snap = metrics.snapshot()
            timers = snap["timers"]
            best_stages = {
                name.split(".", 1)[1]: timers[name]["total_s"]
                for name in ("matcher.prep", "matcher.decode_dispatch",
                             "matcher.decode_wait", "matcher.assemble")
                if name in timers}
            # native prep phase split (REPORTER_TPU_PREP_TIMINGS
            # attribution, now always exported through utils.metrics):
            # candidates = wall of the batch-sorted kernel, select/routes
            # are worker-thread-summed — where prep time went, committed
            # in the artifact instead of needing a rerun
            counters = snap["counters"]
            for phase in ("candidates", "select", "routes"):
                ns = counters.get(f"prep.phase.{phase}_ns")
                if ns:
                    best_stages[f"prep_{phase}"] = round(ns / 1e9, 6)
            best_stages["report"] = round(elapsed - (t_match - t0), 6)
            best_stages["total"] = round(elapsed, 6)
            # serialisation's share of the batch wall — the wire-path
            # health number (ISSUE 11: the native writer's target is
            # <=0.15 serialized, from ~0.27 with the Python columnar
            # writer in BENCH_DEV_r06)
            best_stages["report_share"] = round(
                best_stages["report"] / elapsed, 4)
            # prep's share of the batch wall — the host-pipeline health
            # number (BENCH_r05: 62%; the columnar pipeline's target is
            # <35%). Under the device lanes prep overlaps decode, so
            # stage seconds can sum past the wall total; set
            # REPORTER_TPU_PIPELINE=0 for a serialized breakdown.
            best_stages["prep_share"] = round(
                best_stages.get("prep", 0.0) / elapsed, 4)
            best_stages["pipelined"] = pipeline_enabled()
    return best, best_stages


def _time_report_writers(matches, reqs, repeats=3):
    """The serialisation stage in isolation, one leg per wire backend
    over the SAME matches: the native C writer (bytes straight from run
    columns in one GIL-released call), the Python columnar writer (the
    fallback backend / parity oracle), and the legacy per-run-dict +
    json.dumps path the pre-PR-4 service ran. Ratios between legs are
    box-drift-proof (same process, same matches); the native leg is
    None when the toolchain is unavailable."""
    from reporter_tpu import native
    from reporter_tpu.service import wire
    from reporter_tpu.service.report import (_report_json_py, report,
                                             report_wire)

    mm_runs = [(m, r) for m, r in zip(matches, reqs)
               if not isinstance(m, dict)]
    if not mm_runs:
        return None

    def _leg(fn):
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            for match, req in mm_runs:
                fn(match, req, 15, {0, 1, 2}, {0, 1, 2})
            best = min(best, time.perf_counter() - t0)
        return best

    out = {"n_traces": len(mm_runs)}
    python_s = _leg(_report_json_py)
    out["python_s"] = round(python_s, 6)
    # legacy dict path: dicts pre-materialised outside the timed loop —
    # the pre-PR-4 service got them free from assembly, so charging
    # materialisation here would overstate the win
    plain = [({"segments": [dict(s) for s in m["segments"]],
               "mode": m["mode"]}, r) for m, r in mm_runs]

    def _dict_leg(match, req, thr, rep, trans):
        return json.dumps(report(match, req, thr, rep, trans),
                          separators=(",", ":"))

    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for match, req in plain:
            _dict_leg(match, req, 15, {0, 1, 2}, {0, 1, 2})
        best = min(best, time.perf_counter() - t0)
    out["dict_s"] = round(best, 6)
    out["dict_vs_python"] = round(best / python_s, 3)
    if native.available() and wire.use_native():
        def _native_leg():
            best = float("inf")
            for _ in range(repeats):
                # drop the chunk memos so EVERY repeat pays the whole-
                # chunk C emission plus its slice lookups — without
                # this, repeats 2+ time pure dict hits and the
                # committed native_vs_python ratio would overstate the
                # writer (the serving path builds the memo once per
                # chunk lifetime, which one repeat models exactly)
                for match, _req in mm_runs:
                    match.cols.arrays.pop("_wire_chunk", None)
                t0 = time.perf_counter()
                for match, req in mm_runs:
                    report_wire(match, req, 15, {0, 1, 2}, {0, 1, 2})
                best = min(best, time.perf_counter() - t0)
            return best

        native_s = _native_leg()
        out["native_s"] = round(native_s, 6)
        out["native_vs_python"] = round(native_s / python_s, 3)
    else:
        out["native_s"] = None
        out["native_vs_python"] = None
    return out


def _bucketing_leg(city, matcher, reqs_pool):
    """The adaptive-bucket before/after pair (ISSUE 13): one MIXED-
    length batch — raw lengths straddling the fixed 16/64/256 ladder
    rungs — decoded twice over the same traces: once with the splitter
    off (``REPORTER_TPU_BUCKETS=@off``, the fixed-ladder status quo)
    and once with the default occupancy-driven splitter. Records the
    profiler's whole-leg ``padding_waste`` for each, the split count,
    and the adaptive leg's recompile-storm count (must be 0: every
    sub-bucket is a NEW shape = one episode each, never a second
    compile of a known shape). A true same-box pair, gated by
    ``perf_gate --max-padding-waste``. An explicit ``skipped`` record
    when the native runtime is absent (the splitter lives in the
    native dispatch path) — the gate passes an explicit skip with a
    note, vs hard-failing a silently missing block."""
    if matcher.runtime is None:
        return {"skipped": "no native runtime: the adaptive splitter "
                "lives in the native dispatch path"}
    from reporter_tpu.core.tracebatch import TraceBatch
    from reporter_tpu.obs import profiler
    from reporter_tpu.synth import generate_trace
    from reporter_tpu.utils import metrics

    # mixed raw lengths sitting ON pow2 rungs the fixed 16/64/256/1024
    # ladder mostly lacks (32 and 128 pad 2x under it), subsampled 2x
    # so point spacing clears the interpolation distance (kept ~= raw —
    # the waste measured is BUCKET pad, not jitter drops); pow2 group
    # counts so row padding stays exact in both legs
    plan = ((16, 32), (32, 32), (64, 16), (128, 8))
    rng = np.random.default_rng(13)
    mixed = []
    for want_len, count in plan:
        got, attempts = 0, 0
        while got < count:
            attempts += 1
            if attempts > 500 * count:
                raise RuntimeError(
                    f"could not build {count} mixed traces of {want_len}")
            tr = generate_trace(city, f"mix{want_len}-{got}", rng,
                                noise_m=4.0,
                                min_route_edges=max(4, want_len // 5),
                                max_route_edges=90)
            if tr is None or len(tr.points) < 2 * want_len:
                continue
            req = tr.request_json()
            req["trace"] = tr.points[:2 * want_len:2]
            req["match_options"] = reqs_pool[0]["match_options"]
            mixed.append(req)
            got += 1
    tb = TraceBatch.from_requests(mixed)
    tb.options = mixed[0]["match_options"]

    saved = os.environ.get("REPORTER_TPU_BUCKETS")

    def _leg(spec):
        if spec is None:
            os.environ.pop("REPORTER_TPU_BUCKETS", None)
        else:
            os.environ["REPORTER_TPU_BUCKETS"] = spec
        profiler.reset()
        splits0 = metrics.default.counter("decode.bucket.split")
        # two passes: the second exercises the recorded-waste decision
        # path (the first may decide from the raw-length projection)
        matcher.match_many(tb)
        matcher.match_many(tb)
        prof = profiler.snapshot(n_events=0)
        return {
            "padding_waste": prof["totals"]["padding_waste"],
            "splits": metrics.default.counter("decode.bucket.split")
            - splits0,
            "recompiles": sum(max(0, s["compiles"] - 1)
                              for s in prof["shapes"]),
        }

    try:
        fixed = _leg("@off")
        adaptive = _leg(None)
    finally:
        if saved is None:
            os.environ.pop("REPORTER_TPU_BUCKETS", None)
        else:
            os.environ["REPORTER_TPU_BUCKETS"] = saved
        profiler.reset()
    return {
        "n_traces": len(mixed),
        "fixed_waste": fixed["padding_waste"],
        "adaptive_waste": adaptive["padding_waste"],
        "splits": adaptive["splits"],
        "recompiles": adaptive["recompiles"],
    }


def _query_leg(n_segments: int = 256, repeats: int = 3):
    """The serving-tier batched-query pair (ISSUE 14): ONE
    ``query_many(256)`` sweep vs 256 single ``query_segment`` calls
    over the same synthetic store — 8 partitions x 4 live deltas, every
    segment with histogram cells and transitions (the pre-compaction
    steady state a dashboard hits). Answers are asserted EQUAL before
    timing (the speedup must never be a different answer), and the
    best-of-N ratio is gated by ``perf_gate --min-query-ratio``."""
    import shutil
    import tempfile

    from reporter_tpu.core.osmlr import make_segment_id
    from reporter_tpu.datastore import (
        LocalDatastore,
        ObservationBatch,
        query_many,
        query_segment,
    )

    tmp = tempfile.mkdtemp(prefix="bench_query_")
    try:
        ds = LocalDatastore(tmp)
        rng = np.random.default_rng(7)
        tiles = [1000 + i for i in range(8)]
        seg_ids = [make_segment_id(2, tiles[i % 8], i)
                   for i in range(n_segments)]
        seg_arr = np.array(seg_ids, dtype=np.int64)
        for d in range(4):
            n_obs = n_segments * 8
            dur = rng.uniform(5, 30, n_obs)
            obs = ObservationBatch(
                segment_id=rng.choice(seg_arr, size=n_obs),
                next_id=rng.choice(seg_arr, size=n_obs),
                duration_s=dur,
                count=np.ones(n_obs, dtype=np.int64),
                length_m=(dur * rng.uniform(3, 20, n_obs))
                .astype(np.int64) + 1,
                queue_m=np.zeros(n_obs, dtype=np.int64),
                min_ts=rng.integers(1500000000, 1500600000, n_obs),
                max_ts=rng.integers(1500600000, 1500700000, n_obs))
            ds.ingest(obs, ingest_key=f"bench-{d}")

        many = query_many(ds, seg_ids)  # warm handles + assert parity
        singles = [query_segment(ds, s) for s in seg_ids]
        if many != singles:
            raise RuntimeError("query_many answers differ from single "
                               "queries — parity broken, ratio void")
        best_single = best_many = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            for s in seg_ids:
                query_segment(ds, s)
            best_single = min(best_single, time.perf_counter() - t0)
        for _ in range(repeats):
            t0 = time.perf_counter()
            query_many(ds, seg_ids)
            best_many = min(best_many, time.perf_counter() - t0)
        return {
            "n_segments": n_segments,
            "partitions": 8,
            "live_deltas_per_partition": 4,
            "single_s": round(best_single, 6),
            "many_s": round(best_many, 6),
            "batch_ratio": round(best_single / best_many, 2),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _routes_leg(city, matcher, params, reqs, n_chunk: int = 64,
                repeats: int = 3):
    """The route-kernel triple (ISSUE 16): the same chunk's candidate
    pairs costed three ways — the chunk-batched device relax
    (graph/route_device.py, its serving shape: ONE fill per chunk), the
    per-trace host Dijkstra (graph/route.py, warm RouteCache) and the
    per-trace native memo (rt_route_matrices, warm memo). BEFORE any
    timing, the serving paths (batch prep, per-trace native, device
    fill) must agree byte-identical — the speedup must never be a
    different answer. The numpy reference accumulates in float64 and
    casts on store, so it is held to the seed's route tolerance
    (rtol=1e-5, atol=1e-3) instead of bytes. Best-of-N wall per leg;
    ``device_vs_native`` is the route-stage speedup the pipelined
    prep_share should reflect when REPORTER_TPU_ROUTE_DEVICE is on."""
    if matcher.runtime is None:
        return {"skipped": "no native runtime: the native prep tensors "
                "are the shared pair workload"}
    from reporter_tpu.graph.route import RouteCache, candidate_route_matrices
    from reporter_tpu.graph.route_device import DeviceRouteKernel
    from reporter_tpu.graph.spatial import CandidateSet
    from reporter_tpu.matcher.batchpad import prepare_batch

    kern = DeviceRouteKernel(city)
    sub = [r["trace"] for r in reqs[:n_chunk]]
    T = matcher.prepare(sub[0]).T
    host = prepare_batch(matcher.runtime, sub, params, T, n_threads=0)
    prep = dict(host.prep)
    B = len(sub)

    def _trace_cands(b):
        nk = int(prep["num_kept"][b])
        edge = prep["edge_ids"][b, :nk]
        off = prep["offset_m"][b, :nk]
        z = np.zeros_like(off)
        cands = CandidateSet(edge_ids=edge, dist_m=prep["dist_m"][b, :nk],
                             offset_m=off, proj_x=z, proj_y=z)
        gc = prep["gc_m"][b, :max(nk - 1, 0)]
        dt = prep["dt"][b, :max(nk - 1, 0)] \
            if params.max_route_time_factor > 0 and nk > 1 else None
        return nk, cands, gc, dt

    kw = dict(max_route_distance_factor=params.max_route_distance_factor,
              backward_tolerance_m=params.backward_tolerance_m,
              max_route_time_factor=params.max_route_time_factor,
              min_time_bound_s=params.min_time_bound_s,
              turn_penalty_factor=params.turn_penalty_factor)
    cache = RouteCache(city)

    # -- parity BEFORE timing: all three paths, identical pairs ----------
    n_pairs = 0
    for b in range(B):
        nk, cands, gc, dt = _trace_cands(b)
        if nk < 2:
            continue
        oracle = prep["route_m"][b, :nk - 1]
        nat = matcher.runtime.route_matrices(cands, gc, dt=dt, **kw)
        np_route = candidate_route_matrices(city, cands, gc, cache=cache,
                                            dt=dt, **kw)
        if not np.array_equal(oracle, nat):
            raise RuntimeError(f"native route paths disagree on trace {b} "
                               "— parity broken, timings void")
        if not np.allclose(oracle, np_route, rtol=1e-5, atol=1e-3):
            raise RuntimeError(f"numpy route reference disagrees on trace "
                               f"{b} — parity broken, timings void")
        n_pairs += int((cands.edge_ids[:-1] != -1).sum()) \
            * cands.edge_ids.shape[1]
    dev = dict(prep)
    dev["route_m"] = prep["route_m"].copy()
    dev["max_finite"] = prep["max_finite"].copy()
    kern.fill_prep(dev, params, B)  # also warms the jit cache
    if not np.array_equal(dev["route_m"], prep["route_m"]):
        raise RuntimeError("device route tensor differs from the host "
                           "oracle — parity broken, timings void")

    # -- timed legs over the identical, parity-proven workload -----------
    best_dev = best_host = best_nat = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        kern.fill_prep(dev, params, B)
        best_dev = min(best_dev, time.perf_counter() - t0)
    for _ in range(repeats):
        t0 = time.perf_counter()
        for b in range(B):
            nk, cands, gc, dt = _trace_cands(b)
            if nk >= 2:
                candidate_route_matrices(city, cands, gc, cache=cache,
                                         dt=dt, **kw)
        best_host = min(best_host, time.perf_counter() - t0)
    for _ in range(repeats):
        t0 = time.perf_counter()
        for b in range(B):
            nk, cands, gc, dt = _trace_cands(b)
            if nk >= 2:
                matcher.runtime.route_matrices(cands, gc, dt=dt, **kw)
        best_nat = min(best_nat, time.perf_counter() - t0)
    return {
        "n_traces": B,
        "T": int(T),
        "n_pairs": n_pairs,
        "parity": "byte-identical",
        "device_s": round(best_dev, 6),
        "host_s": round(best_host, 6),
        "native_s": round(best_nat, 6),
        "device_vs_host": round(best_host / best_dev, 2),
        "device_vs_native": round(best_nat / best_dev, 2),
    }


def main():
    n_traces = int(os.environ.get("BENCH_TRACES", 512))
    n_base = int(os.environ.get("BENCH_BASELINE_TRACES", 128))
    T_bucket = int(os.environ.get("BENCH_T", 64))
    K = int(os.environ.get("BENCH_K", 8))
    repeats = int(os.environ.get("BENCH_REPEATS", 5))
    base_repeats = int(os.environ.get("BENCH_BASELINE_REPEATS", 3))

    # bounded-patience accelerator init: probe the chip in a subprocess
    # (bounded, retried, env-tunable patience), fall back to CPU and say
    # so in the artifact rather than exiting nonzero on a tunnel flake
    from reporter_tpu.utils import runtime as rt

    # ONE probe per process tree: the first verdict lands in a temp file
    # every later probe site (the gate below, ensure_backend's retries,
    # child processes) reads back — BENCH_r05 burned ~6 min on 4
    # sequential 90 s probe timeouts before the CPU fallback
    if not os.environ.get(rt.ENV_PROBE_CACHE):
        import tempfile
        fd, probe_cache = tempfile.mkstemp(prefix="reporter_probe_",
                                           suffix=".json")
        os.close(fd)
        os.unlink(probe_cache)  # empty file would read as no-verdict anyway
        os.environ[rt.ENV_PROBE_CACHE] = probe_cache

    # pipelined-lane probe BEFORE any in-parent accelerator init: the
    # chip is single-client, so the child must attach while this process
    # does NOT hold it (probing against a held chip measures contention,
    # not pipeline viability). Sequence: cheap reachability probe ->
    # (only if reachable) pipelined child -> ensure_backend init.
    probe_pipelined = None
    forced = (os.environ.get(rt.ENV_PLATFORM) or "auto").lower()
    # falsy (unset OR empty) matches pipeline_enabled()'s own parsing,
    # so the gate and the matcher can't disagree about "" meaning auto
    pipeline_unset = not os.environ.get("REPORTER_TPU_PIPELINE", "").strip()
    if forced != "cpu" and pipeline_unset \
            and rt.accelerator_available(tries=1):
        ok, probe_pipelined = _probe_pipelined_accel(
            float(os.environ.get("BENCH_PIPE_PROBE_TIMEOUT", 240)))
        if not ok:
            os.environ["REPORTER_TPU_PIPELINE"] = "0"

    # 3 tries by default for the artifact run; an explicit env var wins
    # (parsed by the runtime's tolerant _env_int, not re-parsed here).
    # On a healthy chip this re-probe is one redundant attach after the
    # gate just proved one — accepted: ensure_backend's probe + init are
    # one audited unit, and the extra round trip is bounded patience,
    # not artifact risk.
    rt.ensure_backend(
        probe_tries=None if os.environ.get(rt.ENV_PROBE_TRIES) else 3)

    import jax

    # a reachability flake can skip the gate while ensure_backend's
    # 3-try probe still lands the accelerator — never run the unproven
    # threaded lanes on hardware the gate didn't clear: serialize and
    # say so in the artifact
    if forced != "cpu" and pipeline_unset and probe_pipelined is None \
            and jax.devices()[0].platform != "cpu":
        probe_pipelined = ("gate skipped (reachability flake); "
                          "serialized defensively")
        os.environ["REPORTER_TPU_PIPELINE"] = "0"

    from reporter_tpu.matcher.assemble import assemble_segments
    from reporter_tpu.matcher.cpu_ref import viterbi_decode_numpy
    from reporter_tpu.ops import decode_backend
    # each leg measures its own architecture end-to-end through the
    # wire: the batched leg serialises via report_wire — the serving
    # path's entry point (native C writer emitting response bytes in
    # one GIL-released call when armed, Python columnar writer
    # otherwise) — while the baseline leg keeps report_json, which for
    # its plain-dict matches IS the reference-shaped dict + json.dumps
    from reporter_tpu.service.report import report_json as make_report
    from reporter_tpu.service.report import report_wire

    platform = jax.devices()[0].platform

    # the chunked overlap path is the architecture being measured: on the
    # CPU fallback the threaded lanes are proven safe (TestDevicePipeline
    # pins identical results), so the batched leg always exercises them
    # unless the operator explicitly said otherwise — the headline then
    # reports pipelined: true with prep overlapping decode/assemble. An
    # accelerator keeps the gate's verdict (unproven tunnel + threads).
    if platform == "cpu" and pipeline_unset:
        os.environ["REPORTER_TPU_PIPELINE"] = "1"

    # the batched leg runs with the device route kernel ON by default
    # (BENCH_ROUTE_DEVICE=0 opts out): the committed artifact measures
    # the chunk-batched relax as the serving route path, with the host
    # Dijkstra held to byte-parity by the routes leg below. An explicit
    # REPORTER_TPU_ROUTE_DEVICE in the environment wins.
    if os.environ.get("BENCH_ROUTE_DEVICE", "1") not in ("0", "off",
                                                         "false"):
        os.environ.setdefault("REPORTER_TPU_ROUTE_DEVICE", "1")

    city, matcher, params, reqs, tb = build_inputs(n_traces, T_bucket, K)
    sigma = np.float32(params.effective_sigma)
    beta = np.float32(params.beta)

    # -- baseline leg: the reference architecture, one trace at a time ----
    # single-threaded prep + numpy Viterbi + assembly + report on the CPU;
    # re-prep included so both legs measure the same end-to-end scope
    # (route caches are warm in both — steady state); best-of-N so the
    # denominator is as steady as the numerator
    n_base = min(n_base, len(reqs))
    base_best = float("inf")
    for _ in range(base_repeats):
        t0 = time.perf_counter()
        for i in range(n_base):
            p = matcher.prepare(reqs[i]["trace"])
            valid = p.edge_ids != -1
            path, _ = viterbi_decode_numpy(p.dist_m, valid, p.route_m,
                                           p.gc_m, p.case, sigma, beta)
            match = assemble_segments(city, p, path)
            make_report(match, reqs[i], 15, {0, 1, 2}, {0, 1, 2})
        base_best = min(base_best, time.perf_counter() - t0)
    baseline_tps = n_base / base_best

    # -- batched leg: the production path end-to-end ----------------------
    matcher.match_many(reqs[:8])  # warmup: compile the bucket shapes
    profile_dir = os.environ.get("BENCH_PROFILE")
    if profile_dir:
        # opt-in device profile of one batched pass (TensorBoard/Perfetto
        # viewable via jax.profiler — utils/metrics.device_trace); a
        # profiler failure must not cost the artifact
        try:
            from reporter_tpu.utils.metrics import device_trace
            with device_trace(profile_dir):
                matcher.match_many(tb)
        except Exception as e:
            print(f"profile pass failed (continuing): {e}",
                  file=sys.stderr)
    best, stages = _time_batched_leg(matcher, tb, reqs, report_wire,
                                     repeats)
    batched_tps = n_traces / best

    # -- wire-backend split: native vs Python vs legacy dict --------------
    # one match pass, three serialisation legs over identical matches —
    # the tentpole's isolated win, committed next to the stage share
    report_writers = _time_report_writers(matcher.match_many(tb), reqs)

    # device-compute telemetry of the whole run (obs/profiler.py): a
    # steady-state bench should compile each decode shape exactly once
    # (in warmup) — recompiles here mean the timed legs paid XLA, and
    # padding_waste is the fixed-bucket overhead the artifact now
    # carries toward the variable-length bucketing work
    from reporter_tpu.obs import profiler
    prof = profiler.snapshot(n_events=0)
    compile_field = {
        "episodes": prof["compile_episodes"],
        "shapes": len(prof["shapes"]),
        "recompiles": sum(max(0, s["compiles"] - 1)
                          for s in prof["shapes"]),
        "compile_s": round(sum(s["compile_s"] for s in prof["shapes"]),
                           6),
        "padding_waste": prof["totals"]["padding_waste"],
    }

    # -- adaptive-bucket before/after pair (ISSUE 13) ---------------------
    # fixed-ladder vs occupancy-driven splitting over one mixed-length
    # batch; runs AFTER compile_field so its profiler resets can't eat
    # the main run's telemetry
    try:
        bucketing_field = _bucketing_leg(city, matcher, reqs)
    except Exception as e:  # record the failure, keep the artifact
        bucketing_field = {"error": str(e)[:200]}

    # -- serving-tier batched-query pair (ISSUE 14) -----------------------
    # query_many(256) vs 256 singles over one synthetic store; parity
    # asserted inside the leg, ratio gated by perf_gate
    try:
        query_field = _query_leg()
    except Exception as e:  # record the failure, keep the artifact
        query_field = {"error": str(e)[:200]}

    # -- route-kernel triple (ISSUE 16) -----------------------------------
    # device relax vs host Dijkstra vs native memo on identical pairs;
    # parity asserted byte-identical inside the leg before any timing
    try:
        routes_field = _routes_leg(city, matcher, params, reqs)
    except Exception as e:  # record the failure, keep the artifact
        routes_field = {"error": str(e)[:200]}

    # -- optional second decode backend: the fused pallas kernel ----------
    # recorded in the same artifact so hardware claims in docstrings trace
    # to a committed number; default-on only where it runs compiled (tpu)
    pallas_field = None
    want_pallas = os.environ.get("BENCH_PALLAS",
                                 "1" if platform == "tpu" else "0")
    if want_pallas not in ("0", "off", "false"):
        saved = os.environ.get("REPORTER_TPU_DECODE")
        os.environ["REPORTER_TPU_DECODE"] = "pallas"
        try:
            matcher.match_many(reqs[:8])  # compile the pallas shapes
            p_best, p_stages = _time_batched_leg(
                matcher, tb, reqs, report_wire, max(2, repeats - 2))
            pallas_field = {"traces_per_sec": round(n_traces / p_best, 1),
                            "stages": p_stages}
        except Exception as e:  # record the failure, keep the artifact
            pallas_field = {"error": str(e)[:200]}
        finally:
            if saved is None:
                os.environ.pop("REPORTER_TPU_DECODE", None)
            else:
                os.environ["REPORTER_TPU_DECODE"] = saved

    print(json.dumps({
        "metric": f"synthetic-city traces/sec map-matched end-to-end "
                  f"(columnar prep+decode+assemble+report-serialise, "
                  f"T={T_bucket}, "
                  f"K={K}, platform={platform}, "
                  f"decode={decode_backend(T_bucket, K)}) "
                  f"batched match_many over a zero-dict TraceBatch vs "
                  f"single-process single-thread CPU numpy baseline "
                  f"(Meili-analog)",
        "value": round(batched_tps, 1),
        "unit": "traces/sec",
        "vs_baseline": round(batched_tps / baseline_tps, 2),
        "stages": stages,
        "report_writers": report_writers,
        "baseline": {"traces_per_sec": round(baseline_tps, 1),
                     "n_traces": n_base, "repeats": base_repeats},
        "compile": compile_field,
        "bucketing": bucketing_field,
        "query": query_field,
        "routes": routes_field,
        "probe": dict(rt.probe_info,
                      **({"pipelined_probe": probe_pipelined}
                         if probe_pipelined else {})),
        "pallas": pallas_field,
    }))
    return 0


if __name__ == "__main__":
    if "--streaming" in sys.argv[1:]:
        # the incremental matcher's per-appended-point leg (ISSUE 19)
        # times growing windows, not bulk replays — its own module,
        # reachable as `python bench.py --streaming` for one-command
        # symmetry with the throughput legs
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from tools import stream_bench
        sys.exit(stream_bench.main(sys.argv[1:]))
    if "--feed-fanout" in sys.argv[1:]:
        # the freshness tier's fan-out leg (ISSUE 18) lives in its own
        # module — a serving bench like tools/prefork_bench.py, not a
        # matcher throughput leg — but rides bench.py's front door so
        # `python bench.py --feed-fanout 1000` is one command
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from tools import feed_fanout_bench
        sys.exit(feed_fanout_bench.main(sys.argv[1:]))
    sys.exit(main())
