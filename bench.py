#!/usr/bin/env python
"""Benchmark: batched TPU map-matching throughput vs the reference's
one-trace-at-a-time single-process architecture.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "traces/sec", "vs_baseline": N}

Method: build a synthetic city, synthesise noisy GPS traces, then time
two END-TO-END legs over the same traces (steady state: route caches
warm, shapes compiled — a long-running city service):

  baseline leg — the reference's architecture (reference:
  py/reporter_service.py:240, Batch.java:66-68 — one C++ Meili call per
  trace on one CPU thread): single-threaded host prep + the pure-numpy
  single-trace Viterbi (matcher/cpu_ref.py) + segment assembly +
  report(), one trace at a time, no accelerator;

  batched leg  — this framework's architecture: SegmentMatcher.match_many
  (thread-pooled host prep, padded batches, vmapped associative-scan
  Viterbi on the accelerator, async d2h, vectorised assembly) + report().

``vs_baseline`` is batched/baseline throughput — the architectural
speedup toward BASELINE.md's >=50x-over-single-process-Meili north star,
with the baseline an honest single-process CPU stand-in, not a batch=1
accelerator call. Env knobs: BENCH_TRACES (default 512),
BENCH_BASELINE_TRACES (default 24), BENCH_T (bucket, default 64),
BENCH_K (default 8), BENCH_REPEATS (default 5).
"""
import json
import os
import sys
import time

import numpy as np


def build_inputs(n_traces, T_bucket, K):
    from reporter_tpu.matcher import MatchParams, SegmentMatcher
    from reporter_tpu.synth import build_grid_city, generate_trace

    city = build_grid_city(rows=20, cols=20, spacing_m=200.0, seed=42)
    params = MatchParams(max_candidates=K)
    matcher = SegmentMatcher(net=city, params=params)
    rng = np.random.default_rng(7)
    prepared, reqs = [], []
    # routes long enough to fill the bucket at ~1 point/sec, then sliced
    min_edges = max(4, T_bucket // 12)
    attempts = 0
    while len(prepared) < n_traces:
        attempts += 1
        if attempts > 50 * n_traces:
            raise RuntimeError(f"could not build T={T_bucket} traces")
        tr = generate_trace(city, f"veh-{len(prepared)}", rng, noise_m=4.0,
                            min_route_edges=min_edges, max_route_edges=60)
        if tr is None or len(tr.points) < T_bucket // 2:
            continue
        points = tr.points[:T_bucket]
        p = matcher.prepare(points)
        if p.T != T_bucket:
            continue
        prepared.append(p)
        req = tr.request_json()
        req["trace"] = points
        req["match_options"] = {"mode": "auto",
                                "report_levels": [0, 1, 2],
                                "transition_levels": [0, 1, 2]}
        reqs.append(req)
    return city, matcher, params, prepared, reqs


def main():
    n_traces = int(os.environ.get("BENCH_TRACES", 512))
    n_base = int(os.environ.get("BENCH_BASELINE_TRACES", 24))
    T_bucket = int(os.environ.get("BENCH_T", 64))
    K = int(os.environ.get("BENCH_K", 8))

    # bounded-patience accelerator init: probe the chip in a subprocess
    # (bounded, retried), fall back to CPU and say so in the metric rather
    # than exiting nonzero on a tunnel flake (round-1 BENCH rc=1)
    from reporter_tpu.utils.runtime import ensure_backend
    ensure_backend(probe_tries=3)

    import jax

    from reporter_tpu.matcher import MatchParams
    from reporter_tpu.matcher.assemble import assemble_segments
    from reporter_tpu.matcher.cpu_ref import viterbi_decode_numpy
    from reporter_tpu.ops import decode_backend
    from reporter_tpu.service.report import report as make_report

    platform = jax.devices()[0].platform
    city, matcher, params, prepared, reqs = build_inputs(
        n_traces, T_bucket, K)
    sigma = np.float32(params.effective_sigma)
    beta = np.float32(params.beta)

    # -- baseline leg: the reference architecture, one trace at a time ----
    # single-threaded prep + numpy Viterbi + assembly + report on the CPU;
    # re-prep included so both legs measure the same end-to-end scope
    # (route caches are warm in both — steady state)
    n_base = min(n_base, len(reqs))
    t0 = time.perf_counter()
    for i in range(n_base):
        p = matcher.prepare(reqs[i]["trace"])
        valid = p.edge_ids != -1
        path, _ = viterbi_decode_numpy(p.dist_m, valid, p.route_m, p.gc_m,
                                       p.case, sigma, beta)
        match = assemble_segments(city, p, path)
        make_report(match, reqs[i], 15, {0, 1, 2}, {0, 1, 2})
    baseline_tps = n_base / (time.perf_counter() - t0)

    # -- batched leg: the production path end-to-end ----------------------
    # match_many = thread-pooled prep + padded batches + device decode
    # (sharded if a mesh is up) + vectorised assembly; then report()
    matcher.match_many(reqs[:8])  # warmup: compile the bucket shapes
    best = float("inf")
    for _ in range(int(os.environ.get("BENCH_REPEATS", 5))):
        t0 = time.perf_counter()
        matches = matcher.match_many(reqs)
        for req, match in zip(reqs, matches):
            make_report(match, req, 15, {0, 1, 2}, {0, 1, 2})
        best = min(best, time.perf_counter() - t0)
    batched_tps = n_traces / best

    print(json.dumps({
        "metric": f"synthetic-city traces/sec map-matched end-to-end "
                  f"(prep+decode+assemble+report, T={T_bucket}, K={K}, "
                  f"platform={platform}, decode={decode_backend(T_bucket, K)}) "
                  f"batched match_many vs single-process single-thread "
                  f"CPU numpy baseline (Meili-analog)",
        "value": round(batched_tps, 1),
        "unit": "traces/sec",
        "vs_baseline": round(batched_tps / baseline_tps, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
