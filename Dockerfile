# Single image containing every service in the framework — the matcher
# HTTP service (default CMD), the streaming worker, the batch pipeline,
# and the ops tools — mirroring the reference's one-image layout
# (reference: Dockerfile:1-56, which bundled the Java worker and the
# Python matcher service with Valhalla installed from a PPA).
#
# TPU deployments build FROM a jax[tpu] base on the TPU VM instead of
# installing jax[cpu]; everything else is identical.
FROM python:3.12-slim

# native toolchain for the C++ host runtime (the reference instead
# apt-installed prebuilt valhalla, Dockerfile:29-32)
RUN apt-get update && \
    apt-get install -y --no-install-recommends g++ make curl && \
    rm -rf /var/lib/apt/lists/*

# CPU jax by default; TPU images override (see comment above)
RUN pip install --no-cache-dir "jax[cpu]" numpy

WORKDIR /srv/reporter
COPY reporter_tpu/ reporter_tpu/
COPY tests/ tests/
COPY bench.py README.md ./

# build the C++ host runtime (spatial index + bounded Dijkstra,
# native/src/host_runtime.cpp)
RUN make -C reporter_tpu/native

# bake a default synthetic-city graph + matcher config so the image runs
# out of the box; production mounts a real graph over /data (the
# reference instead baked a valhalla config + tile dir, Dockerfile:42-49)
RUN mkdir -p /data && \
    python -m reporter_tpu graph build-synth --rows 20 --cols 20 \
        --spacing-m 200 --seed 0 --out /data/graph.npz && \
    printf '{"graph": "/data/graph.npz"}\n' > /data/reporter.json

ENV PYTHONUNBUFFERED=1 \
    THRESHOLD_SEC=15 \
    MATCH_BATCH_MAX=256 \
    MATCH_BATCH_WAIT_MS=20

EXPOSE 8002
# default service, like the reference's CMD reporter_service.py
# (Dockerfile:55); other entry points:
#   python -m reporter_tpu stream ...      (streaming worker)
#   python -m reporter_tpu pipeline ...    (historical batch pipeline)
CMD ["python", "-m", "reporter_tpu", "serve", "/data/reporter.json", \
     "0.0.0.0:8002"]
