#!/usr/bin/env python
"""The overload proof: admission control keeps p99 inside the SLO.

An open-loop arrival generator (arrivals keep coming whether or not
responses return — the only honest overload model; a closed loop
self-throttles and hides the queue) drives the REAL service stack at a
multiple of its measured capacity, twice:

  unshed  admission disarmed, dispatcher queue unbounded — the
          pre-ISSUE-15 behaviour. Every request is admitted, the queue
          grows for the whole leg, and p99 blows through the SLO
          budget: the leg MUST breach, or the harness has no overload
          to prove anything about.
  armed   ``REPORTER_TPU_ADMISSION=1`` + a bounded queue. The gate
          sheds at the door with 429 + Retry-After; the requests it
          ADMITS ride a bounded queue and must meet the budget.

Gates (all hard):
  - armed-leg p99 over admitted (200) responses <= the SLO budget;
  - armed-leg goodput (200s inside the budget, per second) >= the
    unshed leg's — shedding must BUY something, not just refuse work;
  - the unshed leg breaches the same budget (the control);
  - zero silent loss: every arrival is accounted as a 200, a counted
    429 carrying a positive ``retry_after_s``, or a counted error —
    and the shed counters (``admission.shed.*`` +
    ``dispatch.queue.{rejected,evicted}``) cover every 429;
  - the pressure ladder stepped down at least one rung during the
    armed leg (sustained shed pressure is exactly what it watches).

Usage:
    REPORTER_TPU_PLATFORM=cpu python tools/overload.py [--smoke]
        [--duration S] [--factor F] [--out overload.json]

``--smoke`` is the CI shape (short leg, clamped rate). The artifact
records both legs for debugging; tools/chaos.py ``overload_recovery``
proves the recovery half (ladder steps back up, spools drain).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")


def log(msg: str) -> None:
    print(f"overload: {msg}", flush=True)


def fail(msg: str) -> int:
    sys.stderr.write(f"overload: FAIL: {msg}\n")
    return 1


def _city():
    from reporter_tpu.synth import build_grid_city
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=11,
                           service_road_fraction=0.0,
                           internal_fraction=0.0)


def _requests(city, n: int):
    import numpy as np

    from reporter_tpu.synth import generate_trace
    out = []
    seed = 0
    while len(out) < n:
        seed += 1
        rng = np.random.default_rng(seed)
        tr = generate_trace(city, f"veh-{seed}", rng, noise_m=3.0,
                            min_route_edges=6)
        if tr is None:
            continue
        out.append({"uuid": tr.uuid, "trace": tr.points,
                    "match_options": {"mode": "auto",
                                      "report_levels": [0, 1],
                                      "transition_levels": [0, 1]}})
    return out


def _fresh_service(matcher, max_batch: int,
                   floor_per_trace_s: float = 0.0):
    """A fresh ReporterService (and so a fresh dispatcher + gate built
    from the CURRENT env) over a shared, warm matcher.

    ``floor_per_trace_s`` adds a deterministic per-trace service-time
    floor around the REAL match call — the stand-in for device decode
    cost on hardware where it dominates. The control plane under test
    (gate, EWMA model, bounded queue, ladder) sees exactly what it
    would see there, while a 2-core CI box reaches saturation at a few
    hundred open-loop threads instead of a few thousand. ``0`` runs
    the raw stack (a real accelerator box drives the rate up instead).
    """
    from reporter_tpu.service.server import ReporterService
    service = ReporterService(matcher, threshold_sec=15,
                              max_batch=max_batch, max_wait_ms=10.0)
    if floor_per_trace_s > 0.0:
        orig = service.dispatcher._match_many

        def floored(batch):
            time.sleep(floor_per_trace_s * len(batch))
            return orig(batch)

        service.dispatcher._match_many = floored
    return service


def _call(service, trace):
    """One request through the same gate -> handle -> release path the
    HTTP handler runs; returns (status, retry_after_s or None,
    latency_s)."""
    t0 = time.monotonic()
    gate = service.admission
    if gate is not None:
        shed = gate.admit()
        if shed is not None:
            return 429, shed.retry_after_s, time.monotonic() - t0
    try:
        code, body = _handle_timed(service, dict(trace))
    finally:
        if gate is not None:
            gate.release()
    retry = None
    if code == 429:  # the bounded-queue backstop inside handle()
        try:
            retry = json.loads(body).get("retry_after_s")
        except Exception:
            pass
    return code, retry, time.monotonic() - t0


def _handle_timed(service, trace):
    """service.handle under the same stage timer the HTTP handler uses,
    so the gate's windowed-p99 SLO sensor sees the same histogram a
    real deployment feeds it."""
    from reporter_tpu.utils import metrics
    with metrics.timer("service.handle"):
        return service.handle(trace)


def _warm(service, reqs, n: int = 4) -> None:
    """Prime a fresh leg's dispatcher EWMA (and the windowed SLO
    sensor) with a few sequential requests, outside the measurement:
    a gate with no service-time estimate yet cannot run its deadline
    check, and a real fleet is never cold when the spike arrives.
    Batched warm-ups cover the (rows, T) decode shapes the open loop
    will form, so no measured request pays a one-time XLA compile —
    compile noise is real but it is PR 8's story, not this proof's."""
    for size in (1, 2, 3, 4, 6, 8, 16, 32):
        service.dispatcher.submit_many(
            [dict(r) for r in reqs[:size]])
    for req in reqs[:n]:
        _call(service, req)


def _open_loop(service, reqs, rate_hz: float, n: int):
    """Fire ``n`` arrivals at a fixed open-loop rate, one thread per
    arrival (arrivals never wait for responses); returns the result
    list [(status, retry_after_s, latency_s)]."""
    results = []
    res_lock = threading.Lock()

    def one(req):
        got = _call(service, req)
        with res_lock:
            results.append(got)

    threads = []
    t0 = time.monotonic()
    for i in range(n):
        wait = (t0 + i / rate_hz) - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        th = threading.Thread(target=one, args=(reqs[i % len(reqs)],),
                              daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=120.0)
    alive = sum(1 for th in threads if th.is_alive())
    if alive:
        raise RuntimeError(f"{alive} requests never completed")
    return results


def _p99(latencies):
    if not latencies:
        return None
    ordered = sorted(latencies)
    return ordered[min(len(ordered) - 1,
                       max(0, int(0.99 * len(ordered)) - 1))]


def _leg_stats(results, budget_s: float, wall_s: float) -> dict:
    oks = [r for r in results if r[0] == 200]
    sheds = [r for r in results if r[0] == 429]
    errors = [r for r in results if r[0] not in (200, 429)]
    ok_lat = [r[2] for r in oks]
    in_budget = sum(1 for lt in ok_lat if lt <= budget_s)
    return {
        "sent": len(results),
        "ok": len(oks),
        "shed": len(sheds),
        "errors": len(errors),
        "shed_missing_retry_after": sum(
            1 for r in sheds if not r[1] or r[1] <= 0),
        "p50_ms": round(sorted(ok_lat)[len(ok_lat) // 2] * 1000.0, 1)
        if ok_lat else None,
        "p99_ms": round(_p99(ok_lat) * 1000.0, 1) if ok_lat else None,
        "goodput_per_s": round(in_budget / wall_s, 2),
        "in_budget": in_budget,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="overload")
    parser.add_argument("--smoke", action="store_true",
                        help="CI shape: short legs, clamped rate")
    parser.add_argument("--duration", type=float, default=None,
                        help="seconds per open-loop leg")
    parser.add_argument("--factor", type=float, default=2.0,
                        help="arrival rate as a multiple of capacity")
    parser.add_argument("--max-requests", type=int, default=600,
                        help="cap on arrivals per leg (thread bound)")
    parser.add_argument("--service-floor-ms", type=float, default=20.0,
                        help="deterministic per-trace service floor "
                        "(device-cost stand-in; 0 = raw stack)")
    parser.add_argument("--out", default=None,
                        help="write the artifact JSON here")
    args = parser.parse_args(argv)
    floor_s = max(0.0, args.service_floor_ms / 1000.0)
    duration = args.duration if args.duration is not None \
        else (4.0 if args.smoke else 8.0)

    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.service import admission
    from reporter_tpu.utils import metrics

    city = _city()
    matcher = SegmentMatcher(net=city)
    reqs = _requests(city, 24)

    # ---- calibration: sequential closed-loop, admission off ---------
    for key in ("REPORTER_TPU_ADMISSION", "REPORTER_TPU_SLO_MS"):
        os.environ.pop(key, None)
    service = _fresh_service(matcher, max_batch=32,
                             floor_per_trace_s=floor_s)
    for req in reqs[:4]:   # warm the compile caches out of the timing
        _call(service, req)
    t0 = time.monotonic()
    n_cal = 24
    for i in range(n_cal):
        code, _retry, _lat = _call(service, reqs[i % len(reqs)])
        if code != 200:
            return fail(f"calibration request failed with {code}")
    mean_s = (time.monotonic() - t0) / n_cal
    service.dispatcher.close()
    capacity_hz = 1.0 / mean_s
    rate_hz = min(args.factor * capacity_hz, 80.0 if args.smoke
                  else 150.0)
    n_arrivals = min(int(rate_hz * duration), args.max_requests)
    # SLO budget: generous vs the unloaded mean (12x — room for the
    # bounded queue, the admitted request's own batch, and a busy
    # 2-core box's scheduler jitter), tiny vs the queue an unshed
    # 2x-capacity leg builds (its tail grows with the LEG, not the
    # service time)
    budget_s = max(0.3, 12.0 * mean_s)
    budget_ms = int(budget_s * 1000.0)
    log(f"calibrated: mean {mean_s * 1000.0:.1f} ms -> capacity "
        f"{capacity_hz:.1f}/s; driving {rate_hz:.1f}/s x "
        f"{n_arrivals} arrivals, SLO {budget_ms} ms")

    artifact = {"kind": "overload", "mean_service_ms":
                round(mean_s * 1000.0, 2),
                "rate_hz": round(rate_hz, 2), "arrivals": n_arrivals,
                "slo_budget_ms": budget_ms, "legs": {}}
    wall = n_arrivals / rate_hz

    # ---- leg 1: unshed (the control) --------------------------------
    metrics.default.reset()
    admission._reset_module()
    os.environ["REPORTER_TPU_QUEUE_MAX"] = "0"      # unbounded
    os.environ["REPORTER_TPU_SLO_MS"] = f"service.handle={budget_ms}"
    service = _fresh_service(matcher, max_batch=32,
                             floor_per_trace_s=floor_s)
    _warm(service, reqs)
    unshed = _leg_stats(_open_loop(service, reqs, rate_hz, n_arrivals),
                        budget_s, wall)
    service.dispatcher.close()
    artifact["legs"]["unshed"] = unshed
    log(f"unshed: {unshed}")

    # ---- leg 2: admission armed --------------------------------------
    metrics.default.reset()
    admission._reset_module()
    os.environ["REPORTER_TPU_ADMISSION"] = "1"
    os.environ["REPORTER_TPU_PRESSURE_HOLD_S"] = "1.0"
    # bound the queue so even a full one drains inside ~a third of the
    # budget: the admitted request still pays its own batch (budget/4)
    # plus a busy box's scheduler jitter on top of the queue wait
    qmax = max(6, int(0.35 * budget_s * capacity_hz))
    os.environ["REPORTER_TPU_QUEUE_MAX"] = str(qmax)
    # latency-targeted micro-batching: batches shrink so no admitted
    # request hides behind a whole fixed-size batch in service — the
    # EWMA flush model is half of what this harness proves
    os.environ["REPORTER_TPU_BATCH_LATENCY_MS"] = str(
        max(40, budget_ms // 4))
    # in-flight backstop: binds from the very first arrival (the
    # deadline check needs an EWMA; this cap does not) and closes the
    # admit->enqueue race — N handler threads admitted against the
    # same stale queue depth cannot overshoot the wait the deadline
    # check predicted, because admitted-but-unanswered is itself capped
    # at the queue bound
    os.environ["REPORTER_TPU_INFLIGHT_MAX"] = str(qmax)
    service = _fresh_service(matcher, max_batch=32,
                             floor_per_trace_s=floor_s)
    _warm(service, reqs)
    armed = _leg_stats(_open_loop(service, reqs, rate_hz, n_arrivals),
                       budget_s, wall)
    reg = metrics.default
    armed["counters"] = {
        name: reg.counter(name) for name in
        ("admission.admitted", "admission.shed.queue",
         "admission.shed.slo", "admission.shed.inflight",
         "admission.errors", "dispatch.queue.rejected",
         "dispatch.queue.evicted")}
    armed["pressure_level_seen"] = admission.current_level()
    service.dispatcher.close()
    artifact["legs"]["armed"] = armed
    log(f"armed: {armed}")

    # cleanup env for whoever runs next in this interpreter
    for key in ("REPORTER_TPU_ADMISSION", "REPORTER_TPU_SLO_MS",
                "REPORTER_TPU_QUEUE_MAX",
                "REPORTER_TPU_PRESSURE_HOLD_S",
                "REPORTER_TPU_BATCH_LATENCY_MS",
                "REPORTER_TPU_INFLIGHT_MAX"):
        os.environ.pop(key, None)
    admission._reset_module()

    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=1, sort_keys=True)
        log(f"artifact -> {args.out}")

    # ---- the gates ---------------------------------------------------
    budget_p99 = budget_ms
    if unshed["errors"]:
        return fail(f"unshed leg had {unshed['errors']} hard errors")
    if unshed["p99_ms"] is None or unshed["p99_ms"] <= budget_p99:
        return fail(f"unshed leg did not breach the SLO "
                    f"(p99 {unshed['p99_ms']} ms <= {budget_p99} ms) — "
                    "no overload was generated; the armed leg proves "
                    "nothing")
    if armed["errors"]:
        return fail(f"armed leg had {armed['errors']} hard errors")
    if armed["ok"] == 0:
        return fail("armed leg admitted nothing — the gate is shedding "
                    "everything, which is an outage with extra steps")
    if armed["p99_ms"] is None or armed["p99_ms"] > budget_p99:
        return fail(f"admitted-request p99 {armed['p99_ms']} ms "
                    f"breached the SLO budget {budget_p99} ms with "
                    "admission armed")
    if armed["goodput_per_s"] < unshed["goodput_per_s"]:
        return fail(f"armed goodput {armed['goodput_per_s']}/s fell "
                    f"below unshed {unshed['goodput_per_s']}/s — "
                    "shedding made things worse")
    if armed["shed_missing_retry_after"]:
        return fail(f"{armed['shed_missing_retry_after']} shed "
                    "responses carried no positive Retry-After")
    counted = sum(v for k, v in armed["counters"].items()
                  if k.startswith(("admission.shed.",
                                   "dispatch.queue.rejected",
                                   "dispatch.queue.evicted")))
    if counted < armed["shed"]:
        return fail(f"{armed['shed']} sheds but only {counted} counted "
                    "— silent loss on the shed path")
    if armed["sent"] != armed["ok"] + armed["shed"] + armed["errors"]:
        return fail("armed leg arrivals do not reconcile: "
                    f"{armed['sent']} != {armed['ok']} + "
                    f"{armed['shed']} + {armed['errors']}")
    if armed["pressure_level_seen"] < 1:
        return fail("sustained shedding never stepped the pressure "
                    "ladder down a rung")
    log(f"ok: armed p99 {armed['p99_ms']} ms <= {budget_p99} ms with "
        f"goodput {armed['goodput_per_s']}/s (unshed breached at "
        f"{unshed['p99_ms']} ms, goodput {unshed['goodput_per_s']}/s); "
        f"{armed['shed']} sheds, all counted, all with Retry-After")
    return 0


if __name__ == "__main__":
    sys.exit(main())
