#!/usr/bin/env python
"""CLI for the perf ledger (library: reporter_tpu/obs/ledger.py).

``LEDGER.jsonl`` is the normalised perf history every
``BENCH_r0*``/``BENCH_DEV_r0*``/``MULTICHIP_r0*`` artifact flattens
into — vs_baseline *ratios* and per-stage *shares* of wall (never
absolutes: bench boxes drift ~2x), each entry carrying its round's
box-drift context note. ``tools/perf_gate.py`` gates CI against the
ledger medians.

Usage:
    python tools/perf_ledger.py seed  [--out LEDGER.jsonl] [--repo DIR]
    python tools/perf_ledger.py append ARTIFACT.json [--out LEDGER.jsonl]
        [--source LABEL] [--context NOTE] [--kind KIND]
    python tools/perf_ledger.py show  [--ledger LEDGER.jsonl]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from reporter_tpu.obs import ledger  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="perf_ledger",
                                     description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_seed = sub.add_parser("seed", help="(re)build the ledger from the "
                            "checked-in BENCH_*/MULTICHIP_* artifacts")
    p_seed.add_argument("--out", default=ledger.DEFAULT_LEDGER)
    p_seed.add_argument("--repo", default=REPO)

    p_app = sub.add_parser("append", help="append one bench.py artifact")
    p_app.add_argument("artifact", help="bench.py output JSON file, "
                       "or '-' for stdin")
    p_app.add_argument("--out", default=ledger.DEFAULT_LEDGER)
    p_app.add_argument("--source", default=None,
                       help="source label (default: the file name)")
    p_app.add_argument("--context", default=None,
                       help="box-drift / provenance note to carry along")
    p_app.add_argument("--kind", default="bench",
                       choices=("bench", "bench_dev"))

    p_show = sub.add_parser("show", help="print the ledger, one line "
                            "per entry")
    p_show.add_argument("--ledger", default=ledger.DEFAULT_LEDGER)

    args = parser.parse_args(argv)

    if args.cmd == "seed":
        entries = ledger.seed_entries(args.repo)
        ledger.write_ledger(args.out, entries)
        gated = sum(1 for e in entries if e["vs_baseline"] is not None)
        print(f"seeded {len(entries)} entries ({gated} with ratios) "
              f"-> {args.out}")
        return 0

    if args.cmd == "append":
        if args.artifact == "-":
            parsed = json.load(sys.stdin)
            source = args.source or "stdin"
        else:
            with open(args.artifact, encoding="utf-8") as f:
                parsed = json.load(f)
            source = args.source or os.path.basename(args.artifact)
        label = time.strftime("%Y-%m-%dT%H:%MZ", time.gmtime())
        entry = ledger.entry_from_bench(parsed, source, label,
                                        args.kind,
                                        context=args.context)
        with open(args.out, "a", encoding="utf-8") as f:
            f.write(json.dumps(entry, separators=(",", ":")) + "\n")
        print(f"appended {source} (vs_baseline="
              f"{entry['vs_baseline']}, scope={entry['scope']}) "
              f"-> {args.out}")
        return 0

    # show
    for e in ledger.load_ledger(args.ledger):
        shares = e.get("stage_shares") or {}
        print(f"{e['label']:<24} {e['kind']:<10} "
              f"scope={e.get('scope', 'full'):<6} "
              f"plat={e.get('platform') or '-':<5} "
              f"vs_baseline={e.get('vs_baseline') or '-':<7} "
              f"prep_share={shares.get('prep', '-')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
