#!/usr/bin/env python
"""trace_cli: Chrome/Perfetto trace-event export for reporter traces.

Two subcommands:

  convert   Turn recorded span JSON into a trace-event file that
            chrome://tracing or https://ui.perfetto.dev loads directly.
            Accepts any of the three shapes this framework emits:
              - a flight-recorder postmortem dump
                (``.flightrec/flightrec-<pid>-*.json``: spans + in_flight)
              - a ``?trace=1`` /report response ({"report":..., "trace":...})
              - a bare span-record list or a {"traceEvents": [...]} object
                (already-exported traces pass through unchanged)

  record    Run one synthetic /report request through the real stack
            with tracing armed and write its trace-event JSON — the
            zero-setup way to SEE the pipeline (service -> dispatcher ->
            matcher prep/decode/assemble -> serialisation) on a timeline.

Usage:
  python tools/trace_cli.py convert <in.json> [-o out.json]
  python tools/trace_cli.py record [-o out.json] [--traces N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")


def spans_from_payload(payload):
    """(closed spans, in-flight spans) from any recognised JSON shape;
    already-exported traceEvents come back as (None, events)."""
    if isinstance(payload, list):
        return payload, []
    if not isinstance(payload, dict):
        raise ValueError("unrecognised trace payload (want a JSON "
                         "object or span list)")
    if "traceEvents" in payload:
        return None, payload["traceEvents"]
    if "trace" in payload and isinstance(payload["trace"], dict) \
            and "traceEvents" in payload["trace"]:
        return None, payload["trace"]["traceEvents"]
    if "spans" in payload:  # flight-recorder dump
        return payload.get("spans", []), payload.get("in_flight", [])
    raise ValueError("unrecognised trace payload (no spans / "
                     "traceEvents / trace key)")


def cmd_convert(args) -> int:
    from reporter_tpu.obs import trace as obs_trace

    with open(args.input, encoding="utf-8") as f:
        payload = json.load(f)
    spans, extra = spans_from_payload(payload)
    if spans is None:
        obj = {"traceEvents": extra, "displayTimeUnit": "ms"}
    else:
        obj = obs_trace.to_trace_events(spans, in_flight=extra)
    out = args.output or (os.path.splitext(args.input)[0] + ".trace.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(obj, f, separators=(",", ":"))
    print(f"{len(obj['traceEvents'])} events -> {out} "
          "(load in chrome://tracing or ui.perfetto.dev)")
    return 0


def cmd_record(args) -> int:
    from reporter_tpu.utils.runtime import force_virtual_cpu
    force_virtual_cpu()

    import numpy as np

    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.obs import trace as obs_trace
    from reporter_tpu.service.server import ReporterService
    from reporter_tpu.synth import build_grid_city, generate_trace

    city = build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=5,
                           service_road_fraction=0.0,
                           internal_fraction=0.0)
    rng = np.random.default_rng(7)
    reqs = []
    for i in range(args.traces):
        tr = None
        while tr is None:
            tr = generate_trace(city, f"trace-cli-{i}", rng, noise_m=3.0,
                                min_route_edges=8)
        reqs.append({"uuid": tr.uuid, "trace": tr.points,
                     "match_options": {"mode": "auto",
                                       "report_levels": [0, 1, 2],
                                       "transition_levels": [0, 1, 2]}})
    service = ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                              max_batch=64, max_wait_ms=5.0)
    service.handle(reqs[0])  # warm the jit caches outside the recording

    obs_trace.force_begin()
    try:
        with obs_trace.span("service.request", source="trace_cli") as root:
            for req in reqs:
                code, _body = service.handle(req)
                if code != 200:
                    print(f"request failed ({code})", file=sys.stderr)
                    return 1
        obj = obs_trace.export_trace(root)
    finally:
        obs_trace.force_end()
    out = args.output or "reporter_trace.json"
    with open(out, "w", encoding="utf-8") as f:
        json.dump(obj, f, separators=(",", ":"))
    print(f"{len(obj['traceEvents'])} events over {args.traces} "
          f"request(s) -> {out} (load in chrome://tracing or "
          "ui.perfetto.dev)")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="trace_cli", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_conv = sub.add_parser("convert", help="span JSON -> trace events")
    p_conv.add_argument("input")
    p_conv.add_argument("-o", "--output")
    p_rec = sub.add_parser("record", help="record one traced request")
    p_rec.add_argument("-o", "--output")
    p_rec.add_argument("--traces", type=int, default=1)
    args = parser.parse_args(argv)
    if args.cmd == "convert":
        return cmd_convert(args)
    return cmd_record(args)


if __name__ == "__main__":
    sys.exit(main())
