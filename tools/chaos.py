#!/usr/bin/env python
"""Chaos harness: scripted failure scenarios over the REAL stack.

Every robustness mechanism in this repo is provable, or it is a story:
the deterministic failpoint layer (reporter_tpu/utils/faults.py) arms
named faults with seeded specs, and each scenario below replays a
synthetic stream under one failure domain and asserts the defined
degraded behavior — including *output parity* against a fault-free run
where the mechanism promises it.

Scenarios (run the named ones, default ``storm kill_restore``):

  storm         native prep error storm -> circuit breaker OPENS ->
                chunks served via the numpy fallback BYTE-IDENTICALLY ->
                cooldown -> half-open probe -> circuit re-closes
  kill_restore  crash failpoint (os._exit 137, SIGKILL-grade) at an
                exact mid-stream offer -> restart -> snapshot restore ->
                tile output byte-identical to a fault-free run (no lost
                reports beyond the snapshot window, no duplicate tiles)
  stream_resume incremental matcher (ISSUE 19): a commit-site error ->
                batch-path fallback with byte-identical tiles; then a
                mid-stream SIGKILL -> snapshot v3 restores the carried
                per-trace decode state -> resumed run's final tiles
                byte-identical to fault-free
  submit_burst  matcher 5xx burst -> bounded requeue under the retry
                budget -> recovery without loss; a dead matcher ->
                trace-JSON dead-letter spool instead of silent drops
  egress_outage sink down -> every tile dead-letters -> `datastore
                ingest --delete` replay -> histogram datastore parity
                with a fault-free run
  lease_kill    SIGKILL the datastore writer-lease holder mid-compaction
                -> manifests untorn -> another process steals the dead
                holder's lease -> recovery replay ledger-deduped ->
                store cells equal a fresh fault-free ingest
  swap_kill     SIGKILL a registry worker in the WIDEST map-swap window
                (candidate loaded + shadow-gated, old version serving,
                lease held) -> lease steal clean -> recovery replays the
                pre-swap tree under v1 (deduped) + post-swap tree under
                v2 -> store cells equal a fault-free run, every base
                segment tagged exactly one epoch, pinned views match;
                a pre-swap dead-letter trace spool then drains through
                the NEW graph without crashing

Usage:
  REPORTER_TPU_PLATFORM=cpu python tools/chaos.py [scenario ...]
  (``all`` runs every scenario; REPORTER_TPU_CHAOS_REQUIRE_NATIVE=1
  makes a missing native runtime a failure instead of a skip — CI sets
  it so the storm scenario can never silently stop testing the breaker)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")  # never probe a chip

FMT = r",sv,\|,0,1,2,3,4"  # uuid|lat|lon|time|accuracy


def log(msg: str) -> None:
    print(f"chaos: {msg}", flush=True)


def fail(msg: str) -> int:
    sys.stderr.write(f"chaos: FAIL: {msg}\n")
    return 1


def _city():
    from reporter_tpu.synth import build_grid_city
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=5,
                           service_road_fraction=0.0, internal_fraction=0.0)


def _lines(city, n_traces: int, seed: int = 9):
    import numpy as np
    from reporter_tpu.synth import generate_trace
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n_traces):
        tr = None
        while tr is None:
            tr = generate_trace(city, f"veh-{i}", rng, noise_m=3.0,
                                min_route_edges=8)
        for p in tr.points:
            lines.append("|".join([tr.uuid, str(p["lat"]), str(p["lon"]),
                                   str(p["time"]), str(p["accuracy"])]))
    return lines


def _make_worker(city, out_dir: str, state_path=None,
                 report_flush_interval_s: float = 1e9):
    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.service.server import ReporterService
    from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
    from reporter_tpu.streaming.formatter import Formatter
    from reporter_tpu.streaming.state import StateStore
    from reporter_tpu.streaming.worker import StreamWorker, inproc_submitter

    service = ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                              max_batch=64, max_wait_ms=5.0)
    return StreamWorker(
        Formatter.from_config(FMT), inproc_submitter(service),
        Anonymiser(TileSink(out_dir), privacy=1, quantisation=3600,
                   source="chaos"),
        reports="0,1,2", transitions="0,1,2", flush_interval_s=1e9,
        state=StateStore(state_path, interval_s=0.0) if state_path else None,
        submit_many=service.report_many,
        report_flush_interval_s=report_flush_interval_s)


def _tile_tree(root: str) -> dict:
    """{relpath: bytes} of every tile file under a sink dir (spools
    excluded) — the byte-parity comparand."""
    out = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in (".deadletter", ".traces",
                                          ".flightrec"))
        for name in sorted(filenames):
            path = os.path.join(dirpath, name)
            with open(path, "rb") as f:
                out[os.path.relpath(path, root)] = f.read()
    return out


def _as_plain(result) -> dict:
    """A match result (dict or lazy MatchRuns) as a canonical dict."""
    return {"segments": [dict(s) for s in result["segments"]],
            "mode": result["mode"]}


# ---------------------------------------------------------------------------
def scenario_storm() -> int:
    """Native error storm: circuit opens, fallback serves byte-identical
    results, cooldown passes, a probe re-closes the circuit."""
    from reporter_tpu import native
    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.utils import faults, metrics

    if not native.available():
        if os.environ.get("REPORTER_TPU_CHAOS_REQUIRE_NATIVE"):
            return fail("native runtime unavailable but required")
        log("storm SKIPPED (native runtime unavailable)")
        return 0

    # cooldown sized so storm calls land well inside it on a slow box
    # (a probe slipping in mid-storm just fails and re-opens, but every
    # probed chunk is one not counted as short-circuited)
    os.environ["REPORTER_TPU_CIRCUIT_THRESHOLD"] = "3"
    os.environ["REPORTER_TPU_CIRCUIT_COOLDOWN_S"] = "3.0"
    try:
        import numpy as np
        from reporter_tpu.synth import generate_trace
        city = _city()
        matcher = SegmentMatcher(net=city)
        if matcher.runtime is None:
            return fail("native runtime did not attach")
        rng = np.random.default_rng(11)
        reqs = []
        for i in range(8):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"storm-{i}", rng, noise_m=3.0,
                                    min_route_edges=8)
            reqs.append({"uuid": tr.uuid, "trace": tr.points,
                         "match_options": {"mode": "auto",
                                           "report_levels": [0, 1, 2],
                                           "transition_levels": [0, 1, 2]}})

        # fault-free reference results through the native path
        want = [_as_plain(r) for r in matcher.match_many(reqs)]
        metrics.default.reset()

        # the storm: every native prep errors until the circuit trips
        # (seeded, prob 1 — replays bit-identically); no fire limit, the
        # breaker itself must stop the bleeding
        faults.configure("native.prep=error@0")
        stormed = []
        for _ in range(5):
            stormed.append([_as_plain(r) for r in matcher.match_many(reqs)])
        snap = metrics.default.snapshot()["counters"]
        if matcher.circuit.snapshot()["state"] not in ("open", "half_open"):
            return fail(f"circuit did not open: {matcher.circuit.snapshot()}")
        if not snap.get("matcher.circuit.opened"):
            return fail(f"no open transition counted: {snap}")
        if not snap.get("matcher.circuit.fallback_chunks"):
            return fail(f"no chunk was short-circuited to the fallback: "
                        f"{snap}")
        for got in stormed:
            if got != want:
                return fail("fallback results diverged from the "
                            "fault-free native run")
        log(f"storm: circuit opened after "
            f"{snap.get('matcher.circuit.native_errors', 0)} native "
            f"errors, {snap.get('matcher.circuit.fallback_chunks')} "
            f"chunks served degraded, results byte-identical")

        # recovery: faults gone, cooldown elapses, one probe re-closes
        faults.clear()
        time.sleep(3.2)
        after = [_as_plain(r) for r in matcher.match_many(reqs)]
        snap = metrics.default.snapshot()["counters"]
        if matcher.circuit.snapshot()["state"] != "closed":
            return fail(f"circuit did not re-close: "
                        f"{matcher.circuit.snapshot()}")
        if not snap.get("matcher.circuit.probes") \
                or not snap.get("matcher.circuit.closed"):
            return fail(f"no half-open probe/close recorded: {snap}")
        if after != want:
            return fail("post-recovery results diverged")
        log(f"storm ok: probe re-closed the circuit "
            f"(probes={snap['matcher.circuit.probes']})")
        return 0
    finally:
        faults.clear()
        os.environ.pop("REPORTER_TPU_CIRCUIT_THRESHOLD", None)
        os.environ.pop("REPORTER_TPU_CIRCUIT_COOLDOWN_S", None)


# ---------------------------------------------------------------------------
def scenario_kill_restore() -> int:
    """SIGKILL-grade crash mid-stream, restart, restore: tile output must
    be byte-identical to an uninterrupted run."""
    from reporter_tpu.utils import faults as faults_mod

    with tempfile.TemporaryDirectory() as tmp:
        city = _city()
        graph = os.path.join(tmp, "city.npz")
        city.save(graph)
        lines = _lines(city, n_traces=8)
        k = len(lines) // 2
        full = os.path.join(tmp, "full.txt")
        tail = os.path.join(tmp, "tail.txt")
        with open(full, "w") as f:
            f.write("\n".join(lines) + "\n")
        with open(tail, "w") as f:
            f.write("\n".join(lines[k:]) + "\n")

        def cmd(inp, out, state):
            return [sys.executable, "-m", "reporter_tpu", "stream",
                    "-f", FMT, "--graph", graph, "-p", "1", "-q", "3600",
                    "-i", "1000000000", "-s", "chaos", "-o", out,
                    "--input", inp, "--state-file", state,
                    "--state-interval", "0", "--uuid-filter", "off",
                    "-r", "0,1,2", "-x", "0,1,2",
                    "--report-flush-interval", "1000000000"]

        env = dict(os.environ, REPORTER_TPU_PLATFORM="cpu")
        env.pop("REPORTER_TPU_FAULTS", None)

        out_ref = os.path.join(tmp, "ref")
        log(f"kill_restore: fault-free run over {len(lines)} probes")
        p = subprocess.run(cmd(full, out_ref, os.path.join(tmp, "s_ref")),
                           env=env, cwd=REPO, capture_output=True,
                           text=True, timeout=600)
        if p.returncode != 0:
            return fail(f"fault-free run rc={p.returncode}: "
                        f"{p.stderr[-2000:]}")

        out_chaos = os.path.join(tmp, "chaos")
        state = os.path.join(tmp, "s_chaos")
        log(f"kill_restore: crashing at offer {k + 1}")
        # tracing armed on the crash leg only: the flight recorder's
        # postmortem must name the exact span in flight at SIGKILL
        # (tile bytes are unaffected — spans never touch the sink)
        env_crash = dict(env, REPORTER_TPU_TRACE="1",
                         REPORTER_TPU_FAULTS=f"worker.offer=crash+{k}#1")
        p = subprocess.run(cmd(full, out_chaos, state), env=env_crash,
                           cwd=REPO, capture_output=True, text=True,
                           timeout=600)
        if p.returncode != faults_mod.CRASH_EXIT_CODE:
            return fail(f"crash run rc={p.returncode} "
                        f"(want {faults_mod.CRASH_EXIT_CODE}): "
                        f"{p.stderr[-2000:]}")
        if not os.path.exists(state):
            return fail("no state snapshot survived the crash")
        rec_dir = os.path.join(out_chaos, ".deadletter", ".flightrec")
        dumps = sorted(os.listdir(rec_dir)) if os.path.isdir(rec_dir) \
            else []
        if not dumps:
            return fail(f"crash left no flight-recorder dump in {rec_dir}")
        with open(os.path.join(rec_dir, dumps[-1]), encoding="utf-8") as f:
            post = json.load(f)
        inflight = [s["name"] for s in post.get("in_flight", [])]
        if not post["reason"].startswith("crash.worker.offer") \
                or "worker.offer" not in inflight:
            return fail(f"postmortem does not name the SIGKILL'd span: "
                        f"reason={post['reason']!r} in_flight={inflight}")
        log(f"kill_restore: postmortem {dumps[-1]} names in-flight "
            f"span worker.offer")

        log("kill_restore: restarting from the snapshot")
        p = subprocess.run(cmd(tail, out_chaos, state), env=env, cwd=REPO,
                           capture_output=True, text=True, timeout=600)
        if p.returncode != 0:
            return fail(f"restore run rc={p.returncode}: "
                        f"{p.stderr[-2000:]}")
        if "Restored state" not in p.stderr:
            return fail("restore run did not restore the snapshot")

        ref, got = _tile_tree(out_ref), _tile_tree(out_chaos)
        if not ref:
            return fail("fault-free run wrote no tiles")
        if got != ref:
            only_ref = sorted(set(ref) - set(got))
            only_got = sorted(set(got) - set(ref))
            differ = sorted(k for k in set(ref) & set(got)
                            if ref[k] != got[k])
            return fail(f"tile trees diverge: missing={only_ref[:5]} "
                        f"extra={only_got[:5]} differ={differ[:5]}")
        log(f"kill_restore ok: {len(ref)} tile files byte-identical "
            f"across crash+restore")
        return 0


# ---------------------------------------------------------------------------
def scenario_stream_resume() -> int:
    """Incremental matcher crash-resume (ISSUE 19): two legs over the
    ``match.incremental.commit`` fault site.

    Leg A arms an *error* on a fixed-lag commit: the advance aborts, the
    carried states drop, and the trace serves through the windowed batch
    path — tiles byte-identical to a fault-free run (fallback, never
    approximation). Leg B SIGKILLs the worker mid-stream AFTER several
    incremental reports, so the last state snapshot (v3) carries live
    per-trace decode state; the restarted worker must restore those
    frames, resume the incremental decode mid-stream, and still produce
    byte-identical final tiles."""
    from reporter_tpu.utils import faults as faults_mod

    with tempfile.TemporaryDirectory() as tmp:
        city = _city()
        graph = os.path.join(tmp, "city.npz")
        city.save(graph)
        lines = _lines(city, n_traces=6)
        k = len(lines) * 2 // 3  # past several incremental flushes
        full = os.path.join(tmp, "full.txt")
        tail = os.path.join(tmp, "tail.txt")
        with open(full, "w") as f:
            f.write("\n".join(lines) + "\n")
        with open(tail, "w") as f:
            f.write("\n".join(lines[k:]) + "\n")

        def cmd(inp, out, state):
            return [sys.executable, "-m", "reporter_tpu", "stream",
                    "-f", FMT, "--graph", graph, "-p", "1", "-q", "3600",
                    "-i", "1000000000", "-s", "chaos", "-o", out,
                    "--input", inp, "--state-file", state,
                    "--state-interval", "0", "--uuid-filter", "off",
                    "-r", "0,1,2", "-x", "0,1,2",
                    # flush report-ready sessions immediately: mid-stream
                    # reports are what build + snapshot carried state
                    "--report-flush-interval", "0"]

        # a tightened lag bound makes fixed-lag commits fire well inside
        # the synthetic windows (so the armed commit site is hot) while
        # still converging — at 4 the noise outlives the lag and every
        # trace falls back to the batch path, leaving no carried state
        # for leg B's snapshot to prove anything with
        env = dict(os.environ, REPORTER_TPU_PLATFORM="cpu",
                   REPORTER_TPU_INCREMENTAL_LAG="16")
        env.pop("REPORTER_TPU_FAULTS", None)

        out_ref = os.path.join(tmp, "ref")
        log(f"stream_resume: fault-free run over {len(lines)} probes")
        p = subprocess.run(cmd(full, out_ref, os.path.join(tmp, "s_ref")),
                           env=env, cwd=REPO, capture_output=True,
                           text=True, timeout=600)
        if p.returncode != 0:
            return fail(f"fault-free run rc={p.returncode}: "
                        f"{p.stderr[-2000:]}")
        ref = _tile_tree(out_ref)
        if not ref:
            return fail("fault-free run wrote no tiles")

        # -- leg A: commit error -> batch-path fallback, same bytes ----
        out_err = os.path.join(tmp, "err")
        env_err = dict(env,
                       REPORTER_TPU_FAULTS="match.incremental.commit="
                                           "error#1")
        log("stream_resume: leg A — error on a fixed-lag commit")
        p = subprocess.run(cmd(full, out_err, os.path.join(tmp, "s_err")),
                           env=env_err, cwd=REPO, capture_output=True,
                           text=True, timeout=600)
        if p.returncode != 0:
            return fail(f"commit-error run rc={p.returncode}: "
                        f"{p.stderr[-2000:]}")
        if "incremental match failed" not in p.stderr:
            return fail("commit fault never fired (the leg proved "
                        "nothing): no fallback warning in stderr")
        got = _tile_tree(out_err)
        if got != ref:
            differ = sorted(x for x in set(ref) & set(got)
                            if ref[x] != got[x])
            return fail(f"commit-error tiles diverge from fault-free: "
                        f"missing={sorted(set(ref) - set(got))[:5]} "
                        f"differ={differ[:5]}")
        log(f"stream_resume: leg A ok — {len(ref)} tile files "
            f"byte-identical under a commit fault")

        # -- leg B: SIGKILL mid-stream, restore snapshot v3, resume ----
        out_chaos = os.path.join(tmp, "chaos")
        state = os.path.join(tmp, "s_chaos")
        env_crash = dict(env,
                         REPORTER_TPU_FAULTS=f"worker.offer=crash+{k}#1")
        log(f"stream_resume: leg B — crashing at offer {k + 1}")
        p = subprocess.run(cmd(full, out_chaos, state), env=env_crash,
                           cwd=REPO, capture_output=True, text=True,
                           timeout=600)
        if p.returncode != faults_mod.CRASH_EXIT_CODE:
            return fail(f"crash run rc={p.returncode} "
                        f"(want {faults_mod.CRASH_EXIT_CODE}): "
                        f"{p.stderr[-2000:]}")
        if not os.path.exists(state):
            return fail("no state snapshot survived the crash")
        # the snapshot must actually CARRY carried state — an empty v3
        # section would make the restore leg vacuously pass
        from reporter_tpu.streaming import state as state_mod
        from reporter_tpu.streaming.anonymiser import Anonymiser
        from reporter_tpu.streaming.batcher import PointBatcher

        class _Null:
            def write(self, *a, **kw):
                return None
        with open(state, "rb") as f:
            frames = state_mod.restore_bytes(
                f.read(), PointBatcher(lambda t: None, lambda a, b: None),
                Anonymiser(_Null(), 1, 3600))
        if not frames:
            return fail("crash snapshot carries no incremental decode "
                        "state (v3 section empty)")
        log(f"stream_resume: snapshot carries {len(frames)} carried "
            f"decode state(s)")

        log("stream_resume: restarting from the snapshot")
        p = subprocess.run(cmd(tail, out_chaos, state), env=env, cwd=REPO,
                           capture_output=True, text=True, timeout=600)
        if p.returncode != 0:
            return fail(f"restore run rc={p.returncode}: "
                        f"{p.stderr[-2000:]}")
        if "Restored state" not in p.stderr:
            return fail("restore run did not restore the snapshot")
        if "carried incremental decode state" not in p.stderr:
            return fail("restore run did not restore the carried "
                        "incremental decode states")

        got = _tile_tree(out_chaos)
        if got != ref:
            only_ref = sorted(set(ref) - set(got))
            only_got = sorted(set(got) - set(ref))
            differ = sorted(x for x in set(ref) & set(got)
                            if ref[x] != got[x])
            return fail(f"tile trees diverge: missing={only_ref[:5]} "
                        f"extra={only_got[:5]} differ={differ[:5]}")
        log(f"stream_resume ok: {len(ref)} tile files byte-identical "
            f"across commit fault AND crash+resume")
        return 0


# ---------------------------------------------------------------------------
def scenario_submit_burst() -> int:
    """Transient matcher failures requeue under the budget and recover;
    a dead matcher dead-letters trace JSON instead of dropping."""
    from reporter_tpu.utils import faults, metrics

    with tempfile.TemporaryDirectory() as tmp:
        city = _city()
        lines = _lines(city, n_traces=4)

        # part 1: a 2-failure burst (within the default budget of 2)
        metrics.default.reset()
        out = os.path.join(tmp, "burst")
        worker = _make_worker(city, out, report_flush_interval_s=0.0)
        faults.configure("matcher.submit=error@0#2")
        try:
            worker.run(iter(lines))
        finally:
            faults.clear()
        snap = metrics.default.snapshot()["counters"]
        if not snap.get("batch.requeued"):
            return fail(f"burst did not requeue: {snap}")
        if snap.get("batch.dropped"):
            return fail(f"burst within budget still dropped: {snap}")
        if not _tile_tree(out):
            return fail("no tiles written after requeue recovery")
        log(f"submit_burst: {snap['batch.requeued']} requeues, 0 drops, "
            f"tiles written after recovery")

        # part 2: the matcher stays dead — budget exhausts, trace JSON
        # dead-letters, the stream itself survives
        metrics.default.reset()
        out2 = os.path.join(tmp, "dead")
        worker = _make_worker(city, out2, report_flush_interval_s=0.0)
        faults.configure("matcher.submit=error@0")
        try:
            worker.run(iter(lines))
        finally:
            faults.clear()
        snap = metrics.default.snapshot()["counters"]
        if not snap.get("batch.dropped") or not snap.get("batch.deadletter"):
            return fail(f"dead matcher did not dead-letter: {snap}")
        spool = worker.batcher.deadletter_dir
        names = sorted(os.listdir(spool)) if os.path.isdir(spool) else []
        if not names:
            return fail("no trace JSON in the dead-letter spool")
        with open(os.path.join(spool, names[0]), encoding="utf-8") as f:
            body = json.load(f)
        if not body.get("uuid") or not body.get("trace"):
            return fail(f"unreplayable dead-letter body: {body}")
        log(f"submit_burst ok: dead matcher -> {len(names)} trace(s) "
            f"spooled for replay, stream survived")
        return 0


# ---------------------------------------------------------------------------
def scenario_egress_outage() -> int:
    """Sink outage: every tile dead-letters; `datastore ingest --delete`
    replays the spool into a store that matches a fault-free run's."""
    from reporter_tpu.datastore import LocalDatastore, ingest_dir
    from reporter_tpu.utils import faults, metrics

    with tempfile.TemporaryDirectory() as tmp:
        city = _city()
        lines = _lines(city, n_traces=6)

        metrics.default.reset()
        out = os.path.join(tmp, "out")
        worker = _make_worker(city, out)
        faults.configure("egress.http=error@0")
        try:
            worker.run(iter(lines))
        finally:
            faults.clear()
        snap = metrics.default.snapshot()["counters"]
        if not snap.get("egress.fail") or not snap.get("egress.deadletter"):
            return fail(f"outage not spooled: {snap}")
        if _tile_tree(out):
            return fail("tiles reached a dead sink")
        spool = worker.anonymiser.sink.deadletter

        ds = LocalDatastore(os.path.join(tmp, "store"))
        got = ingest_dir(ds, spool, delete=True)
        if not got["rows"] or got["failures"]:
            return fail(f"dead-letter replay failed: {got}")
        leftover = [p for p in _tile_tree(spool)]
        if leftover:
            return fail(f"replayed spool not drained: {leftover[:5]}")

        # fault-free control run -> same aggregate store contents
        out2 = os.path.join(tmp, "out2")
        worker2 = _make_worker(city, out2)
        worker2.run(iter(lines))
        ds2 = LocalDatastore(os.path.join(tmp, "store2"))
        got2 = ingest_dir(ds2, out2)
        s1, s2 = ds.stats(), ds2.stats()
        for key in ("rows", "cells", "transitions"):
            if s1[key] != s2[key]:
                return fail(f"replayed store diverges on {key}: "
                            f"{s1[key]} != {s2[key]}")
        log(f"egress_outage ok: {got['files']} tiles replayed from the "
            f"spool, store parity with fault-free run "
            f"({s1['rows']} rows)")
        return 0


# ---------------------------------------------------------------------------
def scenario_decode_poison() -> int:
    """Decode + assemble failure domains (ISSUE 9): a decode storm opens
    the decode breaker and every chunk is served via the numpy oracle
    byte-identically until a probe re-closes it; then a poisoned
    assemble quarantines exactly ONE trace to the dead-letter spool
    while every other trace's bytes stay identical."""
    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.utils import faults, metrics

    os.environ["REPORTER_TPU_CIRCUIT_THRESHOLD"] = "3"
    os.environ["REPORTER_TPU_CIRCUIT_COOLDOWN_S"] = "3.0"
    try:
        import numpy as np
        from reporter_tpu.synth import generate_trace
        city = _city()
        matcher = SegmentMatcher(net=city)
        rng = np.random.default_rng(12)
        reqs = []
        for i in range(8):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"poison-{i}", rng, noise_m=3.0,
                                    min_route_edges=8)
            reqs.append({"uuid": tr.uuid, "trace": tr.points,
                         "match_options": {"mode": "auto",
                                           "report_levels": [0, 1, 2],
                                           "transition_levels": [0, 1, 2]}})
        want = [_as_plain(r) for r in matcher.match_many(reqs)]
        metrics.default.reset()

        # part 1: decode storm — every device dispatch errors until the
        # decode breaker trips; chunks serve via the per-trace oracle
        faults.configure("decode.dispatch=error@0")
        stormed = []
        for _ in range(5):
            stormed.append([_as_plain(r) for r in matcher.match_many(reqs)])
        snap = metrics.default.snapshot()["counters"]
        if matcher.circuit_decode.snapshot()["state"] not in ("open",
                                                             "half_open"):
            return fail(f"decode circuit did not open: "
                        f"{matcher.circuit_decode.snapshot()}")
        if not snap.get("matcher.circuit.decode.opened"):
            return fail(f"no decode open transition counted: {snap}")
        if not snap.get("matcher.circuit.decode.fallback_chunks"):
            return fail(f"no chunk was short-circuited to the oracle: "
                        f"{snap}")
        for got in stormed:
            if got != want:
                return fail("oracle-decoded results diverged from the "
                            "fault-free device run")
        log(f"decode_poison: decode circuit opened after "
            f"{snap.get('matcher.circuit.decode.errors', 0)} errors, "
            f"{snap.get('matcher.circuit.decode.fallback_chunks')} "
            f"chunks decoded by the oracle, results byte-identical")

        faults.clear()
        time.sleep(3.2)
        after = [_as_plain(r) for r in matcher.match_many(reqs)]
        snap = metrics.default.snapshot()["counters"]
        if matcher.circuit_decode.snapshot()["state"] != "closed":
            return fail(f"decode circuit did not re-close: "
                        f"{matcher.circuit_decode.snapshot()}")
        if not snap.get("matcher.circuit.decode.probes") \
                or not snap.get("matcher.circuit.decode.closed"):
            return fail(f"no decode probe/close recorded: {snap}")
        if after != want:
            return fail("post-recovery decode results diverged")
        log("decode_poison: probe re-closed the decode circuit")

        # part 2: poisoned assemble — on the native path the first
        # eligible call is the whole-batch assembler (breaker failure ->
        # scalar fallback), so one more firing poisons exactly one
        # trace; pure-numpy paths go straight to the scalar loop
        metrics.default.reset()
        with tempfile.TemporaryDirectory() as spool:
            matcher.quarantine_spool = spool
            limit = 2 if matcher.runtime is not None else 1
            faults.configure(f"matcher.assemble=error@0#{limit}")
            try:
                got = [_as_plain(r) for r in matcher.match_many(reqs)]
            finally:
                faults.clear()
                matcher.quarantine_spool = None
            snap = metrics.default.snapshot()["counters"]
            if snap.get("matcher.assemble.quarantined") != 1:
                return fail(f"expected exactly 1 quarantined trace: "
                            f"{snap}")
            poisoned = [i for i, (g, w) in enumerate(zip(got, want))
                        if g != w]
            if len(poisoned) != 1:
                return fail(f"poison leaked past one trace: {poisoned}")
            if got[poisoned[0]]["segments"]:
                return fail("poisoned trace did not degrade to an "
                            "empty match")
            names = sorted(os.listdir(spool))
            if len(names) != 1:
                return fail(f"expected 1 spooled poison body: {names}")
            with open(os.path.join(spool, names[0]),
                      encoding="utf-8") as f:
                body = json.load(f)
            if body.get("uuid") != reqs[poisoned[0]]["uuid"] \
                    or not body.get("trace"):
                return fail(f"unreplayable poison body: "
                            f"{str(body)[:200]}")
        log(f"decode_poison ok: 1 trace quarantined "
            f"({reqs[poisoned[0]]['uuid']}), other {len(reqs) - 1} "
            f"traces byte-identical")
        return 0
    finally:
        faults.clear()
        os.environ.pop("REPORTER_TPU_CIRCUIT_THRESHOLD", None)
        os.environ.pop("REPORTER_TPU_CIRCUIT_COOLDOWN_S", None)


# ---------------------------------------------------------------------------
def _store_fingerprint(root: str) -> dict:
    """{relpath: bytes} of a datastore tree — the byte-parity comparand
    (meta.json excluded: it carries a wall-clock 'created' stamp; dot
    files excluded: ``.lease`` carries the holder pid/deadline and
    ``.profile`` the replay-dependent memo dump — control state, not
    data, so parity must not read them)."""
    out = {}
    for dirpath, dirnames, filenames in os.walk(root):
        # dot DIRS too: .tmp- stage dirs and .orphan- asides hold
        # non-dot column files that are not committed data
        dirnames[:] = sorted(d for d in dirnames
                             if not d.startswith("."))
        for name in sorted(filenames):
            if name == "meta.json" or name.startswith("."):
                continue
            path = os.path.join(dirpath, name)
            with open(path, "rb") as f:
                out[os.path.relpath(path, root)] = f.read()
    return out


def scenario_double_ingest() -> int:
    """The (epoch, tile) dedupe ledger: an `ingest --delete` re-run of an
    already-ingested directory appends NOTHING (store byte-identical),
    and a worker crash-replayed between tee ingest and epoch commit
    re-offers every flush — deduped, store equal to a fault-free run."""
    from reporter_tpu.datastore import LocalDatastore, ingest_dir
    from reporter_tpu.utils import faults as faults_mod
    from reporter_tpu.utils import metrics

    with tempfile.TemporaryDirectory() as tmp:
        city = _city()
        lines = _lines(city, n_traces=6)

        # leg 1: directory replay idempotence
        out = os.path.join(tmp, "out")
        worker = _make_worker(city, out)
        worker.run(iter(lines))
        store_dir = os.path.join(tmp, "store")
        ds = LocalDatastore(store_dir)
        first = ingest_dir(ds, out)
        if not first["rows"]:
            return fail(f"first ingest empty: {first}")
        before = _store_fingerprint(store_dir)
        metrics.default.reset()
        again = ingest_dir(ds, out, delete=True)
        snap = metrics.default.snapshot()["counters"]
        if again["rows"] != 0:
            return fail(f"re-ingest appended rows: {again}")
        if not snap.get("datastore.ingest.deduped"):
            return fail(f"no dedupe counted: {snap}")
        if _store_fingerprint(store_dir) != before:
            return fail("re-ingest changed store bytes")
        if _tile_tree(out):
            return fail("--delete left tile files behind")
        log(f"double_ingest: --delete re-run of {again['files']} files "
            f"deduped to 0 rows, store byte-identical")

        # leg 2: crash between tee ingest + egress and the epoch commit
        # (worker.post_egress) -> restart re-emits the whole flush ->
        # ledger dedupes the tee, sink overwrites the tiles
        graph = os.path.join(tmp, "city.npz")
        city.save(graph)
        full = os.path.join(tmp, "full.txt")
        empty = os.path.join(tmp, "empty.txt")
        with open(full, "w") as f:
            f.write("\n".join(lines) + "\n")
        with open(empty, "w") as f:
            f.write("")

        def cmd(inp, out_dir, state, store):
            return [sys.executable, "-m", "reporter_tpu", "stream",
                    "-f", FMT, "--graph", graph, "-p", "1", "-q", "3600",
                    "-i", "1000000000", "-s", "chaos", "-o", out_dir,
                    "--input", inp, "--state-file", state,
                    "--state-interval", "0", "--uuid-filter", "off",
                    "-r", "0,1,2", "-x", "0,1,2",
                    "--datastore", store,
                    "--report-flush-interval", "1000000000"]

        env = dict(os.environ, REPORTER_TPU_PLATFORM="cpu")
        env.pop("REPORTER_TPU_FAULTS", None)

        out_ref = os.path.join(tmp, "ref_out")
        store_ref = os.path.join(tmp, "ref_store")
        p = subprocess.run(
            cmd(full, out_ref, os.path.join(tmp, "s_ref"), store_ref),
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=600)
        if p.returncode != 0:
            return fail(f"fault-free run rc={p.returncode}: "
                        f"{p.stderr[-2000:]}")

        out_chaos = os.path.join(tmp, "chaos_out")
        store_chaos = os.path.join(tmp, "chaos_store")
        state = os.path.join(tmp, "s_chaos")
        env_crash = dict(env,
                         REPORTER_TPU_FAULTS="worker.post_egress=crash#1")
        p = subprocess.run(cmd(full, out_chaos, state, store_chaos),
                           env=env_crash, cwd=REPO, capture_output=True,
                           text=True, timeout=600)
        if p.returncode != faults_mod.CRASH_EXIT_CODE:
            return fail(f"crash run rc={p.returncode} "
                        f"(want {faults_mod.CRASH_EXIT_CODE}): "
                        f"{p.stderr[-2000:]}")
        # restart over an EMPTY stream: everything re-emitted comes from
        # the restored snapshot — the pure crash-replay window
        p = subprocess.run(cmd(empty, out_chaos, state, store_chaos),
                           env=env, cwd=REPO, capture_output=True,
                           text=True, timeout=600)
        if p.returncode != 0:
            return fail(f"restore run rc={p.returncode}: "
                        f"{p.stderr[-2000:]}")
        if "dedupe" not in p.stderr:
            return fail("restore run logged no ledger dedupe — the tee "
                        "replay was not deduplicated")

        ref_t, got_t = _tile_tree(out_ref), _tile_tree(out_chaos)
        if not ref_t or got_t != ref_t:
            return fail(f"tile trees diverge across crash-replay: "
                        f"ref={len(ref_t)} got={len(got_t)}")
        s_ref = LocalDatastore(store_ref).stats()
        s_got = LocalDatastore(store_chaos).stats()
        for key in ("rows", "cells", "transitions"):
            if s_ref[key] != s_got[key]:
                return fail(f"crash-replayed store diverges on {key}: "
                            f"{s_got[key]} != {s_ref[key]}")
        log(f"double_ingest ok: crash-replayed tee deduped "
            f"({s_got['rows']} rows, {len(got_t)} tile files "
            f"byte-identical to fault-free)")
        return 0


# ---------------------------------------------------------------------------
def scenario_replay_drain() -> int:
    """The automated dead-letter replayer: a full matcher + sink outage
    spools every trace and tile; once the outage clears, the drainer
    empties both spools (re-submitting traces through the live pipeline,
    re-egressing tiles) and the datastore ends equal to a fresh ingest
    of the final tile tree — nothing lost, nothing duplicated."""
    from reporter_tpu.datastore import LocalDatastore, ingest_dir
    from reporter_tpu.utils import faults, metrics

    os.environ["REPORTER_TPU_REPLAY_INTERVAL_S"] = "1000000"
    os.environ["REPORTER_TPU_REPLAY_ATTEMPTS"] = "10"
    try:
        with tempfile.TemporaryDirectory() as tmp:
            from reporter_tpu.matcher import SegmentMatcher
            from reporter_tpu.service.server import ReporterService
            from reporter_tpu.streaming.anonymiser import (Anonymiser,
                                                           TileSink)
            from reporter_tpu.streaming.formatter import Formatter
            from reporter_tpu.streaming.worker import (StreamWorker,
                                                       inproc_submitter)

            city = _city()
            lines = _lines(city, n_traces=6)
            metrics.default.reset()
            out = os.path.join(tmp, "out")
            store = LocalDatastore(os.path.join(tmp, "store"))

            def tee(_tile, segments, ingest_key=None):
                return store.ingest_segments(segments,
                                             ingest_key=ingest_key)

            service = ReporterService(SegmentMatcher(net=city),
                                      threshold_sec=15, max_batch=64,
                                      max_wait_ms=5.0)
            worker = StreamWorker(
                Formatter.from_config(FMT), inproc_submitter(service),
                Anonymiser(TileSink(out), privacy=1, quantisation=3600,
                           source="chaos", tee=tee),
                reports="0,1,2", transitions="0,1,2",
                flush_interval_s=1e9, submit_many=service.report_many,
                report_flush_interval_s=0.0, datastore=store)
            if worker.drainer is None:
                return fail("drainer did not arm")

            # phase 1 — total matcher outage: every submit (live and
            # drainer replay alike — same failpoint) fails, so every
            # qualifying trace dead-letters; nothing reports, so no
            # tiles exist yet
            faults.configure("matcher.submit=error@0,egress.http=error@0")
            try:
                worker.run(iter(lines))
            finally:
                faults.clear()
            snap = metrics.default.snapshot()["counters"]
            if not snap.get("batch.deadletter"):
                return fail(f"outage spooled no traces: {snap}")
            backlog = worker.drainer.backlog()
            if not backlog["traces"]:
                return fail(f"trace spool empty before drain: {backlog}")
            log(f"replay_drain: matcher outage spooled "
                f"{backlog['traces']} trace(s)")

            # phase 2 — matcher back, sink still down: the drainer
            # re-submits every spooled trace through the live pipeline;
            # their recovered segments flush to tiles, which fail egress
            # and seed the TILE spool
            faults.configure("egress.http=error@0")
            try:
                worker.drain()
            finally:
                faults.clear()
            snap = metrics.default.snapshot()["counters"]
            if not snap.get("replay.traces.ok"):
                return fail(f"drainer re-submitted no traces: {snap}")
            if not snap.get("egress.deadletter"):
                return fail(f"recovered flush spooled no tiles: {snap}")
            backlog = worker.drainer.backlog()
            if backlog["traces"]:
                return fail(f"trace spool not drained: {backlog}")
            if not backlog["tiles"]:
                return fail(f"tile spool empty before drain: {backlog}")
            log(f"replay_drain: sink outage spooled {backlog['tiles']} "
                f"tile(s) from the recovered flush")

            # phase 3 — everything back: one drain cycle re-egresses the
            # spooled tiles and leaves both spools empty
            worker.drain()
            snap = metrics.default.snapshot()["counters"]
            backlog = worker.drainer.backlog()
            if backlog["traces"] or backlog["tiles"]:
                return fail(f"spools not drained: {backlog}")
            if snap.get("replay.quarantined"):
                return fail(f"recoverable entries were quarantined: "
                            f"{snap}")
            if not snap.get("replay.traces.ok") \
                    or not snap.get("replay.tiles.ok"):
                return fail(f"drainer replayed nothing: {snap}")
            tiles = _tile_tree(out)
            if not tiles:
                return fail("no tiles reached the sink after drain")

            # store parity: the tee-fed store must equal a fresh store
            # built from the final tile tree (end-to-end exactly-once)
            fresh = LocalDatastore(os.path.join(tmp, "fresh"))
            got = ingest_dir(fresh, out)
            if got["failures"]:
                return fail(f"tile-tree ingest failed: {got}")
            s1, s2 = store.stats(), fresh.stats()
            for key in ("rows", "cells", "transitions"):
                if s1[key] != s2[key]:
                    return fail(f"store diverges on {key}: "
                                f"{s1[key]} != {s2[key]}")
            log(f"replay_drain ok: {snap['replay.traces.ok']} trace(s) "
                f"re-submitted, {snap['replay.tiles.ok']} tile(s) "
                f"re-egressed, spools empty, store parity "
                f"({s1['rows']} rows)")
            return 0
    finally:
        faults.clear()
        os.environ.pop("REPORTER_TPU_REPLAY_INTERVAL_S", None)
        os.environ.pop("REPORTER_TPU_REPLAY_ATTEMPTS", None)


# ---------------------------------------------------------------------------
_PREFORK_SCRIPT = r"""
import json, os, signal, socket, sys, threading, time, urllib.request

import numpy as np

from reporter_tpu.matcher import SegmentMatcher
from reporter_tpu.service.prefork import serve_prefork
from reporter_tpu.service.server import ReporterService
from reporter_tpu.synth import build_grid_city, generate_trace

city = build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=3,
                       service_road_fraction=0.0, internal_fraction=0.0)
with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
base = f"http://127.0.0.1:{port}"


def make_service():
    # POST-fork, per worker: apply REPORTER_TPU_PLATFORM /
    # REPORTER_TPU_VIRTUAL_DEVICES in THIS process (the parent stays
    # jax-free so forking is safe). Under the CI 2-proc x 2-device
    # leg each worker then sees the forced mesh and its slot-derived
    # REPORTER_TPU_DEVICE_SLICE claims exactly one device — a wrong
    # slice fails the worker at startup, which fails the scenario.
    from reporter_tpu.utils.runtime import ensure_backend
    ensure_backend()
    want = os.environ.get("REPORTER_TPU_VIRTUAL_DEVICES")
    if want:
        import jax
        assert len(jax.devices()) == int(want), \
            (len(jax.devices()), want)
        from reporter_tpu.parallel import mesh as pmesh
        owned = pmesh.device_slice(jax.local_devices())
        assert len(owned) == max(1, int(want) // 2), owned
    return ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                           max_batch=64, max_wait_ms=5.0)


def req_body(seed):
    rng = np.random.default_rng(seed)
    tr = None
    while tr is None:
        tr = generate_trace(city, f"veh-{seed}", rng, noise_m=3.0)
    return json.dumps(tr.request_json()).encode()


def call(path, body=None, timeout=120.0):
    r = urllib.request.Request(base + path, data=body,
                               method="POST" if body else "GET")
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return resp.status, resp.headers.get("X-Reporter-Proc"), resp.read()


verdict = {"ok": False}


def probe():
    time.sleep(2.0)  # fork window: children must fork off a quiet parent
    try:
        _probe()
    except Exception as e:
        verdict["err"] = f"{type(e).__name__}: {e}"


def _probe():
    deadline = time.time() + 180
    while True:
        try:
            call("/stats", timeout=5)
            break
        except Exception:
            if time.time() > deadline:
                verdict["err"] = "service never came up"
                return
            time.sleep(0.2)
    bodies = [req_body(i) for i in range(6)]
    tags = {}
    for i in range(300):
        st, tag, _ = call("/report", bodies[i % len(bodies)])
        assert st == 200 and tag
        tags.setdefault(tag.split(":")[0], tag)
        if len(tags) == 2 and i >= 10:
            break
    if len(tags) < 2:
        verdict["err"] = f"one worker answered everything: {tags}"
        return
    os.kill(int(tags["p0"].split(":")[1]), signal.SIGKILL)
    retried = 0
    for i in range(30):
        try:
            st, _t, _ = call("/report", bodies[i % len(bodies)])
        except Exception:
            retried += 1
            st, _t, _ = call("/report", bodies[i % len(bodies)])
        assert st == 200
        time.sleep(0.02)
    new_tag = None
    deadline = time.time() + 120
    while time.time() < deadline:
        _st, tag, _ = call("/stats", timeout=10)
        if tag and tag.startswith("p0:") and tag != tags["p0"]:
            new_tag = tag
            break
        time.sleep(0.1)
    verdict.update(ok=bool(new_tag), retried=retried,
                   tags=sorted(tags.values()), new_tag=new_tag)


t = threading.Thread(target=probe, daemon=True)
try:
    urllib.request.urlopen(base + "/stats", timeout=0.2)
except Exception:
    pass  # warms the opener machinery in the MAIN thread, pre-fork
t.start()


def reaper():
    t.join()
    os.kill(os.getpid(), signal.SIGTERM)


threading.Thread(target=reaper, daemon=True).start()
rc = serve_prefork(make_service, "127.0.0.1", port, 2)
print("VERDICT:" + json.dumps(verdict))
sys.exit(0 if verdict.get("ok") and rc == 0 else 1)
"""


def scenario_prefork_kill() -> int:
    """2-process SO_REUSEPORT serving under load: both workers answer,
    one is SIGKILLed mid-load, the supervisor restarts it in its slot
    (new pid), no request fails after one retry — and the per-slot
    writer identities keep epoch-named tile files collision-free."""
    # the process half: kill + restart + retry, in a fresh interpreter
    # (the parent must fork its workers before anything imports jax)
    p = subprocess.run([sys.executable, "-c", _PREFORK_SCRIPT],
                       cwd=REPO, capture_output=True, text=True,
                       timeout=600)
    lines = [ln for ln in p.stdout.splitlines()
             if ln.startswith("VERDICT:")]
    if p.returncode != 0 or not lines:
        return fail(f"prefork service leg rc={p.returncode}: "
                    f"{(p.stdout + p.stderr)[-2000:]}")
    verdict = json.loads(lines[-1][len("VERDICT:"):])
    log(f"prefork_kill: workers {verdict['tags']} -> SIGKILL p0 -> "
        f"restarted as {verdict['new_tag']} "
        f"({verdict['retried']} request(s) needed their one retry)")

    # the identity half: two workers sharing one sink must never emit
    # colliding epoch tile names — each slot's writer id is distinct,
    # and a RESTARTED slot reuses its id so committed-epoch markers
    # dedupe its re-emits instead of a new id duplicating tiles
    from reporter_tpu.service.prefork import writer_id_for_slot
    from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
    with tempfile.TemporaryDirectory() as tmp:
        names = {}
        for slot in range(2):
            os.environ["REPORTER_TPU_WRITER_ID"] = \
                writer_id_for_slot(slot, base="")
            try:
                a = Anonymiser(TileSink(os.path.join(tmp, "out")),
                               privacy=1, quantisation=3600,
                               source="chaos")
            finally:
                os.environ.pop("REPORTER_TPU_WRITER_ID", None)
            names[slot] = a.epoch_file_name(0)
        if names[0] == names[1]:
            return fail(f"slot writer ids collide: {names}")
    log(f"prefork_kill ok: epoch file names per slot {names}")
    return 0


def _store_cells(store) -> dict:
    """The layout-independent parity comparand — ONE definition,
    shared with bigreplay (HistogramStore.merged_cells)."""
    return store.merged_cells()


def _assert_untorn(store):
    """Every manifest parses and every segment it lists mmaps with all
    its columns — the 'no torn manifest' post-crash invariant. Returns
    an error string or None."""
    for level, index in store.partitions():
        pdir = store.partition_dir(level, index)
        manifest = store._read_manifest(pdir)
        for name in manifest["segments"]:
            if store.load_segment(pdir, name) is None:
                return (f"manifest {level}/{index} lists {name} "
                        "but its columns are missing — torn commit")
    return None


def scenario_lease_kill() -> int:
    """The cross-process writer lease under SIGKILL: two writer workers
    + the dead-letter drainer + the background compactor all pointed at
    ONE store; the compaction holder is killed mid-commit (crash
    failpoint in the widest window: base- dir renamed, manifest not yet
    rewritten); another process steals the dead holder's lease, the
    manifests are untorn, the exactly-once ledger still dedupes every
    flush, and the recovered store's cells equal a fault-free ingest of
    the same tile trees (end-to-end exactly-once under the crash)."""
    from reporter_tpu.datastore import LocalDatastore, ingest_dir
    from reporter_tpu.utils import faults as faults_mod
    from reporter_tpu.utils import metrics

    with tempfile.TemporaryDirectory() as tmp:
        city = _city()
        lines = _lines(city, n_traces=8)
        graph = os.path.join(tmp, "city.npz")
        city.save(graph)
        # two writer shards of one stream (the bigreplay ownership
        # contract): each worker runs with its own writer id, tees into
        # the SAME store, with the replay drainer + compactor armed
        shard = [[], []]
        for ln in lines:
            shard[hash(ln.split("|", 1)[0]) % 2].append(ln)
        inputs = []
        for w, lns in enumerate(shard):
            p = os.path.join(tmp, f"in-{w}.txt")
            with open(p, "w") as f:
                f.write("\n".join(lns) + "\n")
            inputs.append(p)

        def cmd(inp, out_dir, store):
            return [sys.executable, "-m", "reporter_tpu", "stream",
                    "-f", FMT, "--graph", graph, "-p", "1", "-q", "3600",
                    "-i", "0", "-s", "chaos", "-o", out_dir,
                    "--input", inp, "--uuid-filter", "off",
                    "-r", "0,1,2", "-x", "0,1,2",
                    "--datastore", store,
                    "--datastore-max-deltas", "1",
                    # continuous report flushes -> many tee ingests per
                    # partition -> real delta pressure for the paced
                    # compactor to crash inside
                    "--report-flush-interval", "0"]

        def run_shard(w, store, out_prefix, env):
            out_dir = os.path.join(tmp, f"{out_prefix}-{w}")
            e = dict(env, REPORTER_TPU_WRITER_ID=f"w{w}")
            p = subprocess.run(cmd(inputs[w], out_dir, store), env=e,
                               cwd=REPO, capture_output=True,
                               text=True, timeout=600)
            return out_dir, p

        base_env = dict(os.environ, REPORTER_TPU_PLATFORM="cpu",
                        REPORTER_TPU_COMPACT_INTERVAL_S="0.05",
                        REPORTER_TPU_REPLAY_INTERVAL_S="0.2",
                        REPORTER_TPU_STORE_LEASE_S="30")
        base_env.pop("REPORTER_TPU_FAULTS", None)

        # chaos leg: writer 0 crashes mid-compaction HOLDING the lease;
        # writer 1 then runs fault-free against the dead holder's store
        # (its first mutation steals the stale lease in-process)
        store_chaos = os.path.join(tmp, "store_chaos")
        outs = []
        out_dir, p = run_shard(0, store_chaos, "chaos", dict(
            base_env, REPORTER_TPU_FAULTS="datastore.compact=crash#1"))
        outs.append(out_dir)
        if p.returncode != faults_mod.CRASH_EXIT_CODE:
            return fail(f"chaos writer 0 rc={p.returncode} "
                        f"(want {faults_mod.CRASH_EXIT_CODE}): "
                        f"{p.stderr[-2000:]}")

        # no torn manifest anywhere, despite the mid-commit SIGKILL
        ds = LocalDatastore(store_chaos)
        err = _assert_untorn(ds)
        if err:
            return fail(err)

        # THIS process is "another process": the SIGKILLed holder
        # never released (a clean exit would have), so our first
        # mutation must STEAL the dead pid's lease (expiry covers the
        # stuck-alive case) — the steal counter is the crash signal
        metrics.default.reset()
        ingest_dir(ds, out_dir)
        snap = metrics.default.snapshot()["counters"]
        if not snap.get("datastore.lease.steals"):
            return fail(f"no lease steal counted after holder death: "
                        f"{ {k: v for k, v in snap.items() if 'lease' in k} }")
        # hand it back so writer 1 serves the same store CLEANLY
        # (vacant acquire, no steal — routine-restart semantics)
        ds.lease.release()

        out_dir, p = run_shard(1, store_chaos, "chaos", base_env)
        outs.append(out_dir)
        if p.returncode != 0:
            return fail(f"chaos writer 1 rc={p.returncode}: "
                        f"{p.stderr[-2000:]}")

        # recovery must converge the store: replay every sink tree
        # (ledger-deduped for flushes the tees already committed,
        # fresh appends for any the crash lost) and finish the
        # interrupted compaction
        metrics.default.reset()
        for out_dir in outs:
            ingest_dir(ds, out_dir)
        ds.compact(max_deltas=0)
        snap = metrics.default.snapshot()["counters"]
        if not snap.get("datastore.ingest.deduped"):
            return fail("ledger deduped nothing on the recovery replay "
                        "— exactly-once ledger lost in the crash")

        # end-to-end exactly-once parity: the recovered tee store must
        # equal a FRESH, fault-free ingest of the same tile trees cell
        # for cell — every observation that reached a tile is counted
        # exactly once despite the crash, steal and replay (layouts
        # differ — compaction points differ — so cells, not bytes)
        ref = LocalDatastore(os.path.join(tmp, "store_fresh"))
        for out_dir in outs:
            ingest_dir(ref, out_dir)
        if _store_cells(ds) != _store_cells(ref):
            return fail("recovered store cells differ from a fresh "
                        "fault-free ingest of the same tiles")
        # and a SECOND replay into the recovered store appends nothing
        before = _store_cells(ds)
        for out_dir in outs:
            got = ingest_dir(ds, out_dir)
            if got["rows"]:
                return fail(f"re-ingest appended {got['rows']} rows — "
                            "ledger failed to dedupe after the crash")
        if _store_cells(ds) != before:
            return fail("re-ingest changed store cells despite 0 rows")

        # the datastore.lease failpoint: an injected lease-layer fault
        # refuses the mutation loudly (callers spool/retry) instead of
        # proceeding on an unknown lease state
        faults_mod.configure("datastore.lease=error#1")
        try:
            ds.lease._deadline = 0.0  # force the slow path
            try:
                ds.compact(max_deltas=0)
                return fail("datastore.lease=error did not refuse the "
                            "mutation")
            except Exception:
                pass
        finally:
            faults_mod.clear()

    log("lease_kill ok: mid-compaction SIGKILL left no torn manifest, "
        "the next process stole the dead holder's lease, ledger "
        "deduped the replay, store cells equal a fresh fault-free "
        "ingest of the same tiles")
    return 0


# ---------------------------------------------------------------------------
# the swap_kill child: the stream CLI cannot swap, so the victim drives
# CityRegistry directly — load v1 (stamping the store's epoch), commit
# the pre-swap tile tree, then swap to v2 with the city.swap crash
# failpoint armed. The failpoint sits in the WIDEST window (candidate
# loaded + shadow-gated, nothing flipped), so the os._exit(137) lands
# with the datastore lease still held and both versions resident.
_SWAP_CHILD_SCRIPT = r"""
import os, sys

from reporter_tpu.datastore import ingest_dir
from reporter_tpu.service.cities import CityRegistry

store = os.environ["SWAP_CHILD_STORE"]
g1 = os.environ["SWAP_CHILD_G1"]
g2 = os.environ["SWAP_CHILD_G2"]
out_a = os.environ["SWAP_CHILD_TILES"]

reg = CityRegistry(
    config={"metro": {"graph": g1, "datastore": store}},
    budget_bytes=1 << 40)
entry = reg.get("metro")
assert entry.map_version, "v1 load did not mint a map version"
got = ingest_dir(entry.service.datastore, out_a)
assert got["rows"] and not got["failures"], got
# armed city.swap=crash#1 fires inside: loaded+gated, v1 serving
reg.swap("metro", {"graph": g2, "datastore": store})
sys.exit(3)  # unreachable when the failpoint is armed
"""


def scenario_swap_kill() -> int:
    """Zero-downtime map lifecycle under SIGKILL (ISSUE 20): a
    registry-driven worker dies at the ``city.swap`` crash failpoint —
    the widest swap window (candidate v2 loaded and shadow-gated, v1
    still serving, datastore lease held). Recovery must steal the dead
    holder's lease, replay the pre-swap tile tree under v1's epoch
    (ledger-deduped — it committed before the crash) and the post-swap
    tree under v2's, and end with store cells equal to a fault-free
    run's, every base segment tagged exactly ONE epoch, and per-epoch
    pinned views matching the reference — exactly-once ACROSS map
    versions. A pre-swap dead-letter trace spool must then drain
    through the NEW graph without crashing."""
    from reporter_tpu.datastore import EpochView, LocalDatastore, ingest_dir
    from reporter_tpu.graph.version import map_version
    from reporter_tpu.utils import faults as faults_mod
    from reporter_tpu.utils import metrics

    def pinned_cells(store, mv):
        # merged_cells only sweeps partitions()/live_segments(), the
        # exact protocol EpochView serves — call it unbound on the view
        return LocalDatastore.merged_cells(EpochView(store, mv))

    with tempfile.TemporaryDirectory() as tmp:
        city = _city()
        # v2: same geometry and segment ids (shadow scores agree), new
        # speed profile -> a genuinely different content hash
        city2 = _city()
        city2.edge_speed_kph = city2.edge_speed_kph * 1.1
        g1 = os.path.join(tmp, "city-v1.npz")
        g2 = os.path.join(tmp, "city-v2.npz")
        city.save(g1)
        city2.save(g2)
        mv1, mv2 = map_version(city), map_version(city2)
        if mv1 == mv2:
            return fail("speed change did not mint a new map version")

        # tile trees: A is pre-swap (v1) traffic, B is post-swap (v2)
        lines_a = _lines(city, n_traces=6, seed=9)
        lines_b = _lines(city2, n_traces=6, seed=31)
        out_a = os.path.join(tmp, "tiles-v1")
        out_b = os.path.join(tmp, "tiles-v2")
        wa = _make_worker(city, out_a, report_flush_interval_s=0.0)
        wa.run(iter(lines_a))
        wb = _make_worker(city2, out_b, report_flush_interval_s=0.0)
        wb.run(iter(lines_b))
        if not _tile_tree(out_a) or not _tile_tree(out_b):
            return fail("tile trees empty before the chaos leg")

        # chaos leg: the victim ingests tree A under v1, then dies at
        # the city.swap failpoint holding the lease
        store_chaos = os.path.join(tmp, "store_chaos")
        env = dict(os.environ, REPORTER_TPU_PLATFORM="cpu",
                   REPORTER_TPU_FAULTS="city.swap=crash#1",
                   REPORTER_TPU_STORE_LEASE_S="30",
                   SWAP_CHILD_STORE=store_chaos, SWAP_CHILD_G1=g1,
                   SWAP_CHILD_G2=g2, SWAP_CHILD_TILES=out_a)
        p = subprocess.run([sys.executable, "-c", _SWAP_CHILD_SCRIPT],
                           env=env, cwd=REPO, capture_output=True,
                           text=True, timeout=600)
        if p.returncode != faults_mod.CRASH_EXIT_CODE:
            return fail(f"swap victim rc={p.returncode} "
                        f"(want {faults_mod.CRASH_EXIT_CODE}): "
                        f"{p.stderr[-2000:]}")

        # no torn manifest despite the mid-swap SIGKILL
        ds = LocalDatastore(store_chaos)
        err = _assert_untorn(ds)
        if err:
            return fail(err)

        # recovery is "another process": the dead holder never
        # released, so our first mutation must STEAL its lease; the
        # pre-swap tree re-ingests under v1's epoch and every flush
        # the victim committed dedupes through the epoch-qualified
        # ledger (key@mv1) — nothing double-counts across the crash
        metrics.default.reset()
        ds.set_map_version(mv1)
        ingest_dir(ds, out_a)
        snap = metrics.default.snapshot()["counters"]
        if not snap.get("datastore.lease.steals"):
            return fail(f"no lease steal counted after victim death: "
                        f"{ {k: v for k, v in snap.items() if 'lease' in k} }")
        if not snap.get("datastore.ingest.deduped"):
            return fail("epoch-qualified ledger deduped nothing on the "
                        "v1 recovery replay — exactly-once lost")
        # the post-swap world: v2 traffic lands under the new epoch
        ds.set_map_version(mv2)
        got = ingest_dir(ds, out_b)
        if not got["rows"] or got["failures"]:
            return fail(f"v2 ingest after recovery failed: {got}")
        ds.compact(max_deltas=0)

        # epoch integrity: every post-compaction segment carries
        # exactly one tag, both epochs exist, nothing mixes
        tags_seen = set()
        for level, index in ds.partitions():
            manifest = ds._read_manifest(ds.partition_dir(level, index))
            tags = manifest.get("epochs", {})
            for name in manifest["segments"]:
                tag = tags.get(name)
                if tag not in (mv1, mv2):
                    return fail(f"segment {level}/{index}/{name} has "
                                f"epoch tag {tag!r} (want {mv1} or "
                                f"{mv2}) — mixed/missing epoch")
                tags_seen.add(tag)
        if tags_seen != {mv1, mv2}:
            return fail(f"expected both epochs in the recovered store, "
                        f"got {sorted(tags_seen)}")

        # parity vs a fault-free run of the same two epochs: merged
        # cells AND each pinned view must match cell for cell — the
        # crash neither lost nor duplicated either version's traffic
        ref = LocalDatastore(os.path.join(tmp, "store_fresh"))
        ref.set_map_version(mv1)
        ingest_dir(ref, out_a)
        ref.set_map_version(mv2)
        ingest_dir(ref, out_b)
        ref.compact(max_deltas=0)
        if _store_cells(ds) != _store_cells(ref):
            return fail("recovered store cells differ from a fresh "
                        "fault-free two-epoch ingest")
        for mv in (mv1, mv2):
            if pinned_cells(ds, mv) != pinned_cells(ref, mv):
                return fail(f"pinned view {mv} differs from the "
                            "fault-free reference — epochs mixed "
                            "across the crash")
        # a second replay of BOTH trees appends nothing (either epoch)
        for mv, out_dir in ((mv1, out_a), (mv2, out_b)):
            ds.set_map_version(mv)
            got = ingest_dir(ds, out_dir)
            if got["rows"]:
                return fail(f"re-ingest under {mv} appended "
                            f"{got['rows']} rows — ledger failed "
                            "after the crash")

        # drainer leg: trace JSON spooled on v1 (dead matcher) must
        # replay through the NEW graph's pipeline without crashing
        os.environ["REPORTER_TPU_REPLAY_INTERVAL_S"] = "1000000"
        os.environ["REPORTER_TPU_REPLAY_ATTEMPTS"] = "10"
        try:
            metrics.default.reset()
            out_sw = os.path.join(tmp, "swapspool")
            w1 = _make_worker(city, out_sw, report_flush_interval_s=0.0)
            faults_mod.configure("matcher.submit=error@0")
            try:
                w1.run(iter(lines_a))
            finally:
                faults_mod.clear()
            snap = metrics.default.snapshot()["counters"]
            if not snap.get("batch.deadletter"):
                return fail(f"dead matcher spooled no pre-swap traces: "
                            f"{snap}")
            w2 = _make_worker(city2, out_sw, report_flush_interval_s=0.0)
            if w2.drainer is None:
                return fail("post-swap drainer did not arm")
            backlog = w2.drainer.backlog()
            if not backlog["traces"]:
                return fail(f"pre-swap spool empty before the post-swap "
                            f"drain: {backlog}")
            w2.drain()
            snap = metrics.default.snapshot()["counters"]
            if not snap.get("replay.traces.ok"):
                return fail(f"post-swap drainer replayed no pre-swap "
                            f"traces: {snap}")
            backlog = w2.drainer.backlog()
            if backlog["traces"]:
                return fail(f"pre-swap spool not drained on the new "
                            f"graph: {backlog}")
        finally:
            os.environ.pop("REPORTER_TPU_REPLAY_INTERVAL_S", None)
            os.environ.pop("REPORTER_TPU_REPLAY_ATTEMPTS", None)

    log(f"swap_kill ok: mid-swap SIGKILL (epochs {mv1} -> {mv2}) left "
        "no torn manifest, the lease steal was clean, both epochs "
        "recovered to fault-free parity with single-tagged segments, "
        "and the pre-swap spool drained through the new graph")
    return 0


def scenario_overload_recovery() -> int:
    """Load management end-to-end (ISSUE 15): drive the service past
    capacity with admission armed -> the gate sheds (counted, every
    429 carrying Retry-After) and the pressure ladder steps DOWN with
    its rung effects applied; an injected ``admission.gate`` fault
    fails OPEN (admitted, counted); cut the load -> the ladder steps
    back UP under hysteresis via /health ticks, /health returns 200
    with zero open breakers, and a backpressure-shed streaming spool
    drains to empty through the dead-letter drainer."""
    import threading

    import numpy as np

    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.service import admission
    from reporter_tpu.service.server import ReporterService
    from reporter_tpu.synth import generate_trace
    from reporter_tpu.utils import faults, metrics

    env_keys = ("REPORTER_TPU_ADMISSION", "REPORTER_TPU_SLO_MS",
                "REPORTER_TPU_QUEUE_MAX", "REPORTER_TPU_INFLIGHT_MAX",
                "REPORTER_TPU_PRESSURE_HOLD_S")
    saved = {k: os.environ.get(k) for k in env_keys}
    service = None
    try:
        # the health-SLO budget is generous on purpose: pressure in
        # this scenario comes from the bounded queue, and recovery
        # must be able to show a 200 /health (the lifetime p99 of
        # admitted requests stays far under 5 s)
        os.environ["REPORTER_TPU_ADMISSION"] = "1"
        os.environ["REPORTER_TPU_SLO_MS"] = "service.handle=5000"
        os.environ["REPORTER_TPU_QUEUE_MAX"] = "4"
        os.environ["REPORTER_TPU_INFLIGHT_MAX"] = "4"
        os.environ["REPORTER_TPU_PRESSURE_HOLD_S"] = "0.1"
        metrics.default.reset()
        admission._reset_module()

        city = _city()
        rng = np.random.default_rng(23)
        reqs = []
        for i in range(6):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"ovl-{i}", rng, noise_m=3.0,
                                    min_route_edges=6)
            reqs.append({"uuid": tr.uuid, "trace": tr.points,
                         "match_options": {"mode": "auto",
                                           "report_levels": [0, 1],
                                           "transition_levels": [0, 1]}})
        service = ReporterService(SegmentMatcher(net=city),
                                  threshold_sec=15, max_batch=8,
                                  max_wait_ms=5.0)
        if service.admission is None:
            return fail("REPORTER_TPU_ADMISSION=1 built no gate")
        # deterministic capacity: a per-trace service floor stands in
        # for device decode cost (the same device-cost model
        # tools/overload.py uses)
        orig_match = service.dispatcher._match_many
        service.dispatcher._match_many = \
            lambda b: (time.sleep(0.03 * len(b)), orig_match(b))[1]

        def call(req):
            gate = service.admission
            shed = gate.admit()
            if shed is not None:
                return 429, shed.retry_after_s
            try:
                code, body = service.handle(dict(req))
            finally:
                gate.release()
            retry = None
            if code == 429:
                # the dispatcher-backstop shed: its Retry-After rides
                # the body (the HTTP handler lifts it into the header)
                try:
                    retry = json.loads(body).get("retry_after_s")
                except Exception:
                    pass
            return code, retry

        # ---- phase 1: drive past capacity -------------------------
        results = []
        res_lock = threading.Lock()
        stop = threading.Event()

        def hammer(idx):
            while not stop.is_set():
                got = call(reqs[idx % len(reqs)])
                with res_lock:
                    results.append(got)
                if got[0] == 429:
                    # a well-behaved client backs off; a spinning one
                    # would just measure how fast 429s render
                    time.sleep(0.01)

        threads = [threading.Thread(target=hammer, args=(i,),
                                    daemon=True) for i in range(12)]
        for th in threads:
            th.start()
        time.sleep(1.5)
        stop.set()
        for th in threads:
            th.join(timeout=60.0)
        sheds = [r for r in results if r[0] == 429]
        oks = [r for r in results if r[0] == 200]
        errors = [r for r in results if r[0] not in (200, 429)]
        if errors:
            return fail(f"{len(errors)} hard errors under overload")
        if not sheds or not oks:
            return fail(f"expected both sheds and admits: "
                        f"{len(sheds)} sheds / {len(oks)} oks")
        if any(not r[1] or r[1] <= 0 for r in sheds):
            return fail("a shed carried no positive Retry-After")
        reg = metrics.default
        counted = sum(reg.counter(f"admission.shed.{r}") for r in
                      ("queue", "slo", "inflight")) \
            + reg.counter("dispatch.queue.rejected") \
            + reg.counter("dispatch.queue.evicted")
        if counted < len(sheds):
            return fail(f"{len(sheds)} sheds but only {counted} "
                        "counted — silent loss on the shed path")
        level_down = admission.current_level()
        if level_down < 1:
            return fail("sustained sheds never stepped the ladder down")
        from reporter_tpu.obs import profiler as prof_mod
        if level_down >= 1 and not prof_mod.shadow_stats()["suspended"]:
            return fail("shed_shadow rung did not suspend the sampler")
        log(f"overload: {len(oks)} admitted, {len(sheds)} shed "
            f"(all counted, Retry-After set), ladder at "
            f"{admission.RUNGS[level_down]}")

        # ---- phase 2: injected gate fault fails OPEN ---------------
        faults.configure("admission.gate=error#1")
        code, _retry = call(reqs[0])
        faults.configure("")
        if code != 200:
            return fail(f"gate fault did not fail open (got {code})")
        if not reg.counter("admission.errors"):
            return fail("gate fault was not counted")
        log("gate fault failed open: request admitted, error counted")

        # ---- phase 3: cut load; ladder steps back up via /health --
        deadline = time.monotonic() + 20.0
        code = None
        while time.monotonic() < deadline:
            code, _body = service.health()
            if admission.current_level() == 0:
                break
            time.sleep(0.05)
        if admission.current_level() != 0:
            return fail(f"ladder stuck at level "
                        f"{admission.current_level()} after load cut")
        code, body = service.health()
        health = json.loads(body)
        if code != 200:
            return fail(f"/health {code} after recovery: {body[:300]}")
        if health["degraded"]["open"]:
            return fail(f"open breakers after recovery: "
                        f"{health['degraded']['open']}")
        if health["pressure"]["level"] != 0 \
                or health["pressure"]["transitions"] < 2:
            return fail(f"pressure block wrong: {health['pressure']}")
        if prof_mod.shadow_stats()["suspended"]:
            return fail("shadow sampling still suspended at level 0")
        log(f"recovered: /health 200, ladder at normal after "
            f"{health['pressure']['transitions']} transitions")

        # ---- phase 4: a backpressure-shed spool drains ------------
        from reporter_tpu.streaming.backpressure import \
            BackpressureGovernor
        from reporter_tpu.streaming.batcher import PointBatcher
        from reporter_tpu.streaming.drainer import DeadLetterDrainer
        with tempfile.TemporaryDirectory() as spool_dir:
            trace_spool = os.path.join(spool_dir, ".traces")
            def resubmit(body):
                code, resp = service.handle(dict(body))
                if code != 200:
                    return None
                if not isinstance(resp, str):
                    resp = bytes(resp).decode("utf-8")
                return json.loads(resp)

            governor = BackpressureGovernor(latency_high_s=0.001,
                                            depth_high=1)
            governor.ewma_s = 1.0  # pinned severe pressure
            batcher = PointBatcher(
                resubmit, lambda k, s: None,
                deadletter_dir=trace_spool, governor=governor)
            if not batcher.governor.should_shed():
                return fail("governor not shedding at pinned pressure")
            from reporter_tpu.core.types import Point
            t0 = 1700000000
            for i in range(12):
                batcher.process("bp-veh", Point(
                    lat=0.001 * i, lon=0.0, time=t0 + 30 * i,
                    accuracy=5.0), (t0 + 30 * i) * 1000)
            shed_count = metrics.default.counter("backpressure.shed")
            files = [f for f in os.listdir(trace_spool)
                     if f.endswith(".json")] \
                if os.path.isdir(trace_spool) else []
            if not shed_count or not files:
                return fail(f"backpressure shed nothing "
                            f"(count={shed_count}, files={files})")
            # recovery: replay the spool through the REAL service
            drainer = DeadLetterDrainer(
                spool_dir, trace_root=trace_spool, submit=resubmit,
                forward=lambda key, seg: None)
            drainer.drain_now()
            left = [f for f in os.listdir(trace_spool)
                    if f.endswith(".json")]
            if left:
                return fail(f"spool did not drain: {left}")
            log(f"backpressure: {shed_count} session(s) shed to the "
                "spool under pinned pressure, drained to empty on "
                "recovery")
        return 0
    finally:
        faults.configure("")
        if service is not None:
            service.dispatcher.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        metrics.default.reset()
        admission._reset_module()


SCENARIOS = {
    "storm": scenario_storm,
    "kill_restore": scenario_kill_restore,
    "stream_resume": scenario_stream_resume,
    "prefork_kill": scenario_prefork_kill,
    "submit_burst": scenario_submit_burst,
    "egress_outage": scenario_egress_outage,
    "decode_poison": scenario_decode_poison,
    "double_ingest": scenario_double_ingest,
    "replay_drain": scenario_replay_drain,
    "lease_kill": scenario_lease_kill,
    "swap_kill": scenario_swap_kill,
    "overload_recovery": scenario_overload_recovery,
}


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    names = argv or ["storm", "kill_restore"]
    if names == ["all"]:
        names = list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        return fail(f"unknown scenario(s) {unknown}; "
                    f"one of {sorted(SCENARIOS)} or 'all'")
    for name in names:
        log(f"=== scenario {name} ===")
        rc = SCENARIOS[name]()
        if rc:
            return rc
    log(f"all {len(names)} scenario(s) passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
