#!/usr/bin/env python
"""Chaos harness: scripted failure scenarios over the REAL stack.

Every robustness mechanism in this repo is provable, or it is a story:
the deterministic failpoint layer (reporter_tpu/utils/faults.py) arms
named faults with seeded specs, and each scenario below replays a
synthetic stream under one failure domain and asserts the defined
degraded behavior — including *output parity* against a fault-free run
where the mechanism promises it.

Scenarios (run the named ones, default ``storm kill_restore``):

  storm         native prep error storm -> circuit breaker OPENS ->
                chunks served via the numpy fallback BYTE-IDENTICALLY ->
                cooldown -> half-open probe -> circuit re-closes
  kill_restore  crash failpoint (os._exit 137, SIGKILL-grade) at an
                exact mid-stream offer -> restart -> snapshot restore ->
                tile output byte-identical to a fault-free run (no lost
                reports beyond the snapshot window, no duplicate tiles)
  submit_burst  matcher 5xx burst -> bounded requeue under the retry
                budget -> recovery without loss; a dead matcher ->
                trace-JSON dead-letter spool instead of silent drops
  egress_outage sink down -> every tile dead-letters -> `datastore
                ingest --delete` replay -> histogram datastore parity
                with a fault-free run

Usage:
  REPORTER_TPU_PLATFORM=cpu python tools/chaos.py [scenario ...]
  (``all`` runs every scenario; REPORTER_TPU_CHAOS_REQUIRE_NATIVE=1
  makes a missing native runtime a failure instead of a skip — CI sets
  it so the storm scenario can never silently stop testing the breaker)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")  # never probe a chip

FMT = r",sv,\|,0,1,2,3,4"  # uuid|lat|lon|time|accuracy


def log(msg: str) -> None:
    print(f"chaos: {msg}", flush=True)


def fail(msg: str) -> int:
    sys.stderr.write(f"chaos: FAIL: {msg}\n")
    return 1


def _city():
    from reporter_tpu.synth import build_grid_city
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=5,
                           service_road_fraction=0.0, internal_fraction=0.0)


def _lines(city, n_traces: int, seed: int = 9):
    import numpy as np
    from reporter_tpu.synth import generate_trace
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n_traces):
        tr = None
        while tr is None:
            tr = generate_trace(city, f"veh-{i}", rng, noise_m=3.0,
                                min_route_edges=8)
        for p in tr.points:
            lines.append("|".join([tr.uuid, str(p["lat"]), str(p["lon"]),
                                   str(p["time"]), str(p["accuracy"])]))
    return lines


def _make_worker(city, out_dir: str, state_path=None,
                 report_flush_interval_s: float = 1e9):
    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.service.server import ReporterService
    from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
    from reporter_tpu.streaming.formatter import Formatter
    from reporter_tpu.streaming.state import StateStore
    from reporter_tpu.streaming.worker import StreamWorker, inproc_submitter

    service = ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                              max_batch=64, max_wait_ms=5.0)
    return StreamWorker(
        Formatter.from_config(FMT), inproc_submitter(service),
        Anonymiser(TileSink(out_dir), privacy=1, quantisation=3600,
                   source="chaos"),
        reports="0,1,2", transitions="0,1,2", flush_interval_s=1e9,
        state=StateStore(state_path, interval_s=0.0) if state_path else None,
        submit_many=service.report_many,
        report_flush_interval_s=report_flush_interval_s)


def _tile_tree(root: str) -> dict:
    """{relpath: bytes} of every tile file under a sink dir (spools
    excluded) — the byte-parity comparand."""
    out = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in (".deadletter", ".traces",
                                          ".flightrec"))
        for name in sorted(filenames):
            path = os.path.join(dirpath, name)
            with open(path, "rb") as f:
                out[os.path.relpath(path, root)] = f.read()
    return out


def _as_plain(result) -> dict:
    """A match result (dict or lazy MatchRuns) as a canonical dict."""
    return {"segments": [dict(s) for s in result["segments"]],
            "mode": result["mode"]}


# ---------------------------------------------------------------------------
def scenario_storm() -> int:
    """Native error storm: circuit opens, fallback serves byte-identical
    results, cooldown passes, a probe re-closes the circuit."""
    from reporter_tpu import native
    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.utils import faults, metrics

    if not native.available():
        if os.environ.get("REPORTER_TPU_CHAOS_REQUIRE_NATIVE"):
            return fail("native runtime unavailable but required")
        log("storm SKIPPED (native runtime unavailable)")
        return 0

    # cooldown sized so storm calls land well inside it on a slow box
    # (a probe slipping in mid-storm just fails and re-opens, but every
    # probed chunk is one not counted as short-circuited)
    os.environ["REPORTER_TPU_CIRCUIT_THRESHOLD"] = "3"
    os.environ["REPORTER_TPU_CIRCUIT_COOLDOWN_S"] = "3.0"
    try:
        import numpy as np
        from reporter_tpu.synth import generate_trace
        city = _city()
        matcher = SegmentMatcher(net=city)
        if matcher.runtime is None:
            return fail("native runtime did not attach")
        rng = np.random.default_rng(11)
        reqs = []
        for i in range(8):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"storm-{i}", rng, noise_m=3.0,
                                    min_route_edges=8)
            reqs.append({"uuid": tr.uuid, "trace": tr.points,
                         "match_options": {"mode": "auto",
                                           "report_levels": [0, 1, 2],
                                           "transition_levels": [0, 1, 2]}})

        # fault-free reference results through the native path
        want = [_as_plain(r) for r in matcher.match_many(reqs)]
        metrics.default.reset()

        # the storm: every native prep errors until the circuit trips
        # (seeded, prob 1 — replays bit-identically); no fire limit, the
        # breaker itself must stop the bleeding
        faults.configure("native.prep=error@0")
        stormed = []
        for _ in range(5):
            stormed.append([_as_plain(r) for r in matcher.match_many(reqs)])
        snap = metrics.default.snapshot()["counters"]
        if matcher.circuit.snapshot()["state"] not in ("open", "half_open"):
            return fail(f"circuit did not open: {matcher.circuit.snapshot()}")
        if not snap.get("matcher.circuit.opened"):
            return fail(f"no open transition counted: {snap}")
        if not snap.get("matcher.circuit.fallback_chunks"):
            return fail(f"no chunk was short-circuited to the fallback: "
                        f"{snap}")
        for got in stormed:
            if got != want:
                return fail("fallback results diverged from the "
                            "fault-free native run")
        log(f"storm: circuit opened after "
            f"{snap.get('matcher.circuit.native_errors', 0)} native "
            f"errors, {snap.get('matcher.circuit.fallback_chunks')} "
            f"chunks served degraded, results byte-identical")

        # recovery: faults gone, cooldown elapses, one probe re-closes
        faults.clear()
        time.sleep(3.2)
        after = [_as_plain(r) for r in matcher.match_many(reqs)]
        snap = metrics.default.snapshot()["counters"]
        if matcher.circuit.snapshot()["state"] != "closed":
            return fail(f"circuit did not re-close: "
                        f"{matcher.circuit.snapshot()}")
        if not snap.get("matcher.circuit.probes") \
                or not snap.get("matcher.circuit.closed"):
            return fail(f"no half-open probe/close recorded: {snap}")
        if after != want:
            return fail("post-recovery results diverged")
        log(f"storm ok: probe re-closed the circuit "
            f"(probes={snap['matcher.circuit.probes']})")
        return 0
    finally:
        faults.clear()
        os.environ.pop("REPORTER_TPU_CIRCUIT_THRESHOLD", None)
        os.environ.pop("REPORTER_TPU_CIRCUIT_COOLDOWN_S", None)


# ---------------------------------------------------------------------------
def scenario_kill_restore() -> int:
    """SIGKILL-grade crash mid-stream, restart, restore: tile output must
    be byte-identical to an uninterrupted run."""
    from reporter_tpu.utils import faults as faults_mod

    with tempfile.TemporaryDirectory() as tmp:
        city = _city()
        graph = os.path.join(tmp, "city.npz")
        city.save(graph)
        lines = _lines(city, n_traces=8)
        k = len(lines) // 2
        full = os.path.join(tmp, "full.txt")
        tail = os.path.join(tmp, "tail.txt")
        with open(full, "w") as f:
            f.write("\n".join(lines) + "\n")
        with open(tail, "w") as f:
            f.write("\n".join(lines[k:]) + "\n")

        def cmd(inp, out, state):
            return [sys.executable, "-m", "reporter_tpu", "stream",
                    "-f", FMT, "--graph", graph, "-p", "1", "-q", "3600",
                    "-i", "1000000000", "-s", "chaos", "-o", out,
                    "--input", inp, "--state-file", state,
                    "--state-interval", "0", "--uuid-filter", "off",
                    "-r", "0,1,2", "-x", "0,1,2",
                    "--report-flush-interval", "1000000000"]

        env = dict(os.environ, REPORTER_TPU_PLATFORM="cpu")
        env.pop("REPORTER_TPU_FAULTS", None)

        out_ref = os.path.join(tmp, "ref")
        log(f"kill_restore: fault-free run over {len(lines)} probes")
        p = subprocess.run(cmd(full, out_ref, os.path.join(tmp, "s_ref")),
                           env=env, cwd=REPO, capture_output=True,
                           text=True, timeout=600)
        if p.returncode != 0:
            return fail(f"fault-free run rc={p.returncode}: "
                        f"{p.stderr[-2000:]}")

        out_chaos = os.path.join(tmp, "chaos")
        state = os.path.join(tmp, "s_chaos")
        log(f"kill_restore: crashing at offer {k + 1}")
        # tracing armed on the crash leg only: the flight recorder's
        # postmortem must name the exact span in flight at SIGKILL
        # (tile bytes are unaffected — spans never touch the sink)
        env_crash = dict(env, REPORTER_TPU_TRACE="1",
                         REPORTER_TPU_FAULTS=f"worker.offer=crash+{k}#1")
        p = subprocess.run(cmd(full, out_chaos, state), env=env_crash,
                           cwd=REPO, capture_output=True, text=True,
                           timeout=600)
        if p.returncode != faults_mod.CRASH_EXIT_CODE:
            return fail(f"crash run rc={p.returncode} "
                        f"(want {faults_mod.CRASH_EXIT_CODE}): "
                        f"{p.stderr[-2000:]}")
        if not os.path.exists(state):
            return fail("no state snapshot survived the crash")
        rec_dir = os.path.join(out_chaos, ".deadletter", ".flightrec")
        dumps = sorted(os.listdir(rec_dir)) if os.path.isdir(rec_dir) \
            else []
        if not dumps:
            return fail(f"crash left no flight-recorder dump in {rec_dir}")
        with open(os.path.join(rec_dir, dumps[-1]), encoding="utf-8") as f:
            post = json.load(f)
        inflight = [s["name"] for s in post.get("in_flight", [])]
        if not post["reason"].startswith("crash.worker.offer") \
                or "worker.offer" not in inflight:
            return fail(f"postmortem does not name the SIGKILL'd span: "
                        f"reason={post['reason']!r} in_flight={inflight}")
        log(f"kill_restore: postmortem {dumps[-1]} names in-flight "
            f"span worker.offer")

        log("kill_restore: restarting from the snapshot")
        p = subprocess.run(cmd(tail, out_chaos, state), env=env, cwd=REPO,
                           capture_output=True, text=True, timeout=600)
        if p.returncode != 0:
            return fail(f"restore run rc={p.returncode}: "
                        f"{p.stderr[-2000:]}")
        if "Restored state" not in p.stderr:
            return fail("restore run did not restore the snapshot")

        ref, got = _tile_tree(out_ref), _tile_tree(out_chaos)
        if not ref:
            return fail("fault-free run wrote no tiles")
        if got != ref:
            only_ref = sorted(set(ref) - set(got))
            only_got = sorted(set(got) - set(ref))
            differ = sorted(k for k in set(ref) & set(got)
                            if ref[k] != got[k])
            return fail(f"tile trees diverge: missing={only_ref[:5]} "
                        f"extra={only_got[:5]} differ={differ[:5]}")
        log(f"kill_restore ok: {len(ref)} tile files byte-identical "
            f"across crash+restore")
        return 0


# ---------------------------------------------------------------------------
def scenario_submit_burst() -> int:
    """Transient matcher failures requeue under the budget and recover;
    a dead matcher dead-letters trace JSON instead of dropping."""
    from reporter_tpu.utils import faults, metrics

    with tempfile.TemporaryDirectory() as tmp:
        city = _city()
        lines = _lines(city, n_traces=4)

        # part 1: a 2-failure burst (within the default budget of 2)
        metrics.default.reset()
        out = os.path.join(tmp, "burst")
        worker = _make_worker(city, out, report_flush_interval_s=0.0)
        faults.configure("matcher.submit=error@0#2")
        try:
            worker.run(iter(lines))
        finally:
            faults.clear()
        snap = metrics.default.snapshot()["counters"]
        if not snap.get("batch.requeued"):
            return fail(f"burst did not requeue: {snap}")
        if snap.get("batch.dropped"):
            return fail(f"burst within budget still dropped: {snap}")
        if not _tile_tree(out):
            return fail("no tiles written after requeue recovery")
        log(f"submit_burst: {snap['batch.requeued']} requeues, 0 drops, "
            f"tiles written after recovery")

        # part 2: the matcher stays dead — budget exhausts, trace JSON
        # dead-letters, the stream itself survives
        metrics.default.reset()
        out2 = os.path.join(tmp, "dead")
        worker = _make_worker(city, out2, report_flush_interval_s=0.0)
        faults.configure("matcher.submit=error@0")
        try:
            worker.run(iter(lines))
        finally:
            faults.clear()
        snap = metrics.default.snapshot()["counters"]
        if not snap.get("batch.dropped") or not snap.get("batch.deadletter"):
            return fail(f"dead matcher did not dead-letter: {snap}")
        spool = worker.batcher.deadletter_dir
        names = sorted(os.listdir(spool)) if os.path.isdir(spool) else []
        if not names:
            return fail("no trace JSON in the dead-letter spool")
        with open(os.path.join(spool, names[0]), encoding="utf-8") as f:
            body = json.load(f)
        if not body.get("uuid") or not body.get("trace"):
            return fail(f"unreplayable dead-letter body: {body}")
        log(f"submit_burst ok: dead matcher -> {len(names)} trace(s) "
            f"spooled for replay, stream survived")
        return 0


# ---------------------------------------------------------------------------
def scenario_egress_outage() -> int:
    """Sink outage: every tile dead-letters; `datastore ingest --delete`
    replays the spool into a store that matches a fault-free run's."""
    from reporter_tpu.datastore import LocalDatastore, ingest_dir
    from reporter_tpu.utils import faults, metrics

    with tempfile.TemporaryDirectory() as tmp:
        city = _city()
        lines = _lines(city, n_traces=6)

        metrics.default.reset()
        out = os.path.join(tmp, "out")
        worker = _make_worker(city, out)
        faults.configure("egress.http=error@0")
        try:
            worker.run(iter(lines))
        finally:
            faults.clear()
        snap = metrics.default.snapshot()["counters"]
        if not snap.get("egress.fail") or not snap.get("egress.deadletter"):
            return fail(f"outage not spooled: {snap}")
        if _tile_tree(out):
            return fail("tiles reached a dead sink")
        spool = worker.anonymiser.sink.deadletter

        ds = LocalDatastore(os.path.join(tmp, "store"))
        got = ingest_dir(ds, spool, delete=True)
        if not got["rows"] or got["failures"]:
            return fail(f"dead-letter replay failed: {got}")
        leftover = [p for p in _tile_tree(spool)]
        if leftover:
            return fail(f"replayed spool not drained: {leftover[:5]}")

        # fault-free control run -> same aggregate store contents
        out2 = os.path.join(tmp, "out2")
        worker2 = _make_worker(city, out2)
        worker2.run(iter(lines))
        ds2 = LocalDatastore(os.path.join(tmp, "store2"))
        got2 = ingest_dir(ds2, out2)
        s1, s2 = ds.stats(), ds2.stats()
        for key in ("rows", "cells", "transitions"):
            if s1[key] != s2[key]:
                return fail(f"replayed store diverges on {key}: "
                            f"{s1[key]} != {s2[key]}")
        log(f"egress_outage ok: {got['files']} tiles replayed from the "
            f"spool, store parity with fault-free run "
            f"({s1['rows']} rows)")
        return 0


SCENARIOS = {
    "storm": scenario_storm,
    "kill_restore": scenario_kill_restore,
    "submit_burst": scenario_submit_burst,
    "egress_outage": scenario_egress_outage,
}


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    names = argv or ["storm", "kill_restore"]
    if names == ["all"]:
        names = list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        return fail(f"unknown scenario(s) {unknown}; "
                    f"one of {sorted(SCENARIOS)} or 'all'")
    for name in names:
        log(f"=== scenario {name} ===")
        rc = SCENARIOS[name]()
        if rc:
            return rc
    log(f"all {len(names)} scenario(s) passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
