#!/usr/bin/env python
"""ThreadSanitizer driver for the native host runtime (ISSUE 10).

`sanitize_tests.sh tsan` runs THIS script — not pytest — against the
`-fsanitize=thread` build. The pytest harness deadlocks under a
preloaded libtsan on common glibc pairings (observed: the session hangs
at the first test with every thread asleep, while the identical
operations in a plain script run clean), and a CI stage must never
hang. So the tsan leg drives the same native concurrency surface the
native test files cover, directly:

- WorkerPool span handoff: `prepare_batch` with
  ``REPORTER_TPU_PREP_THREADS=4`` shards spans across the pool with no
  phase barrier — the handoff of staged buffers between the submitting
  thread and the workers is exactly what TSan instruments.
- Striped route-memo clock eviction: a small
  ``REPORTER_TPU_ROUTE_MEMO`` bound forces concurrent whole-row
  lookups/inserts AND evictions from all four workers at once.
- Bit-identity contracts ride along (thread counts 1/2/5 must produce
  identical tensors; eviction pressure must not change a value), so the
  leg still fails on a *logic* race TSan happens not to flag.

Any TSan report aborts the process (``halt_on_error=1`` in the caller's
TSAN_OPTIONS) and fails the leg; any parity failure exits 1.
"""
from __future__ import annotations

import os
import sys
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")  # never probe a chip

PREP_KEYS = ("edge_ids", "dist_m", "offset_m", "route_m", "gc_m", "case",
             "kept_idx", "num_kept", "dwell", "has_cands", "max_finite")


def log(msg: str) -> None:
    print(f"tsan-drive: {msg}", flush=True)


def fail(msg: str) -> int:
    sys.stderr.write(f"tsan-drive: FAIL: {msg}\n")
    return 1


def main() -> int:
    import numpy as np

    from reporter_tpu import native
    from reporter_tpu.core.geo import equirectangular_m
    from reporter_tpu.graph import SpatialGrid
    from reporter_tpu.matcher import MatchParams, SegmentMatcher
    from reporter_tpu.matcher.batchpad import prepare_batch
    from reporter_tpu.synth import build_grid_city, generate_trace

    if not native.available():
        # the shell wrapper already proved the toolchain and built the
        # library; reaching here without it is a wiring error, not a skip
        return fail("native runtime unavailable (REPORTER_TPU_NATIVE_LIB "
                    "not set to the tsan build?)")

    city = build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=5)
    rng = np.random.default_rng(11)
    traces = []
    while len(traces) < 20:
        tr = generate_trace(city, f"p{len(traces)}", rng, noise_m=5.0,
                            min_route_edges=3, max_route_edges=14)
        if tr is not None and len(tr.points) >= 4:
            traces.append(tr.points[:60])

    # -- leg 1: batch-sorted candidate kernel vs the numpy grid ------------
    matcher = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
    grid = SpatialGrid(city)
    pts = [p for t in traces for p in t]
    lat = np.array([p["lat"] for p in pts])
    lon = np.array([p["lon"] for p in pts])
    c_np = grid.candidates(lat, lon, k=8)
    c_nat = matcher.runtime.candidates(lat, lon, k=8)
    if not np.array_equal(c_np.edge_ids, c_nat.edge_ids):
        return fail("batch-sorted candidate edges diverge from SpatialGrid")
    if not np.allclose(c_np.dist_m, c_nat.dist_m, atol=1e-3):
        return fail("batch-sorted candidate distances diverge")
    log(f"candidates parity: {len(pts)} points")

    # -- leg 2: prep bit-identical across thread counts --------------------
    outs = []
    for n_threads in (1, 2, 5):
        b = prepare_batch(matcher.runtime, traces, matcher.params, 64,
                          n_threads=n_threads)
        outs.append(b.prep)
    for k in PREP_KEYS:
        for other in outs[1:]:
            if not np.array_equal(np.asarray(outs[0][k]),
                                  np.asarray(other[k])):
                return fail(f"prep key {k} differs across thread counts")
    log("prep bit-identity: thread counts 1/2/5")

    # -- leg 3: concurrent prep storm over the WorkerPool ------------------
    # several Python threads each hammer their own runtime handle while
    # the in-handle pool (REPORTER_TPU_PREP_THREADS, 4 in this leg)
    # shards spans — TSan watches the staging-buffer handoff and every
    # shared-memo row op; bit-identity to the quiet run rides along
    errors: list = []
    golden = outs[0]

    def storm(rounds: int) -> None:
        try:
            m = SegmentMatcher(net=city,
                               params=MatchParams(max_candidates=8))
            for _ in range(rounds):
                b = prepare_batch(m.runtime, traces, m.params, 64,
                                  n_threads=4)
                for k in PREP_KEYS:
                    if not np.array_equal(np.asarray(b.prep[k]),
                                          np.asarray(golden[k])):
                        raise AssertionError(
                            f"prep key {k} diverged under the storm")
        except BaseException as e:  # surfaced below, never swallowed
            errors.append(e)

    threads = [threading.Thread(target=storm, args=(3,)) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        return fail(f"concurrent prep storm: {errors[0]}")
    log("concurrent prep storm: 4 threads x 3 rounds, parity held")

    # -- leg 4: striped route-memo clock eviction under pressure -----------
    os.environ["REPORTER_TPU_ROUTE_MEMO"] = "64"
    try:
        m = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
        prepare_batch(m.runtime, traces, m.params, 64, n_threads=4)
        a = prepare_batch(m.runtime, traces, m.params, 64, n_threads=4)
        stats = m.runtime.route_memo_stats()
        if stats["evictions"] <= 0:
            return fail(f"route-memo bound never evicted ({stats})")
        if stats["size"] > 64:
            return fail(f"route-memo exceeded its bound ({stats})")
    finally:
        del os.environ["REPORTER_TPU_ROUTE_MEMO"]
    for k in PREP_KEYS:
        if not np.array_equal(np.asarray(a.prep[k]),
                              np.asarray(golden[k])):
            return fail(f"prep key {k} changed under memo eviction")
    log(f"route-memo eviction: {stats['evictions']} evictions at "
        f"bound 64, values exact")

    # -- leg 5: cross-call memo reuse (whole-row hit path) ------------------
    tr = None
    rng2 = np.random.default_rng(4)
    while tr is None:
        tr = generate_trace(city, "memo", rng2, noise_m=4.0,
                            min_route_edges=8)
    m = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
    mlat = np.array([p["lat"] for p in tr.points])
    mlon = np.array([p["lon"] for p in tr.points])
    cands = m.runtime.candidates(mlat, mlon, k=8)
    gc = np.asarray(equirectangular_m(mlat[:-1], mlon[:-1], mlat[1:],
                                      mlon[1:]), dtype=np.float32)
    m.runtime.route_matrices(cands, gc)
    s1 = m.runtime.route_memo_stats()
    m.runtime.route_matrices(cands, gc)
    s2 = m.runtime.route_memo_stats()
    if not (s2["hits"] > s1["hits"] and s2["misses"] == s1["misses"]):
        return fail(f"route-memo cross-call reuse broken ({s1} -> {s2})")
    log("route-memo cross-call reuse: hit path exercised")

    # -- leg 6: wire writer storm (ISSUE 11) --------------------------------
    # Many Python threads serialise /report bodies from ONE shared
    # chunk's RunColumns concurrently — the GIL-released per-trace and
    # whole-chunk C calls read the shared columns (and the cached
    # packed-pointer array) at the same time, and threads race the
    # chunk-memo build (the benign last-writer-wins documented in
    # service/wire.py) — exactly the serving pattern under
    # BoundedThreadingHTTPServer. The columns are SYNTHESISED here (no
    # decode: jax under a preloaded libtsan is the deadlock this driver
    # exists to avoid); byte parity with the single-threaded Python
    # writer rides along.
    from reporter_tpu.matcher.matcher import MatchRuns, RunColumns
    from reporter_tpu.service.report import _report_json_py, report_wire

    wrng = np.random.default_rng(17)
    n_traces_w, runs_per = 16, 6
    n_runs = n_traces_w * runs_per
    starts = np.round(1.5e9 + np.cumsum(
        wrng.uniform(1.0, 9.0, n_runs)), 3)
    ends = np.round(starts + wrng.uniform(0.5, 6.0, n_runs), 3)
    starts[::17] = -1.0  # sentinel rows, like real discontinuities
    ends[::17] = -1.0
    seg_id = wrng.integers(0, 1 << 40, n_runs).astype(np.int64)
    seg_id[::5] = -1  # unassociated rows
    n_ways = 2 * n_runs
    runs_dict = {
        "seg_id": seg_id,
        "internal": (wrng.random(n_runs) < 0.15).astype(np.uint8),
        "start": starts, "end": ends,
        "length": wrng.integers(5, 900, n_runs).astype(np.int32),
        "queue": wrng.integers(0, 60, n_runs).astype(np.int32),
        "begin_idx": np.arange(n_runs, dtype=np.int32),
        "end_idx": np.arange(1, n_runs + 1, dtype=np.int32),
        "way_off": np.arange(0, n_ways + 1, 2,
                             dtype=np.int64)[:n_runs + 1],
        "ways": wrng.integers(1, 1 << 30, n_ways).astype(np.int64),
    }
    wcols = RunColumns(runs_dict)
    run_off = np.arange(0, n_runs + 1, runs_per, dtype=np.int64)
    t_ends = np.round(
        np.array([starts[min(hi, n_runs) - 1] + 30.0
                  for hi in run_off[1:]]), 3)
    wcols.arrays["_run_off"] = run_off
    wcols.arrays["_trace_end"] = np.ascontiguousarray(t_ends,
                                                      np.float64)
    runs = []
    for t in range(n_traces_w):
        mr = MatchRuns(wcols, int(run_off[t]), int(run_off[t + 1]),
                       "auto")
        rq = {"uuid": f"wire-{t}",
              "trace": [{"time": float(t_ends[t])}]}
        runs.append((mr, rq))
    want = [_report_json_py(mm, rq, 15, {0, 1, 2}, {0, 1, 2})
            .encode("utf-8") for mm, rq in runs]
    wire_errors: list = []

    def wire_storm(rounds: int) -> None:
        try:
            for _ in range(rounds):
                # force fresh chunk-memo builds so threads race the
                # whole-chunk C emission, not just memo reads
                wcols.arrays.pop("_wire_chunk", None)
                for (mm, rq), exp in zip(runs, want):
                    got = report_wire(mm, rq, 15, {0, 1, 2}, {0, 1, 2})
                    if bytes(got) != exp:
                        raise AssertionError(
                            f"wire bytes diverged for {rq['uuid']}")
        except BaseException as e:
            wire_errors.append(e)

    wthreads = [threading.Thread(target=wire_storm, args=(6,))
                for _ in range(4)]
    for t in wthreads:
        t.start()
    for t in wthreads:
        t.join()
    if wire_errors:
        return fail(f"wire writer storm: {wire_errors[0]}")
    from reporter_tpu.utils import metrics
    if metrics.counter("wire.native") <= 0:
        return fail("wire writer storm never took the native backend")
    log(f"wire writer storm: 4 threads x 6 rounds over {len(runs)} "
        f"traces, byte parity held")

    # -- leg 7: memo warm/export vs live prep (ISSUE 14) --------------------
    # The serving tier pre-warms a newly resident city's route memo
    # from a profile artifact WHILE requests may already be hammering
    # the same handle: rt_route_memo_warm's bounded Dijkstra + batched
    # row inserts race rt_prepare_batch's row lookups/inserts and
    # rt_route_memo_export's whole-stripe walks. Bit-identity of the
    # prep outputs rides along (a warmed kernel must equal a computed
    # one), so a logic race TSan misses still fails the leg.
    wm = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
    ea0, eb0 = matcher.runtime.route_memo_export(1 << 16)
    if ea0.size == 0:
        return fail("nothing to export from the warmed handle")
    warm_errors: list = []

    def warm_storm(rounds: int) -> None:
        try:
            for _ in range(rounds):
                wm.runtime.route_memo_warm(ea0, eb0)
                wm.runtime.route_memo_export(1 << 16)
        except BaseException as e:
            warm_errors.append(e)

    def prep_storm(rounds: int) -> None:
        try:
            for _ in range(rounds):
                b = prepare_batch(wm.runtime, traces, wm.params, 64,
                                  n_threads=4)
                for k in PREP_KEYS:
                    if not np.array_equal(np.asarray(b.prep[k]),
                                          np.asarray(golden[k])):
                        raise AssertionError(
                            f"prep key {k} diverged under warm storm")
        except BaseException as e:
            warm_errors.append(e)

    wsthreads = ([threading.Thread(target=warm_storm, args=(4,))
                  for _ in range(2)]
                 + [threading.Thread(target=prep_storm, args=(3,))
                    for _ in range(2)])
    for t in wsthreads:
        t.start()
    for t in wsthreads:
        t.join()
    if warm_errors:
        return fail(f"memo warm/export storm: {warm_errors[0]}")
    wstats = wm.runtime.route_memo_stats()
    if wstats["size"] <= 0:
        return fail(f"warm storm left an empty memo ({wstats})")
    log(f"memo warm/export storm: 2 warmers x 2 preppers over "
        f"{ea0.size} pairs, prep parity held")

    log("clean: all legs passed under the tsan build")
    return 0


if __name__ == "__main__":
    sys.exit(main())
