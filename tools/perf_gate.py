#!/usr/bin/env python
"""CI perf regression gate: ratios vs the ledger median, never
absolutes.

Fails (exit 1) when a candidate bench run's ``vs_baseline`` ratio —
batched throughput over the SAME box's single-process baseline — falls
more than ``--tolerance`` below the median of comparable ledger
entries, or when a stage's share of wall grows more than
``--share-tolerance`` (absolute) above the ledger median share.
Absolute traces/sec are never compared: bench boxes drift ~2x between
rounds (BENCH_DEV_r06 measured it), and a gate on absolutes would flap
on every box change. This is the "ratio-tolerance mode" ci.yml runs.

Comparable = same ``platform``, a recorded ratio, and (for the share
check) the same ``pipelined`` flag — pipelined stage seconds overlap
the wall, so shares are only meaningful against like-pipelined runs.

Box drift containment: even ratios drift between box draws (the
committed history spans 13.9-27.8 on the same code lineage). An
artifact may therefore carry a same-session ``control`` run — the
prior configuration re-benched on the SAME box. A candidate below the
cross-box floor still passes the ratio check iff the control is ALSO
below the floor (the box provably can't reach the median that day) and
the candidate is within ``--tolerance`` of the control. A healthy box
gets no leniency, and shares/padding/query gates are never relaxed.

Usage:
    # gate a fresh bench artifact (e.g. bench_smoke --out) against the
    # committed ledger
    python tools/perf_gate.py --candidate artifact.json

    # ledger self-consistency: the newest comparable entry gated
    # against the median of the rest (the CI sanity leg)
    python tools/perf_gate.py --self-check

    # change-feed fan-out: zero-silent-loss accounting + fanout floor
    # over a tools/feed_fanout_bench.py artifact (ISSUE 18)
    python tools/perf_gate.py --feed BENCH_FEED_r01.json

    # incremental matcher: per-appended-point decode flatness + zero
    # parity mismatches over a tools/stream_bench.py artifact (ISSUE 19)
    python tools/perf_gate.py --streaming BENCH_STREAM_r01.json

Exit 0 prints the verdict JSON with ``"pass": true``; any regression
prints the offending comparison and exits 1. An empty comparable pool
passes with a note (bootstrap-friendly) unless ``--require-history``.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from typing import List, Optional, Tuple

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
from reporter_tpu.obs import ledger as perf_ledger  # noqa: E402

DEFAULT_TOLERANCE = 0.15
DEFAULT_SHARE_TOLERANCE = 0.20


def load_candidate(path: str) -> dict:
    """A candidate entry from either a raw bench.py artifact or an
    already-normalised ledger-entry JSON object."""
    if path == "-":
        d = json.load(sys.stdin)
        source = "stdin"
    else:
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
        source = os.path.basename(path)
    if "metric" in d:  # raw bench.py artifact
        return perf_ledger.entry_from_bench(d, source, "candidate",
                                            "bench")
    if "vs_baseline" in d:  # already-normalised ledger entry
        d.setdefault("source", source)
        d.setdefault("stage_shares", None)
        d.setdefault("platform", None)
        d.setdefault("pipelined", None)
        return d
    raise SystemExit(f"candidate {source} is neither a bench artifact "
                     "nor a ledger entry (no vs_baseline)")


def comparable_pool(entries: List[dict], platform: Optional[str],
                    scope: Optional[str] = None) -> List[dict]:
    pool = [e for e in entries
            if e.get("vs_baseline") is not None
            and e.get("kind") in ("bench", "bench_dev")]
    if platform:
        pool = [e for e in pool if e.get("platform") == platform]
    if scope:
        # like-scale only: a 48-trace smoke run's ratio is structurally
        # below a 512-trace run's (amortisation) — never cross-compare
        pool = [e for e in pool if e.get("scope", "full") == scope]
    return pool


def gate(candidate: dict, entries: List[dict], tolerance: float,
         share_tolerance: float, require_history: bool
         ) -> Tuple[bool, dict]:
    """(passed, verdict) — the pure decision, unit-testable."""
    platform = candidate.get("platform")
    scope = candidate.get("scope", "full")
    pool = comparable_pool(entries, platform, scope)
    verdict: dict = {
        "candidate": {"source": candidate.get("source"),
                      "platform": platform, "scope": scope,
                      "vs_baseline": candidate.get("vs_baseline"),
                      "pipelined": candidate.get("pipelined")},
        "pool": len(pool),
        "tolerance": tolerance,
        "share_tolerance": share_tolerance,
        "failures": [],
    }
    if not pool:
        verdict["note"] = ("no comparable ledger entries for platform="
                           f"{platform!r} scope={scope!r}; nothing to "
                           "gate against (append smoke-scope history "
                           "with perf_ledger.py to make this bind)")
        return (not require_history), verdict

    median = statistics.median(e["vs_baseline"] for e in pool)
    floor = median * (1.0 - tolerance)
    verdict["median_vs_baseline"] = round(median, 3)
    verdict["floor"] = round(floor, 3)
    cand_vs = candidate.get("vs_baseline")
    # same-box drift control (BENCH artifacts carry it as a "control"
    # block; ledger entries as control_vs_baseline): the PRIOR
    # configuration re-benched in the same session. When the control
    # itself lands below the cross-box floor the box demonstrably
    # cannot reach the ledger median that day — ratios drift ~2x
    # between box draws just like absolutes (r10 measured it) — so the
    # binding comparison becomes candidate-vs-control on the SAME box.
    # A healthy box (control at/above floor) gets no such leniency.
    ctrl = candidate.get("control_vs_baseline")
    if ctrl is None and isinstance(candidate.get("control"), dict):
        ctrl = candidate["control"].get("vs_baseline")
    if cand_vs is None:
        verdict["failures"].append(
            {"check": "ratio", "reason": "candidate has no vs_baseline "
             "(failed run?)"})
    elif cand_vs < floor:
        if ctrl is not None and ctrl < floor \
                and cand_vs >= ctrl * (1.0 - tolerance):
            verdict["ratio_drift_control"] = {
                "control_vs_baseline": ctrl,
                "control_floor": round(ctrl * (1.0 - tolerance), 3),
                "note": (f"vs_baseline {cand_vs} is below the cross-box "
                         f"floor {floor:.2f}, but the same-box control "
                         f"run only reached {ctrl} — box drift, not a "
                         "code regression; gated against the control "
                         "instead"),
            }
        else:
            verdict["failures"].append(
                {"check": "ratio", "candidate": cand_vs,
                 "median": round(median, 3), "floor": round(floor, 3),
                 "reason": f"vs_baseline {cand_vs} fell more than "
                 f"{tolerance:.0%} below the ledger median {median:.2f}"})

    shares = candidate.get("stage_shares")
    pipelined = candidate.get("pipelined")
    if shares and pipelined is not None:
        like = [e for e in pool
                if e.get("stage_shares")
                and e.get("pipelined") == pipelined]
        share_medians = {}
        for stage in perf_ledger.SHARE_STAGES:
            vals = [e["stage_shares"][stage] for e in like
                    if stage in e["stage_shares"]]
            if vals:
                share_medians[stage] = statistics.median(vals)
        verdict["share_medians"] = {k: round(v, 4)
                                    for k, v in share_medians.items()}
        for stage, cand_share in shares.items():
            med = share_medians.get(stage)
            if med is None:
                continue
            if cand_share > med + share_tolerance:
                verdict["failures"].append(
                    {"check": "share", "stage": stage,
                     "candidate": cand_share, "median": round(med, 4),
                     "reason": f"{stage} share {cand_share} grew more "
                     f"than {share_tolerance} above the ledger median "
                     f"{med:.3f}"})
    return (not verdict["failures"]), verdict


def gate_shares_absolute(candidate: dict, max_shares: dict
                         ) -> Tuple[bool, dict]:
    """Absolute per-stage share ceilings (``--max-share report=0.2``):
    the median gate only catches REGRESSIONS vs history — a ceiling
    pins a stage's share below a hard target (ISSUE 11: the native
    wire writer must hold the serialized ``report`` share at or below
    its acceptance number, not merely match the ledger median)."""
    shares = candidate.get("stage_shares") or {}
    verdict: dict = {"candidate": {"source": candidate.get("source"),
                                   "stage_shares": shares},
                     "max_shares": max_shares, "failures": []}
    for stage, ceil in max_shares.items():
        got = shares.get(stage)
        if got is None:
            verdict["failures"].append(
                {"check": "max_share", "stage": stage,
                 "reason": f"candidate records no {stage!r} share to "
                 "hold under the ceiling"})
        elif got > ceil:
            verdict["failures"].append(
                {"check": "max_share", "stage": stage, "candidate": got,
                 "ceiling": ceil,
                 "reason": f"{stage} share {got} exceeds the hard "
                 f"ceiling {ceil}"})
    return (not verdict["failures"]), verdict


def gate_padding_waste(candidate: dict, ceiling: float
                       ) -> Tuple[bool, dict]:
    """Hard ceiling on the adaptive-bucket leg's padding waste
    (``--max-padding-waste 0.10``): the ISSUE-13 acceptance number —
    the mixed-length bench batch's decoded point slots must stay
    mostly real probes, not pad. Reads the bench artifact's
    ``bucketing.adaptive_waste`` (the after-leg of the before/after
    pair); a candidate without the block fails loudly — the ceiling
    the caller believes binds must never be skipped silently. The one
    exception is an EXPLICIT skip (``bucketing.skipped``, recorded by
    bench.py when the native runtime is absent): a declared
    native-less run passes with the note carried into the verdict —
    nothing regressed, the leg just cannot run there."""
    bucketing = candidate.get("bucketing") or {}
    waste = bucketing.get("adaptive_waste")
    verdict: dict = {"candidate": {"source": candidate.get("source"),
                                   "bucketing": bucketing or None},
                     "max_padding_waste": ceiling, "failures": []}
    if bucketing.get("skipped"):
        verdict["note"] = f"bucketing leg skipped: {bucketing['skipped']}"
        return True, verdict
    if waste is None:
        verdict["failures"].append(
            {"check": "padding_waste", "reason": "candidate records no "
             "bucketing.adaptive_waste to hold under the ceiling"})
    elif waste > ceiling:
        verdict["failures"].append(
            {"check": "padding_waste", "candidate": waste,
             "ceiling": ceiling,
             "reason": f"adaptive-bucket padding waste {waste} exceeds "
             f"the hard ceiling {ceiling} (fixed-ladder leg recorded "
             f"{bucketing.get('fixed_waste')})"})
    return (not verdict["failures"]), verdict


def gate_query_ratio(candidate: dict, floor: float) -> Tuple[bool, dict]:
    """Floor on the serving-tier batched-query speedup
    (``--min-query-ratio 5``): the ISSUE-14 acceptance number — ONE
    ``query_many(256)`` sweep must answer at least ``floor``x faster
    than 256 single queries (bench.py's ``query`` block; answers are
    parity-asserted inside the leg before timing). A candidate without
    the block fails loudly; an explicit ``error`` record fails with the
    recorded reason — a silently missing ratio must never pass a floor
    the caller believes binds."""
    query = candidate.get("query") or {}
    ratio = query.get("batch_ratio")
    verdict: dict = {"candidate": {"source": candidate.get("source"),
                                   "query": query or None},
                     "min_query_ratio": floor, "failures": []}
    if query.get("error"):
        verdict["failures"].append(
            {"check": "query_ratio", "reason": "query leg failed: "
             + str(query["error"])})
    elif ratio is None:
        verdict["failures"].append(
            {"check": "query_ratio", "reason": "candidate records no "
             "query.batch_ratio to hold over the floor"})
    elif ratio < floor:
        verdict["failures"].append(
            {"check": "query_ratio", "candidate": ratio, "floor": floor,
             "reason": f"query_many({query.get('n_segments')}) answered "
             f"only {ratio}x faster than single queries (floor {floor})"})
    return (not verdict["failures"]), verdict


def gate_multichip(path: str, min_ratio: float) -> Tuple[bool, dict]:
    """Gate a tools/multichip_bench.py artifact: every leg ran, ratios
    were measured, and no device count fell below ``min_ratio`` x the
    1-device throughput (on a CPU box the virtual mesh shards compute-
    bound work over the same cores, so the default floor only catches
    a catastrophic sharding regression; raise it on real hardware)."""
    with open(path, encoding="utf-8") as f:
        art = json.load(f)
    ratios = art.get("ratios") or {}
    verdict = {
        "candidate": {"source": os.path.basename(path),
                      "kind": "multichip",
                      "n_devices": art.get("n_devices")},
        "ratios": ratios, "min_ratio": min_ratio, "failures": [],
    }
    if not art.get("ok"):
        verdict["failures"].append(
            {"check": "multichip", "reason": "artifact reports ok=false "
             f"(tail: {art.get('tail', '')[:120]})"})
    if not ratios:
        verdict["failures"].append(
            {"check": "multichip", "reason": "artifact carries no "
             "device-count ratios (legacy liveness-only verdict? "
             "re-run tools/multichip_bench.py)"})
    # the r06 lesson: every leg must have SEEN the device count it
    # claims to measure — an artifact whose legs disagree with their
    # requested counts carries ratios of nothing (the committed r06
    # ratios 0.71-0.89 were exactly this, devices_seen: 1 everywhere)
    for leg in art.get("legs") or []:
        if leg.get("devices_seen") != leg.get("n_devices"):
            verdict["failures"].append(
                {"check": "multichip", "n_devices": leg.get("n_devices"),
                 "devices_seen": leg.get("devices_seen"),
                 "reason": f"leg requested {leg.get('n_devices')} "
                 f"device(s) but saw {leg.get('devices_seen')} — the "
                 "forced host-device count never reached the leg, so "
                 "its throughput ratio is meaningless"})
    for count, ratio in sorted(ratios.items(), key=lambda kv: int(kv[0])):
        if ratio < min_ratio:
            verdict["failures"].append(
                {"check": "multichip", "n_devices": int(count),
                 "candidate": ratio, "floor": min_ratio,
                 "reason": f"{count}-device throughput fell to {ratio}x "
                 f"the 1-device leg (floor {min_ratio})"})
    return (not verdict["failures"]), verdict


def gate_bigreplay(path: str, min_ratio: float) -> Tuple[bool, dict]:
    """Gate a tools/bigreplay.py artifact: the chaos leg's throughput
    over the clean leg's (same process, same box — a true ratio) must
    not fall below ``min_ratio``. This is the "robustness never
    silently costs performance" leg: a fault-path regression (a
    blocking drainer, an over-eager breaker, a spool fsync storm)
    shows up as the chaos leg slowing relative to clean long before it
    shows in clean-path medians."""
    with open(path, encoding="utf-8") as f:
        art = json.load(f)
    if art.get("kind") != "bigreplay":
        raise SystemExit(f"{path} is not a bigreplay artifact")
    ratio = art.get("fault_throughput_ratio")
    verdict = {
        "candidate": {"source": os.path.basename(path),
                      "kind": "bigreplay",
                      "probes": art.get("probes"),
                      "agreement": art.get("agreement")},
        "fault_throughput_ratio": ratio,
        "min_ratio": min_ratio,
        "failures": [],
    }
    if ratio is None:
        verdict["failures"].append(
            {"check": "bigreplay", "reason": "artifact carries no "
             "fault_throughput_ratio (failed run?)"})
    elif ratio < min_ratio:
        verdict["failures"].append(
            {"check": "bigreplay", "candidate": ratio,
             "floor": min_ratio,
             "reason": f"chaos-leg throughput fell to {ratio:.2f}x the "
             f"clean leg (floor {min_ratio}) — the robustness machinery "
             "is taxing the hot path"})
    return (not verdict["failures"]), verdict


def gate_feed(path: str, min_fanout: float) -> Tuple[bool, dict]:
    """Gate a tools/feed_fanout_bench.py artifact: the zero-silent-loss
    contract (ISSUE 18). Every subscriber must be accounted for —
    delivered, shed with the explicit 429 + Retry-After signal, or an
    error — with ``silent_lost == 0`` and ``errors == 0``; the
    accounting must close (delivered + shed + errors + silent_lost ==
    subscribers); and ``fanout_ratio`` must hold ``min_fanout``. A
    missing field fails loudly — an artifact that never counted a
    category must not pass a gate about counting."""
    with open(path, encoding="utf-8") as f:
        art = json.load(f)
    if art.get("kind") != "feed_fanout":
        raise SystemExit(f"{path} is not a feed_fanout artifact")
    verdict = {
        "candidate": {"source": os.path.basename(path),
                      "kind": "feed_fanout",
                      "subscribers": art.get("subscribers"),
                      "procs": art.get("procs"),
                      "delivery_p99_ms": art.get("delivery_p99_ms")},
        "fanout_ratio": art.get("fanout_ratio"),
        "min_fanout_ratio": min_fanout,
        "failures": [],
    }
    fields = ("subscribers", "delivered", "shed", "errors",
              "silent_lost", "fanout_ratio")
    missing = [k for k in fields if art.get(k) is None]
    if missing:
        verdict["failures"].append(
            {"check": "feed", "reason": "artifact is missing "
             f"{missing} — a category that was never counted cannot "
             "pass a loss gate"})
        return False, verdict
    if art["silent_lost"]:
        verdict["failures"].append(
            {"check": "feed", "candidate": art["silent_lost"],
             "floor": 0,
             "reason": f"{art['silent_lost']} subscriber(s) saw "
             "neither the event nor an explicit shed signal — the "
             "zero-silent-loss contract is broken"})
    if art["errors"]:
        verdict["failures"].append(
            {"check": "feed", "candidate": art["errors"], "floor": 0,
             "reason": f"{art['errors']} subscriber(s) errored "
             f"({art.get('error_kinds')})"})
    accounted = art["delivered"] + art["shed"] + art["errors"] \
        + art["silent_lost"]
    if accounted != art["subscribers"]:
        verdict["failures"].append(
            {"check": "feed", "candidate": accounted,
             "floor": art["subscribers"],
             "reason": f"accounting open: delivered+shed+errors+lost "
             f"= {accounted} != {art['subscribers']} subscribers"})
    if art["fanout_ratio"] < min_fanout:
        verdict["failures"].append(
            {"check": "feed", "candidate": art["fanout_ratio"],
             "floor": min_fanout,
             "reason": f"fanout_ratio {art['fanout_ratio']} < floor "
             f"{min_fanout}: the measured commit did not reach enough "
             "of the subscriber fleet"})
    return (not verdict["failures"]), verdict


def gate_streaming(path: str, max_ratio: float) -> Tuple[bool, dict]:
    """Gate a tools/stream_bench.py artifact: the incremental matcher's
    flat-decode contract (ISSUE 19). Per-appended-point decode p99 at
    the longest window over the shortest (``flatness_ratio``) must stay
    within ``max_ratio`` — a carried-state advance whose cost grows
    with the window length is a whole-window re-decode wearing a cache
    — parity mismatches against the batch oracle must be ZERO, and
    every leg must have actually served incrementally (flatness over an
    all-fallback leg is vacuous). A missing field fails loudly."""
    with open(path, encoding="utf-8") as f:
        art = json.load(f)
    if art.get("kind") != "streaming":
        raise SystemExit(f"{path} is not a streaming artifact")
    verdict = {
        "candidate": {"source": os.path.basename(path),
                      "kind": "streaming",
                      "lag": art.get("lag"),
                      "windows": sorted(int(t) for t in
                                        (art.get("legs") or {}))},
        "flatness_ratio": art.get("flatness_ratio"),
        "max_stream_ratio": max_ratio,
        "failures": [],
    }
    legs = art.get("legs") or {}
    missing = [k for k in ("flatness_ratio", "parity_mismatches")
               if art.get(k) is None]
    if not legs or len(legs) < 2:
        missing.append("legs")
    if missing:
        verdict["failures"].append(
            {"check": "streaming", "reason": "artifact is missing "
             f"{missing} — a quantity that was never measured cannot "
             "pass a flatness gate"})
        return False, verdict
    if art["parity_mismatches"]:
        verdict["failures"].append(
            {"check": "streaming", "candidate": art["parity_mismatches"],
             "floor": 0,
             "reason": f"{art['parity_mismatches']} served window(s) "
             "differed from the batch oracle — the byte-parity "
             "contract is broken"})
    for t, leg in sorted(legs.items(), key=lambda kv: int(kv[0])):
        if not leg.get("served"):
            verdict["failures"].append(
                {"check": "streaming", "candidate": 0, "floor": 1,
                 "reason": f"T={t} served no window incrementally — "
                 "its decode timings gate nothing"})
    if art["flatness_ratio"] > max_ratio:
        verdict["failures"].append(
            {"check": "streaming", "candidate": art["flatness_ratio"],
             "ceiling": max_ratio,
             "reason": f"flatness_ratio {art['flatness_ratio']} > "
             f"{max_ratio}: per-appended-point decode cost grows with "
             "the window length"})
    return (not verdict["failures"]), verdict


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="perf_gate",
                                     description=__doc__.splitlines()[0])
    parser.add_argument("--ledger", default=perf_ledger.DEFAULT_LEDGER)
    parser.add_argument("--candidate",
                        help="bench artifact or ledger-entry JSON file "
                        "('-' for stdin)")
    parser.add_argument("--self-check", action="store_true",
                        help="gate the newest comparable ledger entry "
                        "against the median of the rest")
    parser.add_argument("--bigreplay",
                        help="bigreplay artifact: gate the chaos/clean "
                        "throughput ratio against --min-fault-ratio")
    parser.add_argument("--multichip",
                        help="multichip_bench artifact: gate every "
                        "device-count throughput ratio against "
                        "--min-device-ratio")
    parser.add_argument("--feed",
                        help="feed_fanout_bench artifact: gate the "
                        "zero-silent-loss accounting and fanout ratio "
                        "against --min-fanout-ratio")
    parser.add_argument("--streaming",
                        help="stream_bench artifact: gate per-appended-"
                        "point decode flatness against "
                        "--max-stream-ratio and parity mismatches "
                        "against zero")
    parser.add_argument("--max-stream-ratio", type=float, default=1.5,
                        help="ceiling for decode p99 at the longest "
                        "window over the shortest in the --streaming "
                        "gate (default 1.5; parity gates at zero "
                        "regardless)")
    parser.add_argument("--min-fanout-ratio", type=float, default=0.95,
                        help="floor for delivered/subscribers in the "
                        "--feed gate (default 0.95; loss and errors "
                        "gate at zero regardless)")
    parser.add_argument("--min-device-ratio", type=float, default=0.5,
                        help="floor for each N-device over 1-device "
                        "throughput ratio (default 0.5: a CPU box's "
                        "virtual mesh shards the same cores; raise on "
                        "real hardware)")
    parser.add_argument("--max-share", action="append", default=[],
                        metavar="STAGE=CEIL",
                        help="hard absolute ceiling on a candidate "
                        "stage share (repeatable), e.g. report=0.2 — "
                        "checked in addition to the median gate")
    parser.add_argument("--max-padding-waste", type=float, default=None,
                        metavar="CEIL",
                        help="hard ceiling on the candidate's adaptive-"
                        "bucket padding waste (bucketing.adaptive_waste"
                        " from bench.py's before/after pair), e.g. 0.10"
                        " — checked in addition to the median gate")
    parser.add_argument("--min-query-ratio", type=float, default=None,
                        metavar="FLOOR",
                        help="floor on the candidate's batched-query "
                        "speedup (query.batch_ratio from bench.py's "
                        "query_many-vs-singles pair), e.g. 5")
    parser.add_argument("--min-fault-ratio", type=float, default=0.4,
                        help="floor for the bigreplay chaos-over-clean "
                        "throughput ratio (default 0.4 — small smoke "
                        "runs are noisy; raise it for full-scale runs)")
    parser.add_argument("--tolerance", type=float,
                        default=DEFAULT_TOLERANCE,
                        help="allowed relative vs_baseline drop below "
                        "the ledger median (default 0.15)")
    parser.add_argument("--share-tolerance", type=float,
                        default=DEFAULT_SHARE_TOLERANCE,
                        help="allowed absolute stage-share growth above "
                        "the ledger median (default 0.20)")
    parser.add_argument("--require-history", action="store_true",
                        help="fail instead of passing when no "
                        "comparable entries exist")
    args = parser.parse_args(argv)

    max_shares = {}
    for spec in args.max_share:
        try:
            stage, ceil = spec.split("=", 1)
            max_shares[stage.strip()] = float(ceil)
        except ValueError:
            parser.error(f"--max-share wants STAGE=CEIL, got {spec!r}")
    if (max_shares or args.max_padding_waste is not None
            or args.min_query_ratio is not None) \
            and (args.bigreplay or args.multichip or args.feed):
        # those artifacts carry no stage shares / bucketing block —
        # refuse loudly rather than silently ignoring a ceiling the
        # caller believes binds
        parser.error("--max-share/--max-padding-waste/--min-query-ratio "
                     "apply to --candidate/--self-check runs only")

    if args.bigreplay:
        passed, verdict = gate_bigreplay(args.bigreplay,
                                         args.min_fault_ratio)
        verdict["pass"] = passed
        print(json.dumps(verdict, separators=(",", ":")))
        if not passed:
            for f in verdict["failures"]:
                sys.stderr.write(f"perf_gate: FAIL: {f['reason']}\n")
        return 0 if passed else 1

    if args.feed:
        passed, verdict = gate_feed(args.feed, args.min_fanout_ratio)
        verdict["pass"] = passed
        print(json.dumps(verdict, separators=(",", ":")))
        if not passed:
            for f in verdict["failures"]:
                sys.stderr.write(f"perf_gate: FAIL: {f['reason']}\n")
        return 0 if passed else 1

    if args.streaming:
        passed, verdict = gate_streaming(args.streaming,
                                         args.max_stream_ratio)
        verdict["pass"] = passed
        print(json.dumps(verdict, separators=(",", ":")))
        if not passed:
            for f in verdict["failures"]:
                sys.stderr.write(f"perf_gate: FAIL: {f['reason']}\n")
        return 0 if passed else 1

    if args.multichip:
        passed, verdict = gate_multichip(args.multichip,
                                         args.min_device_ratio)
        verdict["pass"] = passed
        print(json.dumps(verdict, separators=(",", ":")))
        if not passed:
            for f in verdict["failures"]:
                sys.stderr.write(f"perf_gate: FAIL: {f['reason']}\n")
        return 0 if passed else 1

    entries = perf_ledger.load_ledger(args.ledger)
    if args.self_check:
        # the BINDING leg: gate the newest full-scope entry (the
        # committed-artifact lineage) against the median of the rest,
        # with an empty pool counting as failure — appended smoke-scope
        # history must neither become the candidate (its first entry
        # would have no pool and pass vacuously) nor break this leg
        pool = comparable_pool(entries, None, "full") \
            or comparable_pool(entries, None)
        if not pool:
            print(json.dumps({"pass": False,
                              "note": "self-check: empty ledger"}))
            return 1
        candidate = pool[-1]  # newest (ledger is append-only)
        rest = [e for e in entries if e is not candidate]
        passed, verdict = gate(candidate, rest, args.tolerance,
                               args.share_tolerance,
                               require_history=True)
    elif args.candidate:
        candidate = load_candidate(args.candidate)
        passed, verdict = gate(candidate, entries, args.tolerance,
                               args.share_tolerance,
                               args.require_history)
    else:
        parser.error("need --candidate FILE, --self-check, "
                     "--bigreplay FILE, --multichip FILE, "
                     "--feed FILE or --streaming FILE")
        return 2  # unreachable; parser.error exits

    if max_shares:  # absolute ceilings, on top of the median gate
        abs_ok, abs_verdict = gate_shares_absolute(candidate, max_shares)
        verdict["max_shares"] = abs_verdict["max_shares"]
        verdict["failures"].extend(abs_verdict["failures"])
        passed = passed and abs_ok

    if args.max_padding_waste is not None:
        pw_ok, pw_verdict = gate_padding_waste(candidate,
                                               args.max_padding_waste)
        verdict["max_padding_waste"] = args.max_padding_waste
        verdict["failures"].extend(pw_verdict["failures"])
        passed = passed and pw_ok

    if args.min_query_ratio is not None:
        qr_ok, qr_verdict = gate_query_ratio(candidate,
                                             args.min_query_ratio)
        verdict["min_query_ratio"] = args.min_query_ratio
        verdict["failures"].extend(qr_verdict["failures"])
        passed = passed and qr_ok

    verdict["pass"] = passed
    print(json.dumps(verdict, separators=(",", ":")))
    if not passed:
        for fail in verdict["failures"]:
            sys.stderr.write(f"perf_gate: FAIL: {fail['reason']}\n")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
