#!/bin/bash
# Tunnel watcher + TPU measurement battery (developer tool).
#
# The axon chip tunnel in this environment is intermittent; this script
# polls until the chip answers, then runs, in order:
#   1. microbench: tiny-jit RTT, h2d bandwidth at two sizes, d2h RTT
#      -> distinguishes per-call latency from bandwidth as the device-
#         chain bottleneck (pre-pipeline hardware run: 84 ms device
#         chain per 512 traces, composition unknown)
#   2. bench.py default (pipelined) -> the headline number
#   3. REPORTER_TPU_DECODE_CHUNK sweep (64/256/512; fewer repeats)
#   4. REPORTER_TPU_WIRE=f32 leg: doubles wire bytes; a large drop
#      means bandwidth-bound, no drop means RTT-bound
# Results land in tpu_lab_results/ as timestamped JSON/logs.
set -u
cd "$(dirname "$0")/.."
OUT=tpu_lab_results
mkdir -p "$OUT"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
LOG="$OUT/lab_$STAMP.log"
MAX_POLLS=${TPU_LAB_MAX_POLLS:-120}          # x interval = watch window
POLL_INTERVAL=${TPU_LAB_POLL_INTERVAL:-300}  # seconds

probe() {
  timeout 75 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu', d" \
    >/dev/null 2>&1
}

echo "[lab $STAMP] watching for the chip tunnel" | tee -a "$LOG"
for ((i = 1; i <= MAX_POLLS; i++)); do
  if probe; then
    echo "[lab] tunnel up on poll $i ($(date -u +%H:%M:%SZ))" | tee -a "$LOG"
    break
  fi
  if ((i == MAX_POLLS)); then
    echo "[lab] window expired without a tunnel" | tee -a "$LOG"
    exit 1
  fi
  sleep "$POLL_INTERVAL"
done

run() { # name, env pairs..., then "--"
  local name=$1
  shift
  echo "[lab] run: $name" | tee -a "$LOG"
  env "$@" timeout 1800 python bench.py 2>>"$LOG" |
    tail -1 >"$OUT/bench_${name}_$STAMP.json" ||
    echo "[lab] $name failed rc=$?" | tee -a "$LOG"
}

# 1. microbench (own interpreter; bounded)
timeout 600 python - >"$OUT/micro_$STAMP.json" 2>>"$LOG" <<'EOF'
import json, time
import numpy as np
import jax, jax.numpy as jnp

def best(f, n=8):
    ts = []
    for _ in range(n):
        t0 = time.perf_counter(); f(); ts.append(time.perf_counter() - t0)
    return {"best_ms": round(min(ts) * 1e3, 3),
            "median_ms": round(sorted(ts)[n // 2] * 1e3, 3)}

out = {"platform": jax.devices()[0].platform}
f = jax.jit(lambda x: x + 1)
x = jnp.ones((8,), jnp.float32)
f(x).block_until_ready()
out["tiny_jit_rtt"] = best(lambda: f(x).block_until_ready())
a1 = np.ones((512, 64, 8, 8), np.float16)   # 4 MB: one route_m chunk x4
a2 = np.ones((2048, 64, 8, 8), np.float16)  # 16 MB
out["h2d_4mb"] = best(lambda: jax.device_put(a1).block_until_ready())
out["h2d_16mb"] = best(lambda: jax.device_put(a2).block_until_ready())
g = jax.jit(lambda x: jnp.argmax(x, -1).astype(jnp.int32))
r = g(jnp.ones((512, 64, 8), jnp.float32)); r.block_until_ready()
out["d2h_128kb"] = best(lambda: np.asarray(r))
print(json.dumps(out))
EOF
echo "[lab] micro done" | tee -a "$LOG"

# 2-4. bench legs (each own interpreter; probe diagnostics inside)
run default
run chunk64 REPORTER_TPU_DECODE_CHUNK=64 BENCH_REPEATS=3
run chunk256 REPORTER_TPU_DECODE_CHUNK=256 BENCH_REPEATS=3
run chunk512 REPORTER_TPU_DECODE_CHUNK=512 BENCH_REPEATS=3
run wire_f32 REPORTER_TPU_WIRE=f32 BENCH_REPEATS=3 BENCH_PALLAS=0
run nopipe REPORTER_TPU_PIPELINE=0 BENCH_REPEATS=3 BENCH_PALLAS=0
echo "[lab] battery complete" | tee -a "$LOG"
ls -la "$OUT" | tee -a "$LOG"
