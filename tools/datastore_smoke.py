#!/usr/bin/env python
"""CI smoke gate for the datastore's produce->consume loop.

Runs synthetic traces through the REAL stack end-to-end, in-process:

  StreamWorker (grid city, in-process matcher) flushes anonymised tiles
  -> ``datastore ingest`` replays the flushed CSV dir into a store
  -> ``datastore compact`` merges the deltas
  -> a served ``/histogram`` HTTP query answers for an aggregated segment

and asserts the response contract: counts survive ingest+compaction
unchanged, the mean sits inside the synthetic city's plausible speed
band, and the percentile CDF is monotone. A regression anywhere on the
flush -> ingest -> store -> query path fails CI here, with the service
surface (not just library calls) on the hook.
"""
import json
import os
import socket
import sys
import tempfile
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")  # CI: never probe


def fail(msg: str) -> int:
    sys.stderr.write(f"datastore smoke: {msg}\n")
    return 1


def main() -> int:
    import numpy as np

    from reporter_tpu.datastore import LocalDatastore, ingest_dir
    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.service.server import ReporterService, serve
    from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
    from reporter_tpu.streaming.formatter import Formatter
    from reporter_tpu.streaming.worker import StreamWorker, inproc_submitter
    from reporter_tpu.synth import build_grid_city, generate_trace

    with tempfile.TemporaryDirectory() as tmp:
        city = build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=5,
                               service_road_fraction=0.0,
                               internal_fraction=0.0)
        service = ReporterService(SegmentMatcher(net=city),
                                  threshold_sec=15, max_batch=64,
                                  max_wait_ms=5.0)
        out_dir = os.path.join(tmp, "results")

        rng = np.random.default_rng(9)
        lines = []
        for i in range(16):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"veh-{i}", rng, noise_m=3.0,
                                    min_route_edges=8)
            for p in tr.points:
                lines.append("|".join([
                    "x", tr.uuid, str(p["lat"]), str(p["lon"]),
                    str(p["time"]), str(p["accuracy"])]))

        worker = StreamWorker(
            Formatter.from_config(",sv,\\|,1,2,3,4,5"),
            inproc_submitter(service),
            Anonymiser(TileSink(out_dir), privacy=1, quantisation=3600,
                       source="smoke"),
            flush_interval_s=1e9)
        worker.run(lines)
        if worker.parse_failures:
            return fail(f"{worker.parse_failures} parse failures")

        store_dir = os.path.join(tmp, "store")
        ds = LocalDatastore(store_dir)
        got = ingest_dir(ds, out_dir)
        if not got["files"] or not got["rows"] or got["failures"]:
            return fail(f"ingest: {got}")
        compacted = ds.compact()
        stats = ds.stats()
        if stats["rows"] != got["rows"]:
            return fail(f"compaction changed row count: "
                        f"{stats['rows']} != {got['rows']}")
        if stats["segments"] != stats["partitions"]:
            return fail(f"compaction left deltas behind: {stats}")

        # the busiest segment, found via the store's own partitions
        from reporter_tpu.datastore import schema
        best, best_count = None, 0
        for level, index in ds.partitions():
            for part in ds.live_segments(level, index):
                seg_ids = schema.split_hist_key(
                    np.asarray(part.hist_key))[0]
                for sid in np.unique(seg_ids):
                    c = int(np.asarray(part.hist_count)[seg_ids == sid].sum())
                    if c > best_count:
                        best, best_count = int(sid), c
        if best is None:
            return fail("no aggregated segments")

        # serve it and query over HTTP — the real /histogram surface
        service_q = ReporterService(SegmentMatcher(net=city), datastore=ds)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        httpd = serve(service_q, "127.0.0.1", port)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/histogram?segment_id={best}",
                    timeout=30) as r:
                body = json.loads(r.read())
        finally:
            httpd.shutdown()

        if body["count"] != best_count:
            return fail(f"query count {body['count']} != stored "
                        f"{best_count}")
        if not (5.0 < body["mean_kph"] < 80.0):
            return fail(f"implausible mean speed {body['mean_kph']} kph")
        ps = body["percentiles"]
        if not (ps["p25"] <= ps["p50"] <= ps["p75"] <= ps["p95"]):
            return fail(f"percentiles not monotone: {ps}")
        if sum(body["histogram"]["counts"]) != body["count"]:
            return fail("histogram counts disagree with total")

        print(f"datastore smoke ok: {got['files']} tiles, {got['rows']} "
              f"rows, {compacted['partitions']} partitions compacted, "
              f"segment {best}: count={body['count']} "
              f"mean={body['mean_kph']} kph p50={ps['p50']}")
        return 0


if __name__ == "__main__":
    sys.exit(main())
