#!/usr/bin/env python
"""Schedule-perturbation fuzzer + disarmed-overhead gate (ISSUE 10).

The runtime witness (reporter_tpu/analysis/racecheck.py) only reports
interleavings that actually happen. This harness makes the unlikely ones
happen: ``REPORTER_TPU_RACEFUZZ=seed[:prob][@max_us]`` injects seeded
microsecond yields at every TrackedLock acquire and the dispatcher's
queue put/get sites (per-site RNG seeded ``crc32(site) ^ seed`` — the
faults-layer replay discipline, bit-identical draw sequences by seed),
then the scenarios below run with the witness + guarded-state audit
armed. ANY RC finding fails the run and prints the replay seed.

Scenarios (each runs in its own interpreter so env arming and the
held-before graph start clean):

  replay        traced multi-writer replay: 2 writer workers x one
                shared service + datastore tee (the bigreplay topology
                at smoke scale), REPORTER_TPU_TRACE=1 and shadow
                sampling on, final drain -> witness findings must be
                empty and perturbation must actually have fired
  submit_burst  tools/chaos.py submit_burst under perturbation
                (requeue/dead-letter paths racing the stream thread)
  storm         tools/chaos.py storm under perturbation (circuit
                breaker + fallback lane handoff; skips without the
                native runtime like chaos itself)

Usage:
  REPORTER_TPU_PLATFORM=cpu python tools/racefuzz.py --seeds 3
  REPORTER_TPU_PLATFORM=cpu python tools/racefuzz.py --seed 7   # replay one
  REPORTER_TPU_PLATFORM=cpu python tools/racefuzz.py --overhead

``--overhead`` is the disarmed-cost gate: the serialized
(REPORTER_TPU_PIPELINE=0) 512-trace match, tracked-but-disarmed locks
(the shipped default) vs ``REPORTER_TPU_LOCKCHECK=raw`` bare
``threading.Lock``s, interleaved repeats, min-of-N per leg, pinned
< 2%.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")  # never probe a chip

DEFAULT_SCENARIOS = ("replay", "submit_burst", "storm")
FMT = r",sv,\|,0,1,2,3,4"  # uuid|lat|lon|time|accuracy
OVERHEAD_TRACES = 512
OVERHEAD_LIMIT_PCT = 2.0


def log(msg: str) -> None:
    print(f"racefuzz: {msg}", flush=True)


# ---- child legs (run in a fresh interpreter, armed by env) -----------------

def _check_findings(context: str) -> int:
    """Zero-findings gate every drive leg ends on. Renders each finding
    in the PR 2 ``path:line: RULE-ID`` form."""
    from reporter_tpu.analysis import racecheck
    lines = racecheck.render()
    for line in lines:
        print(line)
    if lines:
        sys.stderr.write(
            f"racefuzz: FAIL: {len(lines)} witness finding(s) in "
            f"{context}\n")
        return 1
    log(f"{context}: 0 findings "
        f"(held-before edges observed: {racecheck.edge_count()})")
    return 0


def drive_replay() -> int:
    """Traced multi-writer replay: the bigreplay topology at smoke
    scale. Two writer workers share one service (one dispatcher, one
    matcher, its device lanes) and one datastore tee; each writer owns
    its anonymiser/sink. The perturbed schedule must still produce a
    clean run AND a clean witness."""
    import tempfile
    import threading

    import numpy as np

    from reporter_tpu.datastore import LocalDatastore
    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.service.server import ReporterService
    from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
    from reporter_tpu.streaming.formatter import Formatter
    from reporter_tpu.streaming.worker import StreamWorker, inproc_submitter
    from reporter_tpu.synth import build_grid_city, generate_trace
    from reporter_tpu.utils import locks

    if not locks.armed():
        sys.stderr.write("racefuzz: FAIL: witness not armed in child "
                         "(REPORTER_TPU_LOCKCHECK lost?)\n")
        return 1

    city = build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=5,
                           service_road_fraction=0.0,
                           internal_fraction=0.0)
    rng = np.random.default_rng(11)
    shards = [[], []]
    for i in range(12):
        tr = None
        while tr is None:
            tr = generate_trace(city, f"veh-{i}", rng, noise_m=3.0,
                                min_route_edges=8)
        shards[i % 2].extend(
            "|".join([tr.uuid, str(p["lat"]), str(p["lon"]),
                      str(p["time"]), str(p["accuracy"])])
            for p in tr.points)

    with tempfile.TemporaryDirectory() as workdir:
        from reporter_tpu.datastore import BackgroundCompactor
        store = LocalDatastore(os.path.join(workdir, "store"))
        # the serving-tier thread topology (ISSUE 14): a background
        # compactor paced fast enough to contend with both writers'
        # tee ingests on the shared store — its lease/commit paths run
        # under the witness + perturbation like everything else
        compactor = BackgroundCompactor(store, max_deltas=1,
                                        interval_s=0.02).start()

        def tee(_tile, segments, ingest_key=None):
            return store.ingest_segments(segments, ingest_key=ingest_key)

        service = ReporterService(SegmentMatcher(net=city),
                                  threshold_sec=15, max_batch=64,
                                  max_wait_ms=5.0)
        workers, threads = [], []
        for w, shard in enumerate(shards):
            anon = Anonymiser(
                TileSink(os.path.join(workdir, "out"),
                         deadletter=os.path.join(workdir, f"spool-w{w}")),
                privacy=1, quantisation=3600, source="fuzz", tee=tee)
            anon.writer_id = f"w{w}"
            worker = StreamWorker(
                Formatter.from_config(FMT), inproc_submitter(service),
                anon, reports="0,1,2", transitions="0,1,2",
                flush_interval_s=1e9, submit_many=service.report_many,
                report_flush_interval_s=0.5, datastore=store)
            workers.append(worker)
            threads.append(threading.Thread(target=worker.run,
                                            args=(iter(shard),),
                                            daemon=True))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        compactor.stop()
        service.dispatcher.close()

        fails = sum(w.parse_failures for w in workers)
        if fails:
            sys.stderr.write(f"racefuzz: FAIL: {fails} parse failures "
                             "in the replay\n")
            return 1
        yields = locks.fuzz_yields()
        if os.environ.get(locks.ENV_FUZZ) and yields == 0:
            sys.stderr.write("racefuzz: FAIL: perturbation armed but "
                             "zero yields fired — the hooks are dead\n")
            return 1
        log(f"replay: {sum(len(s) for s in shards)} probes, "
            f"2 writers, {yields} perturbation yields")
        return _check_findings("replay")


def drive_chaos(scenario: str) -> int:
    """One tools/chaos.py scenario under the armed witness + fuzz."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos
    rc = getattr(chaos, f"scenario_{scenario}")()
    if rc != 0:
        sys.stderr.write(
            f"racefuzz: FAIL: chaos {scenario} rc={rc} under "
            "perturbation\n")
        return rc
    from reporter_tpu.utils import locks
    log(f"{scenario}: chaos leg clean, "
        f"{locks.fuzz_yields()} perturbation yields")
    return _check_findings(scenario)


def drive_overhead() -> int:
    """One timed leg of the A/B: serialized 512-trace match_many.
    Prints a JSON line the parent parses; the lock flavour is reported
    so the parent can prove each leg ran what it thinks it ran."""
    import numpy as np

    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.synth import build_grid_city, generate_trace
    from reporter_tpu.utils import metrics

    city = build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=5,
                           service_road_fraction=0.0,
                           internal_fraction=0.0)
    rng = np.random.default_rng(23)
    reqs = []
    for i in range(64):
        tr = None
        while tr is None:
            tr = generate_trace(city, f"veh-{i}", rng, noise_m=3.0,
                                min_route_edges=8)
        reqs.append(tr.request_json())
    reqs = (reqs * ((OVERHEAD_TRACES // len(reqs)) + 1))[:OVERHEAD_TRACES]

    matcher = SegmentMatcher(net=city)
    matcher.match_many(reqs[:32])  # warm: compile + caches off the clock
    t0 = time.perf_counter()
    out = matcher.match_many(reqs)
    ms = (time.perf_counter() - t0) * 1e3
    print(json.dumps({
        "ms": round(ms, 2), "traces": len(out),
        "lock_type": type(metrics.default._lock).__name__}), flush=True)
    return 0


# ---- parent orchestration ---------------------------------------------------

def _child_env(**extra: str) -> dict:
    env = dict(os.environ)
    env["REPORTER_TPU_PLATFORM"] = "cpu"
    # a pre-armed operator shell must not leak into the legs: each leg
    # states its own arming exactly
    for var in ("REPORTER_TPU_LOCKCHECK", "REPORTER_TPU_RACEFUZZ",
                "REPORTER_TPU_TRACE", "REPORTER_TPU_SHADOW_SAMPLE",
                "REPORTER_TPU_PIPELINE"):
        env.pop(var, None)
    env.update(extra)
    return env


def _run_child(scenario: str, env: dict) -> "subprocess.CompletedProcess":
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--drive", scenario],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900)


def run_fuzz(seeds, scenarios, prob: float, max_us: float) -> int:
    failures = []
    for seed in seeds:
        for scenario in scenarios:
            spec = f"{seed}:{prob}@{max_us:g}"
            log(f"seed {seed} / {scenario} "
                f"(REPORTER_TPU_RACEFUZZ={spec}) ...")
            t0 = time.monotonic()
            proc = _run_child(scenario, _child_env(
                REPORTER_TPU_LOCKCHECK="1",
                REPORTER_TPU_RACEFUZZ=spec,
                REPORTER_TPU_TRACE="1",
                REPORTER_TPU_SHADOW_SAMPLE="0.5"))
            dt = time.monotonic() - t0
            if proc.returncode != 0:
                failures.append((seed, scenario))
                sys.stdout.write(proc.stdout)
                sys.stderr.write(proc.stderr)
                log(f"seed {seed} / {scenario}: FAIL ({dt:.1f}s) — "
                    f"replay with: REPORTER_TPU_PLATFORM=cpu python "
                    f"tools/racefuzz.py --seed {seed} "
                    f"--scenarios {scenario}")
            else:
                tail = [ln for ln in proc.stdout.splitlines()
                        if ln.startswith("racefuzz:")]
                for ln in tail[-2:]:
                    print("  " + ln)
                log(f"seed {seed} / {scenario}: ok ({dt:.1f}s)")
    if failures:
        sys.stderr.write(
            "racefuzz: FAIL: findings under "
            + ", ".join(f"seed {s} ({sc})" for s, sc in failures) + "\n")
        return 1
    log(f"clean: {len(seeds)} seed(s) x {len(scenarios)} scenario(s), "
        "0 findings")
    return 0


def run_overhead(repeats: int) -> int:
    """Interleaved A/B, min-of-N per leg: raw threading.Lock (A) vs
    tracked-but-disarmed TrackedLock (B, the shipped default)."""
    legs = {"raw": [], "disarmed": []}
    types = {}
    for r in range(repeats):
        for leg, env in (
                ("raw", _child_env(REPORTER_TPU_LOCKCHECK="raw",
                                   REPORTER_TPU_PIPELINE="0")),
                ("disarmed", _child_env(REPORTER_TPU_PIPELINE="0"))):
            proc = _run_child("overhead", env)
            if proc.returncode != 0:
                sys.stdout.write(proc.stdout)
                sys.stderr.write(proc.stderr)
                sys.stderr.write(f"racefuzz: FAIL: overhead {leg} leg "
                                 f"rc={proc.returncode}\n")
                return 1
            rec = json.loads(proc.stdout.strip().splitlines()[-1])
            if rec["traces"] != OVERHEAD_TRACES:
                sys.stderr.write("racefuzz: FAIL: overhead leg matched "
                                 f"{rec['traces']} traces\n")
                return 1
            legs[leg].append(rec["ms"])
            types[leg] = rec["lock_type"]
            log(f"overhead round {r + 1}/{repeats} {leg}: "
                f"{rec['ms']:.1f} ms ({rec['lock_type']})")
    if types.get("raw") != "lock" or types.get("disarmed") != "TrackedLock":
        sys.stderr.write(
            f"racefuzz: FAIL: A/B legs ran the wrong lock flavours "
            f"({types}) — the comparison is meaningless\n")
        return 1
    raw = min(legs["raw"])
    disarmed = min(legs["disarmed"])
    pct = (disarmed - raw) / raw * 100.0
    log(f"serialized {OVERHEAD_TRACES}-trace A/B: raw {raw:.1f} ms vs "
        f"disarmed TrackedLock {disarmed:.1f} ms -> {pct:+.2f}% "
        f"(limit +{OVERHEAD_LIMIT_PCT:.0f}%)")
    if pct > OVERHEAD_LIMIT_PCT:
        sys.stderr.write("racefuzz: FAIL: disarmed lock overhead "
                         f"{pct:+.2f}% exceeds {OVERHEAD_LIMIT_PCT}%\n")
        return 1
    log("overhead gate: ok")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--seeds", type=int, default=None,
                        help="run seeds base..base+N-1 (default 3 when "
                             "neither --seeds nor --seed given)")
    parser.add_argument("--seed", type=int, action="append", default=None,
                        help="run exactly this seed (repeatable) — the "
                             "replay knob a failure report prints")
    parser.add_argument("--base-seed", type=int, default=1,
                        help="first seed for --seeds (default 1)")
    parser.add_argument("--prob", type=float, default=0.25,
                        help="per-site yield probability (default 0.25)")
    parser.add_argument("--max-us", type=float, default=200.0,
                        help="max injected yield in microseconds")
    parser.add_argument("--scenarios", nargs="+",
                        default=list(DEFAULT_SCENARIOS),
                        choices=list(DEFAULT_SCENARIOS),
                        help="scenario subset (default: all)")
    parser.add_argument("--overhead", action="store_true",
                        help="run the disarmed-overhead A/B gate "
                             "instead of fuzzing")
    parser.add_argument("--repeats", type=int, default=3,
                        help="interleaved rounds per overhead leg")
    parser.add_argument("--drive", default=None,
                        help=argparse.SUPPRESS)  # internal child mode
    args = parser.parse_args(argv)

    if args.drive:
        if args.drive == "replay":
            return drive_replay()
        if args.drive == "overhead":
            return drive_overhead()
        return drive_chaos(args.drive)
    if args.overhead:
        return run_overhead(args.repeats)
    if args.seed:
        seeds = args.seed
    else:
        seeds = list(range(args.base_seed,
                           args.base_seed + (args.seeds or 3)))
    return run_fuzz(seeds, args.scenarios, args.prob, args.max_us)


if __name__ == "__main__":
    sys.exit(main())
