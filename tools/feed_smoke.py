#!/usr/bin/env python
"""CI smoke gate for the freshness tier's ingest -> subscribe loop.

Drives the REAL stack end-to-end, in-process:

  a served stack with the freshness tier live (store + /feed + /histogram)
  -> a subscriber opens a ``/feed`` long-poll over HTTP
  -> a tee-shaped ingest lands in the serving process
  -> the subscriber must receive the delta event UNDER A DEADLINE
     (condition-notified delivery, not sleep-polling)
  -> ``/histogram?window=5m`` serves the same rows immediately
  -> ``window=inf`` stays byte-identical to the windowless answer
  -> a streamed point served by the INCREMENTAL matcher (carried
     decode state, ISSUE 19) reports, tees, and reaches an open
     ``/feed`` long-poll under the same deadline — the
     probe -> live-dashboard loop with no whole-window re-decode

A regression anywhere on the ingest -> overlay -> feed -> HTTP path
fails CI here, with the service surface (not just library calls) on
the hook. ``--deadline`` bounds first-delta latency (default 2 s — one
tee cycle is milliseconds; the bound only exists to catch a fallback
to timer polling).
"""
import argparse
import json
import os
import socket
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")  # CI: never probe


def fail(msg: str) -> int:
    sys.stderr.write(f"feed smoke: {msg}\n")
    return 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--deadline", type=float, default=2.0,
                        help="max seconds from ingest to delivered "
                             "delta event")
    args = parser.parse_args(argv)

    from reporter_tpu.core.osmlr import make_segment_id
    from reporter_tpu.core.types import Segment
    from reporter_tpu.datastore import LocalDatastore
    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.service.server import ReporterService, serve
    from reporter_tpu.synth import build_grid_city

    sid = make_segment_id(2, 756425, 10)
    nid = make_segment_id(2, 756425, 11)
    t0 = 1483344000  # Monday 08:00 UTC

    def flush(n, start):
        return [Segment(sid, nid, start + i * 30,
                        start + i * 30 + 10.0, 100, 0) for i in range(n)]

    with tempfile.TemporaryDirectory() as tmp:
        ds = LocalDatastore(os.path.join(tmp, "store"))
        tier = ds.enable_freshness()
        if tier is None:
            return fail("freshness tier did not enable")
        ds.ingest_segments(flush(5, t0), ingest_key="smoke-seed")

        city = build_grid_city(rows=4, cols=4, spacing_m=200.0, seed=5,
                               service_road_fraction=0.0,
                               internal_fraction=0.0)
        service = ReporterService(SegmentMatcher(net=city), datastore=ds)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        url = f"http://127.0.0.1:{port}"
        httpd = serve(service, "127.0.0.1", port)
        try:
            # 1) the seed flush is already on the feed (cursor replay)
            with urllib.request.urlopen(
                    f"{url}/feed?cursor=0&timeout=1", timeout=30) as r:
                seeded = json.loads(r.read())
            if not seeded["events"] or seeded["events"][0]["kind"] != "delta":
                return fail(f"seed flush missing from feed: {seeded}")
            cursor = seeded["cursor"]

            # 2) subscribe first, ingest second: the open long-poll
            # must be woken by the landing flush under the deadline
            got = {}

            def subscribe():
                req = (f"{url}/feed?cursor={cursor}"
                       "&bbox=-180,-90,180,90&level=2&timeout=30")
                with urllib.request.urlopen(req, timeout=60) as r:
                    got["body"] = json.loads(r.read())
                got["t"] = time.monotonic()

            th = threading.Thread(target=subscribe)
            th.start()
            waited = time.monotonic() + 10
            while tier.feed.snapshot()["waiters"] == 0:
                if time.monotonic() > waited:
                    return fail("subscriber never registered as waiter")
                time.sleep(0.005)
            t_ingest = time.monotonic()
            ds.ingest_segments(flush(3, t0 + 3600),
                               ingest_key="smoke-live")
            th.join(timeout=args.deadline + 30)
            if th.is_alive():
                return fail("subscriber still blocked after ingest")
            latency = got["t"] - t_ingest
            if latency > args.deadline:
                return fail(f"first delta took {latency:.3f}s "
                            f"(deadline {args.deadline}s) — is delivery "
                            "sleep-polling?")
            events = got["body"]["events"]
            if not events or events[0]["kind"] != "delta" \
                    or sid not in events[0]["segments"]:
                return fail(f"wrong event delivered: {got['body']}")

            # 3) the freshness window serves the new rows NOW
            with urllib.request.urlopen(
                    f"{url}/histogram?segment_id={sid}&window=5m",
                    timeout=30) as r:
                windowed = json.loads(r.read())
            if windowed["count"] != 8:
                return fail(f"window=5m count {windowed['count']} != 8")

            # 4) ∞-parity: merged reads byte-identical to windowless
            plain = urllib.request.urlopen(
                f"{url}/histogram?segment_id={sid}", timeout=30).read()
            merged = urllib.request.urlopen(
                f"{url}/histogram?segment_id={sid}&window=inf",
                timeout=30).read()
            if plain != merged:
                return fail("window=inf diverged from windowless bytes")

            # 5) ISSUE 19 end-to-end: a streamed point served by the
            # CARRIED-STATE matcher lands on /feed under the same
            # deadline — probe -> incremental report -> worker-tee
            # ingest -> overlay delta, no whole-window re-decode in the
            # loop. The counter check keeps the leg honest: if the
            # incremental path declined and the batch path quietly
            # served, this smoke must fail, not pass vacuously.
            import numpy as np

            from reporter_tpu.streaming.batcher import \
                segments_from_response
            from reporter_tpu.synth import generate_trace
            from reporter_tpu.utils import metrics

            rng = np.random.default_rng(3)
            tr = None
            for _ in range(500):
                tr = generate_trace(city, "inc-smoke", rng, noise_m=4.0)
                if tr is not None:
                    break
            if tr is None:
                return fail("could not generate a smoke trace")
            pts = list(tr.points)
            opts = {"report_levels": [0, 1, 2],
                    "transition_levels": [0, 1, 2]}
            m0 = metrics.counter("match.incremental.matches")
            # first window builds the carried state; the second appends
            # one point and advances it (the steady streaming shape)
            service.report_incremental(
                [{"uuid": "inc-smoke", "trace": pts[:-1],
                  "match_options": opts}])
            resp = service.report_incremental(
                [{"uuid": "inc-smoke", "trace": pts,
                  "match_options": opts}])[0]
            if metrics.counter("match.incremental.matches") < m0 + 2:
                return fail("the incremental path served neither "
                            "window — the streamed-point leg is "
                            "vacuous (batch fallback hid it)")
            rows = [seg for _k, seg in segments_from_response(resp)]
            if not rows:
                return fail("incremental report produced no datastore "
                            "rows")

            cursor2 = got["body"]["cursor"]
            got2 = {}

            def subscribe2():
                req = f"{url}/feed?cursor={cursor2}&timeout=30"
                with urllib.request.urlopen(req, timeout=60) as r:
                    got2["body"] = json.loads(r.read())
                got2["t"] = time.monotonic()

            th2 = threading.Thread(target=subscribe2)
            th2.start()
            waited = time.monotonic() + 10
            while tier.feed.snapshot()["waiters"] == 0:
                if time.monotonic() > waited:
                    return fail("incremental-leg subscriber never "
                                "registered as waiter")
                time.sleep(0.005)
            t_ingest2 = time.monotonic()
            # the worker tee: reported rows ingest into the store
            ds.ingest_segments(rows, ingest_key="smoke-incremental")
            th2.join(timeout=args.deadline + 30)
            if th2.is_alive():
                return fail("subscriber still blocked after the "
                            "incremental report's ingest")
            latency2 = got2["t"] - t_ingest2
            if latency2 > args.deadline:
                return fail(f"incremental report's delta took "
                            f"{latency2:.3f}s (deadline "
                            f"{args.deadline}s)")
            ev2 = got2["body"]["events"]
            if not ev2 or ev2[0]["kind"] != "delta" \
                    or rows[0].id not in ev2[0]["segments"]:
                return fail(f"wrong incremental event: {got2['body']}")
        finally:
            httpd.shutdown()

        print(f"feed smoke ok: seed delivered at cursor {cursor}, "
              f"live delta in {latency * 1000:.1f} ms "
              f"(deadline {args.deadline}s), window=5m count=8, "
              "inf==windowless bytes, incremental streamed point on "
              f"/feed in {latency2 * 1000:.1f} ms "
              f"({len(rows)} row(s))")
        return 0


if __name__ == "__main__":
    sys.exit(main())
