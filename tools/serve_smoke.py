#!/usr/bin/env python
"""CI smoke gate for the multi-city serving tier (ISSUE 14).

Two synthetic cities are flushed through REAL streaming workers into
per-city datastores (worker tee + background compactor + writer lease
all live), then served from ONE fleet — a single HTTP service whose
``city=`` requests route through the byte-budgeted residency LRU
(service/cities.py). Asserted, not just exercised:

- **batched queries**: a ``bbox`` /histogram answer and a repeated-
  ``segment`` batched answer are BOTH cross-checked segment-for-segment
  against single ``segment_id`` queries — answer-identical is the
  contract (datastore/query.py shares one assembler).
- **lease + compactor surface**: /health carries the store's writer-
  lease holder view (held by this process) and the compactor's
  delta-pressure backlog gauge; the background compactor actually
  compacted (no partition left over pressure).
- **city LRU + route-memo pre-warm**: a tiny residency budget forces
  the LRU to evict; the evicted city's route-memo profile (exported
  from its served traffic) pre-warms the reload, and the reloaded
  city's FIRST request batch records shared-memo hits > 0 where the
  cold first load recorded 0 — the cold-start counter pair on
  /profile. Needs the native runtime; set
  REPORTER_TPU_CHAOS_REQUIRE_NATIVE=1 (CI does) to fail rather than
  skip when it is missing.
- **zero-downtime map swap (ISSUE 20)**: 1000 threaded requests
  straddle a live ``registry.swap`` to a new map build — ZERO may
  fail; /health flips its resident ``map_version`` and counts the
  flip in the swap block. A divergent candidate graph is then
  REFUSED by the dual-version shadow gate (agreement below the
  floor), counted and surfaced, with the serving version unchanged.

``--swap-only`` runs just the produce legs + the swap leg (the CI
``swap_smoke`` stage pairs it with ``chaos.py swap_kill``).
"""
import json
import os
import socket
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")  # CI: never probe
# one prep worker slot: the per-slot local memo then soaks up every
# repeat within a process, so SHARED-memo hit counters are a pure
# signal of the pre-warm (see the cold-start assertion below)
os.environ.setdefault("REPORTER_TPU_PREP_THREADS", "1")
# capture every admitted request for the swap shadow gate: the flip
# leg's agreement assertion must not depend on sampling luck
os.environ.setdefault("REPORTER_TPU_SWAP_SAMPLE", "1")

FMT = ",sv,\\|,0,1,2,3,4"


def log(msg: str) -> None:
    print(f"serve smoke: {msg}", flush=True)


def fail(msg: str) -> int:
    sys.stderr.write(f"serve smoke: FAIL: {msg}\n")
    return 1


def _flush_city(tmp: str, name: str, seed: int, n_traces: int):
    """One city's produce leg: worker flushes tiles + tees into the
    city's datastore with the background compactor armed. Returns
    (graph_path, store_dir, request_jsons)."""
    import numpy as np

    from reporter_tpu.datastore import BackgroundCompactor, LocalDatastore
    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.service.server import ReporterService
    from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
    from reporter_tpu.streaming.formatter import Formatter
    from reporter_tpu.streaming.worker import StreamWorker, inproc_submitter
    from reporter_tpu.synth import build_grid_city, generate_trace

    city = build_grid_city(rows=9, cols=9, spacing_m=210.0, seed=seed,
                           service_road_fraction=0.0,
                           internal_fraction=0.0)
    graph = os.path.join(tmp, f"{name}.npz")
    city.save(graph)
    store_dir = os.path.join(tmp, f"store-{name}")
    store = LocalDatastore(store_dir)
    compactor = BackgroundCompactor(store, max_deltas=2,
                                    interval_s=0.05)
    service = ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                              max_batch=64, max_wait_ms=5.0)

    def tee(_tile, segments, ingest_key=None):
        return store.ingest_segments(segments, ingest_key=ingest_key)

    rng = np.random.default_rng(seed * 7 + 1)
    lines, reqs = [], []
    for i in range(12):
        tr = None
        while tr is None:
            tr = generate_trace(city, f"{name}-veh-{i}", rng,
                                noise_m=3.0, min_route_edges=8)
        reqs.append(tr.request_json())
        for p in tr.points:
            lines.append("|".join([tr.uuid, str(p["lat"]), str(p["lon"]),
                                   str(p["time"]), str(p["accuracy"])]))
    worker = StreamWorker(
        Formatter.from_config(FMT), inproc_submitter(service),
        Anonymiser(TileSink(os.path.join(tmp, f"out-{name}")), privacy=1,
                   quantisation=3600, source=name, tee=tee),
        reports="0,1,2", transitions="0,1,2",
        flush_interval_s=1e9, report_flush_interval_s=0.1,
        submit_many=service.report_many, datastore=store,
        compactor=compactor)
    worker.run(lines)
    service.dispatcher.close()
    if worker.parse_failures:
        raise RuntimeError(f"{worker.parse_failures} parse failures")
    # the background compactor owned compaction (the tee never compacts
    # inline any more): after the final pass nothing may sit over
    # pressure
    left = compactor.pending(refresh=True)
    if left["partitions_over"]:
        compactor.run_once()
        left = compactor.pending()
    if left["partitions_over"]:
        raise RuntimeError(f"compactor left pressure behind: {left}")
    return graph, store_dir, reqs


def _get(port: int, path: str) -> dict:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=60) as r:
        return json.loads(r.read())


def _post(port: int, path: str, body: dict) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def main() -> int:
    from reporter_tpu import native
    from reporter_tpu.datastore import (
        BackgroundCompactor,
        LocalDatastore,
        export_profile,
    )
    from reporter_tpu.datastore.profile import profile_path
    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.service.cities import CityRegistry
    from reporter_tpu.service.server import ReporterService, serve
    from reporter_tpu.utils import metrics

    require_native = bool(
        os.environ.get("REPORTER_TPU_CHAOS_REQUIRE_NATIVE"))
    if not native.available() and require_native:
        return fail("native runtime unavailable but required")
    swap_only = "--swap-only" in sys.argv[1:]

    with tempfile.TemporaryDirectory() as tmp:
        graphs, stores, reqs = {}, {}, {}
        for name, seed in (("metro-a", 3), ("metro-b", 17)):
            graphs[name], stores[name], reqs[name] = _flush_city(
                tmp, name, seed, 12)
            log(f"{name}: flushed + tee'd into {stores[name]}")

        # ONE fleet: a tiny residency budget (~one city) so the LRU
        # must swap; the default stack serves metro-a's store directly
        from reporter_tpu.graph.network import RoadNetwork
        registry = CityRegistry(
            {n: {"graph": graphs[n], "datastore": stores[n]}
             for n in graphs},
            budget_bytes=1)  # < one city: strict LRU of exactly 1
        ds_a = LocalDatastore(stores["metro-a"])
        service = ReporterService(
            SegmentMatcher(net=RoadNetwork.load(graphs["metro-a"])),
            datastore=ds_a, cities=registry)
        # a REAL gauge, not a zero stub: one refreshed sweep so the
        # /health assertion below compares against the store's actual
        # (fully compacted) pressure state
        service.compactor = BackgroundCompactor(ds_a, max_deltas=2)
        real_backlog = service.compactor.pending(refresh=True)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        httpd = serve(service, "127.0.0.1", port)
        try:
            if not swap_only:
                # ---- lease + compactor on /health --------------------
                health = _get(port, "/health")
                lease = health["datastore"].get("lease") or {}
                if not lease.get("enabled"):
                    return fail(f"/health carries no live lease view: "
                                f"{health['datastore']}")
                if health.get("compaction") != real_backlog:
                    return fail(f"/health compaction gauge "
                                f"{health.get('compaction')} != the "
                                f"refreshed sweep {real_backlog}")
                if real_backlog["partitions_over"]:
                    return fail(f"worker-leg compactor left pressure: "
                                f"{real_backlog}")

                # ---- batched queries vs single answers ---------------
                bbox_body = _get(
                    port, "/histogram?city=metro-a&bbox=-180,-90,180,90"
                          "&level=2")
                segs = bbox_body["segments"]
                if len(segs) < 5 or bbox_body["truncated"]:
                    return fail(f"bbox query implausible: n="
                                f"{bbox_body['n_segments']} "
                                f"truncated={bbox_body['truncated']}")
                ids = [s["segment_id"] for s in segs]
                for s in segs:
                    single = _get(port, f"/histogram?city=metro-a"
                                        f"&segment_id={s['segment_id']}")
                    if single != s:
                        return fail(f"bbox answer differs from single "
                                    f"for {s['segment_id']}")
                many = _get(port, "/histogram?city=metro-a&"
                            + "&".join(f"segment={i}" for i in ids[:8]))
                for got, want_id in zip(many["results"], ids[:8]):
                    single = _get(port, f"/histogram?city=metro-a"
                                        f"&segment_id={want_id}")
                    if got != single:
                        return fail(f"query_many answer differs from "
                                    f"single for {want_id}")
                log(f"batched parity: {len(segs)} bbox segments + "
                    f"{len(ids[:8])} repeated-param segments all equal "
                    f"their single answers")

            # ---- zero-downtime map swap (ISSUE 20) -------------------
            # v2 = same geometry with uniformly scaled speeds: same
            # segment ids (shadow scores agree — uniform scaling
            # preserves every argmin route), different content hash
            from reporter_tpu.graph.version import map_version
            net_v1 = RoadNetwork.load(graphs["metro-a"])
            mv1 = map_version(net_v1)
            net_v2 = RoadNetwork.load(graphs["metro-a"])
            net_v2.edge_speed_kph = net_v2.edge_speed_kph * 1.1
            g2 = os.path.join(tmp, "metro-a-v2.npz")
            net_v2.save(g2)
            mv2 = map_version(net_v2)
            if mv1 == mv2:
                return fail("speed change minted no new map version")
            # a few warm-up reports make metro-a resident and seed the
            # shadow capture ring before the burst
            for r in reqs["metro-a"][:4]:
                _post(port, "/report", dict(r, city="metro-a"))
            h0 = _get(port, "/health")
            res0 = (h0["cities"]["resident"].get("metro-a") or {})
            if res0.get("map_version") != mv1:
                return fail(f"/health resident map_version "
                            f"{res0.get('map_version')} != {mv1}")

            failures = []

            def hammer(k):
                rs = reqs["metro-a"]
                for i in range(125):
                    r = rs[(k * 131 + i) % len(rs)]
                    try:
                        _post(port, "/report", dict(r, city="metro-a"))
                    except Exception as e:
                        failures.append(f"{type(e).__name__}: {e}")

            threads = [threading.Thread(target=hammer, args=(k,))
                       for k in range(8)]
            for t in threads:
                t.start()
            time.sleep(0.25)  # let the burst straddle the flip
            record = registry.swap(
                "metro-a",
                {"graph": g2, "datastore": stores["metro-a"]})
            for t in threads:
                t.join()
            if failures:
                return fail(f"{len(failures)} of 1000 in-flight "
                            f"requests failed across the flip: "
                            f"{failures[:3]}")
            if record["result"] != "flipped":
                return fail(f"swap did not flip: {record}")
            h1 = _get(port, "/health")
            res1 = (h1["cities"]["resident"].get("metro-a") or {})
            if res1.get("map_version") != mv2:
                return fail(f"/health still shows "
                            f"{res1.get('map_version')} after the "
                            f"flip to {mv2}")
            swap_blk = h1["cities"].get("swap") or {}
            if not swap_blk.get("flips"):
                return fail(f"/health swap block counts no flips: "
                            f"{swap_blk}")
            last = (swap_blk.get("last") or {}).get("metro-a") or {}
            if last.get("result") != "flipped" \
                    or last.get("to") != mv2:
                return fail(f"/health swap.last wrong: {last}")
            log(f"swap flip: 1000 in-flight requests, 0 failures, "
                f"{mv1} -> {mv2} (agreement "
                f"{record.get('agreement')} over "
                f"{record.get('checks')} shadow checks)")

            # refusal: a DIVERGENT graph (different grid) must be
            # refused by the shadow gate — counted, surfaced, and the
            # serving version unchanged
            for r in reqs["metro-a"][:6]:
                _post(port, "/report", dict(r, city="metro-a"))
            from reporter_tpu.synth import build_grid_city
            alien = build_grid_city(rows=6, cols=6, spacing_m=150.0,
                                    seed=2, service_road_fraction=0.0,
                                    internal_fraction=0.0)
            g3 = os.path.join(tmp, "metro-a-alien.npz")
            alien.save(g3)
            record = registry.swap(
                "metro-a",
                {"graph": g3, "datastore": stores["metro-a"]})
            if record["result"] != "refused_shadow":
                return fail(f"divergent graph was not refused: "
                            f"{record}")
            if not record["checks"] \
                    or record["agreement"] >= record["floor"]:
                return fail(f"refusal record implausible: {record}")
            h2 = _get(port, "/health")
            res2 = (h2["cities"]["resident"].get("metro-a") or {})
            swap_blk = h2["cities"].get("swap") or {}
            if res2.get("map_version") != mv2:
                return fail(f"refused swap changed the serving "
                            f"version: {res2.get('map_version')}")
            if not swap_blk.get("refusals"):
                return fail(f"/health swap block counts no refusals: "
                            f"{swap_blk}")
            if (swap_blk.get("last") or {}).get("metro-a", {}) \
                    .get("result") != "refused_shadow":
                return fail(f"/health swap.last missed the refusal: "
                            f"{swap_blk}")
            # still serving v2 after the refusal
            _post(port, "/report",
                  dict(reqs["metro-a"][0], city="metro-a"))
            log(f"swap refusal: divergent graph refused at agreement "
                f"{record['agreement']} (floor {record['floor']}), "
                f"serving version unchanged")
            if swap_only:
                print("serve smoke ok (swap legs only): flip with 0 "
                      "failed in-flight requests; divergent graph "
                      "refused, counted, surfaced")
                return 0

            # ---- city LRU + memo pre-warm ----------------------------
            if not native.available():
                log("native runtime unavailable: memo pre-warm leg "
                    "SKIPPED")
                print("serve smoke ok (memo leg skipped)")
                return 0
            # cold load of metro-b (evicts metro-a: budget < one city)
            ev0 = metrics.default.counter("datastore.city.evictions")
            for r in reqs["metro-b"][:6]:
                _post(port, "/report", dict(r, city="metro-b"))
            entry_b = registry.get("metro-b")
            cold = entry_b.service.matcher.runtime.route_memo_stats()
            if metrics.default.counter("datastore.city.evictions") <= ev0:
                return fail("loading metro-b evicted nothing under a "
                            "1-byte budget")
            if cold["hits"] != 0:
                return fail(f"cold-loaded city counted shared-memo hits "
                            f"without a pre-warm: {cold}")
            if entry_b.warmed_pairs:
                return fail("cold load reported warmed pairs with no "
                            "profile committed")
            # export metro-b's profile from its served traffic, evict,
            # reload: the pre-warm must turn the same first batch into
            # shared-memo hits
            art = export_profile(entry_b.service.matcher,
                                 profile_path(stores["metro-b"]),
                                 city="metro-b")
            if not art["n_pairs"]:
                return fail("profile export found no resident pairs")
            registry.evict("metro-b")
            for r in reqs["metro-b"][:6]:
                _post(port, "/report", dict(r, city="metro-b"))
            prof = _get(port, "/profile")
            city_view = prof.get("cities", {}).get("resident", {}) \
                .get("metro-b")
            if not city_view:
                return fail(f"/profile carries no metro-b residency "
                            f"view: {list(prof.get('cities', {}))}")
            warm = city_view["route_memo"]
            if not city_view["warmed_pairs"]:
                return fail("reload did not pre-warm from the profile")
            if warm["hits"] <= 0:
                return fail(f"pre-warmed first batch recorded no "
                            f"shared-memo hits: {warm} (cold: {cold})")
            log(f"pre-warm: {city_view['warmed_pairs']} pairs warmed, "
                f"first-batch hits {warm['hits']} (cold load: "
                f"{cold['hits']})")
        finally:
            httpd.shutdown()
            service.dispatcher.close()

        print(f"serve smoke ok: 2 cities, one fleet; bbox+batched "
              f"answers identical to singles; LRU swapped under "
              f"budget; pre-warm hits {warm['hits']} > cold "
              f"{cold['hits']}")
        return 0


if __name__ == "__main__":
    sys.exit(main())
