#!/usr/bin/env python
"""Change-feed fan-out under load: N concurrent bbox subscribers
against a pre-fork fleet while a writer process commits.

The freshness tier's acceptance leg (ISSUE 18): >= 1000 concurrent
``/feed`` long-polls held open across a ``SO_REUSEPORT`` pre-fork
fleet, one measured store commit, and every subscriber accounted for —
delivered the event, shed with the explicit 429 + Retry-After signal,
or errored loudly. ``silent_lost`` (a subscriber that saw neither the
event nor a shed signal by its deadline) must be ZERO: cursor replay
over the event ring means a shed-then-retry subscriber still receives
the commit it missed.

Method (one fresh interpreter, prefork_bench.py's template): a seed
flush lands in a temp store, the fleet forks with the freshness tier
enabled, a priming loop makes one paced watcher scan happen on EVERY
worker (so each process's store-watcher baseline predates the measured
commit), N subscriber threads open world-bbox long-polls from
``cursor=0``, and the writer commits once at T0. Per-subscriber
delivery latency is ``recv - T0``; the artifact reports p50/p99 and
``fanout_ratio = delivered / subscribers``.

Prints ONE JSON line:
    {"kind": "feed_fanout", "subscribers": N, "procs": P,
     "waiter_cap": W, "delivered": D, "shed": S, "shed_events": SE,
     "errors": E, "silent_lost": 0, "delivery_p50_ms": ...,
     "delivery_p99_ms": ..., "fanout_ratio": D/N}

Usage (also reachable as ``python bench.py --feed-fanout N``):
    python tools/feed_fanout_bench.py [--feed-fanout 1000] [--procs 2]
        [--waiters 400] [--pool 700] [--out FILE] [--min-fanout 0]

``--waiters`` caps each worker's feed waiter table BELOW its likely
subscriber share on purpose: the run must exercise the shed path
(shed_events > 0 at full scale) and still close the accounting —
that IS the zero-silent-loss claim. ``--min-fanout R`` gates the run
(exit 1 when fanout_ratio < R or silent_lost/errors > 0); the default
only gates on loss, not ratio, so CI-scale runs stay honest.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_FANOUT_SCRIPT = r"""
import json, os, signal, socket, sys, tempfile, threading, time
import urllib.error, urllib.request

from reporter_tpu.core.osmlr import make_segment_id
from reporter_tpu.core.types import Segment
from reporter_tpu.datastore import LocalDatastore
from reporter_tpu.matcher import SegmentMatcher
from reporter_tpu.service.prefork import serve_prefork
from reporter_tpu.service.server import ReporterService
from reporter_tpu.synth import build_grid_city

SUBSCRIBERS = {subscribers}
PROCS = {procs}
RAMP = {ramp}
SUB_DEADLINE = {sub_deadline}

root = tempfile.mkdtemp(prefix="feed_fanout_")
store_dir = os.path.join(root, "store")
sid = make_segment_id(2, 756425, 10)
nid = make_segment_id(2, 756425, 11)
T0H = 1483344000  # Monday 08:00 UTC


def flush(n, start):
    return [Segment(sid, nid, start + i * 30, start + i * 30 + 10.0,
                    100, 0) for i in range(n)]


# the seed flush exists BEFORE the fleet forks: it is part of every
# worker's store-watcher baseline, so the only feed event the run can
# produce is the measured commit below
writer = LocalDatastore(store_dir)
writer.ingest_segments(flush(5, T0H), ingest_key="fanout-seed")

city = build_grid_city(rows=4, cols=4, spacing_m=200.0, seed=5,
                       service_road_fraction=0.0, internal_fraction=0.0)

with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
base = f"http://127.0.0.1:{{port}}"


def make_service():
    return ReporterService(SegmentMatcher(net=city),
                           datastore=LocalDatastore(store_dir))


result = {{}}


def drive():
    time.sleep(2.0)  # quiet-parent fork window
    try:
        _drive()
    except Exception as e:
        result["err"] = f"{{type(e).__name__}}: {{e}}"


def _drive():
    deadline = time.time() + 240
    while True:
        try:
            urllib.request.urlopen(base + "/stats", timeout=5).read()
            break
        except Exception:
            if time.time() > deadline:
                result["err"] = "service never came up"
                return
            time.sleep(0.3)

    # prime EVERY worker's store watcher: a poll lasting at least one
    # pace slice runs watch_store on whichever proc answered, and its
    # first scan is the silent baseline — a worker that baselined
    # AFTER the measured commit would fold it into the baseline and
    # never publish it (= silent loss by harness bug, not by product)
    primed = set()
    for _ in range(400):
        req = urllib.request.Request(
            base + "/feed?cursor=-1&timeout=0.5")
        with urllib.request.urlopen(req, timeout=30) as r:
            r.read()
            tag = r.headers.get("X-Reporter-Proc", "p?")
        primed.add(tag.split(":")[0])
        if len(primed) >= PROCS:
            break
    if len(primed) < PROCS:
        result["err"] = f"primed only {{sorted(primed)}} of {{PROCS}}"
        return

    lock = threading.Lock()
    lat = []
    states = {{"delivered": 0, "shed": 0, "error": 0, "silent": 0}}
    shed_events = [0]
    errs = {{}}
    t0_box = [None]

    def subscriber(i):
        cursor, sheds = 0, 0
        stop = time.monotonic() + SUB_DEADLINE
        outcome = "silent"
        while time.monotonic() < stop:
            req = (base + f"/feed?cursor={{cursor}}"
                   "&bbox=-180,-90,180,90&level=2&timeout=10")
            try:
                with urllib.request.urlopen(req, timeout=40) as r:
                    body = json.loads(r.read())
            except urllib.error.HTTPError as e:
                if e.code == 429 and e.headers.get("Retry-After"):
                    # the explicit shed signal: back off (jittered so
                    # the retry wave does not re-stampede) and retry —
                    # cursor replay makes the missed event recoverable
                    sheds += 1
                    time.sleep(0.2 + (i % 20) * 0.05)
                    continue
                outcome = "error"
                with lock:
                    errs[f"http {{e.code}}"] = \
                        errs.get(f"http {{e.code}}", 0) + 1
                break
            except Exception as e:
                outcome = "error"
                with lock:
                    key = type(e).__name__
                    errs[key] = errs.get(key, 0) + 1
                break
            cursor = body["cursor"]
            if body["events"]:
                t = time.monotonic()
                outcome = "delivered"
                with lock:
                    if t0_box[0] is not None:
                        lat.append(t - t0_box[0])
                break
        if outcome == "silent" and sheds:
            outcome = "shed"  # never delivered, but never silent
        with lock:
            states[outcome] += 1
            shed_events[0] += sheds

    threads = [threading.Thread(target=subscriber, args=(i,),
                                daemon=True)
               for i in range(SUBSCRIBERS)]
    for t in threads:
        t.start()
    time.sleep(RAMP)  # let the long-polls establish

    t0_box[0] = time.monotonic()
    writer.ingest_segments(flush(3, T0H + 3600),
                           ingest_key="fanout-live")
    for t in threads:
        t.join(timeout=SUB_DEADLINE + 60)
    if any(t.is_alive() for t in threads):
        result["err"] = "subscriber threads leaked past the deadline"
        return

    lat_ms = sorted(x * 1000 for x in lat)

    def pct(p):
        if not lat_ms:
            return None
        return round(lat_ms[min(len(lat_ms) - 1,
                                int(p / 100 * len(lat_ms)))], 1)

    result.update(
        subscribers=SUBSCRIBERS, procs=PROCS,
        delivered=states["delivered"], shed=states["shed"],
        shed_events=shed_events[0], errors=states["error"],
        error_kinds=errs, silent_lost=states["silent"],
        delivery_p50_ms=pct(50), delivery_p99_ms=pct(99),
        fanout_ratio=round(states["delivered"] / SUBSCRIBERS, 4))


t = threading.Thread(target=drive, daemon=True)
try:
    urllib.request.urlopen(base + "/stats", timeout=0.2)
except Exception:
    pass  # warm the opener machinery pre-fork, in the main thread
t.start()


def reaper():
    t.join()
    os.kill(os.getpid(), signal.SIGTERM)


threading.Thread(target=reaper, daemon=True).start()
rc = serve_prefork(make_service, "127.0.0.1", port, PROCS)
print("FANOUT:" + json.dumps(result))
sys.exit(0 if "err" not in result else 1)
"""


def run_fanout(subscribers: int, procs: int, waiters: int, pool: int,
               ramp: float, sub_deadline: float) -> dict:
    script = _FANOUT_SCRIPT.format(subscribers=subscribers, procs=procs,
                                   ramp=ramp, sub_deadline=sub_deadline)
    env = dict(os.environ)
    env.update(
        REPORTER_TPU_PLATFORM="cpu",  # fan-out is an I/O bench
        REPORTER_TPU_PREP_THREADS="1",
        OMP_NUM_THREADS="1",
        OPENBLAS_NUM_THREADS="1",
        # each worker must HOLD its subscriber share as open long-polls
        THREAD_POOL_COUNT=str(pool),
        # per-worker feed waiter cap: sized to force the shed path at
        # full scale so the explicit-retry contract is what's measured
        REPORTER_TPU_FRESHNESS_WAITERS=str(waiters),
        # tight watcher pace: delivery latency measures fan-out, not
        # the scan timer
        REPORTER_TPU_FRESHNESS_POLL_S="0.1")
    proc = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                          capture_output=True, text=True, timeout=900,
                          env=env)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("FANOUT:")]
    if proc.returncode != 0 or not lines:
        raise SystemExit(f"fanout leg failed rc={proc.returncode}: "
                         f"{(proc.stdout + proc.stderr)[-2000:]}")
    out = json.loads(lines[-1][len("FANOUT:"):])
    if "err" in out:
        raise SystemExit(f"fanout leg: {out['err']}")
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="feed_fanout_bench",
                                     description=__doc__.splitlines()[0])
    parser.add_argument("--feed-fanout", dest="subscribers", type=int,
                        default=1000, metavar="N",
                        help="concurrent bbox subscribers (default "
                             "1000 — the acceptance floor)")
    parser.add_argument("--procs", type=int, default=2)
    parser.add_argument("--waiters", type=int, default=400,
                        help="per-worker feed waiter cap (REPORTER_TPU_"
                             "FRESHNESS_WAITERS); below the per-worker "
                             "subscriber share so the shed path runs")
    parser.add_argument("--pool", type=int, default=700,
                        help="per-worker server thread pool "
                             "(THREAD_POOL_COUNT)")
    parser.add_argument("--ramp", type=float, default=6.0,
                        help="seconds between subscriber start and the "
                             "measured commit")
    parser.add_argument("--deadline", type=float, default=60.0,
                        help="per-subscriber overall deadline")
    parser.add_argument("--min-fanout", type=float, default=0.0,
                        help="fail below this fanout_ratio (loss and "
                             "errors always gate)")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    res = run_fanout(args.subscribers, args.procs, args.waiters,
                     args.pool, args.ramp, args.deadline)
    art = {"kind": "feed_fanout", "waiter_cap": args.waiters,
           "pool": args.pool, **res}
    body = json.dumps(art, separators=(",", ":"))
    print(body)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(body)

    failures = []
    if art["silent_lost"]:
        failures.append(f"silent_lost {art['silent_lost']} != 0")
    if art["errors"]:
        failures.append(f"errors {art['errors']} != 0 "
                        f"({art['error_kinds']})")
    accounted = art["delivered"] + art["shed"] + art["errors"] \
        + art["silent_lost"]
    if accounted != art["subscribers"]:
        failures.append(f"accounting open: {accounted} != "
                        f"{art['subscribers']} subscribers")
    if args.min_fanout and art["fanout_ratio"] < args.min_fanout:
        failures.append(f"fanout_ratio {art['fanout_ratio']} < floor "
                        f"{args.min_fanout}")
    for f in failures:
        sys.stderr.write(f"feed_fanout_bench: FAIL: {f}\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
