#!/usr/bin/env python
"""CI smoke gate for the bench artifact's stage accounting.

Runs a deliberately tiny CPU-pinned bench (seconds, not minutes — no
accelerator probes, one repeat) and asserts the JSON contract future
tooling depends on: the artifact parses, carries the ``stages``
breakdown with the ``prep`` stage and its ``prep_share`` of batch wall
time, and records whether the chunked overlap path ran (``pipelined``).
A regression in stage accounting — a renamed timer, a dropped share
field, an artifact that stops being one JSON line — fails CI here
instead of silently degrading the committed BENCH artifacts.

``--out PATH`` additionally writes the artifact JSON to a file, which
is what the ``perf_gate`` CI stage consumes (tools/perf_gate.py gates
its vs_baseline ratio and stage shares against the LEDGER.jsonl
medians — ratios, never absolutes, so box drift can't flap it).
"""
import argparse
import json
import os
import subprocess
import sys

REQUIRED_TOP = ("metric", "value", "unit", "vs_baseline", "stages",
                "report_writers", "baseline", "probe", "query", "routes")
REQUIRED_STAGES = ("prep", "decode_dispatch", "decode_wait", "assemble",
                   "report", "total", "prep_share", "report_share",
                   "pipelined")
# native prep phase split (candidates / select / routes) — present
# whenever the C++ runtime ran the prep, which CI guarantees via the
# build stage; a dropped phase counter fails here, not in a review
REQUIRED_NATIVE_STAGES = ("prep_candidates", "prep_select", "prep_routes")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="bench_smoke")
    parser.add_argument("--out", default=None,
                        help="also write the bench artifact JSON here "
                        "(consumed by the perf_gate CI stage)")
    args = parser.parse_args(argv)
    env = dict(
        os.environ,
        REPORTER_TPU_PLATFORM="cpu",  # never contend for the chip in CI
        BENCH_TRACES="48",
        BENCH_BASELINE_TRACES="8",
        BENCH_REPEATS="1",
        BENCH_BASELINE_REPEATS="1",
        BENCH_PALLAS="0",
    )
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, os.path.join(here, "bench.py")],
                          capture_output=True, text=True, timeout=900,
                          env=env, cwd=here)
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr[-2000:] + "\n")
        sys.stderr.write(f"bench smoke: bench.py rc={proc.returncode}\n")
        return 1
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    if not lines:
        sys.stderr.write("bench smoke: no output\n")
        return 1
    try:
        art = json.loads(lines[-1])
    except json.JSONDecodeError as e:
        sys.stderr.write(f"bench smoke: artifact is not JSON: {e}\n")
        return 1
    missing = [k for k in REQUIRED_TOP if k not in art]
    stages = art.get("stages", {})
    missing += [f"stages.{k}" for k in REQUIRED_STAGES if k not in stages]
    try:
        from reporter_tpu import native
        native_ok = native.available()
    except Exception:
        native_ok = False
    if native_ok:
        missing += [f"stages.{k}" for k in REQUIRED_NATIVE_STAGES
                    if k not in stages]
    if missing:
        sys.stderr.write(f"bench smoke: missing keys: {missing}\n")
        return 1
    if not isinstance(stages["pipelined"], bool):
        sys.stderr.write("bench smoke: stages.pipelined must be a bool\n")
        return 1
    share = stages["prep_share"]
    # prep runs on the main thread, so its seconds are bounded by wall
    if not (isinstance(share, float) and 0.0 <= share <= 1.0):
        sys.stderr.write(
            f"bench smoke: stages.prep_share out of range: {share}\n")
        return 1
    r_share = stages["report_share"]
    if not (isinstance(r_share, float) and 0.0 <= r_share <= 1.0):
        sys.stderr.write(
            f"bench smoke: stages.report_share out of range: {r_share}\n")
        return 1
    # the wire-backend split (ISSUE 11): all three legs must time when
    # the C writer is available (CI's build stage guarantees it is).
    # Without the native toolchain there are no MatchRuns to serialise
    # (the numpy fallback returns plain dicts) and the split is None —
    # the smoke must keep passing on native-less boxes, like the
    # native-stage checks above
    writers = art.get("report_writers") or {}
    if native_ok:
        for k in ("python_s", "dict_s", "dict_vs_python", "native_s"):
            if not isinstance(writers.get(k), (int, float)):
                sys.stderr.write(
                    f"bench smoke: report_writers.{k} missing\n")
                return 1
    if not (art["value"] > 0 and art["vs_baseline"] > 0):
        sys.stderr.write("bench smoke: non-positive throughput\n")
        return 1
    # the serving-tier batched-query pair (ISSUE 14): pure numpy, no
    # native/device dependency — the ratio must always be measured
    # (parity is asserted inside the leg; perf_gate floors the ratio)
    query = art.get("query") or {}
    if not isinstance(query.get("batch_ratio"), (int, float)):
        sys.stderr.write(
            f"bench smoke: query.batch_ratio missing: {query}\n")
        return 1
    # the route-kernel triple (ISSUE 16): device relax vs host Dijkstra
    # vs native memo on identical pairs — the leg asserts byte-parity
    # BEFORE timing, so a measured ratio implies parity held; it needs
    # the native prep tensors, so native-less boxes see a skip record
    routes = art.get("routes") or {}
    if native_ok:
        if routes.get("parity") != "byte-identical" or \
                not isinstance(routes.get("device_vs_native"),
                               (int, float)):
            sys.stderr.write(
                f"bench smoke: routes leg broken: {routes}\n")
            return 1
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(art, f)
    print(f"bench smoke ok: {art['value']} traces/sec, "
          f"prep_share={share}, report_share={r_share}, "
          f"native_vs_python={writers.get('native_vs_python')}, "
          f"pipelined={stages['pipelined']}"
          + (f", artifact -> {args.out}" if args.out else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
