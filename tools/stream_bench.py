#!/usr/bin/env python
"""Per-appended-point decode cost and match latency: incremental vs
whole-window.

The incremental matcher's acceptance leg (ISSUE 19). A growing window
is the production streaming regime (the batcher trims nothing while
reports keep consuming zero segments), and the claim under test is
the tentpole's: an appended point costs O(K) DECODE work with carried
state, versus the whole-window re-decode's O(T*K) — so the
incremental per-point decode cost must be FLAT in the window length T
while the batch decode grows with it. Two legs, T=64 and T=256: warm
a single trace's window up to T one appended point at a time, then
measure the next ``--measure`` appended points.

Each leg runs two passes over identical windows, each on a fresh
matcher. Pass 1 times ONLY the incremental path — interleaving the
whole-window oracle between timed calls pollutes the carried-state
tail (the oracle's window-sized allocations land their GC on the next
one-point advance). Pass 2 replays the same windows with the oracle
after every incremental call: parity bytes and the batch-leg timings
come from there. A served window that differs from the oracle by one
byte is a ``parity_mismatch``; the gate (``perf_gate --streaming``)
fails on any non-zero count.

Decode cost is sampled exactly, not wall-clocked around the call: the
matcher's own timers (``match.incremental.decode`` for the carried
path; ``matcher.decode_dispatch`` + ``matcher.decode_wait`` for the
batch path) accumulate total seconds, and the per-call delta of the
total IS that call's decode seconds — the shared serve assembly
(O(window) report emission, paid identically by both paths) stays out
of the gated quantity and inside the reported match latency.

Amortised decode work rides along: ``match.incremental.steps`` per
appended point (<= 1.0; raw points the prep filter drops advance
nothing) and the fixed-lag commit count, read across each measured
stretch.

Prints ONE JSON line:
    {"kind": "streaming", "lag": L, "measure": M,
     "legs": {"64": {"window": 64, "dec_p50_ms": ..., "dec_p99_ms":
     ..., "inc_p50_ms": ..., "inc_p99_ms": ..., "batch_dec_p50_ms":
     ..., "batch_dec_p99_ms": ..., "batch_p50_ms": ..., "batch_p99_ms":
     ..., "steps_per_point": ..., "commits": ..., "served": ...,
     "windows": ...}, "256": {...}}, "parity_mismatches": 0,
     "flatness_ratio": dec_p99[256]/dec_p99[64],
     "batch_growth": batch_dec_p99[256]/batch_dec_p99[64],
     "speedup_p50_at_256": ...}

Usage (also reachable as ``python bench.py --streaming``):
    python tools/stream_bench.py [--streaming] [--windows 64,256]
        [--measure 32] [--out FILE] [--max-ratio 0]

``--max-ratio R`` gates the run inline (exit 1 when flatness_ratio >
R or any parity mismatch); the default 0 skips the ratio gate so
smoke runs on loaded CI boxes stay honest, but mismatches always
fail.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the carried-state path must run, with the PR 8 shadow sampler off so
# no sampled full-window re-decode pollutes the per-point timings (the
# oracle call right next to it does the same check, deterministically)
os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["REPORTER_TPU_SHADOW_SAMPLE"] = "0"
os.environ.pop("REPORTER_TPU_INCREMENTAL", None)

_INC_DECODE = ("match.incremental.decode",)
_BATCH_DECODE = ("matcher.decode_dispatch", "matcher.decode_wait")


def _pctl(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * (len(xs) - 1) + 0.5))]


def _ser(obj):
    """Normalise either submit-path result shape (dict from the Python
    writer, MatchRuns from the native writer) to canonical JSON."""
    if isinstance(obj, dict):
        return json.dumps(obj, sort_keys=True)
    from reporter_tpu.matcher.matcher import render_segments_json
    s = render_segments_json(obj.cols, obj.lo, obj.hi, obj.mode)
    return json.dumps(json.loads(s), sort_keys=True)


def _timer_total_ms(names):
    from reporter_tpu.utils import metrics
    timers = metrics.default.snapshot()["timers"]
    return sum(timers.get(n, {}).get("total_s", 0.0) for n in names) * 1e3


def _long_trace(city, n_points, seed):
    """Stitch generated traces into one >= n_points stream. Stitch
    boundaries are teleports (breakage -> RESTART), exactly what a
    long-lived probe session looks like across coverage gaps."""
    import numpy as np
    from reporter_tpu.synth import generate_trace
    rng = np.random.default_rng(seed)
    pts, t_off, s = [], 0.0, 0
    while len(pts) < n_points:
        tr = None
        for _ in range(500):
            tr = generate_trace(city, f"bench-{seed}-{s}", rng,
                                noise_m=6.0)
            if tr is not None:
                break
        if tr is None:
            raise RuntimeError("could not generate a trace")
        seg = list(tr.points)
        base = seg[0]["time"]
        pts.extend(dict(p, time=p["time"] - base + t_off) for p in seg)
        t_off = pts[-1]["time"] + 5.0
        s += 1
    return pts[:n_points]


def _leg(city, pts, uuid, T, measure):
    """Warm to T, then measure ``measure`` appended points (two passes,
    fresh matcher each; see module doc)."""
    import gc

    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.utils import metrics
    dec_ms, inc_ms, batch_ms, batch_dec_ms = [], [], [], []
    served = windows = mismatches = 0
    steps0 = commits0 = 0

    m = SegmentMatcher(net=city)
    try:
        for hi in range(8, T + measure + 1):
            req = {"uuid": uuid, "trace": pts[:hi]}
            if hi == T + 1:
                steps0 = metrics.counter("match.incremental.steps")
                commits0 = metrics.counter("match.incremental.commits")
                # a collector pause inside a sub-ms advance reads as
                # decode cost; collect now, hold it off while timing
                gc.collect()
                gc.disable()
            d0 = _timer_total_ms(_INC_DECODE)
            t0 = time.perf_counter()
            got = m.match_incremental([req])[0]
            t1 = time.perf_counter()
            if hi > T:  # the warm-up stretch absorbs compiles + ramp
                windows += 1
                inc_ms.append((t1 - t0) * 1e3)
                dec_ms.append(_timer_total_ms(_INC_DECODE) - d0)
                if got is not None:
                    served += 1
    finally:
        gc.enable()
    n = max(1, windows)
    steps_pp = (metrics.counter("match.incremental.steps") - steps0) / n
    commits = metrics.counter("match.incremental.commits") - commits0

    m = SegmentMatcher(net=city)
    # the measured windows (T..T+measure kept points) can pad into a
    # bucket the warm-up stretch never touched — compile it here or the
    # batch p99 reads as jit compile time, not decode
    m.match_many([{"trace": pts[:T + measure]}])
    for hi in range(8, T + measure + 1):
        req = {"uuid": uuid, "trace": pts[:hi]}
        got = m.match_incremental([req])[0]
        d0 = _timer_total_ms(_BATCH_DECODE)
        t1 = time.perf_counter()
        ref = m.match_many([req])[0]
        t2 = time.perf_counter()
        if hi > T:
            batch_ms.append((t2 - t1) * 1e3)
            batch_dec_ms.append(_timer_total_ms(_BATCH_DECODE) - d0)
        if got is not None and _ser(got) != _ser(ref):
            mismatches += 1
    return {
        "window": T,
        "dec_p50_ms": round(_pctl(dec_ms, 0.5), 3),
        "dec_p99_ms": round(_pctl(dec_ms, 0.99), 3),
        "inc_p50_ms": round(_pctl(inc_ms, 0.5), 3),
        "inc_p99_ms": round(_pctl(inc_ms, 0.99), 3),
        "batch_dec_p50_ms": round(_pctl(batch_dec_ms, 0.5), 3),
        "batch_dec_p99_ms": round(_pctl(batch_dec_ms, 0.99), 3),
        "batch_p50_ms": round(_pctl(batch_ms, 0.5), 3),
        "batch_p99_ms": round(_pctl(batch_ms, 0.99), 3),
        "steps_per_point": round(steps_pp, 2),
        "commits": commits,
        "served": served,
        "windows": windows,
    }, mismatches


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="stream_bench", description=__doc__.splitlines()[0])
    ap.add_argument("--streaming", action="store_true",
                    help="accepted for bench.py front-door symmetry")
    ap.add_argument("--windows", default="64,256",
                    help="comma list of window lengths T (default "
                    "64,256; flatness_ratio compares last vs first)")
    ap.add_argument("--measure", type=int, default=32,
                    help="appended points timed per leg (default 32)")
    ap.add_argument("--out", default=None,
                    help="also write the artifact JSON to FILE")
    ap.add_argument("--max-ratio", type=float, default=0.0,
                    help="inline flatness gate: fail when "
                    "dec_p99[T_max]/dec_p99[T_min] exceeds R (default "
                    "0 = report only; mismatches always fail)")
    args = ap.parse_args(argv)
    Ts = sorted(int(t) for t in args.windows.split(","))
    if len(Ts) < 2:
        ap.error("--windows needs at least two lengths")

    from reporter_tpu.matcher import incremental as inc
    from reporter_tpu.synth import build_grid_city

    city = build_grid_city(rows=12, cols=12, spacing_m=200.0, seed=2,
                           service_road_fraction=0.0,
                           internal_fraction=0.0)
    pts = _long_trace(city, max(Ts) + args.measure, seed=11)

    legs, mismatches = {}, 0
    for T in Ts:
        # fresh matchers per leg (inside _leg): leg N must not inherit
        # leg N-1's carried state; compiled buckets share the jit cache
        leg, mm = _leg(city, pts, f"stream-{T}", T, args.measure)
        legs[str(T)] = leg
        mismatches += mm
        sys.stderr.write(
            f"stream_bench: T={T} decode p50/p99 {leg['dec_p50_ms']}/"
            f"{leg['dec_p99_ms']} ms (batch decode "
            f"{leg['batch_dec_p50_ms']}/{leg['batch_dec_p99_ms']} ms), "
            f"match {leg['inc_p50_ms']}/{leg['inc_p99_ms']} ms (batch "
            f"{leg['batch_p50_ms']}/{leg['batch_p99_ms']} ms), served "
            f"{leg['served']}/{leg['windows']}, {mm} mismatch(es)\n")

    lo, hi = str(Ts[0]), str(Ts[-1])
    ratio = round(legs[hi]["dec_p99_ms"] / max(1e-9,
                  legs[lo]["dec_p99_ms"]), 3)
    art = {
        "kind": "streaming",
        "lag": inc.lag_bound(),
        "measure": args.measure,
        "legs": legs,
        "parity_mismatches": mismatches,
        "flatness_ratio": ratio,
        # p50-based: the whole-window growth claim is about typical
        # decode cost; p99 at 32 samples is a max and jitters run to run
        "batch_growth": round(legs[hi]["batch_dec_p50_ms"] / max(
            1e-9, legs[lo]["batch_dec_p50_ms"]), 3),
        "speedup_p50_at_256": round(
            legs[hi]["batch_p50_ms"] / max(1e-9, legs[hi]["inc_p50_ms"]),
            2),
    }
    line = json.dumps(art, separators=(",", ":"))
    print(line)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(line + "\n")

    if mismatches:
        sys.stderr.write(f"stream_bench: FAIL: {mismatches} parity "
                         "mismatch(es) vs the batch oracle\n")
        return 1
    for T, leg in legs.items():
        if not leg["served"]:
            sys.stderr.write(f"stream_bench: FAIL: T={T} served no "
                             "window incrementally — flatness over an "
                             "all-fallback leg is vacuous\n")
            return 1
    if args.max_ratio and ratio > args.max_ratio:
        sys.stderr.write(f"stream_bench: FAIL: flatness_ratio {ratio} "
                         f"> {args.max_ratio}\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
