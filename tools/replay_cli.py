#!/usr/bin/env python
"""Standalone dead-letter replay: drain a worker's spools by hand.

The streaming worker drains its own spools when
``REPORTER_TPU_REPLAY_INTERVAL_S`` is set (streaming/drainer.py); this
CLI drives the SAME drainer one-shot against a spool directory for
split deployments and operators — spooled ``.traces`` bodies are
/report-ready request JSON (re-POSTed to any matcher service), spooled
tiles are flush-layout CSV (re-egressed to any sink the worker could
have written: directory, http(s) endpoint, s3 bucket).

A /report response is observations, and observations may only re-enter
the world through a privacy-culling anonymiser — so trace replay
(``--url``) builds one: recovered segments are culled, tiled and
flushed into ``--sink`` (and teed into ``--datastore``) under this
run's own source name (default ``replay-<pid>``, so recovered tile
files can never collide with a live writer's epoch-named files or an
earlier replay run's). Pass the worker's ``--privacy``/``--quantisation``
so the recovery pipeline enforces the same contract the live one does.
``--discard-responses`` is the explicit opt-out for the one deployment
where dropping them is correct: the remote service owns its own
downstream pipeline.

Usage:
  # re-POST spooled trace JSON; recovered observations re-enter through
  # a real anonymiser into the sink (and the datastore, ledger-deduped)
  python tools/replay_cli.py --spool OUT/.deadletter \
      --url http://host:8002/report --privacy 5 --quantisation 3600 \
      --sink OUT --datastore STORE

  # re-egress spooled tiles only (no trace replay)
  python tools/replay_cli.py --spool OUT/.deadletter --sink OUT

Entries still failing after ``--attempts`` move to ``.quarantine``
(skipped by every scanner) instead of wedging the drain; exit is 0 only
when the spools it was asked to drain are empty.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")  # never probe a chip


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="replay_cli", description=__doc__.splitlines()[0])
    parser.add_argument("--spool", required=True,
                        help="tile dead-letter root (the worker's "
                             "<output>/.deadletter); trace JSON is "
                             "expected under its .traces subdir")
    parser.add_argument("--url",
                        help="matcher /report endpoint to re-POST "
                             "spooled trace JSON to; needs --privacy/"
                             "--quantisation/--sink (the recovery "
                             "pipeline) or --discard-responses")
    parser.add_argument("--sink",
                        help="tile sink (dir / http(s) / s3) to "
                             "re-egress spooled tiles into — and to "
                             "flush trace-replay recoveries into")
    parser.add_argument("--datastore",
                        help="histogram-store dir: spooled tiles also "
                             "replay into it (ledger-deduped, so tiles "
                             "the worker tee already ingested no-op); "
                             "trace-replay recoveries tee into it too")
    parser.add_argument("--privacy", type=int,
                        help="privacy threshold for the trace-replay "
                             "anonymiser (use the worker's value)")
    parser.add_argument("--quantisation", type=int,
                        help="tile time quantisation in seconds for the "
                             "trace-replay anonymiser (worker's value)")
    parser.add_argument("--mode", default="auto",
                        help="travel mode for the recovery anonymiser "
                             "(default auto)")
    parser.add_argument("--source", default=f"replay-{os.getpid()}",
                        help="source name stamped into recovered tile "
                             "files (default replay-<pid> — unique, so "
                             "a recovery can never overwrite a live "
                             "writer's or an earlier replay's tiles)")
    parser.add_argument("--discard-responses", action="store_true",
                        help="replay traces WITHOUT a local recovery "
                             "pipeline: only correct when the remote "
                             "service owns its own downstream pipeline "
                             "— recovered observations are otherwise "
                             "lost the moment the spool entry clears")
    parser.add_argument("--attempts", type=int, default=5,
                        help="attempts per entry before .quarantine "
                             "(default 5)")
    args = parser.parse_args(argv)
    if not args.url and not args.sink and not args.datastore:
        parser.error("nothing to do: pass --url, --sink and/or "
                     "--datastore")
    if args.url and not args.discard_responses and not (
            args.privacy and args.quantisation and args.sink):
        parser.error(
            "--url replays observations: give them a pipeline to land "
            "in (--privacy N --quantisation S --sink DIR, matching the "
            "worker's knobs) or pass --discard-responses if the remote "
            "service owns its own downstream pipeline")

    from reporter_tpu.datastore import LocalDatastore
    from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
    from reporter_tpu.streaming.drainer import DeadLetterDrainer
    from reporter_tpu.streaming.worker import http_submitter
    from reporter_tpu.utils import metrics

    sink = TileSink(args.sink) if args.sink else None
    datastore = LocalDatastore(args.datastore) if args.datastore else None

    recovery = None
    if args.url and not args.discard_responses:
        tee = None
        if datastore is not None:
            def tee(_tile, segments, ingest_key=None, _ds=datastore):
                return _ds.ingest_segments(segments,
                                           ingest_key=ingest_key)
        recovery = Anonymiser(sink, privacy=args.privacy,
                              quantisation=args.quantisation,
                              mode=args.mode, source=args.source,
                              tee=tee)

    drainer = DeadLetterDrainer(
        args.spool,
        submit=http_submitter(args.url) if args.url else None,
        forward=recovery.process if recovery is not None else None,
        sink=sink,
        datastore=datastore,
        max_attempts=args.attempts)
    before = drainer.backlog()
    drained = drainer.drain_now()
    recovered_tiles = recovery.punctuate() if recovery is not None else 0
    after = drainer.backlog()
    snap = metrics.default.snapshot()["counters"]
    print(json.dumps({
        "before": before, "drained": drained, "after": after,
        "quarantined": snap.get("replay.quarantined", 0),
        "traces": {"ok": snap.get("replay.traces.ok", 0),
                   "fail": snap.get("replay.traces.fail", 0)},
        "tiles": {"ok": snap.get("replay.tiles.ok", 0),
                  "fail": snap.get("replay.tiles.fail", 0)},
        "recovered_tiles": recovered_tiles,
    }, indent=2))
    left = (after["traces"] if args.url else 0) + \
        (after["tiles"] if args.sink or args.datastore else 0)
    return 0 if left == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
