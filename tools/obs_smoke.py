#!/usr/bin/env python
"""Observability smoke: the obs layer proven over the real stack.

Four legs, each asserting a contract the README advertises:

  trace     a ``/report?trace=1`` request returns a Perfetto-loadable
            trace-event JSON whose stage spans cover >= 95% of the
            request's root span (no unexplained request time), naming
            every pipeline stage (dispatch -> matcher prep/decode/
            assemble -> serialisation)
  metrics   ``/metrics`` scrapes clean: every line parses as Prometheus
            exposition 0.0.4, histogram buckets are monotone and end at
            the +Inf == _count invariant; ``/stats`` reports
            p50/p95/p99 per stage timer
  slo       a breached ``REPORTER_TPU_SLO_MS`` budget flips /health 503
            with the breach named; clearing it restores 200
  profiler  a second same-shape request adds ZERO
            ``decode.compile.count`` (recompile-count stability);
            ``/profile`` scrapes clean and reports a padding-waste
            ratio in (0, 1) for a mixed-length batch; and
            ``REPORTER_TPU_SHADOW_SAMPLE=1.0`` over the synthetic city
            yields ``decode.shadow.mismatch == 0`` with a non-zero
            sample count
  perf_gate ``tools/perf_gate.py`` passes against a ledger freshly
            seeded from the checked-in bench artifacts, and a doctored
            candidate 20% below the ledger median fails it
  flightrec a worker SIGKILL'd by a deterministic crash failpoint
            (``worker.offer=crash``) leaves a flight-recorder
            postmortem naming the exact span in flight at death

Usage: REPORTER_TPU_PLATFORM=cpu python tools/obs_smoke.py
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")

FMT = r",sv,\|,0,1,2,3,4"  # uuid|lat|lon|time|accuracy

#: every stage the trace must make legible for a single /report request
REQUIRED_SPANS = ("service.request", "service.parse", "service.handle",
                  "dispatch.batch", "dispatch.match_many",
                  "matcher.chunk", "matcher.prep",
                  "matcher.decode_dispatch", "matcher.decode_wait",
                  "matcher.assemble", "report.serialise")

_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE.+-]+( Inf)?$')
_META_RE = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ")


def log(msg: str) -> None:
    print(f"obs_smoke: {msg}", flush=True)


def fail(msg: str) -> int:
    sys.stderr.write(f"obs_smoke: FAIL: {msg}\n")
    return 1


def _city():
    from reporter_tpu.synth import build_grid_city
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=5,
                           service_road_fraction=0.0,
                           internal_fraction=0.0)


def _request(city, uuid: str, seed: int) -> dict:
    import numpy as np

    from reporter_tpu.synth import generate_trace
    rng = np.random.default_rng(seed)
    tr = None
    while tr is None:
        tr = generate_trace(city, uuid, rng, noise_m=3.0,
                            min_route_edges=8)
    return {"uuid": tr.uuid, "trace": tr.points,
            "match_options": {"mode": "auto", "report_levels": [0, 1, 2],
                              "transition_levels": [0, 1, 2]}}


def _post(url: str, body: dict):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as resp:
        return resp.status, resp.read().decode()


def _coverage(events) -> float:
    """Fraction of the root span's wall covered by the union of every
    other span's interval — the "no unexplained request time" number."""
    root = [e for e in events if e["name"] == "service.request"]
    if not root:
        return 0.0
    r0 = root[0]["ts"]
    r1 = r0 + root[0]["dur"]
    ivals = sorted(
        (max(e["ts"], r0), min(e["ts"] + e["dur"], r1))
        for e in events
        if e is not root[0] and e.get("ph") == "X"
        and e["ts"] + e["dur"] > r0 and e["ts"] < r1)
    covered = 0.0
    cur0 = cur1 = None
    for a, b in ivals:
        if cur1 is None or a > cur1:
            if cur1 is not None:
                covered += cur1 - cur0
            cur0, cur1 = a, b
        else:
            cur1 = max(cur1, b)
    if cur1 is not None:
        covered += cur1 - cur0
    return covered / max(root[0]["dur"], 1e-9)


def check_exposition(text: str) -> str:
    """Parse a Prometheus text body; returns "" when clean, else the
    first problem. Validates line grammar, bucket monotonicity and the
    +Inf == _count histogram invariant."""
    buckets: dict = {}
    counts: dict = {}
    for i, line in enumerate(text.strip("\n").split("\n"), start=1):
        if _META_RE.match(line):
            continue
        if not _SAMPLE_RE.match(line):
            return f"line {i} is not exposition format: {line!r}"
        name = line.split("{")[0].split(" ")[0]
        value = float(line.rsplit(" ", 1)[1])
        if name.endswith("_bucket"):
            fam = buckets.setdefault(name, [])
            if fam and value < fam[-1]:
                return f"bucket counts not monotone at line {i}: {line!r}"
            fam.append(value)
        elif name.endswith("_count"):
            counts[name[:-len("_count")]] = value
        if value < 0:
            return f"negative sample at line {i}: {line!r}"
    for fam, vals in buckets.items():
        base = fam[:-len("_bucket")]
        if base in counts and vals[-1] != counts[base]:
            return (f"{fam} +Inf bucket {vals[-1]} != "
                    f"{base}_count {counts[base]}")
    return ""


# ---------------------------------------------------------------------------
def leg_service() -> int:
    """trace + metrics + slo legs over one in-process HTTP service."""
    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.service.server import ReporterService, serve

    city = _city()
    service = ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                              max_batch=64, max_wait_ms=5.0)
    httpd = serve(service, "127.0.0.1", 0)
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        req = _request(city, "obs-0", seed=3)

        # -- trace leg ------------------------------------------------------
        code, _ = _post(f"{base}/report", req)  # warm the jit caches
        if code != 200:
            return fail(f"warmup request failed ({code})")
        t0 = time.perf_counter()
        code, text = _post(f"{base}/report?trace=1", req)
        wall_s = time.perf_counter() - t0
        if code != 200:
            return fail(f"traced request failed ({code})")
        body = json.loads(text)
        if "report" not in body or "trace" not in body:
            return fail(f"?trace=1 response missing report/trace keys: "
                        f"{sorted(body)}")
        if "datastore" not in body["report"]:
            return fail("?trace=1 report payload lost the report schema")
        events = body["trace"].get("traceEvents")
        if not events:
            return fail("empty traceEvents")
        for ev in events:  # Perfetto-loadable: the fields it requires
            if not (ev.get("name") and ev.get("ph") in ("X", "B")
                    and isinstance(ev.get("ts"), (int, float))
                    and isinstance(ev.get("pid"), int)):
                return fail(f"malformed trace event: {ev}")
            if ev["ph"] == "X" and not isinstance(ev.get("dur"),
                                                  (int, float)):
                return fail(f"X event without dur: {ev}")
        names = {e["name"] for e in events}
        missing = [n for n in REQUIRED_SPANS if n not in names]
        if missing:
            return fail(f"trace is missing stage spans {missing}; "
                        f"got {sorted(names)}")
        root_s = next(e["dur"] for e in events
                      if e["name"] == "service.request") / 1e6
        cov = _coverage(events)
        if cov < 0.95:
            return fail(f"stage spans cover only {cov:.1%} of the "
                        f"request root span (want >= 95%)")
        log(f"trace: {len(events)} events, stage coverage {cov:.1%} of "
            f"root ({root_s * 1e3:.1f} ms of {wall_s * 1e3:.1f} ms wall)")

        # -- metrics leg ----------------------------------------------------
        with urllib.request.urlopen(f"{base}/metrics") as resp:
            ctype = resp.headers["Content-type"]
            mtext = resp.read().decode()
        if not ctype.startswith("text/plain"):
            return fail(f"/metrics content type {ctype!r}")
        problem = check_exposition(mtext)
        if problem:
            return fail(f"/metrics not scrape-clean: {problem}")
        for needle in ("reporter_tpu_service_requests_total",
                       "reporter_tpu_service_handle_seconds_bucket",
                       "reporter_tpu_service_handle_seconds_sum",
                       "reporter_tpu_service_handle_seconds_count"):
            if needle not in mtext:
                return fail(f"/metrics missing {needle}")
        with urllib.request.urlopen(f"{base}/stats") as resp:
            stats = json.loads(resp.read().decode())
        handle = stats["timers"].get("service.handle")
        if not handle:
            return fail("no service.handle timer in /stats")
        for key in ("p50_s", "p95_s", "p99_s"):
            if key not in handle:
                return fail(f"/stats timer missing {key}: {handle}")
        if not (handle["p50_s"] <= handle["p95_s"] <= handle["p99_s"]
                <= handle["max_s"]):
            return fail(f"percentiles not ordered: {handle}")
        log(f"metrics: scrape-clean exposition "
            f"({len(mtext.splitlines())} lines), /stats p99 "
            f"{handle['p99_s'] * 1e3:.1f} ms over {handle['count']} "
            "requests")

        # -- slo leg --------------------------------------------------------
        os.environ["REPORTER_TPU_SLO_MS"] = "service.handle=0.000001"
        try:
            try:
                urllib.request.urlopen(f"{base}/health")
                return fail("breached SLO did not flip /health 503")
            except urllib.error.HTTPError as e:
                if e.code != 503:
                    return fail(f"/health {e.code} on SLO breach")
                hbody = json.loads(e.read().decode())
                breaches = hbody.get("slo", {}).get("breaches")
                if not breaches or \
                        breaches[0]["stage"] != "service.handle":
                    return fail(f"breach not named on /health: {hbody}")
        finally:
            os.environ.pop("REPORTER_TPU_SLO_MS", None)
        with urllib.request.urlopen(f"{base}/health") as resp:
            if resp.status != 200:
                return fail("/health did not recover after SLO cleared")
        log("slo: breach flipped /health 503 and named the stage; "
            "clearing the spec restored 200")

        # -- profiler leg ---------------------------------------------------
        from reporter_tpu.obs import profiler

        def counters():
            with urllib.request.urlopen(f"{base}/stats") as resp:
                return json.loads(resp.read().decode())["counters"]

        c0 = counters().get("decode.compile.count", 0)
        if c0 < 1:
            return fail("no compile episode recorded for the first "
                        "requests (compile telemetry dead?)")
        code, _ = _post(f"{base}/report", req)  # SAME shape again
        if code != 200:
            return fail(f"repeat request failed ({code})")
        c1 = counters().get("decode.compile.count", 0)
        if c1 != c0:
            return fail(f"second same-shape request recompiled: "
                        f"decode.compile.count {c0} -> {c1}")

        # mixed-length batch through one dispatcher round trip. Lengths
        # chosen so the 64-bucket pair (40, 45) rides ONE multi-trace
        # chunk: both sit above the 32 rung, so the ISSUE-13 adaptive
        # splitter (which would break a (12, 25, 40) mix into 1-trace
        # pow2 sub-batches) has nothing to reclaim and the wide-event
        # assertions below still see a >=2-trace chunk
        mixed = []
        for i, n_pts in enumerate((12, 40, 45)):
            r = _request(city, f"mix-{i}", seed=20 + i)
            r["trace"] = r["trace"][:n_pts]
            mixed.append(r)
        os.environ["REPORTER_TPU_SHADOW_SAMPLE"] = "1.0"
        try:
            reports = service.report_many(mixed)
            if not all(r is not None for r in reports):
                return fail("mixed-length batch had failed reports")
            if not profiler.drain_shadow(60.0):
                return fail("shadow decode did not drain")
        finally:
            os.environ.pop("REPORTER_TPU_SHADOW_SAMPLE", None)

        with urllib.request.urlopen(f"{base}/profile") as resp:
            prof = json.loads(resp.read().decode())
        for key in ("shapes", "events", "totals", "shadow",
                    "compile_episodes"):
            if key not in prof:
                return fail(f"/profile missing {key}: {sorted(prof)}")
        if not prof["shapes"] or not prof["events"]:
            return fail("/profile has no shapes/events after requests")
        mixed_evs = [e for e in prof["events"] if e["traces"] >= 2]
        if not mixed_evs:
            return fail("no multi-trace wide event for the mixed batch")
        waste = mixed_evs[-1]["padding_waste"]
        if not (0.0 < waste < 1.0):
            return fail(f"mixed-batch padding waste {waste} not in "
                        "(0, 1)")
        cnt = counters()
        sampled = cnt.get("decode.shadow.sampled", 0)
        mismatch = cnt.get("decode.shadow.mismatch", 0)
        if sampled < len(mixed):
            return fail(f"shadow sampled only {sampled} traces")
        if mismatch != 0:
            return fail(f"shadow oracle disagreed on {mismatch} of "
                        f"{sampled} traces (accuracy drift!)")
        storms = sum(max(0, s["compiles"] - 1) for s in prof["shapes"])
        if storms:
            return fail(f"recompile storm: {storms} same-shape "
                        f"recompiles in {prof['shapes']}")
        log(f"profiler: compile stable at {c1} episode(s) across "
            f"repeat requests, mixed-batch padding waste {waste:.3f}, "
            f"shadow {sampled} sampled / 0 mismatches")
        return 0
    finally:
        httpd.shutdown()


# ---------------------------------------------------------------------------
def leg_perf_gate() -> int:
    """The perf ledger/gate contract: a seeded ledger passes the
    self-check; a candidate 20% below the ledger median fails."""
    with tempfile.TemporaryDirectory() as tmp:
        ledger = os.path.join(tmp, "LEDGER.jsonl")
        seed = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "perf_ledger.py"),
             "seed", "--out", ledger, "--repo", REPO],
            capture_output=True, text=True, timeout=60)
        if seed.returncode != 0:
            return fail(f"perf_ledger seed rc={seed.returncode}: "
                        f"{seed.stderr[-500:]}")
        ok = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"),
             "--ledger", ledger, "--self-check"],
            capture_output=True, text=True, timeout=60)
        if ok.returncode != 0:
            return fail(f"perf_gate self-check failed on a clean "
                        f"ledger: {ok.stdout[-500:]}{ok.stderr[-500:]}")
        # doctor a candidate 20% below the cpu full-run median
        import statistics
        with open(ledger, encoding="utf-8") as f:
            entries = [json.loads(line) for line in f if line.strip()]
        pool = [e["vs_baseline"] for e in entries
                if e.get("vs_baseline") and e.get("platform") == "cpu"
                and e.get("scope", "full") == "full"]
        median = statistics.median(pool)
        doctored = os.path.join(tmp, "doctored.json")
        with open(doctored, "w", encoding="utf-8") as f:
            json.dump({"source": "doctored", "platform": "cpu",
                       "scope": "full", "pipelined": False,
                       "vs_baseline": round(median * 0.8, 2),
                       "stage_shares": None, "kind": "bench"}, f)
        bad = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"),
             "--ledger", ledger, "--candidate", doctored],
            capture_output=True, text=True, timeout=60)
        if bad.returncode == 0:
            return fail(f"perf_gate PASSED a 20%-regressed candidate "
                        f"(median {median}): {bad.stdout[-500:]}")
        log(f"perf_gate: clean self-check passed; 20%-regressed "
            f"candidate ({median:.2f} -> {median * 0.8:.2f}) failed "
            "as it must")
        return 0


# ---------------------------------------------------------------------------
def leg_flightrec() -> int:
    """A crash failpoint mid-stream leaves a postmortem naming the span
    in flight at SIGKILL."""
    import numpy as np

    from reporter_tpu.synth import generate_trace
    from reporter_tpu.utils import faults as faults_mod

    with tempfile.TemporaryDirectory() as tmp:
        city = _city()
        graph = os.path.join(tmp, "city.npz")
        city.save(graph)
        rng = np.random.default_rng(9)
        lines = []
        for i in range(4):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"veh-{i}", rng, noise_m=3.0,
                                    min_route_edges=8)
            for p in tr.points:
                lines.append("|".join(
                    [tr.uuid, str(p["lat"]), str(p["lon"]),
                     str(p["time"]), str(p["accuracy"])]))
        inp = os.path.join(tmp, "input.txt")
        with open(inp, "w") as f:
            f.write("\n".join(lines) + "\n")
        out = os.path.join(tmp, "out")
        k = len(lines) // 2
        env = dict(os.environ,
                   REPORTER_TPU_PLATFORM="cpu",
                   REPORTER_TPU_TRACE="1",
                   REPORTER_TPU_FAULTS=f"worker.offer=crash+{k}#1")
        cmd = [sys.executable, "-m", "reporter_tpu", "stream",
               "-f", FMT, "--graph", graph, "-p", "1", "-q", "3600",
               "-i", "1000000000", "-s", "obs", "-o", out,
               "--input", inp, "--uuid-filter", "off",
               "-r", "0,1,2", "-x", "0,1,2"]
        p = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                           text=True, timeout=600)
        if p.returncode != faults_mod.CRASH_EXIT_CODE:
            return fail(f"crash run rc={p.returncode} "
                        f"(want {faults_mod.CRASH_EXIT_CODE}): "
                        f"{p.stderr[-2000:]}")
        rec_dir = os.path.join(out, ".deadletter", ".flightrec")
        dumps = sorted(os.listdir(rec_dir)) if os.path.isdir(rec_dir) \
            else []
        if not dumps:
            return fail(f"no flight-recorder dump under {rec_dir}")
        with open(os.path.join(rec_dir, dumps[-1]),
                  encoding="utf-8") as f:
            post = json.load(f)
        if not post["reason"].startswith("crash.worker.offer"):
            return fail(f"postmortem reason {post['reason']!r}")
        inflight = [s["name"] for s in post.get("in_flight", [])]
        if "worker.offer" not in inflight:
            return fail(f"postmortem does not name the span in flight "
                        f"at SIGKILL: {inflight}")
        if len(post.get("spans", [])) == 0:
            return fail("postmortem ring is empty (tracing was armed)")
        log(f"flightrec: postmortem {dumps[-1]} names in-flight span "
            f"worker.offer with {len(post['spans'])} ring events")
        return 0


def main(argv=None) -> int:
    rc = leg_service()
    if rc:
        return rc
    rc = leg_perf_gate()
    if rc:
        return rc
    rc = leg_flightrec()
    if rc:
        return rc
    log("all legs passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
