#!/usr/bin/env python
"""reporter-lint driver: run the project-native static-analysis suite.

Usage:
  python tools/lint.py                 # full suite over reporter_tpu/,
                                       # tools/ and bench.py
  python tools/lint.py --abi-only      # just the ctypes<->C++ ABI guard
  python tools/lint.py --contracts-only  # just the cross-layer contract
                                       # passes (registry/durability/
                                       # lock-graph/fault-coverage/
                                       # tensor-contract/placement/
                                       # fallback)
  python tools/lint.py --tensors-only  # just the device-contract passes
                                       # (TC/DP/FB): eval_shape harness,
                                       # transfer discipline, fallback
                                       # parity
  python tools/lint.py --list-rules    # rule catalogue
  python tools/lint.py path.py ...     # restrict the code passes to paths

Exit status: 0 clean; 1 findings (or stale baseline entries); 2 usage /
internal error. Output lines are ``file:line: RULE-ID message``.

Baseline workflow: findings listed verbatim in ``tools/lint_baseline.txt``
are accepted (grandfathered) — but an entry that stops firing fails the
run as *stale* so the file can only shrink honestly. ``--write-baseline``
regenerates it from the current findings. ``--abi-only`` and
``--contracts-only`` ignore the baseline entirely: an ABI mismatch or a
registry/doc drift is never acceptable debt — fix the code, the
registry, or README in the same commit.
"""
from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from reporter_tpu import analysis  # noqa: E402
from reporter_tpu.analysis import abi  # noqa: E402

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "lint_baseline.txt")

#: the full-run scan scope: the package, the operational tooling, and
#: the bench entry point (tools/ and bench.py read knobs and metrics
#: too — the registry passes must see them)
DEFAULT_ROOTS = (
    os.path.join(REPO_ROOT, "reporter_tpu"),
    os.path.join(REPO_ROOT, "tools"),
    os.path.join(REPO_ROOT, "bench.py"),
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="reporter-lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        help="files/dirs for the code passes "
                             "(default: reporter_tpu/ tools/ bench.py)")
    parser.add_argument("--abi-only", action="store_true",
                        help="run only the ABI cross-check (pre-commit "
                             "guard; ignores the baseline)")
    parser.add_argument("--contracts-only", action="store_true",
                        help="run only the cross-layer contract passes "
                             "(registry drift, fault coverage, "
                             "durability, lock graph); ignores the "
                             "baseline — fast pre-commit guard")
    parser.add_argument("--tensors-only", action="store_true",
                        help="run only the device-contract passes "
                             "(TC kernel signatures via eval_shape, DP "
                             "transfer discipline, FB fallback parity); "
                             "ignores the baseline — guard for kernel/"
                             "placement changes, needs no device")
    parser.add_argument("--locks-only", action="store_true",
                        help="run only the static lock passes (LD001 "
                             "discipline + LD002/LD003 lock graph); "
                             "ignores the baseline — fast pre-commit "
                             "guard for concurrency changes")
    parser.add_argument("--abi-cpp", default=None,
                        help="override the C++ runtime source path")
    parser.add_argument("--abi-py", default=None,
                        help="override the ctypes binding path")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline file (default tools/lint_baseline.txt)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (report everything)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from current findings")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(analysis.ALL_RULES):
            print(f"{rule}  {analysis.ALL_RULES[rule]}")
        return 0

    cpp_path = args.abi_cpp or os.path.join(REPO_ROOT, abi.DEFAULT_CPP)
    py_path = args.abi_py or os.path.join(REPO_ROOT, abi.DEFAULT_PY)

    def abi_findings():
        if not (os.path.exists(cpp_path) and os.path.exists(py_path)):
            print(f"error: ABI pair missing ({cpp_path}, {py_path})",
                  file=sys.stderr)
            raise SystemExit(2)
        return abi.run_paths(
            cpp_path, py_path,
            os.path.relpath(cpp_path, REPO_ROOT).replace(os.sep, "/"),
            os.path.relpath(py_path, REPO_ROOT).replace(os.sep, "/"))

    if args.abi_only:
        findings = abi_findings()
        for f in findings:
            print(f.render())
        if findings:
            print(f"reporter-lint --abi-only: {len(findings)} ABI "
                  "mismatch(es)", file=sys.stderr)
            return 1
        print("reporter-lint --abi-only: binding matches the C++ runtime")
        return 0

    if args.locks_only:
        files = analysis.collect_py_files(REPO_ROOT, DEFAULT_ROOTS)
        findings = sorted(analysis.filter_suppressed(
            [*analysis.locks.run(files, REPO_ROOT),
             *analysis.lockgraph.run(files, REPO_ROOT)], files))
        for f in findings:
            print(f.render())
        if findings:
            print(f"reporter-lint --locks-only: {len(findings)} lock "
                  "finding(s)", file=sys.stderr)
            return 1
        print(f"reporter-lint --locks-only: lock discipline holds "
              f"({len(files)} files)")
        return 0

    if args.tensors_only:
        files = analysis.collect_py_files(REPO_ROOT, DEFAULT_ROOTS)
        findings = sorted(analysis.filter_suppressed(
            [*analysis.tensorcontract.run(files, REPO_ROOT),
             *analysis.placement.run(files, REPO_ROOT),
             *analysis.fallback.run(files, REPO_ROOT)], files))
        for f in findings:
            print(f.render())
        eval_s = analysis.tensorcontract.LAST_EVAL_SECONDS
        timing = "" if eval_s is None else \
            f" (eval_shape harness: {eval_s:.1f}s)"
        if findings:
            print(f"reporter-lint --tensors-only: {len(findings)} device-"
                  f"contract finding(s){timing}", file=sys.stderr)
            return 1
        print(f"reporter-lint --tensors-only: device contracts hold "
              f"({len(files)} files){timing}")
        return 0

    if args.contracts_only:
        files = analysis.collect_py_files(REPO_ROOT, DEFAULT_ROOTS)
        findings = sorted(
            analysis.filter_suppressed(
                [*analysis.durability.run(files, REPO_ROOT),
                 *analysis.lockgraph.run(files, REPO_ROOT)], files)
            + analysis.run_contract_passes(files, REPO_ROOT))
        for f in findings:
            print(f.render())
        if findings:
            print(f"reporter-lint --contracts-only: {len(findings)} "
                  "contract violation(s)", file=sys.stderr)
            return 1
        print(f"reporter-lint --contracts-only: contracts hold "
              f"({len(files)} files)")
        return 0

    partial = bool(args.paths)
    roots = [os.path.abspath(p) for p in args.paths] if partial \
        else list(DEFAULT_ROOTS)
    files = analysis.collect_py_files(REPO_ROOT, roots)
    findings = analysis.run_code_passes(files, REPO_ROOT)
    if not partial:
        # whole-package-only checks: the ABI pair is fixed
        # infrastructure, and the contract passes' reverse directions
        # (dead entries, README drift, coverage) need every file in view
        findings = sorted(findings + abi_findings()
                          + analysis.run_contract_passes(files, REPO_ROOT))
    else:
        findings = sorted(findings + analysis.run_contract_passes(
            files, REPO_ROOT, full_scope=False))

    if args.write_baseline and partial:
        # a partial run sees a subset of findings; writing it out would
        # silently drop every grandfathered entry outside the paths
        print("error: --write-baseline requires a full run (no paths)",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        with open(args.baseline, "w", encoding="utf-8") as f:
            f.write("# reporter-lint baseline: grandfathered findings.\n"
                    "# Entries must match current findings exactly; stale\n"
                    "# lines fail the lint run. Prefer fixing over listing.\n")
            for fnd in findings:
                f.write(fnd.render() + "\n")
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    baseline = [] if args.no_baseline \
        else analysis.load_baseline(args.baseline)
    new, stale = analysis.compare_baseline(findings, baseline)
    if partial:
        # a partial run cannot judge staleness: entries for files outside
        # the requested paths legitimately did not fire this run
        stale = []
    for f in new:
        print(f.render())
    for entry in stale:
        print(f"stale baseline entry (no longer fires — remove it): "
              f"{entry}")
    if new or stale:
        print(f"reporter-lint: {len(new)} finding(s), {len(stale)} stale "
              f"baseline entr(y/ies)", file=sys.stderr)
        return 1
    n_base = f" ({len(baseline)} baselined)" if baseline else ""
    eval_s = analysis.tensorcontract.LAST_EVAL_SECONDS
    timing = "" if eval_s is None else f", eval_shape {eval_s:.1f}s"
    print(f"reporter-lint: clean — {len(files)} files, "
          f"{len(analysis.ALL_RULES)} rules{n_base}{timing}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
