#!/usr/bin/env python
"""MULTICHIP harness: per-device-count decode throughput ratios.

The committed ``MULTICHIP_r0*`` artifacts recorded only ``{"n_devices":
8, "ok": true}`` — a liveness verdict with no measurement, which is why
ROADMAP item 1 calls reviving this harness "the measurement half" of
the mesh scale-out work. This tool runs the SAME serialized decode leg
at several device counts (``REPORTER_TPU_VIRTUAL_DEVICES`` on the CPU
backend; real chips when the tunnel is up and ``--platform accel``) in
bounded subprocesses and emits one artifact whose throughput RATIOS
(count N over count 1, same box, same leg — the only number that
survives box drift) are parsed by ``obs/ledger.py`` and gated by
``tools/perf_gate.py --multichip``.

Artifact shape (a superset of the legacy verdict keys, so old ledger
seeding still reads it):

    {"n_devices": <max count>, "rc": 0, "ok": true, "skipped": false,
     "tail": "", "legs": [{"n_devices": N, "traces_per_sec": T,
     "rc": 0}, ...], "ratios": {"2": r2, "4": r4, ...}}

On a CPU box the virtual-device mesh shards a compute-bound decode
over the SAME cores, so ratios hover near (or below) 1.0 — the harness
measures, the gate's floor (default 0.5) only catches a catastrophic
sharding regression. On real multi-chip hardware the same artifact
carries the real scaling curve.

Usage:
    python tools/multichip_bench.py [--devices 1,2,4,8] [--traces 96]
        [--out MULTICHIP_rNN.json]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_LEG_CODE = r"""
import json, time
# THE r06 BUG: nothing in this child ever applied the forced host-
# device count, so every leg came up on ONE device and the "2/4/8
# device" ratios measured chunk-size noise (devices_seen: 1 in every
# committed r06 leg). ensure_backend honours REPORTER_TPU_PLATFORM /
# REPORTER_TPU_VIRTUAL_DEVICES BEFORE the first backend resolution —
# it must run before anything imports a jax-touching module.
from reporter_tpu.utils.runtime import ensure_backend
ensure_backend()
import jax
import numpy as np
from reporter_tpu.core.tracebatch import TraceBatch
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.synth import build_grid_city, generate_trace

n_traces = {n_traces}
city = build_grid_city(rows=12, cols=12, spacing_m=200.0, seed=42)
matcher = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
rng = np.random.default_rng(7)
reqs = []
while len(reqs) < n_traces:
    tr = generate_trace(city, f"v{{len(reqs)}}", rng, noise_m=4.0,
                        min_route_edges=5, max_route_edges=60)
    if tr is not None:
        reqs.append(tr.request_json())
tb = TraceBatch.from_requests(reqs)
tb.options = reqs[0]["match_options"]
matcher.match_many(reqs[:8])  # compile the bucket shapes
best = float("inf")
for _ in range(3):
    t0 = time.perf_counter()
    matcher.match_many(tb)
    best = min(best, time.perf_counter() - t0)
from reporter_tpu.ops import decode_mesh_size
print("LEG:" + json.dumps({{
    "devices_seen": len(jax.devices()),
    "mesh_data": decode_mesh_size(),
    "traces_per_sec": round(n_traces / best, 1)}}))
"""


def run_leg(n_devices: int, n_traces: int, timeout_s: float) -> dict:
    env = dict(os.environ,
               REPORTER_TPU_PLATFORM=os.environ.get(
                   "REPORTER_TPU_PLATFORM", "cpu"),
               REPORTER_TPU_VIRTUAL_DEVICES=str(n_devices),
               REPORTER_TPU_SHARD="1",
               REPORTER_TPU_PIPELINE="0")
    # a leg measures ITS device count, not an inherited slice
    env.pop("REPORTER_TPU_DEVICE_SLICE", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _LEG_CODE.format(n_traces=n_traces)],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=HERE)
    except subprocess.TimeoutExpired:
        return {"n_devices": n_devices, "rc": 124,
                "traces_per_sec": None, "tail": "leg timed out"}
    leg = {"n_devices": n_devices, "rc": proc.returncode,
           "traces_per_sec": None, "tail": ""}
    for line in proc.stdout.splitlines():
        if line.startswith("LEG:"):
            parsed = json.loads(line[len("LEG:"):])
            leg["traces_per_sec"] = parsed["traces_per_sec"]
            leg["devices_seen"] = parsed["devices_seen"]
            leg["mesh_data"] = parsed["mesh_data"]
    if proc.returncode != 0 or leg["traces_per_sec"] is None:
        leg["tail"] = (proc.stderr.strip().splitlines() or ["?"])[-1][:200]
    # the r06 lesson, enforced: a leg that did not actually SEE its
    # requested device count is a failed leg, not a slow one — its
    # throughput would silently become a bogus ratio denominator/
    # numerator. (devices_seen is leg-asserted; perf_gate --multichip
    # re-checks the committed artifact.)
    if leg["rc"] == 0 and leg.get("devices_seen") != n_devices:
        leg["rc"] = 5
        leg["tail"] = (f"devices_seen={leg.get('devices_seen')} != "
                       f"requested {n_devices}: the forced host-device "
                       "count never reached the leg")
    return leg


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="multichip_bench",
                                     description=__doc__.splitlines()[0])
    parser.add_argument("--devices", default="1,2,4,8",
                        help="comma-separated device counts (default "
                        "1,2,4,8; count 1 is the ratio denominator and "
                        "is always added)")
    parser.add_argument("--traces", type=int, default=96)
    parser.add_argument("--timeout", type=float, default=600.0,
                        help="per-leg subprocess timeout (seconds)")
    parser.add_argument("--out", default=None,
                        help="write the artifact here (default: stdout "
                        "only)")
    args = parser.parse_args(argv)
    counts = sorted({int(c) for c in args.devices.split(",") if c}
                    | {1})

    legs = [run_leg(n, args.traces, args.timeout) for n in counts]
    base = next((leg["traces_per_sec"] for leg in legs
                 if leg["n_devices"] == 1 and leg["traces_per_sec"]),
                None)
    ratios = {}
    if base:
        for leg in legs:
            if leg["n_devices"] != 1 and leg["traces_per_sec"]:
                ratios[str(leg["n_devices"])] = round(
                    leg["traces_per_sec"] / base, 3)
    ok = all(leg["rc"] == 0 and leg["traces_per_sec"] for leg in legs)
    art = {
        # legacy verdict keys (obs/ledger.py seeded these shapes)
        "n_devices": max(counts), "rc": 0 if ok else 1, "ok": ok,
        "skipped": False,
        "tail": "" if ok else "; ".join(
            f"n={leg['n_devices']}: rc={leg['rc']} {leg['tail']}"
            for leg in legs if leg["rc"] != 0),
        # the measurement half (ROADMAP item 1)
        "legs": legs,
        "ratios": ratios,
    }
    body = json.dumps(art, indent=1)
    print(body)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(body)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
