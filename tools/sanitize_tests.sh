#!/bin/sh
# CI `sanitize` stage: build the native host runtime under sanitizers
# and run the native test files against the instrumented libraries.
#
#   sanitize_tests.sh            # asan + ubsan (the `sanitize` stage)
#   sanitize_tests.sh tsan       # ThreadSanitizer (the `racecheck` stage)
#   sanitize_tests.sh asan|ubsan # one leg in isolation
#
# The Python interpreter itself stays uninstrumented — the sanitizer
# runtime is LD_PRELOADed so the instrumented .so can resolve its
# symbols, and leak checking is off (CPython "leaks" by design at exit;
# we are after overflows/UB/races in host_runtime.cpp, which the
# prep/assemble tests drive hard). The tsan leg runs with
# REPORTER_TPU_PREP_THREADS=4 so the WorkerPool span handoff and the
# striped route-memo's clock eviction actually race. Skips cleanly
# (exit 0 with a notice) when the toolchain lacks sanitizer support,
# per the CI contract.
set -u

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
NATIVE="$ROOT/reporter_tpu/native"
CXX="${CXX:-g++}"
TESTS="tests/test_native.py tests/test_native_batch.py tests/test_prep_v2.py tests/test_report_writer.py tests/test_route_device.py"
# test_report_writer drives the ABI-12 wire writers (per-trace +
# whole-chunk emission, parity + slicing) under the sanitizer
# builds with the same 2-thread prep pool; test_route_device drives
# the ABI-14 additions (skip_routes, candidate pruning, dt output)
# plus the device-vs-host route parity under the instrumented builds
MODE="${1:-default}"

probe() {
    # can this compiler link the sanitizer runtime at all?
    echo 'int main(){return 0;}' | "$CXX" "-fsanitize=$1" -x c++ - \
        -o /tmp/_reporter_san_probe 2>/dev/null
}

cd "$ROOT" || exit 2
rc=0
ran=0

case "$MODE" in
    default|asan|ubsan|tsan) ;;
    *) echo "sanitize: unknown mode '$MODE' (asan|ubsan|tsan)" >&2
       exit 2 ;;
esac

# want <leg>: does the requested MODE include this leg? Legs are named
# by their CLI mode (asan/ubsan/tsan), not the -fsanitize flag probe()
# takes — default runs everything but tsan (the racecheck stage owns it)
want() {
    case "$MODE" in
        default) [ "$1" != tsan ] ;;
        *) [ "$MODE" = "$1" ] ;;
    esac
}

if want asan && probe address; then
    ran=1
    echo "== sanitize: building + testing under AddressSanitizer =="
    make -C "$NATIVE" asan || exit 1
    libasan="$("$CXX" -print-file-name=libasan.so)"
    # libstdc++ rides along in LD_PRELOAD: asan's __cxa_throw interceptor
    # must resolve the real symbol at init, before jaxlib's dlopen'd C++
    # extensions throw (otherwise: "real___cxa_throw != 0" CHECK abort)
    libstdcxx="$("$CXX" -print-file-name=libstdc++.so)"
    LD_PRELOAD="$libasan $libstdcxx" \
    ASAN_OPTIONS="detect_leaks=0:abort_on_error=1" \
    REPORTER_TPU_NATIVE_LIB="$NATIVE/libreporter_host_asan.so" \
    REPORTER_TPU_PREP_THREADS=2 \
    JAX_PLATFORMS=cpu \
        python -m pytest $TESTS -q -p no:cacheprovider || rc=1
elif want asan; then
    echo "== sanitize: $CXX lacks -fsanitize=address; skipping asan =="
fi

if want ubsan && probe undefined; then
    ran=1
    echo "== sanitize: building + testing under UBSan =="
    make -C "$NATIVE" ubsan || exit 1
    UBSAN_OPTIONS="print_stacktrace=1:halt_on_error=1" \
    REPORTER_TPU_NATIVE_LIB="$NATIVE/libreporter_host_ubsan.so" \
    REPORTER_TPU_PREP_THREADS=2 \
    JAX_PLATFORMS=cpu \
        python -m pytest $TESTS -q -p no:cacheprovider || rc=1
elif want ubsan; then
    echo "== sanitize: $CXX lacks -fsanitize=undefined; skipping ubsan =="
fi

if want tsan && probe thread; then
    libtsan="$("$CXX" -print-file-name=libtsan.so)"
    # TSan into an uninstrumented host interpreter is best-effort: the
    # preloaded runtime must survive interpreter startup (some
    # glibc/libtsan pairings abort on "unexpected memory mapping").
    # Probe that before committing the leg — an unusable pairing is a
    # toolchain absence, not a failure, per the skip contract.
    if LD_PRELOAD="$libtsan" TSAN_OPTIONS="report_bugs=0:exitcode=0" \
            python -c "pass" >/dev/null 2>&1; then
        ran=1
        echo "== sanitize: building + testing under ThreadSanitizer =="
        make -C "$NATIVE" tsan || exit 1
        # the tsan leg drives tools/tsan_native_drive.py, NOT pytest:
        # the pytest harness deadlocks under a preloaded libtsan on
        # common glibc pairings (every thread asleep at the first
        # test), and a CI stage must never hang. The driver covers the
        # same native concurrency surface (WorkerPool span handoff,
        # striped route-memo eviction, thread-count bit-identity) —
        # see its module docstring.
        LD_PRELOAD="$libtsan" \
        TSAN_OPTIONS="halt_on_error=1:report_thread_leaks=0:report_signal_unsafe=0" \
        REPORTER_TPU_NATIVE_LIB="$NATIVE/libreporter_host_tsan.so" \
        REPORTER_TPU_PREP_THREADS=4 \
        JAX_PLATFORMS=cpu \
            python tools/tsan_native_drive.py || rc=1
    else
        echo "== sanitize: libtsan cannot preload into this interpreter; skipping tsan =="
    fi
elif want tsan; then
    echo "== sanitize: $CXX lacks -fsanitize=thread; skipping tsan =="
fi

if [ "$ran" = 0 ]; then
    echo "== sanitize: no sanitizer support in this toolchain ($MODE); skipped =="
    exit 0
fi
exit $rc
