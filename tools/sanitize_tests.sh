#!/bin/sh
# CI `sanitize` stage: build the native host runtime under asan and ubsan
# and run the native test files against the instrumented libraries.
#
# The Python interpreter itself stays uninstrumented — the asan runtime is
# LD_PRELOADed so the instrumented .so can resolve its symbols, and leak
# checking is off (CPython "leaks" by design at exit; we are after
# overflows/UB in host_runtime.cpp, which the prep/assemble tests drive
# hard). Skips cleanly (exit 0 with a notice) when the toolchain lacks
# sanitizer support, per the CI contract.
set -u

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
NATIVE="$ROOT/reporter_tpu/native"
CXX="${CXX:-g++}"
TESTS="tests/test_native.py tests/test_native_batch.py tests/test_prep_v2.py"

probe() {
    # can this compiler link the sanitizer runtime at all?
    echo 'int main(){return 0;}' | "$CXX" "-fsanitize=$1" -x c++ - \
        -o /tmp/_reporter_san_probe 2>/dev/null
}

cd "$ROOT" || exit 2
rc=0
ran=0

if probe address; then
    ran=1
    echo "== sanitize: building + testing under AddressSanitizer =="
    make -C "$NATIVE" asan || exit 1
    libasan="$("$CXX" -print-file-name=libasan.so)"
    # libstdc++ rides along in LD_PRELOAD: asan's __cxa_throw interceptor
    # must resolve the real symbol at init, before jaxlib's dlopen'd C++
    # extensions throw (otherwise: "real___cxa_throw != 0" CHECK abort)
    libstdcxx="$("$CXX" -print-file-name=libstdc++.so)"
    LD_PRELOAD="$libasan $libstdcxx" \
    ASAN_OPTIONS="detect_leaks=0:abort_on_error=1" \
    REPORTER_TPU_NATIVE_LIB="$NATIVE/libreporter_host_asan.so" \
    REPORTER_TPU_PREP_THREADS=2 \
    JAX_PLATFORMS=cpu \
        python -m pytest $TESTS -q -p no:cacheprovider || rc=1
else
    echo "== sanitize: $CXX lacks -fsanitize=address; skipping asan =="
fi

if probe undefined; then
    ran=1
    echo "== sanitize: building + testing under UBSan =="
    make -C "$NATIVE" ubsan || exit 1
    UBSAN_OPTIONS="print_stacktrace=1:halt_on_error=1" \
    REPORTER_TPU_NATIVE_LIB="$NATIVE/libreporter_host_ubsan.so" \
    REPORTER_TPU_PREP_THREADS=2 \
    JAX_PLATFORMS=cpu \
        python -m pytest $TESTS -q -p no:cacheprovider || rc=1
else
    echo "== sanitize: $CXX lacks -fsanitize=undefined; skipping ubsan =="
fi

if [ "$ran" = 0 ]; then
    echo "== sanitize: no sanitizer support in this toolchain; skipped =="
    exit 0
fi
exit $rc
