#!/usr/bin/env python
"""Pre-fork serving throughput: N processes vs 1 on the bigreplay mix.

Measures sustained HTTP `/report` request throughput of the service in
single-process mode vs pre-fork ``SO_REUSEPORT`` multi-process mode
(ISSUE 11 acceptance: 2 processes >= 1.6x one process on the bigreplay
topology), using tools/bigreplay.py's city profiles for the request
mix. Each mode runs in a fresh interpreter (the parent must fork its
workers before anything imports jax), takes load from concurrent
client threads against warm workers (every worker has answered
requests before the timed window), and reports requests/sec.

Prints ONE JSON line:
    {"kind": "prefork_bench", "procs": N, "clients": C,
     "duration_s": D, "single_rps": R1, "multi_rps": RN,
     "ratio": RN/R1}

Usage:
    python tools/prefork_bench.py [--procs 2] [--clients 8]
        [--duration 10] [--min-ratio 0] [--out FILE]

``--min-ratio R`` gates the run (exit 1 below R) — the bench box
acceptance leg; CI boxes with one core cannot express the win, so the
default does not gate.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_MODE_SCRIPT = r"""
import json, os, signal, socket, sys, threading, time, urllib.request

import numpy as np

from reporter_tpu.matcher import SegmentMatcher
from reporter_tpu.service.prefork import serve_prefork
from reporter_tpu.service.server import ReporterService
from reporter_tpu.synth import build_grid_city, generate_trace
from tools.bigreplay import CITY_PROFILES

PROCS = {procs}
CLIENTS = {clients}
DURATION = {duration}

# the bigreplay urban-canyon profile: densest graph, noisiest probes
name, grid_kw, noise_m, period_s, _queue = CITY_PROFILES[0]
city = build_grid_city(service_road_fraction=0.0, internal_fraction=0.0,
                       **grid_kw)
rng = np.random.default_rng(1234)
bodies = []
while len(bodies) < 48:
    tr = generate_trace(city, f"bench-{{len(bodies)}}", rng,
                        noise_m=noise_m, sample_period_s=period_s,
                        min_route_edges=8)
    if tr is not None:
        bodies.append(json.dumps(tr.request_json()).encode())

with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
base = f"http://127.0.0.1:{{port}}"


def make_service():
    return ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                           max_batch=64, max_wait_ms=5.0)


def post(body, timeout=120.0):
    r = urllib.request.Request(base + "/report", data=body,
                               method="POST")
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return resp.status, resp.headers.get("X-Reporter-Proc")


result = {{}}


def drive():
    time.sleep(2.0)  # quiet-parent fork window
    try:
        _drive()
    except Exception as e:
        result["err"] = f"{{type(e).__name__}}: {{e}}"


def _drive():
    deadline = time.time() + 240
    while True:
        try:
            post(bodies[0])
            break
        except Exception:
            if time.time() > deadline:
                result["err"] = "service never came up"
                return
            time.sleep(0.3)
    # warm every worker: keep firing until each proc tag has answered
    # enough to have compiled its decode shapes
    seen = {{}}
    for i in range(600):
        _st, tag = post(bodies[i % len(bodies)])
        slot = tag.split(":")[0]
        seen[slot] = seen.get(slot, 0) + 1
        if len(seen) >= PROCS and min(seen.values()) >= 24:
            break
    # timed window: CLIENTS threads firing as fast as the service
    # answers; count successes only (a refused connection mid-run
    # would be a worker death — none expected here)
    stop = time.time() + DURATION
    counts = [0] * CLIENTS

    def client(ci):
        i = ci
        while time.time() < stop:
            st, _tag = post(bodies[i % len(bodies)])
            if st == 200:
                counts[ci] += 1
            i += CLIENTS

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(CLIENTS)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - t0
    result.update(rps=round(sum(counts) / wall, 1), workers=len(seen))


t = threading.Thread(target=drive, daemon=True)
try:
    urllib.request.urlopen(base + "/stats", timeout=0.2)
except Exception:
    pass  # warm the opener machinery pre-fork, in the main thread
t.start()


def reaper():
    t.join()
    os.kill(os.getpid(), signal.SIGTERM)


threading.Thread(target=reaper, daemon=True).start()
rc = serve_prefork(make_service, "127.0.0.1", port, PROCS)
print("MODE:" + json.dumps(result))
sys.exit(0 if result.get("rps") else 1)
"""


def run_mode(procs: int, clients: int, duration: float) -> dict:
    script = _MODE_SCRIPT.format(procs=procs, clients=clients,
                                 duration=duration)
    env = dict(os.environ)
    if procs > 1:
        # process-per-core deployment config: each worker keeps its
        # intra-op parallelism to itself instead of N workers' XLA /
        # BLAS / prep pools all fighting for every core (without this
        # the multi-process leg measures thread thrash, not scaling)
        per = max(1, (os.cpu_count() or procs) // procs)
        env.update(REPORTER_TPU_PREP_THREADS=str(per),
                   OMP_NUM_THREADS=str(per),
                   OPENBLAS_NUM_THREADS=str(per),
                   XLA_FLAGS=(env.get("XLA_FLAGS", "") +
                              " --xla_cpu_multi_thread_eigen=false"
                              ).strip())
    proc = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                          capture_output=True, text=True, timeout=900,
                          env=env)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("MODE:")]
    if proc.returncode != 0 or not lines:
        raise SystemExit(f"procs={procs} leg failed rc={proc.returncode}"
                         f": {(proc.stdout + proc.stderr)[-2000:]}")
    return json.loads(lines[-1][len("MODE:"):])


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="prefork_bench",
                                     description=__doc__.splitlines()[0])
    parser.add_argument("--procs", type=int, default=2)
    parser.add_argument("--clients", type=int, default=8)
    parser.add_argument("--duration", type=float, default=10.0)
    parser.add_argument("--min-ratio", type=float, default=0.0,
                        help="fail below this multi/single ratio "
                        "(bench-box acceptance: 1.6; default no gate)")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    single = run_mode(1, args.clients, args.duration)
    multi = run_mode(args.procs, args.clients, args.duration)
    ratio = round(multi["rps"] / single["rps"], 3) if single["rps"] \
        else None
    art = {"kind": "prefork_bench", "procs": args.procs,
           "clients": args.clients, "duration_s": args.duration,
           "single_rps": single["rps"], "multi_rps": multi["rps"],
           "ratio": ratio}
    body = json.dumps(art, separators=(",", ":"))
    print(body)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(body)
    if args.min_ratio and (ratio is None or ratio < args.min_ratio):
        sys.stderr.write(f"prefork_bench: FAIL: ratio {ratio} < floor "
                         f"{args.min_ratio}\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
