#!/usr/bin/env python
"""bigreplay: the production-fidelity multi-city chaos replay harness.

The SNIPPETS.md target this repo reproduces was a ~1M-probe city replay
at >=99% segment-ID agreement; nothing at that fidelity existed in CI.
This harness generates a seeded synthetic METRO — three city profiles
with distinct failure textures:

  urban   dense 150 m grid, 12 m canyon noise (candidate ambiguity),
          1 Hz probes
  rural   sparse 800 m grid, light noise, 0.2 Hz probes (long gaps —
          the jitter/SKIP machinery's worst case)
  queue   mid grid with injected stop-and-go dwells (the queue-length
          detector's case)

— and replays it through REAL multi-writer streaming workers (one
ReporterService per city shared by N writer workers, per-writer epoch
tile names, one SHARED histogram datastore fed by every worker's tee)
twice: a clean leg, then a chaos leg under a bounded
``REPORTER_TPU_FAULTS`` storm with the dead-letter replayer armed.

Asserted, not just measured:

  * segment-ID agreement between the serving decode path and the
    pure-numpy oracle (cpu_ref) >= ``--min-agreement`` on a trace sample
  * END-TO-END EXACTLY-ONCE: the tee-fed datastore equals a fresh store
    built from the sink's final tile trees cell-for-cell (count + speed
    sums) — every observation that reached a tile is in the datastore
    exactly once, storms and replays included; then the whole sink tree
    is re-ingested into the SAME store and must change NOTHING (the
    manifest ingest ledger dedupes every flush)
  * empty dead-letter spools after the replayer drains (the storm is
    bounded, so recovery must complete)
  * throughput, chaos over clean, written to the artifact —
    ``tools/perf_gate.py --bigreplay`` gates the ratio so robustness
    machinery never silently costs performance

CI runs this smoke-scaled (``--probes 3000``); the paper-scale run is
``python tools/bigreplay.py --probes 1000000 --writers 4``.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("REPORTER_TPU_PLATFORM", "cpu")  # never probe a chip

FMT = r",sv,\|,0,1,2,3,4"  # uuid|lat|lon|time|accuracy

#: the bounded default storm: every error-kind failure domain fires,
#: every site's storm ENDS (#limit), so the run must recover — crash
#: kinds live in tools/chaos.py where a subprocess can absorb them
DEFAULT_FAULTS = ",".join([
    "decode.dispatch=error:0.5@3#10",
    "matcher.assemble=error:0.05@5#6",
    "native.prep=error:0.3@13#10",
    "matcher.submit=error:0.15@11#25",
    "egress.http=error:0.4@7#40",
    "datastore.commit=error:0.05@17#4",
])

#: (name, grid kwargs, noise_m, sample_period_s, queue_dwell) — the
#: three production textures; anchors far apart so tile indexes never
#: collide across cities
CITY_PROFILES = [
    ("urban", dict(rows=14, cols=14, spacing_m=150.0, seed=21,
                   lat0=14.60, lon0=120.98), 12.0, 1.0, False),
    ("rural", dict(rows=7, cols=7, spacing_m=800.0, seed=22,
                   lat0=14.90, lon0=121.40), 4.0, 5.0, False),
    ("queue", dict(rows=10, cols=10, spacing_m=200.0, seed=23,
                   lat0=14.30, lon0=120.60), 5.0, 1.0, True),
]


def log(msg: str) -> None:
    print(f"bigreplay: {msg}", flush=True)


def fail(msg: str) -> int:
    sys.stderr.write(f"bigreplay: FAIL: {msg}\n")
    return 1


def _inject_queue(points, rng):
    """Stop-and-go: dwell the vehicle ~mid-trace for a creeping stretch
    (sub-meter steps, 2 s apart) so the queue detector sees a trailing
    slow streak; later probe times shift by the dwell."""
    if len(points) < 8:
        return points
    j = len(points) // 2
    dwell = []
    base = points[j]
    steps = int(rng.integers(6, 12))
    for k in range(steps):
        dwell.append({
            "lat": round(base["lat"] + float(rng.normal(0.0, 3e-6)), 6),
            "lon": round(base["lon"] + float(rng.normal(0.0, 3e-6)), 6),
            "time": int(base["time"] + (k + 1) * 2),
            "accuracy": base["accuracy"],
        })
    shift = steps * 2
    tail = [dict(p, time=int(p["time"] + shift)) for p in points[j + 1:]]
    return points[:j + 1] + dwell + tail


def build_metro(probes_budget: int, seed: int):
    """[(name, city, traces, lines)] totalling ~``probes_budget`` probes
    split evenly across the city profiles; fully seeded."""
    import numpy as np

    from reporter_tpu.synth import build_grid_city, generate_trace

    out = []
    per_city = probes_budget // len(CITY_PROFILES)
    for name, grid_kw, noise_m, period_s, queue in CITY_PROFILES:
        city = build_grid_city(service_road_fraction=0.0,
                               internal_fraction=0.0, **grid_kw)
        rng = np.random.default_rng(seed * 1000 + grid_kw["seed"])
        traces, lines, n = [], [], 0
        i = 0
        while n < per_city:
            tr = generate_trace(city, f"{name}-veh-{i}", rng,
                                noise_m=noise_m,
                                sample_period_s=period_s,
                                min_route_edges=8)
            i += 1
            if tr is None:
                continue
            pts = _inject_queue(tr.points, rng) if queue else tr.points
            traces.append((tr.uuid, pts))
            for p in pts:
                lines.append("|".join([tr.uuid, str(p["lat"]),
                                       str(p["lon"]), str(p["time"]),
                                       str(p["accuracy"])]))
            n += len(pts)
        out.append((name, city, traces, lines))
        log(f"city {name}: {len(traces)} traces, {n} probes")
    return out


def _shard(lines, writers: int):
    """Writer shards by uuid hash — the multihost ownership contract,
    pre-partitioned (each line's uuid is its first field)."""
    import zlib
    shards = [[] for _ in range(writers)]
    for line in lines:
        uuid = line.split("|", 1)[0]
        shards[zlib.crc32(uuid.encode()) % writers].append(line)
    return shards


def run_leg(metro, writers: int, workdir: str, faults_spec=None,
            flush_interval_s: float = 2.0):
    """One full replay of the metro through C cities x W writer workers
    (threads; one shared service per city, one shared datastore for the
    whole metro). Returns a result dict."""
    from reporter_tpu.datastore import LocalDatastore
    from reporter_tpu.matcher import SegmentMatcher
    from reporter_tpu.service.server import ReporterService
    from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
    from reporter_tpu.streaming.formatter import Formatter
    from reporter_tpu.streaming.worker import StreamWorker, inproc_submitter
    from reporter_tpu.utils import faults, metrics

    metrics.default.reset()
    store = LocalDatastore(os.path.join(workdir, "store"))

    def tee(_tile, segments, ingest_key=None, _ds=store):
        return _ds.ingest_segments(segments, ingest_key=ingest_key)

    workers, threads, out_dirs, spools = [], [], [], []
    total_probes = 0
    for ci, (name, city, _traces, lines) in enumerate(metro):
        out_dir = os.path.join(workdir, f"out-{name}")
        out_dirs.append(out_dir)
        service = ReporterService(SegmentMatcher(net=city),
                                  threshold_sec=15, max_batch=64,
                                  max_wait_ms=5.0)
        for w, shard in enumerate(_shard(lines, writers)):
            if not shard:
                continue
            spool = os.path.join(workdir, f"spool-{name}-w{w}")
            spools.append(spool)
            anon = Anonymiser(TileSink(out_dir, deadletter=spool),
                              privacy=1, quantisation=3600,
                              source=f"big-{name}", tee=tee)
            anon.writer_id = f"w{w}"
            worker = StreamWorker(
                Formatter.from_config(FMT), inproc_submitter(service),
                anon, reports="0,1,2", transitions="0,1,2",
                flush_interval_s=flush_interval_s,
                submit_many=service.report_many,
                report_flush_interval_s=0.5,
                circuit_probe=lambda m=service.matcher: m.circuit.state,
                degraded_probe=service.matcher.open_domains,
                datastore=store)
            # per-matcher quarantine wiring: the utils.spool module
            # globals are last-writer-wins, so in this multi-worker
            # process a poisoned trace must be routed explicitly to a
            # spool of ITS OWN city (its graph) — the first writer's,
            # since the shared matcher can't know which writer submitted
            if service.matcher.quarantine_spool is None:
                service.matcher.quarantine_spool = worker._trace_spool
            workers.append(worker)
            total_probes += len(shard)
            threads.append(threading.Thread(
                target=worker.run, args=(iter(shard),), daemon=True))

    if faults_spec:
        faults.configure(faults_spec)
    t0 = time.monotonic()
    fired = {}
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fired = faults.fired_counts()
    finally:
        faults.clear()
    wall = time.monotonic() - t0

    # post-storm drain: the storm is bounded, so every spool must now
    # drain clean (workers already ran their own paced + final drains;
    # this sweep covers entries whose backoff outlived the stream). A
    # FULL worker drain, not a bare spool sweep: replayed traces forward
    # segments into the anonymiser, which must then flush them to tiles
    # + tee or they would strand unobserved in its slices
    leftover = 0
    for worker in workers:
        if worker.drainer is not None:
            worker.drain()
    for spool in spools:
        from reporter_tpu.utils.spool import backlog
        b = backlog(spool)
        t = backlog(os.path.join(spool, ".traces"))
        leftover += b["files"] + t["files"]

    snap = metrics.default.snapshot()["counters"]
    return {
        "wall_s": round(wall, 3),
        "probes": total_probes,
        "probes_per_s": round(total_probes / wall, 1) if wall else None,
        "workers": len(workers),
        "store": store,
        "out_dirs": out_dirs,
        "spools": spools,
        "spooled_left": leftover,
        "fired": fired,
        "parse_failures": sum(w.parse_failures for w in workers),
        "counters": {k: v for k, v in sorted(snap.items())
                     if k.startswith(("egress.", "batch.", "replay.",
                                      "matcher.circuit", "deadletter.",
                                      "datastore.ingest.deduped",
                                      "matcher.assemble.quarantined"))},
    }


def _store_cells(store):
    """The exactly-once parity comparand — ONE definition, shared with
    chaos lease_kill (HistogramStore.merged_cells)."""
    return store.merged_cells()


def check_exactly_once(leg, workdir: str):
    """tee store == fresh store over the sink trees, and re-ingesting the
    sink trees into the tee store changes nothing (ledger dedupe)."""
    from reporter_tpu.datastore import LocalDatastore, ingest_dir

    file_store = LocalDatastore(os.path.join(workdir, "file_store"))
    for out_dir in leg["out_dirs"]:
        ingest_dir(file_store, out_dir)
    tee_cells = _store_cells(leg["store"])
    file_cells = _store_cells(file_store)
    if tee_cells != file_cells:
        only_tee = len(set(tee_cells) - set(file_cells))
        only_file = len(set(file_cells) - set(tee_cells))
        differ = sum(1 for k in set(tee_cells) & set(file_cells)
                     if tee_cells[k] != file_cells[k])
        return (None, f"tee store != tile-file store: {only_tee} cells "
                f"only in tee, {only_file} only in files, {differ} "
                f"differ — observations were lost or duplicated")
    # the double-ingest proof: every flush is already in the ledger
    before = _store_cells(leg["store"])
    deduped_files = 0
    for out_dir in leg["out_dirs"]:
        got = ingest_dir(leg["store"], out_dir)
        deduped_files += got["files"]
        if got["rows"]:
            return (None, f"re-ingest of {out_dir} appended {got['rows']} "
                    "rows — the ledger failed to dedupe")
    if _store_cells(leg["store"]) != before:
        return (None, "re-ingest changed store contents despite 0 rows")
    return ({"cells": len(tee_cells),
             "count_total": sum(c for c, _s in tee_cells.values()),
             "reingest_files_deduped": deduped_files}, None)


def check_agreement(metro, sample: int, seed: int):
    """Device decode path vs the pure-numpy oracle on a per-city trace
    sample; returns (agreement_ratio, traces_compared, ids_compared)."""
    import numpy as np

    from reporter_tpu.matcher import SegmentMatcher

    class OracleMatcher(SegmentMatcher):
        """The serving matcher with decode pinned to the numpy oracle
        (the decode-domain fallback path, forced)."""

        def _dispatch_stage(self, batch, sigma, beta, decode_batch):
            return self._decode_numpy_chunk(batch, sigma, beta)

    rng = np.random.default_rng(seed)
    agree = total = traces_n = 0
    per_city = max(1, sample // len(metro))
    for name, city, traces, _lines in metro:
        picks = rng.choice(len(traces), size=min(per_city, len(traces)),
                           replace=False)
        reqs = []
        for i in picks:
            uuid, pts = traces[int(i)]
            reqs.append({"uuid": uuid, "trace": pts,
                         "match_options": {"mode": "auto",
                                           "report_levels": [0, 1, 2],
                                           "transition_levels": [0, 1, 2]}})
        device = SegmentMatcher(net=city).match_many(reqs)
        oracle = OracleMatcher(net=city, use_native=False).match_many(reqs)
        for rd, ro in zip(device, oracle):
            sd = [s["segment_id"] for s in rd["segments"]
                  if "segment_id" in s]
            so = [s["segment_id"] for s in ro["segments"]
                  if "segment_id" in s]
            n = max(len(sd), len(so))
            total += n
            agree += sum(1 for a, b in zip(sd, so) if a == b)
            traces_n += 1
    return (agree / total if total else 1.0), traces_n, total


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="bigreplay", description=__doc__.splitlines()[0])
    parser.add_argument("--probes", type=int, default=1_000_000,
                        help="total probe budget across the metro "
                        "(default the paper-scale 1M; CI smoke uses "
                        "~3000)")
    parser.add_argument("--writers", type=int, default=2,
                        help="writer workers per city (default 2)")
    parser.add_argument("--seed", type=int, default=9)
    parser.add_argument("--faults", default=DEFAULT_FAULTS,
                        help="REPORTER_TPU_FAULTS spec for the chaos "
                        "leg (default: a bounded every-domain storm)")
    parser.add_argument("--agreement-sample", type=int, default=30,
                        help="traces sampled for the oracle-agreement "
                        "gate (default 30)")
    parser.add_argument("--min-agreement", type=float, default=0.99)
    parser.add_argument("--out", help="artifact JSON path")
    parser.add_argument("--keep", help="keep working dirs under this "
                        "path instead of a temp dir")
    args = parser.parse_args(argv)

    # the replayer must be live for the chaos leg (paced + end-of-stream
    # drains); generous attempt budget so a bounded storm cannot
    # quarantine entries it would have recovered
    os.environ.setdefault("REPORTER_TPU_REPLAY_INTERVAL_S", "0.5")
    os.environ.setdefault("REPORTER_TPU_REPLAY_ATTEMPTS", "10")

    metro = build_metro(args.probes, args.seed)

    agreement, traces_n, ids_n = check_agreement(
        metro, args.agreement_sample, args.seed)
    log(f"oracle agreement: {agreement:.4f} over {traces_n} traces "
        f"({ids_n} segment ids)")
    if agreement < args.min_agreement:
        return fail(f"segment-ID agreement {agreement:.4f} < "
                    f"{args.min_agreement} vs the numpy oracle")

    tmp = args.keep or tempfile.mkdtemp(prefix="bigreplay-")
    try:
        clean_dir = os.path.join(tmp, "clean")
        chaos_dir = os.path.join(tmp, "chaos")
        os.makedirs(clean_dir, exist_ok=True)
        os.makedirs(chaos_dir, exist_ok=True)

        log(f"clean leg: {args.writers} writers/city x "
            f"{len(metro)} cities")
        clean = run_leg(metro, args.writers, clean_dir)
        log(f"clean: {clean['probes']} probes in {clean['wall_s']} s "
            f"({clean['probes_per_s']}/s)")
        if clean["parse_failures"]:
            return fail(f"clean leg parse failures: "
                        f"{clean['parse_failures']}")

        log(f"chaos leg under storm: {args.faults}")
        chaos = run_leg(metro, args.writers, chaos_dir,
                        faults_spec=args.faults)
        log(f"chaos: {chaos['probes']} probes in {chaos['wall_s']} s "
            f"({chaos['probes_per_s']}/s); counters: "
            f"{json.dumps(chaos['counters'])}")

        if chaos["spooled_left"]:
            return fail(f"{chaos['spooled_left']} dead-letter entries "
                        "left after the replayer drained")
        for leg_name, leg in (("clean", clean), ("chaos", chaos)):
            workdir = clean_dir if leg_name == "clean" else chaos_dir
            verdict, err = check_exactly_once(leg, workdir)
            if err:
                return fail(f"{leg_name} leg: {err}")
            leg["exactly_once"] = verdict
            log(f"{leg_name} exactly-once ok: {verdict}")

        ratio = (chaos["probes_per_s"] / clean["probes_per_s"]
                 if clean["probes_per_s"] else None)
        artifact = {
            "kind": "bigreplay",
            "probes": args.probes,
            "writers": args.writers,
            "cities": [name for name, *_ in metro],
            "seed": args.seed,
            "agreement": round(agreement, 5),
            "agreement_traces": traces_n,
            "min_agreement": args.min_agreement,
            "faults": args.faults,
            "clean": {k: clean[k] for k in
                      ("wall_s", "probes", "probes_per_s", "workers",
                       "exactly_once")},
            "chaos": {k: chaos[k] for k in
                      ("wall_s", "probes", "probes_per_s", "workers",
                       "exactly_once", "counters", "fired")},
            "fault_throughput_ratio": round(ratio, 4) if ratio else None,
        }
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(artifact, f, indent=2)
            log(f"artifact -> {args.out}")
        log(f"ok: agreement {agreement:.4f}, exactly-once proven on "
            f"both legs, fault throughput ratio {ratio}")
        return 0
    finally:
        if not args.keep:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
