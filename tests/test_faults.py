"""Failure domains: the deterministic failpoint layer, the circuit
breaker, the bounded batcher requeue, flush-epoch exactly-once egress,
and the /health probe (ISSUE 5). The chaos harness (tools/chaos.py)
drives the same mechanisms end-to-end; these tests pin each one in
isolation."""
import json
import os

import pytest

from reporter_tpu.utils import faults, metrics
from reporter_tpu.utils.circuit import CircuitBreaker


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends with the fault table empty."""
    faults.clear()
    yield
    faults.clear()


class TestSpecParsing:
    def test_full_grammar(self):
        sites = faults.parse_spec(
            "native.prep=error:0.5@7#10+3,egress.http=timeout")
        fp = sites["native.prep"]
        assert (fp.kind, fp.prob, fp.seed, fp.limit, fp.skip) == \
            ("error", 0.5, 7, 10, 3)
        fp = sites["egress.http"]
        assert (fp.kind, fp.prob, fp.seed, fp.limit, fp.skip) == \
            ("timeout", 1.0, 0, None, 0)

    def test_suffixes_any_order(self):
        a = faults.parse_spec("s=crash+669#1")["s"]
        b = faults.parse_spec("s=crash#1+669")["s"]
        assert (a.limit, a.skip) == (b.limit, b.skip) == (1, 669)

    @pytest.mark.parametrize("bad", [
        "nope", "site=explode", "site=error:2.0", "site=error:x",
        "=error", "site=error@seed"])
    def test_malformed_rejected(self, bad):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)

    def test_configure_and_clear(self):
        faults.configure("a.b=error")
        assert faults.active_spec() == "a.b=error"
        faults.clear()
        assert faults.active_spec() is None
        faults.failpoint("a.b")  # disarmed: must not raise


class TestFiring:
    def test_disarmed_is_noop(self):
        faults.failpoint("anything")

    def test_unlisted_site_is_noop(self):
        faults.configure("other=error")
        faults.failpoint("this.one")

    def test_error_raises_fault_error(self):
        faults.configure("s=error")
        with pytest.raises(faults.FaultError):
            faults.failpoint("s")

    def test_timeout_is_both_kinds(self):
        faults.configure("s=timeout")
        with pytest.raises(TimeoutError):
            faults.failpoint("s")
        with pytest.raises(faults.FaultError):
            faults.failpoint("s")

    def test_limit_bounds_the_storm(self):
        faults.configure("s=error#2")
        for _ in range(2):
            with pytest.raises(faults.FaultError):
                faults.failpoint("s")
        faults.failpoint("s")  # spent
        assert faults.fired_counts() == {"s": 2}

    def test_skip_positions_the_fault(self):
        faults.configure("s=error+3#1")
        for _ in range(3):
            faults.failpoint("s")
        with pytest.raises(faults.FaultError):
            faults.failpoint("s")
        faults.failpoint("s")  # limit 1: one fire only

    def test_probability_replays_bit_identically(self):
        def run():
            faults.configure("s=error:0.4@42")
            fired = []
            for i in range(50):
                try:
                    faults.failpoint("s")
                    fired.append(False)
                except faults.FaultError:
                    fired.append(True)
            return fired
        a, b = run(), run()
        assert a == b
        assert any(a) and not all(a)

    def test_partial_fires_only_after_hook(self):
        faults.configure("s=partial")
        faults.failpoint("s")  # before-hook: partial must not fire
        with pytest.raises(faults.FaultError):
            faults.failpoint("s", after=True)

    def test_error_fires_only_before_hook(self):
        faults.configure("s=error")
        faults.failpoint("s", after=True)
        with pytest.raises(faults.FaultError):
            faults.failpoint("s")


class TestCircuitBreaker:
    def _breaker(self, **kw):
        now = [0.0]
        reg = metrics.Registry()
        kw.setdefault("threshold", 3)
        kw.setdefault("cooldown_s", 10.0)
        cb = CircuitBreaker("test.circuit", clock=lambda: now[0],
                            registry=reg, **kw)
        return cb, now, reg

    def test_opens_after_threshold_consecutive_failures(self):
        cb, _now, reg = self._breaker()
        for _ in range(2):
            cb.record_failure()
        assert cb.state == "closed" and cb.allow()
        cb.record_failure()
        assert cb.state == "open"
        assert not cb.allow()
        assert reg.snapshot()["counters"]["test.circuit.opened"] == 1

    def test_success_resets_the_count(self):
        cb, _now, _reg = self._breaker()
        cb.record_failure()
        cb.record_failure()
        cb.record_success()
        cb.record_failure()
        cb.record_failure()
        assert cb.state == "closed"

    def test_half_open_admits_one_probe(self):
        cb, now, reg = self._breaker()
        for _ in range(3):
            cb.record_failure()
        assert not cb.allow()
        now[0] = 10.0
        assert cb.state == "half_open"
        assert cb.allow()       # the probe
        assert not cb.allow()   # only one at a time
        cb.record_success()
        assert cb.state == "closed" and cb.allow()
        counters = reg.snapshot()["counters"]
        assert counters["test.circuit.probes"] == 1
        assert counters["test.circuit.closed"] == 1

    def test_failed_probe_reopens_for_another_cooldown(self):
        cb, now, _reg = self._breaker()
        for _ in range(3):
            cb.record_failure()
        now[0] = 10.0
        assert cb.allow()
        cb.record_failure()
        assert cb.state == "open"
        assert not cb.allow()
        now[0] = 19.9
        assert not cb.allow()
        now[0] = 20.0
        assert cb.allow()

    def test_snapshot_shape(self):
        cb, now, _reg = self._breaker()
        snap = cb.snapshot()
        assert snap == {"state": "closed", "consecutive_failures": 0,
                        "threshold": 3, "cooldown_remaining_s": 0.0}
        for _ in range(3):
            cb.record_failure()
        now[0] = 4.0
        snap = cb.snapshot()
        assert snap["state"] == "open"
        assert snap["cooldown_remaining_s"] == pytest.approx(6.0)


class TestHealthAction:
    @pytest.fixture(scope="class")
    def city(self):
        from reporter_tpu.synth import build_grid_city
        return build_grid_city(rows=6, cols=6, spacing_m=200.0, seed=5,
                               service_road_fraction=0.0,
                               internal_fraction=0.0)

    def test_healthy_service_reports_200(self, city):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        service = ReporterService(SegmentMatcher(net=city))
        code, body = service.health()
        body = json.loads(body)
        assert code == 200
        assert body["status"] == "ok"
        assert body["graph"]["loaded"] and body["graph"]["edges"] > 0
        assert body["native"]["status"] in ("native", "fallback")
        assert body["circuit"]["state"] == "closed"
        assert body["datastore"] == {"status": "absent"}
        assert body["faults"] is None

    def test_open_circuit_degrades_to_503(self, city):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        service = ReporterService(SegmentMatcher(net=city))
        for _ in range(service.matcher.circuit.threshold):
            service.matcher.circuit.record_failure()
        code, body = service.health()
        assert code == 503
        assert json.loads(body)["status"] == "degraded"

    def test_datastore_health(self, city, tmp_path):
        from reporter_tpu.datastore import LocalDatastore
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        ds = LocalDatastore(str(tmp_path / "store"))
        service = ReporterService(SegmentMatcher(net=city), datastore=ds)
        code, body = service.health()
        assert code == 200
        assert json.loads(body)["datastore"]["status"] == "ok"

    def test_health_over_http(self, city):
        import socket
        import urllib.error
        import urllib.request
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService, serve
        service = ReporterService(SegmentMatcher(net=city))
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        httpd = serve(service, "127.0.0.1", port)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=30) as r:
                assert json.loads(r.read())["status"] == "ok"
            for _ in range(service.matcher.circuit.threshold):
                service.matcher.circuit.record_failure()
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=30)
            assert exc.value.code == 503
            assert json.loads(exc.value.read())["status"] == "degraded"
        finally:
            httpd.shutdown()


class TestFailpointSites:
    """The named sites actually sit where the docs say they sit."""

    def test_state_save_failpoint(self, tmp_path):
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        from reporter_tpu.streaming.batcher import PointBatcher
        from reporter_tpu.streaming.state import StateStore
        store = StateStore(str(tmp_path / "s.bin"))
        b = PointBatcher(lambda t: None, lambda k, s: None)
        a = Anonymiser(TileSink(str(tmp_path / "t")), privacy=1,
                       quantisation=3600)
        faults.configure("state.save=error")
        with pytest.raises(faults.FaultError):
            store.save(b, a)
        assert not os.path.exists(str(tmp_path / "s.bin"))
        faults.clear()
        store.save(b, a)
        assert os.path.exists(str(tmp_path / "s.bin"))

    def test_datastore_commit_failpoint(self, tmp_path):
        import numpy as np
        from reporter_tpu.datastore import LocalDatastore
        from reporter_tpu.datastore.schema import ObservationBatch
        ds = LocalDatastore(str(tmp_path / "store"))
        obs = ObservationBatch(
            segment_id=np.array([1 << 25], dtype=np.int64),
            next_id=np.array([2 << 25], dtype=np.int64),
            duration_s=np.array([30.0]),
            count=np.array([1], dtype=np.int64),
            length_m=np.array([500], dtype=np.int64),
            queue_m=np.array([0], dtype=np.int64),
            min_ts=np.array([1500000000], dtype=np.int64),
            max_ts=np.array([1500000030], dtype=np.int64))
        faults.configure("datastore.commit=error")
        with pytest.raises(faults.FaultError):
            ds.ingest(obs)
        faults.clear()
        assert ds.ingest(obs) == 1

    def test_worker_post_egress_failpoint(self, tmp_path):
        """worker.post_egress sits in THE window the flush-epoch
        machinery exists for: after the sink ack, before the epoch
        marker — a fault there must leave the epoch uncommitted so a
        restore re-emits under the same deterministic names."""
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        from reporter_tpu.streaming.formatter import Formatter
        from reporter_tpu.streaming.state import StateStore
        from reporter_tpu.streaming.worker import StreamWorker
        state = StateStore(str(tmp_path / "s.bin"), interval_s=0.0)
        worker = StreamWorker(
            Formatter.from_config(r",sv,\|,0,1,2,3,4"), lambda t: None,
            Anonymiser(TileSink(str(tmp_path / "t")), privacy=1,
                       quantisation=3600),
            flush_interval_s=1e9, state=state)
        faults.configure("worker.post_egress=error")
        try:
            with pytest.raises(faults.FaultError):
                worker._flush_tiles()
        finally:
            faults.clear()
        assert state.committed_epoch() == -1

    def test_egress_partial_spools_despite_committed_write(self, tmp_path):
        """kind=partial: the tile REACHES the file sink, yet the caller
        sees failure and spools — the committed-but-unacked window."""
        from reporter_tpu.streaming.anonymiser import TileSink
        sink = TileSink(str(tmp_path / "out"))
        faults.configure("egress.http=partial")
        assert sink.store("1_2/0/1", "f", "payload") is False
        assert (tmp_path / "out" / "1_2" / "0" / "1" / "f").exists()
        assert (tmp_path / "out" / ".deadletter" / "1_2" / "0" / "1"
                / "f").exists()
