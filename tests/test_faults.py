"""Failure domains: the deterministic failpoint layer, the circuit
breaker, the bounded batcher requeue, flush-epoch exactly-once egress,
and the /health probe (ISSUE 5). The chaos harness (tools/chaos.py)
drives the same mechanisms end-to-end; these tests pin each one in
isolation."""
import json
import os

import pytest

from reporter_tpu.utils import faults, metrics
from reporter_tpu.utils.circuit import CircuitBreaker


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends with the fault table empty."""
    faults.clear()
    yield
    faults.clear()


class TestSpecParsing:
    def test_full_grammar(self):
        sites = faults.parse_spec(
            "native.prep=error:0.5@7#10+3,egress.http=timeout")
        fp = sites["native.prep"]
        assert (fp.kind, fp.prob, fp.seed, fp.limit, fp.skip) == \
            ("error", 0.5, 7, 10, 3)
        fp = sites["egress.http"]
        assert (fp.kind, fp.prob, fp.seed, fp.limit, fp.skip) == \
            ("timeout", 1.0, 0, None, 0)

    def test_suffixes_any_order(self):
        a = faults.parse_spec("s=crash+669#1")["s"]
        b = faults.parse_spec("s=crash#1+669")["s"]
        assert (a.limit, a.skip) == (b.limit, b.skip) == (1, 669)

    @pytest.mark.parametrize("bad", [
        "nope", "site=explode", "site=error:2.0", "site=error:x",
        "=error", "site=error@seed"])
    def test_malformed_rejected(self, bad):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)

    def test_configure_and_clear(self):
        faults.configure("a.b=error")
        assert faults.active_spec() == "a.b=error"
        faults.clear()
        assert faults.active_spec() is None
        faults.failpoint("a.b")  # disarmed: must not raise


class TestFiring:
    def test_disarmed_is_noop(self):
        faults.failpoint("anything")

    def test_unlisted_site_is_noop(self):
        faults.configure("other=error")
        faults.failpoint("this.one")

    def test_error_raises_fault_error(self):
        faults.configure("s=error")
        with pytest.raises(faults.FaultError):
            faults.failpoint("s")

    def test_timeout_is_both_kinds(self):
        faults.configure("s=timeout")
        with pytest.raises(TimeoutError):
            faults.failpoint("s")
        with pytest.raises(faults.FaultError):
            faults.failpoint("s")

    def test_limit_bounds_the_storm(self):
        faults.configure("s=error#2")
        for _ in range(2):
            with pytest.raises(faults.FaultError):
                faults.failpoint("s")
        faults.failpoint("s")  # spent
        assert faults.fired_counts() == {"s": 2}

    def test_skip_positions_the_fault(self):
        faults.configure("s=error+3#1")
        for _ in range(3):
            faults.failpoint("s")
        with pytest.raises(faults.FaultError):
            faults.failpoint("s")
        faults.failpoint("s")  # limit 1: one fire only

    def test_probability_replays_bit_identically(self):
        def run():
            faults.configure("s=error:0.4@42")
            fired = []
            for i in range(50):
                try:
                    faults.failpoint("s")
                    fired.append(False)
                except faults.FaultError:
                    fired.append(True)
            return fired
        a, b = run(), run()
        assert a == b
        assert any(a) and not all(a)

    def test_partial_fires_only_after_hook(self):
        faults.configure("s=partial")
        faults.failpoint("s")  # before-hook: partial must not fire
        with pytest.raises(faults.FaultError):
            faults.failpoint("s", after=True)

    def test_error_fires_only_before_hook(self):
        faults.configure("s=error")
        faults.failpoint("s", after=True)
        with pytest.raises(faults.FaultError):
            faults.failpoint("s")


class TestCircuitBreaker:
    def _breaker(self, **kw):
        now = [0.0]
        reg = metrics.Registry()
        kw.setdefault("threshold", 3)
        kw.setdefault("cooldown_s", 10.0)
        cb = CircuitBreaker("test.circuit", clock=lambda: now[0],
                            registry=reg, **kw)
        return cb, now, reg

    def test_opens_after_threshold_consecutive_failures(self):
        cb, _now, reg = self._breaker()
        for _ in range(2):
            cb.record_failure()
        assert cb.state == "closed" and cb.allow()
        cb.record_failure()
        assert cb.state == "open"
        assert not cb.allow()
        assert reg.snapshot()["counters"]["test.circuit.opened"] == 1

    def test_success_resets_the_count(self):
        cb, _now, _reg = self._breaker()
        cb.record_failure()
        cb.record_failure()
        cb.record_success()
        cb.record_failure()
        cb.record_failure()
        assert cb.state == "closed"

    def test_half_open_admits_one_probe(self):
        cb, now, reg = self._breaker()
        for _ in range(3):
            cb.record_failure()
        assert not cb.allow()
        now[0] = 10.0
        assert cb.state == "half_open"
        assert cb.allow()       # the probe
        assert not cb.allow()   # only one at a time
        cb.record_success()
        assert cb.state == "closed" and cb.allow()
        counters = reg.snapshot()["counters"]
        assert counters["test.circuit.probes"] == 1
        assert counters["test.circuit.closed"] == 1

    def test_failed_probe_reopens_for_another_cooldown(self):
        cb, now, _reg = self._breaker()
        for _ in range(3):
            cb.record_failure()
        now[0] = 10.0
        assert cb.allow()
        cb.record_failure()
        assert cb.state == "open"
        assert not cb.allow()
        now[0] = 19.9
        assert not cb.allow()
        now[0] = 20.0
        assert cb.allow()

    def test_snapshot_shape(self):
        cb, now, _reg = self._breaker()
        snap = cb.snapshot()
        assert snap == {"state": "closed", "consecutive_failures": 0,
                        "threshold": 3, "cooldown_remaining_s": 0.0}
        for _ in range(3):
            cb.record_failure()
        now[0] = 4.0
        snap = cb.snapshot()
        assert snap["state"] == "open"
        assert snap["cooldown_remaining_s"] == pytest.approx(6.0)


class TestHealthAction:
    @pytest.fixture(scope="class")
    def city(self):
        from reporter_tpu.synth import build_grid_city
        return build_grid_city(rows=6, cols=6, spacing_m=200.0, seed=5,
                               service_road_fraction=0.0,
                               internal_fraction=0.0)

    def test_healthy_service_reports_200(self, city):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        service = ReporterService(SegmentMatcher(net=city))
        code, body = service.health()
        body = json.loads(body)
        assert code == 200
        assert body["status"] == "ok"
        assert body["graph"]["loaded"] and body["graph"]["edges"] > 0
        assert body["native"]["status"] in ("native", "fallback")
        assert body["circuit"]["state"] == "closed"
        assert body["datastore"] == {"status": "absent"}
        assert body["faults"] is None

    def test_open_circuit_degrades_to_503(self, city):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        service = ReporterService(SegmentMatcher(net=city))
        for _ in range(service.matcher.circuit.threshold):
            service.matcher.circuit.record_failure()
        code, body = service.health()
        assert code == 503
        assert json.loads(body)["status"] == "degraded"

    def test_datastore_health(self, city, tmp_path):
        from reporter_tpu.datastore import LocalDatastore
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        ds = LocalDatastore(str(tmp_path / "store"))
        service = ReporterService(SegmentMatcher(net=city), datastore=ds)
        code, body = service.health()
        assert code == 200
        assert json.loads(body)["datastore"]["status"] == "ok"

    def test_health_over_http(self, city):
        import socket
        import urllib.error
        import urllib.request
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService, serve
        service = ReporterService(SegmentMatcher(net=city))
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        httpd = serve(service, "127.0.0.1", port)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=30) as r:
                assert json.loads(r.read())["status"] == "ok"
            for _ in range(service.matcher.circuit.threshold):
                service.matcher.circuit.record_failure()
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=30)
            assert exc.value.code == 503
            assert json.loads(exc.value.read())["status"] == "degraded"
        finally:
            httpd.shutdown()


class TestFailpointSites:
    """The named sites actually sit where the docs say they sit."""

    def test_admission_gate_failpoint_fails_open(self):
        """admission.gate sits at the TOP of the gate's admit path, and
        a fault there fails OPEN: the request is admitted (a broken
        sensor must degrade to serve-everything, never to
        shed-everything) and the failure is counted."""
        from reporter_tpu.service import admission
        from reporter_tpu.service.admission import AdmissionGate

        class Stub:
            queue_max = 0
            max_batch = 8

            def queue_depth(self):
                return 0

            def service_ewma_s(self):
                return None

        admission._reset_module()
        try:
            gate = AdmissionGate(Stub())
            before = metrics.default.counter("admission.errors")
            faults.configure("admission.gate=error#1")
            assert gate.admit() is None          # admitted, not shed
            gate.release()
            assert metrics.default.counter("admission.errors") \
                == before + 1
            faults.clear()
            assert gate.admit() is None
            gate.release()
            assert metrics.default.counter("admission.errors") \
                == before + 1
        finally:
            admission._reset_module()

    def test_city_swap_failpoint(self):
        """city.swap sits in the WIDEST swap window — candidate loaded
        and shadow-gated, old version still serving, nothing flipped:
        a fault there must abort the swap with the OLD entry still
        resident and serving, and a retry after disarm flips cleanly
        (tools/chaos.py swap_kill drives the crash kind in a real
        subprocess)."""
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.cities import CityRegistry
        from reporter_tpu.service.server import ReporterService
        from reporter_tpu.synth import build_grid_city

        def fresh_service():
            city = build_grid_city(rows=5, cols=5, spacing_m=200.0,
                                   seed=3, service_road_fraction=0.0,
                                   internal_fraction=0.0)
            return ReporterService(SegmentMatcher(net=city))

        reg = CityRegistry(loader=lambda name: (fresh_service(), None),
                           budget_bytes=1 << 30)
        old = reg.get("metro")
        flips = metrics.default.counter("swap.flips")
        faults.configure("city.swap=error#1")
        with pytest.raises(faults.FaultError):
            reg.swap("metro", lambda: (fresh_service(), None))
        faults.clear()
        # the failed swap changed nothing: same entry, still serving,
        # no flip counted
        assert reg.get("metro") is old
        assert not old._evicted
        assert metrics.default.counter("swap.flips") == flips
        # disarmed retry flips
        rec = reg.swap("metro", lambda: (fresh_service(), None))
        assert rec["result"] == "flipped"
        assert reg.get("metro") is not old
        assert metrics.default.counter("swap.flips") == flips + 1

    def test_state_save_failpoint(self, tmp_path):
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        from reporter_tpu.streaming.batcher import PointBatcher
        from reporter_tpu.streaming.state import StateStore
        store = StateStore(str(tmp_path / "s.bin"))
        b = PointBatcher(lambda t: None, lambda k, s: None)
        a = Anonymiser(TileSink(str(tmp_path / "t")), privacy=1,
                       quantisation=3600)
        faults.configure("state.save=error")
        with pytest.raises(faults.FaultError):
            store.save(b, a)
        assert not os.path.exists(str(tmp_path / "s.bin"))
        faults.clear()
        store.save(b, a)
        assert os.path.exists(str(tmp_path / "s.bin"))

    def test_datastore_commit_failpoint(self, tmp_path):
        import numpy as np
        from reporter_tpu.datastore import LocalDatastore
        from reporter_tpu.datastore.schema import ObservationBatch
        ds = LocalDatastore(str(tmp_path / "store"))
        obs = ObservationBatch(
            segment_id=np.array([1 << 25], dtype=np.int64),
            next_id=np.array([2 << 25], dtype=np.int64),
            duration_s=np.array([30.0]),
            count=np.array([1], dtype=np.int64),
            length_m=np.array([500], dtype=np.int64),
            queue_m=np.array([0], dtype=np.int64),
            min_ts=np.array([1500000000], dtype=np.int64),
            max_ts=np.array([1500000030], dtype=np.int64))
        faults.configure("datastore.commit=error")
        with pytest.raises(faults.FaultError):
            ds.ingest(obs)
        faults.clear()
        assert ds.ingest(obs) == 1

    def test_worker_post_egress_failpoint(self, tmp_path):
        """worker.post_egress sits in THE window the flush-epoch
        machinery exists for: after the sink ack, before the epoch
        marker — a fault there must leave the epoch uncommitted so a
        restore re-emits under the same deterministic names."""
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        from reporter_tpu.streaming.formatter import Formatter
        from reporter_tpu.streaming.state import StateStore
        from reporter_tpu.streaming.worker import StreamWorker
        state = StateStore(str(tmp_path / "s.bin"), interval_s=0.0)
        worker = StreamWorker(
            Formatter.from_config(r",sv,\|,0,1,2,3,4"), lambda t: None,
            Anonymiser(TileSink(str(tmp_path / "t")), privacy=1,
                       quantisation=3600),
            flush_interval_s=1e9, state=state)
        faults.configure("worker.post_egress=error")
        try:
            with pytest.raises(faults.FaultError):
                worker._flush_tiles()
        finally:
            faults.clear()
        assert state.committed_epoch() == -1

    def test_egress_partial_spools_despite_committed_write(self, tmp_path):
        """kind=partial: the tile REACHES the file sink, yet the caller
        sees failure and spools — the committed-but-unacked window."""
        from reporter_tpu.streaming.anonymiser import TileSink
        sink = TileSink(str(tmp_path / "out"))
        faults.configure("egress.http=partial")
        assert sink.store("1_2/0/1", "f", "payload") is False
        assert (tmp_path / "out" / "1_2" / "0" / "1" / "f").exists()
        assert (tmp_path / "out" / ".deadletter" / "1_2" / "0" / "1"
                / "f").exists()


def _grid_city():
    from reporter_tpu.synth import build_grid_city
    return build_grid_city(rows=6, cols=6, spacing_m=200.0, seed=5,
                           service_road_fraction=0.0,
                           internal_fraction=0.0)


def _reqs(city, n=4, seed=11):
    import numpy as np

    from reporter_tpu.synth import generate_trace
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        tr = None
        while tr is None:
            tr = generate_trace(city, f"fd-{i}", rng, noise_m=3.0,
                                min_route_edges=6)
        out.append({"uuid": tr.uuid, "trace": tr.points,
                    "match_options": {"mode": "auto",
                                      "report_levels": [0, 1, 2],
                                      "transition_levels": [0, 1, 2]}})
    return out


def _plain(result):
    return {"segments": [dict(s) for s in result["segments"]],
            "mode": result["mode"]}


class TestDecodeDomain:
    """ISSUE 9: the decode-dispatch breaker and its numpy-oracle
    fallback — bit-identical on the scan backend."""

    @pytest.fixture(scope="class")
    def city(self):
        return _grid_city()

    def test_decode_fallback_bit_identical(self, city):
        from reporter_tpu.matcher import SegmentMatcher
        m = SegmentMatcher(net=city)
        reqs = _reqs(city)
        want = [_plain(r) for r in m.match_many(reqs)]
        metrics.default.reset()
        faults.configure("decode.dispatch=error@0")
        got = [_plain(r) for r in m.match_many(reqs)]
        faults.clear()
        assert got == want
        snap = metrics.default.snapshot()["counters"]
        assert snap["matcher.circuit.decode.errors"] > 0

    def test_threshold_one_opens_then_probe_recloses(self, city,
                                                     monkeypatch):
        """threshold-1 + zero cooldown: ONE decode error opens the
        breaker; the very next chunk is the half-open probe, and its
        success re-closes — the full state walk in one call pair."""
        from reporter_tpu.matcher import SegmentMatcher
        monkeypatch.setenv("REPORTER_TPU_CIRCUIT_THRESHOLD", "1")
        monkeypatch.setenv("REPORTER_TPU_CIRCUIT_COOLDOWN_S", "0")
        m = SegmentMatcher(net=city)
        reqs = _reqs(city)
        want = [_plain(r) for r in m.match_many(reqs)]
        metrics.default.reset()
        faults.configure("decode.dispatch=error#1")
        got = [_plain(r) for r in m.match_many(reqs)]
        faults.clear()
        assert got == want
        snap = metrics.default.snapshot()["counters"]
        assert snap["matcher.circuit.decode.opened"] == 1
        after = [_plain(r) for r in m.match_many(reqs)]
        assert after == want
        snap = metrics.default.snapshot()["counters"]
        assert snap["matcher.circuit.decode.probes"] >= 1
        assert snap["matcher.circuit.decode.closed"] == 1
        assert m.circuit_decode.snapshot()["state"] == "closed"

    def test_fallback_skips_padded_filler_rows(self, city):
        """A non-pow2 chunk pads its device batch with all-SKIP filler
        rows; the oracle fallback must stay bit-identical while only
        decoding the real traces (degraded mode is exactly when
        throughput is scarcest)."""
        from reporter_tpu.matcher import SegmentMatcher
        m = SegmentMatcher(net=city)
        reqs = _reqs(city, n=5)  # pads past 5 rows on the device batch
        want = [_plain(r) for r in m.match_many(reqs)]
        faults.configure("decode.dispatch=error@0")
        got = [_plain(r) for r in m.match_many(reqs)]
        faults.clear()
        assert got == want

    def test_open_breaker_short_circuits_chunks(self, city, monkeypatch):
        from reporter_tpu.matcher import SegmentMatcher
        monkeypatch.setenv("REPORTER_TPU_CIRCUIT_THRESHOLD", "1")
        monkeypatch.setenv("REPORTER_TPU_CIRCUIT_COOLDOWN_S", "9999")
        m = SegmentMatcher(net=city)
        reqs = _reqs(city)
        want = [_plain(r) for r in m.match_many(reqs)]
        metrics.default.reset()
        faults.configure("decode.dispatch=error#1")
        m.match_many(reqs)
        faults.clear()
        assert m.circuit_decode.snapshot()["state"] == "open"
        assert m.open_domains() == ["decode.dispatch"]
        got = [_plain(r) for r in m.match_many(reqs)]
        assert got == want
        snap = metrics.default.snapshot()["counters"]
        assert snap["matcher.circuit.decode.fallback_chunks"] > 0


class TestRouteDeviceDomain:
    """ISSUE 16: the device route-kernel breaker — a route.device fault
    re-preps the chunk with host routes, bit-identically."""

    @pytest.fixture(scope="class")
    def city(self):
        return _grid_city()

    def test_route_device_fault_falls_back_bit_identical(self, city,
                                                         monkeypatch):
        pytest.importorskip("jax")
        from reporter_tpu import native
        if not native.available():
            pytest.skip("native toolchain unavailable")
        from reporter_tpu.matcher import SegmentMatcher
        reqs = _reqs(city)
        want = [_plain(r) for r in SegmentMatcher(net=city)
                .match_many(reqs)]
        monkeypatch.setenv("REPORTER_TPU_ROUTE_DEVICE", "1")
        m = SegmentMatcher(net=city)
        metrics.default.reset()
        faults.configure("route.device=error@0")
        got = [_plain(r) for r in m.match_many(reqs)]
        faults.clear()
        assert got == want
        snap = metrics.default.snapshot()["counters"]
        assert snap["route.device.errors"] > 0
        assert snap["route.device.fallback_chunks"] > 0
        # disarmed, the device path serves the next batch — same bytes
        after = [_plain(r) for r in m.match_many(reqs)]
        assert after == want

    def test_open_route_breaker_skips_device_per_chunk(self, city,
                                                       monkeypatch):
        """threshold-1 + long cooldown: one device failure opens the
        route.device breaker; subsequent chunks skip the kernel up
        front (circuit_skipped_chunks) and still serve host bytes."""
        pytest.importorskip("jax")
        from reporter_tpu import native
        if not native.available():
            pytest.skip("native toolchain unavailable")
        from reporter_tpu.matcher import SegmentMatcher
        monkeypatch.setenv("REPORTER_TPU_CIRCUIT_THRESHOLD", "1")
        monkeypatch.setenv("REPORTER_TPU_CIRCUIT_COOLDOWN_S", "9999")
        want_m = SegmentMatcher(net=city)
        reqs = _reqs(city)
        want = [_plain(r) for r in want_m.match_many(reqs)]
        monkeypatch.setenv("REPORTER_TPU_ROUTE_DEVICE", "1")
        m = SegmentMatcher(net=city)
        metrics.default.reset()
        faults.configure("route.device=error#1")
        got = [_plain(r) for r in m.match_many(reqs)]
        faults.clear()
        assert got == want
        assert m.circuit_route.snapshot()["state"] == "open"
        assert m.open_domains() == ["route.device"]
        after = [_plain(r) for r in m.match_many(reqs)]
        assert after == want
        snap = metrics.default.snapshot()["counters"]
        assert snap["route.device.circuit_skipped_chunks"] > 0


class TestAssembleDomain:
    """ISSUE 9: assemble degradation — scalar fallback + poisoned-trace
    quarantine that keeps every other trace's bytes unchanged."""

    @pytest.fixture(scope="class")
    def city(self):
        return _grid_city()

    def test_poisoned_trace_quarantined_rest_unchanged(self, city,
                                                       tmp_path):
        from reporter_tpu.matcher import SegmentMatcher
        m = SegmentMatcher(net=city, use_native=False)
        reqs = _reqs(city, n=4)
        want = [_plain(r) for r in m.match_many(reqs)]
        metrics.default.reset()
        m.quarantine_spool = str(tmp_path / "spool")
        # skip=1: the SECOND trace of the chunk poisons, proving the
        # isolation is per-trace, not per-chunk-prefix
        faults.configure("matcher.assemble=error+1#1")
        got = [_plain(r) for r in m.match_many(reqs)]
        faults.clear()
        m.quarantine_spool = None
        snap = metrics.default.snapshot()["counters"]
        assert snap["matcher.assemble.quarantined"] == 1
        poisoned = [i for i, (g, w) in enumerate(zip(got, want))
                    if g != w]
        assert len(poisoned) == 1
        assert got[poisoned[0]] == {"segments": [],
                                    "mode": want[poisoned[0]]["mode"]}
        for i, (g, w) in enumerate(zip(got, want)):
            if i != poisoned[0]:
                assert g == w
        names = os.listdir(str(tmp_path / "spool"))
        assert len(names) == 1
        with open(tmp_path / "spool" / names[0], encoding="utf-8") as f:
            body = json.load(f)
        assert body["uuid"] == reqs[poisoned[0]]["uuid"]
        assert len(body["trace"]) == len(reqs[poisoned[0]]["trace"])

    def test_native_batch_failure_degrades_to_scalar(self, city):
        from reporter_tpu import native
        from reporter_tpu.matcher import SegmentMatcher
        if not native.available():
            pytest.skip("native runtime unavailable")
        m = SegmentMatcher(net=city)
        assert m.runtime is not None
        reqs = _reqs(city)
        want = [_plain(r) for r in m.match_many(reqs)]
        metrics.default.reset()
        # one firing: the whole-batch native assembler fails, the
        # scalar fallback serves the chunk byte-identically
        faults.configure("matcher.assemble=error#1")
        got = [_plain(r) for r in m.match_many(reqs)]
        faults.clear()
        assert got == want
        snap = metrics.default.snapshot()["counters"]
        assert snap["matcher.circuit.assemble.native_errors"] == 1
        assert "matcher.assemble.quarantined" not in snap


class TestSpoolCap:
    """REPORTER_TPU_DEADLETTER_MAX_MB: oldest-first shedding."""

    def test_oldest_shed_first(self, tmp_path, monkeypatch):
        import time as _time

        from reporter_tpu.utils import spool
        metrics.default.reset()
        root = str(tmp_path / "dl")
        # ~1.5 KB cap: two 600 B entries fit, three do not
        monkeypatch.setenv("REPORTER_TPU_DEADLETTER_MAX_MB",
                           str(1500 / (1024 * 1024)))
        payload = "x" * 600
        spool.write(root, "a/oldest", payload)
        os.utime(os.path.join(root, "a/oldest"), (1, 1))
        spool.write(root, "b/mid", payload)
        os.utime(os.path.join(root, "b/mid"), (2, 2))
        spool.write(root, "c/newest", payload)
        assert not os.path.exists(os.path.join(root, "a/oldest"))
        assert os.path.exists(os.path.join(root, "b/mid"))
        assert os.path.exists(os.path.join(root, "c/newest"))
        assert metrics.default.counter("deadletter.shed") == 1

    def test_nested_spools_not_shed_or_counted(self, tmp_path,
                                               monkeypatch):
        from reporter_tpu.utils import spool
        root = str(tmp_path / "dl")
        os.makedirs(os.path.join(root, ".traces"))
        with open(os.path.join(root, ".traces", "t.json"), "w") as f:
            f.write("y" * 4000)
        monkeypatch.setenv("REPORTER_TPU_DEADLETTER_MAX_MB",
                           str(1000 / (1024 * 1024)))
        spool.write(root, "a/tile", "x" * 100)
        # the .traces entry neither counts toward the tile root's cap
        # nor gets shed by it (it is its own spool)
        assert os.path.exists(os.path.join(root, ".traces", "t.json"))
        assert os.path.exists(os.path.join(root, "a/tile"))
        assert spool.backlog(root) == {"files": 1, "bytes": 100}

    def test_restart_inherits_preexisting_spool(self, tmp_path,
                                                monkeypatch):
        """The running byte estimate seeds from disk on the first
        capped write for a root: a restarted worker inheriting a full
        spool must shed immediately, not only after writing a whole
        cap's worth of fresh entries."""
        import time as _time

        from reporter_tpu.utils import spool
        metrics.default.reset()
        root = str(tmp_path / "dl")
        os.makedirs(os.path.join(root, "old"))
        with open(os.path.join(root, "old", "stale"), "w") as f:
            f.write("x" * 1400)
        os.utime(os.path.join(root, "old", "stale"), (1, 1))
        monkeypatch.setenv("REPORTER_TPU_DEADLETTER_MAX_MB",
                           str(1500 / (1024 * 1024)))
        spool.write(root, "a/fresh", "y" * 600)
        assert not os.path.exists(os.path.join(root, "old", "stale"))
        assert os.path.exists(os.path.join(root, "a/fresh"))
        assert metrics.default.counter("deadletter.shed") == 1

    def test_unset_cap_never_sheds(self, tmp_path):
        from reporter_tpu.utils import spool
        metrics.default.reset()
        root = str(tmp_path / "dl")
        for i in range(5):
            spool.write(root, f"f{i}", "z" * 1000)
        assert spool.backlog(root)["files"] == 5
        assert metrics.default.counter("deadletter.shed") == 0


class TestDrainer:
    """The automated dead-letter replayer (streaming/drainer.py)."""

    def _response(self):
        return {"datastore": {"reports": [
            {"id": 1 << 25, "next_id": 2 << 25, "t0": 1500000000,
             "t1": 1500000030, "length": 500, "queue_length": 0}]},
            "segment_matcher": {"segments": []}}

    def _seed_trace(self, root):
        os.makedirs(os.path.join(root, ".traces"), exist_ok=True)
        with open(os.path.join(root, ".traces", "trace-1.u.json"),
                  "w", encoding="utf-8") as f:
            json.dump({"uuid": "u", "trace": [
                {"lat": 14.6, "lon": 120.98, "time": 1500000000},
                {"lat": 14.601, "lon": 120.981, "time": 1500000030}],
                "match_options": {"mode": "auto", "report_levels": [0],
                                  "transition_levels": [0]}}, f)

    def test_trace_replay_forwards_and_deletes(self, tmp_path):
        from reporter_tpu.streaming.drainer import DeadLetterDrainer
        metrics.default.reset()
        root = str(tmp_path / "dl")
        self._seed_trace(root)
        forwarded = []
        d = DeadLetterDrainer(
            root, submit=lambda body: self._response(),
            forward=lambda key, seg: forwarded.append((key, seg)))
        assert d.drain_now() == 1
        assert d.backlog() == {"tiles": 0, "traces": 0}
        assert len(forwarded) == 1 and forwarded[0][1].valid()
        assert metrics.default.counter("replay.traces.ok") == 1

    def test_backoff_then_quarantine(self, tmp_path):
        from reporter_tpu.streaming.drainer import DeadLetterDrainer
        metrics.default.reset()
        root = str(tmp_path / "dl")
        self._seed_trace(root)
        now = [0.0]
        d = DeadLetterDrainer(root, submit=lambda body: None,
                              interval_s=10.0, max_attempts=3,
                              base_backoff_s=5.0,
                              # exact-schedule test: the seeded jitter
                              # has its own pins (test_admission.py)
                              backoff_jitter=0.0,
                              clock=lambda: now[0])
        assert d.maybe_drain() == 0          # attempt 1 fails
        now[0] = 2.0
        assert d.maybe_drain() == 0          # paced: no pass yet
        assert metrics.default.counter("replay.traces.fail") == 1
        now[0] = 10.0
        d.maybe_drain()                      # due (backoff 5s passed)
        assert metrics.default.counter("replay.traces.fail") == 2
        now[0] = 20.0
        d.maybe_drain()                      # attempt 3 -> quarantine
        assert metrics.default.counter("replay.quarantined") == 1
        assert d.backlog()["traces"] == 0
        qdir = os.path.join(root, ".traces", ".quarantine")
        assert len(os.listdir(qdir)) == 1

    def test_poison_replay_loop_terminates_and_quarantines(self,
                                                           tmp_path):
        """A deterministically-poisoned body makes the in-process
        matcher re-quarantine it DURING its own replay (fresh spool
        entry, well-formed empty response). The drainer must (a) score
        that replay as a failure (quarantine-counter delta), (b) share
        the attempt budget across the re-spooled copies (uuid budget
        key + the matcher's deterministic per-uuid poison name), and
        (c) terminate drain_now via the initial-entry snapshot — the
        exact loop that used to hang worker.drain() forever."""
        from reporter_tpu.streaming.drainer import DeadLetterDrainer
        metrics.default.reset()
        root = str(tmp_path / "dl")
        self._seed_trace(root)
        tdir = os.path.join(root, ".traces")

        def poisoned_submit(body):
            # what SegmentMatcher._quarantine_trace does in-process
            with open(os.path.join(tdir, "poison.u.json"), "w",
                      encoding="utf-8") as f:
                json.dump(body, f)
            metrics.count("matcher.assemble.quarantined")
            return self._response()

        d = DeadLetterDrainer(root, submit=poisoned_submit,
                              max_attempts=3)
        assert d.drain_now() == 0      # no hang; scored as failure
        assert d.backlog()["traces"] == 2  # original + one overwrite
        for _ in range(10):
            d._pass(d.clock(), ignore_backoff=True)
        # the shared budget converged every copy into .quarantine
        assert d.backlog()["traces"] == 0
        qdir = os.path.join(tdir, ".quarantine")
        assert sorted(os.listdir(qdir)) == ["poison.u.json",
                                            "trace-1.u.json"]

    def test_budget_key_survives_dotted_uuids(self, tmp_path):
        """uuids are caller-supplied and may contain dots: two fleets'
        'fleet7.bus12' and 'fleet9.bus12' must not share one attempt
        budget (a rightmost-token parse collapsed them), while batcher
        and poison spellings of the SAME uuid must."""
        from reporter_tpu.streaming.drainer import DeadLetterDrainer
        d = DeadLetterDrainer(str(tmp_path / "dl"))
        troot = d.trace_root
        key = lambda name: d._budget_key(troot, os.path.join(troot, name))  # noqa: E731
        assert key("trace-1-000001.fleet7.bus12.json") \
            != key("trace-1-000002.fleet9.bus12.json")
        assert key("trace-1-000001.fleet7.bus12.json") \
            == key("poison.fleet7.bus12.json")
        # non-conforming names fall back to path identity
        assert key("weird") == os.path.join(troot, "weird")

    def test_paced_pass_bounded_by_max_per_pass(self, tmp_path,
                                                monkeypatch):
        """maybe_drain runs on the stream thread: a deep all-due
        backlog must cost at most MAX_PER_PASS attempts per pass."""
        from reporter_tpu.streaming import drainer as drainer_mod
        metrics.default.reset()
        root = str(tmp_path / "dl")
        os.makedirs(os.path.join(root, ".traces"))
        for i in range(5):
            with open(os.path.join(root, ".traces", f"t{i}.u{i}.json"),
                      "w", encoding="utf-8") as f:
                json.dump({"uuid": f"u{i}"}, f)
        monkeypatch.setattr(drainer_mod.DeadLetterDrainer,
                            "MAX_PER_PASS", 2)
        d = drainer_mod.DeadLetterDrainer(
            root, submit=lambda body: None, interval_s=0.0)
        d.maybe_drain()
        assert metrics.default.counter("replay.traces.fail") == 2

    def test_externally_removed_entry_drops_attempt_state(self,
                                                          tmp_path):
        """A spool file unlinked by another hand (cap shed, operator)
        must not pin its attempt/backoff entries forever."""
        from reporter_tpu.streaming.drainer import DeadLetterDrainer
        metrics.default.reset()
        root = str(tmp_path / "dl")
        self._seed_trace(root)
        d = DeadLetterDrainer(root, submit=lambda body: None,
                              max_attempts=10)
        d._pass(0.0, ignore_backoff=True)    # fails, attempt recorded
        assert len(d._attempts) == 1 and len(d._due) == 1
        os.unlink(os.path.join(root, ".traces", "trace-1.u.json"))
        d._pass(100.0, ignore_backoff=True)  # file gone -> state pruned
        assert d._attempts == {} and d._due == {}

    def test_tile_replay_reaches_sink_and_store(self, tmp_path):
        from reporter_tpu.core.types import Segment
        from reporter_tpu.datastore import LocalDatastore
        from reporter_tpu.streaming.anonymiser import TileSink
        from reporter_tpu.streaming.drainer import DeadLetterDrainer
        metrics.default.reset()
        root = str(tmp_path / "dl")
        seg = Segment(1 << 25, 2 << 25, 1500000000, 1500000030, 500, 0)
        payload = "\n".join([Segment.column_layout(),
                             seg.csv_row("AUTO", "t")])
        tile_rel = "1500000000_1500003599/0/100"
        os.makedirs(os.path.join(root, tile_rel))
        with open(os.path.join(root, tile_rel, "t.e00000003"), "w") as f:
            f.write(payload)
        out = str(tmp_path / "out")
        store = LocalDatastore(str(tmp_path / "store"))
        d = DeadLetterDrainer(root, sink=TileSink(out), datastore=store)
        assert d.drain_now() == 1
        assert os.path.exists(os.path.join(out, tile_rel, "t.e00000003"))
        assert d.backlog()["tiles"] == 0
        assert store.stats()["rows"] == 1
        # the replay recorded its ledger key: re-ingesting the sink
        # tree into the same store is a pure no-op
        from reporter_tpu.datastore import ingest_dir
        assert ingest_dir(store, out)["rows"] == 0


class TestIngestLedger:
    """The manifest (source, writer, epoch, tile) dedupe ledger."""

    def _obs(self):
        import numpy as np

        from reporter_tpu.datastore.schema import ObservationBatch
        return ObservationBatch(
            segment_id=np.array([1 << 25], dtype=np.int64),
            next_id=np.array([2 << 25], dtype=np.int64),
            duration_s=np.array([30.0]),
            count=np.array([1], dtype=np.int64),
            length_m=np.array([500], dtype=np.int64),
            queue_m=np.array([0], dtype=np.int64),
            min_ts=np.array([1500000000], dtype=np.int64),
            max_ts=np.array([1500000030], dtype=np.int64))

    def test_keyed_ingest_dedupes_and_survives_compaction(self,
                                                          tmp_path):
        from reporter_tpu.datastore import LocalDatastore
        metrics.default.reset()
        ds = LocalDatastore(str(tmp_path / "store"))
        assert ds.ingest(self._obs(), ingest_key="a/b/c/t.e0") == 1
        assert ds.ingest(self._obs(), ingest_key="a/b/c/t.e0") == 0
        assert metrics.default.counter("datastore.ingest.deduped") == 1
        assert ds.ingest(self._obs(), ingest_key="a/b/c/t.e1") == 1
        ds.compact()
        # the ledger rides the compacted manifest: old keys still dedupe
        assert ds.ingest(self._obs(), ingest_key="a/b/c/t.e0") == 0
        assert ds.ingest(self._obs(), ingest_key="a/b/c/t.e2") == 1
        assert ds.stats()["rows"] == 3

    def test_ledger_cap_slides_dedupe_window(self, tmp_path,
                                             monkeypatch):
        """REPORTER_TPU_INGEST_LEDGER_MAX bounds the per-partition
        ledger: oldest keys age out (counted), the newest N keep
        deduping — the manifest cannot grow one key per flush forever."""
        from reporter_tpu.datastore import LocalDatastore
        metrics.default.reset()
        monkeypatch.setenv("REPORTER_TPU_INGEST_LEDGER_MAX", "2")
        ds = LocalDatastore(str(tmp_path / "store"))
        for epoch in range(3):
            assert ds.ingest(self._obs(),
                             ingest_key=f"a/b/c/t.e{epoch}") == 1
        assert metrics.default.counter(
            "datastore.ingest.ledger_evicted") == 1
        # newest two keys still dedupe...
        assert ds.ingest(self._obs(), ingest_key="a/b/c/t.e2") == 0
        assert ds.ingest(self._obs(), ingest_key="a/b/c/t.e1") == 0
        # ...the evicted oldest is outside the window again (documented
        # slide: replays older than the cap rely on `ingest --delete`)
        assert ds.ingest(self._obs(), ingest_key="a/b/c/t.e0") == 1

    def test_unkeyed_ingest_never_dedupes(self, tmp_path):
        from reporter_tpu.datastore import LocalDatastore
        ds = LocalDatastore(str(tmp_path / "store"))
        assert ds.ingest(self._obs()) == 1
        assert ds.ingest(self._obs()) == 1
        assert ds.stats()["rows"] == 2

    def test_anonymiser_threads_flush_identity_to_tee(self, tmp_path):
        import re

        from reporter_tpu.core.types import Segment
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        keys = []

        def tee(_tile, segments, ingest_key=None):
            keys.append(ingest_key)

        a = Anonymiser(TileSink(str(tmp_path / "out")), privacy=1,
                       quantisation=3600, source="src", tee=tee)
        a.process("k", Segment(1 << 25, 2 << 25, 1500000000,
                               1500000030, 500, 0))
        a.punctuate()
        assert len(keys) == 1
        # the key IS the tile file's relpath: {t0}_{t1}/{level}/{tile}/
        # {source}.e{epoch:08d} — what ingest_dir derives on a replay
        assert re.fullmatch(r"\d+_\d+/\d/\d+/src\.e00000000", keys[0])
        rel = os.path.join(str(tmp_path / "out"),
                           keys[0].replace("/", os.sep))
        assert os.path.exists(rel)

    def test_legacy_two_arg_tee_still_works(self, tmp_path):
        from reporter_tpu.core.types import Segment
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        seen = []
        a = Anonymiser(TileSink(str(tmp_path / "out")), privacy=1,
                       quantisation=3600, source="src",
                       tee=lambda t, segs: seen.append(len(segs)))
        a.process("k", Segment(1 << 25, 2 << 25, 1500000000,
                               1500000030, 500, 0))
        a.punctuate()
        assert seen == [1]


class TestHealthDegradedBlock:
    def test_open_decode_circuit_flips_health(self):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        service = ReporterService(SegmentMatcher(net=_grid_city()))
        m = service.matcher
        code, body = service.health()
        body = json.loads(body)
        assert code == 200
        assert body["degraded"]["open"] == []
        assert set(body["degraded"]["domains"]) == {
            "native.prep", "decode.dispatch", "matcher.assemble",
            "route.device", "match.incremental"}
        assert set(body["deadletter"]) == {"tiles", "traces"}
        for _ in range(m.circuit_decode.threshold):
            m.circuit_decode.record_failure()
        code, body = service.health()
        body = json.loads(body)
        assert code == 503
        assert body["degraded"]["open"] == ["decode.dispatch"]
        assert body["status"] == "degraded"


class TestWireDomain:
    """The native wire writer's failure domain (ISSUE 11): an armed
    ``wire.native`` failpoint degrades that response to the Python
    columnar writer BYTE-IDENTICALLY — never a 500 — while counting
    ``wire.errors``/``wire.fallback`` and feeding the ``wire.circuit``
    breaker."""

    def test_wire_native_fault_degrades_byte_identically(self):
        from reporter_tpu import native
        if not native.available():
            pytest.skip("native toolchain unavailable")
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.matcher.matcher import MatchRuns
        from reporter_tpu.service import wire
        from reporter_tpu.service.report import (_report_json_py,
                                                 report_wire)
        city = _grid_city()
        m = SegmentMatcher(net=city)
        req = _reqs(city, n=1)[0]
        match = m.match_many([req])[0]
        assert isinstance(match, MatchRuns)
        want = _report_json_py(match, req, 15, {0, 1, 2},
                               {0, 1, 2}).encode("utf-8")
        # healthy path: the C writer answers, byte-identical
        n0 = metrics.counter("wire.native")
        assert bytes(report_wire(match, req, 15, {0, 1, 2},
                                 {0, 1, 2})) == want
        assert metrics.counter("wire.native") == n0 + 1
        # armed fault: same bytes via the Python writer, error counted.
        # A FRESH match — the previous call memoised its chunk's native
        # bytes, and a memo hit never re-enters the writer (or its
        # failpoint): there is no writer work left to fail there.
        match = m.match_many([req])[0]
        faults.configure("wire.native=error")
        e0 = metrics.counter("wire.errors")
        f0 = metrics.counter("wire.fallback")
        out = report_wire(match, req, 15, {0, 1, 2}, {0, 1, 2})
        assert bytes(out) == want
        assert metrics.counter("wire.errors") == e0 + 1
        assert metrics.counter("wire.fallback") == f0 + 1
        # disarm and close the breaker again (module singleton)
        faults.clear()
        assert bytes(report_wire(match, req, 15, {0, 1, 2},
                                 {0, 1, 2})) == want
        assert wire.circuit.state == "closed"

    def test_wire_circuit_opens_and_skips_native(self):
        from reporter_tpu import native
        if not native.available():
            pytest.skip("native toolchain unavailable")
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.matcher.matcher import MatchRuns
        from reporter_tpu.service import wire
        from reporter_tpu.service.report import (_report_json_py,
                                                 report_wire)
        city = _grid_city()
        m = SegmentMatcher(net=city)
        req = _reqs(city, n=1)[0]
        match = m.match_many([req])[0]
        assert isinstance(match, MatchRuns)
        want = _report_json_py(match, req, 15, {0, 1, 2},
                               {0, 1, 2}).encode("utf-8")
        faults.configure("wire.native=error")
        try:
            for _ in range(wire.circuit.threshold):
                assert bytes(report_wire(match, req, 15, {0, 1, 2},
                                         {0, 1, 2})) == want
            assert wire.circuit.state == "open"
            # open circuit: the native attempt (and its failpoint) is
            # skipped outright — errors stop accruing, service continues
            e_open = metrics.counter("wire.errors")
            assert bytes(report_wire(match, req, 15, {0, 1, 2},
                                     {0, 1, 2})) == want
            assert metrics.counter("wire.errors") == e_open
        finally:
            faults.clear()
            wire.circuit.record_success()  # re-close the singleton
        assert wire.circuit.state == "closed"
