# Shared environment for the shell test harnesses
# (equivalent of reference tests/env.sh:1-9).
export REPORTER_HOST=${REPORTER_HOST:-localhost}
export REPORTER_PORT=${REPORTER_PORT:-8002}
export REPORTER_URL=${REPORTER_URL:-http://${REPORTER_HOST}:${REPORTER_PORT}/report}
# synth sv layout: uuid|lat|lon|time|accuracy (tools/synth_cli.py emit_sv)
export FORMATTER=${FORMATTER:-',sv,\|,0,1,2,3,4'}
export REPORT_LEVELS=${REPORT_LEVELS:-0,1,2}
export TRANSITION_LEVELS=${TRANSITION_LEVELS:-0,1,2}
export THRESHOLD_SEC=${THRESHOLD_SEC:-15}
# test harnesses never contend for the real chip (conftest's rule, for
# shell entry points): skip the accelerator probe, run on virtual CPU
export REPORTER_TPU_PLATFORM=${REPORTER_TPU_PLATFORM:-cpu}
export REPORTER_TPU_VIRTUAL_DEVICES=${REPORTER_TPU_VIRTUAL_DEVICES:-8}
