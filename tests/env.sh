# Shared environment for the shell test harnesses
# (equivalent of reference tests/env.sh:1-9).
export REPORTER_HOST=${REPORTER_HOST:-localhost}
export REPORTER_PORT=${REPORTER_PORT:-8002}
export REPORTER_URL=${REPORTER_URL:-http://${REPORTER_HOST}:${REPORTER_PORT}/report}
# synth sv layout: uuid|lat|lon|time|accuracy (tools/synth_cli.py emit_sv)
export FORMATTER=${FORMATTER:-',sv,\|,0,1,2,3,4'}
export REPORT_LEVELS=${REPORT_LEVELS:-0,1,2}
export TRANSITION_LEVELS=${TRANSITION_LEVELS:-0,1,2}
export THRESHOLD_SEC=${THRESHOLD_SEC:-15}
