import json

import numpy as np
import pytest

from reporter_tpu.graph.spatial import SpatialGrid
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.matcher.batchpad import (
    LENGTH_BUCKETS, _select_kept, bucket_length, pack_batches, prepare_trace)
from reporter_tpu.matcher.hmm import (
    NORMAL, RESTART, SKIP, viterbi_decode_batch)
from reporter_tpu.synth import build_grid_city, generate_trace
from reporter_tpu.utils import metrics


@pytest.fixture(scope="module")
def city():
    # no service roads / internals for the core accuracy tests
    return build_grid_city(rows=12, cols=12, spacing_m=200.0, seed=2,
                           service_road_fraction=0.0, internal_fraction=0.0)


@pytest.fixture(scope="module")
def matcher(city):
    return SegmentMatcher(net=city)


def make_trace(city, seed, noise=4.0, **kw):
    rng = np.random.default_rng(seed)
    for _ in range(500):
        tr = generate_trace(city, f"veh-{seed}", rng, noise_m=noise, **kw)
        if tr is not None:
            return tr
    raise RuntimeError("could not generate a trace with the given constraints")


class TestPointFiltering:
    def test_jitter_points_excluded(self):
        # three points: 2nd within 10m of the 1st -> excluded
        lat = np.array([14.6, 14.60001, 14.6010])
        lon = np.array([121.0, 121.0, 121.0])
        kept = _select_kept(lat, lon, [True, True, True], 10.0)
        assert kept.tolist() == [0, 2]

    def test_candidateless_points_excluded(self):
        lat = np.array([14.6, 14.601, 14.602])
        lon = np.array([121.0, 121.0, 121.0])
        kept = _select_kept(lat, lon, [True, False, True], 10.0)
        assert kept.tolist() == [0, 2]

    def test_case_codes_from_prepare(self, city, matcher):
        tr = make_trace(city, seed=61)
        p = prepare_trace(city, matcher.grid, tr.points, MatchParams(),
                          matcher.route_cache)
        assert p.case[0] == RESTART
        assert all(c == NORMAL for c in p.case[1:p.num_kept])
        assert all(c == SKIP for c in p.case[p.num_kept:])


class TestBuckets:
    def test_bucket_length(self):
        assert bucket_length(2) == 16
        assert bucket_length(16) == 16
        assert bucket_length(17) == 64
        assert bucket_length(5000) == LENGTH_BUCKETS[-1]


class TestViterbi:
    def test_prefers_low_emission_with_consistent_transitions(self):
        # 3 points, 2 candidates: candidate 0 always near, transitions
        # consistent; candidate 1 far. Viterbi must pick 0 throughout.
        B, T, K = 1, 3, 2
        dist = np.array([[[2.0, 40.0], [2.0, 40.0], [2.0, 40.0]]], np.float32)
        valid = np.ones((B, T, K), bool)
        gc = np.full((B, T - 1), 30.0, np.float32)
        route = np.full((B, T - 1, K, K), 30.0, np.float32)
        case = np.array([[RESTART, NORMAL, NORMAL]], np.int32)
        paths, scores = viterbi_decode_batch(
            dist, valid, route, gc, case, np.float32(4.07), np.float32(3.0))
        assert paths.tolist() == [[0, 0, 0]]
        assert float(scores[0]) > -10.0

    def test_transition_overrides_emission(self):
        # candidate 1 slightly farther but the only one with a consistent
        # route; candidate 0 near but unroutable from itself.
        B, T, K = 1, 2, 2
        dist = np.array([[[2.0, 6.0], [2.0, 6.0]]], np.float32)
        valid = np.ones((B, T, K), bool)
        gc = np.full((B, 1), 30.0, np.float32)
        route = np.full((B, 1, K, K), 1.0e9, np.float32)  # all unreachable...
        route[0, 0, 1, 1] = 30.0                          # ...except 1->1
        case = np.array([[RESTART, NORMAL]], np.int32)
        paths, _ = viterbi_decode_batch(
            dist, valid, route, gc, case, np.float32(4.07), np.float32(3.0))
        assert paths.tolist() == [[1, 1]]

    def test_restart_decodes_both_chains(self):
        # two chains: best candidate differs across the break
        B, T, K = 1, 4, 2
        dist = np.array([[[1.0, 50.0], [1.0, 50.0],
                          [50.0, 1.0], [50.0, 1.0]]], np.float32)
        valid = np.ones((B, T, K), bool)
        gc = np.full((B, T - 1), 20.0, np.float32)
        route = np.full((B, T - 1, K, K), 20.0, np.float32)
        case = np.array([[RESTART, NORMAL, RESTART, NORMAL]], np.int32)
        paths, _ = viterbi_decode_batch(
            dist, valid, route, gc, case, np.float32(4.07), np.float32(3.0))
        assert paths.tolist() == [[0, 0, 1, 1]]


class TestEndToEndMatch:
    def test_decoded_edges_match_truth(self, city, matcher):
        tr = make_trace(city, seed=11, noise=3.0)
        match = matcher.match_many([tr.request_json()])[0]
        assert match["mode"] == "auto"
        got = [s["segment_id"] for s in match["segments"] if "segment_id" in s]
        truth = tr.truth_segments(city)
        # every truth segment observed long enough should be found, in order
        common = [s for s in got if s in truth]
        assert len(common) >= max(1, len(truth) - 2)
        # order preserved
        idx = [truth.index(s) for s in dict.fromkeys(common)]
        assert idx == sorted(idx)

    def test_segment_accuracy_over_many_traces(self, city, matcher):
        """Point-level segment agreement with ground truth >= 97%."""
        agree = total = 0
        reqs, truths = [], []
        for seed in range(20):
            tr = make_trace(city, seed=100 + seed, noise=4.0)
            reqs.append(tr.request_json())
            truths.append(tr)
        matches = matcher.match_many(reqs)
        for match, tr in zip(matches, truths):
            truth_point_segs = [
                int(city.edge_segment_id[e]) for e in tr.point_edges]
            # decoded per-point segment via begin/end shape indices
            decoded = {}
            for s in match["segments"]:
                sid = s.get("segment_id")
                for i in range(s["begin_shape_index"], s["end_shape_index"] + 1):
                    decoded[i] = sid
            for i, true_sid in enumerate(truth_point_segs):
                if true_sid < 0:
                    continue
                total += 1
                if decoded.get(i) == true_sid:
                    agree += 1
        assert total > 100
        assert agree / total >= 0.97, f"accuracy {agree}/{total}"

    def test_match_json_roundtrip(self, city, matcher):
        tr = make_trace(city, seed=21)
        out = matcher.Match(json.dumps(tr.request_json()))
        match = json.loads(out)
        assert "segments" in match and "mode" in match
        seg = next(s for s in match["segments"] if "segment_id" in s)
        for key in ("start_time", "end_time", "length", "queue_length",
                    "internal", "begin_shape_index", "end_shape_index",
                    "way_ids"):
            assert key in seg

    def test_complete_segments_have_plausible_times(self, city, matcher):
        tr = make_trace(city, seed=31, noise=2.0)
        match = matcher.match_many([tr.request_json()])[0]
        complete = [s for s in match["segments"]
                    if s.get("segment_id") and s["length"] > 0]
        assert complete, "expected at least one completely-traversed segment"
        for s in complete:
            dt = s["end_time"] - s["start_time"]
            assert dt > 0
            speed_kph = s["length"] / dt * 3.6
            assert 10.0 < speed_kph < 120.0

    def test_partial_end_segment_flagged(self, city, matcher):
        tr = make_trace(city, seed=41, noise=2.0)
        match = matcher.match_many([tr.request_json()])[0]
        segs = [s for s in match["segments"] if "segment_id" in s]
        # the trace almost surely ends mid-segment
        last = segs[-1]
        if last["end_time"] == -1:
            assert last["length"] == -1


class TestBatching:
    def test_mixed_lengths_pack_into_buckets(self, city, matcher):
        reqs = []
        for seed in (51, 52, 53):
            tr = make_trace(city, seed=seed, max_route_edges=10)
            reqs.append(tr.request_json())
        long_tr = make_trace(city, seed=54, min_route_edges=16,
                             max_route_edges=22)
        reqs.append(long_tr.request_json())
        prepared = [prepare_trace(city, matcher.grid, r["trace"],
                                  MatchParams(), matcher.route_cache)
                    for r in reqs]
        batches = pack_batches(prepared)
        assert {b.dist_m.shape[1] for b in batches} <= set(LENGTH_BUCKETS)
        assert sum(len(b.traces) for b in batches) == 4
        # results come back for every trace regardless of bucket
        matches = matcher.match_many(reqs)
        assert len(matches) == 4
        assert all(m["segments"] for m in matches)


class TestWireEncoding:
    """pack_batches owns the f16 wire policy; decode must be unchanged."""

    def _toy_arrays(self):
        rng = np.random.default_rng(11)
        B, T, K = 4, 12, 5
        dist = rng.uniform(0.0, 40.0, (B, T, K)).astype(np.float32)
        valid = np.ones((B, T, K), dtype=bool)
        gc = rng.uniform(5.0, 40.0, (B, T - 1)).astype(np.float32)
        route = (gc[..., None, None]
                 + rng.exponential(15.0, (B, T - 1, K, K))).astype(np.float32)
        case = np.full((B, T), NORMAL, dtype=np.int32)
        case[:, 0] = RESTART
        return dist, valid, route, gc, case

    def test_f16_wire_matches_f32(self):
        """Kernels upcast f16 inputs: decoded paths must match f32 inputs."""
        from reporter_tpu.graph.route import UNREACHABLE
        from reporter_tpu.ops import decode_batch

        dist, valid, route, gc, case = self._toy_arrays()
        # make some pairs unreachable so the +inf sentinel crosses the wire
        route[:, 3, 1:, :] = UNREACHABLE
        sigma, beta = np.float32(4.07), np.float32(3.0)
        p32, s32 = decode_batch(dist, valid, route, gc, case, sigma, beta)
        with np.errstate(over="ignore"):
            d16, r16, g16 = (dist.astype(np.float16),
                             route.astype(np.float16),
                             gc.astype(np.float16))
        assert np.isinf(r16[0, 3, 1, 0])
        p16, s16 = decode_batch(d16, valid, r16, g16, case, sigma, beta)
        np.testing.assert_array_equal(np.asarray(p32), np.asarray(p16))
        np.testing.assert_allclose(np.asarray(s32), np.asarray(s16),
                                   rtol=2e-2, atol=0.5)

    def test_pack_batches_emits_f16_wire(self, city, matcher):
        from reporter_tpu.graph.route import UNREACHABLE

        traces = [make_trace(city, s) for s in range(2)]
        prepared = [prepare_trace(city, matcher.grid, t.points,
                                  matcher.params, matcher.route_cache)
                    for t in traces]
        (b,) = pack_batches(prepared)
        assert b.dist_m.dtype == np.float16
        assert b.route_m.dtype == np.float16
        assert b.gc_m.dtype == np.float16
        # unreachable sentinels travel as +inf
        unreachable = np.concatenate(
            [p.route_m.ravel() >= UNREACHABLE / 2 for p in prepared])
        assert np.isinf(b.route_m.reshape(len(prepared), -1)
                        .ravel()[unreachable]).all()
        # finite values survive within f16 rounding
        for i, p in enumerate(prepared):
            finite = p.route_m < UNREACHABLE / 2
            np.testing.assert_allclose(
                b.route_m[i].astype(np.float32)[finite],
                p.route_m[finite], rtol=1e-3)

    def test_pack_batches_f32_env_override(self, city, matcher, monkeypatch):
        monkeypatch.setenv("REPORTER_TPU_WIRE", "f32")
        traces = [make_trace(city, 9)]
        prepared = [prepare_trace(city, matcher.grid, traces[0].points,
                                  matcher.params, matcher.route_cache)]
        (b,) = pack_batches(prepared)
        assert b.route_m.dtype == np.float32

    def test_pack_batches_f32_fallback_out_of_range(self, city, matcher):
        """A finite distance beyond WIRE_MAX_M forces the f32 wire."""
        from reporter_tpu.matcher.hmm import WIRE_MAX_M

        tr = make_trace(city, 4)
        p = prepare_trace(city, matcher.grid, tr.points,
                          matcher.params, matcher.route_cache)
        p.route_m[0, 0, 0] = WIRE_MAX_M * 2  # finite, beyond f16-safe
        (b,) = pack_batches([p])
        assert b.route_m.dtype == np.float32
        assert b.route_m[0, 0, 0, 0] == WIRE_MAX_M * 2

    def test_small_bucket_not_padded_to_chunk(self, city, matcher):
        # a bucket smaller than max_batch keeps its exact batch size
        traces = [make_trace(city, s) for s in range(3)]
        prepared = [prepare_trace(city, matcher.grid, t.points,
                                  matcher.params, matcher.route_cache)
                    for t in traces]
        batches = pack_batches(prepared, max_batch=128)
        assert all(b.dist_m.shape[0] == len(b.traces) for b in batches)


class TestDevicePipeline:
    """The device lane (decode dispatch + wait + assembly on a worker
    thread, overlapping host prep of later chunks) must be a pure
    performance change: byte-identical results to the inline path, chunk
    order preserved across buckets, and lane errors raised to the
    caller."""

    def _reqs(self, city, n=10):
        reqs = []
        for seed in range(n - 2):
            reqs.append(make_trace(city, seed=300 + seed).request_json())
        for seed in (390, 391):  # a second T bucket -> extra chunks
            reqs.append(make_trace(city, seed=seed, min_route_edges=16,
                                   max_route_edges=22).request_json())
        return reqs

    @pytest.mark.parametrize("use_native", [True, False])
    def test_pipelined_matches_inline(self, city, monkeypatch, use_native):
        from reporter_tpu import native
        if use_native and not native.available():
            pytest.skip("native runtime unavailable")
        # small chunks force several lane submissions per call (the mesh
        # pad may round the chunk up; with 8 same-bucket traces that
        # still yields multiple chunks alongside the long-trace bucket)
        monkeypatch.setenv("REPORTER_TPU_DECODE_CHUNK", "2")
        m = SegmentMatcher(net=city, use_native=use_native)
        reqs = self._reqs(city)
        monkeypatch.setenv("REPORTER_TPU_PIPELINE", "0")
        inline = m.match_many(reqs)
        monkeypatch.setenv("REPORTER_TPU_PIPELINE", "1")
        piped = m.match_many(reqs)
        assert piped == inline
        assert all(r is not None for r in piped)

    def test_lane_error_propagates(self, city, monkeypatch):
        """A decode explosion no longer kills the batch — the decode
        breaker degrades the chunk to the numpy oracle (ISSUE 9). The
        error only propagates out of the lanes when the fallback fails
        too (the truly-dead case the drain futures must surface)."""
        import reporter_tpu.matcher.cpu_ref as cpu_ref
        import reporter_tpu.ops as ops

        def boom(*a, **kw):
            raise RuntimeError("decode exploded")

        monkeypatch.setattr(ops, "decode_batch", boom)
        m = SegmentMatcher(net=city)
        got = m.match_many(self._reqs(city, n=4))
        assert all(r and r["segments"] for r in got)
        assert metrics.default.counter("matcher.circuit.decode.errors") > 0

        monkeypatch.setattr(cpu_ref, "viterbi_decode_numpy", boom)
        m2 = SegmentMatcher(net=city)
        with pytest.raises(RuntimeError, match="decode exploded"):
            m2.match_many(self._reqs(city, n=4))

    def test_prep_failure_quiesces_lanes(self, city, monkeypatch):
        """A malformed trace mid-dispatch must raise AND leave the shared
        lanes drained so the matcher stays usable."""
        monkeypatch.setenv("REPORTER_TPU_DECODE_CHUNK", "2")
        m = SegmentMatcher(net=city)
        good = self._reqs(city, n=4)
        bad = good[:3] + [{"uuid": "broken"}] + good[3:]  # no "trace" key
        with pytest.raises(KeyError):
            m.match_many(bad)
        after = m.match_many(good)
        assert all(r and r["segments"] for r in after)

    def test_prep_failure_with_futures_in_flight(self, city, monkeypatch):
        """The quiesce path with lane futures actually in flight: on the
        native path a malformed trace raises before any submit (the
        length bucketing walks all traces first), so inject the failure
        into prep of a LATER chunk instead — earlier chunks are already
        on the lanes when it propagates. A native prep failure alone now
        degrades that chunk to the numpy fallback (the circuit-breaker
        failure domain), so BOTH prep paths must fail for the error to
        reach the caller."""
        import reporter_tpu.matcher.matcher as mod

        monkeypatch.setenv("REPORTER_TPU_DECODE_CHUNK", "2")
        m = SegmentMatcher(net=city)
        reqs = self._reqs(city)
        calls = {"n": 0}
        real = mod.prepare_batch

        def flaky(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("prep exploded")
            return real(*a, **kw)

        def numpy_boom(*a, **kw):
            raise RuntimeError("prep exploded in fallback too")

        monkeypatch.setattr(mod, "prepare_batch", flaky)
        monkeypatch.setattr(mod, "prepare_traces_numpy", numpy_boom)
        with pytest.raises(RuntimeError, match="prep exploded"):
            m.match_many(reqs)
        assert calls["n"] == 2, "failure must hit with a chunk in flight"
        monkeypatch.undo()
        monkeypatch.setenv("REPORTER_TPU_DECODE_CHUNK", "2")
        after = m.match_many(reqs)
        assert all(r and r["segments"] for r in after)

    def test_native_prep_failure_degrades_to_fallback(self, city,
                                                      monkeypatch):
        """One flaky native chunk no longer fails the whole call: the
        chunk is served through the numpy path, results stay complete
        and identical, and the breaker counts one failure."""
        import reporter_tpu.matcher.matcher as mod

        monkeypatch.setenv("REPORTER_TPU_DECODE_CHUNK", "2")
        m = SegmentMatcher(net=city)
        if m.runtime is None:
            pytest.skip("native runtime unavailable")
        reqs = self._reqs(city)
        want = [dict(r) for r in m.match_many(reqs)]
        calls = {"n": 0}
        real = mod.prepare_batch

        def flaky(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("prep exploded")
            return real(*a, **kw)

        monkeypatch.setattr(mod, "prepare_batch", flaky)
        got = m.match_many(reqs)
        assert calls["n"] >= 2
        assert [dict(r) for r in got] == want
        assert m.circuit.snapshot()["state"] == "closed", \
            "one flake must not open the circuit"
        monkeypatch.setattr(mod, "prepare_batch", real)
        m.match_many(reqs)
        assert m.circuit.snapshot()["consecutive_failures"] == 0, \
            "a clean native chunk must reset the failure count"

    def test_concurrent_match_many_callers_share_lanes(self, city,
                                                       monkeypatch):
        """Two threads calling match_many on ONE matcher interleave on
        the shared FIFO lanes; each call's results must be complete,
        ordered, and identical to a serial run (the class docstring's
        concurrent-Match safety claim, now with the lanes in play).
        Pin small chunks + pipelining on so the interleaving is real
        regardless of the environment's defaults."""
        from concurrent.futures import ThreadPoolExecutor

        monkeypatch.setenv("REPORTER_TPU_DECODE_CHUNK", "2")
        monkeypatch.setenv("REPORTER_TPU_PIPELINE", "1")
        m = SegmentMatcher(net=city)
        reqs_a = self._reqs(city, n=6)
        reqs_b = [make_trace(city, seed=500 + s).request_json()
                  for s in range(6)]
        want_a, want_b = m.match_many(reqs_a), m.match_many(reqs_b)
        with ThreadPoolExecutor(2) as pool:
            for _ in range(3):  # a few interleavings
                fa = pool.submit(m.match_many, reqs_a)
                fb = pool.submit(m.match_many, reqs_b)
                # bounded waits: a lane deadlock must FAIL this test,
                # not hang the suite until a job-level kill
                assert fa.result(timeout=120) == want_a
                assert fb.result(timeout=120) == want_b

    def test_pipeline_auto_default_is_platform_aware(self, monkeypatch):
        """Env unset -> the default follows the host: ON with multiple
        cores (something to overlap), OFF on a single-core CPU-only
        host (thread hops are pure loss there). Empty string counts as
        unset, matching bench.py's hardware-gate parsing."""
        import os as os_mod

        from reporter_tpu.matcher.matcher import pipeline_enabled

        monkeypatch.delenv("REPORTER_TPU_PIPELINE", raising=False)
        monkeypatch.setattr(os_mod, "cpu_count", lambda: 8)
        assert pipeline_enabled() is True
        monkeypatch.setattr(os_mod, "cpu_count", lambda: 1)
        # tests run on the CPU backend (conftest pins it)
        assert pipeline_enabled() is False
        monkeypatch.setenv("REPORTER_TPU_PIPELINE", "")
        assert pipeline_enabled() is False  # "" == auto, not forced-on
        monkeypatch.setenv("REPORTER_TPU_PIPELINE", "1")
        assert pipeline_enabled() is True
