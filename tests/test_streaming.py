"""Streaming worker tests: formatter parity, batching thresholds, privacy
culling, and an end-to-end replay -> tile files (the in-process analog of
the reference's tests/circle.sh integration test)."""
import json
import os

import numpy as np
import pytest

from reporter_tpu.core.types import Point, Segment
from reporter_tpu.matcher import SegmentMatcher
from reporter_tpu.service.server import ReporterService
from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink, privacy_cull
from reporter_tpu.streaming.batcher import Batch, PointBatcher
from reporter_tpu.streaming.formatter import Formatter
from reporter_tpu.streaming.worker import StreamWorker, inproc_submitter
from reporter_tpu.synth import build_grid_city, generate_trace


class TestFormatter:
    def test_sv_with_date(self):
        # the reference README's pipe-separated example
        f = Formatter.from_config(",sv,\\|,1,9,10,0,5,yyyy-MM-dd HH:mm:ss")
        uuid, p = f.format(
            "2017-01-31 16:00:00|uuid_abcdef|x|x|x|51.3|x|x|x|3.465725|-76.5135033|x|x|x")
        assert uuid == "uuid_abcdef"
        assert p.lat == pytest.approx(3.465725)
        assert p.lon == pytest.approx(-76.5135033)
        assert p.accuracy == 52  # ceil(51.3)
        assert p.time == 1485878400  # 2017-01-31T16:00:00Z

    def test_json_epoch(self):
        f = Formatter.from_config("@json@id@latitude@longitude@timestamp@accuracy")
        uuid, p = f.format(json.dumps({
            "timestamp": 1495037969, "id": "uuid_abcdef",
            "accuracy": 51.305, "latitude": 3.465725,
            "longitude": -76.5135033}))
        assert uuid == "uuid_abcdef"
        assert p.time == 1495037969
        assert p.accuracy == 52

    def test_bogus_config_rejected(self):
        with pytest.raises(Exception):
            Formatter.from_config(",nope,a,b")

    def test_bogus_message_raises(self):
        f = Formatter.from_config(",sv,\\|,1,9,10,0,5")
        with pytest.raises(Exception):
            f.format("not|enough|fields")


class TestBatchThresholds:
    def _pt(self, t, lat=14.6, lon=121.0):
        return Point(lat, lon, 10, t)

    def test_no_report_below_thresholds(self):
        calls = []
        b = Batch(self._pt(0))
        for i in range(1, 5):
            b.update(self._pt(i, lat=14.6 + i * 1e-4))
        out = b.report("u", lambda t: calls.append(t) or {"shape_used": 1},
                       "auto", "0,1", "0,1", 500, 10, 60)
        assert out is None and not calls

    def test_report_fires_and_trims(self):
        b = Batch(self._pt(0))
        # span >500m (0.01 deg ~ 1.1km), >10 points, >60s
        for i in range(1, 12):
            b.update(self._pt(i * 10, lat=14.6 + i * 0.001))
        out = b.report("u", lambda t: {"shape_used": 5}, "auto", "0,1", "0,1",
                       500, 10, 60)
        assert out == {"shape_used": 5}
        assert len(b.points) == 7  # 12 - 5

    def test_bad_response_drops_batch(self):
        b = Batch(self._pt(0))
        for i in range(1, 12):
            b.update(self._pt(i * 10, lat=14.6 + i * 0.001))
        def boom(t):
            raise RuntimeError("match exploded")
        out = b.report("u", boom, "auto", "0,1", "0,1", 500, 10, 60)
        assert out is None and b.points == []

    def test_eviction_with_relaxed_thresholds(self):
        submitted = []
        forwarded = []
        pb = PointBatcher(lambda t: submitted.append(t) or None,
                          lambda k, s: forwarded.append((k, s)))
        pb.process("veh", self._pt(0), stream_time_ms=0)
        pb.process("veh", self._pt(5, lat=14.601), stream_time_ms=5000)
        assert not submitted  # thresholds not met
        pb.punctuate(stream_time_ms=200000)  # past the 60s session gap
        assert len(submitted) == 1  # evicted with (0, 2, 0)
        assert pb.store == {}


class TestPrivacyCull:
    def _seg(self, sid, nid):
        return Segment(sid, nid, 10.0, 20.0, 100, 0)

    def test_cull_below_threshold(self):
        segs = sorted(
            [self._seg(1, 2)] * 3 + [self._seg(1, 3)] + [self._seg(2, 2)] * 2,
            key=Segment.sort_key)
        out = privacy_cull(segs, privacy=2)
        keys = {s.sort_key() for s in out}
        assert (1, 3) not in keys
        assert len(out) == 5

    def test_privacy_one_keeps_all(self):
        segs = [self._seg(1, 2), self._seg(1, 3)]
        assert len(privacy_cull(sorted(segs, key=Segment.sort_key), 1)) == 2

    def test_run_exactly_at_threshold_survives(self):
        segs = [self._seg(1, 2)] * 4
        assert len(privacy_cull(segs, privacy=4)) == 4

    def test_run_one_below_threshold_culled(self):
        segs = [self._seg(1, 2)] * 3
        assert privacy_cull(segs, privacy=4) == []

    def test_adjacent_pairs_do_not_merge(self):
        # (1,2)x2 then (1,3)x2: four same-id rows, but the runs are keyed
        # on (id, next_id) — neither pair reaches privacy=3 by borrowing
        # from its neighbour
        segs = sorted([self._seg(1, 2)] * 2 + [self._seg(1, 3)] * 2,
                      key=Segment.sort_key)
        assert privacy_cull(segs, privacy=3) == []
        # and at privacy=2 both distinct runs survive independently
        out = privacy_cull(segs, privacy=2)
        assert len(out) == 4
        assert {s.sort_key() for s in out} == {(1, 2), (1, 3)}

    def test_mixed_runs_cull_only_short_ones(self):
        segs = sorted([self._seg(1, 2)] * 3 + [self._seg(1, 3)] * 2
                      + [self._seg(2, 4)] * 3, key=Segment.sort_key)
        out = privacy_cull(segs, privacy=3)
        keys = [s.sort_key() for s in out]
        assert keys.count((1, 2)) == 3
        assert keys.count((2, 4)) == 3
        assert (1, 3) not in keys


class TestEndToEndReplay:
    """Replay synthetic sv-formatted probes through the full topology and
    assert tiles land on disk (mirrors tests/circle.sh's asserts)."""

    def test_replay_writes_tiles(self, tmp_path):
        city = build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=5,
                               service_road_fraction=0.0,
                               internal_fraction=0.0)
        service = ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                                  max_batch=64, max_wait_ms=5.0)
        out_dir = str(tmp_path / "results")

        # manufacture raw sv messages from synthetic traces
        rng = np.random.default_rng(9)
        lines = []
        for i in range(6):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"veh-{i}", rng, noise_m=3.0,
                                    min_route_edges=8)
            for p in tr.points:
                lines.append("|".join([
                    "x", tr.uuid, str(p["lat"]), str(p["lon"]),
                    str(p["time"]), str(p["accuracy"])]))

        # privacy 1 so single observations survive (like circle.sh -p 1)
        worker = StreamWorker(
            Formatter.from_config(",sv,\\|,1,2,3,4,5"),
            inproc_submitter(service),
            Anonymiser(TileSink(out_dir), privacy=1, quantisation=3600,
                       source="test"),
            flush_interval_s=1e9)  # flush only at drain
        worker.run(lines)

        assert worker.processed == len(lines)
        assert worker.parse_failures == 0
        # tiles exist and carry the reference's CSV header
        tile_files = []
        for root, _dirs, files in os.walk(out_dir):
            tile_files.extend(os.path.join(root, f) for f in files)
        assert tile_files, "no tiles written"
        with open(tile_files[0]) as f:
            header = f.readline().strip()
        assert header == Segment.column_layout()
        # every data row has 10 columns and the source/mode stamped
        with open(tile_files[0]) as f:
            rows = f.read().strip().split("\n")[1:]
        assert rows
        for row in rows:
            cols = row.split(",")
            assert len(cols) == 10
            assert cols[8] == "test" and cols[9] == "AUTO"
