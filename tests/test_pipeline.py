"""Batch pipeline tests: gather -> batched match -> tile report, end to end
over local files (the reference's S3 path is gated off in this image)."""
import gzip
import os

import numpy as np
import pytest

from reporter_tpu.core.types import Segment
from reporter_tpu.matcher import SegmentMatcher
from reporter_tpu.pipeline.simple_reporter import (
    _windows_of, gather_traces, match_traces, report_tiles)
from reporter_tpu.synth import build_grid_city, generate_trace


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=4,
                           service_road_fraction=0.0, internal_fraction=0.0)


def make_part_file(city, path, n_traces=5, seed=0):
    """Pipe-separated part file shaped like the reference's default valuer
    expects: col1=uuid, col0=time, col9=lat, col10=lon, col5=accuracy."""
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n_traces):
        tr = None
        while tr is None:
            tr = generate_trace(city, f"veh-{seed}-{i}", rng, noise_m=3.0,
                                min_route_edges=8)
        for p in tr.points:
            cols = ["x"] * 11
            cols[0] = str(p["time"])
            cols[1] = tr.uuid
            cols[5] = str(p["accuracy"])
            cols[9] = str(p["lat"])
            cols[10] = str(p["lon"])
            lines.append("|".join(cols))
    with gzip.open(path, "wt") as f:
        f.write("\n".join(lines))
    return lines


class TestWindows:
    def test_split_at_inactivity(self):
        pts = [{"time": t} for t in (0, 10, 20, 300, 310, 320)]
        wins = list(_windows_of(pts, inactivity=120))
        assert [len(w) for w in wins] == [3, 3]

    def test_short_windows_dropped(self):
        pts = [{"time": t} for t in (0, 300, 310)]
        wins = list(_windows_of(pts, inactivity=120))
        assert [len(w) for w in wins] == [2]


class TestPipelineEndToEnd:
    def test_three_stages(self, city, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        src_dir = tmp_path / "src"
        src_dir.mkdir()
        make_part_file(city, str(src_dir / "part-000.gz"), n_traces=4, seed=1)
        make_part_file(city, str(src_dir / "part-001.gz"), n_traces=4, seed=2)

        trace_dir = gather_traces(str(src_dir), ".*",
                                  "lambda l: (lambda c: [c[1], c[0], c[9], "
                                  "c[10], c[5]])(l.split('|'))",
                                  "%Y-%m-%d %H:%M:%S",
                                  [-90.0, -180.0, 90.0, 180.0], concurrency=2)
        shard_files = [os.path.join(r, f)
                       for r, _d, fs in os.walk(trace_dir) for f in fs]
        assert shard_files, "stage 1 produced no shards"

        matcher = SegmentMatcher(net=city)
        match_dir = match_traces(
            trace_dir, matcher, "auto", {0, 1, 2}, {0, 1, 2},
            quantisation=3600, inactivity=120, source="test")
        tile_files = [os.path.join(r, f)
                      for r, _d, fs in os.walk(match_dir) for f in fs]
        assert tile_files, "stage 2 produced no tile rows"
        # rows have the 10-column layout with uppercased mode
        with open(tile_files[0]) as f:
            cols = f.readline().strip().split(",")
        assert len(cols) == 10 and cols[9] == "AUTO" and cols[3] == "1"

        dest = tmp_path / "dest"
        report_tiles(match_dir, str(dest), privacy=1, concurrency=2)
        out_files = [os.path.join(r, f)
                     for r, _d, fs in os.walk(dest) for f in fs]
        assert out_files, "stage 3 wrote nothing"
        with open(out_files[0]) as f:
            assert f.readline().strip() == Segment.column_layout()

    def test_privacy_cull_removes_rare_pairs(self, city, tmp_path):
        match_dir = tmp_path / "matches" / "0_3599" / "0"
        match_dir.mkdir(parents=True)
        rows = (["5,6,10,1,600,0,0,10,src,AUTO\n"] * 3
                + ["7,8,10,1,600,0,0,10,src,AUTO\n"])
        with open(match_dir / "42", "w") as f:
            f.writelines(rows)
        dest = tmp_path / "out"
        report_tiles(str(tmp_path / "matches"), str(dest), privacy=2,
                     concurrency=1)
        out_files = [os.path.join(r, f)
                     for r, _d, fs in os.walk(dest) for f in fs]
        (path,) = out_files
        with open(path) as f:
            body = f.read()
        assert body.count("5,6,") == 3
        assert "7,8," not in body


class TestLongWindowChunking:
    """Windows beyond the largest padding bucket are chunked with a
    holdback overlap rather than truncated."""

    def _points(self, n, dt=1):
        return [{"time": 1500000000 + i * dt, "lat": 14.0 + i * 1e-4,
                 "lon": 121.0} for i in range(n)]

    def test_short_window_untouched(self):
        from reporter_tpu.pipeline.simple_reporter import _windows_of
        pts = self._points(500)
        ws = list(_windows_of(pts, inactivity=120))
        assert len(ws) == 1 and len(ws[0]) == 500

    def test_long_window_chunks_cover_all_points(self):
        from reporter_tpu.pipeline.simple_reporter import (
            MAX_WINDOW_POINTS, _windows_of)
        pts = self._points(2500)
        ws = list(_windows_of(pts, inactivity=120))
        assert all(len(w) <= MAX_WINDOW_POINTS for w in ws)
        covered = {p["time"] for w in ws for p in w}
        assert covered == {p["time"] for p in pts}

    def test_chunk_overlap_spans_holdback(self):
        from reporter_tpu.pipeline.simple_reporter import _windows_of
        pts = self._points(2500)
        ws = list(_windows_of(pts, inactivity=120, holdback_s=15))
        for a, b in zip(ws[:-1], ws[1:]):
            overlap_start = b[0]["time"]
            assert a[-1]["time"] - overlap_start > 15  # covers holdback
            assert overlap_start > a[0]["time"]        # but makes progress

    def test_inactivity_split_still_applies(self):
        from reporter_tpu.pipeline.simple_reporter import _windows_of
        pts = self._points(100)
        pts[50]["time"] += 1000  # gap
        for p in pts[51:]:
            p["time"] += 1000
        ws = list(_windows_of(pts, inactivity=120))
        assert len(ws) == 2
