import numpy as np
import pytest

from reporter_tpu.graph import RoadNetwork, SpatialGrid, candidate_route_matrices
from reporter_tpu.graph.route import RouteCache, route_distance, shortest_path_edges, UNREACHABLE
from reporter_tpu.graph.spatial import PAD_EDGE
from reporter_tpu.synth import build_grid_city, generate_trace


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=1)


class TestNetwork:
    def test_shapes(self, city):
        assert city.num_nodes == 100
        # every run direction covered: 2*(rows*(cols-1) + cols*(rows-1)) edges
        assert city.num_edges == 2 * (10 * 9 + 10 * 9)
        offsets, edges = city.csr()
        assert offsets[-1] == city.num_edges
        assert len(edges) == city.num_edges

    def test_csr_consistent(self, city):
        offsets, edges = city.csr()
        for node in (0, 37, 99):
            out = edges[offsets[node]:offsets[node + 1]]
            assert all(city.edge_start[e] == node for e in out)

    def test_segments_have_lengths(self, city):
        associated = city.edge_segment_id[city.edge_segment_id >= 0]
        assert len(associated) > 0
        for sid in np.unique(associated):
            assert city.segment_length_m[int(sid)] > 0

    def test_save_load_roundtrip(self, city, tmp_path):
        p = str(tmp_path / "city.npz")
        city.save(p)
        loaded = RoadNetwork.load(p)
        np.testing.assert_array_equal(loaded.edge_segment_id, city.edge_segment_id)
        np.testing.assert_allclose(loaded.node_lat, city.node_lat)
        assert loaded.segment_length_m == city.segment_length_m


class TestSpatial:
    def test_candidates_find_true_edge(self, city):
        grid = SpatialGrid(city)
        # a point 5m off the midpoint of edge 0
        nx, ny = city.node_xy()
        e = 0
        mx = (nx[city.edge_start[e]] + nx[city.edge_end[e]]) / 2
        my = (ny[city.edge_start[e]] + ny[city.edge_end[e]]) / 2 + 5.0
        _, to_ll = city.projection()
        lat, lon = to_ll(mx, my)
        cands = grid.candidates(np.array([lat]), np.array([lon]), k=4)
        assert e in cands.edge_ids[0]
        slot = list(cands.edge_ids[0]).index(e)
        assert cands.dist_m[0, slot] == pytest.approx(5.0, abs=0.5)
        assert cands.offset_m[0, slot] == pytest.approx(100.0, abs=2.0)

    def test_padding_when_far_away(self, city):
        grid = SpatialGrid(city)
        lat0 = float(city.node_lat.mean()) + 1.0  # ~111 km north
        cands = grid.candidates(np.array([lat0]), np.array([120.98]), k=4)
        assert (cands.edge_ids[0] == PAD_EDGE).all()

    def test_whole_batch_query_equals_per_point(self, city):
        """The vectorised grid query over ALL points of many traces at
        once (flat columns, the batched prep path) returns exactly the
        per-trace results — including top-k distance-tie ordering and
        points with no candidates."""
        grid = SpatialGrid(city, cell_m=75.0)
        rng = np.random.default_rng(12)
        lat0, lon0 = float(city.node_lat.min()), float(city.node_lon.min())
        # scatter points over the city plus a few far outside it
        lat = lat0 + rng.uniform(-0.002, 0.02, 400)
        lon = lon0 + rng.uniform(-0.002, 0.02, 400)
        lat[::50] += 0.5  # candidate-less rows
        whole = grid.candidates(lat, lon, k=5)
        # split into uneven "traces" and query each separately
        cuts = [0, 7, 64, 65, 200, 400]
        for a, b in zip(cuts[:-1], cuts[1:]):
            part = grid.candidates(lat[a:b], lon[a:b], k=5)
            np.testing.assert_array_equal(whole.edge_ids[a:b],
                                          part.edge_ids)
            np.testing.assert_array_equal(whole.dist_m[a:b], part.dist_m)
            np.testing.assert_array_equal(whole.offset_m[a:b],
                                          part.offset_m)
        assert (whole.edge_ids[::50] == PAD_EDGE).all()


class TestRoute:
    def test_same_edge_forward(self, city):
        d = route_distance(city, 3, 10.0, 3, 150.0, max_dist=1000.0)
        assert d == pytest.approx(140.0)

    def test_adjacent_edges(self, city):
        # follow edge 0 into an out-edge of its end node
        offsets, edges = city.csr()
        end = int(city.edge_end[0])
        nxt = int(edges[offsets[end]])
        d = route_distance(city, 0, 50.0, nxt, 30.0, max_dist=1000.0)
        assert d == pytest.approx((200.0 - 50.0) + 30.0)

    def test_same_edge_backward_loops_by_default(self, city):
        # backward on a directed edge = loop around; far more than the jitter
        d = route_distance(city, 3, 150.0, 3, 140.0, max_dist=5000.0)
        assert d > 100.0

    def test_same_edge_backward_within_tolerance_is_free(self, city):
        d = route_distance(city, 3, 150.0, 3, 140.0, max_dist=5000.0,
                           backward_tolerance_m=25.0)
        assert d == 0.0
        # beyond the tolerance the loop price comes back
        d = route_distance(city, 3, 150.0, 3, 100.0, max_dist=5000.0,
                           backward_tolerance_m=25.0)
        assert d > 100.0

    def test_unreachable_when_bounded(self, city):
        # far corner beyond a tiny bound
        d = route_distance(city, 0, 0.0, city.num_edges - 1, 0.0, max_dist=100.0)
        assert d == UNREACHABLE

    def test_shortest_path_edges_connects(self, city):
        path = shortest_path_edges(city, 0, 99)
        assert path is not None
        assert int(city.edge_start[path[0]]) == 0
        assert int(city.edge_end[path[-1]]) == 99
        for a, b in zip(path[:-1], path[1:]):
            assert city.edge_end[a] == city.edge_start[b]

    def test_cache_hits(self, city):
        cache = RouteCache(city)
        d0 = route_distance(city, 0, 0.0, 5, 10.0, 5000.0, cache)
        before = cache.misses
        # same edge pair, different offset: served from the PAIR level
        # (no new Dijkstra, no node-dict probe), identical arithmetic
        d1 = route_distance(city, 0, 0.0, 5, 20.0, 5000.0, cache)
        assert cache.misses == before and cache.pair_hits >= 1
        assert d1 == pytest.approx(d0 + 10.0)
        # a different source edge still reuses the node-level entry when
        # its Dijkstra was already run
        cache2 = RouteCache(city)
        route_distance(city, 0, 0.0, 5, 10.0, 5000.0, cache2)
        cache2.distances_from(int(city.edge_end[0]), 1000.0)
        assert cache2.hits >= 1


class TestSynthTrace:
    def test_generate(self, city):
        rng = np.random.default_rng(7)
        tr = None
        while tr is None:
            tr = generate_trace(city, "veh-1", rng, noise_m=4.0)
        assert len(tr.points) >= 2
        assert all(p2["time"] > p1["time"] for p1, p2 in zip(tr.points, tr.points[1:]))
        req = tr.request_json()
        assert req["uuid"] == "veh-1"
        assert set(req["match_options"]) == {"mode", "report_levels", "transition_levels"}
        truth = tr.truth_segments(city)
        assert len(truth) >= 1

    def test_route_matrix_includes_truth_transition(self, city):
        rng = np.random.default_rng(3)
        tr = None
        while tr is None:
            tr = generate_trace(city, "veh-2", rng, noise_m=3.0)
        grid = SpatialGrid(city)
        lat = np.array([p["lat"] for p in tr.points])
        lon = np.array([p["lon"] for p in tr.points])
        cands = grid.candidates(lat, lon, k=4)
        from reporter_tpu.core.geo import equirectangular_m
        gc = equirectangular_m(lat[:-1], lon[:-1], lat[1:], lon[1:])
        mats = candidate_route_matrices(city, cands, gc)
        assert mats.shape == (len(tr.points) - 1, 4, 4)
        # at least some transitions should be routable and short
        finite = mats[mats < UNREACHABLE]
        assert finite.size > 0
        assert finite.min() < 100.0
