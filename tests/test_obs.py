"""Observability layer: spans, trace-event export, flight recorder,
Prometheus exposition, SLO checks, heartbeat — ISSUE 7."""
import json
import logging
import os
import re
import socket
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from reporter_tpu.analysis import registry as contract_registry
from reporter_tpu.matcher import SegmentMatcher
from reporter_tpu.obs import flightrec, prom, slo
from reporter_tpu.obs import trace as obs_trace
from reporter_tpu.service.server import ReporterService, serve
from reporter_tpu.synth import build_grid_city, generate_trace
from reporter_tpu.utils import metrics
from reporter_tpu.utils.metrics import BUCKET_BOUNDS_S, Registry


@pytest.fixture(autouse=True)
def _clean_obs():
    """Every test starts and ends disarmed with an empty ring."""
    obs_trace.configure(False)
    flightrec.reset()
    yield
    obs_trace.configure(False)
    flightrec.reset()


# ---------------------------------------------------------------------------
class TestSpans:
    def test_disarmed_is_shared_noop(self):
        # one flag check, zero allocation: the same object every time
        assert obs_trace.span("a") is obs_trace.span("b")
        assert obs_trace.current() is None
        with obs_trace.span("a"):
            pass
        assert flightrec.events() == []

    def test_nesting_and_parent_ids(self):
        obs_trace.configure(True)
        with obs_trace.span("root") as root:
            with obs_trace.span("child") as child:
                with obs_trace.span("grandchild") as gc:
                    pass
        evs = {e["name"]: e for e in flightrec.events()}
        assert evs["root"]["parent_id"] == 0
        assert evs["child"]["parent_id"] == root.span_id
        assert evs["grandchild"]["parent_id"] == child.span_id
        assert evs["root"]["trace_id"] == evs["child"]["trace_id"] \
            == evs["grandchild"]["trace_id"] == root.trace_id
        assert gc.trace_id == root.trace_id
        # children close before parents, durations nest
        assert evs["root"]["dur_ns"] >= evs["child"]["dur_ns"] \
            >= evs["grandchild"]["dur_ns"]

    def test_sibling_spans_share_parent(self):
        obs_trace.configure(True)
        with obs_trace.span("root") as root:
            with obs_trace.span("a"):
                pass
            with obs_trace.span("b"):
                pass
        evs = {e["name"]: e for e in flightrec.events()}
        assert evs["a"]["parent_id"] == root.span_id
        assert evs["b"]["parent_id"] == root.span_id

    def test_force_begin_end_arms_per_request(self):
        assert not obs_trace.enabled()
        obs_trace.force_begin()
        try:
            assert obs_trace.enabled()
            with obs_trace.span("forced"):
                pass
        finally:
            obs_trace.force_end()
        assert not obs_trace.enabled()
        assert [e["name"] for e in flightrec.events()] == ["forced"]

    def test_attach_carries_context_across_threads(self):
        import threading
        obs_trace.configure(True)
        seen = {}

        def worker(ctx):
            with obs_trace.attach(ctx):
                with obs_trace.span("lane") as sp:
                    seen["trace_id"] = sp.trace_id
                    seen["parent_id"] = sp.parent_id

        with obs_trace.span("root") as root:
            ctx = obs_trace.current()
            t = threading.Thread(target=worker, args=(ctx,))
            t.start()
            t.join()
        assert seen["trace_id"] == root.trace_id
        assert seen["parent_id"] == root.span_id

    def test_metrics_timer_doubles_as_span(self):
        obs_trace.configure(True)
        r = Registry()
        with obs_trace.span("root") as root:
            with r.timer("stage.x"):
                pass
        names = [e["name"] for e in flightrec.events()]
        assert "stage.x" in names
        ev = next(e for e in flightrec.events() if e["name"] == "stage.x")
        assert ev["parent_id"] == root.span_id
        # and the timer still recorded
        assert r.snapshot()["timers"]["stage.x"]["count"] == 1

    def test_phase_spans_reconstruct_backwards_from_now(self):
        obs_trace.configure(True)
        with obs_trace.span("prep") as prep:
            obs_trace.phase_spans(("c", "s", "r"), [1000, 0, 3000])
        evs = {e["name"]: e for e in flightrec.events() if e["name"] != "prep"}
        assert set(evs) == {"c", "r"}  # zero-ns phases dropped
        assert evs["c"]["parent_id"] == prep.span_id
        assert evs["c"]["dur_ns"] == 1000 and evs["r"]["dur_ns"] == 3000
        # back-to-back: c ends where r begins
        assert evs["c"]["t0_ns"] + evs["c"]["dur_ns"] == evs["r"]["t0_ns"]
        assert evs["c"]["attrs"]["synthetic"] is True


class TestTraceEvents:
    def test_export_shape(self):
        obs_trace.configure(True)
        with obs_trace.span("root", kind="t") as root:
            with obs_trace.span("child"):
                pass
        obj = obs_trace.export_trace(root)
        assert obj["displayTimeUnit"] == "ms"
        evs = obj["traceEvents"]
        assert {e["name"] for e in evs} == {"root", "child"}
        for e in evs:
            assert e["ph"] == "X"
            assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
            assert e["pid"] == os.getpid()
            assert e["args"]["trace_id"] == root.trace_id
        rootev = next(e for e in evs if e["name"] == "root")
        assert rootev["args"]["kind"] == "t"
        json.dumps(obj)  # serialisable as-is

    def test_export_filters_by_trace_id(self):
        obs_trace.configure(True)
        with obs_trace.span("one") as first:
            pass
        with obs_trace.span("two"):
            pass
        obj = obs_trace.export_trace(first)
        assert [e["name"] for e in obj["traceEvents"]] == ["one"]

    def test_export_of_noop_is_empty(self):
        root = obs_trace.span("never-armed")  # disarmed: the noop
        assert obs_trace.export_trace(root) == {
            "traceEvents": [], "displayTimeUnit": "ms"}

    def test_in_flight_rendered_as_begin_events(self):
        obs_trace.configure(True)
        sp = obs_trace.span("open")
        sp.__enter__()
        try:
            obj = obs_trace.to_trace_events([], flightrec.in_flight())
            assert obj["traceEvents"][0]["ph"] == "B"
            assert obj["traceEvents"][0]["args"]["in_flight"] is True
        finally:
            sp.__exit__(None, None, None)


# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_ring_is_bounded(self):
        obs_trace.configure(True)
        for i in range(flightrec.RING_EVENTS + 50):
            with obs_trace.span("s"):
                pass
        assert len(flightrec.events()) == flightrec.RING_EVENTS

    def test_dump_names_in_flight_span(self, tmp_path, monkeypatch):
        monkeypatch.setattr(flightrec, "_dump_dir", str(tmp_path))
        obs_trace.configure(True)
        with obs_trace.span("done"):
            pass
        sp = obs_trace.span("inflight")
        sp.__enter__()
        try:
            path = flightrec.dump("test.reason", {"k": 1})
        finally:
            sp.__exit__(None, None, None)
        assert path and os.path.exists(path)
        with open(path, encoding="utf-8") as f:
            post = json.load(f)
        assert post["reason"] == "test.reason"
        assert post["extra"] == {"k": 1}
        assert [s["name"] for s in post["in_flight"]] == ["inflight"]
        assert post["in_flight"][0]["age_ns"] >= 0
        assert [s["name"] for s in post["spans"]] == ["done"]
        assert "counters" in post
        # the postmortem is itself counted
        assert metrics.default.snapshot()["counters"]["flightrec.dumps"] >= 1

    def test_dump_without_dir_is_skipped(self, monkeypatch):
        monkeypatch.setattr(flightrec, "_dump_dir", None)
        assert flightrec.dump("nowhere") is None

    def test_env_dir_wins_over_derived(self, tmp_path, monkeypatch):
        monkeypatch.setattr(flightrec, "_dump_dir", str(tmp_path / "env"))
        monkeypatch.setattr(flightrec, "_dir_from_env", True)
        flightrec.set_dump_dir(str(tmp_path / "derived"))
        assert flightrec.dump_dir() == str(tmp_path / "env")

    def test_worker_exception_leaves_postmortem(self, tmp_path,
                                                monkeypatch):
        from reporter_tpu.streaming.worker import StreamWorker
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        from reporter_tpu.streaming.formatter import Formatter
        monkeypatch.setattr(flightrec, "_dir_from_env", False)
        obs_trace.configure(True)

        def boom(_trace):
            raise RuntimeError("matcher exploded")

        worker = StreamWorker(
            Formatter.from_config(r",sv,\|,0,1,2,3,4"), boom,
            Anonymiser(TileSink(str(tmp_path / "out")), 1, 3600))
        # the worker derived its dump dir from the dead-letter spool
        rec_dir = os.path.join(str(tmp_path / "out"), ".deadletter",
                               ".flightrec")
        assert flightrec.dump_dir() == rec_dir
        monkeypatch.setattr(worker, "offer",
                            lambda _m: (_ for _ in ()).throw(
                                RuntimeError("stream died")))
        with pytest.raises(RuntimeError):
            worker.run(iter(["x|1|2|3|4"]))
        dumps = os.listdir(rec_dir)
        assert len(dumps) == 1 and "worker.exception" in dumps[0]


# ---------------------------------------------------------------------------
class TestSLO:
    def test_parse_spec(self):
        assert slo.parse_spec("a.b=250,c=1.5") == {"a.b": 0.25,
                                                   "c": 0.0015}
        assert slo.parse_spec("") == {}
        for bad in ("a", "a=", "a=x", "a=-5", "a=0"):
            with pytest.raises(ValueError):
                slo.parse_spec(bad)

    def test_breach_on_p99(self, monkeypatch):
        r = Registry()
        for _ in range(20):
            r.observe("stage", 0.004)
        monkeypatch.setenv(slo.ENV_VAR, "stage=100")
        out = slo.check(r)
        assert out["breaches"] == []
        monkeypatch.setenv(slo.ENV_VAR, "stage=1")
        out = slo.check(r)
        assert len(out["breaches"]) == 1
        b = out["breaches"][0]
        assert b["stage"] == "stage" and b["p99_s"] > 0.001
        # an idle stage never breaches
        monkeypatch.setenv(slo.ENV_VAR, "stage=1,never_ran=1")
        assert len(slo.check(r)["breaches"]) == 1

    def test_malformed_spec_fails_open(self, monkeypatch):
        monkeypatch.setenv(slo.ENV_VAR, "garbage")
        assert slo.check(Registry()) == {"targets": {}, "breaches": []}

    def test_malformed_spec_warning_counted_once(self, monkeypatch):
        """Fail-open is counted (slo.malformed) — but once per NEW spec
        value, not once per health probe."""
        from reporter_tpu.utils import metrics
        monkeypatch.setenv(slo.ENV_VAR, "surely=not=a=spec")
        slo._cache_spec = None  # drop any cached verdict
        before = metrics.default.counter("slo.malformed")
        assert slo.thresholds() == {}
        assert metrics.default.counter("slo.malformed") == before + 1
        assert slo.thresholds() == {}  # cached: no second count
        assert metrics.default.counter("slo.malformed") == before + 1

    def test_unknown_stage_names_ignored(self, monkeypatch):
        """A target naming a stage that never ran is inert — it can
        neither breach nor error."""
        r = Registry()
        for _ in range(10):
            r.observe("real.stage", 0.5)
        monkeypatch.setenv(slo.ENV_VAR,
                           "no.such.stage=1,real.stage=5000")
        out = slo.check(r)
        assert out["breaches"] == []
        assert set(out["targets"]) == {"no.such.stage", "real.stage"}

    def test_budget_zero_never_flips_health(self, monkeypatch):
        """``stage=0`` is malformed (budgets must be > 0), so the WHOLE
        spec fails open — a zero budget must never 503 a healthy
        service by making every observation a breach."""
        r = Registry()
        r.observe("stage", 0.001)
        monkeypatch.setenv(slo.ENV_VAR, "stage=0")
        slo._cache_spec = None
        out = slo.check(r)
        assert out["targets"] == {} and out["breaches"] == []

    def test_spec_reload_between_requests(self, monkeypatch):
        """The spec is re-read per check (cached per VALUE): an
        operator retuning budgets between requests needs no restart."""
        r = Registry()
        for _ in range(10):
            r.observe("stage", 0.5)
        monkeypatch.setenv(slo.ENV_VAR, "stage=10000")
        assert slo.check(r)["breaches"] == []
        monkeypatch.setenv(slo.ENV_VAR, "stage=1")
        assert len(slo.check(r)["breaches"]) == 1
        monkeypatch.delenv(slo.ENV_VAR)
        out = slo.check(r)
        assert out["targets"] == {} and out["breaches"] == []


# ---------------------------------------------------------------------------
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{le="[^"]+"\})? [0-9eE.+-]+$')
_META_RE = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|histogram)$")


def _assert_scrape_clean(text):
    """A Prometheus text-format parser in miniature: every line must be
    a TYPE comment or a sample, histogram buckets must be cumulative,
    and +Inf must equal _count."""
    buckets = {}
    counts = {}
    assert text.endswith("\n")
    for line in text.strip("\n").split("\n"):
        assert _META_RE.match(line) or _SAMPLE_RE.match(line), line
        if line.startswith("#"):
            continue
        name = line.split("{")[0].split(" ")[0]
        value = float(line.rsplit(" ", 1)[1])
        assert value >= 0, line
        if name.endswith("_bucket"):
            fam = buckets.setdefault(name, [])
            assert not fam or value >= fam[-1], f"non-monotone: {line}"
            fam.append(value)
        elif name.endswith("_count"):
            counts[name[:-len("_count")]] = value
    for fam, vals in buckets.items():
        base = fam[:-len("_bucket")]
        assert vals[-1] == counts[base], fam


class TestPromExposition:
    def _golden_registry(self):
        r = Registry()
        r.count("service.requests", 3)
        r.count("egress.ok")
        r.observe("service.handle", 0.001)
        r.observe("service.handle", 0.002)
        r.observe("service.handle", 0.5)
        return r

    def test_golden_format(self):
        """Pin the exposition bytes: a dashboard built on this format
        must not drift (regenerate the fixture deliberately if the
        format changes)."""
        fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                               "metrics_exposition.txt")
        with open(fixture, encoding="utf-8") as f:
            want = f.read()
        assert prom.render(self._golden_registry()) == want

    def test_golden_is_scrape_clean(self):
        _assert_scrape_clean(prom.render(self._golden_registry()))

    def test_bucket_monotone_and_inf_equals_count(self):
        r = Registry()
        for v in (1e-8, 1e-4, 0.1, 3.0, 1e5):  # incl. an overflow
            r.observe("s", v)
        text = prom.render(r)
        _assert_scrape_clean(text)
        assert f'reporter_tpu_s_seconds_bucket{{le="+Inf"}} 5' in text
        assert "reporter_tpu_s_seconds_count 5" in text

    def test_every_registered_metric_renders(self):
        """Every exact entry in the contract registry's METRICS table,
        fed through the metrics layer as a counter AND a timer, renders
        as valid exposition without name mangling — so no registered
        name can produce an unscrapable /metrics."""
        r = Registry()
        exact = [name for name in contract_registry.METRICS
                 if not name.endswith("*")]
        assert exact, "contract registry lost its METRICS entries"
        for name in exact:
            r.count(name)
            r.observe(name, 0.001)
        text = prom.render(r)
        _assert_scrape_clean(text)
        for name in exact:
            base = prom.PREFIX + "_" + prom.sanitize(name)
            assert f"{base}_total 1" in text, name
            assert f"{base}_seconds_count 1" in text, name

    def test_prefix_pattern_families_render(self):
        """Dynamic families (the registry's `prefix.*` patterns) render
        too — instantiate each pattern with a representative suffix."""
        r = Registry()
        patterns = [name for name in contract_registry.METRICS
                    if name.endswith("*")]
        assert patterns
        for pat in patterns:
            r.count(pat[:-1] + "x")
        _assert_scrape_clean(prom.render(r))


# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=3,
                           service_road_fraction=0.0,
                           internal_fraction=0.0)


@pytest.fixture(scope="module")
def server(city):
    service = ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                              max_batch=64, max_wait_ms=10.0)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    httpd = serve(service, "127.0.0.1", port)
    yield f"http://127.0.0.1:{port}"
    httpd.shutdown()


def make_req(city, seed):
    rng = np.random.default_rng(seed)
    tr = None
    while tr is None:
        tr = generate_trace(city, f"obs-{seed}", rng, noise_m=3.0)
    return tr.request_json()


def post(url, body):
    req = urllib.request.Request(url, data=json.dumps(body).encode(),
                                 method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestServiceObservability:
    def test_metrics_endpoint_scrape_clean(self, city, server):
        post(f"{server}/report", make_req(city, 1))
        with urllib.request.urlopen(f"{server}/metrics") as r:
            assert r.status == 200
            assert r.headers["Content-type"].startswith("text/plain")
            text = r.read().decode()
        _assert_scrape_clean(text)
        assert "reporter_tpu_service_requests_total" in text
        assert "reporter_tpu_service_handle_seconds_bucket" in text

    def test_stats_reports_percentiles(self, city, server):
        post(f"{server}/report", make_req(city, 2))
        with urllib.request.urlopen(f"{server}/stats") as r:
            stats = json.loads(r.read())
        t = stats["timers"]["service.handle"]
        assert t["p50_s"] <= t["p95_s"] <= t["p99_s"] <= t["max_s"]

    def test_trace_flag_ships_span_tree(self, city, server):
        req = make_req(city, 4)
        code, plain = post(f"{server}/report", req)  # warm + compare
        assert code == 200
        code, body = post(f"{server}/report?trace=1", req)
        assert code == 200
        assert set(body) == {"report", "trace"}
        # the report payload is the normal response, unchanged
        assert body["report"]["stats"] == plain["stats"]
        evs = body["trace"]["traceEvents"]
        names = {e["name"] for e in evs}
        for need in ("service.request", "service.parse", "service.handle",
                     "dispatch.batch", "dispatch.match_many",
                     "matcher.chunk", "report.serialise"):
            assert need in names, (need, sorted(names))
        root = next(e for e in evs if e["name"] == "service.request")
        # every event belongs to this one request's trace
        assert {e["args"]["trace_id"] for e in evs} \
            == {root["args"]["trace_id"]}
        # tracing disarms once the request is done
        assert not obs_trace.enabled()

    def test_untraced_requests_record_no_spans(self, city, server):
        flightrec.reset()
        code, _ = post(f"{server}/report", make_req(city, 5))
        assert code == 200
        assert flightrec.events() == []

    def test_trace_flag_falsy_spellings_stay_plain(self, city, server):
        """?trace=false / ?trace=off must NOT arm tracing or change the
        response shape (same falsy set as the env flag)."""
        for spelling in ("false", "off", "0"):
            code, body = post(f"{server}/report?trace={spelling}",
                              make_req(city, 7))
            assert code == 200
            assert "stats" in body and "trace" not in body, spelling

    def test_health_slo_breach_degrades(self, city, server, monkeypatch):
        post(f"{server}/report", make_req(city, 6))
        monkeypatch.setenv(slo.ENV_VAR, "service.handle=0.000001")
        code, body = post_health(server)
        assert code == 503
        assert body["status"] == "degraded"
        assert body["slo"]["breaches"][0]["stage"] == "service.handle"
        monkeypatch.delenv(slo.ENV_VAR)
        code, body = post_health(server)
        assert code == 200 and body["slo"]["breaches"] == []


def post_health(server):
    try:
        with urllib.request.urlopen(f"{server}/health") as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ---------------------------------------------------------------------------
class TestHeartbeat:
    def test_heartbeat_line_is_json(self, tmp_path, monkeypatch, caplog):
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        from reporter_tpu.streaming.formatter import Formatter
        from reporter_tpu.streaming.worker import StreamWorker
        monkeypatch.setenv("REPORTER_TPU_HEARTBEAT_S", "0.0001")

        def submit(_trace):
            return None

        worker = StreamWorker(
            Formatter.from_config(r",sv,\|,0,1,2,3,4"), submit,
            Anonymiser(TileSink(str(tmp_path / "out")), 1, 3600),
            circuit_probe=lambda: "closed")
        assert worker.heartbeat_s == 0.0001
        with caplog.at_level(logging.INFO, "reporter_tpu.streaming"):
            time.sleep(0.001)
            worker.offer("hb-uuid|45.0|-122.0|1000|5")
        lines = [rec.message for rec in caplog.records
                 if rec.message.startswith("heartbeat ")]
        assert lines, "no heartbeat emitted"
        payload = json.loads(lines[0][len("heartbeat "):])
        assert payload["processed"] == 1
        assert payload["batches_in_flight"] == 1
        assert payload["flush_epoch"] == 0
        assert payload["circuit"] == "closed"
        assert payload["msgs_per_s"] >= 0

    def test_heartbeat_default_off(self, tmp_path, monkeypatch):
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        from reporter_tpu.streaming.formatter import Formatter
        from reporter_tpu.streaming.worker import StreamWorker
        monkeypatch.delenv("REPORTER_TPU_HEARTBEAT_S", raising=False)
        worker = StreamWorker(
            Formatter.from_config(r",sv,\|,0,1,2,3,4"), lambda t: None,
            Anonymiser(TileSink(str(tmp_path / "out")), 1, 3600))
        assert worker.heartbeat_s == 0.0
