"""Columnar JSON response writer: byte-for-byte parity with the dict path.

The PR-4 hot path deleted the per-trace dict builders (`_format_runs`,
`_runs_as_lists`, the dict-building `report()` machine) and serialises
/report responses straight from the native assembler's run columns
(matcher.render_segments_json + service.report_json). The contract is
byte-identity: every response the writer emits must equal
``json.dumps(report(<materialised dicts>), separators=(",", ":"))`` on a
recorded fixture — so any drift in number formatting, key order, or the
emission state machine fails here, not in a downstream consumer.
"""
import json
import os

import pytest

from reporter_tpu import native
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.matcher.matcher import (MatchRuns, _jnum,
                                          render_segments_json)
from reporter_tpu.service.report import report, report_json
from reporter_tpu.synth import build_grid_city

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "report_parity.json")

LEVELS = [
    (15, {0, 1, 2}, {0, 1, 2}),
    (15, {0, 1}, {0, 1, 2}),     # unreported level
    (15, {0, 1, 2}, {0}),        # non-transitional successors
    (3600, {0, 1, 2}, {0, 1, 2}),  # holdback swallows everything
]


@pytest.fixture(scope="module")
def fixture():
    with open(FIXTURE) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def city(fixture):
    return build_grid_city(**fixture["city"])


@pytest.fixture(scope="module")
def matchers(city):
    params = MatchParams(max_candidates=8)
    fallback = SegmentMatcher(net=city, params=params, use_native=False)
    if not native.available():
        return None, fallback
    return SegmentMatcher(net=city, params=params), fallback


def _plain_copy(match) -> dict:
    """Materialise a match result into fresh plain dicts, so report()'s
    in-place mutation cannot leak between the two serialisation paths."""
    return {"segments": [dict(s) for s in match["segments"]],
            "mode": match["mode"]}


def _dict_path_bytes(match, req, threshold, rep, trans) -> str:
    return json.dumps(report(_plain_copy(match), req, threshold, rep,
                             trans), separators=(",", ":"))


def test_report_json_byte_parity_on_fixture(fixture, matchers):
    m_native, m_fallback = matchers
    if m_native is None:
        pytest.skip("native toolchain unavailable")
    reqs = fixture["requests"]
    matches = m_native.match_many(reqs)
    assert any(isinstance(m, MatchRuns) for m in matches)
    checked = 0
    for req, match in zip(reqs, matches):
        for threshold, rep, trans in LEVELS:
            want = _dict_path_bytes(match, req, threshold, rep, trans)
            got = report_json(match, req, threshold, rep, trans)
            assert got == want
            checked += 1
    assert checked == len(reqs) * len(LEVELS)


def test_report_json_native_equals_fallback_bytes(fixture, matchers):
    """The full serialised response is byte-identical across the native
    (columnar writer) and numpy-fallback (dict) paths."""
    m_native, m_fallback = matchers
    if m_native is None:
        pytest.skip("native toolchain unavailable")
    reqs = fixture["requests"]
    for req, mn, mf in zip(reqs, m_native.match_many(reqs),
                           m_fallback.match_many(reqs)):
        assert report_json(mn, req, 15, {0, 1, 2}, {0, 1, 2}) \
            == report_json(mf, req, 15, {0, 1, 2}, {0, 1, 2})


def test_match_json_byte_parity(fixture, matchers):
    """Match() serialises through the columnar segments writer —
    byte-identical to json.dumps of the materialised match dict."""
    m_native, _ = matchers
    if m_native is None:
        pytest.skip("native toolchain unavailable")
    for req in fixture["requests"][:4]:
        out = m_native.Match(json.dumps(req))
        match = m_native.match_many([req])[0]
        assert isinstance(match, MatchRuns)
        assert out == json.dumps(match._materialise(),
                                 separators=(",", ":"))
        # and the writer output parses back to the same structure
        assert json.loads(out) == match._materialise()


def test_render_segments_json_empty():
    class _C:
        way_off, ways = [0], []
        seg_id = internal = start = end = length = queue = []
        begin_idx = end_idx = []
    assert render_segments_json(_C(), 0, 0, "auto") \
        == '{"segments":[],"mode":"auto"}'


def test_jnum_matches_json_dumps():
    for v in (0, -1, 7, True, False, None, 0.0, -0.0, -1.0, 3.125,
              1234.567, 1e-7, 1.7976931348623157e308, 123456789.123):
        assert _jnum(v) == json.dumps(v), v


def test_match_runs_mapping_protocol(fixture, matchers):
    m_native, m_fallback = matchers
    if m_native is None:
        pytest.skip("native toolchain unavailable")
    req = fixture["requests"][0]
    mr = m_native.match_many([req])[0]
    plain = m_fallback.match_many([req])[0]
    # equality against the plain-dict fallback result, both directions
    assert mr == plain and plain == mr
    # mapping surface
    assert set(mr.keys()) == {"segments", "mode"}
    assert "segments" in mr and len(mr) == 2
    assert mr.get("nope", 42) == 42
    # report() stamps mode through __setitem__ without losing columns
    mr2 = m_native.match_many([req])[0]
    mr2["mode"] = "auto"
    assert mr2.mode == "auto" and mr2["mode"] == "auto"
    # json.dumps on the lazy object fails loudly (not silently wrong) —
    # serialisation goes through the writers
    with pytest.raises(TypeError):
        json.dumps(m_native.match_many([req])[0])


# ---- ISSUE 11: the native wire writer (ABI 12) ----------------------------
# Cross-path property: for every fixture trace and level combination,
# native C writer bytes == Python columnar writer bytes == legacy dict
# path bytes — including the whole-chunk batch emission's per-trace
# slices, the repr-parity float formatter, and the backend knob.

from reporter_tpu.service import wire
from reporter_tpu.service.report import _report_json_py, report_wire


def test_wire_cross_path_property(fixture, matchers):
    """native bytes == Python writer bytes == legacy dict path, across
    every fixture request and LEVELS combination, on the native-prep
    path. Each (request, levels) cell exercises BOTH the whole-chunk
    batch emission (fresh match -> memo build + slice) and the
    per-trace C call (memo popped)."""
    m_native, _ = matchers
    if m_native is None:
        pytest.skip("native toolchain unavailable")
    if not wire.use_native():
        pytest.skip("native wire backend unavailable")
    reqs = fixture["requests"]
    checked = 0
    for threshold, rep, trans in LEVELS:
        matches = m_native.match_many(reqs)
        for req, match in zip(reqs, matches):
            if not isinstance(match, MatchRuns):
                continue
            dict_bytes = _dict_path_bytes(match, req, threshold, rep,
                                          trans)
            py_bytes = _report_json_py(match, req, threshold, rep,
                                       trans)
            # chunk path: first call builds the whole-chunk buffer,
            # this trace's body is a zero-copy slice of it
            sliced = report_wire(match, req, threshold, rep, trans)
            assert isinstance(sliced, memoryview)
            # per-trace path: with the memo dropped, the same bytes
            # come from the single-trace C call
            match.cols.arrays.pop("_wire_chunk", None)
            memo_off = dict(match.cols.arrays)
            memo_off.pop("_run_off", None)
            memo_off.pop("_trace_end", None)
            from reporter_tpu import native
            per_trace = native.write_report_json(
                memo_off, match.lo, match.hi,
                float(req["trace"][-1]["time"]), float(threshold),
                wire.level_mask(rep), wire.level_mask(trans))
            assert bytes(sliced) == py_bytes.encode("utf-8") \
                == dict_bytes.encode("utf-8") == bytes(per_trace)
            checked += 1
    assert checked >= 4 * 8  # all level combos, most fixture traces


def test_wire_knob_pins_python_writer(fixture, matchers, monkeypatch):
    """REPORTER_TPU_WIRE_NATIVE=off pins the Python columnar writer —
    same bytes, str (not memoryview), zero wire.native counts."""
    from reporter_tpu.utils import metrics
    m_native, _ = matchers
    if m_native is None:
        pytest.skip("native toolchain unavailable")
    req = fixture["requests"][0]
    match = m_native.match_many([req])[0]
    want = _report_json_py(match, req, 15, {0, 1, 2}, {0, 1, 2})
    monkeypatch.setenv(wire.ENV_VAR, "off")
    n0 = metrics.counter("wire.native")
    out = report_wire(match, req, 15, {0, 1, 2}, {0, 1, 2})
    assert not wire.use_native()
    assert isinstance(out, bytes) and out == want.encode("utf-8")
    assert metrics.counter("wire.native") == n0
    monkeypatch.delenv(wire.ENV_VAR)
    assert wire.use_native()


def test_json_double_matches_repr():
    """The C float formatter is pinned against CPython repr()/_jnum
    over the wire's value population: integer-valued doubles, 3-decimal
    rounded epochs/kms, sentinels, and general shortest-repr values."""
    from reporter_tpu import native
    if not native.available():
        pytest.skip("native toolchain unavailable")
    import numpy as np
    values = [0.0, -0.0, -1.0, 1.0, 3.125, 1234.567, 0.1, 0.5, 0.25,
              0.062, 0.0625, 1e-7, 123456789.123, 1.5e9 + 0.123,
              1.7976931348623157e308, 2.5, 97.001, 1e12 + 0.375,
              float("inf"), float("-inf"), float("nan")]
    rng = np.random.default_rng(3)
    values += list(np.round(rng.uniform(0, 2e9, 500), 3))
    values += list(rng.uniform(0, 1, 200))        # general repr path
    values += [float(v) for v in rng.integers(0, 10**15, 100)]
    for v in values:
        got = native.json_double(float(v)).decode()
        assert got == _jnum(float(v)), v


def test_wire_batch_slices_cover_whole_chunk(fixture, matchers):
    """The whole-chunk buffer partitions exactly: per-trace slices are
    contiguous, non-overlapping and cover every emitted byte."""
    from reporter_tpu import native
    m_native, _ = matchers
    if m_native is None:
        pytest.skip("native toolchain unavailable")
    reqs = fixture["requests"]
    matches = m_native.match_many(reqs)
    chunk = next(m for m in matches if isinstance(m, MatchRuns))
    arrays = chunk.cols.arrays
    assert "_run_off" in arrays and "_trace_end" in arrays
    buf, offsets = native.write_report_json_batch(arrays, 15.0, 7, 7)
    assert offsets[0] == 0 and offsets[-1] <= len(buf)
    assert all(a <= b for a, b in zip(offsets, offsets[1:]))
    # every slice is a parseable /report body
    for t in range(len(offsets) - 1):
        body = bytes(buf.data[offsets[t]:offsets[t + 1]])
        parsed = json.loads(body)
        assert set(parsed) >= {"stats", "segment_matcher", "datastore"}


def test_wire_level_semantics_match_python_set_membership(fixture,
                                                         matchers):
    """The mask conversion must never invent or lose a match the
    Python scan's SET-MEMBERSHIP test makes: non-canonical level
    values (strings, non-integral floats, -1) either convert exactly
    or force the Python writer — bytes stay identical either way."""
    from reporter_tpu.utils import metrics
    m_native, _ = matchers
    if m_native is None:
        pytest.skip("native toolchain unavailable")
    req = fixture["requests"][0]
    cases = [
        # strings can never equal an int level: dropped, not coerced
        ({"0", "1", "2"}, {0, 1, 2}),
        ({0, 1, 2}, {"0", "2"}),
        # non-integral floats can never match; integral floats compare
        # equal to int levels and convert exactly
        ({0.0, 1.0, 2.0}, {0, 1, 2}),
        ({2.5, 0}, {0, 1, 2}),
        # -1 matches the no-segment-id level in the set test — only
        # the Python writer expresses that
        ({0, 1, 2}, {-1, 0, 1, 2}),
        ({-1.0}, {0, 1, 2}),
        # unmatchable big levels drop consistently
        ({0, 1, 2, 9, 250}, {0, 1, 2}),
    ]
    for rep, trans in cases:
        match = m_native.match_many([req])[0]
        want = _report_json_py(match, req, 15, rep, trans)
        got = report_wire(match, req, 15, rep, trans)
        assert bytes(got) == want.encode("utf-8"), (rep, trans)
    # masks bail out exactly when membership is inexpressible
    assert wire.level_mask({0, 1, 2}) == 0b111
    assert wire.level_mask({0.0, 2.0}) == 0b101
    assert wire.level_mask({"0", 1}) == 0b010
    assert wire.level_mask({2.5, 1}) == 0b010
    assert wire.level_mask({-1}) is None
    assert wire.level_mask({-1.0, 0}) is None
    assert wire.level_mask({True, False}) == 0b011
    assert wire.level_mask({9, 250, -3}) == 0
