"""Columnar JSON response writer: byte-for-byte parity with the dict path.

The PR-4 hot path deleted the per-trace dict builders (`_format_runs`,
`_runs_as_lists`, the dict-building `report()` machine) and serialises
/report responses straight from the native assembler's run columns
(matcher.render_segments_json + service.report_json). The contract is
byte-identity: every response the writer emits must equal
``json.dumps(report(<materialised dicts>), separators=(",", ":"))`` on a
recorded fixture — so any drift in number formatting, key order, or the
emission state machine fails here, not in a downstream consumer.
"""
import json
import os

import pytest

from reporter_tpu import native
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.matcher.matcher import (MatchRuns, _jnum,
                                          render_segments_json)
from reporter_tpu.service.report import report, report_json
from reporter_tpu.synth import build_grid_city

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "report_parity.json")

LEVELS = [
    (15, {0, 1, 2}, {0, 1, 2}),
    (15, {0, 1}, {0, 1, 2}),     # unreported level
    (15, {0, 1, 2}, {0}),        # non-transitional successors
    (3600, {0, 1, 2}, {0, 1, 2}),  # holdback swallows everything
]


@pytest.fixture(scope="module")
def fixture():
    with open(FIXTURE) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def city(fixture):
    return build_grid_city(**fixture["city"])


@pytest.fixture(scope="module")
def matchers(city):
    params = MatchParams(max_candidates=8)
    fallback = SegmentMatcher(net=city, params=params, use_native=False)
    if not native.available():
        return None, fallback
    return SegmentMatcher(net=city, params=params), fallback


def _plain_copy(match) -> dict:
    """Materialise a match result into fresh plain dicts, so report()'s
    in-place mutation cannot leak between the two serialisation paths."""
    return {"segments": [dict(s) for s in match["segments"]],
            "mode": match["mode"]}


def _dict_path_bytes(match, req, threshold, rep, trans) -> str:
    return json.dumps(report(_plain_copy(match), req, threshold, rep,
                             trans), separators=(",", ":"))


def test_report_json_byte_parity_on_fixture(fixture, matchers):
    m_native, m_fallback = matchers
    if m_native is None:
        pytest.skip("native toolchain unavailable")
    reqs = fixture["requests"]
    matches = m_native.match_many(reqs)
    assert any(isinstance(m, MatchRuns) for m in matches)
    checked = 0
    for req, match in zip(reqs, matches):
        for threshold, rep, trans in LEVELS:
            want = _dict_path_bytes(match, req, threshold, rep, trans)
            got = report_json(match, req, threshold, rep, trans)
            assert got == want
            checked += 1
    assert checked == len(reqs) * len(LEVELS)


def test_report_json_native_equals_fallback_bytes(fixture, matchers):
    """The full serialised response is byte-identical across the native
    (columnar writer) and numpy-fallback (dict) paths."""
    m_native, m_fallback = matchers
    if m_native is None:
        pytest.skip("native toolchain unavailable")
    reqs = fixture["requests"]
    for req, mn, mf in zip(reqs, m_native.match_many(reqs),
                           m_fallback.match_many(reqs)):
        assert report_json(mn, req, 15, {0, 1, 2}, {0, 1, 2}) \
            == report_json(mf, req, 15, {0, 1, 2}, {0, 1, 2})


def test_match_json_byte_parity(fixture, matchers):
    """Match() serialises through the columnar segments writer —
    byte-identical to json.dumps of the materialised match dict."""
    m_native, _ = matchers
    if m_native is None:
        pytest.skip("native toolchain unavailable")
    for req in fixture["requests"][:4]:
        out = m_native.Match(json.dumps(req))
        match = m_native.match_many([req])[0]
        assert isinstance(match, MatchRuns)
        assert out == json.dumps(match._materialise(),
                                 separators=(",", ":"))
        # and the writer output parses back to the same structure
        assert json.loads(out) == match._materialise()


def test_render_segments_json_empty():
    class _C:
        way_off, ways = [0], []
        seg_id = internal = start = end = length = queue = []
        begin_idx = end_idx = []
    assert render_segments_json(_C(), 0, 0, "auto") \
        == '{"segments":[],"mode":"auto"}'


def test_jnum_matches_json_dumps():
    for v in (0, -1, 7, True, False, None, 0.0, -0.0, -1.0, 3.125,
              1234.567, 1e-7, 1.7976931348623157e308, 123456789.123):
        assert _jnum(v) == json.dumps(v), v


def test_match_runs_mapping_protocol(fixture, matchers):
    m_native, m_fallback = matchers
    if m_native is None:
        pytest.skip("native toolchain unavailable")
    req = fixture["requests"][0]
    mr = m_native.match_many([req])[0]
    plain = m_fallback.match_many([req])[0]
    # equality against the plain-dict fallback result, both directions
    assert mr == plain and plain == mr
    # mapping surface
    assert set(mr.keys()) == {"segments", "mode"}
    assert "segments" in mr and len(mr) == 2
    assert mr.get("nope", 42) == 42
    # report() stamps mode through __setitem__ without losing columns
    mr2 = m_native.match_many([req])[0]
    mr2["mode"] = "auto"
    assert mr2.mode == "auto" and mr2["mode"] == "auto"
    # json.dumps on the lazy object fails loudly (not silently wrong) —
    # serialisation goes through the writers
    with pytest.raises(TypeError):
        json.dumps(m_native.match_many([req])[0])
