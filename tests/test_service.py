"""End-to-end /report service tests over the synthetic city."""
import concurrent.futures
import json
import socket
import urllib.parse
import urllib.request

import numpy as np
import pytest

from reporter_tpu.matcher import SegmentMatcher
from reporter_tpu.service.server import ReporterService, serve
from reporter_tpu.synth import build_grid_city, generate_trace


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=3,
                           service_road_fraction=0.0, internal_fraction=0.0)


@pytest.fixture(scope="module")
def server(city):
    service = ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                              max_batch=64, max_wait_ms=30.0)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    httpd = serve(service, "127.0.0.1", port)
    yield f"http://127.0.0.1:{port}"
    httpd.shutdown()


def make_req(city, seed):
    rng = np.random.default_rng(seed)
    tr = None
    while tr is None:
        tr = generate_trace(city, f"veh-{seed}", rng, noise_m=3.0)
    return tr.request_json()


def get(url):
    with urllib.request.urlopen(url) as r:
        return r.status, json.loads(r.read())


def post(url, body):
    req = urllib.request.Request(url, data=json.dumps(body).encode(),
                                 method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestService:
    def test_get_report(self, city, server):
        req = make_req(city, 1)
        q = urllib.parse.urlencode({"json": json.dumps(req)})
        status, body = get(f"{server}/report?{q}")
        assert status == 200
        assert body["datastore"]["mode"] == "auto"
        assert "segments" in body["segment_matcher"]
        assert "stats" in body

    def test_post_report(self, city, server):
        status, body = post(f"{server}/report", make_req(city, 2))
        assert status == 200
        assert isinstance(body["datastore"]["reports"], list)

    def test_missing_uuid_400(self, city, server):
        req = make_req(city, 3)
        del req["uuid"]
        status, body = post(f"{server}/report", req)
        assert status == 400
        assert body["error"] == "uuid is required"

    def test_single_point_400(self, city, server):
        req = make_req(city, 4)
        req["trace"] = req["trace"][:1]
        status, body = post(f"{server}/report", req)
        assert status == 400
        assert "non zero length" in body["error"]

    def test_missing_levels_400(self, city, server):
        req = make_req(city, 5)
        del req["match_options"]["report_levels"]
        status, body = post(f"{server}/report", req)
        assert status == 400
        assert "report_levels" in body["error"]

    def test_bad_action_400(self, server):
        status, body = post(f"{server}/nonsense", {"uuid": "x"})
        assert status == 400
        assert "valid action" in body["error"]

    def test_concurrent_requests_batched(self, city, server):
        reqs = [make_req(city, 100 + i) for i in range(16)]
        with concurrent.futures.ThreadPoolExecutor(16) as pool:
            results = list(pool.map(
                lambda r: post(f"{server}/report", r), reqs))
        assert all(status == 200 for status, _ in results)
        # every response carries that trace's own uuid-independent result;
        # sanity: each has a stats block and parseable reports
        for _, body in results:
            assert "stats" in body and "datastore" in body

    def test_stats_endpoint(self, city, server):
        # make sure at least one request has been counted
        req = make_req(city, 6)
        post(f"{server}/report", req)
        code, body = get(f"{server}/stats")
        assert code == 200
        assert body["counters"]["service.requests"] >= 1
        assert body["counters"]["dispatch.traces"] >= 1
        assert body["timers"]["dispatch.match_many"]["count"] >= 1


class TestDispatchPolicy:
    """Flush policy of the micro-batching dispatcher: idle-grace early
    flush (latency) without giving up burst batching (throughput)."""

    def test_idle_queue_flushes_before_max_wait(self):
        """A lone request must not wait out the full max_wait: with an
        idle queue the batch flushes after the grace window. This is
        the single-handler pathology found under load: every request
        paid the full 20 ms wait for co-batchers that could not exist."""
        import time

        from reporter_tpu.service.dispatch import BatchDispatcher

        d = BatchDispatcher(lambda traces: [{"ok": True}] * len(traces),
                            max_batch=64, max_wait_ms=500.0,
                            idle_grace_ms=5.0)
        try:
            t0 = time.perf_counter()
            out = d.submit({"uuid": "solo"})
            elapsed = time.perf_counter() - t0
            assert out == {"ok": True}
            assert elapsed < 0.25, f"idle flush took {elapsed:.3f}s"
        finally:
            d.close()

    def test_burst_still_batches(self):
        """Traces already enqueued when the loop drains must land in one
        batch regardless of the grace window."""
        from reporter_tpu.service.dispatch import BatchDispatcher

        sizes = []

        def match_many(traces):
            sizes.append(len(traces))
            return [{"i": i} for i in range(len(traces))]

        d = BatchDispatcher(match_many, max_batch=64, max_wait_ms=200.0,
                            idle_grace_ms=5.0)
        try:
            out = d.submit_many([{"uuid": f"u{i}"} for i in range(16)])
            assert len(out) == 16
            assert max(sizes) >= 8, sizes  # the burst batched together
        finally:
            d.close()


class TestPoolSizing:
    def test_default_pool_not_cpu_bound(self, monkeypatch):
        """Handler threads are IO-bound waiters; the default pool must
        not collapse to cpu_count (=1 on small hosts, which serialises
        requests and defeats micro-batching). Reference env knobs win."""
        from reporter_tpu.service.server import BoundedThreadingHTTPServer
        import socket as socket_mod

        monkeypatch.delenv("THREAD_POOL_COUNT", raising=False)
        monkeypatch.delenv("THREAD_POOL_MULTIPLIER", raising=False)
        with socket_mod.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        srv = BoundedThreadingHTTPServer(("127.0.0.1", port), object)
        try:
            assert srv._slots._initial_value == 64
        finally:
            srv.server_close()
        monkeypatch.setenv("THREAD_POOL_COUNT", "3")
        with socket_mod.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        srv = BoundedThreadingHTTPServer(("127.0.0.1", port), object)
        try:
            assert srv._slots._initial_value == 3
        finally:
            srv.server_close()
