"""End-to-end /report service tests over the synthetic city."""
import concurrent.futures
import json
import socket
import urllib.parse
import urllib.request

import numpy as np
import pytest

from reporter_tpu.matcher import SegmentMatcher
from reporter_tpu.service.server import ReporterService, serve
from reporter_tpu.synth import build_grid_city, generate_trace


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=3,
                           service_road_fraction=0.0, internal_fraction=0.0)


@pytest.fixture(scope="module")
def server(city):
    service = ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                              max_batch=64, max_wait_ms=30.0)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    httpd = serve(service, "127.0.0.1", port)
    yield f"http://127.0.0.1:{port}"
    httpd.shutdown()


def make_req(city, seed):
    rng = np.random.default_rng(seed)
    tr = None
    while tr is None:
        tr = generate_trace(city, f"veh-{seed}", rng, noise_m=3.0)
    return tr.request_json()


def get(url):
    with urllib.request.urlopen(url) as r:
        return r.status, json.loads(r.read())


def post(url, body):
    req = urllib.request.Request(url, data=json.dumps(body).encode(),
                                 method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestService:
    def test_get_report(self, city, server):
        req = make_req(city, 1)
        q = urllib.parse.urlencode({"json": json.dumps(req)})
        status, body = get(f"{server}/report?{q}")
        assert status == 200
        assert body["datastore"]["mode"] == "auto"
        assert "segments" in body["segment_matcher"]
        assert "stats" in body

    def test_post_report(self, city, server):
        status, body = post(f"{server}/report", make_req(city, 2))
        assert status == 200
        assert isinstance(body["datastore"]["reports"], list)

    def test_missing_uuid_400(self, city, server):
        req = make_req(city, 3)
        del req["uuid"]
        status, body = post(f"{server}/report", req)
        assert status == 400
        assert body["error"] == "uuid is required"

    def test_single_point_400(self, city, server):
        req = make_req(city, 4)
        req["trace"] = req["trace"][:1]
        status, body = post(f"{server}/report", req)
        assert status == 400
        assert "non zero length" in body["error"]

    def test_missing_levels_400(self, city, server):
        req = make_req(city, 5)
        del req["match_options"]["report_levels"]
        status, body = post(f"{server}/report", req)
        assert status == 400
        assert "report_levels" in body["error"]

    def test_bad_action_400(self, server):
        status, body = post(f"{server}/nonsense", {"uuid": "x"})
        assert status == 400
        assert "valid action" in body["error"]

    def test_concurrent_requests_batched(self, city, server):
        reqs = [make_req(city, 100 + i) for i in range(16)]
        with concurrent.futures.ThreadPoolExecutor(16) as pool:
            results = list(pool.map(
                lambda r: post(f"{server}/report", r), reqs))
        assert all(status == 200 for status, _ in results)
        # every response carries that trace's own uuid-independent result;
        # sanity: each has a stats block and parseable reports
        for _, body in results:
            assert "stats" in body and "datastore" in body

    def test_stats_endpoint(self, city, server):
        # make sure at least one request has been counted
        req = make_req(city, 6)
        post(f"{server}/report", req)
        code, body = get(f"{server}/stats")
        assert code == 200
        assert body["counters"]["service.requests"] >= 1
        assert body["counters"]["dispatch.traces"] >= 1
        assert body["timers"]["dispatch.match_many"]["count"] >= 1
