"""Freshness-tier tests (ISSUE 18): the recent-delta overlay (window=
queries, byte-identity pins, eviction bound, crash-replay dedupe), the
bbox change feed (cursor semantics, resync, condition-notified delivery,
waiter/pressure shedding over HTTP), materialised viewport summaries,
the datastore CLI's --window / feed surfaces, and the end-to-end proof:
a probe the worker tee flushed is visible via ``window=5m`` and
delivered on an open ``/feed`` cursor within one tee cycle."""
import json
import math
import os
import socket
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from reporter_tpu.core.osmlr import make_segment_id
from reporter_tpu.core.types import Segment
from reporter_tpu.datastore import (
    BackgroundCompactor,
    LocalDatastore,
    ObservationBatch,
    OverlayView,
    aggregate,
    parse_window,
)
from reporter_tpu.datastore.feed import ChangeFeed, FeedOverload
from reporter_tpu.datastore.freshness import RecentDeltaOverlay
from reporter_tpu.utils import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Monday 2017-01-02 08:00:00 UTC -> hour-of-week 8
MON_8AM = 1483344000

SID = make_segment_id(2, 756425, 10)
NID = make_segment_id(2, 756425, 11)
WORLD = [-180.0, -90.0, 180.0, 90.0]


def _segs(n, t0=MON_8AM, duration=10.0, length=100, sid=SID, nid=NID,
          spacing=30):
    """n observations of `length` m in `duration` s (36 kph at defaults)."""
    return [Segment(sid, nid, t0 + i * spacing, t0 + i * spacing + duration,
                    length, 0) for i in range(n)]


def _delta(n=3, **kw):
    """One aggregated partition delta for overlay/feed unit tests."""
    return aggregate(ObservationBatch.from_segments(_segs(n, **kw)))[
        (2, 756425)]


class _Clock:
    """Injectable arrival clock (the feed/poll timeouts deliberately
    ignore it — they are wall-clock, so freezing this cannot hang)."""

    def __init__(self, t=float(MON_8AM)):
        self.t = float(t)

    def __call__(self):
        return self.t


class TestParseWindow:
    def test_spellings(self):
        assert parse_window(300) == 300.0
        assert parse_window("300") == 300.0
        assert parse_window("90s") == 90.0
        assert parse_window("5m") == 300.0
        assert parse_window("2h") == 7200.0
        assert parse_window("1d") == 86400.0
        for inf in ("inf", "INF", "infinity", "∞"):
            assert math.isinf(parse_window(inf))

    def test_rejects_junk(self):
        for bad in ("bogus", "", "nan", "-5", 0, -1, "5x"):
            with pytest.raises(ValueError):
                parse_window(bad)


class TestOverlay:
    def test_dedupe_by_ingest_key(self):
        ov = RecentDeltaOverlay(budget_bytes=1 << 20, clock=_Clock())
        assert ov.record(2, 756425, _delta(), "flush-1") is not None
        assert ov.record(2, 756425, _delta(), "flush-1") is None
        assert ov.snapshot()["entries"] == 1
        # keyless (ad-hoc CSV) ingests have no cross-restart identity:
        # each records
        assert ov.record(2, 756425, _delta(), None) is not None
        assert ov.record(2, 756425, _delta(), None) is not None
        assert ov.snapshot()["entries"] == 3

    def test_in_store_upgrade_on_dedupe(self):
        ov = RecentDeltaOverlay(budget_bytes=1 << 20, clock=_Clock())
        e = ov.record(2, 756425, _delta(), "spooled", in_store=False)
        assert e.in_store is False
        # the dead-letter replay re-offers the same key after its
        # append commits: the no-op still flips the entry to committed
        assert ov.record(2, 756425, _delta(), "spooled",
                         in_store=True) is None
        assert e.in_store is True

    def test_window_deltas_age_out(self):
        clk = _Clock()
        ov = RecentDeltaOverlay(budget_bytes=1 << 20, clock=clk)
        ov.record(2, 756425, _delta(), "f1")
        assert list(ov.window_deltas(300.0)) == [(2, 756425)]
        clk.t += 301.0
        assert ov.window_deltas(300.0) == {}
        assert math.isinf(parse_window("inf"))  # inf never ages out

    def test_eviction_bounds_bytes(self):
        ov = RecentDeltaOverlay(budget_bytes=2000, clock=_Clock())
        before = metrics.default.counter("overlay.evicted")
        for i in range(16):
            ov.record(2, 756425, _delta(), f"flush-{i}")
        snap = ov.snapshot()
        assert snap["evicted"] > 0
        assert snap["bytes"] <= 2000
        assert snap["entries"] >= 1  # never evicts to empty
        assert metrics.default.counter("overlay.evicted") \
            == before + snap["evicted"]


class TestWindowQueries:
    def test_windowless_byte_identity(self, tmp_path, monkeypatch):
        """window=None never touches the tier: answers are
        byte-identical to a store where the tier is gate-disabled."""
        ds_on = LocalDatastore(str(tmp_path / "on"))
        assert ds_on.enable_freshness() is not None
        monkeypatch.setenv("REPORTER_TPU_FRESHNESS", "0")
        ds_off = LocalDatastore(str(tmp_path / "off"))
        assert ds_off.enable_freshness() is None
        for ds in (ds_on, ds_off):
            ds.ingest_segments(_segs(20), ingest_key="seed")
        a = json.dumps(ds_on.query(SID), sort_keys=True)
        b = json.dumps(ds_off.query(SID), sort_keys=True)
        assert a == b
        a = json.dumps(ds_on.query_bbox(WORLD, 2), sort_keys=True)
        b = json.dumps(ds_off.query_bbox(WORLD, 2), sort_keys=True)
        assert a == b

    def test_finite_window_sees_recent_only(self, tmp_path):
        clk = _Clock()
        ds = LocalDatastore(str(tmp_path))
        ds.enable_freshness(clock=clk)
        ds.ingest_segments(_segs(5), ingest_key="f1")
        assert ds.query(SID, window="5m")["count"] == 5
        assert ds.query(SID, window=60)["count"] == 5
        clk.t += 600.0
        assert ds.query(SID, window="5m")["count"] == 0
        # the durable store is unaffected by overlay aging
        assert ds.query(SID)["count"] == 5

    def test_inf_parity_after_flush_and_compact(self, tmp_path):
        """The acceptance pin: once every append committed and a
        compaction ran, window=∞ is byte-identical to the plain
        query."""
        ds = LocalDatastore(str(tmp_path))
        ds.enable_freshness()
        ds.ingest_segments(_segs(7), ingest_key="a")
        ds.ingest_segments(_segs(4, t0=MON_8AM + 3600), ingest_key="b")
        ds.compact()
        plain = json.dumps(ds.query(SID), sort_keys=True)
        merged = json.dumps(ds.query(SID, window="inf"), sort_keys=True)
        assert merged == plain
        plain = json.dumps(ds.query_bbox(WORLD, 2), sort_keys=True)
        merged = json.dumps(ds.query_bbox(WORLD, 2, window="inf"),
                            sort_keys=True)
        assert merged == plain

    def test_inf_serves_uncommitted_until_replay_lands(self, tmp_path):
        """A spooled flush (append failed -> in_store=False) exists only
        in the overlay: window=∞ must serve it on top of the compacted
        store, and stop the moment the dead-letter replay commits."""
        ds = LocalDatastore(str(tmp_path))
        tier = ds.enable_freshness()
        ds.ingest_segments(_segs(5), ingest_key="committed")
        tier.overlay.record(2, 756425, _delta(3, t0=MON_8AM + 3600),
                            "spooled-flush", in_store=False)
        assert ds.query(SID)["count"] == 5
        assert ds.query(SID, window="inf")["count"] == 8
        # the replay lands (same ledger key): ∞ converges back
        ds.ingest_segments(_segs(3, t0=MON_8AM + 3600),
                           ingest_key="spooled-flush")
        assert ds.query(SID)["count"] == 8
        assert json.dumps(ds.query(SID, window="inf"), sort_keys=True) \
            == json.dumps(ds.query(SID), sort_keys=True)

    def test_crash_restart_replay_never_double_counts(self, tmp_path):
        """A restarted tee replays its flushes with the same ingest
        keys: the store ledger dedupes on disk, the fresh overlay
        records each replayed delta as already-committed — so merged
        ∞ reads stay byte-identical to compacted-only."""
        ds = LocalDatastore(str(tmp_path))
        ds.enable_freshness()
        for i in range(3):
            ds.ingest_segments(_segs(4, t0=MON_8AM + i * 60),
                               ingest_key=f"flush-{i}")
        rows_before = ds.stats()["rows"]
        # "crash": a new process = new store handle + empty overlay
        ds2 = LocalDatastore(str(tmp_path))
        tier2 = ds2.enable_freshness()
        for i in range(3):  # the replay
            ds2.ingest_segments(_segs(4, t0=MON_8AM + i * 60),
                                ingest_key=f"flush-{i}")
        assert ds2.stats()["rows"] == rows_before
        assert json.dumps(ds2.query(SID, window="inf"), sort_keys=True) \
            == json.dumps(ds2.query(SID), sort_keys=True)
        # a second replay of the same keys no-ops in the overlay too
        n = tier2.overlay.snapshot()["entries"]
        ds2.ingest_segments(_segs(4), ingest_key="flush-0")
        assert tier2.overlay.snapshot()["entries"] == n

    def test_window_without_tier(self, tmp_path, monkeypatch):
        """Gate-disabled: ∞ degrades to the plain store (the overlay
        would add nothing), finite windows are empty (this process has
        witnessed no recent ingests), windowless untouched."""
        monkeypatch.setenv("REPORTER_TPU_FRESHNESS", "off")
        ds = LocalDatastore(str(tmp_path))
        ds.ingest_segments(_segs(5))
        assert ds.query(SID, window="inf")["count"] == 5
        assert ds.query(SID, window="5m")["count"] == 0
        assert ds.query(SID)["count"] == 5


class TestChangeFeed:
    def _feed(self, **kw):
        return ChangeFeed(store=None, clock=_Clock(), **kw)

    def test_cursor_monotone_and_from_now(self):
        feed = self._feed()
        for i in range(3):
            feed._publish("delta", 2, 756425, [SID], False, 1)
        out = feed.poll(cursor=0, timeout_s=0)
        assert [e["seq"] for e in out["events"]] == [1, 2, 3]
        assert out["cursor"] == 3 and not out["resync"]
        # nothing past the returned cursor
        again = feed.poll(cursor=out["cursor"], timeout_s=0)
        assert again["events"] == [] and again["timeout"]
        # cursor=-1 means "from now": the 3 old events are skipped
        assert feed.poll(cursor=-1, timeout_s=0)["events"] == []

    def test_ring_overflow_is_explicit_resync(self):
        feed = self._feed(ring_events=2)
        for _ in range(5):
            feed._publish("delta", 2, 756425, [SID], False, 1)
        out = feed.poll(cursor=0, timeout_s=0)
        assert out["resync"] is True  # loss is never silent
        assert [e["seq"] for e in out["events"]] == [4, 5]
        # a cursor inside the ring does not resync
        assert feed.poll(cursor=4, timeout_s=0)["resync"] is False

    def test_bbox_filter(self):
        feed = self._feed()
        feed._publish("delta", 2, 756425, [SID], False, 1)
        hit = feed.poll(bbox=WORLD, level=2, cursor=0, timeout_s=0)
        assert len(hit["events"]) == 1
        # a far-away viewport sees nothing (but the cursor advances
        # with the ring so the subscriber never replays the miss)
        miss = feed.poll(bbox=[0.0, 0.0, 0.1, 0.1], level=2, cursor=0,
                         timeout_s=0)
        assert miss["events"] == []
        with pytest.raises(ValueError):
            feed.poll(bbox=WORLD, cursor=0, timeout_s=0)  # needs level

    def test_delta_events_carry_map_version(self, tmp_path):
        """A stamped store's ingest hook threads the active epoch into
        every delta event (ISSUE 20)."""
        ds = LocalDatastore(str(tmp_path))
        tier = ds.enable_freshness()
        ds.set_map_version("aaaa00000001")
        ds.ingest_segments(_segs(2), ingest_key="k1")
        out = tier.feed.poll(cursor=0, timeout_s=0)
        (ev,) = out["events"]
        assert ev["kind"] == "delta"
        assert ev["map_version"] == "aaaa00000001"

    def test_epoch_event_bypasses_viewport_filters(self):
        """publish_epoch announces a map flip to EVERY subscriber —
        whatever bbox/level a dashboard watches, its history predates
        the new map, so the event must reach it."""
        from reporter_tpu.utils import metrics
        c0 = metrics.default.counter("datastore.epoch.events")
        feed = self._feed()
        feed.publish_epoch("bbbb00000002")
        assert metrics.default.counter(
            "datastore.epoch.events") == c0 + 1
        # a far-away viewport that filters out every delta still sees
        # the epoch boundary
        out = feed.poll(bbox=[0.0, 0.0, 0.1, 0.1], level=2, cursor=0,
                        timeout_s=0)
        (ev,) = out["events"]
        assert ev["kind"] == "epoch"
        assert ev["map_version"] == "bbbb00000002"
        assert ev["segments"] == [] and ev["rows"] == 0

    def test_waiter_cap_sheds_explicitly(self):
        feed = self._feed(max_waiters_n=0)
        with pytest.raises(FeedOverload) as exc:
            feed.poll(cursor=0, timeout_s=0)
        assert exc.value.reason == "feed_waiters"
        assert exc.value.retry_after_s >= 1
        assert feed.snapshot()["shed"] == 1

    def test_condition_notified_delivery(self, tmp_path):
        """The no-sleep-polling pin: a blocked poll is woken by the
        ingest hook's condition notify, not by a timer — delivery
        latency is a small fraction of the poll timeout."""
        ds = LocalDatastore(str(tmp_path))
        tier = ds.enable_freshness()
        got = {}

        def subscribe():
            t0 = time.monotonic()
            got["out"] = tier.feed.poll(bbox=WORLD, level=2, cursor=0,
                                        timeout_s=30)
            got["elapsed"] = time.monotonic() - t0

        th = threading.Thread(target=subscribe)
        th.start()
        deadline = time.monotonic() + 10
        while tier.feed.snapshot()["waiters"] == 0:
            assert time.monotonic() < deadline, "subscriber never waited"
            time.sleep(0.005)
        ds.ingest_segments(_segs(3), ingest_key="live")
        th.join(timeout=10)
        assert not th.is_alive()
        assert got["elapsed"] < 5.0
        (ev,) = got["out"]["events"]
        assert ev["kind"] == "delta" and ev["tile_index"] == 756425
        assert SID in ev["segments"] and ev["rows"] == 3

    def test_store_watcher_cross_process(self, tmp_path):
        """A second store handle on the same root (the pre-fork fleet
        shape): its feed surfaces the writer's commits as tile events
        via the manifest-seq diff — after a silent baseline scan."""
        writer = LocalDatastore(str(tmp_path))
        writer.ingest_segments(_segs(2), ingest_key="old")
        reader = LocalDatastore(str(tmp_path))
        tier = reader.enable_freshness()
        # first scan baselines: history is not replayed
        assert tier.feed.watch_store(force=True) == 0
        writer.ingest_segments(_segs(3, t0=MON_8AM + 3600),
                               ingest_key="new")
        assert tier.feed.watch_store(force=True) == 1
        (ev,) = tier.feed.poll(cursor=0, timeout_s=0)["events"]
        assert ev["kind"] == "tile" and ev["tile_index"] == 756425
        assert ev["segments"] == []  # sweep the tile, ids unknown here


class TestViewportSummaries:
    def test_compactor_pass_materialises(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        tier = ds.enable_freshness()
        ds.ingest_segments(_segs(20), ingest_key="seed")
        BackgroundCompactor(ds).run_once()
        assert tier.viewports.snapshot() == {"tiles": 1, "refreshes": 1}
        out = tier.viewports.summarise(WORLD, 2)
        assert out["n_tiles"] == 1 and out["count"] == 20
        (tile,) = out["tiles"]
        assert tile["tile_index"] == 756425 and tile["n_segments"] == 1
        assert tile["mean_kph"] == pytest.approx(36.0)
        assert sum(tile["histogram"]["counts"]) == 20

    def test_refresh_memoised_by_manifest_seq(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        tier = ds.enable_freshness()
        ds.ingest_segments(_segs(5), ingest_key="a")
        assert tier.viewports.refresh()["refreshed"] == 1
        assert tier.viewports.refresh()["refreshed"] == 0  # unchanged
        ds.ingest_segments(_segs(5, t0=MON_8AM + 60), ingest_key="b")
        assert tier.viewports.refresh()["refreshed"] == 1
        assert tier.viewports.summarise(WORLD, 2)["count"] == 10

    def test_empty_viewport(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        tier = ds.enable_freshness()
        out = tier.viewports.summarise(WORLD, 2)
        assert out == {"bbox": WORLD, "level": 2, "n_tiles": 0,
                       "count": 0, "tiles": []}


class _StubMatcher:
    def match_many(self, traces):
        return [[] for _ in traces]


@pytest.fixture
def fresh_server(tmp_path):
    """A served stack with the freshness tier live: the store's ingests
    happen IN the serving process, so finite windows and delta events
    work (the co-located-tee shape)."""
    from reporter_tpu.service.server import ReporterService, serve
    ds = LocalDatastore(str(tmp_path / "store"))
    ds.enable_freshness()
    ds.ingest_segments(_segs(20), ingest_key="seed-flush")
    service = ReporterService(_StubMatcher(), datastore=ds)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    httpd = serve(service, "127.0.0.1", port)
    yield f"http://127.0.0.1:{port}", ds
    httpd.shutdown()


def _get(url):
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


class TestFreshnessHTTP:
    def test_window_param(self, fresh_server):
        url, _ds = fresh_server
        code, body, _ = _get(f"{url}/histogram?segment_id={SID}&window=5m")
        assert code == 200 and body["count"] == 20
        code, body, _ = _get(f"{url}/histogram?bbox=-180,-90,180,90"
                             "&level=2&window=300s")
        assert code == 200 and body["segments"][0]["count"] == 20

    def test_inf_window_byte_identical_over_http(self, fresh_server):
        url, _ds = fresh_server
        plain = urllib.request.urlopen(
            f"{url}/histogram?segment_id={SID}").read()
        merged = urllib.request.urlopen(
            f"{url}/histogram?segment_id={SID}&window=inf").read()
        assert merged == plain

    def test_bad_window_400(self, fresh_server):
        url, _ds = fresh_server
        code, body, _ = _get(f"{url}/histogram?segment_id={SID}"
                             "&window=fortnight")
        assert code == 400 and "window" in body["error"]

    def test_viewport_summaries(self, fresh_server):
        url, _ds = fresh_server
        code, body, _ = _get(f"{url}/histogram?viewport=1"
                             "&bbox=-180,-90,180,90&level=2")
        assert code == 200
        assert body["n_tiles"] == 1 and body["count"] == 20
        code, body, _ = _get(f"{url}/histogram?viewport=1")
        assert code == 400 and "bbox" in body["error"]

    def test_feed_delivers_seed_events(self, fresh_server):
        url, _ds = fresh_server
        code, body, _ = _get(f"{url}/feed?cursor=0&timeout=0.2"
                             "&bbox=-180,-90,180,90&level=2")
        assert code == 200
        (ev,) = body["events"]
        assert ev["kind"] == "delta" and SID in ev["segments"]
        assert body["cursor"] == ev["seq"]

    def test_feed_long_poll_end_to_end(self, fresh_server):
        """The e2e freshness proof at the HTTP surface: an open /feed
        cursor is delivered the ingest the moment it lands (condition
        notify through the whole stack), and ``window=5m`` serves the
        same rows immediately after."""
        url, ds = fresh_server
        cur = ds.freshness.feed.cursor
        got = {}

        def subscribe():
            t0 = time.monotonic()
            got["resp"] = _get(f"{url}/feed?cursor={cur}&timeout=30"
                               "&bbox=-180,-90,180,90&level=2")
            got["elapsed"] = time.monotonic() - t0

        th = threading.Thread(target=subscribe)
        th.start()
        deadline = time.monotonic() + 10
        while ds.freshness.feed.snapshot()["waiters"] == 0:
            assert time.monotonic() < deadline, "no waiter registered"
            time.sleep(0.005)
        ds.ingest_segments(_segs(5, t0=MON_8AM + 7200),
                           ingest_key="live-flush")
        th.join(timeout=10)
        assert not th.is_alive() and got["elapsed"] < 5.0
        code, body, _ = got["resp"]
        assert code == 200
        (ev,) = body["events"]
        assert ev["kind"] == "delta" and ev["rows"] == 5
        code, body, _ = _get(f"{url}/histogram?segment_id={SID}"
                             "&window=5m")
        assert code == 200 and body["count"] == 25

    def test_feed_waiter_shed_429_retry_after(self, fresh_server):
        url, ds = fresh_server
        feed = ds.freshness.feed
        old = feed.max_waiters
        feed.max_waiters = 0
        try:
            code, body, headers = _get(f"{url}/feed?cursor=0&timeout=0.1")
            assert code == 429
            assert body["reason"] == "feed_waiters"
            assert headers.get("Retry-After") == str(body["retry_after_s"])
        finally:
            feed.max_waiters = old

    def test_feed_pressure_shed_before_match_path(self, fresh_server):
        """PR 14 integration: at the FEED_SHED_LEVEL rung the feed
        sheds subscribers with the explicit 429 + Retry-After contract
        — fan-out is the first load dropped under pressure."""
        from reporter_tpu.service import admission
        url, _ds = fresh_server
        lad = admission.ladder()
        lad.level = 2
        try:
            code, body, headers = _get(f"{url}/feed?cursor=0&timeout=0.1")
            assert code == 429 and body["reason"] == "pressure"
            assert "Retry-After" in headers
        finally:
            admission._reset_module()

    def test_health_freshness_block(self, fresh_server):
        url, ds = fresh_server
        snap = ds.freshness.snapshot()
        assert snap["overlay"]["entries"] == 1
        assert snap["feed"]["cursor"] >= 1
        assert set(snap) == {"overlay", "feed", "viewports"}


class TestFreshnessCLI:
    def _seed(self, tmp_path, n=5):
        ds = LocalDatastore(str(tmp_path / "s"))
        ds.enable_freshness()
        ds.ingest_segments(_segs(n), ingest_key="cli-seed")
        return ds

    def test_query_window_inf_cross_process(self, tmp_path, capsys):
        from reporter_tpu.tools import datastore_cli
        self._seed(tmp_path)
        assert datastore_cli.main(
            ["query", str(tmp_path / "s"), "--segment", str(SID),
             "--window", "inf"]) == 0
        got = json.loads(capsys.readouterr().out.strip())
        assert got["count"] == 5

    def test_query_finite_window_needs_colocated_tee(self, tmp_path,
                                                     capsys):
        # a fresh CLI process has witnessed no recent ingests: finite
        # windows are empty there (documented), ∞/windowless are not
        from reporter_tpu.tools import datastore_cli
        self._seed(tmp_path)
        assert datastore_cli.main(
            ["query", str(tmp_path / "s"), "--segment", str(SID),
             "--window", "5m"]) == 0
        assert json.loads(capsys.readouterr().out.strip())["count"] == 0

    def test_query_bad_window_exits_cleanly(self, tmp_path, capsys):
        from reporter_tpu.tools import datastore_cli
        self._seed(tmp_path)
        with pytest.raises(SystemExit):
            datastore_cli.main(["query", str(tmp_path / "s"),
                                "--segment", str(SID),
                                "--window", "fortnight"])

    def test_feed_tails_cross_process_commits(self, tmp_path, capsys):
        """`datastore feed` long-polls a store another handle is
        writing to: the in-poll store watcher surfaces the commit as a
        tile event before the poll times out."""
        from reporter_tpu.tools import datastore_cli
        writer = self._seed(tmp_path)

        def late_ingest():
            time.sleep(0.4)  # after the feed's baseline scan
            writer.ingest_segments(_segs(3, t0=MON_8AM + 3600),
                                   ingest_key="late")

        th = threading.Thread(target=late_ingest)
        th.start()
        try:
            assert datastore_cli.main(
                ["feed", str(tmp_path / "s"), "--cursor", "0",
                 "--timeout", "15", "--max-polls", "1"]) == 0
        finally:
            th.join()
        got = json.loads(capsys.readouterr().out.strip())
        assert got["events"], "commit not delivered within one poll"
        assert got["events"][0]["kind"] == "tile"
        assert got["events"][0]["tile_index"] == 756425

    def test_feed_timeout_line(self, tmp_path, capsys):
        from reporter_tpu.tools import datastore_cli
        self._seed(tmp_path)
        assert datastore_cli.main(
            ["feed", str(tmp_path / "s"), "--cursor", "-1",
             "--timeout", "0.05", "--max-polls", "2"]) == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 2
        for line in lines:
            got = json.loads(line)
            assert got["timeout"] is True and got["events"] == []


class TestWorkerTeeFreshness:
    """The ISSUE's acceptance proof at the real producer: a probe
    flushed by a StreamWorker's tee is (a) delivered on an open /feed
    cursor and (b) visible via window=5m — within one tee cycle, with
    delivery via condition notify (the subscriber blocks in poll(),
    never sleep-polls), while windowless queries stay untouched."""

    def test_tee_flush_reaches_feed_and_window(self, tmp_path):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        from reporter_tpu.streaming.formatter import Formatter
        from reporter_tpu.streaming.worker import StreamWorker, \
            inproc_submitter
        from reporter_tpu.synth import build_grid_city, generate_trace

        city = build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=5,
                               service_road_fraction=0.0,
                               internal_fraction=0.0)
        service = ReporterService(SegmentMatcher(net=city),
                                  threshold_sec=15, max_batch=64,
                                  max_wait_ms=5.0)
        store = LocalDatastore(str(tmp_path / "store"))
        tier = store.enable_freshness()

        rng = np.random.default_rng(9)
        lines = []
        for i in range(6):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"veh-{i}", rng, noise_m=3.0,
                                    min_route_edges=8)
            for p in tr.points:
                lines.append("|".join([
                    "x", tr.uuid, str(p["lat"]), str(p["lon"]),
                    str(p["time"]), str(p["accuracy"])]))

        def tee(_tile, segments, ingest_key=None):
            store.ingest_segments(segments, ingest_key=ingest_key)

        def run_worker(out_dir):
            anon = Anonymiser(TileSink(str(tmp_path / out_dir)),
                              privacy=1, quantisation=3600,
                              source="test", tee=tee)
            worker = StreamWorker(
                Formatter.from_config(",sv,\\|,1,2,3,4,5"),
                inproc_submitter(service), anon, flush_interval_s=1e9)
            worker.run(lines)
            assert worker.parse_failures == 0

        # the open cursor: subscribed BEFORE any flush lands (no bbox
        # filter — the synthetic city's segments live at level 1 and
        # viewport filtering has its own tests)
        got = {}

        def subscribe():
            got["out"] = tier.feed.poll(cursor=0, timeout_s=60)

        th = threading.Thread(target=subscribe)
        th.start()
        deadline = time.monotonic() + 10
        while tier.feed.snapshot()["waiters"] == 0:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        try:
            run_worker("results")
        finally:
            th.join(timeout=30)
        assert not th.is_alive()
        assert got["out"]["events"], "tee flush never reached the feed"
        ev = got["out"]["events"][0]
        assert ev["kind"] == "delta" and ev["rows"] > 0

        # the flushed probe is queryable through the 5m window NOW
        sid = ev["segments"][0]
        fresh = store.query(sid, window="5m")
        assert fresh["count"] > 0
        plain = store.query(sid)
        assert plain["count"] >= fresh["count"]
        assert json.dumps(store.query(sid, window="inf"),
                          sort_keys=True) \
            == json.dumps(plain, sort_keys=True)

        # crash-restart replay: the same flushes (same deterministic
        # ingest keys) through a restarted handle never double-count
        rows_before = store.stats()["rows"]
        assert rows_before > 0
        restarted = LocalDatastore(str(tmp_path / "store"))
        restarted.enable_freshness()

        def tee2(_tile, segments, ingest_key=None,
                 _ds=restarted):
            _ds.ingest_segments(segments, ingest_key=ingest_key)

        tee_fn = tee2

        anon = Anonymiser(TileSink(str(tmp_path / "results2")),
                          privacy=1, quantisation=3600, source="test",
                          tee=tee_fn)
        worker = StreamWorker(
            Formatter.from_config(",sv,\\|,1,2,3,4,5"),
            inproc_submitter(service), anon, flush_interval_s=1e9)
        worker.run(lines)
        assert restarted.stats()["rows"] == rows_before
        assert json.dumps(restarted.query(sid, window="inf"),
                          sort_keys=True) \
            == json.dumps(restarted.query(sid), sort_keys=True)


class TestFeedFanoutArtifact:
    """The committed fan-out artifact (BENCH_FEED_r01.json), its
    ledger normalisation, and the perf_gate leg that binds the
    zero-silent-loss contract to it."""

    def test_committed_artifact(self):
        """The checked-in 1000-subscriber run: acceptance scale, every
        subscriber accounted for, nothing silently lost."""
        with open(os.path.join(REPO, "BENCH_FEED_r01.json")) as f:
            art = json.load(f)
        assert art["kind"] == "feed_fanout"
        assert art["subscribers"] >= 1000
        assert art["silent_lost"] == 0
        assert art["errors"] == 0
        assert art["delivered"] + art["shed"] == art["subscribers"]
        assert art["delivery_p99_ms"] is not None

    def test_ledger_entry_normalisation(self):
        from reporter_tpu.obs import ledger
        entry = ledger._feed_entry("BENCH_FEED_r01.json", {
            "kind": "feed_fanout", "subscribers": 1000, "procs": 2,
            "delivered": 1000, "shed": 0, "shed_events": 7,
            "errors": 0, "silent_lost": 0, "fanout_ratio": 1.0,
            "delivery_p99_ms": 950.0})
        assert entry["kind"] == "feed_fanout"
        assert entry["scope"] == "full"
        assert entry["vs_baseline"] == 1.0
        assert entry["ok"] is True
        assert "p99_ms=950.0" in entry["context"]
        smoke = ledger._feed_entry("BENCH_FEED_x.json", {
            "kind": "feed_fanout", "subscribers": 128, "delivered": 120,
            "shed": 7, "errors": 0, "silent_lost": 1,
            "fanout_ratio": 0.9375})
        assert smoke["scope"] == "smoke"
        assert smoke["ok"] is False  # silent loss flips the verdict

    def test_feed_kind_never_pools_with_bench(self):
        """The fanout ratio (~1.0) must not bleed into the bench
        vs_baseline medians perf_gate compares against."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import perf_gate
        entries = [
            {"kind": "bench", "scope": "full", "platform": "cpu",
             "vs_baseline": 20.0},
            {"kind": "feed_fanout", "scope": "full", "platform": "cpu",
             "vs_baseline": 1.0},
        ]
        pool = perf_gate.comparable_pool(entries, "cpu", "full")
        assert len(pool) == 1 and pool[0]["kind"] == "bench"

    def test_seeded_ledger_contains_feed(self):
        from reporter_tpu.obs import ledger
        entries = ledger.seed_entries(REPO)
        feed = [e for e in entries if e["kind"] == "feed_fanout"]
        assert feed, "committed BENCH_FEED artifacts must seed the ledger"
        assert all(e["ok"] for e in feed)

    def test_gate_passes_committed_and_fails_loss(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import perf_gate
        ok, verdict = perf_gate.gate_feed(
            os.path.join(REPO, "BENCH_FEED_r01.json"), 0.95)
        assert ok, verdict
        # one silently lost subscriber fails the gate whatever the ratio
        bad = {"kind": "feed_fanout", "subscribers": 100,
               "delivered": 99, "shed": 0, "errors": 0,
               "silent_lost": 1, "fanout_ratio": 0.99}
        p = tmp_path / "bad.json"
        p.write_text(json.dumps(bad))
        ok, verdict = perf_gate.gate_feed(str(p), 0.0)
        assert not ok
        reasons = " ".join(f["reason"] for f in verdict["failures"])
        assert "zero-silent-loss" in reasons
        # a missing category fails loudly rather than passing vacuously
        p2 = tmp_path / "missing.json"
        p2.write_text(json.dumps({"kind": "feed_fanout",
                                  "subscribers": 100}))
        ok, verdict = perf_gate.gate_feed(str(p2), 0.0)
        assert not ok
        assert "never counted" in verdict["failures"][0]["reason"]
        # open accounting (a subscriber counted twice / not at all)
        p3 = tmp_path / "open.json"
        p3.write_text(json.dumps({"kind": "feed_fanout",
                                  "subscribers": 100, "delivered": 90,
                                  "shed": 0, "errors": 0,
                                  "silent_lost": 0,
                                  "fanout_ratio": 0.9}))
        ok, verdict = perf_gate.gate_feed(str(p3), 0.0)
        assert not ok
        assert "accounting open" in verdict["failures"][0]["reason"]
