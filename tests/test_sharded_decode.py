"""ISSUE 13: device-mesh decode scale-out + adaptive bucketing.

The contract under test is BIT-IDENTITY: the 1-D ``("data",)`` decode
mesh carries no collective, so the sharded scan decode must equal the
single-device scan backend bit for bit — same Viterbi paths, same
/report bytes — at every forced host-device count. Subprocess legs pin
it at N∈{1,2,8} (the device count is fixed at backend init, so each N
is its own interpreter); in-process tests cover the conftest 8-device
mesh, the rows-not-divisible-by-mesh chunk, all-SKIP filler rows, the
adaptive bucket splitter, and the new knobs/gates.
"""
import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from reporter_tpu import ops
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.matcher.batchpad import (DEFAULT_SPLIT_WASTE,
                                           LENGTH_BUCKETS, bucket_ladder)
from reporter_tpu.matcher.matcher import (MatchRuns, _decode_chunk,
                                          match_batch_default,
                                          render_segments_json)
from reporter_tpu.obs import profiler
from reporter_tpu.synth import build_grid_city, generate_trace
from reporter_tpu.utils import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_mesh_cache():
    ops.reset_sharded_cache()
    yield
    ops.reset_sharded_cache()


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=17)


def _mixed_reqs(city, n=5, seed=23, max_edges=10):
    rng = np.random.default_rng(seed)
    reqs = []
    while len(reqs) < n:
        tr = generate_trace(city, f"v{len(reqs)}", rng, noise_m=4.0,
                            min_route_edges=5, max_route_edges=max_edges)
        if tr is not None:
            reqs.append({"uuid": tr.uuid, "trace": tr.points,
                         "match_options": {}})
    return reqs


def _bodies(results):
    out = []
    for r in results:
        if isinstance(r, MatchRuns):
            out.append(render_segments_json(r.cols, r.lo, r.hi, r.mode))
        else:
            out.append(json.dumps(r, separators=(",", ":")))
    return out


# one leg of the forced-host-device parity matrix: seeded city + 5
# traces (NOT divisible by any mesh size — filler rows exercised) end
# to end, plus a raw synthetic decode with an all-SKIP filler row; the
# digest covers report bytes AND path bits
_LEG = r"""
import hashlib, json, os
import numpy as np
from reporter_tpu.utils.runtime import ensure_backend
ensure_backend()
import jax
want = int(os.environ["REPORTER_TPU_VIRTUAL_DEVICES"])
assert len(jax.devices()) == want, (len(jax.devices()), want)
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.matcher.matcher import MatchRuns, render_segments_json
from reporter_tpu.synth import build_grid_city, generate_trace
city = build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=17)
m = SegmentMatcher(net=city, params=MatchParams(max_candidates=6))
if want > 1:
    assert m.decode_mesh is not None and m.decode_mesh.devices.size == want
rng = np.random.default_rng(23)
reqs = []
while len(reqs) < 5:
    tr = generate_trace(city, f"v{len(reqs)}", rng, noise_m=4.0,
                        min_route_edges=5, max_route_edges=10)
    if tr is not None:
        reqs.append({"uuid": tr.uuid, "trace": tr.points,
                     "match_options": {}})
res = m.match_many(reqs)
h = hashlib.sha256()
for r in res:
    if isinstance(r, MatchRuns):
        body = render_segments_json(r.cols, r.lo, r.hi, r.mode)
    else:
        body = json.dumps(r, separators=(",", ":"))
    h.update(body.encode())
from reporter_tpu.matcher.hmm import NORMAL, RESTART, SKIP
from reporter_tpu import ops
rng2 = np.random.default_rng(5)
B, T, K = 8, 16, 4
dist = rng2.uniform(0, 30, (B, T, K)).astype(np.float32)
valid = np.ones((B, T, K), bool)
gc = rng2.uniform(5, 40, (B, T - 1)).astype(np.float32)
route = rng2.uniform(5, 80, (B, T - 1, K, K)).astype(np.float32)
case = np.full((B, T), NORMAL, np.int32)
case[:, 0] = RESTART
case[-1, :] = SKIP  # an all-SKIP filler row must decode inertly
paths, _ = ops.decode_batch(dist, valid, route, gc, case,
                            np.float32(4.07), np.float32(3.0))
if want > 1:
    assert len(paths.sharding.device_set) == want
h.update(np.asarray(paths).tobytes())
print("DIGEST:" + h.hexdigest())
"""


def _run_leg(n_devices: int) -> str:
    env = dict(os.environ,
               REPORTER_TPU_PLATFORM="cpu",
               REPORTER_TPU_VIRTUAL_DEVICES=str(n_devices),
               REPORTER_TPU_DECODE="scan",
               REPORTER_TPU_PIPELINE="0",
               REPORTER_TPU_SHARD="1")
    env.pop("REPORTER_TPU_DEVICE_SLICE", None)
    env.pop("REPORTER_TPU_DECODE_SHARD", None)
    proc = subprocess.run([sys.executable, "-c", _LEG],
                          capture_output=True, text=True, timeout=300,
                          env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    for line in proc.stdout.splitlines():
        if line.startswith("DIGEST:"):
            return line[len("DIGEST:"):]
    raise AssertionError(f"no digest in leg output: {proc.stdout!r}")


class TestForcedHostDeviceParity:
    """The acceptance matrix: N∈{1,2,8} forced host devices, one
    digest over /report bodies + raw path bits, all equal — the
    sharded scan decode IS the single-device scan decode."""

    def test_bit_identity_across_1_2_8_devices(self):
        digests = {n: _run_leg(n) for n in (1, 2, 8)}
        assert digests[2] == digests[1], digests
        assert digests[8] == digests[1], digests


class TestShardedMatchInProcess:
    """In-process (conftest's virtual 8-device mesh): the serving path
    byte-identity + the fan-out sensors."""

    def test_report_bodies_byte_identical_sharded_vs_single(
            self, city, monkeypatch):
        monkeypatch.setenv("REPORTER_TPU_DECODE", "scan")
        m = SegmentMatcher(net=city, params=MatchParams(max_candidates=6))
        reqs = _mixed_reqs(city)  # 5 traces: rows pad 5 -> 8 (filler)
        sharded = _bodies(m.match_many(reqs))
        assert any('"segments":[{' in b for b in sharded)
        monkeypatch.setenv("REPORTER_TPU_DECODE_SHARD", "0")
        ops.reset_sharded_cache()
        single = _bodies(m.match_many(reqs))
        assert sharded == single

    def test_sharded_chunks_counted_and_mesh_in_shape_key(
            self, city, monkeypatch):
        monkeypatch.setenv("REPORTER_TPU_DECODE", "scan")
        profiler.reset()
        before = metrics.default.counter("decode.shard.chunks")
        m = SegmentMatcher(net=city, params=MatchParams(max_candidates=6))
        assert m.decode_mesh is not None
        m.match_many(_mixed_reqs(city))
        assert metrics.default.counter("decode.shard.chunks") > before
        shapes = profiler.snapshot(n_events=0)["shapes"]
        assert shapes and all(s["mesh"] == 8 for s in shapes)

    def test_mesh_change_is_new_shape_not_storm(self, city, monkeypatch):
        """The satellite contract: the same (B, T, K) dispatched on a
        different mesh width is a NEW compile-shape entry — zero
        recompile flags."""
        monkeypatch.setenv("REPORTER_TPU_DECODE", "scan")
        profiler.reset()
        m = SegmentMatcher(net=city, params=MatchParams(max_candidates=6))
        reqs = _mixed_reqs(city)
        m.match_many(reqs)
        monkeypatch.setenv("REPORTER_TPU_DECODE_SHARD", "0")
        ops.reset_sharded_cache()
        m.match_many(reqs)
        shapes = profiler.snapshot(n_events=0)["shapes"]
        meshes = {s["mesh"] for s in shapes}
        assert meshes == {1, 8}
        assert sum(max(0, s["compiles"] - 1) for s in shapes) == 0

    def test_decode_chunk_and_dispatch_depth_scale_with_mesh(
            self, monkeypatch):
        chunk_mesh = _decode_chunk()
        depth_mesh = match_batch_default()
        monkeypatch.setenv("REPORTER_TPU_DECODE_SHARD", "off")
        ops.reset_sharded_cache()
        chunk_one = _decode_chunk()
        assert chunk_mesh == 8 * chunk_one
        assert depth_mesh == max(256, 2 * chunk_mesh)
        # no mesh -> the shipped 256 stands: the 2-chunk depth exists
        # for mesh utilisation, not for fattening single-device
        # batches (tail latency / peak memory)
        assert match_batch_default() == 256

    def test_shard_kill_switches(self, monkeypatch):
        assert ops.decode_mesh_size() == 8
        monkeypatch.setenv("REPORTER_TPU_DECODE_SHARD", "off")
        ops.reset_sharded_cache()
        assert ops.decode_mesh_size() == 1
        assert ops.batch_pad_multiple() is None
        monkeypatch.delenv("REPORTER_TPU_DECODE_SHARD", raising=False)
        monkeypatch.setenv("REPORTER_TPU_SHARD", "0")
        ops.reset_sharded_cache()
        assert ops.decode_mesh_size() == 1

    def test_scan_pad_multiple_is_mesh_size(self, monkeypatch):
        """scan now shards along data (the bit-identity backend), so a
        forced scan backend still pads to the mesh multiple."""
        monkeypatch.setenv("REPORTER_TPU_DECODE", "scan")
        assert ops.batch_pad_multiple() == 8
        monkeypatch.setenv("REPORTER_TPU_DECODE", "pallas")
        assert ops.batch_pad_multiple() is None


class TestDeviceSlice:
    def _slice(self, monkeypatch, spec, n=8):
        from reporter_tpu.parallel import mesh as pmesh
        monkeypatch.setenv(pmesh.ENV_DEVICE_SLICE, spec)
        return pmesh.device_slice(list(range(n)))

    def test_slot_of_procs_blocks(self, monkeypatch):
        assert self._slice(monkeypatch, "0/2") == [0, 1, 2, 3]
        assert self._slice(monkeypatch, "1/2") == [4, 5, 6, 7]
        assert self._slice(monkeypatch, "3/4") == [6, 7]

    def test_more_procs_than_devices_gets_one_each(self, monkeypatch):
        # 8 slots over 4 devices: block math lands slot 5 on device 2,
        # slot 0's empty block falls back to device 0 — every slot
        # always owns exactly one device
        assert self._slice(monkeypatch, "5/8", n=4) == [2]
        assert self._slice(monkeypatch, "0/8", n=4) == [0]

    def test_empty_block_fallback_spreads_evenly(self, monkeypatch):
        # 4 slots over 2 devices must land 2/2, not 3/1: the
        # empty-block fallback uses the proportional index, never
        # slot % n (which piled slots 0 and 2 both onto device 0)
        owned = [self._slice(monkeypatch, f"{s}/4", n=2)[0]
                 for s in range(4)]
        assert owned == [0, 0, 1, 1]

    def test_explicit_range_and_garbage(self, monkeypatch):
        assert self._slice(monkeypatch, "2:4") == [2, 3]
        assert self._slice(monkeypatch, "banana") == list(range(8))
        assert self._slice(monkeypatch, "9/4") == list(range(8))

    def test_sliced_mesh_size(self, monkeypatch):
        monkeypatch.setenv("REPORTER_TPU_DEVICE_SLICE", "0/4")
        ops.reset_sharded_cache()
        assert ops.decode_mesh_size() == 2

    def test_prefork_worker_derives_slot_slice(self, monkeypatch):
        import signal
        from reporter_tpu.service import prefork
        # setenv("") so monkeypatch RECORDS both vars (delenv on an
        # absent var records nothing) and worker_main's direct
        # os.environ writes roll back at teardown; "" is falsy, so the
        # worker still derives its slot slice
        monkeypatch.setenv("REPORTER_TPU_DEVICE_SLICE", "")
        monkeypatch.setenv("REPORTER_TPU_WRITER_ID", "")
        old_term = signal.getsignal(signal.SIGTERM)
        old_int = signal.getsignal(signal.SIGINT)
        captured = {}

        class _Stop(Exception):
            pass

        def boom():
            captured["slice"] = os.environ.get(
                "REPORTER_TPU_DEVICE_SLICE")
            raise _Stop()

        try:
            with pytest.raises(_Stop):
                prefork.worker_main(1, boom, "127.0.0.1", 0, procs=2)
        finally:
            signal.signal(signal.SIGTERM, old_term)
            signal.signal(signal.SIGINT, old_int)
        assert captured["slice"] == "1/2"


class TestAdaptiveBucketing:
    def test_ladder_default_and_env(self, monkeypatch):
        assert bucket_ladder() == (LENGTH_BUCKETS, DEFAULT_SPLIT_WASTE)
        monkeypatch.setenv("REPORTER_TPU_BUCKETS", "8,32,128@0.5")
        assert bucket_ladder() == ((8, 32, 128), 0.5)
        monkeypatch.setenv("REPORTER_TPU_BUCKETS", "@off")
        assert bucket_ladder() == (LENGTH_BUCKETS, 1.0)
        monkeypatch.setenv("REPORTER_TPU_BUCKETS", "64,16@0.2")  # bad
        assert bucket_ladder() == (LENGTH_BUCKETS, DEFAULT_SPLIT_WASTE)

    def test_split_plan_projection(self):
        """A mixed 64-bucket group whose raw lengths project waste past
        the threshold splits into pow2 sub-buckets covering exactly the
        original indices."""
        profiler.reset()
        group = np.arange(8, dtype=np.int64)
        raws = np.array([10, 10, 17, 17, 30, 30, 60, 60], dtype=np.int64)
        before = metrics.default.counter("decode.bucket.split")
        plan = SegmentMatcher._split_bucket(64, group, raws)
        assert [t for t, _ in plan] == [16, 32, 64]
        covered = np.concatenate([idx for _, idx in plan])
        assert sorted(covered.tolist()) == group.tolist()
        assert metrics.default.counter("decode.bucket.split") == before + 1

    @staticmethod
    def _is_noop(plan, T, group):
        return len(plan) == 1 and plan[0][0] == T and plan[0][1] is group

    def test_split_plan_skips_full_buckets(self):
        profiler.reset()
        group = np.arange(4, dtype=np.int64)
        raws = np.array([60, 61, 62, 64], dtype=np.int64)
        assert self._is_noop(
            SegmentMatcher._split_bucket(64, group, raws), 64, group)

    def test_split_plan_consults_recorded_waste(self):
        """The ISSUE wording, pinned: once the PR 8 wide events have
        RECORDED high waste for a shape, the dispatcher splits even a
        group whose raw lengths project full buckets (kept < raw is
        exactly what the projection can't see)."""
        profiler.reset()
        group = np.arange(4, dtype=np.int64)
        raws = np.array([60, 61, 62, 64], dtype=np.int64)
        # record one very wasteful 64-bucket chunk (occupancy 0.1)
        # a mildly-mixed group that PROJECTS under the threshold
        # (1 - 204/256 = 0.20): no split before any chunk is measured
        raws2 = np.array([24, 24, 60, 64], dtype=np.int64)
        assert self._is_noop(
            SegmentMatcher._split_bucket(64, group, raws2), 64, group)
        # record one very wasteful 64-bucket chunk (occupancy 0.1) —
        # the same group now splits on the measured record alone
        profiler.chunk_event(bucket_T=64, K=8, traces=4, rows=4,
                             kept_points=int(0.1 * 4 * 64),
                             raw_points=256)
        plan2 = SegmentMatcher._split_bucket(64, group, raws2)
        assert [t for t, _ in plan2] == [32, 64]
        # full-length raws can't split no matter what the record says
        assert self._is_noop(
            SegmentMatcher._split_bucket(64, group, raws), 64, group)
        profiler.reset()

    def test_split_projection_is_chunk_aware(self):
        """A group one trace past the chunk boundary must not read the
        whole-group pow2 row padding as reclaimable waste: cells are
        accounted per CHUNK, exactly as dispatch pads them, so a
        near-perfectly-packed 513-trace group stays unsplit."""
        profiler.reset()
        group = np.arange(513, dtype=np.int64)
        raws = np.full(513, 64, dtype=np.int64)
        raws[-1] = 16
        plan = SegmentMatcher._split_bucket(64, group, raws, None, 512)
        assert self._is_noop(plan, 64, group)

    def test_split_disabled_by_off_threshold(self, monkeypatch):
        monkeypatch.setenv("REPORTER_TPU_BUCKETS", "@off")
        group = np.arange(8, dtype=np.int64)
        raws = np.array([10] * 8, dtype=np.int64)
        assert self._is_noop(
            SegmentMatcher._split_bucket(64, group, raws), 64, group)

    @pytest.mark.skipif(
        not __import__("reporter_tpu.native", fromlist=["available"])
        .available(), reason="splitter lives in the native dispatch")
    def test_split_results_byte_identical(self, city, monkeypatch):
        """Splitting changes shapes, never bytes: the SKIP tail is
        inert, so a trace decoded at its pow2 sub-bucket yields the
        same report body as at the full ladder bucket."""
        monkeypatch.setenv("REPORTER_TPU_DECODE", "scan")
        m = SegmentMatcher(net=city, params=MatchParams(max_candidates=6))
        # mixed lengths in ONE 64-bucket: 8 traces at raw 18 (pads to
        # 64 fixed, 32 split) + 8 near-full at raw 60 — each sub-batch
        # is a whole mesh multiple, so the split's row re-padding
        # can't eat the reclaimed tail
        reqs = _mixed_reqs(city, n=16, seed=31, max_edges=14)
        for r in reqs[:8]:
            r["trace"] = r["trace"][:18]
        for r in reqs[8:]:
            r["trace"] = r["trace"][:60]
        monkeypatch.setenv("REPORTER_TPU_BUCKETS", "@off")
        profiler.reset()
        fixed = _bodies(m.match_many(reqs))
        waste_fixed = profiler.padding_waste()
        monkeypatch.setenv("REPORTER_TPU_BUCKETS", "@0.2")
        profiler.reset()
        before = metrics.default.counter("decode.bucket.split")
        adaptive = _bodies(m.match_many(reqs))
        waste_adaptive = profiler.padding_waste()
        assert fixed == adaptive
        assert metrics.default.counter("decode.bucket.split") > before
        assert waste_adaptive < waste_fixed


class TestMultichipGate:
    def _art(self, tmp_path, legs, ratios):
        art = {"n_devices": max(l["n_devices"] for l in legs), "rc": 0,
               "ok": True, "skipped": False, "tail": "",
               "legs": legs, "ratios": ratios}
        p = tmp_path / "multichip.json"
        p.write_text(json.dumps(art))
        return str(p)

    def test_gate_rejects_devices_seen_mismatch(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import perf_gate
        path = self._art(tmp_path, [
            {"n_devices": 1, "rc": 0, "traces_per_sec": 100.0,
             "devices_seen": 1},
            {"n_devices": 4, "rc": 0, "traces_per_sec": 90.0,
             "devices_seen": 1},  # the r06 failure mode
        ], {"4": 0.9})
        passed, verdict = perf_gate.gate_multichip(path, 0.5)
        assert not passed
        assert any(f.get("devices_seen") == 1 and f.get("n_devices") == 4
                   for f in verdict["failures"])

    def test_gate_passes_matching_legs(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import perf_gate
        path = self._art(tmp_path, [
            {"n_devices": 1, "rc": 0, "traces_per_sec": 100.0,
             "devices_seen": 1},
            {"n_devices": 4, "rc": 0, "traces_per_sec": 90.0,
             "devices_seen": 4},
        ], {"4": 0.9})
        passed, verdict = perf_gate.gate_multichip(path, 0.5)
        assert passed, verdict

    def test_padding_waste_gate_skip_and_fail(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import perf_gate
        # an EXPLICIT native-less skip passes with a note
        ok, v = perf_gate.gate_padding_waste(
            {"source": "a", "bucketing": {"skipped": "no native"}}, 0.1)
        assert ok and "skipped" in v["note"]
        # a silently missing block still fails loudly
        ok, _v = perf_gate.gate_padding_waste({"source": "a"}, 0.1)
        assert not ok
        # and the ceiling binds
        ok, _v = perf_gate.gate_padding_waste(
            {"source": "a", "bucketing": {"adaptive_waste": 0.2,
                                          "fixed_waste": 0.5}}, 0.1)
        assert not ok


class TestLedgerLegacyScope:
    def test_liveness_only_artifacts_are_legacy(self):
        from reporter_tpu.obs import ledger
        e = ledger._multichip_entry("MULTICHIP_r03.json",
                                    {"n_devices": 8, "rc": 0, "ok": True})
        assert e["scope"] == "legacy"
        assert e["vs_baseline"] is None

    def test_r06_style_mismatched_legs_are_legacy(self):
        from reporter_tpu.obs import ledger
        e = ledger._multichip_entry("MULTICHIP_r06.json", {
            "n_devices": 2, "ok": True, "ratios": {"2": 0.7},
            "legs": [{"n_devices": 1, "devices_seen": 1,
                      "traces_per_sec": 10.0},
                     {"n_devices": 2, "devices_seen": 1,
                      "traces_per_sec": 7.0}]})
        assert e["scope"] == "legacy"
        assert e["vs_baseline"] is None

    def test_measured_artifacts_stay_full(self):
        from reporter_tpu.obs import ledger
        e = ledger._multichip_entry("MULTICHIP_r07.json", {
            "n_devices": 2, "ok": True, "ratios": {"2": 1.1},
            "legs": [{"n_devices": 1, "devices_seen": 1,
                      "traces_per_sec": 10.0},
                     {"n_devices": 2, "devices_seen": 2,
                      "traces_per_sec": 11.0}]})
        assert e["scope"] == "full"
        assert e["vs_baseline"] == 1.1

    def test_committed_legacy_artifacts_out_of_median_pools(self):
        from reporter_tpu.obs import ledger
        entries = ledger.seed_entries(REPO)
        legacy = [e for e in entries if e["kind"] == "multichip"
                  and e["scope"] == "legacy"]
        assert {e["source"] for e in legacy} >= {
            f"MULTICHIP_r0{i}.json" for i in range(1, 6)}
        for e in legacy:
            assert e["vs_baseline"] is None  # can never enter a median
