"""Datastore tests: schema keys, the aggregation kernel, the append-only
store (atomic commits, mmap reads, compaction), ingestion (CSV parity
with the zero-serialisation path, dead-letter replay), the query surface,
the /histogram service action, and the worker-flush round trip the ISSUE
names as the acceptance proof."""
import json
import os
import socket
import threading
import urllib.parse
import urllib.request

import numpy as np
import pytest

from reporter_tpu.core.osmlr import INVALID_SEGMENT_ID, make_segment_id
from reporter_tpu.core.types import Segment
from reporter_tpu.datastore import (
    LocalDatastore,
    ObservationBatch,
    aggregate,
    hours_for_range,
    merge_deltas,
    parse_tile_csv,
)
from reporter_tpu.datastore import schema
from reporter_tpu.datastore.ingest import ingest_dir, scan_tiles
from reporter_tpu.datastore.query import _percentiles
from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink

# Monday 2017-01-02 08:00:00 UTC -> hour-of-week 8
MON_8AM = 1483344000

SID = make_segment_id(2, 756425, 10)
NID = make_segment_id(2, 756425, 11)


def _segs(n, t0=MON_8AM, duration=10.0, length=100, sid=SID, nid=NID,
          spacing=30):
    """n observations of `length` m in `duration` s (36 kph at defaults)."""
    return [Segment(sid, nid, t0 + i * spacing, t0 + i * spacing + duration,
                    length, 0) for i in range(n)]


class TestSchema:
    def test_hist_key_roundtrip(self):
        seg = np.array([SID, NID, 1], dtype=np.int64)
        hour = np.array([0, 8, 167])
        sbin = np.array([0, 7, schema.N_SPEED_BINS - 1])
        key = schema.hist_key(seg, hour, sbin)
        s2, h2, b2 = schema.split_hist_key(key)
        assert (s2 == seg).all() and (h2 == hour).all() and (b2 == sbin).all()

    def test_keys_sort_by_segment_then_hour_then_bin(self):
        lo, hi = schema.segment_key_range(SID)
        assert hi - lo == schema.CELLS_PER_SEGMENT
        below = schema.hist_key(np.array([SID - 1]), np.array([167]),
                                np.array([schema.N_SPEED_BINS - 1]))[0]
        assert below < lo

    def test_max_key_fits_int64(self):
        key = schema.hist_key(np.array([INVALID_SEGMENT_ID]),
                              np.array([167]),
                              np.array([schema.N_SPEED_BINS - 1]))
        assert key.dtype == np.int64 and key[0] > 0

    def test_hour_of_week_monday_epoch(self):
        assert schema.hour_of_week(np.array([MON_8AM]))[0] == 8
        # epoch 0 is Thursday 00:00 -> hour 72
        assert schema.hour_of_week(np.array([0]))[0] == 72
        # a week later, same hour
        assert schema.hour_of_week(np.array([MON_8AM + 7 * 86400]))[0] == 8

    def test_speed_bins(self):
        kph = np.array([0.0, 4.99, 5.0, 36.0, 119.99, 120.0, 500.0])
        bins = schema.speed_bin(kph)
        assert bins.tolist() == [0, 0, 1, 7, 23, 24, 24]

    def test_from_segments_matches_csv_parse(self):
        segs = _segs(5, duration=9.2)  # fractional: exercises rounding
        obs_a = ObservationBatch.from_segments(segs)
        payload = "\n".join([Segment.column_layout()]
                            + [s.csv_row("AUTO", "t") for s in segs])
        obs_b = parse_tile_csv(payload)
        for col in ("segment_id", "next_id", "duration_s", "count",
                    "length_m", "queue_m", "min_ts", "max_ts"):
            np.testing.assert_array_equal(getattr(obs_a, col),
                                          getattr(obs_b, col), err_msg=col)

    def test_valid_mask_drops_bad_rows(self):
        segs = _segs(2) + [Segment(SID, NID, MON_8AM, MON_8AM, 100, 0),
                           Segment(SID, NID, MON_8AM, MON_8AM + 10, 0, 0)]
        obs = ObservationBatch.from_segments(segs)
        assert obs.valid_mask().tolist() == [True, True, False, False]


class TestAggregate:
    def test_counts_and_speed_sums(self):
        deltas = aggregate(ObservationBatch.from_segments(_segs(20)))
        assert list(deltas) == [(2, 756425)]
        d = deltas[(2, 756425)]
        assert len(d) == 1  # one (segment, hour, bin) cell
        assert d.hist_count[0] == 20
        assert d.hist_speed_sum[0] == pytest.approx(20 * 36.0)
        seg, hour, sbin = schema.split_hist_key(d.hist_key)
        assert seg[0] == SID and hour[0] == 8 and sbin[0] == 7

    def test_transitions_exclude_invalid_next(self):
        segs = _segs(3) + [Segment(SID, None, MON_8AM, MON_8AM + 10, 100, 0)]
        d = aggregate(ObservationBatch.from_segments(segs))[(2, 756425)]
        assert d.trans_from.tolist() == [SID]
        assert d.trans_to.tolist() == [NID]
        assert d.trans_count.tolist() == [3]
        # the invalid-next observation still lands in the histogram
        assert d.hist_count.sum() == 4

    def test_multi_partition_split(self):
        other = make_segment_id(0, 99, 1)
        segs = _segs(2) + _segs(3, sid=other, nid=None)
        deltas = aggregate(ObservationBatch.from_segments(segs))
        assert set(deltas) == {(2, 756425), (0, 99)}
        assert deltas[(0, 99)].hist_count.sum() == 3

    def test_merge_deltas_equals_single_pass(self):
        a = _segs(10, duration=10.0)           # 36 kph
        b = _segs(10, duration=20.0, spacing=60)  # 18 kph
        d_all = aggregate(ObservationBatch.from_segments(a + b))[(2, 756425)]
        d_merged = merge_deltas([
            aggregate(ObservationBatch.from_segments(a))[(2, 756425)],
            aggregate(ObservationBatch.from_segments(b))[(2, 756425)]])
        np.testing.assert_array_equal(d_all.hist_key, d_merged.hist_key)
        np.testing.assert_array_equal(d_all.hist_count, d_merged.hist_count)
        np.testing.assert_allclose(d_all.hist_speed_sum,
                                   d_merged.hist_speed_sum)
        np.testing.assert_array_equal(d_all.trans_count, d_merged.trans_count)

    def test_empty_batch(self):
        assert aggregate(ObservationBatch.empty()) == {}


class TestStore:
    def test_append_commit_is_atomic(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        ds.ingest_segments(_segs(5))
        pdir = ds.partition_dir(2, 756425)
        manifest = json.load(open(os.path.join(pdir, "MANIFEST.json")))
        assert manifest["segments"] == ["delta-000001"]
        # no temp debris after a clean commit
        assert not [d for d in os.listdir(pdir) if d.startswith(".tmp")]
        assert not os.path.exists(os.path.join(pdir, ".MANIFEST.tmp"))

    def test_reads_are_mmapped(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        ds.ingest_segments(_segs(5))
        (part,) = ds.live_segments(2, 756425)
        assert isinstance(part.hist_key, np.memmap)
        assert isinstance(part.hist_speed_sum, np.memmap)

    def test_compact_merges_and_preserves_query(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        for _ in range(4):
            ds.ingest_segments(_segs(5))
        before = ds.query(SID)
        assert ds.stats()["segments"] == 4
        out = ds.compact()
        assert out == {"partitions": 1, "merged_segments": 4, "skipped": 0}
        assert ds.stats()["segments"] == 1
        after = ds.query(SID)
        assert after == before
        # idempotent: single-segment partitions are left alone
        assert ds.compact()["merged_segments"] == 0

    def test_compact_filters_by_partition(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        other = make_segment_id(0, 99, 1)
        for _ in range(2):
            ds.ingest_segments(_segs(2))
            ds.ingest_segments(_segs(2, sid=other, nid=None))
        assert ds.compact(level=0)["merged_segments"] == 2
        assert ds.stats()["segments"] == 3  # level-2 partition untouched

    def test_stats_counts(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        ds.ingest_segments(_segs(20))
        s = ds.stats()
        assert s["partitions"] == 1 and s["segments"] == 1
        assert s["rows"] == 20 and s["cells"] == 1 and s["bytes"] > 0

    def test_unknown_partition_queries_empty(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        r = ds.query(SID)
        assert r["count"] == 0 and r["mean_kph"] is None
        assert r["transitions"] == []


class TestEpochs:
    """Epoch'd histograms (ISSUE 20): the store's active map_version
    stamps manifests and ledger keys, compaction groups by epoch, and
    queries pin to ONE epoch by default with ``merge=`` the explicit
    opt-in — histograms never silently mix map builds."""

    MV_A = "aaaa00000001"
    MV_B = "bbbb00000002"

    def _two_epoch_store(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        ds.set_map_version(self.MV_A)
        ds.ingest_segments(_segs(4), ingest_key="k1")
        ds.set_map_version(self.MV_B)
        # same cell, slower traffic: the epochs must stay tellable
        ds.ingest_segments(_segs(4, duration=20.0), ingest_key="k2")
        return ds

    def test_ledger_keys_are_epoch_qualified(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        ds.set_map_version(self.MV_A)
        assert ds.ingest_segments(_segs(3), ingest_key="k") == 3
        # same key, same epoch: exactly-once dedupe as ever
        assert ds.ingest_segments(_segs(3), ingest_key="k") == 0
        # same key, NEW epoch: the post-swap re-score of the same
        # traffic is new data, not a duplicate
        ds.set_map_version(self.MV_B)
        assert ds.ingest_segments(_segs(3), ingest_key="k") == 3

    def test_manifest_epoch_tags_and_counter(self, tmp_path):
        from reporter_tpu.utils import metrics
        c0 = metrics.default.counter("datastore.epoch.stamped_segments")
        ds = self._two_epoch_store(tmp_path)
        pdir = ds.partition_dir(2, 756425)
        manifest = json.load(open(os.path.join(pdir, "MANIFEST.json")))
        assert manifest["map_version"] == self.MV_B
        tags = manifest["epochs"]
        assert set(tags) == set(manifest["segments"])
        assert sorted(tags.values()) == [self.MV_A, self.MV_B]
        assert metrics.default.counter(
            "datastore.epoch.stamped_segments") == c0 + 2

    def test_default_pin_is_active_version_merge_is_opt_in(self,
                                                          tmp_path):
        from reporter_tpu.utils import metrics
        ds = self._two_epoch_store(tmp_path)
        p0 = metrics.default.counter("datastore.epoch.pinned_queries")
        m0 = metrics.default.counter("datastore.epoch.merged_queries")
        latest = ds.query(SID)
        pin_a = ds.query(SID, map_version=self.MV_A)
        pin_b = ds.query(SID, map_version=self.MV_B)
        merged = ds.query(SID, merge=True)
        assert latest == pin_b  # default = the ACTIVE version
        assert pin_a["mean_kph"] != pin_b["mean_kph"]
        assert merged["count"] == pin_a["count"] + pin_b["count"]
        assert metrics.default.counter(
            "datastore.epoch.pinned_queries") == p0 + 3
        assert metrics.default.counter(
            "datastore.epoch.merged_queries") == m0 + 1
        with pytest.raises(ValueError):
            ds.query(SID, map_version=self.MV_A, merge=True)

    def test_query_many_and_bbox_thread_the_pin(self, tmp_path):
        ds = self._two_epoch_store(tmp_path)
        (one_a,) = ds.query_many([SID], map_version=self.MV_A)
        assert one_a == ds.query(SID, map_version=self.MV_A)
        (one_m,) = ds.query_many([SID], merge=True)
        assert one_m == ds.query(SID, merge=True)
        bb = ds.query_bbox((-180, -90, 180, 90), 2,
                           map_version=self.MV_A)
        assert bb["segments"][0] == dict(one_a, segment_id=SID)

    def test_compaction_groups_by_epoch(self, tmp_path):
        """One base per EPOCH — compaction never merges across map
        versions, and every pinned answer is byte-stable across it."""
        ds = LocalDatastore(str(tmp_path))
        ds.set_map_version(self.MV_A)
        for k in range(2):
            ds.ingest_segments(_segs(3), ingest_key=f"a{k}")
        ds.set_map_version(self.MV_B)
        for k in range(2):
            ds.ingest_segments(_segs(3, duration=20.0),
                               ingest_key=f"b{k}")
        pin_a = ds.query(SID, map_version=self.MV_A)
        pin_b = ds.query(SID, map_version=self.MV_B)
        merged = ds.query(SID, merge=True)
        assert ds.compact()["merged_segments"] == 4
        pdir = ds.partition_dir(2, 756425)
        manifest = json.load(open(os.path.join(pdir, "MANIFEST.json")))
        assert len(manifest["segments"]) == 2
        assert sorted(manifest["epochs"].values()) \
            == [self.MV_A, self.MV_B]
        assert ds.query(SID, map_version=self.MV_A) == pin_a
        assert ds.query(SID, map_version=self.MV_B) == pin_b
        assert ds.query(SID, merge=True) == merged

    def test_untagged_legacy_segments_pass_any_pin(self, tmp_path):
        """Enabling versioning on an existing store hides nothing:
        pre-versioning segments (no epoch tag) serve under every pin."""
        ds = LocalDatastore(str(tmp_path))
        ds.ingest_segments(_segs(3), ingest_key="legacy")
        before = ds.query(SID)
        ds.set_map_version(self.MV_A)
        assert ds.query(SID) == before  # default pin
        assert ds.query(SID, map_version="ffff00000009") == before
        assert ds.query(SID, merge=True) == before


class TestIngestDir:
    def _flush_layout(self, root, segs, name="rtpu.abc123"):
        tile_dir = os.path.join(root, "1483344000_1483347599", "2", "756425")
        os.makedirs(tile_dir, exist_ok=True)
        payload = "\n".join([Segment.column_layout()]
                            + [s.csv_row("AUTO", "t") for s in segs])
        with open(os.path.join(tile_dir, name), "w") as f:
            f.write(payload)

    def test_scan_skips_deadletter_and_dotfiles(self, tmp_path):
        self._flush_layout(str(tmp_path), _segs(2))
        self._flush_layout(os.path.join(str(tmp_path), ".deadletter"),
                           _segs(2), name="rtpu.spooled")
        open(os.path.join(str(tmp_path), ".state"), "w").close()
        files = list(scan_tiles(str(tmp_path)))
        assert len(files) == 1 and files[0].endswith("rtpu.abc123")

    def test_scan_skips_flightrec_dumps(self, tmp_path):
        """The flight recorder's postmortems share the spool layout —
        an ingest replay must never mistake span JSON for tile CSV
        (same contract as .traces/.deadletter)."""
        self._flush_layout(str(tmp_path), _segs(2))
        rec = os.path.join(str(tmp_path), ".flightrec")
        os.makedirs(rec)
        with open(os.path.join(rec, "flightrec-1-0001-crash.json"),
                  "w") as f:
            f.write('{"reason":"crash.worker.offer","spans":[]}')
        files = list(scan_tiles(str(tmp_path)))
        assert len(files) == 1 and files[0].endswith("rtpu.abc123")
        # and the same holds scanning a dead-letter spool that carries
        # a nested .flightrec (the default dump location)
        dl = tmp_path / "dl"
        self._flush_layout(str(dl), _segs(2), name="rtpu.spooled")
        os.makedirs(str(dl / ".flightrec"))
        with open(str(dl / ".flightrec" / "flightrec-1-0002-x.json"),
                  "w") as f:
            f.write("{}")
        files = list(scan_tiles(str(dl)))
        assert len(files) == 1 and files[0].endswith("rtpu.spooled")

    def test_ingest_dir_and_delete(self, tmp_path):
        out_dir = tmp_path / "results"
        self._flush_layout(str(out_dir), _segs(5))
        self._flush_layout(str(out_dir), _segs(3), name="rtpu.def456")
        ds = LocalDatastore(str(tmp_path / "store"))
        got = ingest_dir(ds, str(out_dir), delete=True)
        assert got == {"files": 2, "rows": 8, "failures": 0}
        assert list(scan_tiles(str(out_dir))) == []  # replay-safe
        assert ds.query(SID)["count"] == 8

    def test_corrupt_file_counted_not_fatal(self, tmp_path):
        out_dir = tmp_path / "results"
        self._flush_layout(str(out_dir), _segs(2))
        bad = os.path.join(str(out_dir), "1483344000_1483347599", "2",
                           "756425", "rtpu.bad")
        with open(bad, "w") as f:
            f.write("segment_id,\nnot,a,tile")
        ds = LocalDatastore(str(tmp_path / "store"))
        got = ingest_dir(ds, str(out_dir))
        # short rows are dropped row-wise, so the bad file parses to empty
        assert got["files"] == 2 and got["rows"] == 2

    def test_failing_file_quarantined_not_replayed(self, tmp_path):
        # a 10-column row with a non-numeric id raises in the columnar
        # conversion — the file must be quarantined so the next replay
        # cannot double-count any partially committed partitions
        out_dir = tmp_path / "results"
        self._flush_layout(str(out_dir), _segs(2))
        bad = os.path.join(str(out_dir), "1483344000_1483347599", "2",
                           "756425", "rtpu.poison")
        with open(bad, "w") as f:
            f.write("nan?,,1,1,100,0,10,20,s,AUTO")
        ds = LocalDatastore(str(tmp_path / "store"))
        got = ingest_dir(ds, str(out_dir))
        assert got["failures"] == 1 and got["files"] == 1
        assert not os.path.exists(bad)
        assert os.path.exists(os.path.join(os.path.dirname(bad),
                                           ".rtpu.poison.failed"))
        # the quarantined file is invisible to the next replay — and the
        # good file's relpath is already in the partition ledger, so the
        # re-replay is a counted no-op instead of a double count
        from reporter_tpu.utils import metrics
        before = metrics.default.counter("datastore.ingest.deduped")
        again = ingest_dir(ds, str(out_dir))
        assert again == {"files": 1, "rows": 0, "failures": 0}
        assert metrics.default.counter("datastore.ingest.deduped") > before


class TestDeadLetterReplay:
    def test_failed_egress_spools_and_replays(self, tmp_path, monkeypatch):
        from reporter_tpu.utils import metrics
        metrics.default.reset()
        # an http sink whose endpoint is down, spooling under tmp
        dl = str(tmp_path / "dl")
        monkeypatch.setattr("reporter_tpu.utils.http.egress_tile",
                            lambda *a, **kw: False)
        sink = TileSink("http://127.0.0.1:9", deadletter=dl)
        anon = Anonymiser(sink, privacy=1, quantisation=3600, source="t")
        for s in _segs(6):
            anon.process("k", s)
        assert anon.punctuate() == 0  # nothing written to the sink
        snap = metrics.snapshot()["counters"]
        assert snap["egress.fail"] == 1 and "egress.ok" not in snap
        assert snap["egress.deadletter"] == 1
        # the spool replays into a store with the standard ingest
        ds = LocalDatastore(str(tmp_path / "store"))
        got = ingest_dir(ds, dl, delete=True)
        assert got["files"] == 1 and got["rows"] == 6
        assert ds.query(SID)["count"] == 6
        assert list(scan_tiles(dl)) == []

    def test_ok_egress_counts(self, tmp_path):
        from reporter_tpu.utils import metrics
        metrics.default.reset()
        sink = TileSink(str(tmp_path / "out"))
        assert sink.store("1_2/2/756425", "t.x", "payload")
        assert metrics.snapshot()["counters"]["egress.ok"] == 1

    def test_local_sink_default_deadletter_inside_output(self, tmp_path):
        sink = TileSink(str(tmp_path / "out"))
        assert sink.deadletter == str(tmp_path / "out" / ".deadletter")

    def test_remote_sink_default_deadletter_is_absolute(self):
        # a cwd-relative spool would scatter across launch dirs (or hit
        # an unwritable / under a service manager)
        sink = TileSink("http://example.invalid/tiles")
        assert os.path.isabs(sink.deadletter)


class TestQuery:
    def test_mean_and_percentiles_two_speed_cohorts(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        # 30 obs at 36 kph (bin 7) + 10 obs at 72 kph (bin 14)
        ds.ingest_segments(_segs(30, duration=10.0)
                           + _segs(10, duration=5.0, spacing=40))
        r = ds.query(SID)
        assert r["count"] == 40
        assert r["mean_kph"] == pytest.approx((30 * 36 + 10 * 72) / 40)
        # p50 inside bin 7: 35 + (20-0)/30 * 5
        assert r["percentiles"]["p50"] == pytest.approx(35 + 20 / 30 * 5,
                                                        abs=1e-3)
        # p95: target 38 -> bin 14: 70 + (38-30)/10 * 5
        assert r["percentiles"]["p95"] == pytest.approx(70 + 8 / 10 * 5,
                                                        abs=1e-3)
        hist = np.array(r["histogram"]["counts"])
        assert hist[7] == 30 and hist[14] == 10 and hist.sum() == 40

    def test_hours_filter_and_coverage(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        ds.ingest_segments(_segs(10))                       # hour 8
        ds.ingest_segments(_segs(5, t0=MON_8AM + 7200))     # hour 10
        r_all = ds.query(SID)
        assert r_all["count"] == 15 and r_all["hours_covered"] == 2
        r_8 = ds.query(SID, hours=[8])
        assert r_8["count"] == 10 and r_8["coverage"] == 1.0
        r_peak = ds.query(SID, hours=range(7, 10))
        assert r_peak["count"] == 10
        assert r_peak["coverage"] == pytest.approx(1 / 3, abs=1e-4)
        with pytest.raises(ValueError):
            ds.query(SID, hours=[400])

    def test_hours_for_range(self):
        np.testing.assert_array_equal(
            hours_for_range(MON_8AM, MON_8AM + 3 * 3600), [8, 9, 10])
        # mid-hour end still covers its hour
        np.testing.assert_array_equal(
            hours_for_range(MON_8AM, MON_8AM + 3600 + 1), [8, 9])
        # a full week (or more) is every hour
        assert hours_for_range(MON_8AM, MON_8AM + 8 * 86400).size == 168
        assert hours_for_range(MON_8AM, MON_8AM).size == 0

    def test_transitions_ranked(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        nid2 = make_segment_id(2, 756425, 12)
        ds.ingest_segments(_segs(3) + _segs(8, nid=nid2, spacing=40))
        r = ds.query(SID)
        assert r["transitions"] == [{"next_id": nid2, "count": 8},
                                    {"next_id": NID, "count": 3}]

    def test_percentiles_empty(self):
        out = _percentiles(np.zeros(schema.N_SPEED_BINS, dtype=np.int64),
                           (50.0,))
        assert out == {"p50": None}

    def test_percentiles_out_of_range_rejected(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        ds.ingest_segments(_segs(5))
        for bad in (150, 0, -5):
            with pytest.raises(ValueError):
                ds.query(SID, percentiles=(bad,))

    def test_parse_hours_spec(self):
        from reporter_tpu.datastore import parse_hours_spec
        assert parse_hours_spec(None) is None
        assert parse_hours_spec("7-9") == [7, 8, 9]
        assert parse_hours_spec("7,8,9") == [7, 8, 9]
        with pytest.raises(ValueError):
            parse_hours_spec("9-7")


class _StubMatcher:
    def match_many(self, traces):
        return [[] for _ in traces]


@pytest.fixture
def histogram_server(tmp_path):
    from reporter_tpu.service.server import ReporterService, serve
    ds = LocalDatastore(str(tmp_path / "store"))
    ds.ingest_segments(_segs(20))
    service = ReporterService(_StubMatcher(), datastore=ds)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    httpd = serve(service, "127.0.0.1", port)
    yield f"http://127.0.0.1:{port}", ds
    httpd.shutdown()


def _get(url):
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestHistogramAction:
    def test_get_flat_params(self, histogram_server):
        url, _ds = histogram_server
        code, body = _get(f"{url}/histogram?segment_id={SID}")
        assert code == 200
        assert body["count"] == 20
        assert body["mean_kph"] == pytest.approx(36.0)
        assert body["transitions"][0]["next_id"] == NID

    def test_epoch_pin_and_merge_params(self, histogram_server):
        """/histogram grows map_version= (pin) and merge=1 (explicit
        cross-epoch opt-in); the default pins to the store's active
        version, and pin+merge together is a 400 (ISSUE 20)."""
        url, ds = histogram_server
        ds.set_map_version("aaaa00000001")
        ds.ingest_segments(_segs(4), ingest_key="ea")
        ds.set_map_version("bbbb00000002")
        ds.ingest_segments(_segs(4, duration=20.0), ingest_key="eb")
        _, latest = _get(f"{url}/histogram?segment_id={SID}")
        _, pin_a = _get(f"{url}/histogram?segment_id={SID}"
                        f"&map_version=aaaa00000001")
        _, pin_b = _get(f"{url}/histogram?segment_id={SID}"
                        f"&map_version=bbbb00000002")
        _, merged = _get(f"{url}/histogram?segment_id={SID}&merge=1")
        assert latest == pin_b  # default = the active epoch
        # 20 legacy (untagged, pre-versioning) rows serve under every
        # pin; each epoch adds its own 4
        assert pin_a["count"] == 24 and pin_b["count"] == 24
        assert merged["count"] == 28
        code, body = _get(f"{url}/histogram?segment_id={SID}"
                          f"&map_version=aaaa00000001&merge=1")
        assert code == 400 and "mutually exclusive" in body["error"]

    def test_get_hours_range(self, histogram_server):
        url, _ds = histogram_server
        code, body = _get(f"{url}/histogram?segment_id={SID}&hours=7-9")
        assert code == 200 and body["count"] == 20
        code, body = _get(f"{url}/histogram?segment_id={SID}&hours=10,11")
        assert code == 200 and body["count"] == 0

    def test_get_time_range(self, histogram_server):
        url, _ds = histogram_server
        code, body = _get(
            f"{url}/histogram?segment_id={SID}&t0={MON_8AM}&t1={MON_8AM + 3600}")
        assert code == 200 and body["count"] == 20

    def test_post_json_body(self, histogram_server):
        url, _ds = histogram_server
        req = urllib.request.Request(
            url + "/histogram",
            data=json.dumps({"segment_id": SID,
                             "percentiles": [50]}).encode(),
            method="POST")
        with urllib.request.urlopen(req) as r:
            body = json.loads(r.read())
        assert list(body["percentiles"]) == ["p50"]

    def test_get_json_param(self, histogram_server):
        url, _ds = histogram_server
        q = urllib.parse.urlencode({"json": json.dumps({"segment_id": SID})})
        code, body = _get(f"{url}/histogram?{q}")
        assert code == 200 and body["count"] == 20

    def test_bad_percentiles_400(self, histogram_server):
        url, _ds = histogram_server
        code, body = _get(f"{url}/histogram?segment_id={SID}"
                          "&percentiles=150")
        assert code == 400 and "percentile" in body["error"]

    def test_missing_segment_id_400(self, histogram_server):
        url, _ds = histogram_server
        code, body = _get(url + "/histogram")
        assert code == 400 and "segment_id" in body["error"]

    def test_no_datastore_503(self):
        from reporter_tpu.service.server import ReporterService
        service = ReporterService(_StubMatcher())
        code, body = service.histogram({"segment_id": SID})
        assert code == 503


class TestWorkerRoundTrip:
    """The acceptance proof: a StreamWorker flush is ingested (both via
    the tee and via CSV files), compacted, and queried with the expected
    mean speed — and the two ingest paths agree exactly."""

    def test_flush_ingest_compact_query(self, tmp_path):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        from reporter_tpu.streaming.formatter import Formatter
        from reporter_tpu.streaming.worker import StreamWorker, \
            inproc_submitter
        from reporter_tpu.synth import build_grid_city, generate_trace

        city = build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=5,
                               service_road_fraction=0.0,
                               internal_fraction=0.0)
        service = ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                                  max_batch=64, max_wait_ms=5.0)
        out_dir = str(tmp_path / "results")
        tee_store = LocalDatastore(str(tmp_path / "store_tee"))

        rng = np.random.default_rng(9)
        lines = []
        for i in range(6):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"veh-{i}", rng, noise_m=3.0,
                                    min_route_edges=8)
            for p in tr.points:
                lines.append("|".join([
                    "x", tr.uuid, str(p["lat"]), str(p["lon"]),
                    str(p["time"]), str(p["accuracy"])]))

        anon = Anonymiser(TileSink(out_dir), privacy=1, quantisation=3600,
                          source="test",
                          tee=lambda _t, segs:
                          tee_store.ingest_segments(segs))
        worker = StreamWorker(
            Formatter.from_config(",sv,\\|,1,2,3,4,5"),
            inproc_submitter(service), anon, flush_interval_s=1e9)
        worker.run(lines)
        assert worker.parse_failures == 0

        # CSV path: ingest the flushed tiles into a second store
        csv_store = LocalDatastore(str(tmp_path / "store_csv"))
        got = ingest_dir(csv_store, out_dir)
        assert got["files"] > 0 and got["failures"] == 0
        assert got["rows"] > 0

        # both paths agree before and after compaction
        tee_stats = tee_store.stats()
        assert tee_stats["rows"] == got["rows"]
        csv_store.compact()
        tee_store.compact()
        seg_ids = set()
        for level, index in csv_store.partitions():
            for part in csv_store.live_segments(level, index):
                seg_ids.update(
                    schema.split_hist_key(np.asarray(part.hist_key))[0]
                    .tolist())
        assert seg_ids, "no segments aggregated"
        total = 0
        for sid in sorted(seg_ids):
            a = csv_store.query(sid)
            b = tee_store.query(sid)
            assert a == b
            total += a["count"]
            if a["count"]:
                # synthetic city traces drive ~10-60 kph; a histogram
                # mean outside that band means the speed math broke
                assert 5.0 < a["mean_kph"] < 80.0
                ps = a["percentiles"]
                assert ps["p25"] <= ps["p50"] <= ps["p75"] <= ps["p95"]
        assert total == got["rows"]


class TestQueryHandleCache:
    """PR-4 satellite: /histogram stops re-opening mmaps per request —
    a bounded partition-handle LRU keyed by the manifest's segment list,
    with a datastore.query.cache.* metric pair."""

    def test_repeat_query_hits_cache(self, tmp_path):
        from reporter_tpu.utils import metrics
        ds = LocalDatastore(str(tmp_path))
        ds.ingest_segments(_segs(5))
        metrics.default.reset()
        want = ds.query(SID)
        assert ds.query(SID) == want and ds.query(SID) == want
        c = metrics.snapshot()["counters"]
        assert c.get("datastore.query.cache.misses") == 1
        assert c.get("datastore.query.cache.hits") == 2

    def test_append_invalidates_handles(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        ds.ingest_segments(_segs(5))
        assert ds.query(SID)["count"] == 5
        ds.ingest_segments(_segs(5))  # new manifest -> new cache key
        assert ds.query(SID)["count"] == 10

    def test_compaction_invalidates_handles(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        for _ in range(3):
            ds.ingest_segments(_segs(5))
        before = ds.query(SID)
        ds.compact()
        assert ds.query(SID) == before
        # and the cached handle list now reflects the single base segment
        assert len(ds.live_segments(2, 756425)) == 1

    def test_lru_bound_holds(self, tmp_path):
        from reporter_tpu.utils import metrics
        ds = LocalDatastore(str(tmp_path), handle_cache_size=1)
        other = make_segment_id(0, 99, 1)
        ds.ingest_segments(_segs(5))
        ds.ingest_segments(_segs(5, sid=other, nid=None))
        a = ds.query(SID)
        b = ds.query(other)
        metrics.default.reset()
        # alternating partitions with a 1-entry cache: every read misses,
        # results stay correct
        assert ds.query(SID) == a and ds.query(other) == b
        assert len(ds._handles) == 1
        c = metrics.snapshot()["counters"]
        assert c.get("datastore.query.cache.hits") is None

    def test_cache_disabled_at_zero(self, tmp_path):
        ds = LocalDatastore(str(tmp_path), handle_cache_size=0)
        ds.ingest_segments(_segs(5))
        assert ds.query(SID)["count"] == 5
        assert ds.query(SID)["count"] == 5
        assert len(ds._handles) == 0


class TestCompactPolicy:
    """PR-4 satellite: `datastore compact` gains --max-deltas /
    --max-delta-bytes thresholds (and the worker tee the same knobs), so
    compaction no longer needs a manual operator pass."""

    def test_max_deltas_threshold(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        for _ in range(3):
            ds.ingest_segments(_segs(5))
        out = ds.compact(max_deltas=5)  # 3 deltas <= 5: below pressure
        assert out == {"partitions": 0, "merged_segments": 0, "skipped": 1}
        out = ds.compact(max_deltas=2)  # 3 > 2: compacts
        assert out["partitions"] == 1 and out["merged_segments"] == 3
        # a lone base segment exerts no delta pressure
        out = ds.compact(max_deltas=0)
        assert out["partitions"] == 0 and out["skipped"] == 1

    def test_max_delta_bytes_threshold(self, tmp_path):
        ds = LocalDatastore(str(tmp_path))
        ds.ingest_segments(_segs(5))
        ds.ingest_segments(_segs(5))
        assert ds.compact(max_delta_bytes=1 << 30)["partitions"] == 0
        out = ds.compact(max_delta_bytes=16)  # any real delta is bigger
        assert out["partitions"] == 1 and out["merged_segments"] == 2

    def test_cli_passes_thresholds(self, tmp_path, capsys):
        from reporter_tpu.tools import datastore_cli
        ds = LocalDatastore(str(tmp_path))
        for _ in range(4):
            ds.ingest_segments(_segs(5))
        assert datastore_cli.main(
            ["compact", str(tmp_path), "--max-deltas", "8"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["skipped"] == 1 and out["partitions"] == 0
        assert datastore_cli.main(
            ["compact", str(tmp_path), "--max-deltas", "3"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["partitions"] == 1 and out["merged_segments"] == 4

    def test_worker_tee_auto_compacts(self, tmp_path):
        """The worker --datastore tee with thresholds keeps the
        partition's delta count bounded without any manual compact —
        pressure-checked inline on the partitions each flush touched."""
        from reporter_tpu.datastore import LocalDatastore as LDS
        ds = LDS(str(tmp_path))
        for _ in range(6):
            ds.ingest_segments(_segs(5), max_deltas=2)
        # never more than max_deltas+1 segments linger (the policy kicks
        # in as soon as pressure crosses the bound)
        assert ds.stats()["segments"] <= 3
        assert ds.query(SID)["count"] == 30
