"""Pre-fork SO_REUSEPORT multi-process serving (service/prefork.py).

The end-to-end leg runs in a fresh interpreter (``subprocess``) rather
than forking this pytest process: the parent script forks its workers
BEFORE anything imports jax, which is exactly the discipline
service/server.py's ``main`` follows — everything heavyweight happens
post-fork inside ``make_service``.
"""
import json
import os
import signal
import subprocess
import sys

import pytest

from reporter_tpu.service import prefork


def test_writer_id_per_slot():
    """Each worker slot's writer identity is distinct (epoch tile names
    and ingest-ledger keys stay collision-free across workers) and
    composes with an inherited multihost tag."""
    assert prefork.writer_id_for_slot(0) == "p0"
    assert prefork.writer_id_for_slot(3) == "p3"
    assert prefork.writer_id_for_slot(2, "hostA") == "hostA.p2"
    ids = {prefork.writer_id_for_slot(i) for i in range(8)}
    assert len(ids) == 8


def test_exit_code_decoding():
    """rc-137 awareness: a SIGKILLed worker and an ``os._exit(137)``
    crash failpoint decode to the same shell-style code."""
    pid = os.fork()
    if pid == 0:
        os._exit(137)
    _, status = os.waitpid(pid, 0)
    assert prefork._exit_code(status) == 137
    pid = os.fork()
    if pid == 0:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGKILL)
        os._exit(0)  # pragma: no cover - unreachable
    _, status = os.waitpid(pid, 0)
    assert prefork._exit_code(status) == 128 + signal.SIGKILL


_E2E_SCRIPT = r"""
import json, os, signal, socket, sys, threading, time, urllib.request

import numpy as np

from reporter_tpu.matcher import SegmentMatcher
from reporter_tpu.service.prefork import serve_prefork
from reporter_tpu.service.server import ReporterService
from reporter_tpu.synth import build_grid_city, generate_trace
from reporter_tpu.utils import metrics

city = build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=3,
                       service_road_fraction=0.0, internal_fraction=0.0)
# a parent-process counter that must NOT leak into any worker's /metrics
metrics.count("prefork.test.sentinel", 9)

with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
base = f"http://127.0.0.1:{port}"


def make_service():
    return ReporterService(SegmentMatcher(net=city), threshold_sec=15,
                           max_batch=64, max_wait_ms=5.0)


def req_body(seed):
    rng = np.random.default_rng(seed)
    tr = None
    while tr is None:
        tr = generate_trace(city, f"veh-{seed}", rng, noise_m=3.0)
    return json.dumps(tr.request_json()).encode()


def call(path, body=None, timeout=120.0):
    r = urllib.request.Request(base + path, data=body,
                               method="POST" if body else "GET")
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return resp.status, resp.headers.get("X-Reporter-Proc"), resp.read()


verdict = {"ok": False}


# warm the urlopen machinery (opener construction, lazy http imports)
# in the MAIN thread before any worker forks: a probe thread caught
# mid-import at fork time would leave the children deadlocked on the
# inherited per-module import locks (the quiet-parent fork discipline)
try:
    urllib.request.urlopen(base + "/stats", timeout=0.2)
except Exception:
    pass


def probe():
    # park through the fork window below for the same reason
    time.sleep(2.0)
    try:
        _probe()
    except Exception as e:
        verdict["err"] = f"{type(e).__name__}: {e}"


def _probe():
    deadline = time.time() + 180
    while True:
        try:
            call("/stats", timeout=5)
            break
        except Exception:
            if time.time() > deadline:
                verdict["err"] = "server never came up"
                return
            time.sleep(0.2)
    bodies = [req_body(i) for i in range(6)]
    # SO_REUSEPORT spreads fresh connections across both workers
    tags = {}
    for i in range(300):
        st, tag, _ = call("/report", bodies[i % len(bodies)])
        assert st == 200 and tag
        tags.setdefault(tag.split(":")[0], tag)
        if len(tags) == 2 and i >= 10:
            break
    if len(tags) < 2:
        verdict["err"] = f"one worker answered everything: {tags}"
        return
    # per-process /metrics: scrape until both workers answered one
    expos = {}
    for _ in range(300):
        st, tag, body = call("/metrics")
        expos.setdefault(tag.split(":")[0], body.decode())
        if len(expos) == 2:
            break
    for slot, text in expos.items():
        if "prefork_test_sentinel" in text:
            verdict["err"] = f"parent counters leaked into {slot}"
            return
        if "reporter_tpu_service_requests_total" not in text:
            verdict["err"] = f"{slot} reports no work of its own"
            return
    # SIGKILL p0 mid-load: the supervisor restarts it (rc 137 path);
    # no request may fail after ONE retry while the slot is down
    os.kill(int(tags["p0"].split(":")[1]), signal.SIGKILL)
    retried = 0
    for i in range(30):
        try:
            st, _t, _ = call("/report", bodies[i % len(bodies)])
        except Exception:
            retried += 1
            st, _t, _ = call("/report", bodies[i % len(bodies)])
        assert st == 200
        time.sleep(0.02)
    # the restarted slot answers under a NEW pid, same writer slot
    new_tag = None
    deadline = time.time() + 120
    while time.time() < deadline:
        _st, tag, _ = call("/stats", timeout=10)
        if tag and tag.startswith("p0:") and tag != tags["p0"]:
            new_tag = tag
            break
        time.sleep(0.1)
    verdict.update(ok=bool(new_tag), retried=retried,
                   tags=sorted(tags.values()), new_tag=new_tag)


t = threading.Thread(target=probe, daemon=True)
t.start()


def reaper():
    t.join()
    os.kill(os.getpid(), signal.SIGTERM)


threading.Thread(target=reaper, daemon=True).start()
rc = serve_prefork(make_service, "127.0.0.1", port, 2)
print("VERDICT:" + json.dumps(verdict))
sys.exit(0 if verdict.get("ok") and rc == 0 else 1)
"""


def test_prefork_two_workers_end_to_end():
    """Two SO_REUSEPORT workers behind one port: both answer, /metrics
    is per-process with no parent cross-talk, a SIGKILLed worker is
    restarted in its slot, and no request fails after one retry."""
    proc = subprocess.run(
        [sys.executable, "-c", _E2E_SCRIPT],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=600)
    tail = (proc.stdout + proc.stderr)[-2000:]
    assert proc.returncode == 0, tail
    line = [l for l in proc.stdout.splitlines()
            if l.startswith("VERDICT:")]
    assert line, tail
    verdict = json.loads(line[-1][len("VERDICT:"):])
    assert verdict["ok"], verdict
    assert verdict["new_tag"] not in verdict["tags"]
