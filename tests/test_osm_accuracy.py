"""Accuracy on a non-grid city through the REAL OSM import path
(round-4 VERDICT #7).

The grid city's axis-aligned one-edge-per-block layout is the easy case;
this fixture (tools/osm_fixture.py) is a deterministic irregular town —
curved multi-node ways, one-way residentials, primary diagonals, motorway
ramps, service alleys — imported via graph/osm.py (way classification,
junction-split OSMLR synthesis). Gates mirror ci.yml: >=99% on the
complete-segment datastore stream (BASELINE.md north star), >=97.5% strict
per-point attribution, and the determinism of the fixture itself.
"""
import io

import numpy as np
import pytest

from reporter_tpu.graph.osm import network_from_osm_xml
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.synth import generate_trace
from reporter_tpu.tools.accuracy_cli import score
from reporter_tpu.tools.osm_fixture import build_city_xml


@pytest.fixture(scope="module")
def osm_city():
    return network_from_osm_xml(io.BytesIO(build_city_xml().encode()))


def test_fixture_is_deterministic():
    assert build_city_xml() == build_city_xml()


def test_fixture_imports_realistically(osm_city):
    net = osm_city
    assert net.num_edges > 500
    assert net.edge_internal.sum() > 0          # motorway_link ramps
    assert (net.edge_segment_id < 0).sum() > 0  # service alleys
    lens = np.array(list(net.segment_length_m.values()))
    # junction-split OSMLR: block-scale segments, none beyond the cap +
    # one trailing block
    assert 100.0 < lens.mean() < 500.0
    assert lens.max() < 1400.0


def test_accuracy_gates_on_osm_city(osm_city):
    net = osm_city
    # turn penalty 500 mirrors the reference's own accuracy harness
    # (reference: py/generate_test_trace.py:172)
    matcher = SegmentMatcher(
        net=net, params=MatchParams(turn_penalty_factor=500.0))
    rng = np.random.default_rng(0)
    traces = []
    while len(traces) < 24:
        tr = generate_trace(net, f"acc-{len(traces)}", rng, noise_m=4.0,
                            min_route_edges=8)
        if tr is not None:
            traces.append(tr)
    result = score(net, matcher, traces)
    assert result["agreement"] >= 0.99, result
    assert result["point_agreement"] >= 0.975, result
    assert result["segments_emitted"] > 50, result
