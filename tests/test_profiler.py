"""Device-level profiler (obs/profiler.py): compile telemetry,
bucket-occupancy wide events, shadow-accuracy sampling, and the perf
ledger/gate tools — ISSUE 8."""
import importlib.util
import json
import os
import sys
import types
import urllib.request

import numpy as np
import pytest

from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.matcher import batchpad
from reporter_tpu.matcher.batchpad import (
    LENGTH_BUCKETS, bucket_length, kept_point_count, occupancy_stats,
    pack_batches, prepare_trace)
from reporter_tpu.obs import profiler
from reporter_tpu.obs import trace as obs_trace
from reporter_tpu.synth import build_grid_city, generate_trace
from reporter_tpu.utils import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=6,
                           service_road_fraction=0.0,
                           internal_fraction=0.0)


@pytest.fixture(autouse=True)
def _clean_profiler():
    profiler.reset()
    yield
    profiler.reset()


def _counter(name):
    return metrics.counter(name)


# ---------------------------------------------------------------------------
class TestBucketLength:
    """batchpad.bucket_length edge semantics (satellite)."""

    def test_exact_boundaries_stay_in_bucket(self):
        for b in LENGTH_BUCKETS:
            assert bucket_length(b) == b

    def test_one_past_a_boundary_moves_up(self):
        for lo, hi in zip(LENGTH_BUCKETS, LENGTH_BUCKETS[1:]):
            assert bucket_length(lo + 1) == hi

    def test_largest_bucket_caps(self):
        top = LENGTH_BUCKETS[-1]
        assert bucket_length(top + 1) == top
        assert bucket_length(10 * top) == top

    def test_tiny_traces_land_in_smallest(self):
        assert bucket_length(0) == LENGTH_BUCKETS[0]
        assert bucket_length(1) == LENGTH_BUCKETS[0]

    def test_truncation_at_largest_bucket(self, city, monkeypatch):
        """A trace whose kept points exceed the largest bucket is
        truncated to it (shrunken buckets keep the test cheap)."""
        monkeypatch.setattr(batchpad, "LENGTH_BUCKETS", (4, 8))
        m = SegmentMatcher(net=city, use_native=False)
        rng = np.random.default_rng(1)
        tr = None
        while tr is None or len(tr.points) < 20:
            tr = generate_trace(city, "long", rng, noise_m=3.0,
                                min_route_edges=10)
        p = prepare_trace(city, m.grid, tr.points[:20], MatchParams(),
                          m.route_cache)
        assert p.T == 8
        assert p.num_kept <= 8
        # the truncated tail carries no verified dwell
        assert p.trailing_jitter_dwell_s == 0.0


class TestOccupancyMath:
    def test_pinned_waste_fixture(self):
        """The pinned synthetic-batch ratio: 10 + 50 kept points in a
        2-row T=64 batch -> 128 cells, waste exactly 1 - 60/128."""
        cells, occ, waste = occupancy_stats(60, rows=2, T=64)
        assert cells == 128
        assert occ == pytest.approx(60 / 128)
        assert waste == pytest.approx(0.53125)

    def test_empty_batch_is_zero_occupancy(self):
        cells, occ, waste = occupancy_stats(0, rows=0, T=64)
        assert cells == 0 and occ == 0.0 and waste == 1.0

    def test_kept_point_count_matches_prepared_batch(self, city):
        """kept_point_count over a packed batch == the sum of each
        trace's num_kept (pad rows/tails are all-SKIP)."""
        m = SegmentMatcher(net=city, use_native=False)
        rng = np.random.default_rng(2)
        prepared = []
        for i in range(3):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"o{i}", rng, noise_m=3.0,
                                    min_route_edges=6)
            prepared.append(prepare_trace(city, m.grid, tr.points,
                                          MatchParams(), m.route_cache))
        for batch in pack_batches(prepared, pad_pow2=True):
            expect = sum(p.num_kept for p in batch.traces)
            assert kept_point_count(batch) == expect
            rows, T = batch.case.shape
            cells, occ, waste = occupancy_stats(expect, rows, T)
            assert 0.0 < occ < 1.0
            assert waste == pytest.approx(1.0 - expect / cells)


# ---------------------------------------------------------------------------
class TestCompileTelemetry:
    def test_episode_attribution_and_recompile_storm(self, caplog):
        """Direct listener feeds: a dispatch with a compile event is an
        episode; the SAME shape compiling again is a storm."""
        c0 = _counter("decode.compile.count")
        r0 = _counter("decode.compile.recompiles")
        with profiler.dispatch_span(8, 64, 8):
            profiler._on_event_duration(
                "/jax/core/compile/backend_compile_duration", 0.25)
        assert _counter("decode.compile.count") == c0 + 1
        assert _counter("decode.compile.recompiles") == r0
        # steady dispatch: no compile event -> no episode
        with profiler.dispatch_span(8, 64, 8):
            pass
        assert _counter("decode.compile.count") == c0 + 1
        # the same shape compiling AGAIN is the storm signal
        import logging
        with caplog.at_level(logging.WARNING, "reporter_tpu.obs"):
            with profiler.dispatch_span(8, 64, 8):
                profiler._on_event_duration(
                    "/jax/core/compile/backend_compile_duration", 0.1)
        assert _counter("decode.compile.recompiles") == r0 + 1
        assert any("recompile storm" in r.message
                   for r in caplog.records)
        snap = profiler.snapshot()
        (shape,) = snap["shapes"]
        assert shape["compiles"] == 2 and shape["dispatches"] == 3
        assert shape["steady"]["n"] == 1
        assert shape["compile_s"] == pytest.approx(0.35, abs=1e-6)

    def test_backend_switch_is_not_a_storm(self, monkeypatch):
        """A different decode backend compiling the same (B, T, K) is a
        NEW compiled shape, never a recompile storm (bench's pallas
        leg, operator A/Bs via REPORTER_TPU_DECODE)."""
        r0 = _counter("decode.compile.recompiles")
        monkeypatch.setenv("REPORTER_TPU_DECODE", "scan")
        with profiler.dispatch_span(8, 64, 8):
            profiler._on_event_duration(
                "/jax/core/compile/backend_compile_duration", 0.1)
        monkeypatch.setenv("REPORTER_TPU_DECODE", "assoc")
        with profiler.dispatch_span(8, 64, 8):
            profiler._on_event_duration(
                "/jax/core/compile/backend_compile_duration", 0.1)
        assert _counter("decode.compile.recompiles") == r0
        backends = {s["backend"] for s in profiler.snapshot()["shapes"]}
        assert backends == {"scan", "assoc"}

    def test_failed_dispatch_records_nothing(self):
        """An aborted dispatch's wall is time-to-failure, not latency —
        it must not seed the shape table or the steady histograms."""
        with pytest.raises(RuntimeError):
            with profiler.dispatch_span(8, 64, 8):
                raise RuntimeError("device fell over")
        assert profiler.snapshot()["shapes"] == []
        # and a later clean dispatch still opens the shape normally
        with profiler.dispatch_span(8, 64, 8):
            pass
        (shape,) = profiler.snapshot()["shapes"]
        assert shape["dispatches"] == 1

    def test_unrelated_events_ignored(self):
        c0 = _counter("decode.compile.count")
        with profiler.dispatch_span(4, 16, 8):
            profiler._on_event_duration(
                "/jax/core/compile/jaxpr_trace_duration", 0.5)
        assert _counter("decode.compile.count") == c0

    def test_real_match_compiles_once_per_shape(self, city):
        """End to end: an identical second match_many adds ZERO compile
        episodes (the acceptance invariant obs_smoke asserts over
        HTTP)."""
        m = SegmentMatcher(net=city)
        rng = np.random.default_rng(5)
        reqs = []
        for i in range(3):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"c{i}", rng, noise_m=3.0,
                                    min_route_edges=6)
            reqs.append({"uuid": tr.uuid, "trace": tr.points[:12]})
        out = m.match_many(reqs)
        assert all(r is not None for r in out)
        episodes = profiler.compile_count()
        out2 = m.match_many(reqs)
        assert all(r is not None for r in out2)
        assert profiler.compile_count() == episodes
        # and the chunk left a wide event with sane occupancy
        evs = profiler.recent_events()
        assert evs and 0.0 <= evs[-1]["padding_waste"] < 1.0
        assert evs[-1]["traces"] == 3


# ---------------------------------------------------------------------------
class TestWideEvents:
    def test_ring_is_bounded(self, monkeypatch):
        monkeypatch.setenv(profiler.ENV_RING, "16")
        profiler.reset()
        for i in range(50):
            profiler.chunk_event(bucket_T=16, K=8, traces=1, rows=1,
                                 kept_points=8, raw_points=10)
        assert len(profiler.recent_events(None)) == 16
        assert profiler.recent_events(0) == []

    def test_trace_id_joins_armed_requests(self):
        obs_trace.configure(True)
        try:
            with obs_trace.span("test.root") as root:
                profiler.chunk_event(bucket_T=16, K=8, traces=1, rows=1,
                                     kept_points=8, raw_points=10)
                trace_id = root.trace_id
        finally:
            obs_trace.configure(False)
        ev = profiler.recent_events(1)[0]
        assert ev["trace_id"] == trace_id

    def test_disarmed_events_carry_no_trace_id(self):
        profiler.chunk_event(bucket_T=16, K=8, traces=1, rows=1,
                             kept_points=8, raw_points=10)
        assert profiler.recent_events(1)[0]["trace_id"] is None

    def test_queue_depth_stamped(self):
        profiler.note_queue_depth(7)
        profiler.chunk_event(bucket_T=16, K=8, traces=1, rows=1,
                             kept_points=8, raw_points=10)
        assert profiler.recent_events(1)[0]["queue_depth"] == 7

    def test_occupancy_histogram_per_bucket(self):
        before = metrics.snapshot()["timers"].get("decode.occupancy.t64")
        n0 = before["count"] if before else 0
        profiler.chunk_event(bucket_T=64, K=8, traces=2, rows=2,
                             kept_points=60, raw_points=70)
        t = metrics.snapshot()["timers"]["decode.occupancy.t64"]
        assert t["count"] == n0 + 1

    def test_padding_waste_totals(self):
        assert profiler.padding_waste() is None
        profiler.chunk_event(bucket_T=64, K=8, traces=2, rows=2,
                             kept_points=60, raw_points=70)
        assert profiler.padding_waste() == pytest.approx(0.53125)


# ---------------------------------------------------------------------------
def _toy_batch(seed=3):
    """A hand-built 1-trace decode batch + its oracle path."""
    from reporter_tpu.matcher.cpu_ref import viterbi_decode_numpy
    from reporter_tpu.matcher.hmm import NORMAL, RESTART
    B, T, K = 1, 6, 3
    rng = np.random.default_rng(seed)
    dist = rng.uniform(0, 30, (B, T, K)).astype(np.float32)
    valid = np.ones((B, T, K), bool)
    gc = rng.uniform(5, 40, (B, T - 1)).astype(np.float32)
    route = rng.uniform(5, 80, (B, T - 1, K, K)).astype(np.float32)
    case = np.full((B, T), NORMAL, np.int32)
    case[:, 0] = RESTART
    batch = types.SimpleNamespace(dist_m=dist, valid=valid,
                                  route_m=route, gc_m=gc, case=case)
    path, _ = viterbi_decode_numpy(dist[0], valid[0], route[0], gc[0],
                                   case[0], 4.07, 3.0)
    return batch, path


class TestShadowSampling:
    def test_agreeing_decode_has_no_mismatch(self, monkeypatch):
        monkeypatch.setenv(profiler.ENV_SHADOW, "1.0")
        batch, path = _toy_batch()
        m0 = _counter("decode.shadow.mismatch")
        s0 = _counter("decode.shadow.sampled")
        profiler.maybe_shadow(batch, path[None, :], 1, 4.07, 3.0)
        assert profiler.drain_shadow(30.0)
        assert _counter("decode.shadow.sampled") == s0 + 1
        assert _counter("decode.shadow.mismatch") == m0
        assert profiler.shadow_mismatches() == 0

    def test_doctored_decode_is_a_mismatch(self, monkeypatch):
        monkeypatch.setenv(profiler.ENV_SHADOW, "1.0")
        batch, path = _toy_batch()
        bad = path.copy()
        bad[2] = (bad[2] + 1) % 3  # a strictly worse state choice
        m0 = _counter("decode.shadow.mismatch")
        profiler.maybe_shadow(batch, bad[None, :], 1, 4.07, 3.0)
        assert profiler.drain_shadow(30.0)
        assert _counter("decode.shadow.mismatch") == m0 + 1
        assert profiler.shadow_mismatches() == 1

    def test_sampling_accumulator_is_deterministic(self, monkeypatch):
        monkeypatch.setenv(profiler.ENV_SHADOW, "0.5")
        batch, path = _toy_batch()
        c0 = _counter("decode.shadow.chunks")
        for _ in range(4):
            profiler.maybe_shadow(batch, path[None, :], 1, 4.07, 3.0)
            assert profiler.drain_shadow(30.0)
        assert _counter("decode.shadow.chunks") == c0 + 2

    def test_off_by_default(self, monkeypatch):
        monkeypatch.delenv(profiler.ENV_SHADOW, raising=False)
        batch, path = _toy_batch()
        c0 = _counter("decode.shadow.chunks")
        profiler.maybe_shadow(batch, path[None, :], 1, 4.07, 3.0)
        assert profiler.drain_shadow(5.0)
        assert _counter("decode.shadow.chunks") == c0

    def test_submit_failure_never_escapes_or_leaks(self, monkeypatch):
        """A pool-submit failure (thread exhaustion, shutdown) must not
        propagate into the serving drain lane, and must release the
        reserved pending slot."""
        monkeypatch.setenv(profiler.ENV_SHADOW, "1.0")

        def boom():
            raise RuntimeError("can't start new thread")
        monkeypatch.setattr(profiler, "_ensure_shadow_pool", boom)
        batch, path = _toy_batch()
        e0 = _counter("decode.shadow.errors")
        profiler.maybe_shadow(batch, path[None, :], 1, 4.07, 3.0)
        assert _counter("decode.shadow.errors") == e0 + 1
        assert profiler.shadow_stats()["pending"] == 0

    def test_tie_breaks_are_agreement(self, monkeypatch):
        """Two equal-quality paths (exact score tie) are NOT a
        mismatch — the device may break ties differently."""
        from reporter_tpu.matcher.hmm import NORMAL, RESTART
        monkeypatch.setenv(profiler.ENV_SHADOW, "1.0")
        B, T, K = 1, 3, 2
        # symmetric tensors: both states score identically everywhere
        dist = np.full((B, T, K), 5.0, np.float32)
        valid = np.ones((B, T, K), bool)
        gc = np.full((B, T - 1), 10.0, np.float32)
        route = np.full((B, T - 1, K, K), 10.0, np.float32)
        case = np.full((B, T), NORMAL, np.int32)
        case[:, 0] = RESTART
        batch = types.SimpleNamespace(dist_m=dist, valid=valid,
                                      route_m=route, gc_m=gc, case=case)
        other = np.array([[1, 1, 1]], np.int32)  # a different tie path
        m0 = _counter("decode.shadow.mismatch")
        profiler.maybe_shadow(batch, other, 1, 4.07, 3.0)
        assert profiler.drain_shadow(30.0)
        assert _counter("decode.shadow.mismatch") == m0


# ---------------------------------------------------------------------------
class TestServiceSurface:
    @pytest.fixture(scope="class")
    def server(self, city):
        from reporter_tpu.service.server import ReporterService, serve
        service = ReporterService(SegmentMatcher(net=city),
                                  threshold_sec=15, max_batch=16,
                                  max_wait_ms=5.0)
        httpd = serve(service, "127.0.0.1", 0)
        yield f"http://127.0.0.1:{httpd.server_address[1]}", service
        httpd.shutdown()

    def test_profile_action(self, city, server):
        base, service = server
        rng = np.random.default_rng(9)
        tr = None
        while tr is None:
            tr = generate_trace(city, "p0", rng, noise_m=3.0,
                                min_route_edges=6)
        req = urllib.request.Request(
            f"{base}/report",
            data=json.dumps({
                "uuid": tr.uuid, "trace": tr.points,
                "match_options": {"mode": "auto",
                                  "report_levels": [0, 1],
                                  "transition_levels": [0, 1]},
            }).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        with urllib.request.urlopen(f"{base}/profile") as r:
            assert r.status == 200
            prof = json.loads(r.read().decode())
        for key in ("shapes", "events", "totals", "shadow",
                    "queue_depth", "compile_episodes"):
            assert key in prof
        assert prof["events"], "no wide event after a /report"
        ev = prof["events"][-1]
        assert 0.0 <= ev["padding_waste"] < 1.0
        assert ev["bucket_T"] in LENGTH_BUCKETS

    def test_health_carries_shadow_block(self, server):
        base, _service = server
        with urllib.request.urlopen(f"{base}/health") as r:
            body = json.loads(r.read().decode())
        assert "shadow" in body
        assert set(body["shadow"]) >= {"fraction", "sampled",
                                       "mismatch"}


class TestFlightrecWideEvents:
    def test_dump_carries_last_wide_events(self, tmp_path, monkeypatch):
        from reporter_tpu.obs import flightrec
        monkeypatch.setenv(flightrec.ENV_VAR, str(tmp_path))
        flightrec._configure_env()
        try:
            for i in range(20):
                profiler.chunk_event(bucket_T=16, K=8, traces=1, rows=1,
                                     kept_points=8 + i, raw_points=20)
            path = flightrec.dump("test.wide")
            assert path is not None
            with open(path, encoding="utf-8") as f:
                post = json.load(f)
            assert len(post["wide_events"]) == 16  # the last 16
            assert post["wide_events"][-1]["kept_points"] == 27
        finally:
            monkeypatch.delenv(flightrec.ENV_VAR)
            flightrec._dir_from_env = False
            flightrec._dump_dir = None


# ---------------------------------------------------------------------------
def _load_tool(name):
    """Import a tools/*.py script as a module (tools/ is not a
    package)."""
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def ledger_mod():
    from reporter_tpu.obs import ledger
    return ledger


@pytest.fixture(scope="module")
def gate_mod():
    return _load_tool("perf_gate")


class TestPerfLedger:
    def test_entry_from_bench_parses_metric(self, ledger_mod):
        parsed = {
            "metric": "x (columnar prep+decode+assemble+report-serialise,"
                      " T=64, K=8, platform=cpu, decode=scan) y",
            "value": 8000.0, "vs_baseline": 20.0,
            "stages": {"prep": 0.03, "decode_wait": 0.01,
                       "assemble": 0.01, "report": 0.02, "total": 0.06,
                       "pipelined": True},
            "baseline": {"traces_per_sec": 400.0, "n_traces": 128},
        }
        e = ledger_mod.entry_from_bench(parsed, "f.json", "t", "bench")
        assert e["platform"] == "cpu" and e["decode"] == "scan"
        assert e["scope"] == "full" and e["pipelined"] is True
        assert e["stage_shares"]["prep"] == pytest.approx(0.5)
        assert e["stage_shares"]["report"] == pytest.approx(0.3333)

    def test_legacy_scope_drops_report_share(self, ledger_mod):
        parsed = {
            "metric": "x (prep+decode+assemble+report, T=64, K=8, "
                      "platform=cpu, decode=scan) y",
            "vs_baseline": 18.0, "value": 7000.0,
            "stages": {"prep": 0.02, "report": 0.002, "total": 0.04},
            "baseline": {"traces_per_sec": 400.0, "n_traces": 128},
        }
        e = ledger_mod.entry_from_bench(parsed, "f.json", "t", "bench")
        # PR 4 widened the report stage's scope; legacy shares of it
        # must not be gated against
        assert "report" not in e["stage_shares"]
        assert "prep" in e["stage_shares"]

    def test_smoke_scale_detected(self, ledger_mod):
        parsed = {"metric": "x (… platform=cpu, decode=scan)",
                  "vs_baseline": 0.6, "value": 90.0,
                  "stages": {"prep": 0.01, "total": 0.5,
                             "pipelined": True},
                  "baseline": {"traces_per_sec": 160.0, "n_traces": 8}}
        e = ledger_mod.entry_from_bench(parsed, "s.json", "t", "bench")
        assert e["scope"] == "smoke"

    def test_seed_covers_every_artifact(self, ledger_mod):
        entries = ledger_mod.seed_entries(REPO)
        sources = {e["source"] for e in entries}
        assert {"BENCH_r04.json", "BENCH_r05.json",
                "BENCH_DEV_r06.json", "MULTICHIP_r05.json"} <= sources
        ratios = [e for e in entries if e["vs_baseline"] is not None]
        assert len(ratios) >= 6
        # context notes carried where the artifact recorded box drift
        r06 = [e for e in entries if e["label"] == "dev_r06"][0]
        assert "2x" in (r06["context"] or "")

    def test_committed_ledger_covers_the_seed(self, ledger_mod):
        """Every entry a fresh seed derives from the checked-in
        artifacts is present in the committed LEDGER.jsonl (regenerate
        or re-append with `perf_ledger.py` when adding an artifact).
        Containment, not equality: the documented workflow APPENDS
        live entries (e.g. smoke-scope history that makes the CI gate
        bind), and those never come from an artifact."""
        committed = ledger_mod.load_ledger(
            os.path.join(REPO, "LEDGER.jsonl"))
        for entry in ledger_mod.seed_entries(REPO):
            assert entry in committed, entry["label"]


class TestPerfGate:
    def _entries(self, ledger_mod):
        return ledger_mod.seed_entries(REPO)

    def test_clean_candidate_passes(self, ledger_mod, gate_mod):
        entries = self._entries(ledger_mod)
        cand = {"source": "c", "platform": "cpu", "scope": "full",
                "vs_baseline": 19.0, "pipelined": False,
                "stage_shares": {"prep": 0.4}, "kind": "bench"}
        passed, verdict = gate_mod.gate(cand, entries, 0.15, 0.2, False)
        assert passed, verdict

    def test_regressed_ratio_fails(self, ledger_mod, gate_mod):
        entries = self._entries(ledger_mod)
        import statistics
        median = statistics.median(
            e["vs_baseline"] for e in gate_mod.comparable_pool(
                entries, "cpu", "full"))
        cand = {"source": "c", "platform": "cpu", "scope": "full",
                "vs_baseline": round(median * 0.8, 2),
                "pipelined": False, "stage_shares": None,
                "kind": "bench"}
        passed, verdict = gate_mod.gate(cand, entries, 0.15, 0.2, False)
        assert not passed
        assert verdict["failures"][0]["check"] == "ratio"

    def test_drift_control_rescues_below_floor_ratio(self, ledger_mod,
                                                     gate_mod):
        """A candidate below the cross-box floor passes the ratio check
        iff its same-box control is ALSO below the floor (the box
        provably can't reach the median) and the candidate is within
        tolerance of the control."""
        entries = self._entries(ledger_mod)
        import statistics
        median = statistics.median(
            e["vs_baseline"] for e in gate_mod.comparable_pool(
                entries, "cpu", "full"))
        low = round(median * 0.8, 2)
        cand = {"source": "c", "platform": "cpu", "scope": "full",
                "vs_baseline": low, "pipelined": False,
                "stage_shares": None, "kind": "bench",
                "control_vs_baseline": round(median * 0.78, 2)}
        passed, verdict = gate_mod.gate(cand, entries, 0.15, 0.2, False)
        assert passed, verdict
        assert "ratio_drift_control" in verdict

    def test_drift_control_no_leniency_on_healthy_box(self, ledger_mod,
                                                      gate_mod):
        """A control at/above the floor proves the box is fine — the
        slow candidate is a code regression and still fails."""
        entries = self._entries(ledger_mod)
        import statistics
        median = statistics.median(
            e["vs_baseline"] for e in gate_mod.comparable_pool(
                entries, "cpu", "full"))
        cand = {"source": "c", "platform": "cpu", "scope": "full",
                "vs_baseline": round(median * 0.8, 2),
                "pipelined": False, "stage_shares": None,
                "kind": "bench",
                "control_vs_baseline": round(median * 1.0, 2)}
        passed, verdict = gate_mod.gate(cand, entries, 0.15, 0.2, False)
        assert not passed
        assert verdict["failures"][0]["check"] == "ratio"

    def test_drift_control_bounds_the_regression(self, ledger_mod,
                                                 gate_mod):
        """Even on a drifted box the candidate must stay within
        tolerance of the control — drift never hides a real loss."""
        entries = self._entries(ledger_mod)
        import statistics
        median = statistics.median(
            e["vs_baseline"] for e in gate_mod.comparable_pool(
                entries, "cpu", "full"))
        cand = {"source": "c", "platform": "cpu", "scope": "full",
                "vs_baseline": round(median * 0.5, 2),
                "pipelined": False, "stage_shares": None,
                "kind": "bench",
                "control_vs_baseline": round(median * 0.8, 2)}
        passed, verdict = gate_mod.gate(cand, entries, 0.15, 0.2, False)
        assert not passed
        assert verdict["failures"][0]["check"] == "ratio"

    def test_grown_stage_share_fails(self, ledger_mod, gate_mod):
        entries = self._entries(ledger_mod)
        cand = {"source": "c", "platform": "cpu", "scope": "full",
                "vs_baseline": 19.0, "pipelined": False,
                "stage_shares": {"prep": 0.95}, "kind": "bench"}
        passed, verdict = gate_mod.gate(cand, entries, 0.15, 0.2, False)
        assert not passed
        assert any(f["check"] == "share" and f["stage"] == "prep"
                   for f in verdict["failures"])

    def test_unmatched_scope_passes_with_note(self, ledger_mod,
                                              gate_mod):
        entries = self._entries(ledger_mod)
        cand = {"source": "smoke", "platform": "cpu", "scope": "smoke",
                "vs_baseline": 0.5, "pipelined": True,
                "stage_shares": None, "kind": "bench"}
        passed, verdict = gate_mod.gate(cand, entries, 0.15, 0.2, False)
        assert passed and "note" in verdict
        # --require-history makes the empty pool binding
        passed, _ = gate_mod.gate(cand, entries, 0.15, 0.2, True)
        assert not passed


# ---------------------------------------------------------------------------
class TestHeartbeatFields:
    def test_heartbeat_carries_device_vitals(self, tmp_path,
                                             monkeypatch, caplog):
        import logging
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        from reporter_tpu.streaming.formatter import Formatter
        from reporter_tpu.streaming.worker import StreamWorker
        monkeypatch.setenv("REPORTER_TPU_HEARTBEAT_S", "0.0001")
        profiler.chunk_event(bucket_T=64, K=8, traces=2, rows=2,
                             kept_points=60, raw_points=70)
        worker = StreamWorker(
            Formatter.from_config(r",sv,\|,0,1,2,3,4"),
            lambda trace: None,
            Anonymiser(TileSink(str(tmp_path)), 1, 3600, source="t"),
            flush_interval_s=1e9)
        with caplog.at_level(logging.INFO, "reporter_tpu.streaming"):
            worker._hb_last -= 1.0
            worker._maybe_heartbeat()
        lines = [r.message for r in caplog.records
                 if r.message.startswith("heartbeat ")]
        assert lines
        payload = json.loads(lines[0][len("heartbeat "):])
        assert payload["padding_waste"] == pytest.approx(0.5312, abs=1e-3)
        assert payload["compile_count"] == 0
        assert payload["shadow_mismatches"] == 0
