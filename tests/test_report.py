"""Tests for report() semantics (reference: py/reporter_service.py:79-179)."""
import math

import pytest

from reporter_tpu.service.report import report


def seg(segment_id=None, start=0.0, end=10.0, length=600, queue=0,
        internal=False, begin=0, endi=5):
    s = {
        "start_time": start, "end_time": end, "length": length,
        "queue_length": queue, "internal": internal,
        "begin_shape_index": begin, "end_shape_index": endi,
        "way_ids": [],
    }
    if segment_id is not None:
        s["segment_id"] = segment_id
    return s


def trace_ending_at(t):
    return {"uuid": "x", "trace": [{"lat": 0, "lon": 0, "time": 0},
                                   {"lat": 0, "lon": 0, "time": t}]}


LV0_A = 0x100 << 3 | 0   # level 0 ids
LV0_B = 0x200 << 3 | 0
LV0_C = 0x300 << 3 | 0
LV2_A = 0x100 << 3 | 2   # level 2 id


class TestPairEmission:
    def test_basic_pair(self):
        match = {"segments": [
            seg(LV0_A, 0, 30, begin=0),
            seg(LV0_B, 30, 60, begin=5),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        reports = out["datastore"]["reports"]
        assert len(reports) == 1
        r = reports[0]
        assert r["id"] == LV0_A
        assert r["next_id"] == LV0_B
        # t1 = successor's start since its level is in transition_levels
        assert r["t0"] == 0 and r["t1"] == 30
        assert out["datastore"]["mode"] == "auto"

    def test_t1_is_own_end_when_successor_level_not_transitional(self):
        match = {"segments": [
            seg(LV0_A, 0, 28),
            seg(LV2_A, 30, 60),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        (r,) = out["datastore"]["reports"]
        assert r["t1"] == 28          # own end_time, not successor start
        assert "next_id" not in r

    def test_last_segment_not_reported_without_successor(self):
        match = {"segments": [seg(LV0_A, 0, 30)]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        assert out["datastore"]["reports"] == []

    def test_level_not_reported_counts_unreported(self):
        match = {"segments": [
            seg(LV2_A, 0, 30),
            seg(LV0_B, 30, 60),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1, 2})
        assert out["datastore"]["reports"] == []
        assert out["stats"]["unreported_matches"]["count"] == 1


class TestHoldback:
    def test_trailing_segments_withheld(self):
        # trace ends at t=100; segment starting at 90 is within 15s holdback
        match = {"segments": [
            seg(LV0_A, 0, 50, begin=0, endi=3),
            seg(LV0_B, 50, 90, begin=4, endi=7),
            seg(LV0_C, 90, 100, begin=8),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        ids = [r["id"] for r in out["datastore"]["reports"]]
        assert ids == [LV0_A]
        # the trim keeps the boundary-straddling probe: LV0_A's LAST
        # point (end_shape_index 3), not LV0_B's first — the next window
        # needs it to interpolate LV0_B's entry time (report.py)
        assert out["shape_used"] == 3

    def test_shape_used_omitted_when_zero(self):
        # reference quirk: `if shape_used:` drops index 0 (here the
        # straddling probe — the predecessor's last point — IS index 0)
        match = {"segments": [
            seg(LV0_A, 0, 50, begin=0, endi=0),
            seg(LV0_B, 50, 80, begin=1),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        assert "shape_used" not in out

    def test_all_segments_recent_no_reports(self):
        match = {"segments": [
            seg(LV0_A, 95, 97), seg(LV0_B, 97, 99),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        assert out["datastore"]["reports"] == []
        assert "shape_used" not in out


class TestValidity:
    def test_nonpositive_dt_counts_invalid_time(self):
        match = {"segments": [
            seg(LV0_A, 30, 30),  # zero duration with t1=successor start=30
            seg(LV0_B, 30, 60),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        assert out["datastore"]["reports"] == []
        assert out["stats"]["match_errors"]["invalid_times"] == 1

    def test_overspeed_counts_invalid_speed(self):
        # 600m in 2s = 1080 km/h
        match = {"segments": [
            seg(LV0_A, 0, 2, length=600),
            seg(LV0_B, 2, 60),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        assert out["datastore"]["reports"] == []
        assert out["stats"]["match_errors"]["invalid_speeds"] == 1

    def test_partial_length_not_reported(self):
        match = {"segments": [
            seg(LV0_A, -1, 30, length=-1),
            seg(LV0_B, 30, 60),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        assert out["datastore"]["reports"] == []


class TestInternalBridging:
    def test_internal_bridges_pair(self):
        match = {"segments": [
            seg(LV0_A, 0, 30),
            seg(None, 30, 32, length=-1, internal=True),
            seg(LV0_B, 32, 60),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        (r,) = out["datastore"]["reports"]
        assert r["id"] == LV0_A and r["next_id"] == LV0_B
        assert r["t1"] == 32  # successor (LV0_B) start
        # internal does not count as unassociated
        assert out["stats"]["unassociated_segments"] == 0


class TestStats:
    def test_discontinuity_counted(self):
        match = {"segments": [
            seg(LV0_A, 0, -1),
            seg(LV0_B, -1, 60, length=-1),
            seg(LV0_C, 60, 80),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        assert out["stats"]["match_errors"]["discontinuities"] == 1

    def test_unassociated_counted(self):
        match = {"segments": [
            seg(LV0_A, 0, 30),
            seg(None, 30, 40, length=-1, internal=False),
            seg(LV0_B, 40, 60),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        assert out["stats"]["unassociated_segments"] == 1

    def test_successful_stats_accumulate(self):
        match = {"segments": [
            seg(LV0_A, 0, 20, length=500),
            seg(LV0_B, 20, 40, length=700),
            seg(LV0_C, 40, 60),
        ]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        s = out["stats"]["successful_matches"]
        assert s["count"] == 2
        assert s["length"] == pytest.approx(1.2)

    def test_segment_matcher_echoed(self):
        match = {"segments": [seg(LV0_A, 0, 30), seg(LV0_B, 30, 60)]}
        out = report(match, trace_ending_at(100), 15, {0, 1}, {0, 1})
        assert out["segment_matcher"] is match
        assert out["segment_matcher"]["mode"] == "auto"
