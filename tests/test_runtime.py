"""Backend isolation helper (utils/runtime.py).

This is the round-2 fix for the round-1 driver failures: every non-pytest
entry point used to hang on the chip-tunnel block because the isolation
logic lived only in tests/conftest.py. These tests pin the helper's
contract; conftest itself already exercises force_virtual_cpu for real
(it is how this very suite runs on the virtual 8-CPU mesh).
"""
import os
import subprocess
import sys

import jax

from reporter_tpu.utils import runtime


def test_force_virtual_cpu_idempotent():
    # conftest already forced cpu; calling again must be a safe no-op
    runtime.force_virtual_cpu(8)
    runtime.force_virtual_cpu()
    assert jax.default_backend() == "cpu"
    assert len(jax.devices()) == 8


def test_factories_are_popped():
    from jax._src import xla_bridge
    assert list(xla_bridge._backend_factories) == ["cpu"]


def test_ensure_backend_env_cpu(monkeypatch):
    monkeypatch.setattr(runtime, "_decided", None)
    monkeypatch.setenv(runtime.ENV_PLATFORM, "cpu")
    assert runtime.ensure_backend() == "cpu"


def test_ensure_backend_caches_decision(monkeypatch):
    monkeypatch.setattr(runtime, "_decided", "cpu")
    # cached decision short-circuits before any probe or env read
    monkeypatch.setenv(runtime.ENV_PLATFORM, "definitely-invalid")
    assert runtime.ensure_backend() == "cpu"


def test_ensure_backend_rejects_unknown(monkeypatch):
    monkeypatch.setattr(runtime, "_decided", None)
    monkeypatch.setenv(runtime.ENV_PLATFORM, "gpu3000")
    import pytest
    with pytest.raises(ValueError):
        runtime.ensure_backend()


def test_ensure_backend_auto_with_initialized_cpu(monkeypatch):
    # backends are initialised (conftest ran jax on cpu): auto must not
    # probe — it adopts the live backend
    monkeypatch.setattr(runtime, "_decided", None)
    monkeypatch.delenv(runtime.ENV_PLATFORM, raising=False)
    called = []
    monkeypatch.setattr(runtime, "accelerator_available",
                        lambda **kw: called.append(1) or False)
    assert runtime.ensure_backend() == "cpu"
    assert not called


def test_probe_cpu_child_is_not_an_accelerator(monkeypatch, tmp_path):
    # a child that initialises on plain cpu must read as "no accelerator"
    fake = tmp_path / "python"
    fake.write_text("#!/bin/sh\necho cpu\nexit 0\n")
    fake.chmod(0o755)
    monkeypatch.setattr(runtime.sys, "executable", str(fake))
    assert runtime.accelerator_available(timeout_s=5, tries=1) is False


def test_probe_failure_then_success(monkeypatch, tmp_path):
    marker = tmp_path / "tried"
    fake = tmp_path / "python"
    fake.write_text(
        "#!/bin/sh\n"
        f"if [ -e {marker} ]; then echo faketpu; exit 0; fi\n"
        f"touch {marker}\nexit 1\n")
    fake.chmod(0o755)
    monkeypatch.setattr(runtime.sys, "executable", str(fake))
    assert runtime.accelerator_available(timeout_s=5, tries=2) is True


def test_probe_timeout(monkeypatch, tmp_path):
    fake = tmp_path / "python"
    fake.write_text("#!/bin/sh\nsleep 30\n")
    fake.chmod(0o755)
    monkeypatch.setattr(runtime.sys, "executable", str(fake))
    assert runtime.accelerator_available(timeout_s=1, tries=1) is False


def test_fresh_process_force_cpu_never_touches_plugin():
    # end-to-end in a clean interpreter: the registered accelerator
    # plugin (which blocks on its tunnel in this environment) must never
    # be initialised when the helper forces cpu first
    code = (
        "from reporter_tpu.utils.runtime import force_virtual_cpu\n"
        "force_virtual_cpu(4)\n"
        "import jax\n"
        "assert jax.default_backend() == 'cpu'\n"
        "assert len(jax.devices()) == 4\n"
        "print('ok')\n")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-500:]
    assert proc.stdout.strip().endswith("ok")
