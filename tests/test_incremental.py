"""Incremental matcher (ISSUE 19): byte parity with the windowed batch
path, fixed-lag semantics, carried-state lifecycle, and snapshot serde.

The contract under test is absolute: every report the incremental path
SERVES is byte-identical to ``match_many`` over the same window; every
window it cannot reproduce byte-for-byte (lag non-convergence, evicted
state, bucket overflow) comes back ``None`` and the caller re-routes it
through the batch path — fallback, never approximation.
"""
import json

import numpy as np
import pytest

from reporter_tpu.core.types import Point
from reporter_tpu.matcher import SegmentMatcher
from reporter_tpu.matcher import incremental as inc
from reporter_tpu.streaming.batcher import PointBatcher
from reporter_tpu.synth import build_grid_city, generate_trace


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=12, cols=12, spacing_m=200.0, seed=2,
                           service_road_fraction=0.0, internal_fraction=0.0)


@pytest.fixture
def matcher(city):
    # fresh per test: carried state must not leak across tests
    return SegmentMatcher(net=city)


def ser(obj):
    """Normalise either submit-path result shape (dict from the Python
    writer, MatchRuns from the native writer) to canonical JSON."""
    if isinstance(obj, dict):
        return json.dumps(obj, sort_keys=True)
    from reporter_tpu.matcher.matcher import render_segments_json
    s = render_segments_json(obj.cols, obj.lo, obj.hi, obj.mode)
    return json.dumps(json.loads(s), sort_keys=True)


def make_trace(city, seed, noise=4.0, **kw):
    rng = np.random.default_rng(seed)
    for _ in range(500):
        tr = generate_trace(city, f"veh-{seed}", rng, noise_m=noise, **kw)
        if tr is not None:
            return tr
    raise RuntimeError("could not generate a trace")


def stream_parity(m, pts, uuid, start=6, step=3, trim_every=0):
    """Feed growing (optionally prefix-trimmed) windows through BOTH
    paths; assert byte equality wherever the incremental path served.
    Returns (served, windows)."""
    served = windows = 0
    lo = 0
    for hi in range(start, len(pts) + 1, step):
        req = {"uuid": uuid, "trace": pts[lo:hi]}
        got = m.match_incremental([req])[0]
        windows += 1
        if got is not None:
            served += 1
            assert json.dumps(got, sort_keys=True) \
                == ser(m.match_many([req])[0]), \
                f"parity break for {uuid} window [{lo}:{hi}]"
        if trim_every and (hi // step) % trim_every == 0:
            lo = max(lo, hi - 3 * step)  # shape_used-style prefix trim
    return served, windows


def stop_and_go(pts, rng):
    """Inject a stopped-vehicle jitter cluster mid-trace plus a 3 km
    teleport of the tail (breakage -> RESTART)."""
    k = len(pts) // 2
    base = pts[k]
    stop = [dict(lat=base["lat"] + rng.normal(0, 2e-6),
                 lon=base["lon"] + rng.normal(0, 2e-6),
                 time=base["time"] + 1 + i) for i in range(8)]
    shift = stop[-1]["time"] - base["time"]
    tail = [dict(p, time=p["time"] + shift, lat=p["lat"] + 0.027)
            for p in pts[k + 1:]]
    return pts[:k + 1] + stop + tail


class TestParity:
    def test_incremental_matches_batch_noise_profiles(self, city, matcher):
        """The FB registry's parity pin: urban canyon (heavy noise),
        sparse rural (thinned fixes), stop-and-go (jitter clusters +
        breakage teleport) — every served report byte-equals batch."""
        rng = np.random.default_rng(7)
        total_served = 0
        for s in range(2):  # urban canyon: 20 m multipath-grade noise
            pts = list(make_trace(city, seed=100 + s, noise=20.0).points)
            total_served += stream_parity(matcher, pts,
                                          f"canyon-{s}")[0]
        for s in range(2):  # sparse rural: keep every 3rd fix
            pts = list(make_trace(city, seed=200 + s, noise=5.0).points)
            total_served += stream_parity(matcher, pts[::3],
                                          f"rural-{s}", start=4, step=2)[0]
        for s in range(2):  # stop-and-go + breakage
            pts = stop_and_go(
                list(make_trace(city, seed=300 + s, noise=8.0).points), rng)
            total_served += stream_parity(matcher, pts, f"sg-{s}")[0]
        assert total_served > 20  # the path must actually serve, not
        # just fall back its way to vacuous parity

    def test_parity_with_prefix_trims(self, city, matcher):
        """The batcher trims the consumed prefix after a report
        (shape_used): the carried state sees its window shrink from the
        left, resets, replays — and stays byte-exact throughout."""
        pts = list(make_trace(city, seed=42, noise=6.0).points)
        served, _ = stream_parity(matcher, pts, "trim-0", trim_every=2)
        assert served > 0
        assert matcher.incremental_table.resets > 0


class TestFixedLag:
    def test_report_inside_lag_window(self, city, matcher, monkeypatch):
        """A report whose whole window fits inside the lag bound decodes
        purely from the uncommitted ring (zero commits) — and still
        byte-matches the batch path."""
        monkeypatch.setenv(inc.ENV_LAG, "64")
        pts = list(make_trace(city, seed=9, noise=4.0).points)[:12]
        served, windows = stream_parity(matcher, pts, "short-0")
        assert served == windows  # nothing to fall back on: no
        # truncation, no f16 hazard, and commits are never forced
        gauge = matcher.incremental_table.gauge()
        assert gauge["traces"] == 1 and gauge["state_bytes"] > 0

    def test_tight_lag_falls_back_not_wrong(self, city, matcher,
                                            monkeypatch):
        """lag=2 (the floor) forces commits long before backtraces can
        converge under noise: fallbacks are expected and fine — but any
        window that IS served must still be byte-exact."""
        monkeypatch.setenv(inc.ENV_LAG, "2")
        pts = list(make_trace(city, seed=17, noise=12.0).points)
        stream_parity(matcher, pts, "tight-0")


class TestLifecycle:
    def test_kill_switch_serves_nothing(self, city, matcher, monkeypatch):
        monkeypatch.setenv(inc.ENV_INCREMENTAL, "off")
        pts = list(make_trace(city, seed=5).points)
        out = matcher.match_incremental([{"uuid": "k", "trace": pts}])
        assert out == [None]

    def test_pressure_shed_clears_state(self, city, matcher):
        pts = list(make_trace(city, seed=6).points)
        assert matcher.match_incremental(
            [{"uuid": "p", "trace": pts}])[0] is not None
        assert matcher.incremental_table.gauge()["traces"] == 1
        try:
            inc.set_pressure_shed(True)
            out = matcher.match_incremental([{"uuid": "p", "trace": pts}])
            assert out == [None]
            assert matcher.incremental_table.gauge()["traces"] == 0
        finally:
            inc.set_pressure_shed(False)

    def test_eviction_falls_back_byte_identically(self, city, matcher):
        """Mid-stream eviction (budget pressure stand-in): the next
        window replays from scratch and parity holds — eviction costs
        work, never bytes."""
        pts = list(make_trace(city, seed=23, noise=6.0).points)
        mid = max(8, len(pts) // 2)
        assert stream_parity(matcher, pts[:mid], "ev-0")[0] > 0
        matcher.incremental_table.evict("ev-0", "test eviction")
        assert matcher.incremental_table.gauge()["traces"] == 0
        served, _ = stream_parity(matcher, pts, "ev-0",
                                  start=mid, step=3)
        assert served > 0

    def test_session_gap_eviction_drops_carried_state(self, city, matcher):
        """The batcher's session-gap eviction (punctuate) rides the
        on_evict hook: the uuid's carried decode state dies WITH the
        session, after its final relaxed-threshold report."""
        pts = list(make_trace(city, seed=31).points)
        assert matcher.match_incremental(
            [{"uuid": "veh", "trace": pts}])[0] is not None
        assert matcher.incremental_table.gauge()["traces"] == 1
        evicted = []

        def on_evict(uuid):
            matcher.incremental_table.evict(uuid, "session gap")
            evicted.append(uuid)

        pb = PointBatcher(lambda t: None, lambda k, s: None,
                          on_evict=on_evict)
        pb.process("veh", Point(14.6, 121.0, 10, 0), stream_time_ms=0)
        pb.punctuate(stream_time_ms=200_000)  # past the 60 s gap
        assert evicted == ["veh"]
        assert matcher.incremental_table.gauge()["traces"] == 0


class TestSerde:
    def test_carried_state_roundtrip_resumes_byte_exact(self, city,
                                                        matcher):
        """to_blobs -> restore_blobs into a FRESH matcher resumes the
        decode mid-stream with parity intact (the crash-restore path,
        snapshot v3)."""
        pts = list(make_trace(city, seed=55, noise=6.0).points)
        mid = max(9, (len(pts) // 2) // 3 * 3)
        assert stream_parity(matcher, pts[:mid], "crash-0")[0] > 0
        blobs = matcher.incremental_table.to_blobs()
        assert blobs and all(isinstance(b, bytes) for _, b in blobs)

        m2 = SegmentMatcher(net=city)
        assert m2.incremental_table.restore_blobs(blobs) == len(blobs)
        # resumed table picks up where the dead worker stopped: the
        # appended points advance the RESTORED state (resets stay 0)
        served, _ = stream_parity(m2, pts, "crash-0", start=mid, step=3)
        assert served > 0
        assert m2.incremental_table.resets == 0

    def test_blob_roundtrip_carries_map_version(self, city, matcher):
        """Carried state is keyed to the map build that produced it
        (ISSUE 20): the v2 blob trailer round-trips the graph's
        content-derived version."""
        pts = list(make_trace(city, seed=77, noise=5.0).points)
        stream_parity(matcher, pts, "epoch-0")
        table = matcher.incremental_table
        assert table.map_version
        assert table.gauge()["map_version"] == table.map_version
        blobs = table.to_blobs()
        assert blobs
        st = inc.CarriedState.from_bytes(blobs[0][1])
        assert st.map_version == table.map_version
        # an unversioned state (ver-1 era) round-trips None
        bare = inc.CarriedState((1.0, 2.0), False, 4, map_version=None)
        assert inc.CarriedState.from_bytes(
            bare.to_bytes()).map_version is None

    def test_swap_resets_carried_state_against_new_graph(self, city,
                                                         matcher):
        """A hot map swap invalidates carried decode state: a restored
        state from vN RESETS on the vN+1 table (batch-oracle re-frame)
        instead of advancing a decode against the wrong graph, and the
        replayed window still holds byte parity on the new graph."""
        pts = list(make_trace(city, seed=78, noise=5.0).points)
        mid = max(9, (len(pts) // 2) // 3 * 3)
        stream_parity(matcher, pts[:mid], "swap-0")
        blobs = matcher.incremental_table.to_blobs()
        assert blobs

        city2 = build_grid_city(rows=12, cols=12, spacing_m=200.0,
                                seed=2, service_road_fraction=0.0,
                                internal_fraction=0.0)
        city2.edge_speed_kph = city2.edge_speed_kph * 1.3
        m2 = SegmentMatcher(net=city2)
        t2 = m2.incremental_table
        assert t2.map_version != matcher.incremental_table.map_version
        # the blobs parse fine (work avoidance is graph-agnostic)...
        assert t2.restore_blobs(blobs) == len(blobs)
        r0 = t2.resets
        # ...but the first report on the new graph drops them
        stream_parity(m2, pts, "swap-0", start=mid, step=3)
        assert t2.resets > r0

    def test_corrupt_blob_is_skipped_not_fatal(self, city, matcher):
        n = matcher.incremental_table.restore_blobs(
            [("bad", b"\x00\x01garbage")])
        assert n == 0
        assert matcher.incremental_table.gauge()["traces"] == 0

    def test_state_snapshot_v3_carries_frames(self, city, matcher,
                                              tmp_path):
        """StateStore.save tees the carried state into the v3 snapshot;
        restore hands it back through the provider."""
        from reporter_tpu.streaming.anonymiser import Anonymiser
        from reporter_tpu.streaming.state import StateStore

        class NullSink:
            def write(self, *a, **k):
                return None

        pts = list(make_trace(city, seed=71).points)
        assert matcher.match_incremental(
            [{"uuid": "snap", "trace": pts}])[0] is not None

        path = str(tmp_path / "state.bin")
        store = StateStore(path, incremental=lambda:
                           matcher.incremental_table)
        pb = PointBatcher(lambda t: None, lambda k, s: None)
        anon = Anonymiser(NullSink(), 2, 60)
        store.save(pb, anon)

        m2 = SegmentMatcher(net=city)
        store2 = StateStore(path, incremental=lambda:
                            m2.incremental_table)
        pb2 = PointBatcher(lambda t: None, lambda k, s: None)
        anon2 = Anonymiser(NullSink(), 2, 60)
        assert store2.restore(pb2, anon2)
        assert m2.incremental_table.gauge()["traces"] == 1

    def test_v2_snapshot_still_restores(self, city, tmp_path):
        """A pre-incremental (v2) snapshot restores batches/slices as
        before — the missing section is an empty cache, not corruption."""
        from reporter_tpu.streaming import state as state_mod
        from reporter_tpu.streaming.anonymiser import Anonymiser
        from reporter_tpu.streaming.state import StateStore

        class NullSink:
            def write(self, *a, **k):
                return None

        pb = PointBatcher(lambda t: None, lambda k, s: None)
        pb.process("veh", Point(14.6, 121.0, 10, 0), stream_time_ms=0)
        anon = Anonymiser(NullSink(), 2, 60)
        raw = bytearray(state_mod.snapshot_bytes(pb, anon))
        # rewrite the header version to 2 and drop the (empty)
        # incremental section's count field
        import struct
        struct.pack_into("<I", raw, 4, 2)
        raw = bytes(raw[:-4])

        path = str(tmp_path / "state.bin")
        with open(path, "wb") as f:
            f.write(raw)
        pb2 = PointBatcher(lambda t: None, lambda k, s: None)
        anon2 = Anonymiser(NullSink(), 2, 60)
        assert StateStore(path).restore(pb2, anon2)
        assert "veh" in pb2.store
