"""Fork-safety of module singletons (utils.forksafe reset hooks).

``os.fork()`` copies one thread; everything the parent's OTHER threads
were doing is frozen into the child forever — a held lock never
releases, the metrics registry snapshots the parent's work, the flight
recorder ring carries inherited spans. The pre-fork serving mode
(service/prefork.py) leans on the ``utils.forksafe`` hooks to reset all
of it in the child; these tests pin each hook by actually forking.

Every fork here happens from THIS pytest process but touches only
numpy/stdlib state (no jax in the children), and children always exit
via ``os._exit`` so a failing assertion cannot unwind into a second
copy of the pytest session.
"""
import os
import signal
import threading
import time

import pytest

from reporter_tpu.utils import forksafe, locks, metrics, spool
from reporter_tpu.obs import flightrec


def _fork_and_check(child_fn) -> int:
    """Run ``child_fn`` in a forked child; return its exit code. The
    child exits 0 when child_fn returns truthy, 1 otherwise, 2 on an
    exception — and never returns into pytest."""
    pid = os.fork()
    if pid == 0:
        code = 2
        try:
            code = 0 if child_fn() else 1
        except BaseException:
            pass
        finally:
            os._exit(code)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        done, status = os.waitpid(pid, os.WNOHANG)
        if done == pid:
            return os.waitstatus_to_exitcode(status)
        time.sleep(0.02)
    os.kill(pid, signal.SIGKILL)
    os.waitpid(pid, 0)
    pytest.fail("forked child hung (orphaned lock not reset?)")


def test_hooks_registered_at_import():
    # locks, metrics, spool, flightrec each register exactly one hook
    # at import (racecheck resets via the locks hook — it stays
    # stdlib-only and cannot import forksafe itself)
    assert forksafe.hook_count() >= 4


def test_orphaned_tracked_lock_resets_in_child():
    """A TrackedLock held by a thread that does not survive the fork
    must be usable in the child — the hook swaps in a fresh inner lock
    instead of leaving one locked forever."""
    lk = locks.new_lock("forksafe.test.orphan")
    t = threading.Thread(target=lk.acquire)
    t.start()
    t.join()
    assert lk.locked()

    def child():
        if lk.locked():
            return False
        with lk:  # must not deadlock
            pass
        return True

    assert _fork_and_check(child) == 0
    # the parent's view is untouched: its (vanished-thread) hold remains
    assert lk.locked()
    lk._lock = threading.Lock()  # don't leak a held lock into the sweep


def test_metrics_registry_resets_in_child():
    """A forked worker's /metrics reports ITS work, not a copy-on-write
    snapshot of the parent's (per-process metrics contract)."""
    metrics.count("forksafe.test.sentinel", 7)

    def child():
        if metrics.counter("forksafe.test.sentinel") != 0:
            return False
        metrics.count("forksafe.test.child")
        return metrics.counter("forksafe.test.child") == 1

    assert _fork_and_check(child) == 0
    # parent registry untouched by the child's reset
    assert metrics.counter("forksafe.test.sentinel") == 7


def test_spool_caches_reset_in_child(tmp_path):
    """Byte estimates and backlog gauges describe the PARENT's view of
    the spool roots; the child re-seeds from disk on first use."""
    root = str(tmp_path / "spool")
    spool.write(root, "a/tile.json", "x" * 64)
    with spool._lock:
        spool._approx_bytes[root] = 12345  # simulate a stale estimate
    spool.backlog_cached(root)  # populate the TTL cache

    def child():
        with spool._lock:
            if spool._approx_bytes.unwrap() or \
                    spool._backlog_cache.unwrap():
                return False
        # fresh walk still sees the shared on-disk spool
        return spool.backlog(root)["files"] == 1

    assert _fork_and_check(child) == 0
    with spool._lock:
        assert spool._approx_bytes[root] == 12345


def test_flightrec_ring_resets_in_child():
    """A child postmortem carries the child's spans, not inherited
    ones; the dump-dir configuration (deployment-shared) survives."""
    flightrec.record_closed([{"name": "parent.span", "t0_ns": 1,
                              "dur_ns": 2}])
    assert flightrec.events()

    def child():
        return not flightrec.events() and not flightrec.in_flight()

    assert _fork_and_check(child) == 0
    assert flightrec.events()  # parent ring untouched


def test_racecheck_state_resets_in_child():
    """Armed-witness graph state records parent acquisitions that will
    never release in the child — the locks hook clears it."""
    from reporter_tpu.analysis import racecheck
    was_armed = locks.armed()
    locks.arm()
    try:
        a = locks.new_lock("forksafe.test.rc.a")
        b = locks.new_lock("forksafe.test.rc.b")
        with a:
            with b:
                pass
        assert racecheck.edge_count() >= 1

        def child():
            return racecheck.edge_count() == 0

        assert _fork_and_check(child) == 0
        assert racecheck.edge_count() >= 1
    finally:
        if not was_armed:
            locks.disarm()
        racecheck.reset()


def test_native_runtime_fork_guard():
    """The native handle's C++ worker-pool threads do not survive a
    fork: a child calling through an inherited handle must get a loud
    RuntimeError (the matcher's circuit breaker degrades around it),
    not a condvar hang. The route memo rides the handle, so this guard
    is also its proven-unsafe-but-guarded fork story."""
    from reporter_tpu import native
    if not native.available():
        pytest.skip("native toolchain unavailable")
    from reporter_tpu.synth import build_grid_city
    city = build_grid_city(rows=4, cols=4, spacing_m=200.0, seed=5,
                           service_road_fraction=0.0,
                           internal_fraction=0.0)
    rt = native.NativeRuntime(city)
    # sanity in the parent
    assert rt.candidates([city.node_lat[0]], [city.node_lon[0]], 4) \
        is not None

    def child():
        try:
            rt.candidates([city.node_lat[0]], [city.node_lon[0]], 4)
        except RuntimeError as e:
            return "fork" in str(e)
        return False

    assert _fork_and_check(child) == 0
    # the parent's handle still works afterwards (the child neither
    # used nor destroyed it)
    assert rt.candidates([city.node_lat[0]], [city.node_lon[0]], 4) \
        is not None
