"""HTTP egress: retries, swallow-and-log, AWS v2 signing."""
import base64
import hashlib
import hmac
import http.server
import threading

import pytest

from reporter_tpu.utils import http as rhttp


@pytest.fixture
def server():
    """Local HTTP server recording requests; scriptable status codes."""
    state = {"requests": [], "codes": []}

    class Handler(http.server.BaseHTTPRequestHandler):
        def _handle(self):
            length = int(self.headers.get("Content-Length", 0))
            state["requests"].append({
                "method": self.command,
                "path": self.path,
                "headers": dict(self.headers),
                "body": self.rfile.read(length).decode(),
            })
            code = state["codes"].pop(0) if state["codes"] else 200
            self.send_response(code)
            body = b"ok" if code == 200 else b"err"
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        do_POST = do_PUT = _handle

        def log_message(self, fmt, *args):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    state["url"] = f"http://127.0.0.1:{httpd.server_port}"
    yield state
    httpd.shutdown()


class TestRetries:
    def test_post_ok(self, server):
        assert rhttp.post(server["url"] + "/x", "hello") == "ok"
        (req,) = server["requests"]
        assert req["method"] == "POST" and req["body"] == "hello"
        assert req["headers"]["Content-Type"] == "text/plain;charset=utf-8"

    def test_5xx_retried_then_succeeds(self, server, monkeypatch):
        monkeypatch.setattr(rhttp.time, "sleep", lambda s: None)
        server["codes"] = [500, 502]
        assert rhttp.put(server["url"] + "/x", "v") == "ok"
        assert len(server["requests"]) == 3

    def test_4xx_not_retried(self, server, monkeypatch):
        monkeypatch.setattr(rhttp.time, "sleep", lambda s: None)
        server["codes"] = [403]
        assert rhttp.post(server["url"] + "/x", "v") is None
        assert len(server["requests"]) == 1

    def test_connection_refused_swallowed(self, monkeypatch):
        # reference: HttpClient.java:95-98 — errors swallowed, null returned
        monkeypatch.setattr(rhttp.time, "sleep", lambda s: None)
        assert rhttp.post("http://127.0.0.1:9/x", "v") is None


class TestAwsSigning:
    def test_signature_is_hmac_sha1_base64(self):
        expected = base64.b64encode(
            hmac.new(b"secret", b"sign me", hashlib.sha1).digest()).decode()
        assert rhttp.aws_signature("sign me", "secret") == expected

    def test_aws_put_canonical_headers(self, monkeypatch):
        # reference: HttpClient.java:44-58 — resource is /bucket/<key>,
        # string-to-sign is PUT\n\n{type}\n{date}\n{resource}
        captured = {}

        def fake_put(url, body, content_type=None, headers=None):
            captured.update(url=url, body=body, headers=headers)
            return "ok"

        monkeypatch.setattr(rhttp, "put", fake_put)
        date = "Tue, 27 Mar 2007 21:15:45 +0000"
        assert rhttp.aws_put("https://speeds.s3.amazonaws.com",
                             "t/1/2/tile.csv", "payload",
                             "AKID", "secret", date=date) == "ok"
        assert captured["url"] == \
            "https://speeds.s3.amazonaws.com/t/1/2/tile.csv"
        assert captured["headers"]["Host"] == "speeds.s3.amazonaws.com"
        assert captured["headers"]["Date"] == date
        sign_me = ("PUT\n\ntext/plain;charset=utf-8\n" + date
                   + "\n/speeds/t/1/2/tile.csv")
        assert captured["headers"]["Authorization"] == \
            "AWS AKID:" + rhttp.aws_signature(sign_me, "secret")

    def test_aws_put_with_key_prefix(self, monkeypatch):
        # a path on the bucket URL is a key prefix, not part of the host
        captured = {}

        def fake_put(url, body, content_type=None, headers=None):
            captured.update(url=url, headers=headers)
            return "ok"

        monkeypatch.setattr(rhttp, "put", fake_put)
        date = "Tue, 27 Mar 2007 21:15:45 +0000"
        rhttp.aws_put("https://speeds.s3.amazonaws.com/manila/v1",
                      "tile.csv", "p", "AKID", "secret", date=date)
        assert captured["url"] == \
            "https://speeds.s3.amazonaws.com/manila/v1/tile.csv"
        assert captured["headers"]["Host"] == "speeds.s3.amazonaws.com"
        sign_me = ("PUT\n\ntext/plain;charset=utf-8\n" + date
                   + "\n/speeds/manila/v1/tile.csv")
        assert captured["headers"]["Authorization"] == \
            "AWS AKID:" + rhttp.aws_signature(sign_me, "secret")


class TestEgressTile:
    def test_plain_http_routes_to_post(self, server):
        assert rhttp.egress_tile(server["url"], "1_2/0/3/src.abc", "csv")
        (req,) = server["requests"]
        assert req["method"] == "POST"
        assert req["path"] == "/1_2/0/3/src.abc"

    def test_aws_host_routes_to_signed_put(self, monkeypatch):
        calls = {}
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKID")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "sec")
        monkeypatch.setattr(
            rhttp, "aws_put",
            lambda url, key, body, a, s, **kw: calls.update(url=url, key=key)
            or "ok")
        assert rhttp.egress_tile("https://b.s3.amazonaws.com", "k/t.csv", "p")
        assert calls == {"url": "https://b.s3.amazonaws.com", "key": "k/t.csv"}

    def test_aws_host_without_creds_fails_closed(self, monkeypatch):
        monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
        monkeypatch.delenv("AWS_SECRET_ACCESS_KEY", raising=False)
        called = []
        monkeypatch.setattr(rhttp, "aws_put",
                            lambda *a, **kw: called.append(a) or "ok")
        assert not rhttp.egress_tile("https://b.s3.amazonaws.com", "k", "p")
        assert called == []

    def test_aws_host_matching(self):
        assert rhttp.is_aws_host("https://b.s3.amazonaws.com")
        assert rhttp.is_aws_host("https://b.s3.amazonaws.com:443/prefix")
        assert not rhttp.is_aws_host("https://my-amazonaws.com")
        assert not rhttp.is_aws_host("http://127.0.0.1:8080")

    def test_tile_sink_http_uses_egress(self, server):
        from reporter_tpu.streaming.anonymiser import TileSink
        sink = TileSink(server["url"])
        assert sink.store("1_2/0/3", "src.abc", "csv,data") is True
        (req,) = server["requests"]
        assert req["method"] == "POST"
        assert req["path"] == "/1_2/0/3/src.abc"
        assert req["body"] == "csv,data"
