"""HTTP egress: retries, swallow-and-log, AWS v2 signing."""
import base64
import hashlib
import hmac
import http.server
import threading

import pytest

from reporter_tpu.utils import http as rhttp


@pytest.fixture
def server():
    """Local HTTP server recording requests; scriptable status codes."""
    state = {"requests": [], "codes": [], "headers": []}

    class Handler(http.server.BaseHTTPRequestHandler):
        def _handle(self):
            length = int(self.headers.get("Content-Length", 0))
            state["requests"].append({
                "method": self.command,
                "path": self.path,
                "headers": dict(self.headers),
                "body": self.rfile.read(length).decode(),
            })
            code = state["codes"].pop(0) if state["codes"] else 200
            extra = state["headers"].pop(0) if state["headers"] else {}
            self.send_response(code)
            body = b"ok" if code == 200 else b"err"
            self.send_header("Content-Length", str(len(body)))
            for k, v in extra.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        do_POST = do_PUT = _handle

        def log_message(self, fmt, *args):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    state["url"] = f"http://127.0.0.1:{httpd.server_port}"
    yield state
    httpd.shutdown()


class TestRetries:
    def test_post_ok(self, server):
        assert rhttp.post(server["url"] + "/x", "hello") == "ok"
        (req,) = server["requests"]
        assert req["method"] == "POST" and req["body"] == "hello"
        assert req["headers"]["Content-Type"] == "text/plain;charset=utf-8"

    def test_5xx_retried_then_succeeds(self, server, monkeypatch):
        monkeypatch.setattr(rhttp.time, "sleep", lambda s: None)
        server["codes"] = [500, 502]
        assert rhttp.put(server["url"] + "/x", "v") == "ok"
        assert len(server["requests"]) == 3

    def test_4xx_not_retried(self, server, monkeypatch):
        monkeypatch.setattr(rhttp.time, "sleep", lambda s: None)
        server["codes"] = [403]
        assert rhttp.post(server["url"] + "/x", "v") is None
        assert len(server["requests"]) == 1

    def test_connection_refused_swallowed(self, monkeypatch):
        # reference: HttpClient.java:95-98 — errors swallowed, null returned
        monkeypatch.setattr(rhttp.time, "sleep", lambda s: None)
        assert rhttp.post("http://127.0.0.1:9/x", "v") is None


class TestBackoffSchedule:
    """The retry schedule, driven by a fake clock (no real sleeping)."""

    def _sleeps(self, monkeypatch):
        slept = []
        monkeypatch.setattr(rhttp.time, "sleep", slept.append)
        return slept

    def test_exponential_schedule_on_5xx(self, server, monkeypatch):
        slept = self._sleeps(monkeypatch)
        server["codes"] = [500, 502, 500]
        assert rhttp.post(server["url"] + "/x", "v") is None
        # ATTEMPTS=3 -> two sleeps, doubling from BACKOFF_BASE_S
        assert slept == [rhttp.BACKOFF_BASE_S, rhttp.BACKOFF_BASE_S * 2]

    def test_backoff_is_capped(self):
        assert rhttp.retry_delay(50) == rhttp.BACKOFF_CAP_S
        assert rhttp.retry_delay(0) == rhttp.BACKOFF_BASE_S

    def test_429_honours_retry_after_seconds(self, server, monkeypatch):
        slept = self._sleeps(monkeypatch)
        server["codes"] = [429]
        server["headers"] = [{"Retry-After": "7"}]
        assert rhttp.post(server["url"] + "/x", "v") == "ok"
        assert slept == [7.0]

    def test_503_honours_retry_after(self, server, monkeypatch):
        slept = self._sleeps(monkeypatch)
        server["codes"] = [503]
        server["headers"] = [{"Retry-After": "2"}]
        assert rhttp.post(server["url"] + "/x", "v") == "ok"
        assert slept == [2.0]

    def test_retry_after_capped(self, server, monkeypatch):
        slept = self._sleeps(monkeypatch)
        server["codes"] = [429]
        server["headers"] = [{"Retry-After": "86400"}]
        assert rhttp.post(server["url"] + "/x", "v") == "ok"
        assert slept == [rhttp.RETRY_AFTER_CAP_S]

    def test_429_without_header_backs_off_exponentially(self, server,
                                                        monkeypatch):
        slept = self._sleeps(monkeypatch)
        server["codes"] = [429, 429]
        assert rhttp.post(server["url"] + "/x", "v") == "ok"
        assert slept == [rhttp.BACKOFF_BASE_S, rhttp.BACKOFF_BASE_S * 2]

    def test_parse_retry_after_http_date(self):
        # an HTTP-date is relative to the (injected) clock
        now = 1700000000.0
        date = rhttp.email.utils.formatdate(now + 42, usegmt=True)
        got = rhttp.parse_retry_after(date, now=now)
        assert got == pytest.approx(42.0, abs=1.0)

    def test_parse_retry_after_past_date_clamps_to_zero(self):
        now = 1700000000.0
        date = rhttp.email.utils.formatdate(now - 500, usegmt=True)
        assert rhttp.parse_retry_after(date, now=now) == 0.0

    def test_parse_retry_after_garbage_is_none(self):
        assert rhttp.parse_retry_after(None) is None
        assert rhttp.parse_retry_after("soon") is None


class TestAwsSigning:
    def test_signature_is_hmac_sha1_base64(self):
        expected = base64.b64encode(
            hmac.new(b"secret", b"sign me", hashlib.sha1).digest()).decode()
        assert rhttp.aws_signature("sign me", "secret") == expected

    def test_aws_put_canonical_headers(self, monkeypatch):
        # reference: HttpClient.java:44-58 — resource is /bucket/<key>,
        # string-to-sign is PUT\n\n{type}\n{date}\n{resource}
        captured = {}

        def fake_put(url, body, content_type=None, headers=None):
            captured.update(url=url, body=body, headers=headers)
            return "ok"

        monkeypatch.setattr(rhttp, "put", fake_put)
        date = "Tue, 27 Mar 2007 21:15:45 +0000"
        assert rhttp.aws_put("https://speeds.s3.amazonaws.com",
                             "t/1/2/tile.csv", "payload",
                             "AKID", "secret", date=date) == "ok"
        assert captured["url"] == \
            "https://speeds.s3.amazonaws.com/t/1/2/tile.csv"
        assert captured["headers"]["Host"] == "speeds.s3.amazonaws.com"
        assert captured["headers"]["Date"] == date
        sign_me = ("PUT\n\ntext/plain;charset=utf-8\n" + date
                   + "\n/speeds/t/1/2/tile.csv")
        assert captured["headers"]["Authorization"] == \
            "AWS AKID:" + rhttp.aws_signature(sign_me, "secret")

    def test_aws_put_with_key_prefix(self, monkeypatch):
        # a path on the bucket URL is a key prefix, not part of the host
        captured = {}

        def fake_put(url, body, content_type=None, headers=None):
            captured.update(url=url, headers=headers)
            return "ok"

        monkeypatch.setattr(rhttp, "put", fake_put)
        date = "Tue, 27 Mar 2007 21:15:45 +0000"
        rhttp.aws_put("https://speeds.s3.amazonaws.com/manila/v1",
                      "tile.csv", "p", "AKID", "secret", date=date)
        assert captured["url"] == \
            "https://speeds.s3.amazonaws.com/manila/v1/tile.csv"
        assert captured["headers"]["Host"] == "speeds.s3.amazonaws.com"
        sign_me = ("PUT\n\ntext/plain;charset=utf-8\n" + date
                   + "\n/speeds/manila/v1/tile.csv")
        assert captured["headers"]["Authorization"] == \
            "AWS AKID:" + rhttp.aws_signature(sign_me, "secret")


class TestEgressTile:
    def test_plain_http_routes_to_post(self, server):
        assert rhttp.egress_tile(server["url"], "1_2/0/3/src.abc", "csv")
        (req,) = server["requests"]
        assert req["method"] == "POST"
        assert req["path"] == "/1_2/0/3/src.abc"

    def test_aws_host_routes_to_signed_put(self, monkeypatch):
        calls = {}
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKID")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "sec")
        monkeypatch.setattr(
            rhttp, "aws_put",
            lambda url, key, body, a, s, **kw: calls.update(url=url, key=key)
            or "ok")
        assert rhttp.egress_tile("https://b.s3.amazonaws.com", "k/t.csv", "p")
        assert calls == {"url": "https://b.s3.amazonaws.com", "key": "k/t.csv"}

    def test_aws_host_without_creds_fails_closed(self, monkeypatch):
        monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
        monkeypatch.delenv("AWS_SECRET_ACCESS_KEY", raising=False)
        called = []
        monkeypatch.setattr(rhttp, "aws_put",
                            lambda *a, **kw: called.append(a) or "ok")
        assert not rhttp.egress_tile("https://b.s3.amazonaws.com", "k", "p")
        assert called == []

    def test_aws_host_matching(self):
        assert rhttp.is_aws_host("https://b.s3.amazonaws.com")
        assert rhttp.is_aws_host("https://b.s3.amazonaws.com:443/prefix")
        assert not rhttp.is_aws_host("https://my-amazonaws.com")
        assert not rhttp.is_aws_host("http://127.0.0.1:8080")

    def test_tile_sink_http_uses_egress(self, server):
        from reporter_tpu.streaming.anonymiser import TileSink
        sink = TileSink(server["url"])
        assert sink.store("1_2/0/3", "src.abc", "csv,data") is True
        (req,) = server["requests"]
        assert req["method"] == "POST"
        assert req["path"] == "/1_2/0/3/src.abc"
        assert req["body"] == "csv,data"
