"""Complete-traversal semantics of segment assembly (round-4 fixes).

Pins the honest-completeness rules directly (they are otherwise covered
only via native/numpy parity and the accuracy gates):

- a one-point flicker onto a crossing segment must NOT be reported as a
  complete traversal (the pre-round-4 clamped interpolation fabricated
  exactly that);
- apparent backward movement within the matcher's backward tolerance
  does not split a run, so a genuine end-to-end traversal with
  along-track jitter still reports complete;
- the ranking-only turn penalty does not leak into reported times;
- a lone-point chain can never claim completeness.

All through the public match path on hand-built meter-grid networks,
on BOTH the native and numpy backends.
"""
import numpy as np
import pytest

from reporter_tpu import native
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from tests.test_knobs import _net_from_meters, _pts_from_meters

BACKENDS = [True, False]


def _complete_ids(match):
    return [s["segment_id"] for s in match["segments"]
            if s.get("segment_id") is not None and s.get("length", -1) > 0]


def _req(pts):
    return {"uuid": "t", "trace": pts,
            "match_options": {"mode": "auto", "report_levels": [0, 1, 2],
                              "transition_levels": [0, 1, 2]}}


@pytest.fixture(scope="module")
def cross_city():
    """A horizontal road (edges 0-1) crossed mid-way by a long vertical
    road (edges 2-3), sharing the center node."""
    return _net_from_meters(
        [(0.0, 0.0), (200.0, 0.0), (400.0, 0.0),      # horizontal nodes
         (200.0, -1400.0), (200.0, 1400.0)],           # vertical ends
        [(0, 1), (1, 2), (3, 1), (1, 4)])


def _flicker_fixture(cross_city):
    """A hand-built decoded path that flickers one point onto the long
    vertical segment mid-chain: point 0 on the horizontal (edge 0 @180),
    point 1 on the vertical edge 2 @1386 (14 m south of center), point 2
    back on the horizontal (edge 1 @40). Route steps are small and
    finite, exactly the inputs under which the pre-round-4 clamped
    interpolation granted the 1400 m vertical segment BOTH boundary
    times (claiming a complete traversal the route never made)."""
    # the hand-built tensors encode cross_city's geometry; pin the
    # invariants they depend on so a fixture edit fails loudly here
    assert cross_city.segment_length_m[2] == pytest.approx(1400.0, abs=1.0)
    assert float(cross_city.edge_length_m[0]) == pytest.approx(200.0,
                                                               abs=0.5)
    from reporter_tpu.matcher.hmm import NORMAL, RESTART, SKIP
    T, K = 16, 4
    edge_ids = np.full((T, K), -1, np.int32)
    dist = np.full((T, K), 1.0e9, np.float32)
    offset = np.zeros((T, K), np.float32)
    route = np.full((T - 1, K, K), 1.0e9, np.float32)
    gc = np.zeros(T - 1, np.float32)
    case = np.full(T, SKIP, np.int32)
    edge_ids[0, 0], offset[0, 0], dist[0, 0] = 0, 180.0, 1.0
    edge_ids[1, 0], offset[1, 0], dist[1, 0] = 2, 1386.0, 0.5
    edge_ids[2, 0], offset[2, 0], dist[2, 0] = 1, 40.0, 1.0
    route[0, 0, 0] = 34.0   # horiz@180 -> vertical@1386 (via center)
    route[1, 0, 0] = 54.0   # vertical@1386 -> horiz(1)@40
    gc[0], gc[1] = 35.0, 55.0
    case[0], case[1], case[2] = RESTART, NORMAL, NORMAL
    path = np.zeros(T, np.int32)
    times = np.array([0.0, 3.0, 6.0] + [0.0] * 13)
    kept = np.arange(T, dtype=np.int32)
    return dict(edge_ids=edge_ids, dist=dist, offset=offset, route=route,
                gc=gc, case=case, path=path, times=times, kept=kept, n=3)


def test_intersection_flicker_is_not_complete_python(cross_city):
    from reporter_tpu.matcher.assemble import assemble_segments
    from reporter_tpu.matcher.batchpad import PreparedTrace
    f = _flicker_fixture(cross_city)
    p = PreparedTrace(num_raw=3, num_kept=f["n"], kept_idx=f["kept"][:3],
                      times=f["times"][:3], edge_ids=f["edge_ids"],
                      dist_m=f["dist"], offset_m=f["offset"],
                      route_m=f["route"], gc_m=f["gc"], case=f["case"])
    match = assemble_segments(cross_city, p, f["path"])
    on_2 = [s for s in match["segments"] if s.get("segment_id") == 2]
    assert len(on_2) == 1, match["segments"]  # exactly one flicker run
    v = on_2[0]
    # exit IS observed (14 m to the segment end lies on the route to the
    # next probe) but entry is NOT (1386 m of the segment were never
    # routed) -> partial, never complete
    assert v["start_time"] == -1.0 and v["length"] == -1, v
    assert v["end_time"] >= 0.0, v  # the observed exit stays reported


def test_intersection_flicker_is_not_complete_native(cross_city):
    if not native.available():
        pytest.skip("native toolchain unavailable")
    m = SegmentMatcher(net=cross_city, params=MatchParams(max_candidates=4))
    f = _flicker_fixture(cross_city)
    T, K = f["edge_ids"].shape
    prep = {
        "edge_ids": f["edge_ids"][None], "dist_m": f["dist"][None],
        "offset_m": f["offset"][None],
        # native layout: route/gc padded to T time rows
        "route_m": np.concatenate(
            [f["route"], np.zeros((1, K, K), np.float32)])[None],
        "gc_m": np.concatenate([f["gc"], np.zeros(1, np.float32)])[None],
        "case": f["case"][None], "kept_idx": f["kept"][None],
        "num_kept": np.array([f["n"]], np.int32),
        "dwell": np.zeros(1, np.float32),
    }
    runs = m.runtime.assemble_batch(
        f["path"][None], prep, np.array([0, 3], np.int64), f["times"][:3],
        queue_threshold_kph=10.0, interpolation_distance_m=10.0)
    segs = runs["seg_id"][:runs["n_runs"]]
    idx = np.nonzero(segs == 2)[0]
    assert idx.size == 1, segs  # the flicker run exists
    r = int(idx[0])
    assert runs["start"][r] == -1.0 and runs["length"][r] == -1
    assert runs["end"][r] >= 0.0  # the observed exit stays reported


@pytest.mark.parametrize("use_native", BACKENDS)
def test_backward_jitter_keeps_traversal_complete(use_native):
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    road = _net_from_meters([(0.0, 0.0), (400.0, 0.0), (800.0, 0.0)],
                            [(0, 1), (1, 2)])
    # steady eastward drive with one ~15 m apparent backward hop
    # (within the 25 m backward tolerance) mid-segment
    xs = [5, 50, 95, 140, 185, 170, 230, 275, 320, 365, 398]
    pts = _pts_from_meters([(float(x), (-1.0) ** i, 3.0 * i)
                            for i, x in enumerate(xs)])
    m = SegmentMatcher(net=road, use_native=use_native,
                       params=MatchParams())
    match = m.match_many([_req(pts)])[0]
    assert 0 in _complete_ids(match), match["segments"]
    # and the traversal is ONE run, not shattered partials
    runs_on_0 = [s for s in match["segments"] if s.get("segment_id") == 0]
    assert len(runs_on_0) == 1


@pytest.mark.parametrize("use_native", BACKENDS)
def test_turn_penalty_does_not_distort_times(use_native):
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    road = _net_from_meters([(0.0, 0.0), (300.0, 0.0), (300.0, 300.0)],
                            [(0, 1), (1, 2)])
    # unambiguous L-shaped drive: same decoded path with or without the
    # turn penalty, so every reported time must be identical (the penalty
    # is ranking-only; it must not shift cumulative route positions)
    pts = _pts_from_meters(
        [(float(x), 0.5, 2.0 * i) for i, x in enumerate(
            [5, 45, 85, 125, 165, 205, 245, 285])]
        + [(300.5, float(y), 16.0 + 2.0 * j) for j, y in enumerate(
            [25, 65, 105, 145, 185, 225, 265, 295])])
    free = SegmentMatcher(net=road, use_native=use_native,
                          params=MatchParams(turn_penalty_factor=0.0))
    penal = SegmentMatcher(net=road, use_native=use_native,
                           params=MatchParams(turn_penalty_factor=500.0))
    m_free = free.match_many([_req(pts)])[0]
    m_penal = penal.match_many([_req(pts)])[0]
    assert m_free == m_penal


@pytest.mark.parametrize("use_native", BACKENDS)
def test_offnetwork_gap_points_stay_unattributed(use_native):
    """Mid-trace candidate-less probes (vehicle off the mapped network,
    e.g. a parking lot) must NOT be folded into any run's index span;
    jitter-dropped points in the same gap after the detour may join the
    following run."""
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    road = _net_from_meters([(0.0, 0.0), (400.0, 0.0), (800.0, 0.0)],
                            [(0, 1), (1, 2)])
    pts = []
    xs_on = [(230, 0), (275, 1), (320, -1), (365, 0)]  # on segment 0
    for i, (x, y) in enumerate(xs_on):
        pts.append((float(x), float(y), 3.0 * i))
    # off-network detour ACROSS the segment boundary at x=400: 3 probes
    # ~100 m south of the road (outside the 50 m search radius -> no
    # candidates), so the runs on segment 0 and segment 1 have a gap
    # between their spans
    for j, x in enumerate((385, 400, 415)):
        pts.append((float(x), -100.0, 12.0 + 3.0 * j))
    for j, (x, y) in enumerate([(440, 0), (485, 1), (530, -1), (575, 0),
                                (620, 1)]):
        pts.append((float(x), float(y), 21.0 + 3.0 * j))
    m = SegmentMatcher(net=road, use_native=use_native,
                       params=MatchParams())
    match = m.match_many([_req(_pts_from_meters(pts))])[0]
    spans = {s.get("segment_id"):
             (s["begin_shape_index"], s["end_shape_index"])
             for s in match["segments"]}
    assert 0 in spans and 1 in spans, match["segments"]
    covered = set()
    for b, e in spans.values():
        covered.update(range(b, e + 1))
    # the three off-network probes (indices 4, 5, 6) stay unattributed
    assert not covered & {4, 5, 6}, sorted(covered)
    # every on-network probe is covered
    assert {0, 1, 2, 3}.issubset(covered)
    assert set(range(7, 12)).issubset(covered)


@pytest.mark.parametrize("use_native", BACKENDS)
def test_lone_point_chain_never_complete(use_native):
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    road = _net_from_meters([(0.0, 0.0), (40.0, 0.0)], [(0, 1)])
    # two probes, but the second is jitter-dropped (within the
    # interpolation distance): a single kept point on a segment short
    # enough that the widened endpoint tolerance covers both ends
    pts = _pts_from_meters([(20.0, 0.5, 0.0), (22.0, -0.5, 5.0)])
    m = SegmentMatcher(net=road, use_native=use_native,
                       params=MatchParams())
    match = m.match_many([_req(pts)])[0]
    assert not _complete_ids(match), match["segments"]
