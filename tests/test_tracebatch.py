"""Columnar TraceBatch: the zero-dict ingestion contract.

Every ingestion edge (service, streaming worker, batch pipeline, bench)
now hands the matcher one TraceBatch instead of request dicts; these
tests pin (a) the dict-view compatibility surface report() and the tile
emitters rely on, (b) the ragged gather the matcher's chunking uses, and
(c) end-to-end equality: match_many over a TraceBatch must return
byte-identical results to match_many over the request dicts it came
from, on both the native and numpy paths.
"""
import numpy as np
import pytest

from reporter_tpu import native
from reporter_tpu.core.tracebatch import (TraceBatch, as_trace_batch,
                                          points_to_columns)
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.synth import build_grid_city, generate_trace


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=3)


@pytest.fixture(scope="module")
def reqs(city):
    rng = np.random.default_rng(17)
    out = []
    while len(out) < 10:
        tr = generate_trace(city, f"tb-{len(out)}", rng, noise_m=4.0,
                            min_route_edges=3, max_route_edges=12)
        if tr is None or len(tr.points) < 4:
            continue
        r = tr.request_json()
        r["trace"] = tr.points
        r["match_options"] = {"mode": "auto", "report_levels": [0, 1, 2],
                              "transition_levels": [0, 1, 2]}
        out.append(r)
    return out


def test_points_to_columns_roundtrip(reqs):
    pts = reqs[0]["trace"]
    lat, lon, tm, acc = points_to_columns(pts)
    assert lat.tolist() == [p["lat"] for p in pts]
    assert lon.tolist() == [p["lon"] for p in pts]
    assert tm.tolist() == [p["time"] for p in pts]
    assert acc is not None
    assert acc.astype(int).tolist() == [p["accuracy"] for p in pts]


def test_from_requests_views(reqs):
    tb = TraceBatch.from_requests(reqs)
    assert len(tb) == len(reqs)
    for i, req in enumerate(reqs):
        view = tb[i]
        assert view["uuid"] == req["uuid"]
        assert view["match_options"] == req["match_options"]
        pts = view["trace"]
        assert len(pts) == len(req["trace"])
        # first/last/negative indexing, the report() access pattern
        assert pts[-1]["time"] == req["trace"][-1]["time"]
        assert pts[0]["lat"] == pytest.approx(req["trace"][0]["lat"])
        with pytest.raises(IndexError):
            pts[len(pts)]
        # slicing + iteration materialise point dicts lazily
        assert [p["time"] for p in pts[:2]] == \
            [p["time"] for p in req["trace"][:2]]
        assert view.get("missing-key") is None
        assert "trace" in view and "missing-key" not in view


def test_gather_reorders_and_slices(reqs):
    tb = TraceBatch.from_requests(reqs)
    idx = [7, 0, 3, 3]  # out of order, with a repeat
    sub = tb.gather(idx)
    assert len(sub) == 4
    for row, i in enumerate(idx):
        lat, lon, tm = sub.trace_columns(row)
        want_lat, want_lon, want_tm = tb.trace_columns(i)
        np.testing.assert_array_equal(lat, want_lat)
        np.testing.assert_array_equal(lon, want_lon)
        np.testing.assert_array_equal(tm, want_tm)
        assert sub.uuid(row) == tb.uuid(i)
        assert sub.option(row) == tb.option(i)


def test_concat_collapses_shared_options():
    shared = {"mode": "auto"}
    parts = [(f"u{i}", np.zeros(2), np.zeros(2), np.arange(2.0),
              np.zeros(2, np.float32), shared) for i in range(3)]
    tb = TraceBatch.concat(parts)
    assert tb.options is shared  # one object for the whole batch
    mixed = parts[:2] + [("u2", np.zeros(2), np.zeros(2), np.arange(2.0),
                          np.zeros(2, np.float32), {"mode": "auto"})]
    tb2 = TraceBatch.concat(mixed)
    assert isinstance(tb2.options, list)  # equal values, distinct objects


def test_to_request_materialises_dicts(reqs):
    tb = TraceBatch.from_requests(reqs)
    back = tb[2].to_request()
    assert back["uuid"] == reqs[2]["uuid"]
    assert back["match_options"] == reqs[2]["match_options"]
    assert len(back["trace"]) == len(reqs[2]["trace"])
    assert back["trace"][0]["time"] == reqs[2]["trace"][0]["time"]


@pytest.mark.parametrize("use_native", [True, False])
def test_match_many_tracebatch_equals_dicts(city, reqs, use_native):
    if use_native and not native.available():
        pytest.skip("native toolchain unavailable")
    m = SegmentMatcher(net=city, params=MatchParams(),
                       use_native=use_native)
    want = m.match_many(reqs)
    got = m.match_many(as_trace_batch(reqs))
    assert got == want
    # shared-options fast path: same batch with ONE options object
    tb = TraceBatch.from_requests(reqs)
    tb.options = reqs[0]["match_options"]
    assert m.match_many(tb) == want


def test_match_many_mixed_options_split(city, reqs):
    """Per-trace options that change prep params must group correctly
    through the TraceBatch path too (results align per index)."""
    m = SegmentMatcher(net=city, params=MatchParams())
    varied = [dict(r) for r in reqs]
    for j in range(0, len(varied), 2):
        varied[j] = dict(varied[j])
        varied[j]["match_options"] = dict(varied[j]["match_options"],
                                          search_radius=35.0)
    want = m.match_many(varied)
    got = m.match_many(TraceBatch.from_requests(varied))
    assert got == want
