#!/usr/bin/env bash
# End-to-end replay test: synthesise a city + raw probe data, replay it
# through the streaming worker (formatter -> batcher -> in-process TPU
# matcher -> anonymiser), and assert tiles land on disk.
#
# Equivalent of the reference's integration test (tests/circle.sh:26-113),
# with the docker/kafka/S3 scaffolding replaced by the in-process topology:
# same data path, same asserts — >=1 "Writing tile to" log line, log-line
# count == tile-file count, and every logged tile path exists
# (circle.sh:94-113). Runs anywhere python + the package run; no services.
set -euo pipefail
cd "$(dirname "$0")/.."
. tests/env.sh

WORK=$(mktemp -d)
trap 'rm -rf "${WORK}"' EXIT
RESULTS="${WORK}/results"

echo "[e2e] building synthetic city graph"
python -m reporter_tpu graph build-synth --rows 12 --cols 12 \
    --spacing-m 200 --seed 7 --out "${WORK}/city.npz"

echo "[e2e] synthesising raw sv probe data"
python -m reporter_tpu synth --graph "${WORK}/city.npz" --traces 8 \
    --noise-m 4 --seed 3 --format sv > "${WORK}/raw.sv"
N_LINES=$(wc -l < "${WORK}/raw.sv")
[ "${N_LINES}" -gt 0 ] || { echo "[e2e] FAIL: no raw data"; exit 1; }
echo "[e2e] ${N_LINES} raw probe points"

echo "[e2e] replaying through the streaming worker"
# privacy 1 / quantisation 3600 / flush 15 mirror circle.sh's
# `reporter-kafka -p 1 -q 3600 -i 15` invocation (circle.sh:58-66)
python -m reporter_tpu stream -f "${FORMATTER}" --graph "${WORK}/city.npz" \
    -r "${REPORT_LEVELS}" -x "${TRANSITION_LEVELS}" \
    -p 1 -q 3600 -i 15 -s e2e -o "${RESULTS}" \
    --input "${WORK}/raw.sv" 2> "${WORK}/worker.log" || {
  echo "[e2e] FAIL: worker exited nonzero"; cat "${WORK}/worker.log"; exit 1; }

# -- asserts (circle.sh:94-113) -------------------------------------------
WRITES=$(grep -c "Writing tile to" "${WORK}/worker.log" || true)
if [ "${WRITES}" -lt 1 ]; then
  echo "[e2e] FAIL: no tiles were written"; cat "${WORK}/worker.log"; exit 1
fi

FILES=$(find "${RESULTS}" -type f | wc -l)
if [ "${WRITES}" -ne "${FILES}" ]; then
  echo "[e2e] FAIL: ${WRITES} tile writes logged but ${FILES} files found"
  exit 1
fi

# every logged tile path exists: log format is
# "Writing tile to <output>/<time_range>/<level>/<index>/<file> with N segments"
grep "Writing tile to" "${WORK}/worker.log" | \
  sed -e 's/.*Writing tile to //' -e 's/ with.*//' | \
  while read -r TILE_PATH; do
    if [ ! -f "${TILE_PATH}" ]; then
      echo "[e2e] FAIL: logged tile ${TILE_PATH} has no file"; exit 1
    fi
  done

# tile CSVs carry the reference's column layout (Segment.java:55-57)
HEADER=$(find "${RESULTS}" -type f | head -1 | xargs head -1)
case "${HEADER}" in
  segment_id,*) : ;;
  *) echo "[e2e] FAIL: bad tile header: ${HEADER}"; exit 1 ;;
esac

echo "[e2e] PASS: ${WRITES} tiles written and verified"
