"""Native prep v2 (PR 4): batch-sorted candidates, route-pair memo,
threaded worker pool — parity and cache-behavior contracts.

- the batch-sorted candidate kernel must return exactly what
  SpatialGrid.candidates returns, position for position, for scattered
  multi-trace point sets (the sort/scatter must be invisible);
- rt_prepare_batch output is bit-identical across thread counts (the
  pool shards work, never results);
- the cross-call (edge_from, edge_to) route-pair memo hits on repeated
  batches, evicts at its REPORTER_TPU_ROUTE_MEMO bound, disables at 0,
  and never changes a single route value (covered by the parity tests
  in test_native.py / test_native_batch.py running through the same
  route_step).
"""
import numpy as np
import pytest

from reporter_tpu import native
from reporter_tpu.graph import SpatialGrid
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.matcher.batchpad import prepare_batch
from reporter_tpu.synth import build_grid_city, generate_trace

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")

PREP_KEYS = ("edge_ids", "dist_m", "offset_m", "route_m", "gc_m", "case",
             "kept_idx", "num_kept", "dwell", "has_cands", "max_finite")


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=5)


@pytest.fixture(scope="module")
def matcher(city):
    return SegmentMatcher(net=city, params=MatchParams(max_candidates=8))


@pytest.fixture(scope="module")
def traces(city):
    rng = np.random.default_rng(11)
    out = []
    while len(out) < 20:
        tr = generate_trace(city, f"p{len(out)}", rng, noise_m=5.0,
                            min_route_edges=3, max_route_edges=14)
        if tr is not None and len(tr.points) >= 4:
            out.append(tr.points[:60])
    return out


def test_batch_sorted_candidates_match_spatial_grid(city, matcher):
    """Scattered points spanning many grid cells (multiple traces worth,
    shuffled): the sorted sweep + scatter must equal the per-point numpy
    grid query exactly — edges, order within each row, padding."""
    grid = SpatialGrid(city)
    rng = np.random.default_rng(3)
    lat0, lon0 = city.projection_anchor()
    # points across the whole city bbox, plus a far-away dud
    lat = lat0 + rng.uniform(-0.01, 0.01, 400)
    lon = lon0 + rng.uniform(-0.01, 0.01, 400)
    lat[37] += 5.0  # no candidates
    for k in (1, 4, 8):
        c_np = grid.candidates(lat, lon, k=k)
        c_cc = matcher.runtime.candidates(lat, lon, k=k)
        np.testing.assert_array_equal(c_cc.edge_ids, c_np.edge_ids)
        np.testing.assert_allclose(c_cc.dist_m, c_np.dist_m, atol=1e-3)
        np.testing.assert_allclose(c_cc.offset_m, c_np.offset_m, atol=1e-2)


def test_prepare_batch_identical_across_thread_counts(matcher, traces):
    outs = []
    for n_threads in (1, 2, 5):
        b = prepare_batch(matcher.runtime, traces, matcher.params, 64,
                          n_threads=n_threads)
        outs.append(b.prep)
    for k in PREP_KEYS:
        for other in outs[1:]:
            assert np.array_equal(np.asarray(outs[0][k]),
                                  np.asarray(other[k])), k


def test_prep_phase_split_reported(matcher, traces):
    from reporter_tpu.utils import metrics
    metrics.default.reset()
    b = prepare_batch(matcher.runtime, traces, matcher.params, 64,
                      n_threads=2)
    ns = b.prep["phase_ns"]
    assert ns.shape == (3,) and int(ns.sum()) > 0
    counters = metrics.snapshot()["counters"]
    assert counters.get("prep.phase.candidates_ns", 0) > 0
    assert counters.get("prep.phase.routes_ns", 0) > 0


def test_route_memo_hits_across_calls(city):
    """Cross-call reuse through the single-call API, whose per-call
    local memo starts empty every time: call 2 must serve every pair
    from the shared store (hits grow, nothing new learned)."""
    import numpy as np
    from reporter_tpu.core.geo import equirectangular_m
    rng = np.random.default_rng(4)
    from reporter_tpu.synth import generate_trace
    tr = None
    while tr is None:
        tr = generate_trace(city, "memo", rng, noise_m=4.0,
                            min_route_edges=8)
    m = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
    lat = np.array([p["lat"] for p in tr.points])
    lon = np.array([p["lon"] for p in tr.points])
    cands = m.runtime.candidates(lat, lon, k=8)
    gc = np.asarray(equirectangular_m(lat[:-1], lon[:-1], lat[1:],
                                      lon[1:]), dtype=np.float32)
    m.runtime.route_matrices(cands, gc)
    s1 = m.runtime.route_memo_stats()
    assert s1["misses"] > 0 and s1["size"] > 0
    m.runtime.route_matrices(cands, gc)
    s2 = m.runtime.route_memo_stats()
    assert s2["hits"] > s1["hits"]
    assert s2["misses"] == s1["misses"]
    assert s2["size"] == s1["size"]


def test_prep_slot_memo_persists_across_calls(city, traces):
    """prepare_batch worker slots keep their local pair memo between
    calls: an identical single-threaded repeat consults nothing — no new
    shared-memo traffic at all — and produces identical tensors."""
    m = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
    a = prepare_batch(m.runtime, traces, m.params, 64, n_threads=1)
    s1 = m.runtime.route_memo_stats()
    b = prepare_batch(m.runtime, traces, m.params, 64, n_threads=1)
    s2 = m.runtime.route_memo_stats()
    assert s2["misses"] == s1["misses"]
    assert s2["hits"] == s1["hits"]
    for k in PREP_KEYS:
        assert np.array_equal(np.asarray(a.prep[k]),
                              np.asarray(b.prep[k])), k


def test_route_memo_eviction_at_bound(city, traces, monkeypatch):
    monkeypatch.setenv("REPORTER_TPU_ROUTE_MEMO", "64")
    m = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
    prepare_batch(m.runtime, traces, m.params, 64, n_threads=2)
    s = m.runtime.route_memo_stats()
    assert s["evictions"] > 0
    assert s["size"] <= 64  # the configured bound holds
    # values stay exact under eviction pressure: same batch, same tensors
    a = prepare_batch(m.runtime, traces, m.params, 64, n_threads=2)
    m2 = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
    monkeypatch.delenv("REPORTER_TPU_ROUTE_MEMO")
    b = prepare_batch(m2.runtime, traces, m2.params, 64, n_threads=2)
    for k in PREP_KEYS:
        assert np.array_equal(np.asarray(a.prep[k]),
                              np.asarray(b.prep[k])), k


def test_route_memo_disabled_at_zero(city, traces, monkeypatch):
    monkeypatch.setenv("REPORTER_TPU_ROUTE_MEMO", "0")
    m = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
    prepare_batch(m.runtime, traces, m.params, 64, n_threads=2)
    s = m.runtime.route_memo_stats()
    assert s == {"hits": 0, "misses": 0, "size": 0, "evictions": 0}


def test_cache_clear_clears_memo(city, traces):
    m = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
    prepare_batch(m.runtime, traces, m.params, 64, n_threads=2)
    assert m.runtime.route_memo_stats()["size"] > 0
    m.runtime.cache_clear()
    assert m.runtime.route_memo_stats()["size"] == 0
