"""C++ host runtime vs numpy fallback: identical contracts."""
import numpy as np
import pytest

from reporter_tpu import native
from reporter_tpu.core.geo import equirectangular_m
from reporter_tpu.graph import SpatialGrid, candidate_route_matrices
from reporter_tpu.graph.route import RouteCache
from reporter_tpu.graph.spatial import PAD_EDGE
from reporter_tpu.synth import build_grid_city, generate_trace

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=12, cols=12, spacing_m=200.0, seed=8)


@pytest.fixture(scope="module")
def runtime(city):
    return native.NativeRuntime(city)


@pytest.fixture(scope="module")
def trace(city):
    rng = np.random.default_rng(21)
    tr = None
    while tr is None:
        tr = generate_trace(city, "native-test", rng, noise_m=4.0,
                            min_route_edges=8)
    return tr


def test_candidates_match_numpy(city, runtime, trace):
    grid = SpatialGrid(city)
    lat = np.array([p["lat"] for p in trace.points])
    lon = np.array([p["lon"] for p in trace.points])
    c_np = grid.candidates(lat, lon, k=8)
    c_cc = runtime.candidates(lat, lon, k=8)
    np.testing.assert_array_equal(c_cc.edge_ids, c_np.edge_ids)
    np.testing.assert_allclose(c_cc.dist_m, c_np.dist_m, atol=1e-3)
    np.testing.assert_allclose(c_cc.offset_m, c_np.offset_m, atol=1e-2)


def test_route_matrices_match_numpy(city, runtime, trace):
    grid = SpatialGrid(city)
    lat = np.array([p["lat"] for p in trace.points])
    lon = np.array([p["lon"] for p in trace.points])
    cands = grid.candidates(lat, lon, k=8)
    gc = np.asarray(equirectangular_m(lat[:-1], lon[:-1], lat[1:], lon[1:]),
                    dtype=np.float32)
    m_np = candidate_route_matrices(city, cands, gc, cache=RouteCache(city))
    m_cc = runtime.route_matrices(cands, gc)
    # unreachable entries agree exactly; reachable within float tolerance
    np.testing.assert_array_equal(m_cc >= 0.5e9, m_np >= 0.5e9)
    reachable = m_np < 0.5e9
    np.testing.assert_allclose(m_cc[reachable], m_np[reachable], atol=0.5)
    # with the backward tolerance the two backends still agree
    m_np = candidate_route_matrices(city, cands, gc, cache=RouteCache(city),
                                    backward_tolerance_m=25.0)
    m_cc = runtime.route_matrices(cands, gc, backward_tolerance_m=25.0)
    np.testing.assert_array_equal(m_cc >= 0.5e9, m_np >= 0.5e9)
    reachable = m_np < 0.5e9
    np.testing.assert_allclose(m_cc[reachable], m_np[reachable], atol=0.5)


def test_cache_grows_and_clears(city, runtime, trace):
    runtime.cache_clear()
    assert runtime.cache_size() == 0
    grid = SpatialGrid(city)
    lat = np.array([p["lat"] for p in trace.points])
    lon = np.array([p["lon"] for p in trace.points])
    cands = runtime.candidates(lat, lon, k=8)
    gc = np.asarray(equirectangular_m(lat[:-1], lon[:-1], lat[1:], lon[1:]),
                    dtype=np.float32)
    runtime.route_matrices(cands, gc)
    assert runtime.cache_size() > 0
    runtime.cache_clear()
    assert runtime.cache_size() == 0


def test_matcher_uses_native_and_matches_fallback(city, trace):
    from reporter_tpu.matcher import SegmentMatcher
    m_native = SegmentMatcher(net=city, use_native=True)
    m_py = SegmentMatcher(net=city, use_native=False)
    assert m_native.runtime is not None and m_py.runtime is None
    req = trace.request_json(report_levels=(0, 1, 2),
                             transition_levels=(0, 1, 2))
    out_native = m_native.match_many([req])[0]
    out_py = m_py.match_many([req])[0]
    ids_native = [s.get("segment_id") for s in out_native["segments"]]
    ids_py = [s.get("segment_id") for s in out_py["segments"]]
    assert ids_native == ids_py


def test_no_candidates_far_away(city, runtime):
    cands = runtime.candidates(np.array([15.9]), np.array([120.98]), k=4)
    assert (cands.edge_ids == PAD_EDGE).all()
