"""Tiled graph storage: RGT1 format, partition/merge roundtrip, bbox
loading, C++/numpy parser parity."""
import os

import numpy as np
import pytest

from reporter_tpu.core.osmlr import tile_level
from reporter_tpu.graph.tilestore import (
    GraphTileStore,
    edge_tile_assignment,
    merge_tiles,
    tile_from_bytes_np,
    write_tiles,
)
from reporter_tpu.synth import build_grid_city


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=10, cols=10, spacing_m=400.0, seed=11)


def edge_key_set(net):
    """Geometry-keyed multiset of edges, invariant to node/edge reindexing."""
    keys = []
    for e in range(net.num_edges):
        a, b = int(net.edge_start[e]), int(net.edge_end[e])
        keys.append((
            round(float(net.node_lat[a]), 9), round(float(net.node_lon[a]), 9),
            round(float(net.node_lat[b]), 9), round(float(net.node_lon[b]), 9),
            round(float(net.edge_length_m[e]), 3),
            int(net.edge_segment_id[e]),
            round(float(net.edge_segment_offset_m[e]), 3),
            bool(net.edge_internal[e]),
        ))
    return sorted(keys)


class TestAssignment:
    def test_levels_follow_osmlr_ids(self, city):
        levels, tiles = edge_tile_assignment(city)
        assoc = city.edge_segment_id >= 0
        for e in np.flatnonzero(assoc)[:50]:
            assert levels[e] == tile_level(int(city.edge_segment_id[e]))
        assert (levels[~assoc] == 2).all()
        assert (tiles >= 0).all()


class TestRoundtrip:
    def test_write_then_load_all_preserves_graph(self, city, tmp_path):
        written = write_tiles(city, str(tmp_path))
        assert len(written) >= 2  # multiple levels at least
        for rel in written:
            assert os.path.exists(tmp_path / rel)
            assert rel.endswith(".rgt")
        store = GraphTileStore(str(tmp_path))
        assert store.tile_paths() == sorted(written)
        merged = store.load_all()
        assert merged.num_edges == city.num_edges
        assert edge_key_set(merged) == edge_key_set(city)
        assert merged.segment_length_m == city.segment_length_m

    def test_matcher_equivalent_on_merged_graph(self, city, tmp_path):
        # end-to-end: a trace matched on the re-composed graph produces the
        # same segment sequence as on the original
        from reporter_tpu.matcher import SegmentMatcher

        write_tiles(city, str(tmp_path))
        merged = GraphTileStore(str(tmp_path)).load_all()

        rng = np.random.default_rng(5)
        from reporter_tpu.synth import generate_trace
        tr = None
        while tr is None:
            tr = generate_trace(city, "veh", rng, noise_m=3.0)
        (m1,) = SegmentMatcher(net=city).match_many([{"trace": tr.points}])
        (m2,) = SegmentMatcher(net=merged).match_many([{"trace": tr.points}])
        segs1 = [s["segment_id"] for s in m1["segments"]]
        segs2 = [s["segment_id"] for s in m2["segments"]]
        assert segs1 == segs2 and len(segs1) > 0


class TestBboxLoad:
    def test_bbox_scoped_subset(self, city, tmp_path):
        write_tiles(city, str(tmp_path))
        store = GraphTileStore(str(tmp_path))
        lat_mid = float(np.median(city.node_lat))
        lon_mid = float(np.median(city.node_lon))
        sub = store.load_bbox([lon_mid - 0.002, lat_mid - 0.002,
                               lon_mid + 0.002, lat_mid + 0.002])
        assert 0 < sub.num_edges <= city.num_edges

    def test_bbox_missing_raises(self, city, tmp_path):
        write_tiles(city, str(tmp_path))
        store = GraphTileStore(str(tmp_path))
        with pytest.raises(FileNotFoundError):
            store.load_bbox([10.0, 10.0, 10.1, 10.1])


class TestParserParity:
    def test_numpy_and_cpp_parsers_agree(self, city, tmp_path):
        from reporter_tpu import native

        written = write_tiles(city, str(tmp_path))
        raw = open(tmp_path / written[0], "rb").read()
        via_np = tile_from_bytes_np(raw)
        if not native.available():
            pytest.skip("native runtime not built")
        via_cpp = native.parse_tile(raw)
        assert via_cpp is not None
        assert set(via_cpp) == set(via_np)
        for k in via_np:
            np.testing.assert_array_equal(via_cpp[k], via_np[k], err_msg=k)

    def test_malformed_rejected(self):
        with pytest.raises(ValueError, match="bad magic"):
            tile_from_bytes_np(b"JUNK" + b"\0" * 32)
        from reporter_tpu import native
        if native.available():
            assert native.parse_tile(b"JUNK" + b"\0" * 32) is None

    def test_truncation_rejected(self, city, tmp_path):
        written = write_tiles(city, str(tmp_path))
        raw = open(tmp_path / written[0], "rb").read()
        with pytest.raises(ValueError):
            tile_from_bytes_np(raw[:-4])
        from reporter_tpu import native
        if native.available():
            assert native.parse_tile(raw[:-4]) is None


class TestGraphCli:
    def test_tile_untile_info(self, tmp_path, capsys):
        from reporter_tpu.__main__ import main

        npz = str(tmp_path / "g.npz")
        assert main(["graph", "build-synth", "--rows", "6", "--cols", "6",
                     "--out", npz]) == 0
        tile_dir = str(tmp_path / "tiles")
        assert main(["graph", "tile", "--graph", npz,
                     "--out-dir", tile_dir]) == 0
        out2 = str(tmp_path / "g2.npz")
        assert main(["graph", "untile", "--tile-dir", tile_dir,
                     "--out", out2]) == 0
        assert main(["graph", "info", tile_dir]) == 0
        info = capsys.readouterr().out
        assert "nodes" in info

        from reporter_tpu.graph.network import RoadNetwork
        a, b = RoadNetwork.load(npz), RoadNetwork.load(out2)
        assert a.num_edges == b.num_edges
        assert edge_key_set(a) == edge_key_set(b)
