"""KafkaBroker + multi-worker composition, against an in-process fake.

kafka-python is not installed in this image (the reference's integration
test runs a real 4-partition topology, tests/circle.sh:26-77); these
tests install a minimal fake ``kafka`` module to pin:

- producer keying/serialization and consumer decode through KafkaBroker;
- per-partition ordering under uuid keying (the reference's requirement
  for per-uuid point order, circle.sh:58);
- the uuid-filter x consumer-group composition (round-1..3 bug): under a
  group each worker must process its whole partition share — every uuid
  exactly once ACROSS workers, no sha1 second filter dropping messages.
"""
import sys
import types

import pytest

from reporter_tpu.streaming import broker as broker_mod


class _FakeCluster:
    """Shared topic -> partitions -> messages store with group assignment."""

    def __init__(self, n_partitions=4):
        self.n_partitions = n_partitions
        self.topics = {}

    def partitions(self, topic):
        return self.topics.setdefault(
            topic, [[] for _ in range(self.n_partitions)])

    def publish(self, topic, key: bytes, value: bytes):
        part = (hash(key) if key else 0) % self.n_partitions
        self.partitions(topic)[part].append((key, value))


class _Msg:
    __slots__ = ("key", "value")

    def __init__(self, key, value):
        self.key = key
        self.value = value


def _install_fake_kafka(monkeypatch, cluster):
    groups = {}  # (group, topic) -> next member index

    class FakeProducer:
        def __init__(self, bootstrap_servers=None, key_serializer=None,
                     value_serializer=None):
            self.key_serializer = key_serializer or (lambda k: k)
            self.value_serializer = value_serializer or (lambda v: v)

        def send(self, topic, key=None, value=None):
            cluster.publish(topic, self.key_serializer(key),
                            self.value_serializer(value))

    class FakeConsumer:
        """Static round-robin partition assignment per (group, topic):
        member M of N gets partitions p where p % N == M. N is fixed at
        2 for the tests (set via cluster.group_size)."""

        def __init__(self, topic, bootstrap_servers=None, group_id=None):
            n_members = getattr(cluster, "group_size", 1)
            member = groups.setdefault((group_id, topic), 0)
            groups[(group_id, topic)] = member + 1
            parts = cluster.partitions(topic)
            self._msgs = []
            for p in range(len(parts)):
                if p % n_members == member % n_members:
                    self._msgs.extend(_Msg(k, v) for k, v in parts[p])

        def __iter__(self):
            return iter(self._msgs)

    fake = types.ModuleType("kafka")
    fake.KafkaProducer = FakeProducer
    fake.KafkaConsumer = FakeConsumer
    monkeypatch.setitem(sys.modules, "kafka", fake)
    return fake


def test_broker_produce_consume_roundtrip(monkeypatch):
    cluster = _FakeCluster()
    _install_fake_kafka(monkeypatch, cluster)
    b = broker_mod.KafkaBroker("fake:9092")
    b.produce("raw", "veh-1", b"hello")
    b.produce("raw", "veh-1", b"world")
    got = list(b.consume("raw"))
    assert got == [("veh-1", b"hello"), ("veh-1", b"world")]


def test_broker_preserves_per_uuid_order_across_partitions(monkeypatch):
    cluster = _FakeCluster(n_partitions=4)
    _install_fake_kafka(monkeypatch, cluster)
    b = broker_mod.KafkaBroker("fake:9092")
    uuids = [f"veh-{i}" for i in range(8)]
    for seq in range(5):
        for u in uuids:
            b.produce("raw", u, f"{u}:{seq}".encode())
    # same key -> same partition, so per-uuid sequence order survives
    seen = {}
    for key, value in b.consume("raw"):
        seq = int(value.decode().split(":")[1])
        assert seq == seen.get(key, -1) + 1, f"{key} out of order"
        seen[key] = seq
    assert set(seen) == set(uuids) and all(v == 4 for v in seen.values())


def test_group_partitioning_with_auto_filter_covers_every_uuid(monkeypatch):
    """Two group members + the worker's auto uuid-filter decision: every
    uuid processed exactly once ACROSS workers (the sha1 filter must stay
    OFF under a consumer group, else ~half of each member's share drops).
    """
    from reporter_tpu.streaming.worker import resolve_uuid_filter

    # multihost envs set, as a 2-process deployment would have them
    monkeypatch.setenv("REPORTER_TPU_NUM_PROCESSES", "2")
    monkeypatch.setenv("REPORTER_TPU_PROCESS_ID", "0")

    cluster = _FakeCluster(n_partitions=4)
    cluster.group_size = 2
    _install_fake_kafka(monkeypatch, cluster)

    uuids = [f"veh-{i}" for i in range(40)]
    b = broker_mod.KafkaBroker("fake:9092")
    for u in uuids:
        b.produce("raw", u, u.encode())

    processed = []
    for member in range(2):
        monkeypatch.setenv("REPORTER_TPU_PROCESS_ID", str(member))
        uuid_filter = resolve_uuid_filter("auto", bootstrap="fake:9092")
        assert uuid_filter is None  # the composition fix
        consumer_b = broker_mod.KafkaBroker("fake:9092")
        for key, value in consumer_b.consume("raw"):
            if uuid_filter is None or uuid_filter(key):
                processed.append(key)
    assert sorted(processed) == sorted(uuids)  # exactly once, none lost


def test_forced_on_filter_under_group_drops_share(monkeypatch):
    """Documents WHY auto turns the filter off: forcing it on under a
    group loses messages (kept as a guard that the auto default matters)."""
    from reporter_tpu.streaming.worker import resolve_uuid_filter

    monkeypatch.setenv("REPORTER_TPU_NUM_PROCESSES", "2")
    monkeypatch.setenv("REPORTER_TPU_PROCESS_ID", "0")
    cluster = _FakeCluster(n_partitions=4)
    cluster.group_size = 2
    _install_fake_kafka(monkeypatch, cluster)

    uuids = [f"veh-{i}" for i in range(40)]
    b = broker_mod.KafkaBroker("fake:9092")
    for u in uuids:
        b.produce("raw", u, u.encode())

    processed = []
    for member in range(2):
        monkeypatch.setenv("REPORTER_TPU_PROCESS_ID", str(member))
        uuid_filter = resolve_uuid_filter("on", bootstrap="fake:9092")
        assert uuid_filter is not None
        consumer_b = broker_mod.KafkaBroker("fake:9092")
        for key, value in consumer_b.consume("raw"):
            if uuid_filter(key):
                processed.append(key)
    # group split x sha1 split: roughly half the stream is lost
    assert len(processed) < len(uuids)


def test_kafka_unavailable_raises_cleanly(monkeypatch):
    monkeypatch.setitem(sys.modules, "kafka", None)
    with pytest.raises(RuntimeError, match="kafka-python is not installed"):
        broker_mod.KafkaBroker("fake:9092")
