"""Associative-scan Viterbi equivalence + sharded execution on the virtual
8-device CPU mesh."""
import jax
import numpy as np
import pytest

from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.matcher.batchpad import pack_batches, prepare_trace
from reporter_tpu.matcher.hmm import viterbi_decode_batch
from reporter_tpu.ops import viterbi_assoc_batch
from reporter_tpu.parallel import make_mesh, sharded_viterbi
from reporter_tpu.synth import build_grid_city, generate_trace


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=6,
                           service_road_fraction=0.0, internal_fraction=0.0)


@pytest.fixture(scope="module")
def batch(city):
    m = SegmentMatcher(net=city)
    prepared = []
    rng = np.random.default_rng(0)
    for i in range(8):
        tr = None
        while tr is None:
            tr = generate_trace(city, f"v{i}", rng, noise_m=4.0,
                                min_route_edges=8, max_route_edges=14)
        prepared.append(prepare_trace(city, m.grid, tr.points, MatchParams(),
                                      m.route_cache))
    batches = pack_batches(prepared)
    assert len(batches) == 1
    return batches[0]


def path_score_f64(batch, b, path):
    """Re-score a decoded path in float64 numpy (independent of either
    implementation's accumulation order)."""
    from reporter_tpu.matcher.hmm import NORMAL, RESTART, SKIP
    trace = batch.traces[b]
    n = trace.num_kept
    sigma, beta = 4.07, 3.0
    total = 0.0
    for t in range(n):
        k = int(path[t])
        d = float(batch.dist_m[b, t, k])
        total += -0.5 * (d / sigma) ** 2
        if t > 0 and batch.case[b, t] == NORMAL:
            r = float(batch.route_m[b, t - 1, int(path[t - 1]), k])
            assert r < 0.5e9, "decoded through an unroutable transition"
            total += -abs(r - float(batch.gc_m[b, t - 1])) / beta
    return total


def test_assoc_matches_sequential(batch):
    sigma, beta = np.float32(4.07), np.float32(3.0)
    p_seq, _ = viterbi_decode_batch(
        batch.dist_m, batch.valid, batch.route_m, batch.gc_m, batch.case,
        sigma, beta)
    p_assoc, _ = viterbi_assoc_batch(
        batch.dist_m, batch.valid, batch.route_m, batch.gc_m, batch.case,
        sigma, beta)
    # the two decodes may break exact score ties differently (f32 summation
    # order differs); equivalence means equal path *quality*
    for b, trace in enumerate(batch.traces):
        s1 = path_score_f64(batch, b, np.asarray(p_seq)[b])
        s2 = path_score_f64(batch, b, np.asarray(p_assoc)[b])
        assert s2 == pytest.approx(s1, abs=1e-2), f"trace {b}"


def test_restart_semantics_equivalent():
    # hand-built case with a restart in the middle and a skip tail
    from reporter_tpu.matcher.hmm import NORMAL, RESTART, SKIP
    B, T, K = 1, 6, 3
    rng = np.random.default_rng(3)
    dist = rng.uniform(0, 30, (B, T, K)).astype(np.float32)
    valid = np.ones((B, T, K), bool)
    gc = rng.uniform(5, 40, (B, T - 1)).astype(np.float32)
    route = rng.uniform(5, 80, (B, T - 1, K, K)).astype(np.float32)
    case = np.array([[RESTART, NORMAL, NORMAL, RESTART, NORMAL, SKIP]],
                    np.int32)
    sigma, beta = np.float32(4.07), np.float32(3.0)
    p_seq, _ = viterbi_decode_batch(dist, valid, route, gc, case, sigma, beta)
    p_assoc, _ = viterbi_assoc_batch(dist, valid, route, gc, case, sigma, beta)
    np.testing.assert_array_equal(np.asarray(p_seq)[:, :5],
                                  np.asarray(p_assoc)[:, :5])


class TestMesh:
    def test_make_mesh_shapes(self):
        mesh = make_mesh()
        assert mesh.devices.shape == (8, 1)
        mesh2 = make_mesh((4, 2))
        assert mesh2.axis_names == ("data", "seq")
        with pytest.raises(ValueError):
            make_mesh((3, 2))

    def test_sharded_viterbi_matches_single_device(self, batch):
        sigma, beta = np.float32(4.07), np.float32(3.0)
        p_ref, _ = viterbi_decode_batch(
            batch.dist_m, batch.valid, batch.route_m, batch.gc_m,
            batch.case, sigma, beta)
        mesh = make_mesh((4, 2))
        run = sharded_viterbi(mesh)
        p_sh, _ = run(batch.dist_m, batch.valid, batch.route_m, batch.gc_m,
                      batch.case, sigma, beta)
        for b in range(len(batch.traces)):
            s_ref = path_score_f64(batch, b, np.asarray(p_ref)[b])
            s_sh = path_score_f64(batch, b, np.asarray(p_sh)[b])
            assert s_sh == pytest.approx(s_ref, abs=1e-2), f"trace {b}"

    def test_sharded_uses_all_devices(self, batch):
        mesh = make_mesh((8, 1))
        run = sharded_viterbi(mesh)
        p, _ = run(batch.dist_m, batch.valid, batch.route_m, batch.gc_m,
                   batch.case, np.float32(4.07), np.float32(3.0))
        assert len(p.sharding.device_set) == 8
