"""Associative-scan Viterbi equivalence + sharded execution on the virtual
8-device CPU mesh."""
import jax
import numpy as np
import pytest

from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.matcher.batchpad import pack_batches, prepare_trace
from reporter_tpu.matcher.hmm import viterbi_decode_batch
from reporter_tpu.ops import viterbi_assoc_batch
from reporter_tpu.parallel import make_mesh, sharded_viterbi
from reporter_tpu.synth import build_grid_city, generate_trace


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=6,
                           service_road_fraction=0.0, internal_fraction=0.0)


@pytest.fixture(scope="module")
def batch(city):
    m = SegmentMatcher(net=city)
    prepared = []
    rng = np.random.default_rng(0)
    for i in range(8):
        tr = None
        while tr is None:
            tr = generate_trace(city, f"v{i}", rng, noise_m=4.0,
                                min_route_edges=8, max_route_edges=14)
        prepared.append(prepare_trace(city, m.grid, tr.points, MatchParams(),
                                      m.route_cache))
    batches = pack_batches(prepared)
    assert len(batches) == 1
    return batches[0]


def path_score_f64(batch, b, path):
    """Re-score a decoded path in float64 numpy (independent of either
    implementation's accumulation order)."""
    from reporter_tpu.matcher.hmm import NORMAL, RESTART, SKIP
    trace = batch.traces[b]
    n = trace.num_kept
    sigma, beta = 4.07, 3.0
    total = 0.0
    for t in range(n):
        k = int(path[t])
        d = float(batch.dist_m[b, t, k])
        total += -0.5 * (d / sigma) ** 2
        if t > 0 and batch.case[b, t] == NORMAL:
            r = float(batch.route_m[b, t - 1, int(path[t - 1]), k])
            assert r < 0.5e9, "decoded through an unroutable transition"
            total += -abs(r - float(batch.gc_m[b, t - 1])) / beta
    return total


def test_assoc_matches_sequential(batch):
    sigma, beta = np.float32(4.07), np.float32(3.0)
    p_seq, _ = viterbi_decode_batch(
        batch.dist_m, batch.valid, batch.route_m, batch.gc_m, batch.case,
        sigma, beta)
    p_assoc, _ = viterbi_assoc_batch(
        batch.dist_m, batch.valid, batch.route_m, batch.gc_m, batch.case,
        sigma, beta)
    # the two decodes may break exact score ties differently (f32 summation
    # order differs); equivalence means equal path *quality*
    for b, trace in enumerate(batch.traces):
        s1 = path_score_f64(batch, b, np.asarray(p_seq)[b])
        s2 = path_score_f64(batch, b, np.asarray(p_assoc)[b])
        assert s2 == pytest.approx(s1, abs=1e-2), f"trace {b}"


def test_numpy_oracle_matches_device_decodes(batch):
    """cpu_ref.viterbi_decode_numpy (the bench baseline / oracle) agrees
    with the device decode on real prepared traces."""
    from reporter_tpu.matcher.cpu_ref import viterbi_decode_numpy
    sigma, beta = np.float32(4.07), np.float32(3.0)
    p_dev, _ = viterbi_decode_batch(
        batch.dist_m, batch.valid, batch.route_m, batch.gc_m, batch.case,
        sigma, beta)
    for b, trace in enumerate(batch.traces):
        p_np, _ = viterbi_decode_numpy(
            batch.dist_m[b], batch.valid[b], batch.route_m[b],
            batch.gc_m[b], batch.case[b], sigma, beta)
        s_dev = path_score_f64(batch, b, np.asarray(p_dev)[b])
        s_np = path_score_f64(batch, b, p_np)
        assert s_np == pytest.approx(s_dev, abs=1e-2), f"trace {b}"


def test_restart_semantics_equivalent():
    # hand-built case with a restart in the middle and a skip tail
    from reporter_tpu.matcher.hmm import NORMAL, RESTART, SKIP
    B, T, K = 1, 6, 3
    rng = np.random.default_rng(3)
    dist = rng.uniform(0, 30, (B, T, K)).astype(np.float32)
    valid = np.ones((B, T, K), bool)
    gc = rng.uniform(5, 40, (B, T - 1)).astype(np.float32)
    route = rng.uniform(5, 80, (B, T - 1, K, K)).astype(np.float32)
    case = np.array([[RESTART, NORMAL, NORMAL, RESTART, NORMAL, SKIP]],
                    np.int32)
    sigma, beta = np.float32(4.07), np.float32(3.0)
    p_seq, _ = viterbi_decode_batch(dist, valid, route, gc, case, sigma, beta)
    p_assoc, _ = viterbi_assoc_batch(dist, valid, route, gc, case, sigma, beta)
    np.testing.assert_array_equal(np.asarray(p_seq)[:, :5],
                                  np.asarray(p_assoc)[:, :5])


class TestMesh:
    def test_make_mesh_shapes(self):
        mesh = make_mesh()
        assert mesh.devices.shape == (8, 1)
        mesh2 = make_mesh((4, 2))
        assert mesh2.axis_names == ("data", "seq")
        with pytest.raises(ValueError):
            make_mesh((3, 2))

    def test_sharded_viterbi_matches_single_device(self, batch):
        sigma, beta = np.float32(4.07), np.float32(3.0)
        p_ref, _ = viterbi_decode_batch(
            batch.dist_m, batch.valid, batch.route_m, batch.gc_m,
            batch.case, sigma, beta)
        mesh = make_mesh((4, 2))
        run = sharded_viterbi(mesh)
        p_sh, _ = run(batch.dist_m, batch.valid, batch.route_m, batch.gc_m,
                      batch.case, sigma, beta)
        for b in range(len(batch.traces)):
            s_ref = path_score_f64(batch, b, np.asarray(p_ref)[b])
            s_sh = path_score_f64(batch, b, np.asarray(p_sh)[b])
            assert s_sh == pytest.approx(s_ref, abs=1e-2), f"trace {b}"

    def test_route_tensor_shards_along_seq(self, batch):
        """The dominant (B, T-1, K, K) tensor must shard on the seq axis
        (round-3 weakness: it replicated along seq, so per-device memory
        and h2d never dropped with sequence parallelism)."""
        from reporter_tpu.parallel.sharded import shard_batch
        mesh = make_mesh((4, 2))
        dist, valid, route, gc, case = shard_batch(
            mesh, batch.dist_m, batch.valid, batch.route_m, batch.gc_m,
            batch.case)
        spec = route.sharding.spec
        assert tuple(spec) == ("data", "seq", None, None), spec
        assert tuple(gc.sharding.spec) == ("data", "seq")
        # padded T-1 -> T, then split 4 x 2: per-device bytes are exactly
        # total/8 — sequence parallelism halves what data-parallel alone
        # would place per device
        shards = route.addressable_shards
        assert len(shards) == 8
        per_dev = shards[0].data.nbytes
        assert per_dev * 8 == route.nbytes
        B, T = batch.dist_m.shape[0], batch.dist_m.shape[1]
        K = batch.dist_m.shape[2]
        assert route.shape == (B, T, K, K)  # dead step pads T-1 ragged

    def test_sharded_uses_all_devices(self, batch):
        mesh = make_mesh((8, 1))
        run = sharded_viterbi(mesh)
        p, _ = run(batch.dist_m, batch.valid, batch.route_m, batch.gc_m,
                   batch.case, np.float32(4.07), np.float32(3.0))
        assert len(p.sharding.device_set) == 8


class TestProductionShardedPath:
    """decode_batch/match_many route through the process-default mesh when
    more than one device is visible (VERDICT round 1, missing #3)."""

    @pytest.fixture(autouse=True)
    def fresh_mesh_cache(self):
        from reporter_tpu import ops
        ops.reset_sharded_cache()
        yield
        ops.reset_sharded_cache()

    def test_batch_pad_multiple_is_data_axis(self):
        from reporter_tpu import ops
        assert ops.batch_pad_multiple() == 8

    def test_disabled_by_env(self, monkeypatch):
        from reporter_tpu import ops
        monkeypatch.setenv("REPORTER_TPU_SHARD", "0")
        assert ops.batch_pad_multiple() is None

    def test_decode_batch_shards_across_all_devices(self, batch):
        from reporter_tpu import ops
        sigma, beta = np.float32(4.07), np.float32(3.0)
        p, _ = ops.decode_batch(batch.dist_m, batch.valid, batch.route_m,
                                batch.gc_m, batch.case, sigma, beta)
        assert len(p.sharding.device_set) == 8
        # same path quality as the unsharded reference decode
        p_ref, _ = viterbi_decode_batch(
            batch.dist_m, batch.valid, batch.route_m, batch.gc_m,
            batch.case, sigma, beta)
        for b in range(len(batch.traces)):
            s_ref = path_score_f64(batch, b, np.asarray(p_ref)[b])
            s_sh = path_score_f64(batch, b, np.asarray(p)[b])
            assert s_sh == pytest.approx(s_ref, abs=1e-2), f"trace {b}"

    def test_indivisible_batch_falls_through(self, batch):
        from reporter_tpu import ops
        sigma, beta = np.float32(4.07), np.float32(3.0)
        p, _ = ops.decode_batch(batch.dist_m[:3], batch.valid[:3],
                                batch.route_m[:3], batch.gc_m[:3],
                                batch.case[:3], sigma, beta)
        assert p.shape[0] == 3  # decoded fine, just single-device

    def test_match_many_same_results_with_and_without_mesh(
            self, city, monkeypatch):
        m = SegmentMatcher(net=city)
        rng = np.random.default_rng(11)
        reqs = []
        while len(reqs) < 3:
            tr = generate_trace(city, f"mm-{len(reqs)}", rng, noise_m=4.0,
                                min_route_edges=6, max_route_edges=10)
            if tr is not None:
                reqs.append({"uuid": tr.uuid, "trace": tr.points,
                             "match_options": {}})
        from reporter_tpu import ops
        res_sharded = m.match_many(reqs)
        ops._sharded_cache = None
        monkeypatch.setenv("REPORTER_TPU_SHARD", "0")
        res_single = m.match_many(reqs)
        assert res_sharded == res_single
        assert any(r.get("segments") for r in res_sharded)

    def test_service_decodes_on_mesh(self, city):
        """The HTTP service's dispatcher path lands its decode on all 8
        devices (the round-1 verdict's done-condition for this item)."""
        from reporter_tpu import ops
        from reporter_tpu.service.server import ReporterService

        observed = []
        real = ops.decode_batch

        def spy(*args, **kw):
            out = real(*args, **kw)
            observed.append(out[0].sharding.device_set)
            return out

        matcher = SegmentMatcher(net=city)
        service = ReporterService(matcher, max_wait_ms=1.0)
        rng = np.random.default_rng(21)
        tr = None
        while tr is None:
            tr = generate_trace(city, "svc-1", rng, noise_m=4.0,
                                min_route_edges=6, max_route_edges=10)
        trace = {"uuid": tr.uuid, "trace": tr.points,
                 "match_options": {"mode": "auto",
                                   "report_levels": [0, 1, 2],
                                   "transition_levels": [0, 1, 2]}}
        # match_many imports decode_batch from ops at call time, so
        # patching the ops attribute intercepts the service's decode
        try:
            import unittest.mock as mock
            with mock.patch.object(ops, "decode_batch", side_effect=spy):
                status, body = service.handle(trace)
        finally:
            service.dispatcher.close()
        assert status == 200
        assert observed and all(len(s) == 8 for s in observed)


class TestMultihost:
    """parallel/multihost.py: bootstrap no-op path + uuid partitioning."""

    def test_single_host_is_noop(self, monkeypatch):
        from reporter_tpu.parallel import init_multihost
        from reporter_tpu.parallel import multihost
        for var in (multihost.ENV_COORDINATOR, multihost.ENV_NUM_PROCESSES,
                    multihost.ENV_PROCESS_ID):
            monkeypatch.delenv(var, raising=False)
        assert init_multihost() is False

    def test_partition_disjoint_and_complete(self):
        from reporter_tpu.parallel import partition_for_host
        uuids = [f"veh-{i}" for i in range(200)]
        parts = [partition_for_host(uuids, 4, p) for p in range(4)]
        all_idx = sorted(i for part in parts for i in part)
        assert all_idx == list(range(200))
        seen = set()
        for part in parts:
            assert not (seen & set(part))
            seen |= set(part)

    def test_same_uuid_same_host(self):
        from reporter_tpu.parallel import partition_for_host
        uuids = ["a", "b", "a", "c", "a", "b"]
        parts = {p: set(partition_for_host(uuids, 3, p)) for p in range(3)}
        for p, idxs in parts.items():
            owned = {uuids[i] for i in idxs}
            for q, other in parts.items():
                if q != p:
                    assert not (owned & {uuids[i] for i in other})

    def test_partition_stable(self):
        # pinned digest: catches a regression to seed-randomised builtin
        # hash(), which would silently migrate uuids between hosts
        from reporter_tpu.parallel.multihost import host_hash
        assert host_hash("veh-42") == 12078884699722865484

    def test_bad_process_id_raises(self):
        from reporter_tpu.parallel import partition_for_host
        import pytest as _pytest
        with _pytest.raises(ValueError):
            partition_for_host(["a"], 2, 2)

    def test_host_uuid_filter_env(self, monkeypatch):
        from reporter_tpu.parallel import host_uuid_filter
        from reporter_tpu.parallel.multihost import (
            ENV_NUM_PROCESSES, ENV_PROCESS_ID, owned_by_host)
        monkeypatch.delenv(ENV_NUM_PROCESSES, raising=False)
        monkeypatch.delenv(ENV_PROCESS_ID, raising=False)
        assert host_uuid_filter() is None          # single host
        monkeypatch.setenv(ENV_NUM_PROCESSES, "3")
        monkeypatch.setenv(ENV_PROCESS_ID, "1")
        f = host_uuid_filter()
        uuids = [f"veh-{i}" for i in range(50)]
        assert [u for u in uuids if f(u)] == \
            [u for u in uuids if owned_by_host(u, 3, 1)]
        monkeypatch.setenv(ENV_PROCESS_ID, "7")    # out of range
        import pytest as _pytest
        with _pytest.raises(ValueError):
            host_uuid_filter()

    def test_workers_partition_shared_stream(self):
        """Two workers over the same raw stream process disjoint uuids and
        together cover all of them exactly once."""
        from reporter_tpu.parallel.multihost import owned_by_host
        from reporter_tpu.streaming.anonymiser import Anonymiser
        from reporter_tpu.streaming.formatter import Formatter
        from reporter_tpu.streaming.worker import StreamWorker

        seen = [set(), set()]

        def make(pid):
            def submit(trace):
                seen[pid].add(trace["uuid"])
                return {"datastore": {"mode": "auto", "reports": []},
                        "shape_used": len(trace["trace"]), "stats": {}}
            sink = type("S", (), {"store": lambda self, *a, **k: None})()
            return StreamWorker(
                Formatter.from_config(';sv;,;0;2;3;1;4'), submit,
                Anonymiser(sink, 2, 3600),
                flush_interval_s=1e9,
                uuid_filter=lambda u, pid=pid: owned_by_host(u, 2, pid))

        lines = []
        for i in range(12):
            for j in range(12):  # enough points to trigger reports
                lines.append(f"veh-{i},{1500000000 + j * 10},"
                             f"{14.58 + j * 1e-3},121.0,10")
        w0, w1 = make(0), make(1)
        for ln in lines:
            w0.offer(ln)
            w1.offer(ln)
        w0.drain(); w1.drain()
        assert seen[0] and seen[1]
        assert not (seen[0] & seen[1])
        assert seen[0] | seen[1] == {f"veh-{i}" for i in range(12)}
        assert w0.skipped_other_host and w1.skipped_other_host
