"""Pallas fused Viterbi vs the scan and associative decoders (interpret
mode on CPU: same kernel code path as TPU, same numerics)."""
import numpy as np
import pytest

from reporter_tpu.matcher.hmm import (
    NORMAL,
    RESTART,
    SKIP,
    viterbi_decode_batch,
)
from reporter_tpu.ops import (
    decode_backend,
    decode_batch,
    viterbi_assoc_batch,
    viterbi_pallas_batch,
    vmem_bytes_estimate,
    VMEM_BUDGET_BYTES,
)


def random_inputs(B, T, K, seed, with_restarts=True, with_skips=True):
    rng = np.random.default_rng(seed)
    dist = rng.uniform(0.0, 40.0, (B, T, K)).astype(np.float32)
    valid = rng.random((B, T, K)) > 0.1
    valid[:, :, 0] = True  # at least one candidate everywhere
    gc = rng.uniform(5.0, 40.0, (B, T - 1)).astype(np.float32)
    route = (gc[..., None, None]
             + rng.exponential(15.0, (B, T - 1, K, K))).astype(np.float32)
    # sprinkle unreachable routes
    route[rng.random(route.shape) < 0.05] = 1.0e9
    case = np.full((B, T), NORMAL, dtype=np.int32)
    case[:, 0] = RESTART
    if with_restarts:
        for b in range(B):
            for t in rng.integers(2, T - 1, size=2):
                case[b, t] = RESTART
    if with_skips:
        for b in range(B):
            n_skip = int(rng.integers(0, T // 4))
            if n_skip:
                case[b, T - n_skip:] = SKIP
    return (dist, valid, route, gc, case,
            np.float32(4.07), np.float32(3.0))


class TestParity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize("shape", [(4, 16, 4), (3, 33, 8), (2, 64, 8)])
    def test_matches_scan_and_assoc(self, seed, shape):
        B, T, K = shape
        args = random_inputs(B, T, K, seed)
        p_paths, p_scores = viterbi_pallas_batch(*args, interpret=True)
        s_paths, s_scores = viterbi_decode_batch(*args)
        a_paths, a_scores = viterbi_assoc_batch(*args)
        np.testing.assert_allclose(np.asarray(p_scores),
                                   np.asarray(s_scores), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(a_scores),
                                   np.asarray(s_scores), rtol=1e-5)
        # paths may differ only where exact score ties flip; require exact
        # agreement on these inputs (continuous random scores -> no ties)
        np.testing.assert_array_equal(np.asarray(p_paths),
                                      np.asarray(s_paths))

    def test_batch_not_multiple_of_lanes(self):
        args = random_inputs(5, 12, 3, seed=7)
        p_paths, p_scores = viterbi_pallas_batch(*args, interpret=True)
        s_paths, s_scores = viterbi_decode_batch(*args)
        np.testing.assert_array_equal(np.asarray(p_paths),
                                      np.asarray(s_paths))
        np.testing.assert_allclose(np.asarray(p_scores),
                                   np.asarray(s_scores), rtol=1e-5)


class TestDispatch:
    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("REPORTER_TPU_DECODE", "scan")
        assert decode_backend(64, 8) == "scan"
        monkeypatch.setenv("REPORTER_TPU_DECODE", "pallas")
        assert decode_backend(64, 8) == "pallas"

    def test_default_off_tpu_is_scan(self, monkeypatch):
        # ISSUE 13: the CPU default is scan even on the 8-device test
        # mesh — the 1-D ("data",) mesh shards scan rows with zero
        # collectives, so CPU keeps the 4x-cheaper bit-identity
        # backend; only a seq-sharded mesh needs assoc
        monkeypatch.delenv("REPORTER_TPU_DECODE", raising=False)
        assert decode_backend(64, 8) == "scan"  # tests run on cpu
        from reporter_tpu import ops
        monkeypatch.setenv("REPORTER_TPU_SEQ_SHARDS", "2")
        ops.reset_sharded_cache()
        try:
            assert decode_backend(64, 8) == "assoc"
        finally:
            monkeypatch.delenv("REPORTER_TPU_SEQ_SHARDS", raising=False)
            ops.reset_sharded_cache()

    def test_vmem_estimate_gates_large_buckets(self):
        assert vmem_bytes_estimate(64, 8) <= VMEM_BUDGET_BYTES
        assert vmem_bytes_estimate(4096, 64) > VMEM_BUDGET_BYTES

    def test_decode_batch_dispatches(self, monkeypatch):
        args = random_inputs(3, 16, 4, seed=3)
        monkeypatch.setenv("REPORTER_TPU_DECODE", "pallas")
        p = decode_batch(*args)
        monkeypatch.setenv("REPORTER_TPU_DECODE", "scan")
        s = decode_batch(*args)
        np.testing.assert_array_equal(np.asarray(p[0]), np.asarray(s[0]))


def test_default_backend_is_scan_on_lone_cpu_device():
    """The unforced default must be scan on a SINGLE CPU device (assoc's
    O(K^3) is a measured ~4x decode loss there); conftest forces an
    8-device mesh in this process, so probe in a child interpreter."""
    import os
    import subprocess
    import sys

    code = (
        "import sys; sys.path.insert(0, sys.argv[1])\n"
        "from reporter_tpu.utils.runtime import force_virtual_cpu\n"
        "force_virtual_cpu()\n"  # no count: one CPU device
        "import jax\n"
        "assert len(jax.local_devices()) == 1, jax.local_devices()\n"
        "from reporter_tpu.ops import decode_backend\n"
        "print(decode_backend(64, 8))\n")
    env = dict(os.environ)
    env.pop("REPORTER_TPU_DECODE", None)
    env.pop("XLA_FLAGS", None)  # drop the 8-device flag
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", code, repo], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-1500:]
    assert out.stdout.strip().splitlines()[-1] == "scan"
