"""utils.fsio: the atomic-commit helpers, plus regression pins for the
durable writers reporter-lint's DUR pass flagged in PR 6 (tile sink and
dead-letter spool torn-write windows, un-fsync'd datastore segments)."""
import json
import os

import pytest

from reporter_tpu.utils import fsio


class TestAtomicWrite:
    def test_roundtrip_and_no_temp_leftovers(self, tmp_path):
        path = tmp_path / "out.txt"
        fsio.atomic_write_text(str(path), "hello")
        assert path.read_text() == "hello"
        fsio.atomic_write_bytes(str(path), b"\x00\x01")
        assert path.read_bytes() == b"\x00\x01"
        assert [n for n in os.listdir(tmp_path)] == ["out.txt"]

    def test_failed_commit_preserves_previous_contents(self, tmp_path,
                                                       monkeypatch):
        path = tmp_path / "out.txt"
        fsio.atomic_write_text(str(path), "committed")

        def boom(src, dst):
            raise OSError("simulated crash at the rename")
        monkeypatch.setattr(os, "replace", boom)
        with pytest.raises(OSError):
            fsio.atomic_write_text(str(path), "torn")
        monkeypatch.undo()
        assert path.read_text() == "committed"
        # and the failed commit cleaned its temp file up
        assert [n for n in os.listdir(tmp_path)] == ["out.txt"]

    def test_failed_write_leaves_no_temp(self, tmp_path, monkeypatch):
        real_fsync = os.fsync

        def boom(fd):
            raise OSError("simulated fsync failure")
        monkeypatch.setattr(os, "fsync", boom)
        with pytest.raises(OSError):
            fsio.atomic_write_text(str(tmp_path / "x"), "data")
        monkeypatch.setattr(os, "fsync", real_fsync)
        assert os.listdir(tmp_path) == []

    def test_fsync_helpers_tolerate_directories(self, tmp_path):
        p = tmp_path / "f"
        p.write_text("x")
        fsio.fsync_path(str(p))     # file: must not raise
        fsio.fsync_dir(str(tmp_path))   # dir: must not raise
        fsio.fsync_dir(str(tmp_path / "missing"))  # absent: best-effort


class TestDurableWritersUseTheProtocol:
    """The PR 6 DUR fixes, pinned behaviourally: a crash at the rename
    leaves the previous committed state visible and no torn finals."""

    def test_tile_sink_crash_at_rename_leaves_no_torn_tile(
            self, tmp_path, monkeypatch):
        from reporter_tpu.streaming.anonymiser import TileSink
        sink = TileSink(str(tmp_path / "out"))
        assert sink.store("1_2/0/1", "t.e00000000", "epoch0") is True
        tile = tmp_path / "out" / "1_2" / "0" / "1" / "t.e00000000"
        assert tile.read_text() == "epoch0"

        real_replace = os.replace

        def boom(src, dst):
            raise OSError("simulated crash")
        monkeypatch.setattr(os, "replace", boom)
        # the re-emit of the SAME epoch name crashes mid-commit: the
        # sink reports failure, the committed bytes survive untorn
        assert sink.store("1_2/0/1", "t.e00000000", "epoch0-again") \
            is False
        monkeypatch.setattr(os, "replace", real_replace)
        assert tile.read_text() == "epoch0"
        names = os.listdir(tmp_path / "out" / "1_2" / "0" / "1")
        assert names == ["t.e00000000"], names

    def test_deadletter_spool_is_atomic(self, tmp_path):
        from reporter_tpu.streaming.anonymiser import TileSink
        from reporter_tpu.utils import faults
        sink = TileSink(str(tmp_path / "out"))
        faults.configure("egress.http=error")
        try:
            assert sink.store("1_2/0/1", "t.e00000001", "body") is False
        finally:
            faults.clear()
        spool = tmp_path / "out" / ".deadletter" / "1_2" / "0" / "1"
        assert (spool / "t.e00000001").read_text() == "body"
        assert os.listdir(spool) == ["t.e00000001"]

    def test_datastore_segment_commit_survives_reload(self, tmp_path):
        """The fsync'd segment writer still round-trips (mechanics are
        invisible to tests; the commit contract is not)."""
        import numpy as np
        from reporter_tpu.datastore import LocalDatastore
        from reporter_tpu.datastore.schema import ObservationBatch
        ds = LocalDatastore(str(tmp_path / "store"))
        obs = ObservationBatch(
            segment_id=np.array([1 << 25], dtype=np.int64),
            next_id=np.array([2 << 25], dtype=np.int64),
            duration_s=np.array([30.0]),
            count=np.array([1], dtype=np.int64),
            length_m=np.array([500], dtype=np.int64),
            queue_m=np.array([0], dtype=np.int64),
            min_ts=np.array([1500000000], dtype=np.int64),
            max_ts=np.array([1500000030], dtype=np.int64))
        assert ds.ingest(obs) == 1
        stats = ds.stats()
        assert stats["segments"] == 1 and stats["rows"] == 1
        # no stray temp dirs/files in the partition after the commit
        # (.lease is the writer lease, a live control file — not a
        # stray temp)
        store_root = tmp_path / "store"
        stray = [os.path.join(d, n)
                 for d, _, names in os.walk(store_root) for n in names
                 if n.startswith(".") and n not in (".lease",)
                 and n != "MANIFEST.json"]
        assert stray == [], stray
