"""Runtime concurrency analysis (ISSUE 10): the lock-order witness,
the guarded shared-state audit, and the schedule-perturbation layer.

The RC rules are RUNTIME findings — unlike every other rule family they
pin through real threads here, not through AST fixtures (test_lint.py's
every-rule-has-a-fixture check carves them out by the same reasoning).
Covered:

- TrackedLock witness: RC001 lock-order inversion with both acquisition
  stacks, RC002 long hold + the ``long_hold_ok`` exemption, dedupe,
  same-name instances sharing one graph node;
- Guarded / @thread_affine audit: RC003 without the owning lock, RC004
  from a foreign thread, the unwrap() escape hatch, reset_affinity;
- renderer/metrics/flightrec integration (the PR 2 ``path:line: RC0xx``
  contract, ``racecheck.*`` counters, the postmortem dump);
- the seeded fuzz layer: spec grammar, bit-identical replay by seed,
  disarmed no-op;
- the ISSUE 10 shutdown-ordering contracts: BatchDispatcher.close()
  drains in-flight chunks on the real matcher's device lanes, and
  StreamWorker stop joins the shadow pool + pauses the drainer before
  the final flush.
"""
import threading
import time

import numpy as np
import pytest

from reporter_tpu.analysis import racecheck
from reporter_tpu.utils import locks, metrics


@pytest.fixture
def witness():
    """Arm the witness with a tight RC002 threshold; restore the
    session's prior arming state (the witness-armed CI leg runs this
    file armed already) and drop all findings on the way out."""
    was_armed = locks.armed()
    racecheck.reset()
    locks.arm(hold_ms=100.0)
    yield racecheck
    racecheck.reset()
    if was_armed:
        locks.arm()
    else:
        locks.disarm()


def _rules(found):
    return [f.rule for f in found]


class TestLockWitness:
    def test_nested_acquire_records_edge(self, witness):
        a, b = locks.TrackedLock("t.edge.a"), locks.TrackedLock("t.edge.b")
        before = witness.edge_count()
        with a:
            with b:
                pass
        assert witness.edge_count() == before + 1
        assert witness.findings() == []

    def test_rc001_inversion_reported_with_both_stacks(self, witness):
        a, b = locks.TrackedLock("t.inv.a"), locks.TrackedLock("t.inv.b")
        with a:
            with b:
                pass

        def reversed_order():
            with b:
                with a:
                    pass

        t = threading.Thread(target=reversed_order, name="inverter")
        t.start()
        t.join()
        # recording the finding fsyncs a flightrec dump UNDER the held
        # locks — on a loaded box that can trip the tight 100 ms RC002
        # threshold, so pin the RC001 leg, not the exact list
        found = [f for f in witness.findings() if f.rule == "RC001"]
        assert _rules(found) == ["RC001"]
        msg = found[0].render()
        # the cycle, the closing thread, and BOTH acquisition sites
        assert "t.inv.b -> t.inv.a" in msg or "t.inv.a -> t.inv.b" in msg
        assert "inverter" in msg
        assert msg.count("tests/test_racecheck.py") >= 2

    def test_rc001_deduped_per_cycle(self, witness):
        a, b = locks.TrackedLock("t.dup.a"), locks.TrackedLock("t.dup.b")
        with a:
            with b:
                pass

        def reversed_order():
            with b:
                with a:
                    pass

        for _ in range(3):
            t = threading.Thread(target=reversed_order)
            t.start()
            t.join()
        assert _rules(witness.findings()).count("RC001") == 1

    def test_same_name_instances_share_a_node(self, witness):
        # per-instance locks sharing one name (the circuit breakers'
        # pattern) must not self-cycle: same-name edges are skipped
        a1 = locks.TrackedLock("t.same")
        a2 = locks.TrackedLock("t.same")
        with a1:
            with a2:
                pass
        with a2:
            with a1:
                pass
        assert witness.findings() == []
        assert witness.edge_count() == 0

    def test_rc002_long_hold(self, witness):
        lk = locks.TrackedLock("t.hold")
        with lk:
            time.sleep(0.15)  # threshold armed at 100 ms
        found = witness.findings()
        assert _rules(found) == ["RC002"]
        assert "t.hold" in found[0].render()

    def test_rc002_exempts_long_hold_ok(self, witness):
        lk = locks.TrackedLock("t.hold.ok", long_hold_ok=True)
        with lk:
            time.sleep(0.15)
        assert witness.findings() == []

    def test_held_by_me_tracks_owner(self, witness):
        lk = locks.TrackedLock("t.owner")
        seen = {}
        with lk:
            assert lk.held_by_me()
            t = threading.Thread(
                target=lambda: seen.setdefault("other", lk.held_by_me()))
            t.start()
            t.join()
        assert seen["other"] is False
        assert not lk.held_by_me()

    def test_disarmed_lock_is_invisible(self):
        if locks.armed():
            pytest.skip("session armed by env; disarmed path covered "
                        "in the default leg")
        a, b = locks.TrackedLock("t.off.a"), locks.TrackedLock("t.off.b")
        before = racecheck.edge_count()
        with a:
            with b:
                time.sleep(0.01)
        assert racecheck.edge_count() == before
        assert racecheck.findings() == []


class TestGuardedAudit:
    def test_rc003_unlocked_access(self, witness):
        lk = locks.TrackedLock("t.g.lock")
        g = locks.Guarded({}, lk, "t.g.state")
        g["k"] = 1  # no lock held
        found = witness.findings()
        assert _rules(found) == ["RC003"]
        line = found[0].render()
        assert line.startswith("tests/test_racecheck.py:")
        assert "t.g.state" in line and "t.g.lock" in line

    def test_locked_access_is_clean(self, witness):
        lk = locks.TrackedLock("t.g2.lock")
        g = locks.Guarded({}, lk, "t.g2.state")
        with lk:
            g["k"] = 1
            assert g["k"] == 1
            assert len(g) == 1 and "k" in g
        assert witness.findings() == []

    def test_foreign_thread_holding_is_still_a_violation(self, witness):
        # the OWNING lock must be held BY THE ACCESSING thread.
        # long_hold_ok: the main thread holds lk across the child's
        # whole lifecycle (incl. the finding's fsync'd flightrec dump)
        # — an RC002 here would be the harness, not the contract
        lk = locks.TrackedLock("t.g3.lock", long_hold_ok=True)
        g = locks.Guarded({}, lk, "t.g3.state")
        lk.acquire()
        try:
            t = threading.Thread(target=lambda: g.get("k"))
            t.start()
            t.join()
        finally:
            lk.release()
        assert _rules(witness.findings()) == ["RC003"]

    def test_unwrap_bypasses_the_audit(self, witness):
        lk = locks.TrackedLock("t.g4.lock")
        g = locks.Guarded({"k": 1}, lk, "t.g4.state")
        assert g.unwrap()["k"] == 1
        assert witness.findings() == []

    def test_rc004_thread_affinity(self, witness):
        class Owned:
            @locks.thread_affine
            def touch(self):
                return threading.get_ident()

        obj = Owned()
        obj.touch()  # binds to this thread
        t = threading.Thread(target=obj.touch, name="foreign")
        t.start()
        t.join()
        found = witness.findings()
        assert _rules(found) == ["RC004"]
        assert "Owned.touch" in found[0].render()
        # reset_affinity hands the object to a new owner legitimately
        racecheck.reset()
        locks.reset_affinity(obj)
        t2 = threading.Thread(target=obj.touch)
        t2.start()
        t2.join()
        assert witness.findings() == []

    def test_findings_feed_metrics_and_flightrec(self, witness, tmp_path):
        from reporter_tpu.obs import flightrec
        old_dir = flightrec.dump_dir()
        flightrec.set_dump_dir(str(tmp_path))
        try:
            c0 = metrics.default.counter("racecheck.findings")
            lk = locks.TrackedLock("t.m.lock")
            locks.Guarded({}, lk, "t.m.state")["k"] = 1
            assert metrics.default.counter("racecheck.findings") == c0 + 1
            assert metrics.default.counter("racecheck.RC003") >= 1
            dumps = [p.name for p in tmp_path.iterdir()
                     if "racecheck.RC003" in p.name]
            assert dumps, "no flight-recorder postmortem for the finding"
        finally:
            if old_dir:
                flightrec.set_dump_dir(old_dir)


class TestRawMode:
    def test_raw_hands_out_bare_locks_and_refuses_arming(self, witness,
                                                         monkeypatch):
        monkeypatch.setattr(locks, "_RAW", True)
        lk = locks.new_lock("t.raw")
        assert not isinstance(lk, locks.TrackedLock)
        with pytest.raises(RuntimeError, match="raw"):
            locks.arm()

    def test_new_lock_default_is_tracked(self):
        lk = locks.new_lock("t.tracked")
        assert isinstance(lk, locks.TrackedLock)
        assert lk.name == "t.tracked"


class TestFuzzLayer:
    def test_spec_grammar(self):
        s = locks.parse_fuzz_spec("7")
        assert (s.seed, s.prob, s.max_us) == (7, 0.25, 200.0)
        s = locks.parse_fuzz_spec("7:0.5@400")
        assert (s.seed, s.prob, s.max_us) == (7, 0.5, 400.0)
        s = locks.parse_fuzz_spec("9@50")
        assert (s.seed, s.prob, s.max_us) == (9, 0.25, 50.0)
        for bad in ("", "x", "7:0", "7:1.5", "7@0", "7@-3", "7:a@b"):
            with pytest.raises(ValueError):
                locks.parse_fuzz_spec(bad)

    def test_replay_is_bit_identical_by_seed(self):
        def drive(seed):
            spec = locks._FuzzSpec(seed, prob=0.5, max_us=1.0)
            progression = []
            for _ in range(64):
                spec.maybe_yield("test.site")
                progression.append(spec.yields)
            return progression

        assert drive(5) == drive(5)
        # per-site streams are independent: a second site draws its own
        # sequence from crc32(site) ^ seed, unaffected by the first
        spec = locks._FuzzSpec(5, prob=0.5, max_us=1.0)
        for _ in range(10):
            spec.maybe_yield("other.site")
        base = spec.yields
        for _ in range(64):
            spec.maybe_yield("test.site")
        assert spec.yields - base == drive(5)[-1]

    def test_disarmed_fuzz_point_is_a_noop(self):
        assert locks.fuzz_yields() == 0 or locks._FUZZ is not None
        locks.configure_fuzz(None)
        locks.fuzz_point("test.site")  # must not raise, must not sleep
        assert locks.fuzz_yields() == 0

    def test_configure_rejects_malformed(self):
        with pytest.raises(ValueError):
            locks.configure_fuzz("not-a-spec")
        locks.configure_fuzz(None)


class TestDispatcherShutdown:
    """ISSUE 10 satellite: close() while chunks are in flight on both
    device lanes — the drain completes, no slot is orphaned, metrics
    stay consistent."""

    @pytest.fixture(scope="class")
    def city(self):
        from reporter_tpu.synth import build_grid_city
        return build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=2,
                               service_road_fraction=0.0,
                               internal_fraction=0.0)

    def _reqs(self, city, n):
        from reporter_tpu.synth import generate_trace
        rng = np.random.default_rng(3)
        reqs = []
        for i in range(n):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"veh-{i}", rng, noise_m=3.0,
                                    min_route_edges=8)
            reqs.append(tr.request_json())
        return reqs

    def test_close_drains_in_flight_chunks(self, city):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.dispatch import BatchDispatcher

        matcher = SegmentMatcher(net=city)  # real device lanes
        t0 = metrics.default.counter("dispatch.traces")
        # gate the first batch INSIDE the match fn: close() is then
        # guaranteed to land while that batch is in flight on the lanes
        # and the rest are still queued — in-flight by construction,
        # not by sleeping
        started, release = threading.Event(), threading.Event()

        def gated_match(traces):
            started.set()
            assert release.wait(120.0)
            return matcher.match_many(traces)

        # small batches so the submissions span several device batches
        disp = BatchDispatcher(gated_match, max_batch=2, max_wait_ms=5.0)
        reqs = self._reqs(city, 6)
        box = {}

        def submit_all():
            try:
                box["results"] = disp.submit_many(reqs, timeout=120.0)
            except Exception as e:  # surfaced below, not swallowed
                box["error"] = e

        t = threading.Thread(target=submit_all)
        t.start()
        assert started.wait(120.0)  # batch 1 is in flight on the lanes
        deadline = time.monotonic() + 120.0
        while disp._queue.qsize() < len(reqs) - disp.max_batch:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        closer = threading.Thread(target=disp.close,
                                  kwargs={"timeout": 120.0})
        closer.start()
        release.set()  # let the lanes run; the loop must drain ALL slots
        closer.join(120.0)
        t.join(120.0)
        assert not t.is_alive() and not closer.is_alive()
        assert "error" not in box, box
        results = box["results"]
        assert len(results) == len(reqs)
        for res in results:
            assert "segments" in res, res  # MatchRuns mapping view or dict
        # no orphaned slots: the queue is empty and fully accounted
        assert disp._queue.empty()
        assert metrics.default.counter("dispatch.traces") - t0 == len(reqs)
        assert not disp._thread.is_alive()
        # idempotent, and the door is shut
        assert disp.close() is True
        with pytest.raises(RuntimeError, match="closed"):
            disp.submit(reqs[0], timeout=1.0)

    def test_close_wakes_slots_stranded_behind_the_sentinel(self, city):
        # a submit that raced past the closed check can enqueue AFTER
        # the sentinel; close() must wake it with an error instead of
        # leaving it to burn its full wait timeout
        from reporter_tpu.service.dispatch import BatchDispatcher, _Slot

        disp = BatchDispatcher(lambda traces: [{"segments": []}
                                               for _ in traces],
                               max_batch=4, max_wait_ms=5.0)
        assert disp.close(timeout=30.0) is True
        late = _Slot({"uuid": "late"})
        disp._queue.put(late)
        assert disp.close(timeout=30.0) is True  # re-run the sweep
        assert late.event.is_set()
        assert isinstance(late.error, RuntimeError)


class TestWorkerStopOrdering:
    """ISSUE 10 satellite: StreamWorker stop joins the shadow-accuracy
    pool and pauses the drainer BEFORE the final flush — no thread
    outlives the spool/datastore handles."""

    def test_stop_under_load(self, tmp_path, monkeypatch):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.obs import profiler
        from reporter_tpu.service.server import ReporterService
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        from reporter_tpu.streaming.formatter import Formatter
        from reporter_tpu.streaming.worker import (StreamWorker,
                                                   inproc_submitter)
        from reporter_tpu.synth import build_grid_city, generate_trace

        # every chunk sampled -> the shadow pool is busy at stop time;
        # a huge replay interval -> the drainer exists but only the
        # final drain_now + pause touch it
        monkeypatch.setenv("REPORTER_TPU_SHADOW_SAMPLE", "1.0")
        monkeypatch.setenv("REPORTER_TPU_REPLAY_INTERVAL_S", "1e9")

        city = build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=5,
                               service_road_fraction=0.0,
                               internal_fraction=0.0)
        service = ReporterService(SegmentMatcher(net=city),
                                  threshold_sec=15, max_batch=16,
                                  max_wait_ms=5.0)
        rng = np.random.default_rng(7)
        lines = []
        for i in range(6):
            tr = None
            while tr is None:
                tr = generate_trace(city, f"veh-{i}", rng, noise_m=3.0,
                                    min_route_edges=8)
            lines.extend("|".join([tr.uuid, str(p["lat"]), str(p["lon"]),
                                   str(p["time"]), str(p["accuracy"])])
                         for p in tr.points)
        worker = StreamWorker(
            Formatter.from_config(",sv,\\|,0,1,2,3,4"),
            inproc_submitter(service),
            Anonymiser(TileSink(str(tmp_path / "out"),
                                deadletter=str(tmp_path / "spool")),
                       privacy=1, quantisation=3600, source="stoptest"),
            reports="0,1,2", transitions="0,1,2", flush_interval_s=1e9,
            submit_many=service.report_many)
        assert worker.drainer is not None

        worker.run(lines)  # run() ends in drain(): the ordered stop

        assert worker.processed == len(lines)
        assert worker.parse_failures == 0
        # (1) the shadow pool was joined: no sampler thread survives,
        # and the module handle is gone (a later maybe_shadow would
        # lazily rebuild — this stop owed it a full join)
        assert profiler._shadow_pool is None
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("shadow-decode")]
        # (2) the drainer is paused: a stray maybe_drain after stop is
        # a no-op instead of a write into released handles
        assert worker.drainer._paused is True
        assert worker.drainer.maybe_drain() == 0
        service.dispatcher.close()


class TestRendererContract:
    def test_findings_render_as_rule_lines(self, witness):
        lk = locks.TrackedLock("t.r.lock")
        locks.Guarded({}, lk, "t.r.state")["k"] = 1
        lines = witness.render()
        assert len(lines) == 1
        path, line_no, rest = lines[0].split(":", 2)
        assert path == "tests/test_racecheck.py"
        assert int(line_no) > 0
        assert rest.strip().startswith("RC003")

    def test_rules_are_registered_in_the_catalogue(self):
        from reporter_tpu import analysis
        for rule in ("RC001", "RC002", "RC003", "RC004"):
            assert rule in racecheck.RULES
            assert rule in analysis.ALL_RULES

    def test_reset_clears_graph_and_findings(self, witness):
        a, b = locks.TrackedLock("t.rst.a"), locks.TrackedLock("t.rst.b")
        with a:
            with b:
                pass
        locks.Guarded({}, a, "t.rst.state")["k"] = 1
        assert witness.edge_count() == 1 and witness.findings()
        witness.reset()
        assert witness.edge_count() == 0
        assert witness.findings() == []
