"""Overload-proof scale legs and the bigreplay->ledger feed (ISSUE 15).

The fast tests pin the committed 100k-probe BIGREPLAY artifact and its
ledger normalisation; the slow-marked test re-runs the scaled replay
end-to-end (the same harness is 1M-capable: ``--probes 1000000`` on a
box with the minutes to spend — throughput measured here is ~15k
probes/s on the 2-core CI container)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestBigreplayArtifact:
    def test_committed_scaled_artifact(self):
        """The checked-in 100k local run: full scale, >=99% agreement,
        and a fault ratio the full-scale 0.4 floor accepts."""
        with open(os.path.join(REPO, "BIGREPLAY_r01.json")) as f:
            art = json.load(f)
        assert art["kind"] == "bigreplay"
        assert art["probes"] >= 100_000
        assert art["agreement"] >= 0.99
        assert art["fault_throughput_ratio"] >= 0.4

    def test_ledger_entry_normalisation(self):
        from reporter_tpu.obs import ledger
        entry = ledger._bigreplay_entry("BIGREPLAY_r01.json", {
            "kind": "bigreplay", "probes": 100000, "agreement": 0.995,
            "writers": 2, "fault_throughput_ratio": 0.87,
            "clean": {"probes_per_s": 15000.0}})
        assert entry["kind"] == "bigreplay"
        assert entry["scope"] == "full"
        assert entry["vs_baseline"] == 0.87
        assert "agreement=0.995" in entry["context"]
        smoke = ledger._bigreplay_entry("BIGREPLAY_x.json", {
            "kind": "bigreplay", "probes": 3000, "agreement": 1.0,
            "fault_throughput_ratio": 0.5})
        assert smoke["scope"] == "smoke"

    def test_bigreplay_kind_never_pools_with_bench(self):
        """The chaos/clean ratio must not bleed into the bench
        vs_baseline medians perf_gate compares against."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import perf_gate
        entries = [
            {"kind": "bench", "scope": "full", "platform": "cpu",
             "vs_baseline": 20.0},
            {"kind": "bigreplay", "scope": "full", "platform": "cpu",
             "vs_baseline": 0.9},
        ]
        pool = perf_gate.comparable_pool(entries, "cpu", "full")
        assert len(pool) == 1 and pool[0]["kind"] == "bench"

    def test_seeded_ledger_contains_bigreplay(self):
        from reporter_tpu.obs import ledger
        entries = ledger.seed_entries(REPO)
        big = [e for e in entries if e["kind"] == "bigreplay"]
        assert big, "committed BIGREPLAY artifacts must seed the ledger"
        assert all(e["vs_baseline"] for e in big)


@pytest.mark.slow
class TestScaledReplay:
    def test_100k_probe_replay(self, tmp_path):
        """The local scaled leg: 100k probes through the real
        multi-writer chaos replay, gated at the full-scale floor.
        (Swap --probes for 1000000 for the 1M leg — same harness,
        ~10x the wall.)"""
        out = tmp_path / "bigreplay_scaled.json"
        env = dict(os.environ, REPORTER_TPU_PLATFORM="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools/bigreplay.py"),
             "--probes", "100000", "--writers", "2",
             "--agreement-sample", "30", "--out", str(out)],
            env=env, capture_output=True, text=True, timeout=1800)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        art = json.loads(out.read_text())
        assert art["agreement"] >= 0.99
        assert art["fault_throughput_ratio"] >= 0.4
