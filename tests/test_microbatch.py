"""Streaming eviction flush -> ONE device batch (round-4 VERDICT #3).

The reference submits one C++ Match per trace (Batch.java:66-68); this
framework's streaming path must instead flush a punctuate cycle's N
evicted sessions as one padded device batch. Pinned here:

- PointBatcher.punctuate routes ALL due evictions through a single
  submit_many call (N bodies in one call, not N calls);
- through a real ReporterService + BatchDispatcher, the N bodies reach
  SegmentMatcher.match_many as one N-trace batch;
- per-uuid trim/forward semantics survive the batched path.
"""
import os

import numpy as np
import pytest

from reporter_tpu.core.types import Point
from reporter_tpu.streaming.batcher import PointBatcher


def _feed_session(batcher, uuid, t0, lat0=0.0):
    """8 points spanning ~310 m / 49 s: below the report trigger (500 m,
    10 pts, 60 s) so nothing fires during process() — but above the
    relaxed eviction gate (0 m, 2 pts, 0 s)."""
    for i in range(8):
        p = Point(lat=lat0 + i * 0.0004, lon=0.0, accuracy=10,
                  time=t0 + i * 7)
        batcher.process(uuid, p, stream_time_ms=(t0 + i * 7) * 1000)


def test_punctuate_flushes_one_submit_many_call():
    calls = []
    single_calls = []

    def submit_many(bodies):
        calls.append([body["uuid"] for body in bodies])
        return [None] * len(bodies)  # failed round trip: batches drop

    def submit_one(body):  # recorded, NOT raised: Batch.report would
        single_calls.append(body["uuid"])  # swallow an exception silently
        return None

    b = PointBatcher(submit_one, lambda k, s: None,
                     submit_many=submit_many)
    for j in range(5):
        _feed_session(b, f"veh-{j}", t0=1000)
    assert not single_calls, "report fired during process(); sessions " \
        "must stay below the trigger thresholds for this test"
    assert len(b.store) == 5
    b.punctuate(stream_time_ms=(1000 + 8 * 7 + 120) * 1000)
    # the eviction path used ONE submit_many call for all 5 full
    # sessions, and never the per-uuid submit
    assert [sorted(c) for c in calls] == [
        [f"veh-{j}" for j in range(5)]], calls
    assert not single_calls
    assert not b.store
    # each flushed body carried the full 8-point session
    # (not a post-report remnant)


def test_punctuate_bodies_carry_full_sessions():
    bodies_seen = []
    b = PointBatcher(lambda body: None, lambda k, s: None,
                     submit_many=lambda bodies:
                     bodies_seen.extend(bodies) or [None] * len(bodies))
    _feed_session(b, "veh-full", t0=1000)
    b.punctuate(stream_time_ms=10_000_000)
    assert len(bodies_seen) == 1
    assert len(bodies_seen[0]["trace"]) == 8


def test_punctuate_skips_below_relaxed_thresholds():
    calls = []
    b = PointBatcher(lambda body: None, lambda k, s: None,
                     submit_many=lambda bodies: calls.append(len(bodies))
                     or [None] * len(bodies))
    # a single point fails even the relaxed (0 m, 2 pts, 0 s) gate
    b.process("lonely", Point(0.0, 0.0, 10, 1000), 1000 * 1000)
    b.punctuate(stream_time_ms=10_000_000)
    assert not calls
    assert not b.store


def test_submit_many_failure_granularity():
    """A failing device batch costs only ITS traces: submit_many with
    return_exceptions surfaces the error in-place, and report_many turns
    it into per-trace Nones without discarding other batches' results."""
    from reporter_tpu.service.dispatch import BatchDispatcher

    def match_many(traces):
        if any(t.get("poison") for t in traces):
            raise RuntimeError("boom")
        return [{"segments": [], "mode": "auto"} for _ in traces]

    # max_batch=2: [ok, ok] then [poison, ok] form separate batches
    # generous wait: full batches still flush instantly at
    # max_batch=2; the margin only removes scheduler-jitter flake
    d = BatchDispatcher(match_many, max_batch=2, max_wait_ms=2000.0)
    try:
        traces = [{"uuid": "a"}, {"uuid": "b"},
                  {"uuid": "c", "poison": True}, {"uuid": "d"}]
        results = d.submit_many(traces, return_exceptions=True)
        assert results[0] == {"segments": [], "mode": "auto"}
        assert results[1] == {"segments": [], "mode": "auto"}
        assert isinstance(results[2], RuntimeError)
        assert isinstance(results[3], RuntimeError)  # same poisoned batch

        # without return_exceptions the error raises
        with pytest.raises(RuntimeError):
            d.submit_many([{"uuid": "x", "poison": True}])
    finally:
        d.close()


def test_report_many_partial_failure_keeps_good_traces():
    from reporter_tpu.service.server import ReporterService

    class FakeMatcher:
        def match_many(self, traces):
            if any(t.get("poison") for t in traces):
                raise RuntimeError("boom")
            return [{"segments": [], "mode": "auto"} for _ in traces]

    svc = ReporterService(FakeMatcher(), threshold_sec=15, max_batch=2,
                          max_wait_ms=2000.0)
    try:
        opts = {"report_levels": [0, 1], "transition_levels": [0, 1]}
        mk = lambda uuid, poison=False: {
            "uuid": uuid, "poison": poison, "match_options": opts,
            "trace": [{"lat": 0.0, "lon": 0.0, "time": 0},
                      {"lat": 0.0, "lon": 0.0, "time": 5}]}
        out = svc.report_many([mk("a"), mk("b"),
                               mk("c", poison=True), mk("d")])
        assert out[0] is not None and out[1] is not None
        assert "datastore" in out[0]
        assert out[2] is None and out[3] is None  # only the poisoned batch
    finally:
        svc.dispatcher.close()


def test_eviction_batch_reaches_matcher_as_one_call(tmp_path):
    from reporter_tpu.matcher import MatchParams, SegmentMatcher
    from reporter_tpu.service.server import ReporterService
    from reporter_tpu.streaming.worker import inproc_submitter
    from reporter_tpu.synth import build_grid_city, generate_trace

    city = build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=6)
    matcher = SegmentMatcher(net=city, params=MatchParams())
    batch_sizes = []
    real_match_many = matcher.match_many

    def spy(traces):
        batch_sizes.append(len(traces))
        return real_match_many(traces)

    matcher.match_many = spy
    service = ReporterService(matcher, max_wait_ms=200.0)
    forwarded = []
    b = PointBatcher(inproc_submitter(service),
                     lambda k, s: forwarded.append((k, s)),
                     report_on="0,1,2", transition_on="0,1,2",
                     submit_many=service.report_many)

    rng = np.random.default_rng(4)
    n_sessions = 6
    made = 0
    while made < n_sessions:
        tr = generate_trace(city, f"veh-{made}", rng, noise_m=3.0,
                            min_route_edges=8, max_route_edges=20)
        if tr is None or len(tr.points) < 12:
            continue
        t_base = 1000
        for p in tr.points:
            pt = Point(lat=p["lat"], lon=p["lon"], accuracy=10,
                       time=int(t_base + (p["time"] - tr.points[0]["time"])))
            b.process(f"veh-{made}", pt,
                      stream_time_ms=int(pt.time) * 1000)
        made += 1

    batch_sizes.clear()
    b.punctuate(stream_time_ms=10_000_000_000)
    # every evicted session decoded in ONE matcher batch
    assert batch_sizes == [n_sessions], batch_sizes
    assert not b.store
    assert forwarded, "batched eviction forwarded no segment pairs"
    service.dispatcher.close()


def _feed_big_session(batcher, uuid, t0, n=14, lat0=14.6):
    """n points spanning > 500 m / > 60 s / > 10 pts: crosses the
    mid-stream report thresholds while being fed."""
    for i in range(n):
        p = Point(lat=lat0 + i * 0.0006, lon=0.0, accuracy=10,
                  time=t0 + i * 7)
        batcher.process(uuid, p, stream_time_ms=(t0 + i * 7) * 1000)


def test_midstream_reports_flush_as_one_batch():
    """Sessions that cross the report thresholds mid-stream accumulate
    in ``pending`` and flush through ONE submit_many call — the
    reference fires one matcher call per crossing (Batch.java:66-68)."""
    calls = []
    single_calls = []
    b = PointBatcher(lambda body: single_calls.append(body) or None,
                     lambda k, s: None,
                     submit_many=lambda bodies:
                     calls.append([t["uuid"] for t in bodies])
                     or [{"shape_used": 0} for _ in bodies])
    for j in range(4):
        _feed_big_session(b, f"veh-{j}", t0=1000)
    assert not single_calls, "mid-stream reports must not fire at batch=1"
    assert len(b.pending) == 4
    b.flush_pending()
    assert [sorted(c) for c in calls] == [[f"veh-{j}" for j in range(4)]]
    assert not b.pending
    # shape_used 0: nothing consumed, the sessions keep their context
    assert all(batch.points for batch in b.store.values())


def test_failed_midstream_flush_requeues_not_drops():
    """A failed round trip no longer silently drops live sessions
    (the reference's Batch.java:83-87 behavior): the batch requeues
    under the retry budget with its points intact."""
    b = PointBatcher(lambda body: None, lambda k, s: None,
                     submit_many=lambda bodies: [None] * len(bodies),
                     retry_budget=2)
    for j in range(4):
        _feed_big_session(b, f"veh-{j}", t0=1000)
    b.flush_pending()
    assert sorted(b.pending) == [f"veh-{j}" for j in range(4)]
    assert all(batch.points for batch in b.store.values())
    assert all(batch.retries == 1 for batch in b.store.values())


def test_exhausted_budget_deadletters_trace_json(tmp_path):
    """Retries spent: the trace JSON spools for replay (batch.dropped +
    batch.deadletter), the batch empties, and the next window gets a
    fresh budget."""
    import json
    spool = str(tmp_path / "spool")
    b = PointBatcher(lambda body: None, lambda k, s: None,
                     submit_many=lambda bodies: [None] * len(bodies),
                     retry_budget=1, deadletter_dir=spool)
    _feed_big_session(b, "veh-0", t0=1000)
    b.flush_pending()          # failure 1: requeued (budget 1)
    assert b.store["veh-0"].retries == 1
    b.flush_pending()          # failure 2: budget spent -> dead-letter
    assert not b.store["veh-0"].points
    assert b.store["veh-0"].retries == 0
    names = sorted(os.listdir(spool))
    assert len(names) == 1 and names[0].endswith(".veh-0.json")
    with open(os.path.join(spool, names[0])) as f:
        body = json.load(f)
    assert body["uuid"] == "veh-0"
    assert len(body["trace"]) >= 10
    assert body["match_options"]["report_levels"] == [0, 1]


def test_evicted_batch_failure_deadletters_immediately(tmp_path):
    """An evicted session has no next flush to ride — a failed submit
    dead-letters it instead of requeueing a ghost."""
    spool = str(tmp_path / "spool")
    b = PointBatcher(lambda body: None, lambda k, s: None,
                     submit_many=lambda bodies: [None] * len(bodies),
                     retry_budget=5, deadletter_dir=spool)
    _feed_session(b, "veh-gone", t0=1000)
    b.punctuate(stream_time_ms=10_000_000)
    assert "veh-gone" not in b.store
    assert not b.pending
    assert any(".veh-gone." in n for n in os.listdir(spool))


def test_pending_flush_trims_consumed_prefix():
    """A successful batched mid-stream response trims each session at
    shape_used, exactly like the old inline per-trace path."""
    seen = []

    def submit_many(bodies):
        seen.extend(bodies)
        return [{"shape_used": 5} for _ in bodies]

    b = PointBatcher(lambda body: None, lambda k, s: None,
                     submit_many=submit_many)
    _feed_big_session(b, "veh-x", t0=1000)
    n_before = len(b.store["veh-x"].points)
    b.flush_pending()
    assert len(seen) == 1
    assert len(b.store["veh-x"].points) == n_before - 5


def test_pending_autoflush_at_report_flush_size():
    calls = []
    b = PointBatcher(lambda body: None, lambda k, s: None,
                     submit_many=lambda bodies:
                     calls.append(len(bodies)) or [None] * len(bodies),
                     report_flush=3)
    for j in range(3):
        _feed_big_session(b, f"veh-{j}", t0=1000)
    # third crossing hit report_flush=3 -> flushed without punctuate
    assert calls and calls[0] == 3
    assert not b.pending


def test_punctuate_merges_pending_and_evictions_into_one_batch():
    calls = []
    b = PointBatcher(lambda body: None, lambda k, s: None,
                     submit_many=lambda bodies:
                     calls.append(sorted(t["uuid"] for t in bodies))
                     or [None] * len(bodies))
    _feed_big_session(b, "live", t0=10_000_000)   # pending, recent
    _feed_session(b, "idle", t0=1000)             # below thresholds, stale
    # stream time just past "live"'s last update: "idle" is evicted
    # (stale), "live" is still open but pending — ONE batch carries both
    b.punctuate(stream_time_ms=10_000_000 * 1000 + 14 * 7000 + 1)
    assert calls == [["idle", "live"]]
