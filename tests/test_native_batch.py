"""Whole-batch native prep + assembly vs the per-trace paths: identical.

The round-4 hot path moved per-trace host work into two batch-level C++
entry points (host_runtime.cpp rt_prepare_batch / rt_assemble_batch;
reference architecture being replaced: one C++ Match per trace,
py/reporter_service.py:240). These tests pin the parity contract:

- rt_prepare_batch produces the same tensors as prepare_trace for every
  trace in a mixed batch (kept selection, candidates, route matrices,
  case codes, trailing dwell);
- match_many through the native batch path returns byte-identical match
  dicts to the pure-numpy per-trace fallback;
- rt_f32_to_f16 is bit-identical to numpy's float16 cast (the wire
  format both decode paths consume).
"""
import numpy as np
import pytest

from reporter_tpu import native
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.matcher.batchpad import bucket_length, prepare_batch
from reporter_tpu.synth import build_grid_city, generate_trace

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=5)


@pytest.fixture(scope="module")
def matcher(city):
    return SegmentMatcher(net=city, params=MatchParams(max_candidates=8))


@pytest.fixture(scope="module")
def traces(city):
    rng = np.random.default_rng(11)
    out = []
    while len(out) < 24:
        tr = generate_trace(city, f"b{len(out)}", rng, noise_m=5.0,
                            min_route_edges=3, max_route_edges=14)
        if tr is not None and len(tr.points) >= 4:
            tr.points = tr.points[:60]
            out.append(tr)
    return out


def test_prepare_batch_matches_prepare_trace(matcher, traces):
    params = matcher.params
    pts = [tr.points for tr in traces]
    olds = [matcher.prepare(p) for p in pts]
    by_T = {}
    for idx, p in enumerate(pts):
        by_T.setdefault(bucket_length(max(len(p), 1)), []).append(idx)
    for T, idxs in by_T.items():
        batch = prepare_batch(matcher.runtime, [pts[i] for i in idxs],
                              params, T, n_threads=2)
        for row, i in enumerate(idxs):
            old, new = olds[i], batch.traces[row]
            assert old.num_kept == new.num_kept
            nk = old.num_kept
            np.testing.assert_array_equal(old.kept_idx, new.kept_idx)
            np.testing.assert_array_equal(old.edge_ids[:nk],
                                          new.edge_ids[:nk])
            np.testing.assert_allclose(old.dist_m[:nk], new.dist_m[:nk],
                                       rtol=1e-6, atol=1e-4)
            np.testing.assert_allclose(old.offset_m[:nk],
                                       new.offset_m[:nk],
                                       rtol=1e-6, atol=1e-4)
            if nk > 1:
                np.testing.assert_allclose(old.route_m[:nk - 1],
                                           new.route_m[:nk - 1],
                                           rtol=1e-5, atol=1e-3)
                np.testing.assert_allclose(old.gc_m[:nk - 1],
                                           new.gc_m[:nk - 1],
                                           rtol=1e-6, atol=1e-4)
            nmin = min(old.T, T)
            np.testing.assert_array_equal(old.case[:nmin], new.case[:nmin])
            assert old.trailing_jitter_dwell_s == pytest.approx(
                new.trailing_jitter_dwell_s, abs=1e-9)


def test_prepare_batch_pad_rows_are_skip(matcher, traces):
    from reporter_tpu.matcher.hmm import SKIP
    pts = [traces[0].points]
    batch = prepare_batch(matcher.runtime, pts, matcher.params, 64,
                          pad_rows=4)
    assert batch.case.shape[0] == 4
    assert (batch.case[1:] == SKIP).all()
    assert not batch.valid[1:].any()
    assert len(batch.traces) == 1


def test_match_many_native_equals_numpy_fallback(city, matcher, traces):
    reqs = []
    for tr in traces:
        r = tr.request_json()
        r["trace"] = tr.points
        r["match_options"] = {"mode": "auto", "report_levels": [0, 1, 2],
                              "transition_levels": [0, 1, 2]}
        reqs.append(r)
    res_native = matcher.match_many(reqs)
    fallback = SegmentMatcher(net=city, params=matcher.params,
                              use_native=False)
    res_np = fallback.match_many(reqs)
    assert res_native == res_np


def test_match_many_native_equals_numpy_with_jitter_tail(city, matcher):
    # a stalled vehicle: trailing jitter points exercise the dwell /
    # queue_length path through the native batch assembler
    rng = np.random.default_rng(3)
    tr = None
    while tr is None:
        tr = generate_trace(city, "stall", rng, noise_m=4.0,
                            min_route_edges=5, max_route_edges=12)
    last = dict(tr.points[-1])
    for s in range(1, 31):
        p = dict(last)
        p["time"] = last["time"] + s
        p["lat"] = last["lat"] + rng.normal(0, 1e-6)
        p["lon"] = last["lon"] + rng.normal(0, 1e-6)
        tr.points.append(p)
    req = tr.request_json()
    req["trace"] = tr.points
    req["match_options"] = {"mode": "auto", "report_levels": [0, 1, 2],
                            "transition_levels": [0, 1, 2]}
    res_native = matcher.match_many([req])
    fallback = SegmentMatcher(net=city, params=matcher.params,
                              use_native=False)
    assert res_native == fallback.match_many([req])


def test_f16_cast_bit_identical_to_numpy(matcher):
    rng = np.random.default_rng(0)
    a = (rng.standard_normal(100003)
         * (10.0 ** rng.uniform(-6, 9, 100003))).astype(np.float32)
    a[::97] = 1.0e9       # UNREACHABLE / PAD sentinels -> +inf
    a[::31] = 0.0
    a[1::53] = -a[1::53]
    a[2::41] = 65504.0    # f16 max finite
    a[3::67] = 65520.0    # first value rounding to +inf
    with np.errstate(over="ignore"):
        want = a.astype(np.float16)
    got = matcher.runtime.to_f16(a)
    np.testing.assert_array_equal(want.view(np.uint16),
                                  got.view(np.uint16))


def test_prepare_batch_wire_dtype_decision(matcher, traces):
    """The batch ships f16 when every finite distance fits the wire
    (decided from the C++-computed max_finite scalar), f32 otherwise —
    same policy as pack_batches (tests/test_matcher.py)."""
    from reporter_tpu.matcher.hmm import WIRE_MAX_M
    pts = [tr.points for tr in traces[:4]]
    batch = prepare_batch(matcher.runtime, pts, matcher.params, 64)
    assert batch.route_m.dtype == np.float16  # city-scale distances fit
    assert float(batch.prep["max_finite"][0]) <= WIRE_MAX_M

    # a long straight road: consecutive probes ~4.5 km apart (under the
    # 5 km breakage override) produce finite route distances beyond the
    # f16-safe ceiling -> the whole batch falls back to the f32 wire
    from reporter_tpu.matcher import MatchParams, SegmentMatcher
    from tests.test_knobs import _net_from_meters, _pts_from_meters
    road = _net_from_meters(
        [(0.0, 0.0), (4500.0, 0.0), (9000.0, 0.0)], [(0, 1), (1, 2)])
    m2 = SegmentMatcher(net=road,
                        params=MatchParams(breakage_distance=5000.0))
    far = _pts_from_meters([(10.0, 1.0, 0.0), (4510.0, -1.0, 300.0),
                            (8990.0, 1.0, 600.0)])
    # both the serial and the threaded C++ paths must report the max
    # (a multi-trace batch with n_threads>1 exercises the join path,
    # where an unwritten out_max_finite would silently force f16)
    for n_threads in (1, 2):
        b2 = prepare_batch(m2.runtime, [far, far], m2.params, 16,
                           n_threads=n_threads)
        assert float(b2.prep["max_finite"][0]) > WIRE_MAX_M, n_threads
        assert b2.route_m.dtype == np.float32, n_threads


@pytest.mark.parametrize("seed", [1, 7, 19, 42])
def test_native_numpy_parity_sweep(seed):
    """Byte-identical match dicts across varied cities/params — broad
    insurance against native/numpy drift beyond the fixed-seed tests."""
    rows = 6 + (seed % 3) * 2
    city = build_grid_city(rows=rows, cols=rows, spacing_m=150.0 + seed,
                           seed=seed)
    params = MatchParams(
        max_candidates=8,
        turn_penalty_factor=250.0 if seed % 2 else 0.0,
        search_radius=45.0 if seed % 3 == 0 else 50.0)
    rng = np.random.default_rng(seed)
    reqs = []
    attempts = 0
    while len(reqs) < 10 and attempts < 2000:
        attempts += 1
        tr = generate_trace(city, f"s{seed}-{len(reqs)}", rng,
                            noise_m=3.0 + (seed % 4),
                            min_route_edges=4, max_route_edges=16)
        if tr is None or len(tr.points) < 4:
            continue
        r = tr.request_json()
        r["trace"] = tr.points[:60]
        r["match_options"] = {"mode": "auto", "report_levels": [0, 1, 2],
                              "transition_levels": [0, 1, 2]}
        reqs.append(r)
    assert len(reqs) >= 6, f"seed {seed}: too few traces generated"
    a = SegmentMatcher(net=city, params=params).match_many(reqs)
    b = SegmentMatcher(net=city, params=params,
                       use_native=False).match_many(reqs)
    assert a == b


def test_all_decode_backends_accept_t_row_route(matcher, traces):
    """Native prep ships route/gc with T time rows (dead trailing step
    for seq sharding); every decode backend must shed it identically
    (matcher/hmm.py trim_time_pad)."""

    from reporter_tpu.ops import viterbi_assoc_batch, viterbi_pallas_batch
    from reporter_tpu.matcher.hmm import viterbi_decode_batch

    batch = prepare_batch(matcher.runtime,
                          [tr.points for tr in traces[:6]],
                          matcher.params, 64)
    assert batch.route_m.shape[1] == 64  # T rows, not T-1
    sigma, beta = np.float32(4.07), np.float32(3.0)
    args = (batch.dist_m, batch.valid, batch.route_m, batch.gc_m,
            batch.case, sigma, beta)
    p_scan, _ = viterbi_decode_batch(*args)
    p_assoc, _ = viterbi_assoc_batch(*args)
    p_pallas, _ = viterbi_pallas_batch(*args, interpret=True)
    # identical decoded paths over the kept prefixes (ties can only flip
    # under different f32 orderings; these backends agree on this data)
    for b, tr in enumerate(batch.traces):
        nk = tr.num_kept
        np.testing.assert_array_equal(np.asarray(p_scan)[b, :nk],
                                      np.asarray(p_assoc)[b, :nk])
        np.testing.assert_array_equal(np.asarray(p_scan)[b, :nk],
                                      np.asarray(p_pallas)[b, :nk])


def test_match_options_split_batches(matcher, traces):
    # per-trace match_options that change prep params must not share a
    # native prep call; results still line up with per-trace fallback
    reqs = []
    for j, tr in enumerate(traces[:8]):
        r = tr.request_json()
        r["trace"] = tr.points
        opts = {"mode": "auto", "report_levels": [0, 1, 2],
                "transition_levels": [0, 1, 2]}
        if j % 2:
            opts["search_radius"] = 35.0
        r["match_options"] = opts
        reqs.append(r)
    res_native = matcher.match_many(reqs)
    fallback = SegmentMatcher(net=matcher.net, params=matcher.params,
                              use_native=False)
    assert res_native == fallback.match_many(reqs)
