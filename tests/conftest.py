"""Test configuration: run JAX on a virtual 8-device CPU mesh so sharding
paths are exercised without TPU hardware.

Two hazards specific to this environment:
- JAX_PLATFORMS is pre-set to the single real TPU chip's platform; tests
  must never contend for it (bench.py owns the chip), so force cpu.
- sitecustomize registers the TPU PJRT plugin in every interpreter before
  conftest runs; merely setting JAX_PLATFORMS=cpu still initialises that
  backend (and blocks on the chip tunnel), so the factory is removed from
  the registry outright.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax
from jax._src import xla_bridge

# jax was already imported by sitecustomize, so the env var change above
# came too late for its config — update it directly as well
jax.config.update("jax_platforms", "cpu")

# pallas registers MLIR lowering rules for the "tpu" platform at import
# time, which fails once the factory below is popped — import it first
# (tests then run pallas kernels in interpret mode on cpu)
from jax.experimental import pallas as _pl  # noqa: F401,E402
from jax.experimental.pallas import tpu as _pltpu  # noqa: F401,E402

for _name in list(xla_bridge._backend_factories):
    if _name != "cpu":
        xla_bridge._backend_factories.pop(_name, None)

# fail loudly if the force-to-CPU mechanism ever stops working; tests must
# never contend for the single real TPU chip (bench.py owns it)
assert jax.default_backend() == "cpu", (
    "tests must run on the CPU backend, got " + jax.default_backend())
