"""Test configuration: run JAX on a virtual 8-device CPU mesh so sharding
paths are exercised without TPU hardware.

Two hazards specific to this environment:
- JAX_PLATFORMS is pre-set to the single real TPU chip's platform; tests
  must never contend for it (bench.py owns the chip), so force cpu.
- sitecustomize registers the TPU PJRT plugin in every interpreter before
  conftest runs; merely setting JAX_PLATFORMS=cpu still initialises that
  backend (and blocks on the chip tunnel), so the factory is removed from
  the registry outright.

The mechanics live in reporter_tpu.utils.runtime.force_virtual_cpu — the
same helper every CLI front door uses — so pytest and the shell harnesses
share one copy of the isolation logic.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reporter_tpu.utils.runtime import force_virtual_cpu  # noqa: E402

force_virtual_cpu(8)
# child processes spawned by tests (pipeline stages, multihost workers)
# inherit the decision instead of re-probing the chip. Unconditional: a
# pre-set =accel in the operator's shell must not leak into test children
os.environ["REPORTER_TPU_PLATFORM"] = "cpu"
os.environ["REPORTER_TPU_VIRTUAL_DEVICES"] = "8"

import jax  # noqa: E402
import pytest  # noqa: E402

# fail loudly if the force-to-CPU mechanism ever stops working; tests must
# never contend for the single real TPU chip (bench.py owns it)
assert jax.default_backend() == "cpu", (
    "tests must run on the CPU backend, got " + jax.default_backend())


@pytest.fixture(autouse=True)
def _racecheck_gate():
    """The witness-armed CI leg (REPORTER_TPU_LOCKCHECK=1): any RC
    finding the runtime lock witness / guarded-state audit records
    fails the test that surfaced it — zero findings is the contract,
    same as the static suite's empty baseline. Disarmed runs pay one
    flag check per test. Findings are reset after reporting so one
    race does not cascade into every later test."""
    yield
    from reporter_tpu.utils import locks
    if not locks.armed():
        return
    from reporter_tpu.analysis import racecheck
    lines = racecheck.render()
    if lines:
        racecheck.reset()
        pytest.fail("runtime concurrency findings:\n"
                    + "\n".join(lines), pytrace=False)
