#!/usr/bin/env bash
# Live smoke test: fire canned /report requests at a running service,
# 4-way parallel, fail-fast (equivalent of reference tests/live.sh:21-32).
#
# Usage: REPORTER_URL=http://host:8002/report tests/live.sh [graph.npz]
# With a graph argument, request bodies are synthesised against that graph
# so segment ids actually resolve; otherwise the default synthetic city
# matching `python -m reporter_tpu serve` on a build-synth config is used.
set -euo pipefail
GRAPH_ARGS=()
# resolve a relative graph path against the caller's cwd before cd-ing
if [ "$#" -ge 1 ]; then GRAPH_ARGS=(--graph "$(realpath "$1")"); fi
cd "$(dirname "$0")/.."
. tests/env.sh

WORK=$(mktemp -d)
trap 'rm -rf "${WORK}"' EXIT

echo "[live] synthesising canned request bodies"
python -m reporter_tpu synth "${GRAPH_ARGS[@]}" --traces 8 --seed 11 \
    --format json > "${WORK}/bodies.jsonl"

post_one() {
  # curl-equivalent in stdlib python: POST one body, require HTTP 200 and
  # a datastore block in the response
  python - "$1" <<'EOF'
import json, sys, urllib.request
body = sys.argv[1].encode()
req = urllib.request.Request(
    __import__("os").environ["REPORTER_URL"], data=body,
    headers={"Content-Type": "application/json"})
with urllib.request.urlopen(req, timeout=180) as resp:
    assert resp.status == 200, resp.status
    out = json.loads(resp.read())
assert "datastore" in out, out
EOF
}

# warm the service (first request pays XLA compile, ~20-40s on TPU)
echo "[live] warmup request"
post_one "$(head -1 "${WORK}/bodies.jsonl")"

echo "[live] POSTing to ${REPORTER_URL} (4-way parallel, fail-fast)"

FAIL=0
PIDS=()
while IFS= read -r BODY; do
  post_one "${BODY}" &
  PIDS+=("$!")
  if [ "${#PIDS[@]}" -ge 4 ]; then
    for PID in "${PIDS[@]}"; do wait "${PID}" || FAIL=1; done
    PIDS=()
    [ "${FAIL}" -eq 0 ] || { echo "[live] FAIL"; exit 1; }
  fi
done < "${WORK}/bodies.jsonl"
for PID in "${PIDS[@]:-}"; do
  if [ -n "${PID}" ]; then wait "${PID}" || FAIL=1; fi
done
[ "${FAIL}" -eq 0 ] || { echo "[live] FAIL"; exit 1; }
echo "[live] PASS"
