"""Cross-batch route pair cache: correctness under eviction, cache-on ==
cache-off results, and metrics counters.

The pair LRU (graph/route.py RouteCache) keys the node-to-node route
kernel on (edge_from, edge_to) and reapplies offset
arithmetic, turn penalties and the time-admissibility check per query —
so a hit must be bit-identical to a recompute, at ANY capacity (eviction
only costs recomputes, never correctness).
"""
import numpy as np
import pytest

from reporter_tpu.core.geo import equirectangular_m
from reporter_tpu.core.tracebatch import TraceBatch
from reporter_tpu.graph.route import RouteCache, candidate_route_matrices
from reporter_tpu.graph.spatial import SpatialGrid
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.synth import build_grid_city, generate_trace
from reporter_tpu.utils import metrics


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=9)


def _trace_tensors(city, grid, tr, K=6):
    lat = np.array([p["lat"] for p in tr.points])
    lon = np.array([p["lon"] for p in tr.points])
    tm = np.array([p["time"] for p in tr.points], dtype=float)
    cands = grid.candidates(lat, lon, K, 50.0)
    gc = np.atleast_1d(equirectangular_m(lat[:-1], lon[:-1],
                                         lat[1:], lon[1:])).astype(np.float32)
    return cands, gc, np.diff(tm)


def _traces(city, n, seed=21):
    rng = np.random.default_rng(seed)
    out = []
    while len(out) < n:
        tr = generate_trace(city, f"rc-{len(out)}", rng, noise_m=4.0,
                            min_route_edges=3, max_route_edges=10)
        if tr is not None and len(tr.points) >= 3:
            out.append(tr)
    return out


def test_pair_cache_matches_uncached_at_any_capacity(city):
    grid = SpatialGrid(city, cell_m=75.0)
    traces = _traces(city, 6)
    kwargs = dict(backward_tolerance_m=25.0, max_route_time_factor=2.0,
                  min_time_bound_s=15.0, turn_penalty_factor=120.0)
    tensors = [_trace_tensors(city, grid, tr) for tr in traces]
    want = [candidate_route_matrices(city, c, gc, cache=None, dt=dt,
                                     **kwargs)
            for c, gc, dt in tensors]
    for max_pairs in (1, 7, 1 << 20):  # pathological .. generous
        cache = RouteCache(city, max_pairs=max_pairs)
        for _round in range(2):  # second round re-reads cached pairs
            for (c, gc, dt), w in zip(tensors, want):
                got = candidate_route_matrices(city, c, gc, cache=cache,
                                               dt=dt, **kwargs)
                np.testing.assert_array_equal(got, w)
        assert len(cache._pairs) <= max_pairs  # eviction bound holds
    assert cache.pair_hits > 0


def test_node_cache_lru_bound(city):
    cache = RouteCache(city, max_nodes=3)
    for node in range(8):
        cache.distances_from(node, 500.0)
    assert len(cache._cache) <= 3
    # evicted entries recompute correctly
    d = cache.distances_from(0, 500.0)
    assert d[0] == (0.0, 0.0)


def test_cache_counters_reach_metrics(city):
    metrics.default.reset()
    grid = SpatialGrid(city, cell_m=75.0)
    (tr,) = _traces(city, 1, seed=5)
    c, gc, dt = _trace_tensors(city, grid, tr)
    cache = RouteCache(city)
    candidate_route_matrices(city, c, gc, cache=cache, dt=dt,
                             max_route_time_factor=2.0)
    candidate_route_matrices(city, c, gc, cache=cache, dt=dt,
                             max_route_time_factor=2.0)
    counters = metrics.snapshot()["counters"]
    assert counters.get("route.cache.pair_misses", 0) > 0
    assert counters.get("route.cache.pair_hits", 0) > 0
    # flush is delta-based: totals match the cache's own counts
    assert counters["route.cache.pair_hits"] == cache.pair_hits
    assert counters["route.cache.pair_misses"] == cache.pair_misses


def test_segment_ids_identical_cache_on_off_128_traces(city):
    """ISSUE acceptance: a 128-trace synthetic-city run through the numpy
    matcher produces identical segment IDs with the cross-batch cache
    warm (second pass over the same traces) and with it effectively off
    (capacity 1 — every lookup evicted immediately)."""
    traces = _traces(city, 128, seed=33)
    reqs = []
    for tr in traces:
        r = tr.request_json()
        r["trace"] = tr.points[:16]
        r["match_options"] = {"mode": "auto", "report_levels": [0, 1, 2],
                              "transition_levels": [0, 1, 2]}
        reqs.append(r)
    tb = TraceBatch.from_requests(reqs)

    def seg_ids(matches):
        return [[s.get("segment_id") for s in m["segments"]]
                for m in matches]

    m_on = SegmentMatcher(net=city, params=MatchParams(),
                          use_native=False)
    first = seg_ids(m_on.match_many(tb))
    warm = seg_ids(m_on.match_many(tb))  # cross-batch: cache fully warm
    assert warm == first
    assert m_on.route_cache.pair_hits > 0, "second pass must hit the cache"

    m_off = SegmentMatcher(net=city, params=MatchParams(),
                           use_native=False)
    m_off._route_cache = RouteCache(city, max_nodes=1, max_pairs=1)
    off = seg_ids(m_off.match_many(tb))
    assert off == first
