"""Durable streaming state: snapshot/restore roundtrip, corruption
handling, and crash-resume through the full worker topology (the
durability upgrade over the reference's in-memory-only state stores,
reference: BatchingProcessor.java:20-22, AnonymisingProcessor.java:47-59)."""
import numpy as np
import pytest

from reporter_tpu.core.types import Point, Segment
from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
from reporter_tpu.streaming.batcher import Batch, PointBatcher
from reporter_tpu.streaming.state import (StateStore, restore_bytes,
                                          snapshot_bytes)


def _batcher():
    return PointBatcher(lambda trace: None, lambda key, seg: None)


def _anonymiser(tmp_path):
    return Anonymiser(TileSink(str(tmp_path / "tiles")), privacy=1,
                      quantisation=3600)


def _seg(i=1, n=2, t0=1000.0):
    return Segment(id=i, next_id=n, min=t0, max=t0 + 30.0, length=500,
                   queue=0)


class TestSnapshotRoundtrip:
    def test_batches_and_slices_survive(self, tmp_path):
        b, a = _batcher(), _anonymiser(tmp_path)
        batch = Batch(Point(lat=14.6, lon=121.0, accuracy=10, time=100))
        batch.update(Point(lat=14.61, lon=121.01, accuracy=12, time=160))
        batch.last_update = 160000
        b.store["veh-1"] = batch
        a.process("1 2", _seg())
        assert a.slices and a.slice_of

        b2, a2 = _batcher(), _anonymiser(tmp_path)
        restore_bytes(snapshot_bytes(b, a), b2, a2)
        assert set(b2.store) == {"veh-1"}
        got = b2.store["veh-1"]
        assert got.last_update == 160000
        assert got.max_separation == pytest.approx(batch.max_separation)
        assert [p.to_bytes() for p in got.points] == \
            [p.to_bytes() for p in batch.points]
        assert {k: [s.to_bytes() for s in v] for k, v in a2.slices.items()} \
            == {k: [s.to_bytes() for s in v] for k, v in a.slices.items()}
        assert a2.slice_of == a.slice_of

    def test_empty_state_roundtrips(self, tmp_path):
        b, a = _batcher(), _anonymiser(tmp_path)
        b2, a2 = _batcher(), _anonymiser(tmp_path)
        restore_bytes(snapshot_bytes(b, a), b2, a2)
        assert not b2.store and not a2.slices


class TestStateStore:
    def test_restore_missing_file_is_fresh_start(self, tmp_path):
        store = StateStore(str(tmp_path / "state.bin"))
        assert store.restore(_batcher(), _anonymiser(tmp_path)) is False

    def test_save_then_restore(self, tmp_path):
        path = str(tmp_path / "state.bin")
        b, a = _batcher(), _anonymiser(tmp_path)
        b.store["u"] = Batch(Point(lat=1.0, lon=2.0, accuracy=5, time=7))
        StateStore(path).save(b, a)

        b2, a2 = _batcher(), _anonymiser(tmp_path)
        assert StateStore(path).restore(b2, a2) is True
        assert "u" in b2.store

    def test_corrupt_snapshot_discarded(self, tmp_path):
        path = tmp_path / "state.bin"
        path.write_bytes(b"RTS1garbage")
        assert StateStore(str(path)).restore(
            _batcher(), _anonymiser(tmp_path)) is False

    def test_truncated_snapshot_discarded(self, tmp_path):
        b, a = _batcher(), _anonymiser(tmp_path)
        b.store["u"] = Batch(Point(lat=1.0, lon=2.0, accuracy=5, time=7))
        a.process("1 2", _seg())
        raw = snapshot_bytes(b, a)
        path = tmp_path / "state.bin"
        path.write_bytes(raw[:len(raw) // 2])
        b2, a2 = _batcher(), _anonymiser(tmp_path)
        assert StateStore(str(path)).restore(b2, a2) is False
        # clean-discard semantics: nothing half-restored is left behind
        assert not b2.store and not a2.slices and not a2.slice_of

    def test_maybe_save_respects_interval(self, tmp_path):
        now = [0.0]
        store = StateStore(str(tmp_path / "s.bin"), interval_s=30.0,
                           clock=lambda: now[0])
        b, a = _batcher(), _anonymiser(tmp_path)
        assert store.maybe_save(b, a) is False
        now[0] = 31.0
        assert store.maybe_save(b, a) is True
        assert store.maybe_save(b, a) is False


class TestWorkerCrashResume:
    def test_open_batches_survive_a_restart(self, tmp_path):
        """Feed half a trace, 'crash' (no drain), restart from the
        snapshot, feed the rest — reports must still fire, which can only
        happen if the open batch crossed the restart."""
        from reporter_tpu.service.server import ReporterService
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.streaming.formatter import Formatter
        from reporter_tpu.streaming.worker import StreamWorker, \
            inproc_submitter
        from reporter_tpu.synth import build_grid_city, generate_trace

        city = build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=5,
                               service_road_fraction=0.0,
                               internal_fraction=0.0)
        service = ReporterService(SegmentMatcher(net=city),
                                  threshold_sec=15, max_wait_ms=1.0)
        rng = np.random.default_rng(3)
        tr = None
        while tr is None:
            tr = generate_trace(city, "veh", rng, noise_m=3.0,
                                min_route_edges=10)
        lines = [f"veh|{p['lat']}|{p['lon']}|{p['time']}|{p['accuracy']}"
                 for p in tr.points]
        fmt = ",sv,\\|,0,1,2,3,4"
        out = str(tmp_path / "results")
        state_path = str(tmp_path / "state.bin")

        def make_worker():
            # levels 0,1,2 like tests/env.sh: the grid city's streets are
            # mostly level 2, and honest complete-traversal reporting (no
            # fabricated completes) means level-2 exclusion can zero out
            # this short trace's reports
            return StreamWorker(
                Formatter.from_config(fmt), inproc_submitter(service),
                Anonymiser(TileSink(out), privacy=1, quantisation=3600,
                           source="t"),
                reports="0,1,2", transitions="0,1,2",
                flush_interval_s=1e9,
                state=StateStore(state_path, interval_s=0.0))

        w1 = make_worker()
        assert w1.restored is False
        half = len(lines) // 4  # not enough points to have reported yet
        for line in lines[:half]:
            w1.offer(line)
        # snapshot happened via maybe_save (interval 0); simulate crash: no
        # drain, worker dropped
        assert w1.processed == half

        w2 = make_worker()
        assert w2.restored is True
        assert "veh" in w2.batcher.store
        assert len(w2.batcher.store["veh"].points) == half
        for line in lines[half:]:
            w2.offer(line)
        w2.drain()

        import os
        tile_files = [os.path.join(r, f)
                      for r, _d, fs in os.walk(out) for f in fs]
        assert tile_files, "no tiles written after resume"
