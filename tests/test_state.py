"""Durable streaming state: snapshot/restore roundtrip, corruption
handling, and crash-resume through the full worker topology (the
durability upgrade over the reference's in-memory-only state stores,
reference: BatchingProcessor.java:20-22, AnonymisingProcessor.java:47-59)."""
import numpy as np
import pytest

from reporter_tpu.core.types import Point, Segment
from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
from reporter_tpu.streaming.batcher import Batch, PointBatcher
from reporter_tpu.streaming.state import (StateStore, restore_bytes,
                                          snapshot_bytes)


def _batcher():
    return PointBatcher(lambda trace: None, lambda key, seg: None)


def _anonymiser(tmp_path):
    return Anonymiser(TileSink(str(tmp_path / "tiles")), privacy=1,
                      quantisation=3600)


def _seg(i=1, n=2, t0=1000.0):
    return Segment(id=i, next_id=n, min=t0, max=t0 + 30.0, length=500,
                   queue=0)


class TestSnapshotRoundtrip:
    def test_batches_and_slices_survive(self, tmp_path):
        b, a = _batcher(), _anonymiser(tmp_path)
        batch = Batch(Point(lat=14.6, lon=121.0, accuracy=10, time=100))
        batch.update(Point(lat=14.61, lon=121.01, accuracy=12, time=160))
        batch.last_update = 160000
        batch.retries = 1
        b.store["veh-1"] = batch
        b.pending["veh-1"] = None
        a.process("1 2", _seg())
        a.flush_epoch = 7
        assert a.slices and a.slice_of

        b2, a2 = _batcher(), _anonymiser(tmp_path)
        restore_bytes(snapshot_bytes(b, a), b2, a2)
        assert set(b2.store) == {"veh-1"}
        got = b2.store["veh-1"]
        assert got.last_update == 160000
        assert got.retries == 1
        assert got.max_separation == pytest.approx(batch.max_separation)
        assert [p.to_bytes() for p in got.points] == \
            [p.to_bytes() for p in batch.points]
        assert list(b2.pending) == ["veh-1"]
        assert {k: [s.to_bytes() for s in v] for k, v in a2.slices.items()} \
            == {k: [s.to_bytes() for s in v] for k, v in a.slices.items()}
        assert a2.slice_of == a.slice_of
        assert a2.flush_epoch == 7

    def test_points_roundtrip_losslessly(self):
        """The f32 wire format is the value domain: a restored point is
        bit-equal to its never-snapshotted twin (crash/restore output
        parity depends on this — chaos kill_restore scenario)."""
        p = Point(lat=14.600001234, lon=121.0000056789, accuracy=10,
                  time=100)
        q = Point.from_bytes(p.to_bytes())
        assert (q.lat, q.lon, q.accuracy, q.time) == \
            (p.lat, p.lon, p.accuracy, p.time)

    def test_empty_state_roundtrips(self, tmp_path):
        b, a = _batcher(), _anonymiser(tmp_path)
        b2, a2 = _batcher(), _anonymiser(tmp_path)
        restore_bytes(snapshot_bytes(b, a), b2, a2)
        assert not b2.store and not a2.slices


class TestStateStore:
    def test_restore_missing_file_is_fresh_start(self, tmp_path):
        store = StateStore(str(tmp_path / "state.bin"))
        assert store.restore(_batcher(), _anonymiser(tmp_path)) is False

    def test_save_then_restore(self, tmp_path):
        path = str(tmp_path / "state.bin")
        b, a = _batcher(), _anonymiser(tmp_path)
        b.store["u"] = Batch(Point(lat=1.0, lon=2.0, accuracy=5, time=7))
        StateStore(path).save(b, a)

        b2, a2 = _batcher(), _anonymiser(tmp_path)
        assert StateStore(path).restore(b2, a2) is True
        assert "u" in b2.store

    def test_corrupt_snapshot_discarded(self, tmp_path):
        path = tmp_path / "state.bin"
        path.write_bytes(b"RTS1garbage")
        assert StateStore(str(path)).restore(
            _batcher(), _anonymiser(tmp_path)) is False

    def test_truncated_snapshot_discarded(self, tmp_path):
        b, a = _batcher(), _anonymiser(tmp_path)
        b.store["u"] = Batch(Point(lat=1.0, lon=2.0, accuracy=5, time=7))
        a.process("1 2", _seg())
        raw = snapshot_bytes(b, a)
        path = tmp_path / "state.bin"
        path.write_bytes(raw[:len(raw) // 2])
        b2, a2 = _batcher(), _anonymiser(tmp_path)
        assert StateStore(str(path)).restore(b2, a2) is False
        # clean-discard semantics: nothing half-restored is left behind
        assert not b2.store and not a2.slices and not a2.slice_of

    def test_marker_survives_lost_snapshot_and_seeds_epoch(self, tmp_path):
        """A dead snapshot with a live .epoch marker must not restart
        tile numbering at 0 — epoch-named files up to the marker are
        committed at the sink and would be overwritten with new data."""
        store = StateStore(str(tmp_path / "state.bin"))
        store.commit_epoch(4)
        b, a = _batcher(), _anonymiser(tmp_path)
        assert store.restore(b, a) is False
        assert a.flush_epoch == 5
        # corrupt snapshot path seeds identically
        (tmp_path / "state.bin").write_bytes(b"RTS1garbage")
        b2, a2 = _batcher(), _anonymiser(tmp_path)
        assert StateStore(str(tmp_path / "state.bin")).restore(b2, a2) \
            is False
        assert a2.flush_epoch == 5

    def test_v1_snapshot_discarded_as_no_snapshot(self, tmp_path):
        """A pre-epoch (v1) snapshot predates the exactly-once machinery:
        it is discarded like corruption, not half-interpreted."""
        import struct
        path = tmp_path / "state.bin"
        path.write_bytes(struct.pack("<4sIQ", b"RTS1", 1, 0)
                         + struct.pack("<I", 0) * 3)
        assert StateStore(str(path)).restore(
            _batcher(), _anonymiser(tmp_path)) is False

    def test_maybe_save_respects_interval(self, tmp_path):
        now = [0.0]
        store = StateStore(str(tmp_path / "s.bin"), interval_s=30.0,
                           clock=lambda: now[0])
        b, a = _batcher(), _anonymiser(tmp_path)
        assert store.maybe_save(b, a) is False
        now[0] = 31.0
        assert store.maybe_save(b, a) is True
        assert store.maybe_save(b, a) is False


class TestFlushEpochExactlyOnce:
    """The crash-between-egress-and-snapshot window (ISSUE 5): tiles
    reached the sink, the committed-epoch marker landed, the snapshot
    did NOT — restore must skip the epoch instead of double-emitting."""

    def _tiles(self, out):
        import os
        return sorted(os.path.join(r, f)
                      for r, _d, fs in os.walk(out)
                      for f in fs if ".deadletter" not in r)

    def test_crash_after_commit_before_save_skips_epoch(self, tmp_path):
        out = tmp_path / "tiles"
        path = str(tmp_path / "state.bin")
        b, a = _batcher(), Anonymiser(TileSink(str(out)), privacy=1,
                                      quantisation=3600)
        a.process("1 2", _seg())
        store = StateStore(path)
        store.save(b, a)                      # pre-flush snapshot: epoch 0
        epoch = a.flush_epoch
        assert a.punctuate() == 1             # tiles egress as epoch 0
        store.commit_epoch(epoch)             # durable "epoch 0 done"
        tiles = self._tiles(out)
        assert len(tiles) == 1 and tiles[0].endswith(".e00000000")
        # CRASH here: store.save never runs

        b2, a2 = _batcher(), Anonymiser(TileSink(str(out)), privacy=1,
                                        quantisation=3600)
        assert StateStore(path).restore(b2, a2) is True
        assert not a2.slices and not a2.slice_of, \
            "already-egressed slices must be skipped on restore"
        assert a2.flush_epoch == 1
        a2.punctuate()                        # must be a no-op
        assert self._tiles(out) == tiles, "no duplicate tiles"

    def test_crash_before_commit_reemits_same_file_name(self, tmp_path):
        """The other half of the window: egress done (or half-done) but
        the marker missing — restore re-emits epoch 0 under the SAME
        deterministic name, so the file sink overwrites byte-identically
        instead of duplicating (the reference's uuid4 names duplicated)."""
        out = tmp_path / "tiles"
        path = str(tmp_path / "state.bin")
        b, a = _batcher(), Anonymiser(TileSink(str(out)), privacy=1,
                                      quantisation=3600)
        a.process("1 2", _seg())
        StateStore(path).save(b, a)
        a.punctuate()                         # tiles hit the sink...
        before = self._tiles(out)
        # ...CRASH before commit_epoch and save

        b2, a2 = _batcher(), Anonymiser(TileSink(str(out)), privacy=1,
                                        quantisation=3600)
        assert StateStore(path).restore(b2, a2) is True
        assert a2.slices and a2.flush_epoch == 0
        assert a2.punctuate() == 1            # re-emit, same epoch
        after = self._tiles(out)
        assert after == before, "re-emit must overwrite, not duplicate"

    def test_pre_egress_barrier_makes_report_trims_durable(self, tmp_path):
        """The three-step flush protocol's step 1: the snapshot taken
        BEFORE egress carries the report trims and the emptied pending
        set, so a crash after commit_epoch cannot restore untrimmed
        batches that would re-report (and re-emit) segments the sink
        already has."""
        response = {"shape_used": 5, "datastore": {"reports": [{
            "id": 1, "next_id": 2, "t0": 1000.0, "t1": 1030.0,
            "length": 500, "queue_length": 0}]}}
        out = tmp_path / "tiles"
        a = Anonymiser(TileSink(str(out)), privacy=1, quantisation=3600)
        b = PointBatcher(lambda t: None,
                         lambda key, seg: a.process(key, seg),
                         submit_many=lambda tb: [response] * len(tb))
        for i in range(12):
            b.process("veh", Point(lat=14.6 + i * 0.001, lon=121.0,
                                   accuracy=10, time=1000 + i * 10),
                      stream_time_ms=(1000 + i * 10) * 1000)
        assert "veh" in b.pending
        # the worker's _flush_tiles sequence, crashing before the
        # post-flush save:
        b.flush_pending()                     # reports fire, batch trims
        assert len(b.store["veh"].points) == 7
        store = StateStore(str(tmp_path / "state.bin"))
        store.save(b, a)                      # step 1: pre-egress barrier
        epoch = a.flush_epoch
        assert a.punctuate() == 1             # step 2: egress
        store.commit_epoch(epoch)             # step 3: marker
        # CRASH — post-flush save never runs

        a2 = Anonymiser(TileSink(str(out)), privacy=1, quantisation=3600)
        b2 = PointBatcher(lambda t: None, lambda k, s: None)
        assert StateStore(str(tmp_path / "state.bin")).restore(b2, a2)
        assert not a2.slices, "egressed slices skipped"
        assert len(b2.store["veh"].points) == 7, \
            "restored batch must carry the trim, not the full window"
        assert not b2.pending, "consumed report must not be re-pending"

    def test_normal_flush_then_save_does_not_skip(self, tmp_path):
        out = tmp_path / "tiles"
        path = str(tmp_path / "state.bin")
        b, a = _batcher(), Anonymiser(TileSink(str(out)), privacy=1,
                                      quantisation=3600)
        a.process("1 2", _seg())
        store = StateStore(path)
        epoch = a.flush_epoch
        a.punctuate()
        store.commit_epoch(epoch)
        store.save(b, a)                      # the healthy ordering
        a.process("1 2", _seg(t0=2000.0))     # new post-flush state
        store.save(b, a)

        b2, a2 = _batcher(), Anonymiser(TileSink(str(out)), privacy=1,
                                        quantisation=3600)
        assert StateStore(path).restore(b2, a2) is True
        assert a2.slices, "post-flush slices must survive restore"
        assert a2.flush_epoch == 1


class TestWorkerCrashResume:
    def test_open_batches_survive_a_restart(self, tmp_path):
        """Feed half a trace, 'crash' (no drain), restart from the
        snapshot, feed the rest — reports must still fire, which can only
        happen if the open batch crossed the restart."""
        from reporter_tpu.service.server import ReporterService
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.streaming.formatter import Formatter
        from reporter_tpu.streaming.worker import StreamWorker, \
            inproc_submitter
        from reporter_tpu.synth import build_grid_city, generate_trace

        city = build_grid_city(rows=10, cols=10, spacing_m=200.0, seed=5,
                               service_road_fraction=0.0,
                               internal_fraction=0.0)
        service = ReporterService(SegmentMatcher(net=city),
                                  threshold_sec=15, max_wait_ms=1.0)
        rng = np.random.default_rng(3)
        tr = None
        while tr is None:
            tr = generate_trace(city, "veh", rng, noise_m=3.0,
                                min_route_edges=10)
        lines = [f"veh|{p['lat']}|{p['lon']}|{p['time']}|{p['accuracy']}"
                 for p in tr.points]
        fmt = ",sv,\\|,0,1,2,3,4"
        out = str(tmp_path / "results")
        state_path = str(tmp_path / "state.bin")

        def make_worker():
            # levels 0,1,2 like tests/env.sh: the grid city's streets are
            # mostly level 2, and honest complete-traversal reporting (no
            # fabricated completes) means level-2 exclusion can zero out
            # this short trace's reports
            return StreamWorker(
                Formatter.from_config(fmt), inproc_submitter(service),
                Anonymiser(TileSink(out), privacy=1, quantisation=3600,
                           source="t"),
                reports="0,1,2", transitions="0,1,2",
                flush_interval_s=1e9,
                state=StateStore(state_path, interval_s=0.0))

        w1 = make_worker()
        assert w1.restored is False
        half = len(lines) // 4  # not enough points to have reported yet
        for line in lines[:half]:
            w1.offer(line)
        # snapshot happened via maybe_save (interval 0); simulate crash: no
        # drain, worker dropped
        assert w1.processed == half

        w2 = make_worker()
        assert w2.restored is True
        assert "veh" in w2.batcher.store
        assert len(w2.batcher.store["veh"].points) == half
        for line in lines[half:]:
            w2.offer(line)
        w2.drain()

        import os
        tile_files = [os.path.join(r, f)
                      for r, _d, fs in os.walk(out) for f in fs]
        assert tile_files, "no tiles written after resume"
