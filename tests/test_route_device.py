"""ISSUE 16: device-resident batched route costs.

The route-cost stage has three implementations that must agree to the
byte: the chunk-batched device relax (ops/route_relax.py +
graph/route_device.py), the numpy host Dijkstra
(graph.route.candidate_route_matrices) and the native memo
(rt_route_matrices / rt_prepare_batch's route_step). These tests pin:

- edge-semantics parity on crafted candidate sets — unroutable
  (bound-exceeded) pairs, zero-length same-edge pairs, backward jitter
  within/without tolerance, time-capped transitions, padding — identical
  verdicts AND identical bytes across all three paths;
- chunk-level parity through ``prepare_batch(route_kernel=...)``,
  including pow2/mesh filler rows and the dead trailing step;
- report bytes identical with the device kernel on vs off
  (REPORTER_TPU_ROUTE_DEVICE), the acceptance contract;
- the ABI-14 native additions: the ``dt`` output tensor and
  ``skip_routes``;
- FLASH-style candidate pruning (REPORTER_TPU_ROUTE_PRUNE_SIGMA):
  C++ prune == numpy prune, the best candidate always survives, and a
  malformed spec degrades to pruning off;
- the ``route.device`` circuit domain: forced non-convergence
  (REPORTER_TPU_ROUTE_HOPS=1) falls back to host routes byte-identically.
"""
import numpy as np
import pytest

from reporter_tpu import native
from reporter_tpu.graph.route import UNREACHABLE, candidate_route_matrices
from reporter_tpu.graph.spatial import PAD_EDGE, CandidateSet
from reporter_tpu.matcher import MatchParams, SegmentMatcher
from reporter_tpu.matcher.batchpad import bucket_length, prepare_batch
from reporter_tpu.synth import build_grid_city, generate_trace
from reporter_tpu.utils import metrics

jax = pytest.importorskip("jax")

UNREACH = np.float32(UNREACHABLE)
needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native toolchain unavailable")


@pytest.fixture(scope="module")
def city():
    return build_grid_city(rows=8, cols=8, spacing_m=200.0, seed=3)


@pytest.fixture(scope="module")
def kernel(city):
    from reporter_tpu.graph.route_device import DeviceRouteKernel
    return DeviceRouteKernel(city)


def _reqs(city, n=6, seed=11, max_pts=48):
    rng = np.random.default_rng(seed)
    out = []
    while len(out) < n:
        tr = generate_trace(city, f"rd-{len(out)}", rng, noise_m=4.0,
                            min_route_edges=4, max_route_edges=20)
        if tr is None or len(tr.points) < 4:
            continue
        tr.points = tr.points[:max_pts]
        out.append({"uuid": tr.uuid, "trace": tr.points,
                    "match_options": {"mode": "auto",
                                      "report_levels": [0, 1, 2],
                                      "transition_levels": [0, 1, 2]}})
    return out


def _report_bytes(m, reqs):
    from reporter_tpu.service.report import report_json
    return [report_json(match, req, 15, {0, 1, 2}, {0, 1, 2})
            for match, req in zip(m.match_many(reqs), reqs)]


def _pick_edges(city):
    """(e0, e1, e_far): an edge, a continuation out of its end node that
    is not its reverse, and an edge starting far (> the 500 m floor)
    from e0's end node."""
    e_start = np.asarray(city.edge_start)
    e_end = np.asarray(city.edge_end)
    e_len = np.asarray(city.edge_length_m)
    e0 = int(np.argmax(e_len >= 60.0))
    nxt = np.flatnonzero(e_start == e_end[e0])
    e1 = int(nxt[0] if e_end[nxt[0]] != e_start[e0] else nxt[-1])
    lat = np.asarray(city.node_lat)
    lon = np.asarray(city.node_lon)
    d2 = (lat[e_start] - lat[e_end[e0]]) ** 2 \
        + (lon[e_start] - lon[e_end[e0]]) ** 2
    e_far = int(np.argmax(d2))
    return e0, e1, e_far


def _crafted(city):
    """A hand-built (T=4, K=2) candidate set covering the emit ladder:
    backward-within-tolerance, same-edge forward, an adjacent routable
    pair, bound-exceeded (unroutable) far pairs, and a padding slot."""
    e0, e1, e_far = _pick_edges(city)
    edge = np.array([[e0, e0],
                     [e0, e1],
                     [e_far, PAD_EDGE],
                     [e0, e1]], dtype=np.int32)
    offset = np.array([[50.0, 10.0],
                       [30.0, 30.0],
                       [5.0, 0.0],
                       [20.0, 40.0]], dtype=np.float32)
    z = np.zeros_like(offset)
    cands = CandidateSet(edge_ids=edge, dist_m=z + 1.0, offset_m=offset,
                         proj_x=z, proj_y=z)
    # small gc -> every per-step bound is the 500 m floor
    gc = np.array([30.0, 40.0, 30.0], dtype=np.float32)
    return cands, gc


class TestEdgeSemantics:
    """Crafted pairs: identical verdicts and bytes across all paths."""

    def _all_paths(self, city, kernel, cands, gc, **kw):
        # the three implementations default backward_tolerance_m and
        # min_time_bound_s differently (serving passes params
        # explicitly); pin them so the parity compares like with like
        kw.setdefault("backward_tolerance_m", 25.0)
        dev = kernel.route_matrices(cands, gc, **kw)
        host = candidate_route_matrices(city, cands, gc, **kw)
        outs = [dev, host]
        if native.available():
            rt = native.NativeRuntime(city)
            outs.append(rt.route_matrices(cands, gc, **kw))
        for other in outs[1:]:
            assert np.array_equal(outs[0], other)
        return dev

    def test_distance_semantics(self, city, kernel):
        cands, gc = _crafted(city)
        e0 = int(cands.edge_ids[0, 0])
        route = self._all_paths(city, kernel, cands, gc)
        # (e0,50)->(e0,30): 20 m backward <= 25 m tolerance -> free
        assert route[0, 0, 0] == 0.0
        # (e0,10)->(e0,30): same-edge forward -> exactly the 20 m delta
        assert route[0, 1, 0] == np.float32(20.0)
        # (e0,50)->(e1,30): continuation out of e0's end node -> the
        # f32 path-order sum remaining + ob (+ 0 network meters)
        e_len0 = np.float32(np.asarray(city.edge_length_m)[e0])
        want = np.float32(np.float32(e_len0 - np.float32(50.0))
                          + np.float32(30.0))
        assert route[0, 0, 1] == want
        # t1 -> t2: e_far starts > 500 m (the bound floor) away ->
        # unroutable; the padding candidate column is unreachable too
        assert (route[1] == UNREACH).all()
        # t2 -> t3: from e_far (far pair) and from the pad slot
        assert (route[2] == UNREACH).all()

    def test_backward_beyond_tolerance_prices_as_loop(self, city, kernel):
        """40 m backward on one edge exceeds the 25 m tolerance: the
        pair prices as the general loop path — finite (the grid has a
        reverse edge) but never the free backward case."""
        e0, _e1, _f = _pick_edges(city)
        edge = np.array([[e0], [e0]], dtype=np.int32)
        off = np.array([[50.0], [10.0]], dtype=np.float32)
        z = np.zeros_like(off)
        cands = CandidateSet(edge, z + 1.0, off, z, z)
        gc = np.array([30.0], dtype=np.float32)
        route = self._all_paths(city, kernel, cands, gc)
        assert 0.0 < route[0, 0, 0] < UNREACH

    def test_zero_length_same_edge_pair(self, city, kernel):
        cands, gc = _crafted(city)
        off = cands.offset_m.copy()
        off[1, 0] = off[0, 0]  # (e0,50)->(e0,50): zero forward progress
        cands = CandidateSet(cands.edge_ids, cands.dist_m, off,
                             cands.proj_x, cands.proj_y)
        route = self._all_paths(city, kernel, cands, gc)
        assert route[0, 0, 0] == 0.0

    def test_time_cap_semantics(self, city, kernel):
        """A 0.1 s probe delta with a 1 s floor caps every cross-edge
        transition (hundreds of meters at street speed) while the
        zero-length same-edge pair stays free — on all three paths."""
        cands, gc = _crafted(city)
        off = cands.offset_m.copy()
        off[1, 0] = off[0, 0]
        cands = CandidateSet(cands.edge_ids, cands.dist_m, off,
                             cands.proj_x, cands.proj_y)
        dt = np.array([0.1, 0.1, 0.1], dtype=np.float64)
        route = self._all_paths(city, kernel, cands, gc, dt=dt,
                                max_route_time_factor=2.0,
                                min_time_bound_s=1.0)
        assert route[0, 0, 0] == 0.0          # zero meters, zero seconds
        assert route[0, 0, 1] == UNREACH      # adjacent hop, capped

    def test_unroutable_everything_padded(self, city, kernel):
        """An all-pad candidate set returns the all-UNREACHABLE tensor
        (the tail-fill bytes) from the device path too."""
        edge = np.full((3, 2), PAD_EDGE, dtype=np.int32)
        z = np.zeros((3, 2), dtype=np.float32)
        cands = CandidateSet(edge, z, z, z, z)
        gc = np.zeros(2, dtype=np.float32)
        route = kernel.route_matrices(cands, gc)
        assert route.shape == (2, 2, 2)
        assert (route == UNREACH).all()


@needs_native
class TestChunkParity:
    """prepare_batch: device-filled chunks byte-identical to host."""

    @pytest.fixture(scope="class")
    def matcher(self, city):
        return SegmentMatcher(net=city,
                              params=MatchParams(max_candidates=8))

    def _chunks(self, matcher, kernel, reqs, **pb_kw):
        pts = [r["trace"] for r in reqs]
        T = max(bucket_length(len(p)) for p in pts)
        host = prepare_batch(matcher.runtime, pts, matcher.params, T,
                             **pb_kw)
        dev = prepare_batch(matcher.runtime, pts, matcher.params, T,
                            route_kernel=kernel, **pb_kw)
        return host, dev

    def test_route_tensor_byte_identical(self, city, matcher, kernel):
        host, dev = self._chunks(matcher, kernel, _reqs(city))
        assert host.prep["route_m"].tobytes() \
            == dev.prep["route_m"].tobytes()
        assert np.asarray(host.route_m).tobytes() \
            == np.asarray(dev.route_m).tobytes()  # post wire-cast too
        for k in ("edge_ids", "dist_m", "offset_m", "gc_m", "case",
                  "kept_idx", "num_kept", "dt", "max_finite"):
            assert np.array_equal(host.prep[k], dev.prep[k]), k

    def test_deferred_routes_finalize_matches_sync(self, city, matcher,
                                                   kernel):
        """prepare_batch(defer_routes=True) ships the in-flight device
        tensor + a finalize closure; after finalize_wire the batch
        tensors and the prep dict are byte-identical to the synchronous
        device path (wire cast included)."""
        pts = [r["trace"] for r in _reqs(city)]
        T = max(bucket_length(len(p)) for p in pts)
        sync = prepare_batch(matcher.runtime, pts, matcher.params, T,
                             route_kernel=kernel)
        metrics.default.reset()
        deferred = prepare_batch(matcher.runtime, pts, matcher.params, T,
                                 route_kernel=kernel, defer_routes=True)
        # the sync call above warmed the node-kernel cache, so this
        # deferred chunk must have taken the fully-async dispatch path
        snap = metrics.default.snapshot()["counters"]
        assert snap.get("route.device.async_dispatch_chunks", 0) == 1
        assert deferred.finalize is not None
        assert deferred.route_m is None  # installed by finalize_wire
        deferred.finalize_wire()
        assert deferred.finalize is None
        assert np.asarray(deferred.route_m, dtype=np.float32).tobytes() \
            == np.asarray(sync.route_m, dtype=np.float32).tobytes()
        assert np.asarray(deferred.route_m).dtype \
            == np.asarray(sync.route_m).dtype  # same wire decision
        assert deferred.prep["route_m"].tobytes() \
            == sync.prep["route_m"].tobytes()
        assert np.array_equal(deferred.prep["max_finite"],
                              sync.prep["max_finite"])
        deferred.finalize_wire()  # idempotent no-op

    def test_filler_rows_skip_cleanly(self, city, matcher, kernel):
        """pow2/mesh filler rows: 5 traces padded to 8 rows — the
        device path must leave rows 5..8 exactly as the native prefill
        wrote them (all-UNREACHABLE), and the real rows byte-equal."""
        host, dev = self._chunks(matcher, kernel, _reqs(city, n=5),
                                 pad_rows=8)
        hr, dr = host.prep["route_m"], dev.prep["route_m"]
        assert hr.shape[0] == 8
        assert hr.tobytes() == dr.tobytes()
        assert (dr[5:] == UNREACH).all()

    def test_single_point_and_short_traces(self, city, matcher, kernel):
        """nk<=1 traces have no live transitions; mixed with real
        traces the device fill must reproduce the tail-fill bytes."""
        reqs = _reqs(city, n=3)
        reqs[1] = dict(reqs[1], trace=reqs[1]["trace"][:1])
        host, dev = self._chunks(matcher, kernel, reqs)
        assert host.prep["route_m"].tobytes() \
            == dev.prep["route_m"].tobytes()

    def test_dt_tensor_contract(self, city, matcher, kernel):
        """ABI 14: ``dt`` carries kept-point probe time deltas for
        t < num_kept-1 and the -1 sentinel everywhere else (including
        filler rows)."""
        host, _ = self._chunks(matcher, kernel, _reqs(city, n=3),
                               pad_rows=4)
        prep, dt = host.prep, host.prep["dt"]
        for b in range(3):
            nk = int(prep["num_kept"][b])
            view = host.traces[b]
            kept_times = view.times[prep["kept_idx"][b, :nk]]
            if nk > 1:
                assert np.array_equal(dt[b, :nk - 1],
                                      np.diff(kept_times))
            assert (dt[b, max(nk - 1, 0):] == -1.0).all()
        assert (dt[3:] == -1.0).all()

    def test_skip_routes_leaves_tail_fill(self, city, matcher, kernel):
        """skip_routes skips ONLY route_step: candidates/case/dt match a
        full prep, and route rows at/after num_kept-1 still carry the
        tail fill the device path relies on."""
        pts = [r["trace"] for r in _reqs(city, n=2)]
        T = max(bucket_length(len(p)) for p in pts)
        params = matcher.params
        pt_off = np.zeros(len(pts) + 1, dtype=np.int64)
        np.cumsum([len(p) for p in pts], out=pt_off[1:])
        lat = np.array([p["lat"] for ps in pts for p in ps])
        lon = np.array([p["lon"] for ps in pts for p in ps])
        times = np.array([p["time"] for ps in pts for p in ps])

        def prep(skip):
            return matcher.runtime.prepare_batch(
                pt_off, lat, lon, times, T, params.max_candidates,
                search_radius=params.search_radius,
                interpolation_distance=params.interpolation_distance,
                breakage_distance=params.breakage_distance,
                max_route_time_factor=params.max_route_time_factor,
                min_time_bound_s=params.min_time_bound_s,
                skip_routes=skip)

        full, skip = prep(False), prep(True)
        for k in ("edge_ids", "dist_m", "offset_m", "gc_m", "case",
                  "kept_idx", "num_kept", "dt"):
            assert np.array_equal(full[k], skip[k]), k
        for b in range(2):
            n = int(full["num_kept"][b])
            assert np.array_equal(full["route_m"][b, max(n - 1, 0):],
                                  skip["route_m"][b, max(n - 1, 0):])


@needs_native
class TestReportBytes:
    """The acceptance contract: REPORTER_TPU_ROUTE_DEVICE on/off emits
    byte-identical report bodies."""

    def test_reports_byte_identical(self, city, monkeypatch):
        reqs = _reqs(city, n=5)  # non-pow2: filler rows in play
        want = _report_bytes(SegmentMatcher(net=city), reqs)
        monkeypatch.setenv("REPORTER_TPU_ROUTE_DEVICE", "1")
        m = SegmentMatcher(net=city)
        metrics.default.reset()
        got = _report_bytes(m, reqs)
        assert got == want
        snap = metrics.default.snapshot()["counters"]
        assert snap.get("route.device.chunks", 0) > 0
        assert m.circuit_route.snapshot()["state"] == "closed"

    def test_forced_nonconvergence_falls_back(self, city, monkeypatch):
        """REPORTER_TPU_ROUTE_HOPS=1 starves the relax; the chunk must
        re-prep through host routes byte-identically and count the
        failure on the route.device circuit."""
        reqs = _reqs(city, n=4)
        want = _report_bytes(SegmentMatcher(net=city), reqs)
        monkeypatch.setenv("REPORTER_TPU_ROUTE_DEVICE", "1")
        monkeypatch.setenv("REPORTER_TPU_ROUTE_HOPS", "1")
        m = SegmentMatcher(net=city)
        metrics.default.reset()
        got = _report_bytes(m, reqs)
        assert got == want
        snap = metrics.default.snapshot()["counters"]
        assert snap.get("route.device.nonconverged", 0) > 0
        assert snap.get("route.device.fallback_chunks", 0) > 0

    def test_route_domain_registered(self):
        assert ("route.device", "circuit_route") \
            in SegmentMatcher.CIRCUIT_DOMAINS


@needs_native
class TestPruning:
    """FLASH-style candidate pruning: C++ == numpy, best candidate
    survives, prune is a sorted-suffix cut, malformed spec = off."""

    def test_native_prune_matches_numpy_prune(self, city, monkeypatch):
        """The batched C++ prune (rt_prepare_batch) and the per-trace
        numpy mirror (batchpad._prune_candidates) pick the same
        survivors and produce the same tensors."""
        monkeypatch.setenv("REPORTER_TPU_ROUTE_PRUNE_SIGMA", "1.5")
        m = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
        pts = [r["trace"] for r in _reqs(city, n=4)]
        T = max(bucket_length(len(p)) for p in pts)
        batch = prepare_batch(m.runtime, pts, m.params, T)
        for b, p in enumerate(pts):
            old, new = m.prepare(p), batch.traces[b]
            assert old.num_kept == new.num_kept
            nk = old.num_kept
            np.testing.assert_array_equal(old.edge_ids[:nk],
                                          new.edge_ids[:nk])
            np.testing.assert_allclose(old.dist_m[:nk], new.dist_m[:nk],
                                       rtol=1e-6, atol=1e-4)
            if nk > 1:
                np.testing.assert_allclose(old.route_m[:nk - 1],
                                           new.route_m[:nk - 1],
                                           rtol=1e-5, atol=1e-3)

    def test_prune_is_suffix_and_keeps_best(self, city, monkeypatch):
        monkeypatch.setenv("REPORTER_TPU_ROUTE_PRUNE_SIGMA", "0.5")
        m = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
        pts = [r["trace"] for r in _reqs(city, n=4)]
        T = max(bucket_length(len(p)) for p in pts)
        pruned = prepare_batch(m.runtime, pts, m.params, T).prep
        monkeypatch.delenv("REPORTER_TPU_ROUTE_PRUNE_SIGMA")
        full = prepare_batch(m.runtime, pts, m.params, T).prep
        margin = np.float32(0.5 * m.params.effective_sigma)
        cut_any = False
        for b in range(len(pts)):
            for t in range(int(full["num_kept"][b])):
                fe = full["edge_ids"][b, t]
                pe = pruned["edge_ids"][b, t]
                live = np.flatnonzero(pe != PAD_EDGE)
                # live slots are a prefix, and slot 0 always survives
                assert live.size >= 1 and live[-1] == live.size - 1
                assert pe[0] == fe[0]
                # survivors are exactly the within-margin prefix
                # (distance-sorted, f32 compare like the numpy mirror)
                fd = full["dist_m"][b, t]
                keep = ~(fd > fd[0] + margin) & (fe != PAD_EDGE)
                assert np.array_equal(pe != PAD_EDGE, keep)
                cut_any |= int(keep.sum()) < int((fe != PAD_EDGE).sum())
        assert cut_any  # the margin actually bit on this workload

    def test_pruned_reports_identical_across_route_paths(self, city,
                                                         monkeypatch):
        """Pruning shrinks K for BOTH route paths identically, so the
        on/off report parity must hold under it too."""
        monkeypatch.setenv("REPORTER_TPU_ROUTE_PRUNE_SIGMA", "1.0")
        reqs = _reqs(city, n=4)
        want = _report_bytes(SegmentMatcher(net=city), reqs)
        monkeypatch.setenv("REPORTER_TPU_ROUTE_DEVICE", "1")
        got = _report_bytes(SegmentMatcher(net=city), reqs)
        assert got == want

    def test_malformed_spec_disables_pruning(self, city, monkeypatch,
                                             caplog):
        import logging
        monkeypatch.setenv("REPORTER_TPU_ROUTE_PRUNE_SIGMA", "lots")
        m = SegmentMatcher(net=city, params=MatchParams(max_candidates=8))
        pts = [r["trace"] for r in _reqs(city, n=2)]
        T = max(bucket_length(len(p)) for p in pts)
        with caplog.at_level(logging.WARNING, "reporter_tpu.matcher"):
            got = prepare_batch(m.runtime, pts, m.params, T).prep
        monkeypatch.delenv("REPORTER_TPU_ROUTE_PRUNE_SIGMA")
        want = prepare_batch(m.runtime, pts, m.params, T).prep
        assert np.array_equal(got["edge_ids"], want["edge_ids"])
        assert any("ROUTE_PRUNE_SIGMA" in r.getMessage()
                   for r in caplog.records)


class TestRelaxKernel:
    """ops/route_relax.py unit contracts that parity can't see."""

    def test_relax_exact_vs_reference_dijkstra(self, city, kernel):
        """Relaxed bounded distances equal a reference float32 Dijkstra
        from the same sources (inf where the bound cuts)."""
        import heapq

        import jax.numpy as jnp

        from reporter_tpu.ops import route_relax
        e_start = np.asarray(city.edge_start)
        e_end = np.asarray(city.edge_end)
        e_len = np.asarray(city.edge_length_m, dtype=np.float32)
        n = int(city.num_nodes)
        srcs = np.array([0, n // 2, n - 1], dtype=np.int32)
        bound = np.float32(700.0)
        dist, _t, _i, conv = route_relax.relax_csr(
            kernel._e_start, kernel._e_end, kernel._e_len,
            kernel._e_secs, jnp.asarray(srcs), jnp.float32(bound),
            n_nodes=n, max_iters=n)
        assert bool(conv)
        dist = np.asarray(dist)
        adj = {}
        for e in range(len(e_start)):
            adj.setdefault(int(e_start[e]), []).append(e)
        for row, s in enumerate(srcs):
            ref = np.full(n, np.inf, dtype=np.float32)
            ref[s] = np.float32(0.0)
            heap = [(np.float32(0.0), int(s))]
            while heap:
                d, u = heapq.heappop(heap)
                if d > ref[u]:
                    continue
                for e in adj.get(u, ()):
                    nd = d + e_len[e]  # float32, the kernel's path order
                    if nd > bound:
                        continue
                    v = int(e_end[e])
                    if nd < ref[v]:
                        ref[v] = nd
                        heapq.heappush(heap, (nd, v))
            assert np.array_equal(dist[row], ref)

    def test_nonconvergence_reported(self, city, kernel):
        import jax.numpy as jnp

        from reporter_tpu.ops import route_relax
        _d, _t, _i, conv = route_relax.relax_csr(
            kernel._e_start, kernel._e_end, kernel._e_len,
            kernel._e_secs, jnp.asarray(np.array([0], dtype=np.int32)),
            jnp.float32(1e6), n_nodes=int(city.num_nodes), max_iters=1)
        assert not bool(conv)

    def test_node_kernel_cache_hits_stay_exact(self, city):
        """A warm kernel serves repeat chunks from the node-kernel cache
        (hit rows counted, no second relax of the same sources at the
        same bound) and the routes stay byte-identical to a cold
        kernel's — the monotone-bound reuse rule."""
        from reporter_tpu.graph.route_device import DeviceRouteKernel
        from reporter_tpu.utils import metrics

        cands, gc = _crafted(city)
        warm = DeviceRouteKernel(city)
        assert warm._cache_ok  # the 64-node grid fits the cache budget
        first = warm.route_matrices(cands, gc)
        metrics.default.reset()
        again = warm.route_matrices(cands, gc)
        snap = metrics.default.snapshot()["counters"]
        assert snap.get("route.device.cache_hit_rows", 0) > 0
        assert snap.get("route.device.cache_miss_rows", 0) == 0
        cold = DeviceRouteKernel(city).route_matrices(cands, gc)
        assert np.array_equal(first, again)
        assert np.array_equal(first, cold)

    def test_cache_re_relaxes_on_larger_bound(self, city):
        """A query bound above a row's cached bound must re-relax that
        row (cached rows are exact only DOWN the bound ladder)."""
        from reporter_tpu.graph.route_device import DeviceRouteKernel
        from reporter_tpu.utils import metrics

        cands, gc = _crafted(city)
        kern = DeviceRouteKernel(city)
        kern.route_matrices(cands, gc)  # cached at max(500, 5*gc)
        metrics.default.reset()
        wide = kern.route_matrices(cands, gc, min_bound_m=2000.0)
        snap = metrics.default.snapshot()["counters"]
        assert snap.get("route.device.cache_miss_rows", 0) > 0
        cold = DeviceRouteKernel(city).route_matrices(
            cands, gc, min_bound_m=2000.0)
        assert np.array_equal(wide, cold)

    def test_budget_guard_raises(self, city, kernel, monkeypatch):
        from reporter_tpu.graph import route_device
        monkeypatch.setattr(route_device, "_STATE_BUDGET_ELEMS", 8)
        out = {"edge_ids": np.zeros((1, 3, 1), dtype=np.int32),
               "num_kept": np.array([3], dtype=np.int32),
               "gc_m": np.full((1, 3), 10.0, np.float32),
               "dt": np.full((1, 3), -1.0),
               "offset_m": np.zeros((1, 3, 1), np.float32),
               "route_m": np.zeros((1, 3, 1, 1), np.float32),
               "max_finite": np.zeros(1, np.float32)}
        with pytest.raises(RuntimeError, match="over budget"):
            kernel.fill_prep(out, MatchParams(max_candidates=1), 1)


class TestProfileTable:
    """The .profile frontier-bound table round-trip."""

    def test_stats_seed_roundtrip(self, city, kernel):
        kernel.max_iters_seen = 7
        kernel.max_bound_seen = 900.0
        assert kernel.stats() == {"route_hops": 7,
                                  "route_bound_m": 900.0}
        kernel.seed_hint(7)
        assert kernel._iter_cap() == 16  # 2x hint, floored at 16
        kernel.seed_hint(40)
        assert kernel._iter_cap() == 80

    def test_hops_knob_overrides(self, city, kernel, monkeypatch):
        monkeypatch.setenv("REPORTER_TPU_ROUTE_HOPS", "33")
        assert kernel._iter_cap() == 33
        monkeypatch.setenv("REPORTER_TPU_ROUTE_HOPS", "nope")
        assert kernel._iter_cap() >= 2  # malformed -> auto, warned

    @needs_native
    def test_profile_export_carries_route_table(self, city, tmp_path,
                                                monkeypatch):
        from reporter_tpu.datastore import profile as dprofile
        monkeypatch.setenv("REPORTER_TPU_ROUTE_DEVICE", "1")
        m = SegmentMatcher(net=city)
        m.match_many(_reqs(city, n=3))
        path = str(tmp_path / "city.profile")
        art = dprofile.export_profile(m, path, city="test")
        table = art["route_table"]
        assert table is not None and table["route_hops"] > 0
        # warming a fresh matcher seeds its kernel's sweep cap
        m2 = SegmentMatcher(net=city)
        dprofile.warm_matcher(m2, dprofile.load_profile(path))
        kern = m2._device_route_kernel()
        assert kern is not None
        assert kern._hops_hint == table["route_hops"]
