"""Serving-tier tests (ISSUE 14): the cross-process writer lease (and
the torn-manifest hole it closes), the background compactor, batched
multi-segment/bbox queries (answer-identical to singles), the per-city
route-memo profile pre-warm, and the multi-tenant city-residency LRU."""
import json
import os

import numpy as np
import pytest

from reporter_tpu.core.osmlr import make_segment_id
from reporter_tpu.core.tiles import TileHierarchy
from reporter_tpu.datastore import (
    BackgroundCompactor,
    LeaseHeldElsewhere,
    LocalDatastore,
    ObservationBatch,
    export_profile,
    load_profile,
    query_bbox,
    query_many,
    warm_matcher,
)
from reporter_tpu.datastore.ingest import ingest_dir, scan_tiles
from reporter_tpu.datastore.lease import LEASE_NAME, StoreLease
from reporter_tpu.datastore.profile import PROFILE_NAME, profile_path
from reporter_tpu.datastore.query import bbox_partitions, bbox_tile_range
from reporter_tpu.utils import metrics

# Monday 2017-01-02 08:00:00 UTC -> hour-of-week 8
MON_8AM = 1483344000


def _obs(seg_ids, rng, n_obs, with_transitions=True):
    """Random observations over the given segment ids."""
    seg_arr = np.asarray(seg_ids, dtype=np.int64)
    dur = rng.uniform(5, 30, n_obs)
    return ObservationBatch(
        segment_id=rng.choice(seg_arr, size=n_obs),
        next_id=rng.choice(seg_arr, size=n_obs) if with_transitions
        else np.full(n_obs, -1, dtype=np.int64),
        duration_s=dur,
        count=np.ones(n_obs, dtype=np.int64),
        length_m=(dur * rng.uniform(3, 20, n_obs)).astype(np.int64) + 1,
        queue_m=np.zeros(n_obs, dtype=np.int64),
        min_ts=rng.integers(MON_8AM, MON_8AM + 600000, n_obs),
        max_ts=rng.integers(MON_8AM + 600000, MON_8AM + 700000, n_obs))


def _seed_store(root, seg_ids, deltas=3, n_obs=256, seed=3):
    ds = LocalDatastore(str(root))
    rng = np.random.default_rng(seed)
    for d in range(deltas):
        ds.ingest(_obs(seg_ids, rng, n_obs), ingest_key=f"seed-{d}")
    return ds


#: a live pid that is NOT this process — the foreign-holder impostor
FOREIGN_PID = os.getppid()


class TestStoreLease:
    def test_acquire_creates_file_and_fast_path(self, tmp_path):
        lease = StoreLease(str(tmp_path), ttl_s=30.0)
        assert lease.acquire()
        assert os.path.exists(lease.path)
        state = json.loads(open(lease.path).read())
        assert state["pid"] == os.getpid()
        # fast path: well inside the TTL no disk I/O happens — mangle
        # the file and acquire() must not notice
        os.unlink(lease.path)
        assert lease.acquire()
        assert not os.path.exists(lease.path)

    def test_disabled_ttl_zero_touches_nothing(self, tmp_path):
        lease = StoreLease(str(tmp_path), ttl_s=0.0)
        assert lease.acquire() and lease.held()
        assert not os.path.exists(lease.path)
        assert lease.snapshot() == {"enabled": False}

    def test_foreign_live_holder_rejected(self, tmp_path):
        other = StoreLease(str(tmp_path), ttl_s=60.0)
        other.owner_pid = FOREIGN_PID
        assert other.acquire()
        mine = StoreLease(str(tmp_path), ttl_s=60.0)
        assert not mine.acquire()
        assert not mine.held()
        with pytest.raises(LeaseHeldElsewhere):
            mine.require()

    def test_dead_holder_stolen_immediately(self, tmp_path):
        lease = StoreLease(str(tmp_path), ttl_s=60.0)
        with open(lease.path, "w") as f:
            json.dump({"pid": 999999999, "deadline": 9e18}, f)
        c0 = metrics.default.counter("datastore.lease.steals")
        assert lease.acquire()
        assert metrics.default.counter("datastore.lease.steals") == c0 + 1

    def test_expired_live_holder_stolen(self, tmp_path):
        other = StoreLease(str(tmp_path), ttl_s=60.0)
        other.owner_pid = FOREIGN_PID
        assert other.acquire()
        # expire it on disk (the holder is alive — getppid — but stale)
        with open(other.path, "w") as f:
            json.dump({"pid": FOREIGN_PID, "deadline": 1.0}, f)
        mine = StoreLease(str(tmp_path), ttl_s=60.0)
        e0 = metrics.default.counter("datastore.lease.expired")
        assert mine.acquire()
        assert metrics.default.counter("datastore.lease.expired") == e0 + 1

    def test_release_frees_for_next_holder(self, tmp_path):
        a = StoreLease(str(tmp_path), ttl_s=60.0)
        a.owner_pid = FOREIGN_PID
        assert a.acquire()
        b = StoreLease(str(tmp_path), ttl_s=60.0)
        assert not b.acquire()
        a.release()
        s0 = metrics.default.counter("datastore.lease.steals")
        assert b.acquire()
        # a released lease is vacant, not stolen
        assert metrics.default.counter("datastore.lease.steals") == s0

    def test_torn_lease_body_is_no_holder(self, tmp_path):
        lease = StoreLease(str(tmp_path), ttl_s=60.0)
        with open(lease.path, "w") as f:
            f.write('{"pid": 12')  # torn mid-write
        assert lease.acquire()

    def test_forked_child_does_not_inherit_belief(self, tmp_path):
        lease = StoreLease(str(tmp_path), ttl_s=60.0)
        assert lease.acquire()
        # simulate the fork: belief was recorded under another identity
        lease._belief_pid = 12345
        assert not lease.held()
        assert lease.acquire()  # re-acquires under its own identity

    def test_worker_drain_releases_the_lease(self, synth_city,
                                             tmp_path):
        """A CLEAN worker exit hands the lease back, so routine
        restarts acquire a vacant lease — steals stay a crash
        signal."""
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        from reporter_tpu.streaming.anonymiser import Anonymiser, TileSink
        from reporter_tpu.streaming.formatter import Formatter
        from reporter_tpu.streaming.worker import (
            StreamWorker,
            inproc_submitter,
        )
        ds = LocalDatastore(str(tmp_path / "store"))
        service = ReporterService(
            SegmentMatcher(net=synth_city, use_native=False))
        worker = StreamWorker(
            Formatter.from_config(r",sv,\|,0,1,2,3,4"),
            inproc_submitter(service),
            Anonymiser(TileSink(str(tmp_path / "out")), privacy=1,
                       quantisation=3600, source="t",
                       tee=lambda _t, segs, ingest_key=None:
                       ds.ingest_segments(segs, ingest_key=ingest_key)),
            reports="0,1,2", transitions="0,1,2",
            flush_interval_s=1e9, datastore=ds)
        from reporter_tpu.synth import generate_trace
        rng = np.random.default_rng(2)
        tr = None
        while tr is None:
            tr = generate_trace(synth_city, "rel-1", rng, noise_m=3.0,
                                min_route_edges=8)
        lines = ["|".join([tr.uuid, str(p["lat"]), str(p["lon"]),
                           str(p["time"]), str(p["accuracy"])])
                 for p in tr.points]
        worker.run(iter(lines))
        service.dispatcher.close()
        state = json.loads(open(ds.lease.path).read() or "{}")
        assert state.get("pid") is None  # released, not left to rot
        s0 = metrics.default.counter("datastore.lease.steals")
        LocalDatastore(str(tmp_path / "store")).lease.acquire()
        assert metrics.default.counter("datastore.lease.steals") == s0

    def test_snapshot_holder_view(self, tmp_path):
        lease = StoreLease(str(tmp_path), ttl_s=60.0)
        lease.acquire()
        snap = lease.snapshot()
        assert snap["enabled"] and snap["held_by_us"]
        assert snap["holder_pid"] == os.getpid()
        assert 0 < snap["expires_in_s"] <= 60.0

    def test_lease_failpoint_refuses_mutation(self, tmp_path):
        from reporter_tpu.utils import faults
        seg = make_segment_id(2, 9, 1)
        ds = _seed_store(tmp_path / "s", [seg], deltas=1, n_obs=8)
        ds.lease._deadline = 0.0  # force the slow path
        faults.configure("datastore.lease=error#1")
        try:
            with pytest.raises(Exception):
                ds.ingest(_obs([seg], np.random.default_rng(0), 4),
                          ingest_key="x")
        finally:
            faults.clear()
        # after the injected fault the store serves mutations again
        assert ds.ingest(_obs([seg], np.random.default_rng(0), 4),
                         ingest_key="x") > 0


class TestTornManifestRegression:
    """The pre-lease hole, pinned: two writers each passing their OWN
    in-process lock can interleave a compaction's commit window with an
    append — before this PR the last manifest write silently dropped
    the append's committed segment AND its exactly-once ledger key.
    Defense in depth now: the lease REFUSES the foreign mutation up
    front, and the seq fence catches any interleave that slips past it
    (lease disabled, or a holder stalled beyond its TTL) by aborting
    LOUDLY before the manifest write — the racing writer's committed
    data survives either way."""

    def _seeded(self, root, ttl):
        seg = make_segment_id(2, 44, 7)
        a = LocalDatastore(str(root))
        a.lease._ttl = ttl
        rng = np.random.default_rng(1)
        for d in range(3):
            a.ingest(_obs([seg], rng, 16, with_transitions=False),
                     ingest_key=f"seed-{d}")
        b = LocalDatastore(str(root))
        b.lease._ttl = ttl
        return a, b, seg

    def test_interleaved_commit_aborts_via_seq_fence(self, tmp_path,
                                                     monkeypatch):
        """The pre-lease hole scenario, replayed with the lease OFF:
        B's append lands inside A's compaction commit window. Before
        this PR, A's last manifest write silently dropped B's
        committed delta and ledger key; the seq fence now detects the
        moved manifest and aborts A LOUDLY — B's data survives."""
        a, b, seg = self._seeded(tmp_path / "store", ttl=0.0)  # no lease
        level, index = 2, 44
        pdir = a.partition_dir(level, index)
        delta_b = _obs([seg], np.random.default_rng(2), 8,
                       with_transitions=False)

        orig_commit = a._commit_segment

        def commit_with_race(pdir_, tmp_, name):
            orig_commit(pdir_, tmp_, name)
            # B's append lands INSIDE A's compaction commit window
            # (between A's segment rename and A's manifest write) —
            # trivially possible across processes, where A's _lock
            # means nothing to B
            assert b.ingest(delta_b, ingest_key="b-key") > 0

        monkeypatch.setattr(a, "_commit_segment", commit_with_race)
        with pytest.raises(RuntimeError, match="stale commit"):
            a._compact_partition(level, index)

        # B's committed delta and its exactly-once ledger key SURVIVE;
        # A's merged base- dir is ignorable manifest-invisible garbage
        manifest = a._read_manifest(pdir)
        assert "b-key" in manifest.get("ingested", {})
        assert manifest["ingested"]["b-key"] in manifest["segments"]
        assert all(a.load_segment(pdir, n) is not None
                   for n in manifest["segments"])

    def test_stale_holder_fails_loudly_at_commit(self, tmp_path,
                                                 monkeypatch):
        """A holder that stalls past its TTL inside the staged write
        and is stolen from must fail LOUDLY at the commit point — the
        orphan-clearing rmtree must never fire against a live new
        holder's committed data."""
        a, _b, seg = self._seeded(tmp_path / "store", ttl=60.0)
        orig_stage = a._stage_segment

        def stage_and_lose_lease(pdir_, delta):
            tmp_ = orig_stage(pdir_, delta)
            # the stall: our on-disk deadline lapses mid-stage and a
            # live foreign process steals the lease
            with open(a.lease.path, "w") as f:
                json.dump({"pid": os.getpid(), "deadline": 1.0}, f)
            a.lease._deadline = 0.0
            thief = StoreLease(a.lease.root, ttl_s=60.0)
            thief.owner_pid = FOREIGN_PID
            assert thief.acquire()
            return tmp_

        monkeypatch.setattr(a, "_stage_segment", stage_and_lose_lease)
        manifest_before = a._read_manifest(a.partition_dir(2, 44))
        with pytest.raises(LeaseHeldElsewhere):
            a.ingest(_obs([seg], np.random.default_rng(3), 8,
                          with_transitions=False), ingest_key="late")
        # nothing committed: manifest untouched, no new segment dirs
        after = a._read_manifest(a.partition_dir(2, 44))
        assert after == manifest_before

    def test_lease_refuses_the_interleave(self, tmp_path, monkeypatch):
        a, b, seg = self._seeded(tmp_path / "store", ttl=60.0)
        # A is a foreign live process holding the lease; B is us (the
        # seeding ran under our real pid — hand the lease over first)
        a.lease.release()
        a.lease.owner_pid = FOREIGN_PID
        assert a.lease.acquire()
        delta_b = _obs([seg], np.random.default_rng(2), 8,
                       with_transitions=False)
        with pytest.raises(LeaseHeldElsewhere):
            b.ingest(delta_b, ingest_key="b-key")
        with pytest.raises(LeaseHeldElsewhere):
            b.compact()
        # and ingest_dir refuses up front without quarantining anything
        spool = tmp_path / "spool"
        spool.mkdir()
        (spool / "tilefile").write_text("segment_id\n")
        with pytest.raises(LeaseHeldElsewhere):
            ingest_dir(b, str(spool))
        assert (spool / "tilefile").exists()


def _multi_partition_ids():
    """Segment ids spanning two level-2 partitions and one level-1."""
    return ([make_segment_id(2, 100, i) for i in range(6)]
            + [make_segment_id(2, 101, i) for i in range(5)]
            + [make_segment_id(1, 40, i) for i in range(4)])


class TestBatchedQueries:
    def test_query_many_parity_with_singles(self, tmp_path):
        ids = _multi_partition_ids()
        ds = _seed_store(tmp_path / "s", ids, deltas=3, n_obs=400)
        many = ds.query_many(ids)
        singles = [ds.query(s) for s in ids]
        assert many == singles

    def test_hours_subset_and_percentiles_parity(self, tmp_path):
        ids = _multi_partition_ids()
        ds = _seed_store(tmp_path / "s", ids, deltas=2, n_obs=300)
        hours = list(range(5, 40))
        pcts = (10.0, 50.0, 99.0)
        many = ds.query_many(ids, hours=hours, percentiles=pcts)
        singles = [ds.query(s, hours=hours, percentiles=pcts)
                   for s in ids]
        assert many == singles

    def test_duplicates_and_input_order(self, tmp_path):
        ids = _multi_partition_ids()
        ds = _seed_store(tmp_path / "s", ids, deltas=1, n_obs=100)
        asked = [ids[3], ids[0], ids[3], ids[7]]
        got = ds.query_many(asked)
        assert [g["segment_id"] for g in got] == asked
        assert got[0] == got[2] == ds.query(ids[3])
        # duplicates are equal but independent dicts: mutating one
        # answer must not contaminate its twin
        assert got[0] is not got[2]
        got[0]["transitions"].append({"next_id": -1, "count": 0})
        assert got[2] == ds.query(ids[3])

    def test_empty_results(self, tmp_path):
        ids = _multi_partition_ids()
        ds = _seed_store(tmp_path / "s", ids, deltas=1, n_obs=100)
        absent_same_partition = make_segment_id(2, 100, 4000)
        absent_partition = make_segment_id(2, 777, 1)
        got = ds.query_many([absent_same_partition, absent_partition])
        for g, seg in zip(got, (absent_same_partition, absent_partition)):
            assert g == ds.query(seg)
            assert g["count"] == 0 and g["mean_kph"] is None
            assert g["percentiles"]["p50"] is None
            assert g["transitions"] == []
        assert ds.query_many([]) == []

    def test_handle_lru_survives_mid_sweep_compaction(self, tmp_path):
        """The compactor swapping a manifest mid-sweep must not tear a
        reader: handles fetched before the swap stay valid mmaps
        (POSIX unlink), and answers are count-preserving across it."""
        ids = [make_segment_id(2, 100, i) for i in range(4)]
        ds = _seed_store(tmp_path / "s", ids, deltas=4, n_obs=200)
        before = ds.query_many(ids)
        parts = ds.live_segments(2, 100)  # the mid-sweep handles
        assert len(parts) == 4
        ds.compact()  # manifest swap + segment dir deletion
        # the pre-swap handles still read (old mmaps)
        total_pre = sum(int(np.asarray(p.hist_count).sum())
                        for p in parts)
        after = ds.query_many(ids)
        assert sum(r["count"] for r in after) == total_pre
        for b, a in zip(before, after):
            assert b["count"] == a["count"]
            assert b["mean_kph"] == a["mean_kph"]

    def test_batched_segment_counter(self, tmp_path):
        ids = _multi_partition_ids()
        ds = _seed_store(tmp_path / "s", ids, deltas=1, n_obs=64)
        c0 = metrics.default.counter("datastore.query.batched_segments")
        ds.query_many(ids)
        assert metrics.default.counter(
            "datastore.query.batched_segments") == c0 + len(ids)


class TestBboxQueries:
    def _geo_store(self, tmp_path):
        t = TileHierarchy().tiles(2)
        tiles = {"berlin": t.tile_id(52.5, 13.4),
                 "nearby": t.tile_id(52.5, 13.7),
                 "far": t.tile_id(-33.9, 151.2)}
        ids = {name: [make_segment_id(2, tile, i) for i in range(3)]
               for name, tile in tiles.items()}
        ds = LocalDatastore(str(tmp_path / "geo"))
        rng = np.random.default_rng(5)
        for name, segs in ids.items():
            ds.ingest(_obs(segs, rng, 60), ingest_key=f"geo-{name}")
        return ds, tiles, ids

    def test_bbox_selects_resident_partitions(self, tmp_path):
        ds, tiles, ids = self._geo_store(tmp_path)
        out = ds.query_bbox([13.0, 52.0, 14.0, 53.0], 2)
        got = {r["segment_id"] for r in out["segments"]}
        assert got == set(ids["berlin"]) | set(ids["nearby"])
        assert out["n_segments"] == 6 and not out["truncated"]
        # each answer equals its single query
        for r in out["segments"]:
            assert r == ds.query(r["segment_id"])

    def test_world_bbox_clamps_and_catches_everything(self, tmp_path):
        ds, _tiles, ids = self._geo_store(tmp_path)
        out = ds.query_bbox([-500.0, -200.0, 500.0, 200.0], 2)
        assert {r["segment_id"] for r in out["segments"]} \
            == {s for segs in ids.values() for s in segs}

    def test_resident_ids_cached_and_invalidated(self, tmp_path):
        """The bbox enumeration's resident-id list caches keyed by
        manifest content — an append re-keys it (new ids appear), the
        cache never serves a stale set."""
        ds, tiles, ids = self._geo_store(tmp_path)
        tile = tiles["berlin"]
        got1 = ds.resident_segments(2, tile)
        assert set(got1.tolist()) == set(ids["berlin"])
        got2 = ds.resident_segments(2, tile)
        assert got2 is got1  # cache hit: same array object
        new_seg = make_segment_id(2, tile, 99)
        ds.ingest(_obs([new_seg], np.random.default_rng(8), 10),
                  ingest_key="fresh")
        got3 = ds.resident_segments(2, tile)
        assert new_seg in got3.tolist()

    def test_truncation_is_explicit(self, tmp_path):
        ds, _tiles, _ids = self._geo_store(tmp_path)
        out = ds.query_bbox([-180, -90, 180, 90], 2, max_segments=2)
        assert out["truncated"] and len(out["segments"]) == 2

    def test_validation(self, tmp_path):
        ds, _t, _i = self._geo_store(tmp_path)
        with pytest.raises(ValueError):
            query_bbox(ds, [10, 10, 5, 5], 2)  # empty box (lat)
        with pytest.raises(ValueError):
            query_bbox(ds, [0, 0, 1, 1], 9)  # unknown level

    def test_antimeridian_bbox_wraps(self, tmp_path):
        """maxx < minx is an antimeridian crossing, not an error —
        the reference _split_antimeridian semantics (core/tiles.py)."""
        t = TileHierarchy().tiles(2)
        fiji_e = [make_segment_id(2, t.tile_id(-17.8, 179.6), i)
                  for i in range(2)]
        fiji_w = [make_segment_id(2, t.tile_id(-17.8, -179.6), i)
                  for i in range(2)]
        ds = LocalDatastore(str(tmp_path / "fiji"))
        rng = np.random.default_rng(6)
        ds.ingest(_obs(fiji_e + fiji_w, rng, 40), ingest_key="fiji")
        out = ds.query_bbox([179.0, -19.0, -179.0, -16.0], 2)
        assert {r["segment_id"] for r in out["segments"]} \
            == set(fiji_e) | set(fiji_w)

    def test_zero_width_bbox_is_not_a_world_wrap(self, tmp_path):
        """min_lon == max_lon is a degenerate one-column viewport —
        it must NOT trip the antimeridian wrap into a whole-world
        sweep."""
        ds, _tiles, ids = self._geo_store(tmp_path)
        out = ds.query_bbox([13.4, 52.0, 13.4, 53.0], 2)
        got = {r["segment_id"] for r in out["segments"]}
        assert got == set(ids["berlin"])  # never 'far' (Sydney)

    def test_bbox_tile_range_matches_tile_bbox_edges(self):
        """Boundary clamps agree with Tiles.tile_bbox round trips: a
        bbox equal to one tile's own bbox selects exactly that tile
        (the shared max edge belongs to the neighbour, which the range
        includes — same contract as tiles_for_bbox)."""
        t = TileHierarchy().tiles(2)
        tile = t.tile_id(52.5, 13.4)
        bb = t.tile_bbox(tile)
        r0, r1, c0, c1, ncols = bbox_tile_range(
            [bb.minx, bb.miny, bb.maxx, bb.maxy], 2)
        assert r0 * ncols + c0 == tile
        ids = bbox_partitions([bb.minx, bb.miny, bb.maxx, bb.maxy], 2)
        assert tile in ids and len(ids) == 4  # + max-edge neighbours
        # world max corner clamps instead of erroring
        r0b, r1b, c0b, c1b, _ = bbox_tile_range([179.9, 89.9, 999, 999],
                                                2)
        assert r1b == t.nrows - 1 and c1b == t.ncolumns - 1


class TestBackgroundCompactor:
    def _pressured(self, tmp_path, deltas=4):
        seg = make_segment_id(2, 61, 2)
        return _seed_store(tmp_path / "s", [seg], deltas=deltas,
                           n_obs=64), seg

    def test_run_once_compacts_over_pressure(self, tmp_path):
        ds, _seg = self._pressured(tmp_path)
        comp = BackgroundCompactor(ds, max_deltas=1, interval_s=0.0)
        backlog = comp.pending(refresh=True)
        assert backlog["partitions_over"] == 1
        assert backlog["delta_segments"] == 4
        assert backlog["delta_bytes"] > 0
        got = comp.run_once()
        assert got["compacted"] == 1
        assert comp.pending()["partitions_over"] == 0
        names = ds._read_manifest(ds.partition_dir(2, 61))["segments"]
        assert names == ["base-000005"]

    def test_below_pressure_skips(self, tmp_path):
        ds, _seg = self._pressured(tmp_path, deltas=2)
        comp = BackgroundCompactor(ds, max_deltas=4, interval_s=0.0)
        got = comp.run_once()
        assert got["compacted"] == 0

    def test_unleased_process_gauges_but_never_compacts(self, tmp_path):
        ds, _seg = self._pressured(tmp_path)
        ds.lease.release()  # the seeding held it under our real pid
        other = StoreLease(ds.root, ttl_s=60.0)
        other.owner_pid = FOREIGN_PID
        assert other.acquire()
        comp = BackgroundCompactor(ds, max_deltas=1, interval_s=0.0)
        u0 = metrics.default.counter("datastore.compactor.unleased")
        got = comp.run_once()
        assert got.get("unleased") and got["compacted"] == 0
        assert got["backlog"]["partitions_over"] == 1  # still gauging
        assert metrics.default.counter(
            "datastore.compactor.unleased") == u0 + 1
        names = ds._read_manifest(ds.partition_dir(2, 61))["segments"]
        assert len(names) == 4  # untouched

    def test_thread_lifecycle(self, tmp_path):
        ds, _seg = self._pressured(tmp_path)
        comp = BackgroundCompactor(ds, max_deltas=1,
                                   interval_s=0.005).start()
        deadline = 200
        while comp.pending(refresh=True)["partitions_over"] \
                and deadline > 0:
            import time
            time.sleep(0.01)
            deadline -= 1
        comp.stop()
        assert comp.pending()["partitions_over"] == 0
        assert comp._thread is None

    def test_crashed_commit_orphan_is_cleared(self, tmp_path):
        """A holder SIGKILLed between segment rename and manifest
        write leaves an orphan dir at the NEXT seq's name; the next
        holder's commit at that seq must replace it, not ENOTEMPTY
        (found live by chaos lease_kill)."""
        import shutil
        ds, _seg = self._pressured(tmp_path, deltas=3)
        pdir = ds.partition_dir(2, 61)
        # fabricate the crash artifact: the would-be base-000004 dir
        # renamed in place, manifest never rewritten
        src = os.path.join(pdir, "delta-000001")
        orphan = os.path.join(pdir, "base-000004")
        shutil.copytree(src, orphan)
        before = ds.query(make_segment_id(2, 61, 2))
        assert ds.compact()["partitions"] == 1  # no ENOTEMPTY
        manifest = ds._read_manifest(pdir)
        assert manifest["segments"] == ["base-000004"]
        assert ds.query(make_segment_id(2, 61, 2)) == before

    def test_zero_interval_never_starts(self, tmp_path):
        ds, _seg = self._pressured(tmp_path)
        comp = BackgroundCompactor(ds, max_deltas=1, interval_s=0.0)
        comp.start()
        assert comp._thread is None
        comp.stop()

    def test_stop_then_start_compacts_again(self, tmp_path):
        """A stopped compactor must be restartable — a set stop event
        carried into the fresh thread would kill it on its first
        wait() and compaction would silently cease."""
        import time
        ds, seg = self._pressured(tmp_path, deltas=4)
        comp = BackgroundCompactor(ds, max_deltas=1,
                                   interval_s=0.005).start()
        comp.stop()
        rng = np.random.default_rng(9)
        for d in range(4):  # fresh pressure after the stop
            ds.ingest(_obs([seg], rng, 32), ingest_key=f"again-{d}")
        comp.start()
        deadline = 200
        while comp.pending(refresh=True)["partitions_over"] \
                and deadline > 0:
            time.sleep(0.01)
            deadline -= 1
        comp.stop()
        assert comp.pending()["partitions_over"] == 0


class TestWalkerSkips:
    def test_scan_tiles_skips_lease_and_profile(self, tmp_path):
        root = tmp_path / "store"
        seg = make_segment_id(2, 9, 1)
        ds = _seed_store(root, [seg], deltas=1, n_obs=8)
        ds.lease._deadline = 0.0
        ds.lease.acquire()  # writes .lease
        (root / PROFILE_NAME).write_text('{"version":1,"pairs":[]}')
        names = {os.path.basename(p) for p in scan_tiles(str(root))}
        assert LEASE_NAME not in names
        assert PROFILE_NAME not in names

    def test_spool_accounting_skips_control_files(self, tmp_path):
        from reporter_tpu.utils import spool
        root = tmp_path / "spool"
        root.mkdir()
        (root / "tile1").write_text("data")
        (root / LEASE_NAME).write_text('{"pid": 1}')
        (root / PROFILE_NAME).write_text("{}")
        got = spool.backlog(str(root))
        assert got["files"] == 1 and got["bytes"] == 4

    def test_store_fingerprint_ignores_control_files(self, tmp_path):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        import chaos
        root = tmp_path / "store"
        seg = make_segment_id(2, 9, 1)
        ds = _seed_store(root, [seg], deltas=1, n_obs=8)
        before = chaos._store_fingerprint(str(root))
        ds.lease._deadline = 0.0
        ds.lease.acquire()
        (root / PROFILE_NAME).write_text("{}")
        assert chaos._store_fingerprint(str(root)) == before


@pytest.fixture(scope="module")
def synth_city():
    from reporter_tpu.synth import build_grid_city
    return build_grid_city(rows=7, cols=7, spacing_m=220.0, seed=11,
                           service_road_fraction=0.0,
                           internal_fraction=0.0)


def _native_matcher(city):
    from reporter_tpu import native
    if not native.available():
        pytest.skip("native runtime unavailable")
    from reporter_tpu.matcher import SegmentMatcher
    m = SegmentMatcher(net=city)
    if m.runtime is None:
        pytest.skip("native runtime unavailable")
    return m


def _city_requests(city, n=6, seed=23):
    from reporter_tpu.synth import generate_trace
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        tr = None
        while tr is None:
            tr = generate_trace(city, f"warm-{i}", rng, noise_m=3.0,
                                min_route_edges=8)
        reqs.append(tr.request_json())
    return reqs


class TestProfileWarm:
    def test_export_load_warm_roundtrip(self, synth_city, tmp_path,
                                        monkeypatch):
        monkeypatch.setenv("REPORTER_TPU_PREP_THREADS", "1")
        matcher = _native_matcher(synth_city)
        reqs = _city_requests(synth_city)
        matcher.match_many(reqs)
        path = profile_path(str(tmp_path))
        art = export_profile(matcher, path, city="testville")
        assert art["n_pairs"] > 0
        assert art["memo_stats"]["size"] > 0
        loaded = load_profile(path)
        assert loaded["city"] == "testville"
        assert loaded["pairs"] == art["pairs"]

        # a FRESH matcher (cold memo): the pre-warm inserts the pairs,
        # and the same first request batch now hits the shared memo —
        # where a cold matcher's first batch records zero shared hits
        cold = _native_matcher(synth_city)
        assert cold.runtime.route_memo_stats()["hits"] == 0
        cold.match_many(reqs)
        cold_hits = cold.runtime.route_memo_stats()["hits"]
        assert cold_hits == 0  # single prep slot: local memo soaks all

        warm = _native_matcher(synth_city)
        warmed = warm_matcher(warm, loaded)
        assert warmed == art["n_pairs"]
        assert warm.runtime.route_memo_stats()["size"] >= warmed
        warm.match_many(reqs)
        assert warm.runtime.route_memo_stats()["hits"] > 0

    def test_warm_results_bit_identical(self, synth_city, monkeypatch,
                                        tmp_path):
        """The pre-warm changes latency, never answers: a warmed
        matcher's reports equal a cold matcher's byte-for-byte."""
        monkeypatch.setenv("REPORTER_TPU_PREP_THREADS", "1")
        from reporter_tpu.service.report import report_json
        matcher = _native_matcher(synth_city)
        reqs = _city_requests(synth_city)
        matcher.match_many(reqs)
        path = profile_path(str(tmp_path))
        export_profile(matcher, path)

        def bodies(m):
            out = []
            for req, match in zip(reqs, m.match_many(reqs)):
                out.append(report_json(match, req, 15, {0, 1, 2},
                                       {0, 1, 2}))
            return out

        cold = _native_matcher(synth_city)
        warm = _native_matcher(synth_city)
        warm_matcher(warm, load_profile(path))
        assert bodies(cold) == bodies(warm)

    def test_load_profile_absent_and_corrupt(self, tmp_path):
        assert load_profile(str(tmp_path / "nope")) is None
        bad = tmp_path / PROFILE_NAME
        bad.write_text("{not json")
        assert load_profile(str(bad)) is None
        bad.write_text('{"version": 99}')
        assert load_profile(str(bad)) is None

    def test_malformed_pairs_cost_only_the_warm(self, synth_city,
                                                monkeypatch):
        """Ragged / non-pair 'pairs' in a version-1 artifact skip the
        pre-warm instead of raising out of the city load."""
        monkeypatch.setenv("REPORTER_TPU_PREP_THREADS", "1")
        matcher = _native_matcher(synth_city)
        assert warm_matcher(matcher, {"version": 1,
                                      "pairs": [[1, 2], [3]]}) == 0
        assert warm_matcher(matcher, {"version": 1,
                                      "pairs": [1, 2]}) == 0

    def test_warm_on_fallback_is_zero(self, synth_city, tmp_path):
        from reporter_tpu.matcher import SegmentMatcher
        m = SegmentMatcher(net=synth_city, use_native=False)
        prof = {"version": 1, "pairs": [[0, 1]]}
        assert warm_matcher(m, prof) == 0
        assert warm_matcher(m, None) == 0

    def test_foreign_graph_pairs_skipped(self, synth_city, monkeypatch):
        monkeypatch.setenv("REPORTER_TPU_PREP_THREADS", "1")
        matcher = _native_matcher(synth_city)
        n_edges = int(matcher.net.num_edges)
        prof = {"version": 1,
                "pairs": [[0, 1], [n_edges + 5, 0], [-3, 2]]}
        assert warm_matcher(matcher, prof) == 1


class TestCityRegistry:
    def _registry(self, synth_city, tmp_path, budget):
        from reporter_tpu.service.cities import CityEntry, CityRegistry
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService

        built = []

        def loader(name):
            seg = make_segment_id(2, 100 + len(name), 1)
            ds = _seed_store(tmp_path / f"store-{name}", [seg],
                             deltas=1, n_obs=16)
            svc = ReporterService(
                SegmentMatcher(net=synth_city, use_native=False),
                datastore=ds)
            built.append(name)
            return svc, 100  # 100 "bytes" per city

        return (CityRegistry(budget_bytes=budget, loader=loader),
                built)

    def test_lru_eviction_under_budget(self, synth_city, tmp_path):
        registry, built = self._registry(synth_city, tmp_path,
                                         budget=250)
        e0 = metrics.default.counter("datastore.city.evictions")
        a = registry.get("a")
        registry.get("b")
        registry.get("a")  # refresh a's recency
        registry.get("c")  # 300 > 250: evicts b (LRU), not a
        snap = registry.snapshot()
        assert sorted(snap["resident"]) == ["a", "c"]
        assert metrics.default.counter(
            "datastore.city.evictions") == e0 + 1
        assert registry.get("a") is a  # still resident, same entry
        # b reloads on demand
        registry.get("b")
        assert built.count("b") == 2

    def test_most_recent_never_evicted(self, synth_city, tmp_path):
        registry, _ = self._registry(synth_city, tmp_path, budget=1)
        registry.get("a")
        registry.get("b")
        assert sorted(registry.snapshot()["resident"]) == ["b"]

    def test_unknown_city_raises(self, synth_city, tmp_path):
        from reporter_tpu.service.cities import CityRegistry
        registry = CityRegistry({"x": {"graph": "nope.npz"}})
        with pytest.raises(KeyError):
            registry.get("unconfigured")

    def test_eviction_closes_dispatcher(self, synth_city, tmp_path):
        registry, _ = self._registry(synth_city, tmp_path, budget=1)
        a = registry.get("a")
        registry.get("b")
        with pytest.raises(RuntimeError):
            a.service.dispatcher.submit({"uuid": "x", "trace": []})

    def test_pinned_entry_closes_at_release_not_eviction(self,
                                                        synth_city,
                                                        tmp_path):
        """An LRU eviction must not stop a city's dispatcher while a
        handler thread is still serving through it: the close defers
        to the last release()."""
        registry, _ = self._registry(synth_city, tmp_path, budget=1)
        a = registry.acquire("a")  # pinned, as server._route does
        assert a._refs == 1  # the pin lands INSIDE the map lock
        registry.get("b")  # evicts a from the map...
        assert sorted(registry.snapshot()["resident"]) == ["b"]
        # ...but a's dispatcher is still alive for the in-flight request
        a.service.dispatcher.submit_many([], return_exceptions=True)
        registry.release(a)
        with pytest.raises(RuntimeError):
            a.service.dispatcher.submit({"uuid": "x", "trace": []})
        # a pinned HIT also pins atomically
        b = registry.acquire("b")
        b2 = registry.acquire("b")
        assert b is b2 and b._refs == 2
        registry.release(b)
        registry.release(b2)
        assert b._refs == 0


class TestMapSwap:
    """Zero-downtime map lifecycle (ISSUE 20): the hot swap flips at a
    request boundary behind the dual-version shadow gate, refuses
    rather than evicting a pinned unrelated city, and in-flight pins
    keep vN's stack alive through the flip."""

    def _svc(self, city, tmp_path, name):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        seg = make_segment_id(2, 100, 1)
        ds = _seed_store(tmp_path / f"store-{name}", [seg],
                         deltas=1, n_obs=16)
        return ReporterService(
            SegmentMatcher(net=city, use_native=False), datastore=ds)

    def test_swap_flips_to_new_version(self, synth_city, tmp_path):
        from reporter_tpu.graph.version import map_version
        from reporter_tpu.service.cities import CityRegistry
        from reporter_tpu.synth import build_grid_city
        city2 = build_grid_city(rows=7, cols=7, spacing_m=220.0,
                                seed=11, service_road_fraction=0.0,
                                internal_fraction=0.0)
        city2.edge_speed_kph = city2.edge_speed_kph * 1.2
        assert map_version(city2) != map_version(synth_city)
        svc1 = self._svc(synth_city, tmp_path, "v1")
        svc2 = self._svc(city2, tmp_path, "v2")
        reg = CityRegistry(loader=lambda n: (svc1, 100),
                           budget_bytes=1 << 30)
        f0 = metrics.default.counter("swap.flips")
        old = reg.get("metro")
        assert old.map_version == map_version(synth_city)
        # the load stamped the city's store: epoch-qualified ledger
        # keys + manifest tags flow from here
        assert old.service.datastore.map_version == old.map_version
        rec = reg.swap("metro", lambda: (svc2, 100))
        assert rec["result"] == "flipped"
        assert rec["from"] == map_version(synth_city)
        assert rec["to"] == map_version(city2)
        assert metrics.default.counter("swap.flips") == f0 + 1
        new = reg.get("metro")
        assert new is not old and new.map_version == rec["to"]
        assert new.service.datastore.map_version == rec["to"]
        # unpinned vN closed at the flip boundary
        with pytest.raises(RuntimeError):
            old.service.dispatcher.submit({"uuid": "x", "trace": []})
        snap = reg.snapshot()
        assert snap["swap"]["flips"] == 1
        assert snap["swap"]["last"]["metro"]["result"] == "flipped"
        assert snap["resident"]["metro"]["map_version"] == rec["to"]

    def test_pin_on_old_survives_flip_until_release(self, synth_city,
                                                    tmp_path):
        """In-flight requests finish on vN: a pin taken before the
        flip keeps vN's dispatcher alive until the LAST release, while
        new traffic already routes to vN+1."""
        from reporter_tpu.service.cities import CityRegistry
        svc1 = self._svc(synth_city, tmp_path, "p1")
        svc2 = self._svc(synth_city, tmp_path, "p2")
        reg = CityRegistry(loader=lambda n: (svc1, 100),
                           budget_bytes=1 << 30)
        old = reg.acquire("metro")  # pinned, as server._route does
        rec = reg.swap("metro", lambda: (svc2, 100))
        assert rec["result"] == "flipped"
        # vN still serves the in-flight request through its pin...
        old.service.dispatcher.submit_many([], return_exceptions=True)
        # ...while new requests route to vN+1
        assert reg.get("metro").service is svc2
        reg.release(old)
        with pytest.raises(RuntimeError):
            old.service.dispatcher.submit({"uuid": "x", "trace": []})

    def test_shadow_gate_refuses_divergent_graph(self, synth_city,
                                                 tmp_path,
                                                 monkeypatch):
        from reporter_tpu.service.cities import CityRegistry
        from reporter_tpu.synth import build_grid_city
        monkeypatch.setenv("REPORTER_TPU_SWAP_SAMPLE", "1")
        alien = build_grid_city(rows=5, cols=5, spacing_m=150.0,
                                seed=2, service_road_fraction=0.0,
                                internal_fraction=0.0)
        svc1 = self._svc(synth_city, tmp_path, "s1")
        svc2 = self._svc(alien, tmp_path, "s2")
        reg = CityRegistry(loader=lambda n: (svc1, 100),
                           budget_bytes=1 << 30)
        old = reg.get("metro")
        for req in _city_requests(synth_city, n=4):
            old.observe(req)  # as server._route does on admitted 200s
        r0 = metrics.default.counter("swap.refusals")
        rec = reg.swap("metro", lambda: (svc2, 100))
        assert rec["result"] == "refused_shadow"
        assert rec["checks"] == 4 and rec["agreement"] < rec["floor"]
        assert metrics.default.counter("swap.refusals") == r0 + 1
        # the old version keeps serving; the candidate was closed
        assert reg.get("metro") is old
        snap = reg.snapshot()["swap"]
        assert snap["refusals"] == 1
        assert snap["last"]["metro"]["result"] == "refused_shadow"
        with pytest.raises(RuntimeError):
            svc2.dispatcher.submit({"uuid": "x", "trace": []})
        # operator override: an intentional map change flips anyway
        svc3 = self._svc(alien, tmp_path, "s3")
        rec = reg.swap("metro", lambda: (svc3, 100), force=True)
        assert rec["result"] == "flipped" and rec["forced"]

    def test_eviction_flushes_incremental_state(self, synth_city,
                                                tmp_path):
        """An evicted city's carried incremental decode state flushes
        with its stack (counted in match.incremental.evictions) — a
        vacated slot must not leak per-trace device state."""
        from reporter_tpu.service.cities import CityRegistry
        svc1 = self._svc(synth_city, tmp_path, "e1")
        svc2 = self._svc(synth_city, tmp_path, "e2")
        services = {"a": svc1, "b": svc2}
        reg = CityRegistry(loader=lambda n: (services[n], 100),
                           budget_bytes=100)
        a = reg.get("a")
        req = _city_requests(synth_city, n=1)[0]
        a.service.matcher.match_incremental(
            [{"uuid": "evict-1", "trace": req["trace"]}])
        table = a.service.matcher.incremental_table
        assert table.gauge()["traces"] == 1
        e0 = metrics.default.counter("match.incremental.evictions")
        reg.get("b")  # budget of one city: evicts + closes a
        assert table.gauge()["traces"] == 0
        assert metrics.default.counter(
            "match.incremental.evictions") == e0 + 1

    def test_swap_publishes_epoch_feed_event(self, synth_city,
                                             tmp_path):
        """A flip announces the new epoch on the candidate store's
        change feed — dashboards re-query instead of merging across
        map builds (ISSUE 20)."""
        from reporter_tpu.service.cities import CityRegistry
        svc1 = self._svc(synth_city, tmp_path, "f1")
        svc2 = self._svc(synth_city, tmp_path, "f2")
        tier = svc2.datastore.enable_freshness()
        assert tier is not None
        reg = CityRegistry(loader=lambda n: (svc1, 100),
                           budget_bytes=1 << 30)
        reg.get("metro")
        rec = reg.swap("metro", lambda: (svc2, 100))
        assert rec["result"] == "flipped"
        out = tier.feed.poll(cursor=0, timeout_s=0)
        epochs = [e for e in out["events"] if e["kind"] == "epoch"]
        assert epochs and epochs[-1]["map_version"] == rec["to"]

    def test_budget_refusal_spares_pinned_city(self, synth_city,
                                               tmp_path):
        """Dual residency during the swap counts BOTH versions against
        the byte budget; a pinned unrelated city refuses the swap
        (never evicted mid-request), an unpinned one is evicted."""
        from reporter_tpu.service.cities import CityRegistry
        built = []

        def loader(name):
            svc = self._svc(synth_city, tmp_path, f"b{len(built)}")
            built.append(name)
            return svc, 100

        reg = CityRegistry(loader=loader, budget_bytes=250)
        reg.get("metro")
        other = reg.acquire("other")  # pinned unrelated city
        e0 = metrics.default.counter("datastore.city.evictions")
        rec = reg.swap("metro")  # 100*3 > 250 with 'other' pinned
        assert rec["result"] == "refused_budget"
        assert rec["pinned"] == ["other"]
        assert sorted(reg.snapshot()["resident"]) == ["metro", "other"]
        assert metrics.default.counter(
            "datastore.city.evictions") == e0
        # unpinned: the unrelated LRU city is evicted and the swap
        # proceeds
        reg.release(other)
        rec = reg.swap("metro")
        assert rec["result"] == "flipped"
        assert sorted(reg.snapshot()["resident"]) == ["metro"]
        assert metrics.default.counter(
            "datastore.city.evictions") == e0 + 1


class TestServiceRouting:
    @pytest.fixture()
    def routed_service(self, synth_city, tmp_path):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.cities import CityRegistry
        from reporter_tpu.service.server import ReporterService

        seg = make_segment_id(2, 301, 4)
        ds_b = _seed_store(tmp_path / "store-b", [seg], deltas=1,
                           n_obs=32)

        def loader(name):
            if name != "b":
                raise KeyError(name)
            return ReporterService(
                SegmentMatcher(net=synth_city, use_native=False),
                datastore=ds_b), 1
        service = ReporterService(
            SegmentMatcher(net=synth_city, use_native=False),
            cities=CityRegistry(loader=loader))
        yield service, seg, ds_b
        service.dispatcher.close()

    def test_histogram_routes_by_city(self, routed_service):
        service, seg, ds_b = routed_service
        # no city, no default datastore -> 503
        code, _ = service.histogram({"segment_id": seg})
        assert code == 503
        code, body = service.histogram({"segment_id": seg, "city": "b"})
        assert code == 200
        assert json.loads(body) == ds_b.query(seg)

    def test_batched_histogram_params(self, routed_service):
        service, seg, ds_b = routed_service
        code, body = service.histogram({"segments": [seg, seg + 8],
                                        "city": "b"})
        assert code == 200
        assert json.loads(body)["results"] \
            == ds_b.query_many([seg, seg + 8])
        code, body = service.histogram(
            {"bbox": [-180, -90, 180, 90], "level": 2, "city": "b"})
        assert code == 200
        got = json.loads(body)
        assert {r["segment_id"] for r in got["segments"]} \
            >= {seg}
        # bbox without level is a 400, as is nothing at all
        assert service.histogram({"bbox": [0, 0, 1, 1],
                                  "city": "b"})[0] == 400
        assert service.histogram({"city": "b"})[0] == 400

    def test_unknown_city_is_400(self, routed_service):
        service, seg, _ = routed_service
        code, body = service.histogram({"segment_id": seg,
                                        "city": "atlantis"})
        assert code == 400 and "atlantis" in body

    def test_report_routes_by_city(self, routed_service, synth_city):
        service, _seg, _ds = routed_service
        req = _city_requests(synth_city, n=1)[0]
        code, body = service.handle(dict(req, city="b"))
        assert code == 200
        code_direct, body_direct = service.handle(req)
        assert code_direct == 200
        as_json = json.loads(bytes(body) if isinstance(body, memoryview)
                             else body)
        direct = json.loads(bytes(body_direct)
                            if isinstance(body_direct, memoryview)
                            else body_direct)
        # same graph both sides: the routed answer matches the default
        assert as_json == direct

    def test_health_carries_lease_and_compactor(self, synth_city,
                                                tmp_path):
        from reporter_tpu.matcher import SegmentMatcher
        from reporter_tpu.service.server import ReporterService
        seg = make_segment_id(2, 305, 4)
        ds = _seed_store(tmp_path / "s", [seg], deltas=2, n_obs=16)
        service = ReporterService(
            SegmentMatcher(net=synth_city, use_native=False),
            datastore=ds)
        service.compactor = BackgroundCompactor(ds, max_deltas=1,
                                                interval_s=0.0)
        service.compactor.pending(refresh=True)
        try:
            code, body = service.health()
            got = json.loads(body)
            assert got["datastore"]["lease"]["enabled"]
            assert got["compaction"]["partitions_over"] == 1
        finally:
            service.dispatcher.close()

    def test_health_surfaces_map_versions(self, routed_service,
                                          synth_city):
        """/health carries the default stack's graph map_version plus
        the per-resident-city versions and the swap block (ISSUE 20)."""
        from reporter_tpu.graph.version import map_version
        service, seg, ds_b = routed_service
        service.cities.get("b")  # make the routed city resident
        code, body = service.health()
        got = json.loads(body)
        assert got["graph"]["map_version"] == map_version(synth_city)
        resident = got["cities"]["resident"]["b"]
        assert resident["map_version"] == map_version(synth_city)
        swap = got["cities"]["swap"]
        assert swap == {"flips": 0, "refusals": 0, "last": {}}


class TestDatastoreCliBatched:
    def test_query_segments_and_bbox(self, tmp_path, capsys):
        from reporter_tpu.tools import datastore_cli
        t = TileHierarchy().tiles(2)
        tile = t.tile_id(52.5, 13.4)
        ids = [make_segment_id(2, tile, i) for i in range(3)]
        ds = _seed_store(tmp_path / "s", ids, deltas=1, n_obs=60)
        assert datastore_cli.main(
            ["query", str(tmp_path / "s"),
             "--segments", ",".join(str(i) for i in ids)]) == 0
        got = json.loads(capsys.readouterr().out.strip())
        assert got["results"] == ds.query_many(ids)
        assert datastore_cli.main(
            ["query", str(tmp_path / "s"),
             "--bbox", "13.0,52.0,14.0,53.0", "--bbox-level", "2"]) == 0
        got = json.loads(capsys.readouterr().out.strip())
        assert {r["segment_id"] for r in got["segments"]} == set(ids)

    def test_profile_show_absent(self, tmp_path, capsys):
        from reporter_tpu.tools import datastore_cli
        seg = make_segment_id(2, 9, 1)
        _seed_store(tmp_path / "s", [seg], deltas=1, n_obs=8)
        assert datastore_cli.main(["profile", str(tmp_path / "s")]) == 0
        got = json.loads(capsys.readouterr().out.strip())
        assert got["present"] is False

    def test_profile_export_via_replay(self, synth_city, tmp_path,
                                       capsys, monkeypatch):
        from reporter_tpu import native
        if not native.available():
            pytest.skip("native runtime unavailable")
        monkeypatch.setenv("REPORTER_TPU_PREP_THREADS", "1")
        from reporter_tpu.tools import datastore_cli
        seg = make_segment_id(2, 9, 1)
        store = tmp_path / "s"
        _seed_store(store, [seg], deltas=1, n_obs=8)
        graph = tmp_path / "city.npz"
        synth_city.save(str(graph))
        replay = tmp_path / "traces.jsonl"
        with open(replay, "w") as f:
            for r in _city_requests(synth_city, n=3):
                f.write(json.dumps(r) + "\n")
        assert datastore_cli.main(
            ["profile", str(store), "--graph", str(graph),
             "--replay", str(replay), "--city", "cli-town"]) == 0
        got = json.loads(capsys.readouterr().out.strip())
        assert got["replayed"] == 3 and got["n_pairs"] > 0
        art = load_profile(str(store / PROFILE_NAME))
        assert art["city"] == "cli-town"
        assert len(art["pairs"]) == got["n_pairs"]
