import math

import pytest

from reporter_tpu.core import (
    INVALID_SEGMENT_ID,
    BoundingBox,
    Point,
    Segment,
    TileHierarchy,
    TimeQuantisedTile,
    equirectangular_m,
    make_segment_id,
    segment_index,
    tile_id_of_segment,
    tile_index,
    tile_level,
    tiles_for_bbox,
)


class TestOsmlr:
    def test_invalid_sentinel(self):
        # reference: Segment.java:16
        assert INVALID_SEGMENT_ID == 0x3FFFFFFFFFFF

    def test_roundtrip(self):
        sid = make_segment_id(2, 12345, 678)
        assert tile_level(sid) == 2
        assert tile_index(sid) == 12345
        assert segment_index(sid) == 678

    def test_tile_id_masks_off_segment_index(self):
        sid = make_segment_id(1, 99, 1000)
        assert tile_id_of_segment(sid) == make_segment_id(1, 99, 0)
        assert tile_id_of_segment(sid) == (sid & 0x1FFFFFF)

    def test_ranges_checked(self):
        with pytest.raises(ValueError):
            make_segment_id(8, 0, 0)
        with pytest.raises(ValueError):
            make_segment_id(0, 1 << 22, 0)
        with pytest.raises(ValueError):
            make_segment_id(0, 0, 1 << 21)


class TestGeo:
    def test_equirectangular_equator_lon_degree(self):
        # one degree of longitude at the equator ~ 111.3 km
        d = equirectangular_m(0.0, 0.0, 0.0, 1.0)
        assert abs(d - 20037581.187 / 180.0) < 1.0

    def test_symmetric(self):
        a = equirectangular_m(14.6, 121.0, 14.61, 121.01)
        b = equirectangular_m(14.61, 121.01, 14.6, 121.0)
        assert a == pytest.approx(b)


class TestPoint:
    def test_binary_roundtrip(self):
        p = Point(14.5995, 120.9842, 50, 1700000000)
        raw = p.to_bytes()
        assert len(raw) == Point.SIZE == 20
        q = Point.from_bytes(raw)
        assert q.accuracy == 50 and q.time == 1700000000
        assert q.lat == pytest.approx(14.5995, abs=1e-4)

    def test_json_str(self):
        p = Point(1.5, -2.25, 10, 123)
        assert p.to_json_str() == '{"lat":1.5,"lon":-2.25,"time":123,"accuracy":10}'


class TestSegment:
    def test_valid(self):
        s = Segment(5, 6, 10.0, 20.0, 100, 0)
        assert s.valid()
        assert not Segment(5, 6, 0.0, 20.0, 100, 0).valid()
        assert not Segment(5, 6, 10.0, 10.0, 100, 0).valid()
        assert not Segment(5, 6, 10.0, 20.0, 0, 0).valid()
        assert not Segment(5, 6, 10.0, 20.0, 100, -1).valid()

    def test_none_next_becomes_invalid(self):
        s = Segment(5, None, 10.0, 20.0, 100, 0)
        assert s.next_id == INVALID_SEGMENT_ID

    def test_csv_row(self):
        s = Segment(42, None, 10.4, 19.6, 100, 3)
        row = s.csv_row("AUTO", "src")
        # duration=round(9.2)=9, min floor=10, max ceil=20, empty next_id
        assert row == "42,,9,1,100,3,10,20,src,AUTO"

    def test_binary_roundtrip(self):
        s = Segment(make_segment_id(0, 7, 9), make_segment_id(0, 7, 10),
                    1.5, 9.5, 250, 12)
        raw = s.to_bytes()
        assert len(raw) == Segment.SIZE == 40
        t = Segment.from_bytes(raw)
        assert t == s


class TestTimeQuantisedTile:
    def test_span_buckets(self):
        # a segment from t=3599 to t=7201 with 3600s quantisation touches 3 buckets
        # (reference: TimeQuantisedTile.java:26-35)
        s = Segment(make_segment_id(0, 7, 9), None, 3599.0, 7201.0, 100, 0)
        tiles = TimeQuantisedTile.tiles_for(s, 3600)
        assert [t.time_range_start for t in tiles] == [0, 3600, 7200]
        assert all(t.tile_id == s.tile_id() for t in tiles)

    def test_level_index_extraction(self):
        s = Segment(make_segment_id(1, 500, 3), None, 10.0, 20.0, 100, 0)
        (tile,) = TimeQuantisedTile.tiles_for(s, 3600)
        assert tile.tile_level() == 1
        assert tile.tile_index() == 500

    def test_binary_roundtrip(self):
        t = TimeQuantisedTile(7200, 0x1ABCDE)
        assert TimeQuantisedTile.from_bytes(t.to_bytes()) == t


class TestTiles:
    def test_hierarchy_shapes(self):
        h = TileHierarchy()
        assert h.tiles(2).ncolumns == 1440 and h.tiles(2).nrows == 720
        assert h.tiles(1).ncolumns == 360 and h.tiles(1).nrows == 180
        assert h.tiles(0).ncolumns == 90 and h.tiles(0).nrows == 45

    def test_row_col_edges(self):
        t = TileHierarchy().tiles(0)
        assert t.row(-91) == -1 and t.col(-181) == -1
        assert t.row(90.0) == t.nrows - 1
        assert t.col(180.0) == t.ncolumns - 1

    def test_file_path_level2(self):
        t = TileHierarchy().tiles(2)
        # max_tile_id=1036799 (7 digits -> padded to 9)
        assert t.file_path(756425, 2, "gph") == "2/000/756/425.gph"

    def test_file_path_level0_leading_zero(self):
        t = TileHierarchy().tiles(0)
        # max_tile_id=4049 (4 digits -> padded to 6)
        assert t.file_path(2415, 0, "gph") == "0/002/415.gph"

    def test_max_edge_clamps_to_last_row_col(self):
        # x == maxx / y == maxy belong to the last column/row, not -1
        # (reference: get_tiles.py:41-60 edge handling)
        for level in (0, 1, 2):
            t = TileHierarchy().tiles(level)
            assert t.col(180.0) == t.ncolumns - 1
            assert t.row(90.0) == t.nrows - 1
            assert t.tile_id(90.0, 180.0) == t.max_tile_id

    def test_out_of_bbox_is_minus_one(self):
        for level in (0, 1, 2):
            t = TileHierarchy().tiles(level)
            assert t.row(90.0 + 1e-9) == -1 and t.row(-90.0 - 1e-9) == -1
            assert t.col(180.0 + 1e-9) == -1 and t.col(-180.0 - 1e-9) == -1
            assert t.tile_id(91.0, 0.0) == -1
            assert t.tile_id(0.0, 181.0) == -1

    def test_tile_id_bbox_roundtrip_all_levels(self):
        # id -> bbox -> id round-trips for interior points at every level
        for level in (0, 1, 2):
            t = TileHierarchy().tiles(level)
            for tile_id in (0, 17, t.ncolumns - 1, t.ncolumns,
                            t.max_tile_id // 2, t.max_tile_id):
                box = t.tile_bbox(tile_id)
                cy = (box.miny + box.maxy) / 2
                cx = (box.minx + box.maxx) / 2
                assert t.tile_id(cy, cx) == tile_id
                # the min corner is inclusive; size matches the level
                assert t.tile_id(box.miny, box.minx) == tile_id
                assert box.maxx - box.minx == pytest.approx(t.tilesize)

    def test_bbox_tile_id_roundtrip(self):
        # lat/lon -> id -> bbox contains the original point
        for level in (0, 1, 2):
            t = TileHierarchy().tiles(level)
            for lat, lon in ((14.6, 121.0), (-33.9, 151.2), (0.0, 0.0),
                             (89.99, 179.99), (-90.0, -180.0)):
                tid = t.tile_id(lat, lon)
                box = t.tile_bbox(tid)
                assert box.minx <= lon <= box.maxx
                assert box.miny <= lat <= box.maxy

    def test_tile_bbox_range_checked(self):
        t = TileHierarchy().tiles(0)
        with pytest.raises(ValueError):
            t.tile_bbox(-1)
        with pytest.raises(ValueError):
            t.tile_bbox(t.max_tile_id + 1)

    def test_manila_bbox_contains_known_tile(self):
        # Manila ~ (14.6, 121.0)
        paths = list(tiles_for_bbox([120.9, 14.5, 121.1, 14.7], "gph"))
        t2 = TileHierarchy().tiles(2)
        expected = t2.file_path(t2.tile_id(14.6, 121.0), 2, "gph")
        assert expected in paths

    def test_antimeridian_split(self):
        paths = list(tiles_for_bbox([179.5, -1.0, -179.5, 1.0], "gph", levels=(0,)))
        assert len(paths) > 0
        # tiles from both sides of the antimeridian appear
        t0 = TileHierarchy().tiles(0)
        west = t0.file_path(t0.tile_id(0.0, 179.9), 0, "gph")
        east = t0.file_path(t0.tile_id(0.0, -179.9), 0, "gph")
        assert west in paths and east in paths
