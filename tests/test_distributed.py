"""A REAL 2-process jax.distributed job (round-4 VERDICT #9).

Every prior round only exercised init_multihost's no-op path; this spawns
two actual interpreters that rendezvous through
``jax.distributed.initialize`` (coordinator + 2 processes, CPU backend),
then verifies on BOTH processes:

- init_multihost returned True (the initialize branch ran);
- jax sees process_count == 2 (a real multi-controller job, not two
  singletons);
- uuid-space partitioning is disjoint and complete across the job — the
  Kafka keyed-partition contract (reference: tests/circle.sh:58,
  load-historical-data/README.md multi-instance scale-out).
"""
import json
import os
import socket
import subprocess
import sys

import pytest

_CHILD = r"""
import json, os, sys
sys.path.insert(0, os.environ["REPO_ROOT"])

from reporter_tpu.parallel import init_multihost, partition_for_host

# distributed rendezvous FIRST (it must run before any backend init),
# then pin the CPU backend by popping non-CPU PJRT factories — the
# environment's sitecustomize plugin ignores JAX_PLATFORMS and would
# block this child on the chip tunnel otherwise
ran = init_multihost()
from reporter_tpu.utils.runtime import force_virtual_cpu
force_virtual_cpu()
import jax
uuids = [f"veh-{i}" for i in range(100)]
mine = partition_for_host(uuids, int(os.environ["REPORTER_TPU_NUM_PROCESSES"]),
                          int(os.environ["REPORTER_TPU_PROCESS_ID"]))
print(json.dumps({
    "ran": ran,
    "process_index": jax.process_index(),
    "process_count": jax.process_count(),
    "n_devices": len(jax.devices()),
    "mine": mine,
}))
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_distributed_init():
    # bounded by the children's communicate(timeout=150) below — no
    # pytest-timeout plugin in this image
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # no virtual-device flag in children
        env.update({
            "REPO_ROOT": repo_root,
            "REPORTER_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "REPORTER_TPU_NUM_PROCESSES": "2",
            "REPORTER_TPU_PROCESS_ID": str(pid),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed child hung (rendezvous never "
                        "completed)")
        assert p.returncode == 0, f"child failed:\n{err[-2000:]}"
        results.append(json.loads(out.strip().splitlines()[-1]))

    for r in results:
        assert r["ran"] is True          # the initialize branch executed
        assert r["process_count"] == 2   # one job, two controllers
    assert sorted(r["process_index"] for r in results) == [0, 1]

    # uuid partitioning across the job: disjoint and complete
    mine0, mine1 = results[0]["mine"], results[1]["mine"]
    assert not set(mine0) & set(mine1)
    assert sorted(mine0 + mine1) == list(range(100))
    assert mine0 and mine1  # both hosts own a share
